// C inference API (reference: paddle/fluid/inference/capi_exp/pd_inference_api.h
// PD_* surface). The predictor itself is the framework's Python Predictor over
// a jit.save StableHLO artifact; this library embeds CPython so C/C++/Go hosts
// link one .so and never touch Python. All entry points are GIL-correct both
// when this library OWNS the interpreter (pure C host) and when it is loaded
// INTO a Python process (tests via ctypes).
#include <Python.h>

#include <cstdint>
#include <cstring>
#include <string>

namespace {

struct PdPredictor {
  PyObject* obj;  // paddle_tpu.inference.Predictor
};

bool g_we_initialized = false;

struct Gil {
  PyGILState_STATE st;
  Gil() { st = PyGILState_Ensure(); }
  ~Gil() { PyGILState_Release(st); }
};

PyObject* bridge() {  // paddle_tpu.inference.capi_bridge (imported once)
  static PyObject* mod = nullptr;
  if (!mod) mod = PyImport_ImportModule("paddle_tpu.inference.capi_bridge");
  return mod;
}

thread_local std::string g_last_error;

void capture_error() {
  PyObject *type, *value, *tb;
  PyErr_Fetch(&type, &value, &tb);
  if (value) {
    PyObject* s = PyObject_Str(value);
    if (s) {
      g_last_error = PyUnicode_AsUTF8(s);
      Py_DECREF(s);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
}

}  // namespace

extern "C" {

// Initialize the embedded interpreter (no-op inside a Python host).
int PD_Init() {
  if (!Py_IsInitialized()) {
    Py_InitializeEx(0);
    g_we_initialized = true;
    PyEval_SaveThread();  // release the GIL for PyGILState_Ensure users
  }
  return 0;
}

const char* PD_GetLastError() { return g_last_error.c_str(); }

// Load a predictor from a jit.save prefix (the .pdmodel/.pdiparams pair).
void* PD_PredictorCreate(const char* model_prefix) {
  PD_Init();
  Gil gil;
  PyObject* mod = bridge();
  if (!mod) { capture_error(); return nullptr; }
  PyObject* pred =
      PyObject_CallMethod(mod, "create", "s", model_prefix);
  if (!pred) { capture_error(); return nullptr; }
  return new PdPredictor{pred};
}

// Run on one float32 input; copies the float32 output into out_buf.
// Returns the number of output elements, or -1 on error. Size query: pass
// out_buf=NULL (out_shape/out_ndim still fill, bounded by out_shape_cap).
int64_t PD_PredictorRunFloat(void* handle, const float* data,
                             const int64_t* shape, int ndim, float* out_buf,
                             int64_t out_cap, int64_t* out_shape,
                             int out_shape_cap, int* out_ndim) {
  if (!handle) return -1;
  Gil gil;
  PdPredictor* p = static_cast<PdPredictor*>(handle);
  int64_t n = 1;
  PyObject* shp = PyTuple_New(ndim);
  for (int i = 0; i < ndim; ++i) {
    n *= shape[i];
    PyTuple_SET_ITEM(shp, i, PyLong_FromLongLong(shape[i]));
  }
  PyObject* raw = PyBytes_FromStringAndSize(
      reinterpret_cast<const char*>(data), n * sizeof(float));
  PyObject* res = PyObject_CallMethod(bridge(), "run_f32", "OOO", p->obj, raw,
                                      shp);
  Py_DECREF(raw);
  Py_DECREF(shp);
  if (!res) { capture_error(); return -1; }
  // res = (bytes, shape tuple)
  PyObject* out_bytes = PyTuple_GetItem(res, 0);
  PyObject* out_shp = PyTuple_GetItem(res, 1);
  Py_ssize_t nbytes = PyBytes_Size(out_bytes);
  int64_t count = nbytes / static_cast<int64_t>(sizeof(float));
  int odim = static_cast<int>(PyTuple_Size(out_shp));
  if (out_ndim) *out_ndim = odim;
  if (out_shape) {
    int lim = odim < out_shape_cap ? odim : out_shape_cap;
    for (int i = 0; i < lim; ++i)
      out_shape[i] = PyLong_AsLongLong(PyTuple_GetItem(out_shp, i));
  }
  if (out_buf && out_cap >= count) {
    std::memcpy(out_buf, PyBytes_AsString(out_bytes),
                count * sizeof(float));
  } else if (out_buf) {
    Py_DECREF(res);
    g_last_error = "output buffer too small";
    return -1;
  }
  Py_DECREF(res);
  return count;
}

int PD_PredictorGetInputNum(void* handle) {
  if (!handle) return -1;
  Gil gil;
  PdPredictor* p = static_cast<PdPredictor*>(handle);
  PyObject* names = PyObject_CallMethod(p->obj, "get_input_names", nullptr);
  if (!names) { capture_error(); return -1; }
  int n = static_cast<int>(PyList_Size(names));
  Py_DECREF(names);
  return n;
}

void PD_PredictorDestroy(void* handle) {
  if (!handle) return;
  Gil gil;
  PdPredictor* p = static_cast<PdPredictor*>(handle);
  Py_XDECREF(p->obj);
  delete p;
}

}  // extern "C"
