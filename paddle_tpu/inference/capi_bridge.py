"""Python side of the C inference API (inference/capi/pd_capi.cpp).

Kept pointer-free: tensors cross the ABI as bytes + shape tuples, so the C
layer needs no numpy C API and the bridge stays version-proof.
"""
from __future__ import annotations

import numpy as np


def create(model_prefix: str):
    from . import Config, create_predictor

    return create_predictor(Config(model_prefix))


def run_f32(predictor, raw: bytes, shape):
    arr = np.frombuffer(raw, np.float32).reshape(tuple(int(d) for d in shape))
    out = predictor.run([arr])[0]
    out = np.ascontiguousarray(np.asarray(out), np.float32)
    return out.tobytes(), tuple(int(d) for d in out.shape)


def load_capi_lib():
    """Build (once) and return the ctypes handle of libpd_capi.so — the
    artifact a C/C++/Go host links against."""
    import os
    import subprocess

    from ..utils import cpp_extension

    import sysconfig

    src_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)), "capi")
    # header/lib flags from THE RUNNING interpreter (python3-config may be
    # absent or belong to a different python)
    v = sysconfig.get_config_var
    paths = sysconfig.get_paths()
    inc = [f"-I{paths['include']}"]
    if paths.get("platinclude") and paths["platinclude"] != paths["include"]:
        inc.append(f"-I{paths['platinclude']}")
    ldflags = [f"-L{v('LIBDIR')}", f"-lpython{v('LDVERSION')}"]
    try:
        ld = subprocess.run(["python3-config", "--ldflags", "--embed"],
                            capture_output=True, text=True)
        if ld.returncode == 0 and ld.stdout.strip():
            ldflags = ld.stdout.split()
    except OSError:
        pass
    build_dir = cpp_extension.get_build_directory()
    os.makedirs(build_dir, exist_ok=True)
    return cpp_extension.load(
        "pd_capi", [os.path.join(src_dir, "pd_capi.cpp")],
        build_directory=build_dir, extra_cxx_cflags=inc,
        extra_ldflags=ldflags)
