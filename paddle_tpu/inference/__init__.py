"""paddle.inference: the deployment predictor API.

Reference: paddle/fluid/inference/api/analysis_predictor.h:91 (AnalysisPredictor
over an optimized program) + python/paddle/inference/__init__.py (Config /
create_predictor / Tensor handles). TPU-native: the "optimized program" is a
jax.export StableHLO artifact produced by paddle_tpu.jit.save — XLA is the
analysis/optimization pass stack, so Config's IR-pass switches are no-ops kept
for API compatibility.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import jax.numpy as jnp
import numpy as np

__all__ = ["Config", "Predictor", "create_predictor", "PredictorTensor"]


class Config:
    """Reference inference/api/paddle_analysis_config.h role."""

    def __init__(self, prog_file: Optional[str] = None,
                 params_file: Optional[str] = None):
        if prog_file and prog_file.endswith(".pdmodel"):
            prog_file = prog_file[: -len(".pdmodel")]
        self.prefix = prog_file
        self._ir_optim = True
        self._memory_optim = True
        self._device = "tpu"

    def set_prog_file(self, path):
        self.prefix = path[:-len(".pdmodel")] if path.endswith(".pdmodel") else path

    def prog_file(self):
        return (self.prefix or "") + ".pdmodel"

    def params_file(self):
        return (self.prefix or "") + ".pdiparams"

    # API-compat switches; XLA always optimizes (no discrete IR passes here)
    def switch_ir_optim(self, flag=True):
        self._ir_optim = flag

    def enable_memory_optim(self, flag=True):
        self._memory_optim = flag

    def enable_persistent_cache(self, dir: Optional[str] = None):
        """Warm-start switch: route this predictor's per-shape compiles of
        the loaded program through the on-disk executable cache
        (``paddle_tpu.jit.persistent_cache``) so a fresh serving process
        performs zero fresh XLA compiles for shapes it has served before.
        The reference analogue is AnalysisConfig's optimized-program
        serialization (``SetOptimCacheDir``)."""
        from ..jit import persistent_cache

        persistent_cache.enable(dir)
        return self

    def disable_glog_info(self):
        pass

    def enable_use_gpu(self, *a, **k):  # GPU configs are inert on TPU builds
        pass

    def disable_gpu(self):
        pass


class PredictorTensor:
    """Input/output handle (reference api/paddle_tensor.h ZeroCopyTensor)."""

    def __init__(self, name):
        self.name = name
        self._value = None

    def copy_from_cpu(self, array):
        self._value = np.ascontiguousarray(array)

    def copy_to_cpu(self):
        return np.asarray(self._value)

    def reshape(self, shape):
        if self._value is not None:
            self._value = self._value.reshape(shape)

    def shape(self):
        return list(self._value.shape) if self._value is not None else []


class Predictor:
    """Runs the exported program (AnalysisPredictor role)."""

    def __init__(self, config: Config):
        from .. import jit

        if not config.prefix:
            raise ValueError("Config needs the model path prefix")
        self._layer = jit.load(config.prefix)
        self._meta = self._load_meta(config.prefix)
        n = self._n_inputs = int(self._meta["num_inputs"])
        self._inputs: Dict[str, PredictorTensor] = {
            f"x{i}": PredictorTensor(f"x{i}") for i in range(n)}
        self._outputs: Dict[str, PredictorTensor] = {}

    @staticmethod
    def _load_meta(prefix):
        import json

        with open(prefix + ".pdmeta") as f:
            return json.load(f)

    def get_input_specs(self):
        """Saved trace signatures (batch dim included; ``None`` dims were
        exported symbolic). Consumed by ``serving.ServingEngine``."""
        from ..static import InputSpec

        return [InputSpec(tuple(s["shape"]), s["dtype"])
                for s in self._meta.get("input_specs", [])]

    def get_input_names(self) -> List[str]:
        return list(self._inputs)

    def get_input_handle(self, name) -> PredictorTensor:
        return self._inputs[name]

    def run(self, inputs: Optional[List[np.ndarray]] = None):
        """Either positional arrays, or handles filled via copy_from_cpu."""
        if inputs is not None:
            arrays = [np.asarray(a) for a in inputs]
        else:
            arrays = [self._inputs[n]._value for n in self.get_input_names()]
            if any(a is None for a in arrays):
                missing = [n for n in self._inputs if self._inputs[n]._value is None]
                raise RuntimeError(f"inputs not set: {missing}")
        outs = self._layer(*[jnp.asarray(a) for a in arrays])
        outs = outs if isinstance(outs, (list, tuple)) else [outs]
        self._outputs = {}
        results = []
        for i, o in enumerate(outs):
            t = PredictorTensor(f"out{i}")
            t.copy_from_cpu(np.asarray(o.data))
            self._outputs[t.name] = t
            results.append(t.copy_to_cpu())
        return results

    def get_output_names(self) -> List[str]:
        return list(self._outputs)

    def get_output_handle(self, name) -> PredictorTensor:
        return self._outputs[name]


def create_predictor(config: Config) -> Predictor:
    return Predictor(config)
