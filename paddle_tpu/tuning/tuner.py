"""The online-tuner driver: one loop, many policies, one ledger.

``OnlineTuner`` periodically assembles a telemetry view from its signal
sources (merged ``fleet_telemetry``, ``slo``, flight-recorder step
series — whatever the host wires in), drives each registered
:class:`~paddle_tpu.tuning.policy.TuningPolicy` through the
observe -> propose -> apply -> measure -> keep-or-rollback state
machine, and publishes every decision through the ``tuner``
observability provider (proposals / applies / keeps / rollbacks /
active config digests).

Safety rails:

* **Kill-switch** — ``PT_ONLINE_TUNING=0`` disables every actuation
  path at the tick level; the provider still reports (``enabled:
  false``) so a fleet with tuning off is visibly off, not silently
  stuck.
* **One in-flight proposal per policy** — a policy under measurement
  cannot propose again; refuted proposals roll back through the same
  boundary they applied through.
* **Flap damping** — a rolled-back target digest is embargoed and each
  keep/rollback starts the policy's ``cooldown_s`` quiet period.
* **No blocking work under the ledger lock** — policy verbs (which may
  fence fleets or roll restarts) run outside it; the lock guards only
  bookkeeping, per the repo's CC-lint contract.
"""
from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence

from .policy import Proposal, TuningPolicy

__all__ = ["OnlineTuner", "tuning_enabled"]


def tuning_enabled() -> bool:
    """The ``PT_ONLINE_TUNING`` kill-switch (default: enabled).  Read
    per-tick so an operator can flip a live process's behavior."""
    return os.environ.get("PT_ONLINE_TUNING", "1") not in ("0", "false")


class _PolicyState:
    def __init__(self) -> None:
        self.phase = "idle"                    # idle | measuring
        self.proposal: Optional[Proposal] = None
        self.cooldown_until = 0.0
        self.rejected: List[str] = []          # embargoed target digests
        self.counts = {"proposals": 0, "applies": 0, "keeps": 0,
                       "rollbacks": 0, "apply_failures": 0, "errors": 0}


class OnlineTuner:
    """Drive ``policies`` every ``interval_s`` (call :meth:`tick`
    yourself for deterministic tests/drills, or :meth:`start` the
    ``pt-tuner-driver`` thread).  ``signal_sources`` maps signal names
    to zero-arg callables; their results form the ``signals`` dict every
    policy observes — single scrape per tick, shared by all policies."""

    def __init__(self, policies: Sequence[TuningPolicy], *,
                 signal_sources: Optional[Dict[str, Callable[[], Any]]]
                 = None, interval_s: float = 5.0,
                 provider_name: Optional[str] = "tuner"):
        from ..analysis.lockdep import lock as _named_lock  # lazy: no cycle

        self.policies = list(policies)
        self.signal_sources = dict(signal_sources or {})
        self.interval_s = float(interval_s)
        self._state = {p.name: _PolicyState() for p in self.policies}
        self._decisions: deque = deque(maxlen=128)
        self._mu = _named_lock("tuning.tuner")
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.ticks = 0
        if provider_name:
            from ..observability import register_provider

            register_provider(provider_name, self.snapshot)

    # -- ledger ---------------------------------------------------------------
    def _record(self, policy: TuningPolicy, event: str,
                proposal: Optional[Proposal], **extra) -> None:
        row = {"t": time.time(), "policy": policy.name, "event": event}
        if proposal is not None:
            row.update(proposal.to_dict())
        row.update(extra)
        with self._mu:
            self._decisions.append(row)

    # -- the loop -------------------------------------------------------------
    def _signals(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        for name, fn in self.signal_sources.items():
            try:
                out[name] = fn()
            except Exception as e:  # a dead source must not stop tuning
                out[name] = {"error": f"{type(e).__name__}: {e}"}
        return out

    def tick(self, now: Optional[float] = None) -> None:
        """One full observe/propose/apply/measure pass (no-op when the
        kill-switch is off)."""
        if not tuning_enabled():
            return
        now = time.monotonic() if now is None else now
        self.ticks += 1
        signals = self._signals()
        for policy in self.policies:
            st = self._state[policy.name]
            try:
                policy.observe(signals)
            except Exception:
                st.counts["errors"] += 1
                continue
            if st.phase == "measuring":
                self._measure(policy, st)
            elif st.phase == "idle" and now >= st.cooldown_until:
                self._propose(policy, st, now)

    def _propose(self, policy: TuningPolicy, st: _PolicyState,
                 now: float) -> None:
        try:
            prop = policy.propose()
        except Exception:
            st.counts["errors"] += 1
            return
        if prop is None or prop.to_digest in st.rejected:
            return
        st.counts["proposals"] += 1
        self._record(policy, "propose", prop)
        try:
            applied = policy.apply(prop)
        except Exception as e:
            st.counts["errors"] += 1
            self._record(policy, "apply_error", prop,
                         error=f"{type(e).__name__}: {e}")
            return
        if not applied:
            st.counts["apply_failures"] += 1
            self._record(policy, "apply_skipped", prop)
            return
        st.counts["applies"] += 1
        st.phase = "measuring"
        st.proposal = prop
        self._record(policy, "apply", prop)

    def _measure(self, policy: TuningPolicy, st: _PolicyState) -> None:
        prop = st.proposal
        assert prop is not None
        try:
            verdict = policy.measure(prop)
        except Exception:
            st.counts["errors"] += 1
            verdict = False  # an unmeasurable apply is an unsafe apply
        if verdict is None:
            return  # window still filling
        if verdict:
            st.counts["keeps"] += 1
            self._record(policy, "keep", prop)
        else:
            try:
                policy.rollback(prop)
            except Exception as e:
                st.counts["errors"] += 1
                self._record(policy, "rollback_error", prop,
                             error=f"{type(e).__name__}: {e}")
            st.counts["rollbacks"] += 1
            st.rejected.append(prop.to_digest)
            self._record(policy, "rollback", prop)
        st.phase = "idle"
        st.proposal = None
        st.cooldown_until = time.monotonic() + policy.cooldown_s

    # -- lifecycle ------------------------------------------------------------
    def start(self) -> "OnlineTuner":
        if self._thread is not None:
            return self
        self._stop.clear()

        def run():
            while not self._stop.wait(self.interval_s):
                try:
                    self.tick()
                except Exception:
                    pass  # the driver thread must survive any tick

        self._thread = threading.Thread(target=run, name="pt-tuner-driver",
                                        daemon=True)
        self._thread.start()
        return self

    def close(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5)

    # -- provider -------------------------------------------------------------
    def snapshot(self) -> Dict[str, Any]:
        with self._mu:
            decisions = list(self._decisions)
        pol: Dict[str, Any] = {}
        for policy in self.policies:
            st = self._state[policy.name]
            row: Dict[str, Any] = dict(st.counts)
            row["phase"] = st.phase
            row["active"] = policy.active_digest()
            if st.rejected:
                row["rejected"] = list(st.rejected)
            if st.proposal is not None:
                row["in_flight"] = st.proposal.to_dict()
            try:
                row.update(policy.snapshot())
            except Exception as e:
                row["snapshot_error"] = f"{type(e).__name__}: {e}"
            pol[policy.name] = row
        return {"enabled": tuning_enabled(), "ticks": self.ticks,
                "policies": pol, "decisions": decisions}
