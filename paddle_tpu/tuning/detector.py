"""Sustained-regression detection over a step-time series.

The online tuner must never act on a single slow step: GC pauses,
checkpoint commits and page migrations all produce legitimate spikes.
:class:`RegressionDetector` therefore keeps a ROBUST windowed baseline
(median + MAD over recent healthy samples — elevated samples are
excluded so the baseline cannot chase the regression it is trying to
detect) and declares a regression only after ``sustain_n`` CONSECUTIVE
elevated samples.  Recovery is hysteretic: once regressed, the detector
returns to ``ok`` only after ``recover_n`` consecutive samples below a
LOWER threshold (``recover_ratio < trigger_ratio``), so a series
oscillating around the trigger line cannot flap the state.

The class is pure (no clocks, no I/O): feed it milliseconds, read the
state.  Both the flight-recorder-driven plan tuner and the unit matrix
in ``tests/test_tuning.py`` drive this exact object.
"""
from __future__ import annotations

import math
from collections import deque
from typing import Deque, Dict, List, Optional

__all__ = ["RegressionDetector"]

# 1.4826 * MAD estimates sigma for normally-distributed noise
_MAD_SIGMA = 1.4826


def _median(xs: List[float]) -> float:
    s = sorted(xs)
    n = len(s)
    mid = n // 2
    return s[mid] if n % 2 else 0.5 * (s[mid - 1] + s[mid])


class RegressionDetector:
    """Three-state detector: ``warming`` -> ``ok`` <-> ``regressed``.

    A sample is *elevated* when it exceeds EVERY guard at once:

    * ``baseline * trigger_ratio``   (relative shift),
    * ``baseline + min_abs_ms``      (absolute floor — a 3 ms baseline
      tripling to 9 ms is noise, not a regression), and
    * ``baseline + mad_k * sigma``   (noise-adaptive: a naturally noisy
      series needs a larger excursion to count).

    ``sustain_n`` consecutive elevated samples flip the state to
    ``regressed``; a single spike (or any sub-``sustain_n`` burst)
    resets the streak and never triggers.  Elevated samples are NOT
    admitted to the baseline window, so the pre-regression baseline
    stays frozen for the rescorer to compare against.
    """

    def __init__(self, *, baseline_window: int = 32, min_samples: int = 8,
                 trigger_ratio: float = 1.3, min_abs_ms: float = 5.0,
                 mad_k: float = 4.0, sustain_n: int = 5,
                 recover_ratio: float = 1.1, recover_n: int = 5):
        if not (1.0 < recover_ratio <= trigger_ratio):
            raise ValueError(
                f"need 1 < recover_ratio <= trigger_ratio for hysteresis, "
                f"got recover={recover_ratio} trigger={trigger_ratio}")
        if sustain_n < 2 or recover_n < 1:
            raise ValueError("sustain_n must be >=2 (never single-spike) "
                             "and recover_n >=1")
        self.baseline_window = int(baseline_window)
        self.min_samples = max(int(min_samples), 2)
        self.trigger_ratio = float(trigger_ratio)
        self.min_abs_ms = float(min_abs_ms)
        self.mad_k = float(mad_k)
        self.sustain_n = int(sustain_n)
        self.recover_ratio = float(recover_ratio)
        self.recover_n = int(recover_n)
        self._healthy: Deque[float] = deque(maxlen=self.baseline_window)
        self._elevated_run: Deque[float] = deque(maxlen=max(sustain_n, 64))
        self._recover_streak = 0
        self.state = "warming"
        self.samples = 0
        self.triggers = 0          # ok -> regressed transitions
        self.recoveries = 0        # regressed -> ok transitions

    # -- thresholds -----------------------------------------------------------
    def baseline_ms(self) -> Optional[float]:
        if len(self._healthy) < self.min_samples:
            return None
        return _median(list(self._healthy))

    def _sigma(self) -> float:
        xs = list(self._healthy)
        med = _median(xs)
        mad = _median([abs(x - med) for x in xs])
        return _MAD_SIGMA * mad

    def trigger_threshold_ms(self) -> Optional[float]:
        base = self.baseline_ms()
        if base is None:
            return None
        return max(base * self.trigger_ratio, base + self.min_abs_ms,
                   base + self.mad_k * self._sigma())

    def recover_threshold_ms(self) -> Optional[float]:
        base = self.baseline_ms()
        if base is None:
            return None
        return max(base * self.recover_ratio,
                   base + 0.5 * self.min_abs_ms)

    def regressed_ms(self) -> Optional[float]:
        """Live measured step time while regressed: the median of the
        elevated run — what the rescorer anchors the ACTIVE candidate
        to (the model's prediction is refuted by measurement)."""
        if not self._elevated_run:
            return None
        return _median(list(self._elevated_run))

    # -- feed -----------------------------------------------------------------
    def update(self, ms: float) -> str:
        """Feed one step-time sample (milliseconds); returns the state."""
        ms = float(ms)
        if not math.isfinite(ms) or ms < 0:
            return self.state
        self.samples += 1
        trig = self.trigger_threshold_ms()
        if trig is None:  # still warming the baseline
            self._healthy.append(ms)
            if self.baseline_ms() is not None:
                self.state = "ok"
            return self.state

        if self.state == "regressed":
            rec = self.recover_threshold_ms()
            if ms <= rec:
                self._recover_streak += 1
                if self._recover_streak >= self.recover_n:
                    self.state = "ok"
                    self.recoveries += 1
                    self._elevated_run.clear()
                    self._recover_streak = 0
                    self._healthy.append(ms)
            else:
                self._recover_streak = 0
                self._elevated_run.append(ms)
            return self.state

        # state == "ok"
        if ms > trig:
            self._elevated_run.append(ms)
            if len(self._elevated_run) >= self.sustain_n:
                self.state = "regressed"
                self.triggers += 1
                self._recover_streak = 0
        else:
            self._elevated_run.clear()
            self._healthy.append(ms)
        return self.state

    def reset(self) -> None:
        """Forget everything — called after an actuator changes the
        config under measurement (old baseline no longer describes the
        new config's step time)."""
        self._healthy.clear()
        self._elevated_run.clear()
        self._recover_streak = 0
        self.state = "warming"

    def snapshot(self) -> Dict[str, object]:
        return {"state": self.state, "samples": self.samples,
                "baseline_ms": self.baseline_ms(),
                "trigger_ms": self.trigger_threshold_ms(),
                "regressed_ms": self.regressed_ms(),
                "triggers": self.triggers, "recoveries": self.recoveries}
