"""The ``TuningPolicy`` contract: observe -> propose -> apply-at-boundary
-> measure -> keep-or-rollback.

Every online actuator — the plan re-ranker, the serving-shape deriver,
and the future autoscaler (ROADMAP direction 1) — is ONE policy behind
this interface.  The :class:`~paddle_tpu.tuning.tuner.OnlineTuner`
drives the state machine and owns the decision ledger; policies supply
the domain logic and the boundary-safe apply/rollback mechanics.

The contract, precisely:

* ``observe(signals)`` — fold new telemetry in.  ``signals`` is the
  tuner-assembled view (merged ``fleet_telemetry``, the ``slo``
  snapshot, flight-recorder step series) so a policy never scrapes on
  its own.
* ``propose()`` — return a :class:`Proposal` when a better config wins
  by the policy's margin, else ``None``.  Proposals are *predictions*:
  they carry the measurable claim the post-apply window will test.
* ``apply(proposal)`` — apply AT A BOUNDARY (checkpoint commit for
  training plans, rolling-restart fence for serving shapes).  Returns
  False if the boundary could not be taken; the tuner drops the
  proposal and re-observes.
* ``measure(proposal)`` — called repeatedly after a successful apply:
  ``True`` = prediction confirmed (keep), ``False`` = refuted
  (the tuner calls ``rollback``), ``None`` = measurement window still
  filling.
* ``rollback(proposal)`` — restore the pre-apply config through the
  same boundary mechanism.  A rolled-back target is remembered by the
  tuner so the identical proposal is not re-applied while the
  cooldown holds.
"""
from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Dict, Optional

__all__ = ["Proposal", "TuningPolicy"]


@dataclass
class Proposal:
    """One proposed config change plus the claim that justifies it."""
    policy: str                     # proposing policy's name
    kind: str                       # "plan" | "serving_shape" | ...
    from_digest: str                # active config identity
    to_digest: str                  # proposed config identity
    payload: Any                    # what apply() needs (config/shape)
    predicted: Dict[str, float] = field(default_factory=dict)
    created_t: float = field(default_factory=time.monotonic)

    def to_dict(self) -> Dict[str, Any]:
        return {"policy": self.policy, "kind": self.kind,
                "from": self.from_digest, "to": self.to_digest,
                "predicted": dict(self.predicted)}


class TuningPolicy:
    """Base policy: subclasses override the five verbs.  ``name`` keys
    the ledger and the ``tuner`` provider; ``cooldown_s`` is the
    minimum quiet period after a keep/rollback before this policy may
    propose again (flap damping)."""

    name = "policy"
    cooldown_s = 30.0

    def observe(self, signals: Dict[str, Any]) -> None:
        """Fold the tuner-assembled telemetry view into policy state."""

    def propose(self) -> Optional[Proposal]:
        return None

    def apply(self, proposal: Proposal) -> bool:
        raise NotImplementedError

    def measure(self, proposal: Proposal) -> Optional[bool]:
        """True=confirmed, False=refuted, None=window still filling."""
        return True

    def rollback(self, proposal: Proposal) -> None:
        raise NotImplementedError

    def active_digest(self) -> str:
        """Identity of the currently-applied config (provider surface)."""
        return ""

    def snapshot(self) -> Dict[str, Any]:
        """Extra policy-specific provider fields (optional)."""
        return {}
