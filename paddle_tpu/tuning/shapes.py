"""Serving-shape derivation from live size distributions.

The quantile-cover problem: given the observed request-size
distribution (prompt token counts, sparse miss counts, active slot
occupancy), pick the SMALLEST bucket set that

* covers the p-quantile (every request at or below the p99 size fits
  some bucket — the engine never rejects in-distribution traffic), and
* bounds the padding-waste fraction (padded - real tokens as a share of
  padded tokens) below ``max_waste``,

under a ``max_buckets`` cap (each bucket is one AOT-compiled
executable — buckets are not free).  The algorithm is greedy-split:
start from the single covering bucket, repeatedly add the observed size
whose addition removes the most padding, stop when the waste bound
holds or the bucket budget is spent.  It is deterministic for a given
weighted size multiset (ties break toward the smaller size), which is
what makes derived shapes reproducible across replicas and restarts.

Sizes may come in raw (``[(size, weight), ...]``) or as a cumulative
histogram snapshot (``bounds``/``counts`` as merged fleet telemetry
carries them) — histogram buckets are collapsed to their UPPER bound,
so a histogram-derived cover is conservative by construction.
"""
from __future__ import annotations

import math
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

__all__ = [
    "quantile_cover", "weighted_quantile", "padding_waste",
    "sizes_from_histogram", "derive_buckets_from_histogram",
    "derive_slots_from_histogram", "shape_digest",
]


def _norm_sizes(sizes: Iterable) -> List[Tuple[int, float]]:
    """Collapse to a sorted weighted multiset of positive int sizes."""
    acc: Dict[int, float] = {}
    for item in sizes:
        if isinstance(item, (tuple, list)):
            s, w = item
        else:
            s, w = item, 1.0
        s = int(s)
        w = float(w)
        if s <= 0 or w <= 0:
            continue
        acc[s] = acc.get(s, 0.0) + w
    return sorted(acc.items())


def weighted_quantile(sizes: Iterable, q: float) -> Optional[int]:
    """Smallest observed size with cumulative weight >= q (0<q<=1)."""
    pairs = _norm_sizes(sizes)
    if not pairs:
        return None
    total = sum(w for _s, w in pairs)
    target = q * total
    cum = 0.0
    for s, w in pairs:
        cum += w
        if cum >= target - 1e-12:
            return s
    return pairs[-1][0]


def padding_waste(sizes: Iterable, buckets: Sequence[int]) -> float:
    """Padding-waste fraction of ``buckets`` over ``sizes``: padded
    minus real, as a share of padded (0 = exact fit).  Sizes above the
    largest bucket are EXCLUDED — they are rejected, not padded."""
    bs = sorted(int(b) for b in buckets)
    if not bs:
        return 0.0
    pad_tot = real_tot = 0.0
    for s, w in _norm_sizes(sizes):
        b = next((x for x in bs if x >= s), None)
        if b is None:
            continue
        pad_tot += b * w
        real_tot += s * w
    return (pad_tot - real_tot) / pad_tot if pad_tot > 0 else 0.0


def _align_up(x: int, align: int) -> int:
    return ((int(x) + align - 1) // align) * align


def quantile_cover(sizes: Iterable, *, q: float = 0.99,
                   max_waste: float = 0.25, max_buckets: int = 8,
                   align: int = 1, min_bucket: Optional[int] = None,
                   max_size: Optional[int] = None) -> Tuple[int, ...]:
    """Derive the smallest bucket set covering the ``q``-quantile of
    ``sizes`` with padding waste <= ``max_waste`` (greedy-split under a
    ``max_buckets`` cap; see module docstring).

    ``align`` rounds every bucket up (page/lane granularity);
    ``min_bucket`` floors the smallest bucket; ``max_size`` clamps the
    covering bucket (an engine hard limit such as ``max_seq_len``).
    Returns a sorted, deduplicated, strictly-increasing tuple — always
    non-empty when any in-range size was observed.
    """
    if not (0.0 < q <= 1.0):
        raise ValueError(f"q must be in (0, 1], got {q}")
    if not (0.0 <= max_waste < 1.0):
        raise ValueError(f"max_waste must be in [0, 1), got {max_waste}")
    if max_buckets < 1:
        raise ValueError("max_buckets must be >= 1")
    align = max(int(align), 1)
    pairs = _norm_sizes(sizes)
    if max_size is not None:
        pairs = [(s, w) for s, w in pairs if s <= int(max_size)]
    if not pairs:
        return ()
    p_cut = weighted_quantile(pairs, q)
    covered = [(s, w) for s, w in pairs if s <= p_cut]
    cover = _align_up(p_cut, align)
    if max_size is not None:
        cover = min(cover, int(max_size))
        cover = max(cover, p_cut)  # never un-cover the quantile
    if min_bucket is not None:
        cover = max(cover, int(min_bucket))
    buckets = [cover]

    def waste(bs: List[int]) -> float:
        return padding_waste(covered, bs)

    # candidate split points: observed (aligned) sizes below the cover
    cands = sorted({_align_up(s, align) for s, _w in covered
                    if _align_up(s, align) < cover
                    and (min_bucket is None
                         or _align_up(s, align) >= int(min_bucket))})
    while waste(buckets) > max_waste and len(buckets) < max_buckets:
        best, best_w = None, waste(buckets)
        for c in cands:
            if c in buckets:
                continue
            w = waste(sorted(buckets + [c]))
            # strictly-better, ties toward the SMALLER size (c ascends)
            if w < best_w - 1e-12:
                best, best_w = c, w
        if best is None:
            break
        buckets = sorted(buckets + [best])
    return tuple(buckets)


# ---------------------------------------------------------------------------
# histogram adapters (merged fleet-telemetry snapshots)
# ---------------------------------------------------------------------------

def sizes_from_histogram(bounds: Sequence[float], counts: Sequence[float]
                         ) -> List[Tuple[int, float]]:
    """Weighted sizes from cumulative-free histogram parts: each bucket
    collapses to its UPPER bound (conservative — derived buckets can
    only over-cover).  The +Inf bucket collapses to the largest finite
    bound: telemetry histograms are provisioned with a top bound above
    any admissible request, so mass there is clamped, not invented."""
    out: List[Tuple[int, float]] = []
    finite = [b for b in bounds if math.isfinite(b)]
    top = max(finite) if finite else None
    for b, c in zip(bounds, counts):
        if c <= 0:
            continue
        ub = b if math.isfinite(b) else top
        if ub is None or ub <= 0:
            continue
        out.append((int(math.ceil(ub)), float(c)))
    return out


def derive_buckets_from_histogram(bounds: Sequence[float],
                                  counts: Sequence[float], **kw
                                  ) -> Tuple[int, ...]:
    """``quantile_cover`` over a histogram delta (see
    :func:`sizes_from_histogram` for the collapse rule)."""
    return quantile_cover(sizes_from_histogram(bounds, counts), **kw)


def derive_slots_from_histogram(bounds: Sequence[float],
                                counts: Sequence[float], *,
                                q: float = 0.99, headroom: int = 1,
                                min_slots: int = 1,
                                max_slots: Optional[int] = None
                                ) -> Optional[int]:
    """Generation slot count from the occupancy distribution: the
    ``q``-quantile of concurrently-active slots plus ``headroom`` —
    enough capacity that admission control, not slot exhaustion, is the
    binding constraint at the tail."""
    sizes = sizes_from_histogram(bounds, counts)
    pq = weighted_quantile(sizes, q)
    if pq is None:
        return None
    n = max(int(pq) + int(headroom), int(min_slots))
    return min(n, int(max_slots)) if max_slots is not None else n


def shape_digest(shape: Dict) -> str:
    """Stable short digest of a serving-shape dict — the identity the
    tuner ledger and the ``tuner`` provider report for active configs."""
    import hashlib
    import json

    blob = json.dumps(shape, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode()).hexdigest()[:12]
