"""Online auto-tuning: the runtime that retunes itself from live
telemetry (docs/performance.md, "Online tuning").

Three actuators behind one :class:`~paddle_tpu.tuning.policy.
TuningPolicy` contract (observe -> propose -> apply-at-boundary ->
measure -> keep-or-rollback):

* :class:`~paddle_tpu.tuning.plan_tuner.ElasticPlanTuner` — re-ranks
  the cached ``plan()`` candidates under live step-time measurements
  and swaps the training fleet at a checkpoint-boundary fence.
* :class:`~paddle_tpu.tuning.serving_tuner.ServingShapePolicy` —
  derives serving buckets / generation slots / sparse miss-caps from
  live request-size histograms and rolls them out through the
  zero-downtime rolling-restart fence (AOT pre-warm before cutover).
* The future autoscaler (ROADMAP direction 1) is just another policy.

``PT_ONLINE_TUNING=0`` is the global kill-switch.
"""
from .detector import RegressionDetector
from .policy import Proposal, TuningPolicy
from .shapes import (derive_buckets_from_histogram,
                     derive_slots_from_histogram, padding_waste,
                     quantile_cover, shape_digest, sizes_from_histogram,
                     weighted_quantile)
from .tuner import OnlineTuner, tuning_enabled

__all__ = [
    "RegressionDetector", "Proposal", "TuningPolicy", "OnlineTuner",
    "tuning_enabled", "quantile_cover", "weighted_quantile",
    "padding_waste", "sizes_from_histogram",
    "derive_buckets_from_histogram", "derive_slots_from_histogram",
    "shape_digest",
]


def __getattr__(name):  # lazy: serving/fleet deps stay import-light
    if name in ("ServingShapePolicy", "apply_tuned_shape"):
        from . import serving_tuner

        return getattr(serving_tuner, name)
    if name == "ElasticPlanTuner":
        from . import plan_tuner

        return plan_tuner.ElasticPlanTuner
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
