"""Plan re-rank actuator: swap a training fleet onto a better parallel
plan when live step times refute the active one.

:class:`ElasticPlanTuner` runs inside the RANK-0 worker of an elastic
gang (feed it ``on_step(ms)`` from a fit callback).  The loop:

* **observe** — every completed step's wall time feeds a
  :class:`~paddle_tpu.tuning.detector.RegressionDetector` (robust
  windowed baseline + sustained-regression test; a single spike never
  triggers).
* **propose** — on a sustained regression, re-score the cached
  ``plan()`` candidates under live conditions
  (:func:`~paddle_tpu.distributed.auto_parallel.planner.
  rescore_candidates` with the calibrated link model), ANCHORING the
  active plan to its measured degraded step time.  A different feasible
  candidate must win by ``margin`` against that measured anchor.
* **apply at the boundary** — publish the winner as
  ``fleet/plan_override`` and raise a ``retune:plan`` fence: every
  worker drains to its committed checkpoint and exits
  ``EXIT_FENCED``; the supervisor restarts the gang (planned — no
  crash budget spent) and the next generation's ``replan()`` picks the
  override up.  The swap happens exactly at a checkpoint boundary,
  never mid-step.
* **measure, keep-or-rollback** — the tuner state survives the fence
  in the control-plane store.  The next generation's tuner measures
  ``measure_steps`` steps under the new plan: median at or below
  ``target_ms`` (the regressed measurement minus the margin) confirms
  the win; otherwise the old plan is re-published and a
  ``retune:rollback`` fence restores it, with the refuted digest
  embargoed so the tuner never flaps.

``PT_ONLINE_TUNING=0`` turns every verb into a no-op.
"""
from __future__ import annotations

import statistics
import time
from typing import Any, Dict, List, Optional, Sequence

from .detector import RegressionDetector
from .policy import Proposal, TuningPolicy
from .tuner import tuning_enabled

__all__ = ["ElasticPlanTuner", "PLAN_STATE_KEY", "PLAN_OVERRIDE_KEY"]

PLAN_STATE_KEY = "fleet/tuner/plan_state"
PLAN_OVERRIDE_KEY = "fleet/plan_override"


def _fresh_state() -> Dict[str, Any]:
    return {"phase": "idle", "active": None, "from_desc": None,
            "from_digest": "", "to_digest": "", "reg_ms": 0.0,
            "target_ms": 0.0, "proposal": None,
            "counters": {"proposals": 0, "applies": 0, "keeps": 0,
                         "rollbacks": 0},
            "rejected": [], "cooldown_until": 0.0, "last_verdict": None}


class ElasticPlanTuner(TuningPolicy):
    """The plan actuator as a :class:`TuningPolicy`, self-driven per
    step (it cannot ride the ``OnlineTuner`` thread: the apply boundary
    kills this very process, so state persists in the fleet store and a
    fresh instance in the next generation finishes the measurement).

    ``candidates`` is the cached ``plan()`` output (``PlanCandidate``s
    or their ``to_dict()`` descriptors) enumerated for THIS world size;
    ``profile`` the matching ``ModelProfile``.  Construct on rank 0
    only."""

    name = "plan_rerank"
    kind = "plan"

    def __init__(self, ctx, profile, candidates: Sequence, *,
                 margin: float = 0.2, measure_steps: int = 5,
                 skip_steps: int = 2, cooldown_s: float = 10.0,
                 detector: Optional[RegressionDetector] = None,
                 link=None, hbm_bytes: Optional[float] = None,
                 optimizer: Any = "adamw",
                 register_provider_name: Optional[str] = "tuner"):
        self.ctx = ctx
        self.profile = profile
        self.candidates = list(candidates)
        self.margin = float(margin)
        self.measure_steps = int(measure_steps)
        self.skip_steps = int(skip_steps)
        self.cooldown_s = float(cooldown_s)
        self.detector = detector or RegressionDetector()
        self.hbm_bytes = hbm_bytes
        self.optimizer = optimizer
        if link is None:
            try:
                from ..cost_model.comm import calibrated_link_model

                link = calibrated_link_model()
            except Exception:
                link = None
        self.link = link
        self._state: Optional[Dict[str, Any]] = None
        self._measure_ms: List[float] = []
        self._fence_raised = False
        if register_provider_name:
            try:
                from ..observability import register_provider

                register_provider(register_provider_name, self.snapshot)
            except Exception:
                pass

    # -- store plumbing -------------------------------------------------------
    def _store(self):
        return getattr(self.ctx, "store", None)

    def _load(self) -> Dict[str, Any]:
        if self._state is not None:
            return self._state
        st = None
        store = self._store()
        if store is not None:
            from ..distributed.fleet.runtime import _probe_json

            try:
                st = _probe_json(store, PLAN_STATE_KEY)
            except Exception:
                st = None
        self._state = dict(_fresh_state(), **st) if isinstance(st, dict) \
            else _fresh_state()
        if self._state["active"] is None:
            self._state["active"] = self._active_digest_from_plan()
        return self._state

    def _save(self) -> None:
        store = self._store()
        if store is not None and self._state is not None:
            from ..distributed.fleet.runtime import _publish

            _publish(store, PLAN_STATE_KEY, self._state)

    def _active_desc(self) -> Optional[Dict[str, Any]]:
        """This generation's published plan descriptor."""
        store = self._store()
        if store is None:
            return None
        from ..distributed.fleet.runtime import _probe_json

        try:
            return _probe_json(store,
                               f"fleet/{self.ctx.gen}/plan")
        except Exception:
            return None

    def _active_digest_from_plan(self) -> str:
        desc = self._active_desc()
        if not isinstance(desc, dict):
            return ""
        from ..distributed.auto_parallel.planner import plan_digest

        cfg = desc.get("config", desc)
        try:
            return plan_digest(cfg)
        except Exception:
            return ""

    def _raise_fence(self, reason: str) -> None:
        store = self._store()
        if store is None:
            return
        from ..distributed.fleet.runtime import _publish

        gen = self.ctx.gen
        # reason FIRST: by the time any worker (or the supervisor) sees
        # the fence counter, the planned "retune:*" name is probe-able
        _publish(store, f"fleet/{gen}/fence_reason", reason)
        store.add(f"fleet/{gen}/fence", 1)
        self._fence_raised = True

    # -- the per-step driver --------------------------------------------------
    def on_step(self, ms: float) -> None:
        """Feed one completed training step's wall time (rank 0)."""
        if not tuning_enabled() or self._fence_raised:
            return
        st = self._load()
        if st["phase"] == "measure":
            self._measure(None, step_ms=float(ms))
            return
        state = self.detector.update(float(ms))
        if state != "regressed":
            return
        if time.time() < float(st.get("cooldown_until", 0.0)):
            return
        prop = self.propose()
        if prop is None:
            # nothing wins under live conditions: hold off re-scoring
            # every subsequent elevated step
            st["cooldown_until"] = time.time() + self.cooldown_s
            self._save()
            return
        st["counters"]["proposals"] += 1
        self.apply(prop)

    # -- policy verbs ---------------------------------------------------------
    def observe(self, signals: Dict[str, Any]) -> None:
        for ms in signals.get("step_ms", ()) or ():
            self.on_step(float(ms))

    def propose(self) -> Optional[Proposal]:
        st = self._load()
        reg_ms = self.detector.regressed_ms()
        if not reg_ms:
            return None
        from ..distributed.auto_parallel.planner import (plan_digest,
                                                         rescore_candidates)

        active = st["active"] or self._active_digest_from_plan()
        reg_s = reg_ms / 1e3
        ranked = rescore_candidates(
            self.profile, self.candidates, link=self.link,
            hbm_bytes=self.hbm_bytes, optimizer=self.optimizer,
            measured={active: reg_s})
        target_ms = reg_ms * (1.0 - self.margin)
        for c in ranked:
            if not c.feasible:
                break
            d = plan_digest(c.config)
            if d == active or d in st["rejected"]:
                continue
            # the challenger must beat the MEASURED degraded step time
            # by the margin (model-predicted absolute scale is not
            # trusted against wall clocks — the anchor is)
            if c.predicted_step_s <= target_ms / 1e3:
                return Proposal(
                    policy=self.name, kind=self.kind,
                    from_digest=active, to_digest=d,
                    payload=c.to_dict() if hasattr(c, "to_dict")
                    else {"config": dict(c.config)},
                    predicted={"predicted_step_ms":
                               round(c.predicted_step_s * 1e3, 3),
                               "target_ms": round(target_ms, 3),
                               "regressed_ms": round(reg_ms, 3),
                               "baseline_ms":
                               round(self.detector.baseline_ms() or 0.0,
                                     3)})
            break  # ranked: the first eligible candidate is the winner
        return None

    def apply(self, proposal: Proposal) -> bool:
        """Publish the override and raise the planned fence — the swap
        lands at the next checkpoint boundary in a fresh generation."""
        if not tuning_enabled():
            return False
        store = self._store()
        if store is None:
            return False
        st = self._load()
        st.update(phase="measure", to_digest=proposal.to_digest,
                  from_digest=proposal.from_digest,
                  from_desc=self._active_desc(),
                  reg_ms=proposal.predicted.get("regressed_ms", 0.0),
                  target_ms=proposal.predicted.get("target_ms", 0.0),
                  proposal=proposal.to_dict(),
                  active=proposal.to_digest)
        st["counters"]["applies"] += 1
        self._save()
        from ..distributed.fleet.runtime import _publish

        _publish(store, PLAN_OVERRIDE_KEY, proposal.payload)
        self._raise_fence("retune:plan")
        return True

    def _measure(self, _proposal, step_ms: Optional[float] = None
                 ) -> Optional[bool]:
        st = self._load()
        if step_ms is not None:
            self._measure_ms.append(step_ms)
        if len(self._measure_ms) < self.skip_steps + self.measure_steps:
            return None
        med = statistics.median(self._measure_ms[self.skip_steps:])
        kept = med <= float(st["target_ms"]) or st["target_ms"] <= 0
        st["last_verdict"] = {"kept": bool(kept),
                              "measured_ms": round(med, 3),
                              "target_ms": st["target_ms"],
                              "digest": st["to_digest"]}
        st["cooldown_until"] = time.time() + self.cooldown_s
        st["phase"] = "idle"
        self._measure_ms = []
        if kept:
            st["counters"]["keeps"] += 1
            st["active"] = st["to_digest"]
            self._save()
            return True
        self.rollback(_proposal)
        return False

    def measure(self, proposal: Proposal) -> Optional[bool]:
        return self._measure(proposal)

    def rollback(self, _proposal) -> None:
        """Re-publish the pre-swap plan and fence back onto it."""
        st = self._load()
        st["counters"]["rollbacks"] += 1
        if st["to_digest"]:
            st["rejected"] = sorted(set(st["rejected"])
                                    | {st["to_digest"]})
        st["active"] = st["from_digest"]
        st["phase"] = "idle"
        self._save()
        store = self._store()
        if store is not None and isinstance(st["from_desc"], dict):
            from ..distributed.fleet.runtime import _publish

            _publish(store, PLAN_OVERRIDE_KEY, st["from_desc"])
            self._raise_fence("retune:rollback")

    # -- provider surface -----------------------------------------------------
    def active_digest(self) -> str:
        st = self._load()
        return st["active"] or ""

    def snapshot(self) -> Dict[str, Any]:
        st = dict(self._load())
        st["detector"] = self.detector.snapshot()
        st["enabled"] = tuning_enabled()
        return st
