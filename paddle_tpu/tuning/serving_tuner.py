"""Serving-shape actuator: derive buckets/slots/miss-caps from live
request distributions and apply them fleet-wide with zero downtime.

Two halves:

* :class:`ServingShapePolicy` — the supervisor-side
  :class:`~paddle_tpu.tuning.policy.TuningPolicy`.  It folds the merged
  ``fleet_telemetry`` histograms (``prompt_tokens``,
  ``gen_active_slots``, ``request_tokens``, ``sparse_miss_rows``)
  through restart-safe :class:`~paddle_tpu.observability.fleet.
  HistogramWindow`s, derives a shape via quantile-cover
  (:mod:`paddle_tpu.tuning.shapes`), and actuates it through
  ``ServingFleet.apply_serving_shape`` — a rolling restart in which
  every replica AOT-warms the NEW bucket family before re-admitting
  traffic, so the zero-retrace invariant holds across the cutover.

* :func:`apply_tuned_shape` — the replica-side respec, invoked by
  ``replica_main`` when the supervisor stamped ``PT_TUNED_SHAPE`` into
  the spawn env.  Duck-typed over the two engine families: a
  generation engine (bucket/slot config baked at construction) is
  REBUILT with the tuned config; a batch serving engine (respec-able
  in place) gets a derived :class:`~paddle_tpu.serving.buckets.
  BucketSpec`.  Both paths validate through ``BucketSpec`` — a bad
  derivation fails before any executable is warmed.

The measurable claim a shape proposal carries is its predicted padding
waste over the observation window; the post-apply measurement window
recomputes live waste under the new shape and the tuner keeps or rolls
back on that evidence.
"""
from __future__ import annotations

import copy
import time
from typing import Any, Dict, Optional, Tuple

from .policy import Proposal, TuningPolicy
from .shapes import (derive_buckets_from_histogram,
                     derive_slots_from_histogram, padding_waste,
                     shape_digest, sizes_from_histogram)

__all__ = ["ServingShapePolicy", "apply_tuned_shape", "DECLARED_DIGEST"]

# the identity of the hand-declared (un-tuned) shape: rollback target
DECLARED_DIGEST = "declared"

# histogram family -> shape field it derives
_FAMILIES = ("prompt_tokens", "gen_active_slots", "request_tokens",
             "sparse_miss_rows")


def _validate_shape(shape: Dict[str, Any]) -> None:
    """Every derived axis runs through the SAME BucketSpec validation
    as a hand-declared spec (satellite contract): positive ints, no
    duplicates, canonical ascending, floor respected."""
    from ..serving.buckets import BucketSpec

    floor = shape.get("observed_floor")
    if shape.get("prefill_buckets"):
        BucketSpec._validated("prefill_buckets",
                              shape["prefill_buckets"], floor=floor)
    if shape.get("seq_buckets"):
        BucketSpec._validated("seq_buckets", shape["seq_buckets"],
                              floor=floor)
    if shape.get("miss_caps"):
        BucketSpec._validated("miss_caps", shape["miss_caps"])
    if "max_slots" in shape and int(shape["max_slots"]) < 1:
        raise ValueError(
            f"tuned shape: max_slots must be >= 1, got "
            f"{shape['max_slots']}")


def apply_tuned_shape(engine, shape: Dict[str, Any]):
    """Replica-side respec: apply a derived serving shape to a freshly
    built engine BEFORE warmup.  Returns the engine to serve (possibly
    a rebuilt instance).  Unknown engine kinds pass through untouched —
    a tuned fleet can mix respec-able and fixed-shape replicas."""
    _validate_shape(shape)
    cfg = getattr(engine, "config", None)
    if cfg is not None and hasattr(cfg, "prefill_buckets"):
        # generation engine: slots/pages/buckets are baked into the
        # arenas at construction — rebuild with the tuned config
        new_cfg = copy.copy(cfg)
        if shape.get("prefill_buckets"):
            new_cfg.prefill_buckets = tuple(
                sorted({int(b) for b in shape["prefill_buckets"]}))
        if shape.get("max_slots"):
            new_cfg.max_slots = int(shape["max_slots"])
            new_cfg.num_pages = None  # re-derive for the new slot count
        name = getattr(engine, "name", None)
        try:
            return type(engine)(engine.model, new_cfg, name=name)
        except TypeError:
            return type(engine)(engine.model, new_cfg)
    if hasattr(engine, "respec") and shape.get("seq_buckets"):
        # batch serving engine: swap the BucketSpec in place (respec
        # AOT-warms the new family before the swap)
        from ..serving.buckets import BucketSpec

        old = engine.buckets
        spec = BucketSpec(
            batch_sizes=tuple(shape.get("batch_buckets")
                              or old.batch_sizes),
            seq_lens=tuple(shape["seq_buckets"]),
            seq_axis=old.seq_axis, pad_value=old.pad_value,
            observed_floor=shape.get("observed_floor"))
        engine.respec(spec)
        return engine
    tgt = getattr(engine, "target", None)
    if shape.get("miss_caps") and hasattr(tgt, "set_miss_caps"):
        tgt.set_miss_caps(shape["miss_caps"])
    return engine


class ServingShapePolicy(TuningPolicy):
    """Derive serving shapes from live size distributions and roll them
    out at the rolling-restart fence boundary.

    ``declared`` is the hand-declared shape the fleet booted with (the
    rollback target and the waste baseline); fields mirror the tuned
    shape: ``prefill_buckets``, ``max_slots``, ``seq_buckets``,
    ``miss_caps``.  A proposal is raised only when the derived shape
    differs from the active one AND its predicted padding waste beats
    the active shape's live waste by ``improve_margin`` on ``min_count``
    or more in-window requests."""

    name = "serving_shape"
    kind = "serving_shape"

    def __init__(self, fleet, declared: Optional[Dict[str, Any]] = None,
                 *, window_s: float = 60.0, min_count: int = 50,
                 q: float = 0.99, max_waste: float = 0.25,
                 max_buckets: int = 8, align: int = 1,
                 min_bucket: Optional[int] = None,
                 max_size: Optional[int] = None,
                 slot_headroom: int = 1,
                 max_slots_cap: Optional[int] = None,
                 improve_margin: float = 0.05,
                 measure_count: int = 20,
                 measure_timeout_s: float = 120.0,
                 cooldown_s: float = 30.0):
        from ..observability.fleet import HistogramWindow

        self.fleet = fleet
        self.declared = dict(declared or {})
        self.window_s = float(window_s)
        self.min_count = int(min_count)
        self.q = float(q)
        self.max_waste = float(max_waste)
        self.max_buckets = int(max_buckets)
        self.align = int(align)
        self.min_bucket = min_bucket
        self.max_size = max_size
        self.slot_headroom = int(slot_headroom)
        self.max_slots_cap = max_slots_cap
        self.improve_margin = float(improve_margin)
        self.measure_count = int(measure_count)
        self.measure_timeout_s = float(measure_timeout_s)
        self.cooldown_s = float(cooldown_s)
        self._win = {f: HistogramWindow(window_s=self.window_s)
                     for f in _FAMILIES}
        self._active: Dict[str, Any] = dict(self.declared)
        self._active_digest = DECLARED_DIGEST
        self._prev: Optional[Dict[str, Any]] = None
        self._prev_digest = DECLARED_DIGEST
        self._applied_t: Optional[float] = None
        self._measure_base: Optional[Dict[str, int]] = None

    # -- observe --------------------------------------------------------------
    def observe(self, signals: Dict[str, Any]) -> None:
        merged = signals.get("fleet_telemetry") or {}
        hists = merged.get("histograms", {})
        now = time.monotonic()
        for fam, win in self._win.items():
            snap = (hists.get(fam) or {}).get("fleet")
            win.update(now, snap)

    # -- derivation -----------------------------------------------------------
    def _window_sizes(self, family: str):
        bounds, counts = self._win[family].delta()
        return sizes_from_histogram(bounds, counts) if bounds else []

    def _derive(self) -> Tuple[Optional[Dict[str, Any]], Dict[str, float]]:
        """(shape, prediction) from the current windows — None when no
        family has enough in-window mass to derive from."""
        shape: Dict[str, Any] = {}
        predicted: Dict[str, float] = {}
        kw = dict(q=self.q, max_waste=self.max_waste,
                  max_buckets=self.max_buckets, align=self.align,
                  min_bucket=self.min_bucket, max_size=self.max_size)
        for fam, field in (("prompt_tokens", "prefill_buckets"),
                           ("request_tokens", "seq_buckets"),
                           ("sparse_miss_rows", "miss_caps")):
            bounds, counts = self._win[fam].delta()
            if not bounds or sum(counts) < self.min_count:
                continue
            fam_kw = dict(kw)
            if fam == "sparse_miss_rows":
                # a zero-miss lookup still needs a (smallest) cap
                fam_kw["min_bucket"] = max(int(self.min_bucket or 1), 1)
            buckets = derive_buckets_from_histogram(bounds, counts,
                                                    **fam_kw)
            if buckets:
                shape[field] = list(buckets)
                sizes = sizes_from_histogram(bounds, counts)
                predicted[f"{field}_waste"] = round(
                    padding_waste(sizes, buckets), 4)
                floor = min(s for s, _w in sizes)
                shape["observed_floor"] = min(
                    shape.get("observed_floor", floor), floor)
        sb, sc = self._win["gen_active_slots"].delta()
        if sb and sum(sc) >= self.min_count:
            slots = derive_slots_from_histogram(
                sb, sc, q=self.q, headroom=self.slot_headroom,
                max_slots=self.max_slots_cap)
            if slots:
                shape["max_slots"] = int(slots)
        if not shape:
            return None, {}
        # observed_floor below any derived bucket axis would make the
        # spec self-rejecting for axes whose smallest observed size is
        # larger; only keep a floor that every axis satisfies
        floor = shape.get("observed_floor")
        if floor is not None:
            for f in ("prefill_buckets", "seq_buckets"):
                if shape.get(f) and shape[f][0] < floor:
                    shape.pop("observed_floor", None)
                    break
        shape["digest"] = shape_digest(
            {k: v for k, v in shape.items() if k != "digest"})
        return shape, predicted

    def _live_waste(self) -> Dict[str, float]:
        """Padding waste of the CURRENT window under the ACTIVE shape."""
        out: Dict[str, float] = {}
        for fam, field in (("prompt_tokens", "prefill_buckets"),
                           ("request_tokens", "seq_buckets"),
                           ("sparse_miss_rows", "miss_caps")):
            buckets = self._active.get(field)
            if not buckets:
                continue
            sizes = self._window_sizes(fam)
            if sizes:
                out[f"{field}_waste"] = round(
                    padding_waste(sizes, buckets), 4)
        return out

    # -- propose --------------------------------------------------------------
    def propose(self) -> Optional[Proposal]:
        shape, predicted = self._derive()
        if shape is None or shape["digest"] == self._active_digest:
            return None
        live = self._live_waste()
        # the proposal must WIN: on every axis both shapes cover, the
        # derived waste beats live by the margin on at least one axis
        # and regresses none (axes the active shape doesn't declare are
        # a free win — the derived shape covers a blind spot)
        better, worse = False, False
        for key, pw in predicted.items():
            lw = live.get(key)
            if lw is None:
                better = True
            elif pw <= lw - self.improve_margin:
                better = True
            elif pw > lw + self.improve_margin:
                worse = True
        if shape.get("max_slots") and \
                shape["max_slots"] != self._active.get("max_slots"):
            better = True
        if worse or not better:
            return None
        return Proposal(policy=self.name, kind=self.kind,
                        from_digest=self._active_digest,
                        to_digest=shape["digest"], payload=shape,
                        predicted=predicted)

    # -- actuate --------------------------------------------------------------
    def apply(self, proposal: Proposal) -> bool:
        out = self.fleet.apply_serving_shape(proposal.payload)
        if not out.get("ok"):
            return False
        self._prev, self._prev_digest = self._active, self._active_digest
        self._active = dict(proposal.payload)
        self._active_digest = proposal.to_digest
        self._applied_t = time.monotonic()
        # measurement restarts from the post-apply distribution only
        self._measure_base = {
            f: self._win[f].total() for f in _FAMILIES}
        return True

    def measure(self, proposal: Proposal) -> Optional[bool]:
        assert self._applied_t is not None
        fresh = 0
        for fam in ("prompt_tokens", "request_tokens"):
            base = (self._measure_base or {}).get(fam, 0)
            fresh += max(self._win[fam].total() - base, 0)
        if fresh < self.measure_count:
            if time.monotonic() - self._applied_t > \
                    self.measure_timeout_s:
                return True  # no traffic to refute the claim: keep
            return None
        live = self._live_waste()
        for key, pw in proposal.predicted.items():
            lw = live.get(key)
            if lw is not None and lw > pw + self.improve_margin:
                return False  # live waste blew past the predicted claim
        return True

    def rollback(self, proposal: Proposal) -> None:
        if self._prev_digest == DECLARED_DIGEST:
            with self.fleet._lock:
                self.fleet.extra_env.pop("PT_TUNED_SHAPE", None)
            self.fleet.rolling_restart()
        else:
            assert self._prev is not None
            self.fleet.apply_serving_shape(self._prev)
        self._active = dict(self._prev or self.declared)
        self._active_digest = self._prev_digest

    # -- provider surface -----------------------------------------------------
    def active_digest(self) -> str:
        return self._active_digest

    def snapshot(self) -> Dict[str, Any]:
        return {"active_shape": {k: v for k, v in self._active.items()},
                "window_counts": {f: self._win[f].total()
                                  for f in _FAMILIES},
                "live_waste": self._live_waste()}
