"""paddle.compat — text/bytes conversion helpers.

Reference: python/paddle/compat.py:25 (to_text/to_bytes recursing through
containers, py2-era round/floor_division retained for script compat).
"""
from __future__ import annotations

import math

__all__ = ["to_text", "to_bytes", "round", "floor_division",
           "get_exception_message"]


def _convert(obj, one, inplace):
    if obj is None:
        return obj
    if isinstance(obj, list):
        if inplace:
            obj[:] = [_convert(i, one, inplace) for i in obj]
            return obj
        return [_convert(i, one, inplace) for i in obj]
    if isinstance(obj, set):
        conv = {_convert(i, one, inplace) for i in obj}
        if inplace:
            obj.clear()
            obj.update(conv)
            return obj
        return conv
    if isinstance(obj, dict):
        conv = {_convert(k, one, False): _convert(v, one, False)
                for k, v in obj.items()}
        if inplace:
            obj.clear()
            obj.update(conv)
            return obj
        return conv
    if isinstance(obj, (tuple,)):
        return tuple(_convert(i, one, False) for i in obj)
    return one(obj)


def to_text(obj, encoding="utf-8", inplace=False):
    """bytes -> str recursively through list/set/dict/tuple (compat.py:25)."""
    def one(x):
        return x.decode(encoding) if isinstance(x, (bytes, bytearray)) else x
    return _convert(obj, one, inplace)


def to_bytes(obj, encoding="utf-8", inplace=False):
    """str -> bytes recursively through containers (compat.py:121)."""
    def one(x):
        return x.encode(encoding) if isinstance(x, str) else x
    return _convert(obj, one, inplace)


def round(x, d=0):  # noqa: A001 (the reference shadows the builtin too)
    """Python-2-style half-away-from-zero rounding (compat.py:206)."""
    if x is None or (isinstance(x, float) and math.isnan(x)):
        return x
    if isinstance(x, float) and math.isinf(x):
        return x
    p = 10 ** d
    if x >= 0:
        out = math.floor(x * p + 0.5) / p
    else:
        out = math.ceil(x * p - 0.5) / p
    return out if d > 0 else float(int(out)) if d == 0 else out


def floor_division(x, y):
    return x // y


def get_exception_message(exc) -> str:
    return str(exc)
