"""paddle.onnx (reference: python/paddle/onnx/export.py wrapping paddle2onnx).

TPU-native re-design: paddle2onnx walks a ProgramDesc; here the captured
jaxpr of the model's forward IS the graph, so export is a jaxpr->ONNX
converter. The ONNX file is emitted with a hand-rolled protobuf wire encoder
(the ModelProto schema is stable; no onnx package ships in the image), so
the artifact is a standard `.onnx` consumable by onnxruntime/netron outside.
The inference path that stays on TPU should prefer `paddle_tpu.jit.save`
(StableHLO via jax.export); this module serves the interchange role.
"""
from __future__ import annotations

import struct
from typing import Dict, List, Optional

import numpy as np

__all__ = ["export"]


# -- protobuf wire-format encoder --------------------------------------------
# wire types: 0 varint, 1 fixed64, 2 length-delimited, 5 fixed32

def _varint(v: int) -> bytes:
    out = bytearray()
    v &= (1 << 64) - 1
    while True:
        b = v & 0x7F
        v >>= 7
        out.append(b | (0x80 if v else 0))
        if not v:
            return bytes(out)


def _field(num: int, wire: int) -> bytes:
    return _varint((num << 3) | wire)


def _f_varint(num: int, v: int) -> bytes:
    return _field(num, 0) + _varint(v)


def _f_bytes(num: int, payload: bytes) -> bytes:
    return _field(num, 2) + _varint(len(payload)) + payload


def _f_str(num: int, s: str) -> bytes:
    return _f_bytes(num, s.encode())


def _f_float(num: int, v: float) -> bytes:
    return _field(num, 5) + struct.pack("<f", float(v))


# -- ONNX message builders (field numbers per the official onnx.proto) -------

_DTYPE = {"float32": 1, "uint8": 2, "int8": 3, "int32": 6, "int64": 7,
          "bool": 9, "float16": 10, "float64": 11, "bfloat16": 16}


def _tensor_proto(name: str, arr: np.ndarray) -> bytes:
    dt = _DTYPE.get(str(arr.dtype))
    if dt is None:
        raise ValueError(f"onnx export: unsupported dtype {arr.dtype}")
    msg = b"".join(_f_varint(1, int(d)) for d in arr.shape)
    msg += _f_varint(2, dt)
    msg += _f_str(8, name)
    msg += _f_bytes(9, np.ascontiguousarray(arr).tobytes())
    return msg


def _value_info(name: str, shape, dtype: str) -> bytes:
    dims = b"".join(_f_bytes(1, _f_varint(1, int(d))) for d in shape)
    tensor_type = _f_varint(1, _DTYPE[dtype]) + _f_bytes(2, dims)
    type_proto = _f_bytes(1, tensor_type)
    return _f_str(1, name) + _f_bytes(2, type_proto)


def _attr(name: str, value) -> bytes:
    msg = _f_str(1, name)
    if isinstance(value, bool) or isinstance(value, (int, np.integer)):
        msg += _f_varint(3, int(value)) + _f_varint(20, 2)   # INT
    elif isinstance(value, float):
        msg += _f_float(2, value) + _f_varint(20, 1)          # FLOAT
    elif isinstance(value, str):
        msg += _f_bytes(4, value.encode()) + _f_varint(20, 3)  # STRING
    elif isinstance(value, (list, tuple)) and all(
            isinstance(v, (int, np.integer)) for v in value):
        msg += b"".join(_f_varint(8, int(v)) for v in value)
        msg += _f_varint(20, 7)                               # INTS
    elif isinstance(value, np.ndarray):
        msg += _f_bytes(5, _tensor_proto(name + "_t", value))
        msg += _f_varint(20, 4)                               # TENSOR
    else:
        raise ValueError(f"onnx export: bad attribute {name}={value!r}")
    return msg


def _node(op_type: str, inputs: List[str], outputs: List[str],
          name: str = "", **attrs) -> bytes:
    msg = b"".join(_f_str(1, i) for i in inputs)
    msg += b"".join(_f_str(2, o) for o in outputs)
    if name:
        msg += _f_str(3, name)
    msg += _f_str(4, op_type)
    msg += b"".join(_f_bytes(5, _attr(k, v)) for k, v in attrs.items())
    return msg


class _Graph:
    def __init__(self, name: str):
        self.name = name
        self.nodes: List[bytes] = []
        self.initializers: List[bytes] = []
        self.inputs: List[bytes] = []
        self.outputs: List[bytes] = []
        self.n = 0

    def fresh(self, hint="v") -> str:
        self.n += 1
        return f"{hint}_{self.n}"

    def add(self, op_type, inputs, outputs=None, **attrs):
        outputs = outputs or [self.fresh(op_type.lower())]
        self.nodes.append(_node(op_type, inputs, outputs,
                                name=f"{op_type}_{self.n}", **attrs))
        return outputs[0]

    def const(self, arr: np.ndarray, name=None) -> str:
        name = name or self.fresh("const")
        self.initializers.append(_tensor_proto(name, np.asarray(arr)))
        return name

    def serialize(self, opset: int) -> bytes:
        g = b"".join(_f_bytes(1, n) for n in self.nodes)
        g += _f_str(2, self.name)
        g += b"".join(_f_bytes(5, t) for t in self.initializers)
        g += b"".join(_f_bytes(11, i) for i in self.inputs)
        g += b"".join(_f_bytes(12, o) for o in self.outputs)
        opset_id = _f_str(1, "") + _f_varint(2, opset)
        model = _f_varint(1, 8)                   # ir_version 8
        model += _f_str(2, "paddle_tpu")          # producer_name
        model += _f_str(3, "1.0")
        model += _f_bytes(7, g)
        model += _f_bytes(8, opset_id)
        return model


# -- jaxpr -> ONNX conversion -------------------------------------------------

def _np_of(var):
    return np.asarray(var)


def _convert_eqn(g: _Graph, eqn, env: Dict[int, str]):
    import jax

    name = eqn.primitive.name

    def inp(i):
        v = eqn.invars[i]
        if type(v).__name__ == "Literal":
            return g.const(np.asarray(v.val))
        return env[id(v)]

    def set_out(val, i=0):
        env[id(eqn.outvars[i])] = val

    # sub-jaxpr wrappers inline transparently
    sub = None
    for key in ("jaxpr", "call_jaxpr", "fun_jaxpr"):
        j = eqn.params.get(key)
        if j is not None:
            sub = j.jaxpr if hasattr(j, "jaxpr") else j
            break
    if sub is not None:
        closed = eqn.params.get("jaxpr") or eqn.params.get("call_jaxpr") or \
            eqn.params.get("fun_jaxpr")
        consts = getattr(closed, "consts", [])
        for cv, cval in zip(sub.constvars, consts):
            env[id(cv)] = g.const(np.asarray(cval))
        for ov, iv in zip(eqn.invars, sub.invars):
            if type(ov).__name__ != "Literal":
                env[id(iv)] = env[id(ov)]
            else:
                env[id(iv)] = g.const(np.asarray(ov.val))
        _convert_jaxpr(g, sub, env)
        for ov, iv in zip(eqn.outvars, sub.outvars):
            env[id(ov)] = env[id(iv)]
        return

    binop = {"add": "Add", "sub": "Sub", "mul": "Mul", "div": "Div",
             "max": "Max", "min": "Min", "pow": "Pow"}
    unop = {"exp": "Exp", "log": "Log", "tanh": "Tanh", "sqrt": "Sqrt",
            "neg": "Neg", "abs": "Abs", "erf": "Erf", "logistic": "Sigmoid",
            "floor": "Floor", "ceil": "Ceil", "sign": "Sign", "sin": "Sin",
            "cos": "Cos", "stop_gradient": "Identity", "copy": "Identity"}
    if name in binop:
        set_out(g.add(binop[name], [inp(0), inp(1)]))
    elif name in unop:
        set_out(g.add(unop[name], [inp(0)]))
    elif name == "rsqrt":
        s = g.add("Sqrt", [inp(0)])
        set_out(g.add("Reciprocal", [s]))
    elif name == "erfc":  # no ONNX Erfc: 1 - Erf(x)
        e = g.add("Erf", [inp(0)])
        one = g.const(np.asarray(1.0, np.dtype(eqn.invars[0].aval.dtype)))
        set_out(g.add("Sub", [one, e]))
    elif name == "log1p":
        one = g.const(np.asarray(1.0, np.dtype(eqn.invars[0].aval.dtype)))
        set_out(g.add("Log", [g.add("Add", [one, inp(0)])]))
    elif name == "expm1":
        one = g.const(np.asarray(1.0, np.dtype(eqn.invars[0].aval.dtype)))
        set_out(g.add("Sub", [g.add("Exp", [inp(0)]), one]))
    elif name == "square":
        set_out(g.add("Mul", [inp(0), inp(0)]))
    elif name == "integer_pow":
        p = g.const(np.asarray(float(eqn.params["y"]), np.float32))
        set_out(g.add("Pow", [inp(0), p]))
    elif name == "dot_general":
        (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
        lnd = len(eqn.invars[0].aval.shape)
        rnd = len(eqn.invars[1].aval.shape)
        if not lb and not rb and lc == (lnd - 1,) and rc == (rnd - 2 if rnd > 1 else 0,):
            set_out(g.add("MatMul", [inp(0), inp(1)]))
        else:
            raise ValueError(
                f"onnx export: general dot_general {eqn.params['dimension_numbers']} "
                "not supported (batched/transposed contractions)")
    elif name == "reshape":
        shape = g.const(np.asarray(eqn.outvars[0].aval.shape, np.int64))
        set_out(g.add("Reshape", [inp(0), shape]))
    elif name == "transpose":
        set_out(g.add("Transpose", [inp(0)],
                      perm=list(eqn.params["permutation"])))
    elif name == "broadcast_in_dim":
        # insert singleton dims then Expand to the target shape
        out_shape = eqn.outvars[0].aval.shape
        bdims = eqn.params["broadcast_dimensions"]
        interim = [1] * len(out_shape)
        for i, d in enumerate(bdims):
            interim[d] = eqn.invars[0].aval.shape[i]
        r = g.add("Reshape", [inp(0), g.const(np.asarray(interim, np.int64))])
        set_out(g.add("Expand",
                      [r, g.const(np.asarray(out_shape, np.int64))]))
    elif name == "reduce_sum":
        # ReduceSum takes axes as an INPUT from opset 13
        axes = g.const(np.asarray(eqn.params["axes"], np.int64))
        set_out(g.add("ReduceSum", [inp(0), axes], keepdims=0))
    elif name in ("reduce_max", "reduce_min", "reduce_prod"):
        # axes stay an ATTRIBUTE for these until opset 18
        op = {"reduce_max": "ReduceMax", "reduce_min": "ReduceMin",
              "reduce_prod": "ReduceProd"}[name]
        set_out(g.add(op, [inp(0)], keepdims=0,
                      axes=list(eqn.params["axes"])))
    elif name == "convert_element_type":
        to = _DTYPE[str(np.dtype(eqn.params["new_dtype"]))]
        set_out(g.add("Cast", [inp(0)], to=to))
    elif name == "select_n":
        # select_n(pred, on_false, on_true) with bool pred == Where
        set_out(g.add("Where", [inp(0), inp(2), inp(1)]))
    elif name == "concatenate":
        set_out(g.add("Concat", [inp(i) for i in range(len(eqn.invars))],
                      axis=int(eqn.params["dimension"])))
    elif name == "slice":
        starts = g.const(np.asarray(eqn.params["start_indices"], np.int64))
        ends = g.const(np.asarray(eqn.params["limit_indices"], np.int64))
        axes = g.const(np.arange(len(eqn.params["start_indices"]),
                                 dtype=np.int64))
        strides = eqn.params.get("strides") or \
            [1] * len(eqn.params["start_indices"])
        steps = g.const(np.asarray(strides, np.int64))
        set_out(g.add("Slice", [inp(0), starts, ends, axes, steps]))
    elif name == "squeeze":
        shape = g.const(np.asarray(eqn.outvars[0].aval.shape, np.int64))
        set_out(g.add("Reshape", [inp(0), shape]))
    elif name == "gather":
        # safe only for the simple take-along-one-axis form; anything else
        # (multi-dim index maps, partial slices) must not silently miscompile
        dn = eqn.params["dimension_numbers"]
        slice_sizes = eqn.params["slice_sizes"]
        x_shape = eqn.invars[0].aval.shape
        sim = tuple(dn.start_index_map)
        if (len(sim) == 1 and tuple(dn.collapsed_slice_dims) == sim
                and all(s == (1 if i == sim[0] else x_shape[i])
                        for i, s in enumerate(slice_sizes))):
            set_out(g.add("Gather", [inp(0), inp(1)], axis=int(sim[0])))
        else:
            raise ValueError(
                f"onnx export: general gather {dn} has no ONNX mapping; "
                "use paddle_tpu.jit.save for the StableHLO artifact")
    elif name == "conv_general_dilated":
        dn = eqn.params["dimension_numbers"]
        nd = len(eqn.invars[0].aval.shape)
        iota = tuple(range(nd))
        if (tuple(dn.lhs_spec) != iota or tuple(dn.rhs_spec) != iota
                or tuple(dn.out_spec) != iota):
            raise ValueError(
                f"onnx export: conv layout {dn} is not NC*/OI* "
                "(channel-first); transpose to NCHW before export")
        if eqn.params["batch_group_count"] != 1:
            raise ValueError("onnx export: batch_group_count > 1 conv has "
                             "no ONNX mapping")
        if any(d != 1 for d in eqn.params["lhs_dilation"]):
            raise ValueError(
                "onnx export: lhs-dilated (transposed) conv is not mapped; "
                "export the ConvTranspose layer form instead")
        pads = [p[0] for p in eqn.params["padding"]] + \
            [p[1] for p in eqn.params["padding"]]
        set_out(g.add("Conv", [inp(0), inp(1)],
                      strides=list(eqn.params["window_strides"]),
                      pads=pads,
                      dilations=list(eqn.params["rhs_dilation"]),
                      group=int(eqn.params["feature_group_count"]),
                      kernel_shape=list(eqn.invars[1].aval.shape[2:])))
    elif name in ("reduce_window_max", "reduce_window_sum"):
        wd = eqn.params["window_dimensions"]
        ws = eqn.params["window_strides"]
        pad = eqn.params["padding"]
        if any(d != 1 for d in eqn.params["base_dilation"]) or \
                any(d != 1 for d in eqn.params["window_dilation"]):
            raise ValueError("onnx export: dilated pooling windows have no "
                             "ONNX pooling mapping")
        if wd[0] != 1 or wd[1] != 1 or pad[0] != (0, 0) or pad[1] != (0, 0):
            raise ValueError(
                f"onnx export: reduce_window over batch/channel dims "
                f"(window {wd}) is not a spatial pooling; no mapping")
        sp_wd = list(wd[2:])
        sp_ws = list(ws[2:])
        sp_pads = [p[0] for p in pad[2:]] + [p[1] for p in pad[2:]]
        if name == "reduce_window_max":
            set_out(g.add("MaxPool", [inp(0)], kernel_shape=sp_wd,
                          strides=sp_ws, pads=sp_pads))
        else:
            # ONNX has no SumPool: AveragePool (counting padded cells, which
            # reduce_window_sum's zero-padding matches) times window size
            ap = g.add("AveragePool", [inp(0)], kernel_shape=sp_wd,
                       strides=sp_ws, pads=sp_pads, count_include_pad=1)
            k = 1
            for d in sp_wd:
                k *= int(d)
            kc = g.const(np.asarray(
                float(k), np.dtype(eqn.invars[0].aval.dtype)))
            set_out(g.add("Mul", [ap, kc]))
    elif name == "pad":
        cfg = eqn.params["padding_config"]
        if any(interior != 0 for _, _, interior in cfg):
            raise ValueError("onnx export: interior (dilating) pad has no "
                             "ONNX mapping")
        if any(lo < 0 or hi < 0 for lo, hi, _ in cfg):
            raise ValueError("onnx export: negative pad (cropping) has no "
                             "ONNX Pad mapping")
        pads = [c[0] for c in cfg] + [c[1] for c in cfg]
        set_out(g.add("Pad", [inp(0), g.const(np.asarray(pads, np.int64)),
                              inp(1)], mode="constant"))
    elif name == "argmax":
        set_out(g.add("ArgMax", [inp(0)], axis=int(eqn.params["axes"][0]),
                      keepdims=0))
    elif name == "iota":
        aval = eqn.outvars[0].aval
        rng = np.arange(aval.shape[eqn.params["dimension"]])
        arr = np.broadcast_to(
            rng.reshape([-1 if i == eqn.params["dimension"] else 1
                         for i in range(len(aval.shape))]),
            aval.shape).astype(np.dtype(aval.dtype))
        set_out(g.const(arr))
    else:
        raise ValueError(
            f"onnx export: primitive {name!r} has no ONNX mapping yet; "
            "use paddle_tpu.jit.save for the StableHLO artifact")


def _convert_jaxpr(g: _Graph, jaxpr, env: Dict[int, str]):
    for cv in jaxpr.constvars:
        if id(cv) not in env:
            raise ValueError(
                "onnx export: unbound jaxpr constant (graph shape beyond "
                "the ONNX converter; use paddle_tpu.jit.save for the "
                "StableHLO artifact)")
    for eqn in jaxpr.eqns:
        _convert_eqn(g, eqn, env)


def export(layer, path, input_spec=None, opset_version=17, **configs):
    """Export `layer`'s forward as a standard .onnx file.

    input_spec: list of example Tensors or jit.InputSpec (static shapes).
    Covers the inference op corpus (matmul/conv-free transformer blocks,
    MLPs, elementwise/norm/softmax chains); primitives without a mapping
    raise with a pointer to the StableHLO path.
    """
    import jax

    from .core.tensor import Tensor
    from .core import autograd
    from .jit import _Binder

    if input_spec is None:
        raise ValueError("onnx.export needs input_spec (example Tensors or "
                         "InputSpec with concrete shapes)")
    examples = []
    for spec in input_spec:
        if isinstance(spec, Tensor):
            examples.append(spec.data)
        elif hasattr(spec, "shape"):
            shape = [1 if (d is None or d == -1) else int(d)
                     for d in spec.shape]
            dt = str(getattr(spec, "dtype", "float32")).split(".")[-1]
            examples.append(np.zeros(shape, dt))
        else:
            raise ValueError(f"bad input_spec entry {spec!r}")

    params = [p for _, p in layer.named_parameters()]
    buffers = [b for _, b in layer.named_buffers()] \
        if hasattr(layer, "named_buffers") else []
    tensors = params + buffers

    def fn(*flat):
        ts, xs = flat[:len(tensors)], flat[len(tensors):]
        with _Binder(tensors) as b:
            b.bind(list(ts))
            with autograd.no_grad():
                out = layer(*[Tensor(a) for a in xs])
        return out.data if isinstance(out, Tensor) else out

    arrays = [t.data for t in tensors] + examples
    closed = jax.make_jaxpr(fn)(*arrays)

    g = _Graph(getattr(layer, "_full_name", None) or type(layer).__name__)
    env: Dict[int, str] = {}
    # params/buffers become initializers; user inputs become graph inputs
    for i, v in enumerate(closed.jaxpr.invars):
        if i < len(tensors):
            env[id(v)] = g.const(np.asarray(arrays[i]), name=f"param_{i}")
        else:
            nm = f"input_{i - len(tensors)}"
            env[id(v)] = nm
            g.inputs.append(_value_info(nm, v.aval.shape, str(v.aval.dtype)))
    for cv, cval in zip(closed.jaxpr.constvars, closed.consts):
        env[id(cv)] = g.const(np.asarray(cval))
    _convert_jaxpr(g, closed.jaxpr, env)
    for i, ov in enumerate(closed.jaxpr.outvars):
        nm = env[id(ov)]
        out_name = f"output_{i}"
        g.add("Identity", [nm], [out_name])
        g.outputs.append(_value_info(out_name, ov.aval.shape,
                                     str(ov.aval.dtype)))

    if not path.endswith(".onnx"):
        path = path + ".onnx"
    with open(path, "wb") as f:
        f.write(g.serialize(int(opset_version)))
    return path
