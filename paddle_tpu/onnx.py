"""paddle.onnx (reference: python/paddle/onnx/export.py wraps paddle2onnx).

paddle2onnx is CUDA/ProgramDesc-specific and has no TPU meaning; the portable
deployment artifact on this framework is the StableHLO export, which any ONNX
toolchain consuming MLIR can ingest.
"""


def export(layer, path, input_spec=None, opset_version=9, **configs):
    raise NotImplementedError(
        "ONNX export is not provided on the TPU framework; use "
        "paddle_tpu.jit.save(layer, path, input_spec=[...]) to produce a "
        "portable StableHLO program (.pdmodel) instead")
