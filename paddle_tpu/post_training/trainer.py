"""The trainer half of the post-training loop: a policy-gradient
objective over ``elastic_fit``, fed round-by-round from the rollout
process through the control-plane ``TCPStore``, publishing every
weight update through a ``WeightPublisher``.

Off-policy correction: each trained token carries the BEHAVIOR logprob
it was sampled under (from the serving fleet's ledger) and the weight
version that produced it. The loss importance-weights by
``exp(clip(current_logprob - behavior_logprob))`` — stop-gradient on
the ratio, REINFORCE on the logprob — so rollouts that are a version
behind the trainer are still usable, just down/up-weighted by how far
the policy has moved.

Batch wire format (one store key per round, JSON):
    ids  [B, L]     int64   prompt + generated tokens, right-padded
    y    [B, L, 5]  float32 per-position (target, behavior_lp,
                            advantage, mask, supervised) — mask=1 on
                            positions that predict a trained token;
                            supervised=1 marks prompt-continuation
                            positions trained as plain weighted CE
                            (importance ratio pinned to 1), the
                            rejection-sampling half of the objective
"""
from __future__ import annotations

import json
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .buffer import Trajectory

__all__ = ["make_rl_batch", "make_rl_loss", "StoreBatchDataset",
           "WeightPushCallback", "rl_fit", "put_batch"]


# ---------------------------------------------------------------------------
# batch packing (rollout process side)
# ---------------------------------------------------------------------------

def make_rl_batch(trajs: Sequence[Trajectory], seq_len: int,
                  baseline: float = 0.0, prompt_weight: float = 1.0
                  ) -> Tuple[np.ndarray, np.ndarray]:
    """Pack trajectories into the ``(ids, y)`` wire batch. Position
    ``p`` of ``ids`` predicts the token at ``p+1``, so generated token
    ``j`` (absolute position ``len(prompt)+j``) is supervised at
    ``p = len(prompt)+j-1``. Advantage is the per-token reward against
    a constant ``baseline`` — matches push their logprob up,
    mismatches push it down, and a fully-converged batch keeps
    reinforcing the right tokens instead of going silent.

    When ``prompt_weight > 0``, the prompt's own continuation positions
    (``p`` in ``0..len(prompt)-2``) are packed as SUPERVISED targets
    (``sup=1``, advantage ``prompt_weight``, behavior 0): the prompt is
    verified-correct pattern data, so distilling it keeps the policy
    anchored in contexts greedy rollouts never explore."""
    B, L = len(trajs), int(seq_len)
    ids = np.zeros((B, L), dtype=np.int64)
    y = np.zeros((B, L, 5), dtype=np.float32)
    for b, tr in enumerate(trajs):
        full = tr.prompt + tr.tokens
        ids[b, :min(L, len(full))] = full[:L]
        if prompt_weight > 0:
            for p in range(min(len(tr.prompt) - 1, L)):
                y[b, p] = (full[p + 1], 0.0, float(prompt_weight),
                           1.0, 1.0)
        per = tr.token_rewards
        if per is None:
            per = [tr.reward] * len(tr.tokens)
        for j, tok in enumerate(tr.tokens):
            p = len(tr.prompt) + j - 1
            if p < 0 or p >= L:
                continue
            y[b, p] = (tok, tr.logprobs[j],
                       float(per[j]) - float(baseline), 1.0, 0.0)
    return ids, y


def make_rl_loss(ratio_clip: float = 2.0) -> Callable:
    """The hapi-shaped loss ``fn(logits, y) -> scalar``: masked
    importance-weighted REINFORCE on generated tokens, plain weighted
    cross-entropy on supervised (``sup=1``) positions — the importance
    ratio is pinned to 1 there because the target never came from the
    behavior policy (see module docstring)."""
    c = float(ratio_clip)

    def rl_loss(logits, y):
        from .. import ops
        from ..nn import functional as F
        from ..ops import manipulation as man

        vocab = int(logits.shape[-1])
        logp = F.log_softmax(logits.astype("float32"), axis=-1)
        tgt = y[:, :, 0].astype("int64")
        beh, adv = y[:, :, 1], y[:, :, 2]
        mask, sup = y[:, :, 3], y[:, :, 4]
        lp = ops.sum(logp * man.one_hot(tgt, vocab), axis=-1)  # [B,L]
        # stop-gradient importance ratio: the correction is a WEIGHT,
        # clipped in log space so a stale behavior policy cannot blow
        # up a single token's gradient
        ratio = ops.exp(ops.clip(lp - beh, -c, c)).detach()
        w = ratio * (1.0 - sup) + sup
        num = ops.sum(w * adv * lp * mask)
        den = ops.clip(ops.sum(mask), 1.0, None)
        return -(num / den)

    return rl_loss


# ---------------------------------------------------------------------------
# store-backed feed: rollout process -> trainer process
# ---------------------------------------------------------------------------

def _batch_key(prefix: str, k: int) -> str:
    return f"{prefix}/batch/{k}"


def put_batch(store, prefix: str, k: int, ids: np.ndarray,
              y: np.ndarray) -> None:
    """Publish round ``k``'s packed batch (rollout-process side)."""
    store.set(_batch_key(prefix, k), json.dumps(
        {"ids": ids.tolist(), "y": y.tolist()}))


class StoreBatchDataset:
    """The trainer's dataset view over the store: ``rounds`` rollout
    rounds of ``batch_size`` rows each, where reading a row of round
    ``k`` BLOCKS on the store key until the rollout process publishes
    it. With ``steps_per_round > 1`` each round's batch is replayed
    that many consecutive global steps (inner optimisation on a fixed
    batch) before the loop advances to — and blocks on — the next
    round. The loader's prefetch thread parks on the next key while
    the train step runs: the natural rollout->train pipeline, no
    polling loop."""

    def __init__(self, store, prefix: str, rounds: int, batch_size: int,
                 seq_len: int, steps_per_round: int = 1):
        self.store = store
        self.prefix = str(prefix)
        self.rounds = int(rounds)
        self.batch_size = int(batch_size)
        self.seq_len = int(seq_len)
        self.steps_per_round = max(1, int(steps_per_round))
        self._cache: Tuple[int, np.ndarray, np.ndarray] = (-1, None, None)

    def __len__(self) -> int:
        return self.rounds * self.steps_per_round * self.batch_size

    def __getitem__(self, i: int):
        step, r = divmod(int(i), self.batch_size)
        k = step // self.steps_per_round
        ck, ids, y = self._cache
        if ck != k:
            key = _batch_key(self.prefix, k)
            self.store.wait([key])
            d = json.loads(self.store.get(key).decode())
            ids = np.asarray(d["ids"], dtype=np.int64)
            y = np.asarray(d["y"], dtype=np.float32)
            self._cache = (k, ids, y)
        return ids[r], y[r]


# ---------------------------------------------------------------------------
# weight push callback (trainer side)
# ---------------------------------------------------------------------------

class WeightPushCallback:
    """hapi callback: after every ``push_every``-th trained batch,
    snapshot the live GPT params and publish them as the next weight
    version (plus a store marker the rollout process can watch).
    Duck-typed for hapi's CallbackList (set_model/set_params)."""

    def __init__(self, publisher, *, store=None, prefix: str = "ptq",
                 base_version: int = 0, push_every: int = 1):
        self.publisher = publisher
        self.store = store
        self.prefix = str(prefix)
        self.base_version = int(base_version)
        self.push_every = max(1, int(push_every))
        self.pushed: List[int] = []
        self.model = None
        self.params: Dict[str, Any] = {}
        self._step = 0

    def set_model(self, model):
        self.model = model

    def set_params(self, params):
        self.params = params

    def on_train_batch_end(self, step, logs=None):
        self._step += 1
        if self._step % self.push_every:
            return
        from ..serving.generation import (_extract_gpt_params,
                                          flatten_gpt_params)

        flat = flatten_gpt_params(_extract_gpt_params(self.model.network))
        ver = self.base_version + len(self.pushed) + 1
        loss = float(np.asarray((logs or {}).get("loss", 0.0)))
        self.publisher.publish(flat, version=ver,
                               meta={"step": self._step, "loss": loss})
        self.pushed.append(ver)
        if self.store is not None:
            self.store.set(f"{self.prefix}/pushed", str(ver))
            self.store.set(f"{self.prefix}/loss/{ver}", repr(loss))


# ---------------------------------------------------------------------------
# the trainer entry
# ---------------------------------------------------------------------------

def rl_fit(build: Callable, *, store, publisher, rounds: int,
           batch_size: int, seq_len: int, ratio_clip: float = 2.0,
           prefix: str = "ptq", base_version: int = 0,
           steps_per_round: int = 1, push_every: Optional[int] = None,
           fit_kw: Optional[Dict] = None) -> Dict[str, Any]:
    """Run the RL objective under ``elastic_fit``: ``build(ctx)``
    returns ``{"network", "optimizer"}`` (a ``GPTForCausalLM`` + its
    optimizer); the dataset, loss, and weight-push callback are wired
    here. Each rollout round trains ``steps_per_round`` global steps on
    its batch, then publishes one streamed weight version
    (``push_every`` defaults to ``steps_per_round`` — one push per
    round). Returns elastic_fit's result dict plus ``pushed`` (the
    published version list)."""
    from ..distributed.fleet.runtime import elastic_fit

    spr = max(1, int(steps_per_round))
    push_cb = WeightPushCallback(publisher, store=store, prefix=prefix,
                                 base_version=base_version,
                                 push_every=(spr if push_every is None
                                             else push_every))

    def _build(ctx):
        parts = dict(build(ctx))
        parts["loss"] = make_rl_loss(ratio_clip)
        parts["dataset"] = StoreBatchDataset(store, prefix, rounds,
                                             batch_size, seq_len,
                                             steps_per_round=spr)
        parts["callbacks"] = list(parts.get("callbacks") or []) + [push_cb]
        return parts

    out = elastic_fit(_build, global_batch=batch_size, epochs=1,
                      replan=False, fit_kw=fit_kw)
    out["pushed"] = list(push_cb.pushed)
    return out
