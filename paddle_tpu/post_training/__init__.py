"""Post-training RL loop: rollout -> reward -> train -> publish.

Composes the two fleets the repo already owns — ``ServingFleet``
generates (exactly-once token streams with behavior logprobs),
``ElasticFleet`` trains — through the streaming weight-distribution
service in :mod:`.weights`. The loop:

1. :class:`RolloutWorker` submits seeded prompts through the serving
   fleet and emits ``(prompt, tokens, behavior_logprobs,
   weight_version)`` trajectories.
2. :class:`ReplayBuffer` rewards them (programmatic or model-scored)
   and samples staleness-bounded, seed-deterministic batches.
3. :func:`rl_fit` trains the importance-weighted policy-gradient
   objective under ``elastic_fit``.
4. :class:`WeightPublisher` streams each update to every replica's
   :class:`WeightSubscriber` (chunked, digest-verified, resumable);
   ``EngineBase.swap_weights()`` applies it in place between batches,
   ``rolling_restart()`` is the fallback.

Everything registers with the process-wide telemetry hub under the
``post_training`` provider: loop rounds, trajectory counts, buffer
depth/staleness, published/applied weight versions, push latency.
"""
from __future__ import annotations

import weakref
from typing import Any, Dict

from .buffer import (ReplayBuffer, Trajectory, model_scored_reward,
                     pattern_reward)
from .rollout import RolloutWorker, cyclic_prompts
from .trainer import (StoreBatchDataset, WeightPushCallback, make_rl_batch,
                      make_rl_loss, put_batch, rl_fit)
from .weights import WeightPublisher, WeightSubscriber, pack_state, \
    unpack_state

__all__ = [
    "ReplayBuffer", "Trajectory", "pattern_reward", "model_scored_reward",
    "RolloutWorker", "cyclic_prompts",
    "WeightPublisher", "WeightSubscriber", "pack_state", "unpack_state",
    "make_rl_batch", "make_rl_loss", "rl_fit", "put_batch",
    "StoreBatchDataset", "WeightPushCallback",
    "track", "loop_note", "provider_snapshot",
]


# ---------------------------------------------------------------------------
# the post_training hub provider: weak registry of live loop components
# ---------------------------------------------------------------------------

_components: "weakref.WeakSet" = weakref.WeakSet()
_loop_state: Dict[str, Any] = {}


def track(obj):
    """Register a loop component (buffer / publisher / subscriber /
    rollout worker — anything with ``stats()``) so its rows appear in
    the ``post_training`` provider. Weak: a collected component's rows
    disappear with it."""
    _components.add(obj)
    return obj


def loop_note(**kw) -> None:
    """Record scalar loop-level facts (round, rewards, push latency)
    into the provider snapshot — the drill's heartbeat."""
    _loop_state.update({k: v for k, v in kw.items()})


def provider_snapshot() -> Dict[str, Any]:
    out: Dict[str, Any] = {"loop": dict(_loop_state)}
    rows = []
    for obj in list(_components):
        try:
            st = dict(obj.stats())
        except Exception:
            continue
        st["kind"] = type(obj).__name__
        rows.append(st)
    out["components"] = sorted(
        rows, key=lambda r: (r.get("kind", ""), str(r.get("name", ""))))
    return out


def _register_provider() -> None:
    try:
        from ..observability import register_provider

        register_provider("post_training", provider_snapshot)
    except Exception:  # observability stack unavailable: stay usable
        pass


_register_provider()
