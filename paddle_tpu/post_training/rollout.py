"""The rollout tier: seeded prompts through the ServingFleet, out come
``Trajectory`` records.

A ``RolloutWorker`` submits each prompt with ``return_logprobs=True``
and reads back ``(full_seq, behavior_logprobs)`` — the fleet's
emitted-token ledger makes that stream exactly-once even when the
serving replica crashes mid-generation, and the request's
``weight_version`` pin (stamped at first dispatch, re-stamped on a
version re-prefill) tells us exactly which weights produced it.
"""
from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from .buffer import Trajectory

__all__ = ["RolloutWorker", "cyclic_prompts"]


def cyclic_prompts(pattern: Sequence[int], prompt_len: int,
                   seed: int = 0) -> Callable[[int], List[int]]:
    """Seeded prompt source for the pattern task: each prompt is a
    window of the cyclic pattern starting at a seeded-random phase, so
    the correct continuation is always defined but never constant."""
    pat = [int(t) for t in pattern]
    rng = np.random.default_rng(int(seed))

    def fn(i: int) -> List[int]:
        start = int(rng.integers(0, len(pat)))
        return [pat[(start + j) % len(pat)] for j in range(prompt_len)]

    return fn


class RolloutWorker:
    """Drives generation through a ``ServingFleet`` (or any object with
    the same ``submit``) and converts results into trajectories.

    ``rollout(n)`` submits ``n`` prompts concurrently, waits for all
    futures, and returns one ``Trajectory`` per prompt — tokens and
    behavior logprobs exactly as emitted (ledger order), stamped with
    the weight version the fleet pinned the request to.
    """

    def __init__(self, fleet, prompt_fn: Callable[[int], Sequence[int]],
                 *, max_new_tokens: int = 8, timeout: float = 120.0,
                 name: str = "rollout"):
        self.fleet = fleet
        self.prompt_fn = prompt_fn
        self.max_new_tokens = int(max_new_tokens)
        self.timeout = float(timeout)
        self.name = str(name)
        from ..analysis.lockdep import lock as _named_lock  # lazy

        self._lock = _named_lock(
            f"post_training.rollout.RolloutWorker[{name}]._lock")
        self._counters: Dict[str, int] = {
            "submitted": 0, "completed": 0, "failed": 0, "tokens": 0,
        }
        self._seq = 0

    def rollout(self, n: int,
                on_trajectory: Optional[Callable] = None
                ) -> List[Trajectory]:
        """One rollout round: ``n`` concurrent requests -> up to ``n``
        trajectories (failed requests are counted and skipped, never
        fabricated)."""
        subs = []
        for _ in range(int(n)):
            with self._lock:
                i = self._seq
                self._seq += 1
                self._counters["submitted"] += 1
            prompt = [int(t) for t in self.prompt_fn(i)]
            fut = self.fleet.submit(np.asarray(prompt, dtype=np.int64),
                                    max_new_tokens=self.max_new_tokens,
                                    return_logprobs=True)
            subs.append((prompt, fut))
        out: List[Trajectory] = []
        deadline = time.monotonic() + self.timeout
        for prompt, fut in subs:
            try:
                seq, lps = fut.result(
                    timeout=max(0.1, deadline - time.monotonic()))
            except Exception:
                with self._lock:
                    self._counters["failed"] += 1
                continue
            toks = [int(t) for t in np.asarray(seq)[len(prompt):]]
            ver = self._request_version(fut)
            traj = Trajectory(prompt, toks,
                              [float(x) for x in np.asarray(lps)],
                              ver)
            with self._lock:
                self._counters["completed"] += 1
                self._counters["tokens"] += len(toks)
            if on_trajectory is not None:
                on_trajectory(traj)
            out.append(traj)
        return out

    @staticmethod
    def _request_version(fut) -> int:
        """The weight version the fleet pinned this request to (stamped
        on the future by ``FleetRequest``); -1 when unknown (e.g. a
        bare engine without versioned dispatch)."""
        req = getattr(fut, "_pt_req", None)
        ver = getattr(req, "weight_version", None)
        try:
            return int(ver)
        except (TypeError, ValueError):
            return -1

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {"name": self.name, **dict(self._counters)}
