"""The streaming weight-distribution service: trainer -> replicas.

``WeightPublisher`` holds the last few published weight versions and
serves them over the serving fleet's length-prefixed frame protocol;
``WeightSubscriber`` polls it from each replica, pulls new versions in
digest-verified chunks, and applies them in place through
``engine.swap_weights()`` — a weight push costs seconds, not a respawn.

The protocol is PULL-based and resumable by construction:

    subscriber                      publisher
    ----------                      ---------
    {"op": "head"}             ->   {"version": latest or 0}
    {"op": "manifest", v}      ->   {names, digest, n_chunks, ...}
    {"op": "chunk", v, index}  ->   {data: b64, sha}     (one per ask)

Each chunk is SHA-256 verified on receipt and the assembled blob
against the manifest digest, so a corrupted transfer is rejected, not
applied. A subscriber that loses its connection mid-transfer keeps the
chunks it already verified and, on reconnect, asks only for the
missing ones (the resume path). Because the publisher only ever sends
one chunk per request, a slow subscriber back-pressures ITSELF — its
next ask waits on its own socket — while the publisher's select loop
keeps serving everyone else from per-connection output buffers.
"""
from __future__ import annotations

import base64
import hashlib
import itertools
import json
import os
import select
import socket
import struct
import threading
import time
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ..serving.fleet import recv_frame, send_frame, _json_default

__all__ = ["WeightPublisher", "WeightSubscriber", "pack_state",
           "unpack_state"]


# ---------------------------------------------------------------------------
# state (de)serialization: flat {name: array} <-> one contiguous blob
# ---------------------------------------------------------------------------

def _np_dtype(spec: str) -> np.dtype:
    try:
        return np.dtype(spec)
    except TypeError:
        import ml_dtypes  # bf16 et al (always present under jax)

        return np.dtype(getattr(ml_dtypes, spec))


def pack_state(state: Dict[str, Any]) -> Tuple[bytes, List[Dict]]:
    """Flat ``{name: array}`` -> (blob, manifest names). Names are
    sorted so the same state always packs to the same bytes (and the
    same digest)."""
    names: List[Dict[str, Any]] = []
    parts: List[bytes] = []
    off = 0
    for k in sorted(state):
        a = np.ascontiguousarray(np.asarray(state[k]))
        raw = a.tobytes()
        names.append({"name": str(k), "dtype": str(a.dtype),
                      "shape": list(a.shape), "offset": off,
                      "size": len(raw)})
        parts.append(raw)
        off += len(raw)
    return b"".join(parts), names


def unpack_state(blob: bytes, names: List[Dict]) -> Dict[str, np.ndarray]:
    out: Dict[str, np.ndarray] = {}
    for m in names:
        seg = blob[m["offset"]:m["offset"] + m["size"]]
        arr = np.frombuffer(seg, dtype=_np_dtype(m["dtype"]))
        out[m["name"]] = arr.reshape(m["shape"]).copy()
    return out


def _sha(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


# ---------------------------------------------------------------------------
# publisher
# ---------------------------------------------------------------------------

class WeightPublisher:
    """Trainer-side version store + frame-protocol server.

    ``publish(state)`` snapshots the state into chunked, digest-indexed
    form; subscribers pull it at their own pace. The serve loop is ONE
    thread (``pt-posttrain-pub-<name>``) multiplexing every connection
    with non-blocking sockets and per-connection output buffers — a
    subscriber that stops reading stalls only its own buffer (bounded;
    past the cap it is disconnected), never the loop.
    """

    def __init__(self, name: str = "trainer", host: str = "127.0.0.1",
                 chunk_bytes: int = 1 << 20, keep_versions: int = 2,
                 max_outbuf: int = 64 << 20):
        self.name = str(name)
        self.chunk_bytes = int(chunk_bytes)
        self.keep_versions = max(1, int(keep_versions))
        self.max_outbuf = int(max_outbuf)
        self._listen = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listen.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listen.bind((host, 0))
        self._listen.listen(16)
        self._listen.setblocking(False)
        self.host, self.port = self._listen.getsockname()
        from ..analysis.lockdep import lock as _named_lock  # lazy

        self._lock = _named_lock(
            f"post_training.weights.WeightPublisher[{name}]._lock")
        self._versions: "OrderedDict[int, Dict[str, Any]]" = OrderedDict()
        self._latest = 0
        self._counters: Dict[str, int] = {}
        self._conns: Dict[socket.socket, bytearray] = {}
        self._outbuf: Dict[socket.socket, bytearray] = {}
        self._wake_r, self._wake_w = os.pipe()
        os.set_blocking(self._wake_r, False)
        os.set_blocking(self._wake_w, False)
        self._stopped = False
        self._thread: Optional[threading.Thread] = None
        # test seam: serve N more chunk requests, then drop that
        # connection without replying (the mid-transfer crash drill)
        self.drop_after_chunks: Optional[int] = None

    # -- lifecycle ------------------------------------------------------------
    @property
    def endpoint(self) -> Tuple[str, int]:
        return (self.host, self.port)

    def start(self) -> "WeightPublisher":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._serve, daemon=True,
                name=f"pt-posttrain-pub-{self.name}")
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stopped = True
        try:
            os.write(self._wake_w, b"x")
        except OSError:
            pass
        t = self._thread
        if t is not None:
            t.join(timeout=5)
            self._thread = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    # -- publishing -----------------------------------------------------------
    def publish(self, state: Dict[str, Any],
                version: Optional[int] = None,
                meta: Optional[Dict[str, Any]] = None) -> int:
        """Snapshot ``state`` as a new version (monotonic; defaults to
        latest+1) and retire versions beyond ``keep_versions``. Returns
        the published version number."""
        t0 = time.monotonic()
        blob, names = pack_state(state)
        chunks = [blob[i:i + self.chunk_bytes]
                  for i in range(0, len(blob), self.chunk_bytes)] or [b""]
        rec = {
            "names": names, "digest": _sha(blob),
            "chunks": chunks, "sha": [_sha(c) for c in chunks],
            "meta": dict(meta or {}), "t_publish": time.time(),
            "nbytes": len(blob),
        }
        with self._lock:
            ver = int(version) if version is not None else self._latest + 1
            if ver <= self._latest and ver in self._versions:
                raise ValueError(f"version {ver} already published")
            self._versions[ver] = rec
            self._latest = max(self._latest, ver)
            while len(self._versions) > self.keep_versions:
                self._versions.popitem(last=False)
            self._counters["published"] = \
                self._counters.get("published", 0) + 1
            self._counters["published_bytes"] = \
                self._counters.get("published_bytes", 0) + len(blob)
            self._last_pack_ms = round((time.monotonic() - t0) * 1e3, 2)
        return ver

    def latest_version(self) -> int:
        with self._lock:
            return self._latest

    def corrupt_chunk_for_test(self, version: int, index: int) -> None:
        """Flip bytes in a stored chunk WITHOUT updating its digest —
        the digest-mismatch rejection drill."""
        with self._lock:
            rec = self._versions[int(version)]
            c = bytearray(rec["chunks"][int(index)])
            c[0] = c[0] ^ 0xFF if c else 0
            rec["chunks"][int(index)] = bytes(c)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "name": self.name, "latest_version": self._latest,
                "held_versions": sorted(self._versions),
                "conns": len(self._conns),
                **dict(self._counters),
            }

    # -- serve loop -----------------------------------------------------------
    def _serve(self) -> None:
        while not self._stopped:
            with self._lock:
                wl = [c for c, b in self._outbuf.items() if b]
            rl = [self._listen, self._wake_r] + list(self._conns)
            try:
                rs, ws, _ = select.select(rl, wl, [], 0.1)
            except OSError:
                rs, ws = [], []
            for s in rs:
                if s is self._listen:
                    try:
                        conn, _ = self._listen.accept()
                    except OSError:
                        continue
                    conn.setblocking(False)
                    self._conns[conn] = bytearray()
                    self._outbuf[conn] = bytearray()
                elif s is self._wake_r:
                    try:
                        os.read(self._wake_r, 4096)
                    except OSError:
                        pass
                else:
                    self._readable(s)
            for s in ws:
                self._writable(s)
        for c in list(self._conns):
            self._drop(c)
        try:
            self._listen.close()
        except OSError:
            pass
        for fd in (self._wake_r, self._wake_w):
            try:
                os.close(fd)
            except OSError:
                pass

    def _drop(self, conn) -> None:
        self._conns.pop(conn, None)
        with self._lock:
            self._outbuf.pop(conn, None)
        try:
            conn.close()
        except OSError:
            pass

    def _readable(self, conn) -> None:
        try:
            data = conn.recv(65536)
        except BlockingIOError:
            return
        except OSError:
            data = b""
        if not data:
            self._drop(conn)
            return
        buf = self._conns.get(conn)
        if buf is None:
            return
        buf += data
        while len(buf) >= 4:
            (n,) = struct.unpack(">I", bytes(buf[:4]))
            if len(buf) < 4 + n:
                break
            msg = json.loads(bytes(buf[4:4 + n]).decode())
            del buf[:4 + n]
            if not self._handle(conn, msg):
                return  # connection dropped mid-parse

    def _send(self, conn, obj: Dict[str, Any]) -> bool:
        data = json.dumps(obj, separators=(",", ":"),
                          default=_json_default).encode()
        frame = struct.pack(">I", len(data)) + data
        with self._lock:
            buf = self._outbuf.get(conn)
            if buf is None:
                return False
            if len(buf) + len(frame) > self.max_outbuf:
                over = True
            else:
                buf += frame
                over = False
        if over:  # pathological non-reader: disconnect, it can resume
            self._counters["slow_disconnects"] = \
                self._counters.get("slow_disconnects", 0) + 1
            self._drop(conn)
            return False
        self._writable(conn)
        return True

    def _writable(self, conn) -> None:
        while True:
            with self._lock:
                buf = self._outbuf.get(conn)
                if not buf:
                    return
                pending = bytes(buf[:262144])
            try:
                sent = conn.send(pending)  # pd-lint: disable=CC001
            except (BlockingIOError, InterruptedError):
                return  # kernel buffer full: select's writable set owns it
            except OSError:
                self._drop(conn)
                return
            with self._lock:
                buf = self._outbuf.get(conn)
                if buf is None:
                    return
                del buf[:sent]

    def _handle(self, conn, msg: Dict[str, Any]) -> bool:
        op, rid = msg.get("op"), msg.get("rid")
        if op == "head":
            with self._lock:
                latest = self._latest
            return self._send(conn, {"rid": rid, "event": "reply",
                                     "version": latest})
        if op == "manifest":
            ver = int(msg.get("version", 0))
            with self._lock:
                rec = self._versions.get(ver)
                if rec is not None:
                    reply = {"rid": rid, "event": "reply",
                             "version": ver, "names": rec["names"],
                             "digest": rec["digest"],
                             "n_chunks": len(rec["chunks"]),
                             "meta": rec["meta"],
                             "t_publish": rec["t_publish"],
                             "nbytes": rec["nbytes"],
                             "chunk_bytes": self.chunk_bytes}
                else:
                    reply = None
            if reply is None:
                return self._send(conn, {
                    "rid": rid, "event": "error", "kind": "VersionGone",
                    "msg": f"version {ver} not held"})
            return self._send(conn, reply)
        if op == "chunk":
            ver, idx = int(msg.get("version", 0)), int(msg.get("index", -1))
            if self.drop_after_chunks is not None:
                self.drop_after_chunks -= 1
                if self.drop_after_chunks < 0:
                    self.drop_after_chunks = None
                    self._drop(conn)  # the mid-transfer crash seam
                    return False
            with self._lock:
                rec = self._versions.get(ver)
                chunk = sha = None
                if rec is not None and 0 <= idx < len(rec["chunks"]):
                    chunk, sha = rec["chunks"][idx], rec["sha"][idx]
                    self._counters["chunks_served"] = \
                        self._counters.get("chunks_served", 0) + 1
                    self._counters["bytes_served"] = \
                        self._counters.get("bytes_served", 0) + len(chunk)
            if chunk is None:
                return self._send(conn, {
                    "rid": rid, "event": "error", "kind": "VersionGone",
                    "msg": f"version {ver} chunk {idx} not held"})
            return self._send(conn, {
                "rid": rid, "event": "reply", "version": ver,
                "index": idx, "sha": sha,
                "data": base64.b64encode(chunk).decode()})
        return self._send(conn, {"rid": rid, "event": "error",
                                 "kind": "BadRequest",
                                 "msg": f"unknown op {op!r}"})


# ---------------------------------------------------------------------------
# subscriber
# ---------------------------------------------------------------------------

class WeightSubscriber:
    """Replica-side puller: polls the publisher's head, pulls any newer
    version chunk-by-chunk (verifying each against its SHA-256 and the
    assembled blob against the manifest digest), and applies it through
    ``engine.swap_weights(state, version=...)`` — or a plain
    ``on_update(state, version, meta)`` callback when no engine is
    given. Partial transfers survive connection loss: verified chunks
    are kept keyed by (version, digest) and only the missing ones are
    re-pulled after reconnect."""

    def __init__(self, host: str, port: int, *, engine=None,
                 on_update: Optional[Callable] = None,
                 name: str = "sub", poll_interval: float = 0.25,
                 rpc_timeout_s: float = 30.0):
        if engine is None and on_update is None:
            raise ValueError("need an engine or an on_update callback")
        self.endpoint = (str(host), int(port))
        self.engine = engine
        self.on_update = on_update
        self.name = str(name)
        self.poll_interval = float(poll_interval)
        self._rpc_timeout = float(rpc_timeout_s)
        from ..analysis.lockdep import lock as _named_lock  # lazy

        self._lock = _named_lock(
            f"post_training.weights.WeightSubscriber[{name}]._lock")
        self._sock: Optional[socket.socket] = None
        self._rid = itertools.count(1)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.applied_version = int(getattr(engine, "weight_version", 0)
                                   or 0)
        self._failed_version: Optional[int] = None  # apply() refused it
        self._partial: Optional[Dict[str, Any]] = None
        self._counters: Dict[str, int] = {}
        self._last: Dict[str, Any] = {}

    # -- lifecycle ------------------------------------------------------------
    def start(self) -> "WeightSubscriber":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, daemon=True,
                name=f"pt-posttrain-sub-{self.name}")
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self._close_sock()
        t = self._thread
        if t is not None:
            t.join(timeout=5)
            self._thread = None

    def alive(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.fetch_once()
            except Exception:
                self._counters["poll_errors"] = \
                    self._counters.get("poll_errors", 0) + 1
                self._close_sock()
            self._stop.wait(self.poll_interval)

    # -- transport ------------------------------------------------------------
    def _close_sock(self) -> None:
        s, self._sock = self._sock, None
        if s is not None:
            try:
                s.close()
            except OSError:
                pass

    def _rpc(self, op: str, **kw) -> Dict[str, Any]:
        if self._sock is None:
            self._sock = socket.create_connection(self.endpoint,
                                                  timeout=5)
            self._sock.settimeout(self._rpc_timeout)
        msg = {"op": op, "rid": next(self._rid)}
        msg.update(kw)
        try:
            send_frame(self._sock, msg)
            frame = recv_frame(self._sock)
        except (OSError, ValueError):
            self._close_sock()
            raise ConnectionError(f"publisher {self.endpoint} lost")
        if frame is None:
            self._close_sock()
            raise ConnectionError(f"publisher {self.endpoint} closed")
        if frame.get("event") == "error":
            raise RuntimeError(
                f"{frame.get('kind')}: {frame.get('msg')}")
        return frame

    # -- one poll -------------------------------------------------------------
    def fetch_once(self) -> Optional[int]:
        """Check head; transfer + apply a newer version if there is
        one. Returns the newly applied version, else None. Raises on
        connection loss (the loop retries; verified chunks persist)."""
        head = int(self._rpc("head").get("version", 0))
        if head <= self.applied_version or head == self._failed_version:
            return None
        man = self._rpc("manifest", version=head)
        ver, digest = int(man["version"]), str(man["digest"])
        n_chunks = int(man["n_chunks"])
        with self._lock:
            part = self._partial
            if part is None or part["version"] != ver or \
                    part["digest"] != digest:
                part = {"version": ver, "digest": digest, "chunks": {}}
                self._partial = part
            elif part["chunks"]:
                self._counters["resumed_transfers"] = \
                    self._counters.get("resumed_transfers", 0) + 1
        t0 = time.monotonic()
        for idx in range(n_chunks):
            with self._lock:
                if idx in part["chunks"]:
                    continue  # verified before the connection loss
            reply = self._rpc("chunk", version=ver, index=idx)
            raw = base64.b64decode(reply["data"])
            if _sha(raw) != reply["sha"]:
                self._counters["chunk_rejects"] = \
                    self._counters.get("chunk_rejects", 0) + 1
                raise ConnectionError(f"chunk {idx} hash mismatch")
            with self._lock:
                part["chunks"][idx] = raw
                self._counters["chunks_fetched"] = \
                    self._counters.get("chunks_fetched", 0) + 1
        blob = b"".join(part["chunks"][i] for i in range(n_chunks))
        if _sha(blob) != digest:
            # corrupted at rest on the publisher: refuse to apply and
            # drop the partial so a republish transfers cleanly
            with self._lock:
                self._partial = None
            self._counters["digest_rejects"] = \
                self._counters.get("digest_rejects", 0) + 1
            raise RuntimeError(f"version {ver} digest mismatch")
        state = unpack_state(blob, man["names"])
        t_apply = time.monotonic()
        try:
            if self.engine is not None:
                self.engine.swap_weights(state, version=ver)
            else:
                self.on_update(state, ver, man.get("meta") or {})
        except Exception:
            self._failed_version = ver  # do not spin on a bad version
            self._counters["apply_errors"] = \
                self._counters.get("apply_errors", 0) + 1
            raise
        now = time.monotonic()
        with self._lock:
            self.applied_version = ver
            self._partial = None
            self._counters["applies"] = self._counters.get("applies", 0) + 1
            self._last = {
                "version": ver, "nbytes": int(man.get("nbytes", 0)),
                "transfer_ms": round((t_apply - t0) * 1e3, 2),
                "apply_ms": round((now - t_apply) * 1e3, 2),
                # publisher + subscriber share the drill host: wall
                # clock delta IS the push latency
                "push_latency_ms": round(
                    (time.time() - float(man.get("t_publish", 0))) * 1e3,
                    2),
            }
        return ver

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            part = self._partial
            return {
                "name": self.name,
                "applied_version": self.applied_version,
                "partial_chunks": len(part["chunks"]) if part else 0,
                "last": dict(self._last),
                **dict(self._counters),
            }
