"""Versioned replay/reward buffer for the post-training loop.

Trajectories arrive from the rollout tier stamped with the
``weight_version`` that produced them; rewards are computed on add by a
pluggable reward fn (programmatic pattern match or model-scored);
sampling is deterministic under the buffer's seed and staleness-bounded
— trajectories more than ``staleness_limit`` versions behind the
trainer's current version are evicted, counted, and never trained on.
"""
from __future__ import annotations

import itertools
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = ["Trajectory", "ReplayBuffer", "pattern_reward",
           "model_scored_reward"]

_traj_ids = itertools.count(1)


class Trajectory:
    """One rollout: the prompt, the generated tokens, the behavior
    logprobs they were sampled under, and the weight version that
    produced them (the staleness / importance-weighting key)."""

    __slots__ = ("prompt", "tokens", "logprobs", "weight_version",
                 "reward", "token_rewards", "id", "meta")

    def __init__(self, prompt: Sequence[int], tokens: Sequence[int],
                 logprobs: Sequence[float], weight_version: int,
                 reward: float = 0.0,
                 token_rewards: Optional[Sequence[float]] = None,
                 meta: Optional[Dict[str, Any]] = None):
        self.prompt = [int(t) for t in prompt]
        self.tokens = [int(t) for t in tokens]
        self.logprobs = [float(x) for x in logprobs]
        if len(self.logprobs) != len(self.tokens):
            raise ValueError(
                f"{len(self.tokens)} tokens but "
                f"{len(self.logprobs)} behavior logprobs")
        self.weight_version = int(weight_version)
        self.reward = float(reward)
        self.token_rewards = ([float(x) for x in token_rewards]
                              if token_rewards is not None else None)
        self.id = next(_traj_ids)
        self.meta = dict(meta or {})

    def to_dict(self) -> Dict[str, Any]:
        return {"prompt": self.prompt, "tokens": self.tokens,
                "logprobs": self.logprobs,
                "weight_version": self.weight_version,
                "reward": self.reward,
                "token_rewards": self.token_rewards,
                "meta": self.meta}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Trajectory":
        return cls(d["prompt"], d["tokens"], d["logprobs"],
                   d["weight_version"], reward=d.get("reward", 0.0),
                   token_rewards=d.get("token_rewards"),
                   meta=d.get("meta"))

    def __repr__(self):
        return (f"Trajectory(id={self.id}, v={self.weight_version}, "
                f"len={len(self.tokens)}, reward={self.reward:.3f})")


# ---------------------------------------------------------------------------
# reward functions — (traj) -> (scalar_reward, per_token_rewards | None)
# ---------------------------------------------------------------------------

def pattern_reward(pattern: Sequence[int]) -> Callable:
    """Programmatic reward for the drill's cyclic-pattern task: given a
    prompt ending inside ``pattern``, each generated token scores 1.0
    when it is the next pattern element and 0.0 otherwise; the scalar
    reward is the mean. Per-token credit keeps the gradient useful even
    for greedy (zero-exploration) rollouts."""
    pat = [int(t) for t in pattern]
    if len(set(pat)) != len(pat):
        raise ValueError("pattern tokens must be unique")

    def fn(traj: Trajectory) -> Tuple[float, List[float]]:
        last = traj.prompt[-1]
        try:
            j = pat.index(last)
        except ValueError:
            return 0.0, [0.0] * len(traj.tokens)
        per = [1.0 if t == pat[(j + 1 + i) % len(pat)] else 0.0
               for i, t in enumerate(traj.tokens)]
        return (sum(per) / len(per) if per else 0.0), per

    return fn


def model_scored_reward(model) -> Callable:
    """Model-scored reward: mean log-likelihood of the generated tokens
    under a frozen scorer model (``model(ids) -> logits [B,S,V]``). The
    RLHF-shaped alternative to a programmatic check."""

    def fn(traj: Trajectory) -> Tuple[float, List[float]]:
        if not traj.tokens:
            return 0.0, []
        from ..hapi.model import _as_tensor

        full = np.asarray(traj.prompt + traj.tokens,
                          dtype=np.int64)[None, :]
        logits = np.asarray(model(_as_tensor(full)), dtype=np.float64)[0]
        # logprob of token at position p comes from logits at p-1
        lse = np.log(np.exp(logits - logits.max(-1, keepdims=True))
                     .sum(-1)) + logits.max(-1)
        per = []
        for i, t in enumerate(traj.tokens):
            p = len(traj.prompt) + i
            per.append(float(logits[p - 1, t] - lse[p - 1]))
        return float(np.mean(per)), per

    return fn


# ---------------------------------------------------------------------------
# the buffer
# ---------------------------------------------------------------------------

class ReplayBuffer:
    """Bounded, versioned trajectory store.

    - ``add(traj)`` computes the reward (when a ``reward_fn`` is set)
      and appends; past ``capacity`` the oldest entries fall off.
    - ``sample(n, current_version=...)`` first evicts everything more
      than ``staleness_limit`` versions behind ``current_version``,
      then draws ``n`` trajectories without replacement (uniformly,
      from the buffer's own seeded RNG — same seed, same adds, same
      sample order).
    """

    def __init__(self, capacity: int = 4096, seed: int = 0,
                 staleness_limit: Optional[int] = None,
                 reward_fn: Optional[Callable] = None,
                 name: str = "replay"):
        self.name = str(name)
        self.capacity = int(capacity)
        self.staleness_limit = (int(staleness_limit)
                                if staleness_limit is not None else None)
        self.reward_fn = reward_fn
        self._rng = np.random.default_rng(int(seed))
        from ..analysis.lockdep import lock as _named_lock  # lazy

        self._lock = _named_lock(
            f"post_training.buffer.ReplayBuffer[{name}]._lock")
        self._items: List[Trajectory] = []
        self._counters: Dict[str, int] = {
            "added": 0, "sampled": 0, "evicted_stale": 0,
            "evicted_capacity": 0,
        }
        self._t_created = time.time()

    def __len__(self) -> int:
        with self._lock:
            return len(self._items)

    def add(self, traj: Trajectory) -> Trajectory:
        if self.reward_fn is not None and traj.token_rewards is None:
            reward, per = self.reward_fn(traj)
            traj.reward = float(reward)
            traj.token_rewards = ([float(x) for x in per]
                                  if per is not None else None)
        with self._lock:
            self._items.append(traj)
            self._counters["added"] += 1
            while len(self._items) > self.capacity:
                self._items.pop(0)
                self._counters["evicted_capacity"] += 1
        return traj

    def _evict_stale_locked(self, current_version: Optional[int]) -> None:
        if current_version is None or self.staleness_limit is None:
            return
        floor = int(current_version) - self.staleness_limit
        kept = [t for t in self._items if t.weight_version >= floor]
        self._counters["evicted_stale"] += len(self._items) - len(kept)
        self._items = kept

    def sample(self, n: int,
               current_version: Optional[int] = None) -> List[Trajectory]:
        with self._lock:
            self._evict_stale_locked(current_version)
            if not self._items:
                return []
            k = min(int(n), len(self._items))
            idx = self._rng.choice(len(self._items), size=k, replace=False)
            out = [self._items[i] for i in sorted(int(i) for i in idx)]
            self._counters["sampled"] += len(out)
            return out

    def mean_reward(self, last: Optional[int] = None) -> float:
        with self._lock:
            items = self._items[-int(last):] if last else self._items
            if not items:
                return 0.0
            return float(np.mean([t.reward for t in items]))

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            versions: Dict[int, int] = {}
            for t in self._items:
                versions[t.weight_version] = \
                    versions.get(t.weight_version, 0) + 1
            newest = max(versions) if versions else 0
            stale = (float(np.mean([newest - t.weight_version
                                    for t in self._items]))
                     if self._items else 0.0)
            return {
                "name": self.name, "depth": len(self._items),
                "capacity": self.capacity,
                "staleness_limit": self.staleness_limit,
                "mean_reward": (float(np.mean([t.reward
                                               for t in self._items]))
                                if self._items else 0.0),
                "version_histogram": {str(k): versions[k]
                                      for k in sorted(versions)},
                "mean_staleness": round(stale, 3),
                **dict(self._counters),
            }
