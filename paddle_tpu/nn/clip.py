"""Gradient clipping (reference: python/paddle/fluid/clip.py —
ClipGradByValue/Norm/GlobalNorm). Each exposes ``_apply_jax(list_of_grads)``,
a pure function composed into the optimizer's fused jitted step."""
from __future__ import annotations

import jax.numpy as jnp


class ClipGradBase:
    def _apply_jax(self, grads):
        raise NotImplementedError

    def __call__(self, params_grads):
        # static-graph style API compat: list of (param, grad) tensors
        from ..core.tensor import Tensor

        gs = [g.data for _, g in params_grads]
        new = self._apply_jax(gs)
        return [(p, Tensor(g)) for (p, _), g in zip(params_grads, new)]


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -float(max)

    def _apply_jax(self, grads):
        return [jnp.clip(g, self.min, self.max) for g in grads]


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def _apply_jax(self, grads):
        out = []
        for g in grads:
            norm = jnp.sqrt(jnp.sum(jnp.square(g.astype(jnp.float32))))
            scale = jnp.minimum(self.clip_norm / jnp.maximum(norm, 1e-12), 1.0)
            out.append((g.astype(jnp.float32) * scale).astype(g.dtype))
        return out


class ClipGradByGlobalNorm(ClipGradBase):
    def __init__(self, clip_norm, group_name="default_group", auto_skip_clip=False):
        self.clip_norm = float(clip_norm)

    def _apply_jax(self, grads):
        sq = sum(jnp.sum(jnp.square(g.astype(jnp.float32))) for g in grads)
        gnorm = jnp.sqrt(sq)
        scale = jnp.minimum(self.clip_norm / jnp.maximum(gnorm, 1e-12), 1.0)
        return [(g.astype(jnp.float32) * scale).astype(g.dtype) for g in grads]
