"""Weight initializers (reference: python/paddle/nn/initializer/, fluid initializers).

Each initializer is a callable ``(shape, dtype) -> jax array`` drawing from the
global threefry stream — functional keys under the hood, stateful seed API on top.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ...framework import random as random_mod
from ...framework import dtype as dtype_mod


def _fans(shape):
    shape = tuple(shape)
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    receptive = int(np.prod(shape[2:]))
    # conv kernels stored OIHW: fan_in = in_ch * k*k, fan_out = out_ch * k*k
    return shape[1] * receptive, shape[0] * receptive


class Initializer:
    def __call__(self, shape, dtype):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, shape, dtype):
        return jnp.full(tuple(shape), self.value, dtype_mod.convert_dtype(dtype))


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0):
        self.low, self.high = low, high

    def __call__(self, shape, dtype):
        return jax.random.uniform(
            random_mod.next_key(), tuple(shape), dtype_mod.convert_dtype(dtype),
            self.low, self.high)


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype):
        return self.mean + self.std * jax.random.normal(
            random_mod.next_key(), tuple(shape), dtype_mod.convert_dtype(dtype))


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype):
        return self.mean + self.std * jax.random.truncated_normal(
            random_mod.next_key(), -2.0, 2.0, tuple(shape), dtype_mod.convert_dtype(dtype))


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None):
        self._fan_in, self._fan_out = fan_in, fan_out

    def __call__(self, shape, dtype):
        fi, fo = _fans(shape)
        fi = self._fan_in if self._fan_in is not None else fi
        fo = self._fan_out if self._fan_out is not None else fo
        limit = math.sqrt(6.0 / (fi + fo))
        return jax.random.uniform(
            random_mod.next_key(), tuple(shape), dtype_mod.convert_dtype(dtype),
            -limit, limit)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None):
        self._fan_in, self._fan_out = fan_in, fan_out

    def __call__(self, shape, dtype):
        fi, fo = _fans(shape)
        fi = self._fan_in if self._fan_in is not None else fi
        fo = self._fan_out if self._fan_out is not None else fo
        std = math.sqrt(2.0 / (fi + fo))
        return std * jax.random.normal(
            random_mod.next_key(), tuple(shape), dtype_mod.convert_dtype(dtype))


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self._fan_in = fan_in

    def __call__(self, shape, dtype):
        fi, _ = _fans(shape)
        fi = self._fan_in if self._fan_in is not None else fi
        limit = math.sqrt(6.0 / fi)
        return jax.random.uniform(
            random_mod.next_key(), tuple(shape), dtype_mod.convert_dtype(dtype),
            -limit, limit)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self._fan_in = fan_in

    def __call__(self, shape, dtype):
        fi, _ = _fans(shape)
        fi = self._fan_in if self._fan_in is not None else fi
        std = math.sqrt(2.0 / fi)
        return std * jax.random.normal(
            random_mod.next_key(), tuple(shape), dtype_mod.convert_dtype(dtype))


class Assign(Initializer):
    def __init__(self, value):
        self.value = value

    def __call__(self, shape, dtype):
        from ...core.tensor import Tensor

        v = self.value
        if isinstance(v, Tensor):
            arr = v.data
        else:
            arr = jnp.asarray(np.asarray(v))
        assert tuple(arr.shape) == tuple(shape), f"Assign shape {arr.shape} != {shape}"
        return arr.astype(dtype_mod.convert_dtype(dtype))


class Orthogonal(Initializer):
    def __init__(self, gain=1.0):
        self.gain = gain

    def __call__(self, shape, dtype):
        init = jax.nn.initializers.orthogonal(self.gain)
        return init(random_mod.next_key(), tuple(shape), dtype_mod.convert_dtype(dtype))


class Dirac(Initializer):
    def __init__(self, groups=1):
        self.groups = groups

    def __call__(self, shape, dtype):
        arr = np.zeros(shape, dtype=np.float32)
        oc, ic = shape[0], shape[1]
        centers = [s // 2 for s in shape[2:]]
        for i in range(min(oc, ic)):
            arr[(i, i) + tuple(centers)] = 1.0
        return jnp.asarray(arr, dtype_mod.convert_dtype(dtype))


def calculate_gain(nonlinearity, param=None):
    gains = {
        "sigmoid": 1.0, "linear": 1.0, "conv2d": 1.0, "tanh": 5.0 / 3.0,
        "relu": math.sqrt(2.0), "selu": 3.0 / 4.0,
    }
    if nonlinearity == "leaky_relu":
        a = 0.01 if param is None else param
        return math.sqrt(2.0 / (1 + a**2))
    return gains.get(nonlinearity, 1.0)
