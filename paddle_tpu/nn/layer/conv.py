"""Conv layers (reference: python/paddle/nn/layer/conv.py). Kernels OIHW."""
from __future__ import annotations

import math

from .. import functional as F
from .. import initializer as I
from .layers import Layer


class _ConvNd(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride, padding,
                 dilation, groups, weight_attr, bias_attr, ndim):
        super().__init__()
        if isinstance(kernel_size, int):
            kernel_size = (kernel_size,) * ndim
        self._in_channels = in_channels
        self._out_channels = out_channels
        self._kernel_size = tuple(kernel_size)
        self._stride = stride
        self._padding = padding
        self._dilation = dilation
        self._groups = groups
        fan_in = in_channels * int(math.prod(self._kernel_size)) // groups
        bound = 1.0 / math.sqrt(fan_in)
        self.weight = self.create_parameter(
            [out_channels, in_channels // groups, *self._kernel_size],
            attr=weight_attr, default_initializer=I.KaimingUniform(fan_in=fan_in))
        if bias_attr is False:
            self.bias = None
            self._parameters["bias"] = None
        else:
            self.bias = self.create_parameter(
                [out_channels], attr=bias_attr, is_bias=True,
                default_initializer=I.Uniform(-bound, bound))


class Conv2D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 dilation=1, groups=1, padding_mode="zeros", weight_attr=None,
                 bias_attr=None, data_format="NCHW"):
        super().__init__(in_channels, out_channels, kernel_size, stride, padding,
                         dilation, groups, weight_attr, bias_attr, 2)
        self._data_format = data_format

    def forward(self, x):
        return F.conv2d(x, self.weight, self.bias, self._stride, self._padding,
                        self._dilation, self._groups, self._data_format)

    def extra_repr(self):
        return (f"{self._in_channels}, {self._out_channels}, "
                f"kernel_size={self._kernel_size}, stride={self._stride}")


class Conv1D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 dilation=1, groups=1, padding_mode="zeros", weight_attr=None,
                 bias_attr=None, data_format="NCL"):
        super().__init__(in_channels, out_channels, kernel_size, stride, padding,
                         dilation, groups, weight_attr, bias_attr, 1)

    def forward(self, x):
        return F.conv1d(x, self.weight, self.bias, self._stride, self._padding,
                        self._dilation, self._groups)


class Conv2DTranspose(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 output_padding=0, groups=1, dilation=1, weight_attr=None,
                 bias_attr=None, data_format="NCHW"):
        super().__init__()
        if isinstance(kernel_size, int):
            kernel_size = (kernel_size, kernel_size)
        self._stride = stride
        self._padding = padding
        self._output_padding = output_padding
        self._dilation = dilation
        self._groups = groups
        fan_in = in_channels * kernel_size[0] * kernel_size[1]
        self.weight = self.create_parameter(
            [in_channels, out_channels // groups, *kernel_size], attr=weight_attr,
            default_initializer=I.KaimingUniform(fan_in=fan_in))
        if bias_attr is False:
            self.bias = None
            self._parameters["bias"] = None
        else:
            self.bias = self.create_parameter([out_channels], attr=bias_attr, is_bias=True)

    def forward(self, x, output_size=None):
        return F.conv2d_transpose(
            x, self.weight, self.bias, self._stride, self._padding,
            output_padding=0 if output_size is not None else self._output_padding,
            groups=self._groups, dilation=self._dilation,
            output_size=output_size)
