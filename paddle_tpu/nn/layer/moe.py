"""Mixture-of-Experts layers (expert parallelism over the 'ep' mesh axis).

Reference: paddle/fluid/operators/collective/global_scatter_op.cc +
global_gather_op.cc (expert-parallel all-to-all by counts) and
python/paddle/distributed/models/moe/utils.py — the snapshot has only these
primitives, no production MoE layer; BASELINE config 5 (DeepSeekMoE/Qwen2-MoE
4D) requires the full layer.

TPU-native design: capacity-dense GShard-style routing — top-k gate, tokens
packed into a static [E, capacity, d] buffer via one-hot dispatch einsums;
expert weights are stacked on a leading E dim with dist_spec P('ep', ...), so
GSPMD lowers the dispatch/combine einsums into exactly the all_to_all pattern
the reference's global_scatter/global_gather hand-code, and the per-expert
FFNs run as one batched MXU matmul. No ragged shapes, no host round-trips.
"""
from __future__ import annotations

import contextlib
import functools
import math

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ...core.dispatch import primitive
from ...core.tensor import Tensor
from .layers import Layer
from .. import initializer as I

# -- aux-loss plumbing --------------------------------------------------------
# MoE layers record their load-balancing loss here; model heads drain it and
# add it to the objective. Works eagerly and under trace (values are traced
# scalars); scan/pipeline stacks thread it explicitly (models/llama.py).

_AUX_STACK = []


@contextlib.contextmanager
def collect_aux():
    bucket = []
    _AUX_STACK.append(bucket)
    try:
        yield bucket
    finally:
        _AUX_STACK.pop()


def record_aux(v):
    if _AUX_STACK:
        _AUX_STACK[-1].append(v)


def drain_aux(bucket):
    """Sum of recorded aux losses as a Tensor (0.0 when none)."""
    if not bucket:
        return None
    total = bucket[0]
    for v in bucket[1:]:
        total = total + v
    return total


def _route(xt, wg, top_k):
    """Router: fp32 softmax + renormalized top-k, and the Switch/GShard
    load-balancing aux (e * sum(frac_tokens * frac_probs))."""
    n, _ = xt.shape
    e = wg.shape[1]
    logits = jnp.matmul(xt.astype(jnp.float32), wg.astype(jnp.float32))
    probs = jax.nn.softmax(logits, axis=-1)  # [n, e]
    gate_v, gate_i = jax.lax.top_k(probs, top_k)  # [n, k]
    gate_v = gate_v / jnp.maximum(jnp.sum(gate_v, -1, keepdims=True), 1e-9)
    me = jnp.mean(probs, axis=0)
    ce = jnp.mean(jax.nn.one_hot(gate_i[:, 0], e, dtype=jnp.float32), axis=0)
    aux = e * jnp.sum(me * ce)
    return gate_v, gate_i, aux


def _expert_ffn(expert_in, w_gate, w_up, w_down, ep_degree):
    """Batched per-expert SwiGLU on [e, cap, h] buffers (one MXU matmul per
    projection; gate/up separate so the silu(gate)*up multiply stays local
    per mp shard). Inputs/outputs carry checkpoint names so
    FLAGS_remat_policy='moe' can pin them across the remat boundary (the
    backward then rebuilds only g/u from the saved buffer instead of
    re-running dispatch + the down projection)."""
    from jax.ad_checkpoint import checkpoint_name

    expert_in = checkpoint_name(_ep_constraint(expert_in, ep_degree),
                                "moe_buf")
    g = jnp.einsum("ech,ehi->eci", expert_in, w_gate)
    u = jnp.einsum("ech,ehi->eci", expert_in, w_up)
    act = jax.nn.silu(g) * u
    expert_out = jnp.einsum("eci,eih->ech", act, w_down)
    return checkpoint_name(_ep_constraint(expert_out, ep_degree), "moe_out")


@primitive("moe_mlp")
def _moe_mlp(x, wg, w_gate, w_up, w_down, *, top_k, capacity_factor,
             ep_degree, dispatch="index"):
    """Routed expert FFN: [b, s, h] -> ([b, s, h], aux_loss).

    Four dispatch strategies; the capacity modes share drop semantics
    (slot-major: every token's 1st choice outranks any 2nd choice):

    - 'index' (default): capacity slots assigned by a cumsum over the
      [k*n, e] expert one-hot — no argsort, no inverse permutation (the
      choice-major flat order IS the combine order), all row movement plain
      gathers. v5e at the bench shape: 19% faster fwd+bwd than 'sort'.
    - 'sort': tokens argsorted by expert id; each (token, choice) takes the
      next position in its expert's capacity buffer via a gather. The
      TPU-native form of the reference's count-based global_scatter
      (global_scatter_op.cc builds exactly these per-expert contiguous
      buffers from counts).
    - 'gmm': DROPLESS grouped matmul (kernels/grouped_matmul.py, megablox
      Pallas kernel on TPU) — rows sorted by expert, per-expert ragged row
      blocks walked back-to-back on the MXU; no capacity, no padding waste,
      capacity_factor ignored. Single-device experts only (falls back to
      'index' when ep_degree > 1 — ragged row counts can't cross a GSPMD
      all_to_all with static shapes).
    - 'fused': DROPLESS fused routing/dispatch (kernels/pallas/
      moe_dispatch.py) — the whole router (top-k + sort-by-expert
      position counters) is one Pallas kernel and row movement runs as
      scalar-prefetch gathers with gather-only VJPs, feeding the same
      grouped matmul; row order (and therefore output) matches 'gmm'
      without executing the argsort. Single-device experts and
      num_experts <= 128 only (falls back to 'index' outside that).
    - 'einsum': GShard one-hot dispatch/combine einsums. O(n*e*cap)
      intermediates — kept as the oracle for parity tests.

    `dispatch` is a primitive ATTR (cache-key participant): the caller reads
    the flag so a set_flags after the first call still takes effect.
    """
    if dispatch == "fused" and ep_degree <= 1:
        from ...kernels.pallas.moe_dispatch import MAX_EXPERTS, fused_moe_mlp

        if wg.shape[1] <= MAX_EXPERTS:
            return fused_moe_mlp(x, wg, w_gate, w_up, w_down, top_k=top_k)
    if dispatch == "gmm" and ep_degree <= 1:
        return _moe_mlp_gmm(x, wg, w_gate, w_up, w_down, top_k=top_k)
    impl = {"einsum": _moe_mlp_einsum, "sort": _moe_mlp_sort}.get(
        dispatch, _moe_mlp_index)
    return impl(x, wg, w_gate, w_up, w_down, top_k=top_k,
                capacity_factor=capacity_factor, ep_degree=ep_degree)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def _idx_dispatch(xt, slot_src, slot, keep, top_k):
    """buf[s] = xt[slot_src[s]] (zero row for empty slots) with a
    GATHER-ONLY backward: XLA's transpose of this gather is a [e*cap, h]
    scatter-add — serialized row writes on TPU, measured at 21% of the MoE
    MLP fwd+bwd. The cotangent is instead gathered back through `slot`
    (d_xt[t] = sum_k d_buf[slot[k,t]] masked by keep) — the same index
    structure, no scatter."""
    xt_pad = jnp.concatenate([xt, jnp.zeros((1, xt.shape[1]), xt.dtype)])
    return xt_pad[slot_src]


def _idx_dispatch_fwd(xt, slot_src, slot, keep, top_k):
    return _idx_dispatch(xt, slot_src, slot, keep, top_k), \
        (slot, keep, xt.shape[0])


def _idx_dispatch_bwd(top_k, res, g_buf):
    slot, keep, n = res
    ec = g_buf.shape[0]
    picked = jnp.where(keep[:, None],
                       g_buf[jnp.clip(slot, 0, ec - 1)],
                       jnp.zeros((), g_buf.dtype))
    d_xt = jnp.sum(picked.reshape(top_k, n, -1), axis=0)
    return d_xt, None, None, None


_idx_dispatch.defvjp(_idx_dispatch_fwd, _idx_dispatch_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5,))
def _idx_combine(y, gates, slot, keep, slot_rowsrc, top_k):
    """out[t] = sum_k keep * y[slot[k,t]] * gates[k,t], backward all
    gathers: d_y[s] = d_out[slot_rowsrc[s] % n] * gates[slot_rowsrc[s]]
    (slot_rowsrc maps each slot to its flat choice-major row, built by a
    cheap int32 scatter in the caller), d_gates[r] = <d_out[t_r], y[slot[r]]>."""
    kn = slot.shape[0]
    n = kn // top_k
    ec = y.shape[0]
    contrib = jnp.where(keep[:, None],
                        y[jnp.clip(slot, 0, ec - 1)],
                        jnp.zeros((), y.dtype)) * \
        gates[:, None].astype(y.dtype)
    return jnp.sum(contrib.reshape(top_k, n, -1), axis=0)


def _idx_combine_fwd(y, gates, slot, keep, slot_rowsrc, top_k):
    return _idx_combine(y, gates, slot, keep, slot_rowsrc, top_k), \
        (y, gates, slot, keep, slot_rowsrc)


def _idx_combine_bwd(top_k, res, d_out):
    y, gates, slot, keep, slot_rowsrc = res
    kn = slot.shape[0]
    n = kn // top_k
    ec = y.shape[0]
    # d_y: route each occupied slot back to its token's cotangent row
    occupied = slot_rowsrc < kn
    row = jnp.clip(slot_rowsrc, 0, kn - 1)
    d_y = jnp.where(occupied[:, None],
                    d_out[row % n] * gates[row][:, None].astype(d_out.dtype),
                    jnp.zeros((), d_out.dtype)).astype(y.dtype)
    # d_gates: rowwise dot of the token cotangent with the expert output
    y_rows = jnp.where(keep[:, None],
                       y[jnp.clip(slot, 0, ec - 1)],
                       jnp.zeros((), y.dtype))
    tok = jnp.arange(kn, dtype=jnp.int32) % n
    d_gates = jnp.sum(d_out[tok].astype(jnp.float32) *
                      y_rows.astype(jnp.float32), axis=1).astype(gates.dtype)
    return d_y, d_gates, None, None, None


_idx_combine.defvjp(_idx_combine_fwd, _idx_combine_bwd)


def _moe_mlp_index(x, wg, w_gate, w_up, w_down, *, top_k, capacity_factor,
                   ep_degree):
    """Capacity dispatch without the sort: positions come from a cumsum over
    the [k*n, e] one-hot (GShard's position_in_expert), so there is no
    argsort, no searchsorted, and — because the flat order is choice-major
    by construction — no inverse permutation at combine time. Row movement
    is two gathers FORWARD AND BACKWARD (_idx_dispatch/_idx_combine custom
    vjps); only int32 index vectors are ever scattered."""
    b, s, h = x.shape
    n = b * s
    e = wg.shape[1]
    kn = top_k * n
    cap = max(int(math.ceil(capacity_factor * top_k * n / e)), top_k)

    xt = x.reshape(n, h)
    gate_v, gate_i, aux = _route(xt, wg, top_k)

    # choice-major flattening: all 1st choices precede any 2nd choice, so
    # the running count gives 1st choices capacity priority
    flat_e = gate_i.T.reshape(kn)
    flat_g = gate_v.T.reshape(kn)
    oh = flat_e[:, None] == jnp.arange(e, dtype=flat_e.dtype)[None, :]
    pos = jnp.cumsum(oh.astype(jnp.int32), axis=0) - 1
    pos_in_e = jnp.sum(jnp.where(oh, pos, 0), axis=1)
    keep = pos_in_e < cap
    # dropped entries land on a scratch slot past the buffer
    slot = jnp.where(keep, flat_e * cap + pos_in_e, e * cap)

    # slot -> flat (choice-major) row: the ONE int32 scatter; the token
    # map follows arithmetically (row = k*n + t, so token = row % n with
    # the empty-slot sentinel mapped to n for the zero pad row)
    slot_rowsrc = jnp.full((e * cap + 1,), kn, jnp.int32).at[slot].set(
        jnp.arange(kn, dtype=jnp.int32), mode="drop")[:-1]
    slot_src = jnp.where(slot_rowsrc < kn, slot_rowsrc % n, n)
    # name the routing decisions (~1MB total) so FLAGS_remat_policy='route'
    # pins them across the remat boundary: the backward recompute then
    # skips the router matmul + softmax + top_k + cumsum + int scatters
    from jax.ad_checkpoint import checkpoint_name

    slot = checkpoint_name(slot, "moe_route")
    keep = checkpoint_name(keep, "moe_route")
    slot_src = checkpoint_name(slot_src, "moe_route")
    slot_rowsrc = checkpoint_name(slot_rowsrc, "moe_route")
    flat_g = checkpoint_name(flat_g, "moe_route")
    buf = _idx_dispatch(xt, slot_src, slot, keep, top_k)

    expert_out = _expert_ffn(buf.reshape(e, cap, h), w_gate, w_up,
                             w_down, ep_degree).reshape(e * cap, h)

    out = _idx_combine(expert_out, flat_g, slot, keep, slot_rowsrc, top_k)
    return out.reshape(b, s, h), aux


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _gmm_disp_gather(xt, order, inv, top_k):
    """xs[i] = xt[order[i] // top_k] with a gather-only backward: the
    cotangent is unsorted by `inv` (a gather, not the scatter XLA would
    emit for this op's transpose) and summed over the k choice copies."""
    return jnp.take(xt, order // top_k, axis=0)


def _gmm_disp_fwd(xt, order, inv, top_k):
    return jnp.take(xt, order // top_k, axis=0), (inv, xt.shape[0])


def _gmm_disp_bwd(top_k, res, g):
    inv, n = res
    gt = jnp.take(g, inv, axis=0).reshape(n, top_k, -1).sum(axis=1)
    return gt, None, None


_gmm_disp_gather.defvjp(_gmm_disp_fwd, _gmm_disp_bwd)


@jax.custom_vjp
def _perm_rows(x, perm, inv_perm):
    """x[perm] for a permutation, with the backward expressed as the inverse
    gather instead of XLA's scatter transpose."""
    return jnp.take(x, perm, axis=0)


def _perm_rows_fwd(x, perm, inv_perm):
    return jnp.take(x, perm, axis=0), (inv_perm,)


def _perm_rows_bwd(res, g):
    (inv_perm,) = res
    return jnp.take(g, inv_perm, axis=0), None, None


_perm_rows.defvjp(_perm_rows_fwd, _perm_rows_bwd)


def _moe_mlp_gmm(x, wg, w_gate, w_up, w_down, *, top_k):
    """Dropless expert FFN: sort the k*n (token, choice) rows by expert and
    run the ragged per-expert blocks through one grouped matmul per
    projection (kernels/grouped_matmul.py). Executed FLOPs == activated
    FLOPs — no capacity padding, no drops."""
    from ...kernels.grouped_matmul import grouped_matmul

    b, s, h = x.shape
    n = b * s
    e = wg.shape[1]
    kn = top_k * n

    xt = x.reshape(n, h)
    gate_v, gate_i, aux = _route(xt, wg, top_k)

    flat_e = gate_i.reshape(kn)  # token-major: row t*k+c = choice c of t
    order = jnp.argsort(flat_e, stable=True)
    inv = jnp.zeros((kn,), jnp.int32).at[order].set(
        jnp.arange(kn, dtype=jnp.int32))  # int scatter, not a second sort
    group_sizes = jnp.bincount(flat_e, length=e)

    xs = _gmm_disp_gather(xt, order, inv, top_k)  # [kn, h] expert-grouped
    g_proj = grouped_matmul(xs, w_gate, group_sizes)
    u_proj = grouped_matmul(xs, w_up, group_sizes)
    act = jax.nn.silu(g_proj) * u_proj
    ys = grouped_matmul(act, w_down, group_sizes)  # [kn, h]

    y_tok = _perm_rows(ys, inv, order).reshape(n, top_k, h)
    out = jnp.sum(y_tok * gate_v[:, :, None].astype(x.dtype), axis=1)
    return out.reshape(b, s, h), aux


def _moe_mlp_sort(x, wg, w_gate, w_up, w_down, *, top_k, capacity_factor,
                  ep_degree):
    """All [*, h]-row movement is GATHERS — TPU scatters of wide rows
    serialize, so the two scatters here touch only int32 index vectors
    (slot->source map and inverse permutation)."""
    b, s, h = x.shape
    n = b * s
    e = wg.shape[1]
    kn = top_k * n
    cap = max(int(math.ceil(capacity_factor * top_k * n / e)), top_k)

    xt = x.reshape(n, h)
    gate_v, gate_i, aux = _route(xt, wg, top_k)

    # slot-major flattening (all 1st choices before any 2nd choice), then a
    # stable sort by expert groups tokens while preserving choice priority
    flat_e = gate_i.T.reshape(kn)
    flat_g = gate_v.T.reshape(kn)
    order = jnp.argsort(flat_e, stable=True)
    sorted_e = flat_e[order]
    starts = jnp.searchsorted(sorted_e, jnp.arange(e))  # [e] group offsets
    pos = jnp.arange(kn, dtype=jnp.int32) - starts[sorted_e]
    keep = pos < cap
    # dropped entries land on a scratch slot past the buffer
    slot = jnp.where(keep, sorted_e * cap + pos, e * cap)
    tok = order % n  # flat index j = choice*n + token

    # dispatch: slot -> source token map (int scatter), then one row gather;
    # unfilled slots point at a zero row
    slot_src = jnp.full((e * cap + 1,), n, jnp.int32).at[slot].set(
        tok.astype(jnp.int32), mode="drop")[:-1]
    xt_pad = jnp.concatenate([xt, jnp.zeros((1, h), x.dtype)])
    buf = xt_pad[slot_src]

    expert_out = _expert_ffn(buf.reshape(e, cap, h), w_gate, w_up,
                             w_down, ep_degree).reshape(e * cap, h)

    # combine: gather each kept choice's output row, undo the sort with the
    # inverse permutation (int scatter + gather), then sum the k choices
    contrib = jnp.where(
        keep[:, None],
        expert_out[jnp.clip(slot, 0, e * cap - 1)],
        jnp.zeros((), x.dtype)) * flat_g[order][:, None].astype(x.dtype)
    inv = jnp.zeros((kn,), jnp.int32).at[order].set(
        jnp.arange(kn, dtype=jnp.int32))
    out = jnp.sum(contrib[inv].reshape(top_k, n, h), axis=0)
    return out.reshape(b, s, h), aux


def _moe_mlp_einsum(x, wg, w_gate, w_up, w_down, *, top_k, capacity_factor,
                    ep_degree):
    b, s, h = x.shape
    n = b * s
    e = wg.shape[1]
    cap = max(int(math.ceil(capacity_factor * top_k * n / e)), top_k)

    xt = x.reshape(n, h)
    gate_v, gate_i, aux = _route(xt, wg, top_k)

    # slot-major one-hot so the 1st choice wins capacity over 2nd choices
    oh = jax.nn.one_hot(gate_i.T.reshape(top_k * n), e, dtype=jnp.float32)
    pos = (jnp.cumsum(oh, axis=0) - 1.0) * oh  # [k*n, e] position in expert
    pos_in_e = jnp.sum(pos, axis=-1)  # [k*n]
    keep = (pos_in_e < cap).astype(jnp.float32)[:, None] * oh  # [k*n, e]
    # dispatch/combine [k*n, e, cap]
    cap_oh = jax.nn.one_hot(pos_in_e.astype(jnp.int32), cap, dtype=jnp.float32)
    disp = keep[:, :, None] * cap_oh[:, None, :]
    disp = disp.reshape(top_k, n, e, cap).transpose(1, 0, 2, 3)  # [n, k, e, cap]
    combine = disp * gate_v[:, :, None, None]
    disp = jnp.sum(disp, axis=1)  # [n, e, cap]
    combine = jnp.sum(combine, axis=1)

    expert_in = jnp.einsum("nec,nh->ech", disp.astype(x.dtype), xt)
    expert_out = _expert_ffn(expert_in, w_gate, w_up, w_down, ep_degree)
    out = jnp.einsum("ech,nec->nh", expert_out, combine.astype(x.dtype))
    return out.reshape(b, s, h), aux


def _ep_constraint(t, ep_degree):
    if ep_degree <= 1:
        return t
    from ...distributed.meta_parallel.mp_layers import constrain_spec

    return constrain_spec(t, ("ep", None, None))


class ExpertMLP(Layer):
    """Stacked per-expert SwiGLU FFN weights, expert dim sharded over 'ep'."""

    def __init__(self, num_experts, hidden_size, intermediate_size):
        super().__init__()
        e, h, i = num_experts, hidden_size, intermediate_size
        self.gate = self.create_parameter(
            [e, h, i], default_initializer=I.XavierUniform())
        self.up = self.create_parameter(
            [e, h, i], default_initializer=I.XavierUniform())
        self.down = self.create_parameter(
            [e, i, h], default_initializer=I.XavierUniform())
        self.gate.dist_spec = P("ep", None, "mp")
        self.up.dist_spec = P("ep", None, "mp")
        self.down.dist_spec = P("ep", "mp", None)


class MoELayer(Layer):
    """Gated expert layer (role of the post-snapshot reference MoELayer;
    dispatch = global_scatter, combine = global_gather, both emerging from
    GSPMD on the einsums given the 'ep' placement).

    recompute_interval/group args kept for API shape.
    """

    def __init__(self, d_model, num_experts, intermediate_size=None, top_k=2,
                 capacity_factor=1.25, gate=None, recompute_interval=0,
                 group=None):
        super().__init__()
        self.d_model = d_model
        self.num_experts = num_experts
        self.top_k = top_k
        self.capacity_factor = float(capacity_factor)
        intermediate_size = intermediate_size or 4 * d_model
        self.gate_weight = self.create_parameter(
            [d_model, num_experts], default_initializer=I.XavierUniform())
        self.experts = ExpertMLP(num_experts, d_model, intermediate_size)

    def forward(self, x):
        from ...distributed.mesh import get_mesh_env
        from ...framework import flags as flags_mod

        env = get_mesh_env()
        ep = env.get_dim("ep") if env is not None else 1
        mode = flags_mod.get_flags("FLAGS_moe_dispatch")["FLAGS_moe_dispatch"]
        out, aux = _moe_mlp(x, self.gate_weight, self.experts.gate,
                            self.experts.up, self.experts.down, top_k=self.top_k,
                            capacity_factor=self.capacity_factor, ep_degree=ep,
                            dispatch=mode)
        record_aux(aux)
        return out
