"""Round-3 layer-zoo completion: the 1-D/3-D variants, unpooling, padding,
alpha dropout, hierarchical-sigmoid/CTC losses, and beam-search decoding the
reference exports from paddle.nn (python/paddle/nn/__init__.py) that were
still missing. Thin Layer wrappers over nn.functional — the math lives there.
"""
from __future__ import annotations

import math

from .. import functional as F
from .. import initializer as I
from .layers import Layer
from .conv import _ConvNd


class Conv3D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCDHW"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, weight_attr, bias_attr, 3)

    def forward(self, x):
        return F.conv3d(x, self.weight, self.bias, self._stride,
                        self._padding, self._dilation, self._groups)


class _ConvTransposeNd(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride,
                 padding, output_padding, groups, dilation, weight_attr,
                 bias_attr, ndim):
        super().__init__()
        if isinstance(kernel_size, int):
            kernel_size = (kernel_size,) * ndim
        self._stride = stride
        self._padding = padding
        self._output_padding = output_padding
        self._groups = groups
        self._dilation = dilation
        fan_in = in_channels * int(math.prod(kernel_size))
        self.weight = self.create_parameter(
            [in_channels, out_channels // groups, *kernel_size],
            attr=weight_attr, default_initializer=I.KaimingUniform(fan_in=fan_in))
        if bias_attr is False:
            self.bias = None
            self._parameters["bias"] = None
        else:
            bound = 1.0 / math.sqrt(max(fan_in, 1))
            self.bias = self.create_parameter(
                [out_channels], attr=bias_attr, is_bias=True,
                default_initializer=I.Uniform(-bound, bound))


class Conv1DTranspose(_ConvTransposeNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, groups=1, dilation=1,
                 weight_attr=None, bias_attr=None, data_format="NCL"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, output_padding, groups, dilation,
                         weight_attr, bias_attr, 1)

    def forward(self, x, output_size=None):
        return F.conv1d_transpose(x, self.weight, self.bias, self._stride,
                                  self._padding, self._output_padding,
                                  self._groups, self._dilation, output_size)


class Conv3DTranspose(_ConvTransposeNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, groups=1, dilation=1,
                 weight_attr=None, bias_attr=None, data_format="NCDHW"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, output_padding, groups, dilation,
                         weight_attr, bias_attr, 3)

    def forward(self, x, output_size=None):
        return F.conv3d_transpose(x, self.weight, self.bias, self._stride,
                                  self._padding, self._output_padding,
                                  self._groups, self._dilation, output_size)


def _pool_layer(fname, ndims_kw=None):
    class _Pool(Layer):
        def __init__(self, kernel_size, stride=None, padding=0,
                     ceil_mode=False, return_mask=False, exclusive=True,
                     divisor_override=None, data_format=None, name=None):
            super().__init__()
            self.kernel_size = kernel_size
            self.stride = stride
            self.padding = padding
            self.return_mask = return_mask
            self.exclusive = exclusive
            self._fn = getattr(F, fname)
            self._is_max = fname.startswith("max")

        def forward(self, x):
            if self._is_max:
                return self._fn(x, self.kernel_size, self.stride,
                                self.padding, return_mask=self.return_mask)
            return self._fn(x, self.kernel_size, self.stride, self.padding,
                            exclusive=self.exclusive)

    _Pool.__name__ = "".join(w.capitalize() for w in fname.split("_"))
    return _Pool


MaxPool1D = _pool_layer("max_pool1d")
AvgPool1D = _pool_layer("avg_pool1d")
MaxPool3D = _pool_layer("max_pool3d")
AvgPool3D = _pool_layer("avg_pool3d")


class _AdaptivePool(Layer):
    def __init__(self, output_size, fname, return_mask=False):
        super().__init__()
        self.output_size = output_size
        self.return_mask = return_mask
        self._fn = getattr(F, fname)
        self._is_max = "max" in fname

    def forward(self, x):
        if self._is_max:
            return self._fn(x, self.output_size,
                            return_mask=self.return_mask)
        return self._fn(x, self.output_size)


class AdaptiveAvgPool1D(_AdaptivePool):
    def __init__(self, output_size, name=None):
        super().__init__(output_size, "adaptive_avg_pool1d")


class AdaptiveMaxPool1D(_AdaptivePool):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__(output_size, "adaptive_max_pool1d", return_mask)


class AdaptiveAvgPool3D(_AdaptivePool):
    def __init__(self, output_size, data_format="NCDHW", name=None):
        super().__init__(output_size, "adaptive_avg_pool3d")


class AdaptiveMaxPool3D(_AdaptivePool):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__(output_size, "adaptive_max_pool3d", return_mask)


class _MaxUnPool(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, fname="",
                 data_format=None, output_size=None, name=None):
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.output_size = output_size
        self._fn = getattr(F, fname)

    def forward(self, x, indices):
        return self._fn(x, indices, self.kernel_size, self.stride,
                        self.padding, output_size=self.output_size)


class MaxUnPool1D(_MaxUnPool):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCL", output_size=None, name=None):
        super().__init__(kernel_size, stride, padding, "max_unpool1d",
                         output_size=output_size)


class MaxUnPool2D(_MaxUnPool):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None, name=None):
        super().__init__(kernel_size, stride, padding, "max_unpool2d",
                         output_size=output_size)


class MaxUnPool3D(_MaxUnPool):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None, name=None):
        super().__init__(kernel_size, stride, padding, "max_unpool3d",
                         output_size=output_size)


class _PadNd(Layer):
    def __init__(self, padding, mode, value, data_format):
        super().__init__()
        self._padding = padding
        self._mode = mode
        self._value = value
        self._data_format = data_format

    def forward(self, x):
        return F.pad(x, self._padding, self._mode, self._value,
                     self._data_format)


class Pad1D(_PadNd):
    def __init__(self, padding, mode="constant", value=0.0,
                 data_format="NCL", name=None):
        super().__init__(padding, mode, value, data_format)


class Pad3D(_PadNd):
    def __init__(self, padding, mode="constant", value=0.0,
                 data_format="NCDHW", name=None):
        super().__init__(padding, mode, value, data_format)


class Dropout3D(Layer):
    def __init__(self, p=0.5, data_format="NCDHW", name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        return F.dropout3d(x, self.p, training=self.training)


class AlphaDropout(Layer):
    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        return F.alpha_dropout(x, self.p, training=self.training)


class PairwiseDistance(Layer):
    def __init__(self, p=2.0, epsilon=1e-6, keepdim=False, name=None):
        super().__init__()
        self.p, self.epsilon, self.keepdim = p, epsilon, keepdim

    def forward(self, x, y):
        return F.pairwise_distance(x, y, self.p, self.epsilon, self.keepdim)


class Fold(Layer):
    def __init__(self, output_sizes, kernel_sizes, strides=1, paddings=0,
                 dilations=1, name=None):
        super().__init__()
        self._args = (output_sizes, kernel_sizes, strides, paddings,
                      dilations)

    def forward(self, x):
        return F.fold(x, *self._args)


class InstanceNorm1D(Layer):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCL",
                 name=None):
        super().__init__()
        self._epsilon = epsilon
        self.scale = self.create_parameter(
            [num_features], attr=weight_attr,
            default_initializer=I.Constant(1.0))
        self.bias = self.create_parameter([num_features], attr=bias_attr,
                                          is_bias=True)

    def forward(self, x):
        return F.instance_norm(x, weight=self.scale, bias=self.bias,
                               eps=self._epsilon)


class InstanceNorm3D(InstanceNorm1D):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCDHW",
                 name=None):
        super().__init__(num_features, epsilon, momentum, weight_attr,
                         bias_attr)


class CTCLoss(Layer):
    def __init__(self, blank=0, reduction="mean"):
        super().__init__()
        self.blank = blank
        self.reduction = reduction

    def forward(self, log_probs, labels, input_lengths, label_lengths):
        return F.ctc_loss(log_probs, labels, input_lengths, label_lengths,
                          self.blank, self.reduction)


class HSigmoidLoss(Layer):
    def __init__(self, feature_size, num_classes, weight_attr=None,
                 bias_attr=None, is_custom=False, is_sparse=False, name=None):
        super().__init__()
        if is_custom:
            raise ValueError("HSigmoidLoss custom trees are not supported; "
                             "the default complete-binary-tree coding is")
        self.num_classes = num_classes
        bound = 1.0 / math.sqrt(feature_size)
        self.weight = self.create_parameter(
            [num_classes - 1, feature_size], attr=weight_attr,
            default_initializer=I.Uniform(-bound, bound))
        self.bias = self.create_parameter([num_classes - 1], attr=bias_attr,
                                          is_bias=True)

    def forward(self, input, label):
        return F.hsigmoid_loss(input, label, self.num_classes, self.weight,
                               self.bias)


class BeamSearchDecoder:
    """Beam-search decoder over an RNN cell (reference nn/decode.py:77).

    Greedy-expand beams each step using the cell; drive with the module-level
    dynamic_decode below. Python-loop decoding (eager), matching the
    reference's dynamic_decode while-op semantics at beam_size fan-out.
    """

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn

    @staticmethod
    def _map_state(fn, *states):
        """Apply fn leafwise over (possibly nested tuple/list) cell states."""
        s0 = states[0]
        if isinstance(s0, (list, tuple)):
            return type(s0)(
                BeamSearchDecoder._map_state(fn, *parts)
                for parts in zip(*states))
        return fn(*states)

    def decode(self, inits, max_step_num=16):
        """Returns (token ids [B, beam, T], scores [B, beam])."""
        import numpy as np

        from ...ops import creation

        import paddle_tpu as paddle

        # step 0: expand the start token into beam_size beams per row
        inp = self._embed_ids(None, inits)
        out, state = self.cell(inp, inits)
        logits = self.output_fn(out) if self.output_fn else out
        lp = np.asarray(
            paddle.nn.functional.log_softmax(logits, axis=-1).numpy())
        B = lp.shape[0]
        top = np.argsort(-lp, axis=-1)[:, : self.beam_size]
        # beams[b] = list of (tokens, score, finished); cell states live in
        # slot_states[k], row-batched: row b of slot_states[k] is the state of
        # (row b, beam k). Step-0 state is parent-agnostic (all beams share it)
        beams = [[([int(top[b, k])], float(lp[b, top[b, k]]),
                   int(top[b, k]) == self.end_token)
                  for k in range(self.beam_size)] for b in range(B)]
        slot_states = [state] * self.beam_size

        for _ in range(1, max_step_num):
            if all(fin for bs in beams for *_x, fin in bs):
                break
            # ONE batched cell call per beam slot: rows advance together.
            # Expansions remember their parent slot so states can be re-
            # gathered after per-row re-ranking (standard beam-search state
            # reordering; reference nn/decode.py _beam_search_step gather).
            expansions = [[] for _ in range(B)]
            stepped = []  # stepped[k] = cell state after advancing slot k
            for k in range(self.beam_size):
                tokens = np.array([beams[b][k][0][-1] for b in range(B)],
                                  "int64")
                inp = self._embed_ids(tokens, inits)
                out, st2 = self.cell(inp, slot_states[k])
                stepped.append(st2)
                logits = self.output_fn(out) if self.output_fn else out
                lp = np.asarray(
                    paddle.nn.functional.log_softmax(logits, axis=-1).numpy())
                for b in range(B):
                    toks, score, fin = beams[b][k]
                    if fin:
                        expansions[b].append((toks, score, k, True))
                        continue
                    for t in np.argsort(-lp[b])[: self.beam_size]:
                        expansions[b].append(
                            (toks + [int(t)], score + float(lp[b, t]), k,
                             int(t) == self.end_token))
            parent = np.zeros((B, self.beam_size), "int64")
            for b in range(B):
                expansions[b].sort(key=lambda c: -c[1])
                sel = expansions[b][: self.beam_size]
                beams[b] = [(toks, score, fin) for toks, score, _j, fin in sel]
                parent[b] = [j for _t, _s, j, _f in sel]

            def _gather(k, *leaves):
                arrs = [np.asarray(l.numpy() if hasattr(l, "numpy") else l)
                        for l in leaves]
                stacked = np.stack(arrs)  # [beam, B, ...]
                return creation.to_tensor(stacked[parent[:, k], np.arange(B)])

            slot_states = [
                self._map_state(lambda *ls, _k=k: _gather(_k, *ls), *stepped)
                for k in range(self.beam_size)]

        T = max(len(toks) for bs in beams for toks, *_x in bs)
        ids = np.full((B, self.beam_size, T), self.end_token, "int64")
        scores = np.zeros((B, self.beam_size), "float32")
        for b in range(B):
            for k, (toks, score, *_x) in enumerate(beams[b]):
                ids[b, k, : len(toks)] = toks
                scores[b, k] = score
        return creation.to_tensor(ids), creation.to_tensor(scores)

    def _embed_ids(self, tokens, inits):
        """Batched embedding of one token per row (None = start token)."""
        import numpy as np

        from ...ops import creation

        ref = inits[0] if isinstance(inits, (list, tuple)) else inits
        batch = ref.shape[0]
        if tokens is None:
            tokens = np.full((batch,), self.start_token, "int64")
        ids = creation.to_tensor(np.asarray(tokens, "int64"))
        if self.embedding_fn is not None:
            return self.embedding_fn(ids)
        return ids


def dynamic_decode(decoder, inits=None, max_step_num=16, **kwargs):
    """reference nn/decode.py dynamic_decode over a BeamSearchDecoder."""
    return decoder.decode(inits, max_step_num=max_step_num)


class Unfold(Layer):
    def __init__(self, kernel_sizes, strides=1, paddings=0, dilations=1,
                 name=None):
        super().__init__()
        self._args = (kernel_sizes, strides, paddings, dilations)

    def forward(self, x):
        return F.unfold(x, *self._args)


class ZeroPad2D(_PadNd):
    def __init__(self, padding, data_format="NCHW", name=None):
        super().__init__(padding, "constant", 0.0, data_format)


class UpsamplingNearest2D(Layer):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self.size, self.scale_factor = size, scale_factor

    def forward(self, x):
        return F.interpolate(x, self.size, self.scale_factor, "nearest")


class UpsamplingBilinear2D(UpsamplingNearest2D):
    def forward(self, x):
        return F.interpolate(x, self.size, self.scale_factor, "bilinear",
                             align_corners=True)


class SpectralNorm(Layer):
    """Standalone spectral-norm layer (reference nn/layer/norm.py
    SpectralNorm): power-iterates on a held weight and returns the
    normalized weight (the hook-based variant is nn.utils.spectral_norm)."""

    def __init__(self, weight_shape, dim=0, power_iters=1, eps=1e-12,
                 name=None, dtype="float32"):
        super().__init__()
        self._dim = dim
        self._power_iters = power_iters
        self._eps = eps
        import numpy as np

        h = weight_shape[dim]
        w = int(np.prod(weight_shape)) // h
        self.weight_u = self.create_parameter(
            [h], default_initializer=I.Normal(0.0, 1.0))
        self.weight_v = self.create_parameter(
            [w], default_initializer=I.Normal(0.0, 1.0))
        self.weight_u.stop_gradient = True
        self.weight_v.stop_gradient = True

    def forward(self, weight):
        import paddle_tpu as paddle

        dim = self._dim
        mat = weight
        if dim != 0:
            perm = [dim] + [d for d in range(weight.ndim) if d != dim]
            mat = paddle.transpose(mat, perm)
        h = mat.shape[0]
        mat2 = paddle.reshape(mat, [h, -1])
        u, v = self.weight_u, self.weight_v
        for _ in range(self._power_iters):
            v_new = paddle.matmul(mat2, u, transpose_x=True)
            v = v_new / (paddle.norm(v_new) + self._eps)
            u_new = paddle.matmul(mat2, v)
            u = u_new / (paddle.norm(u_new) + self._eps)
        sigma = (u * paddle.matmul(mat2, v)).sum()
        out = mat2 / sigma
        out = paddle.reshape(out, list(mat.shape))
        if dim != 0:
            inv = [0] * weight.ndim
            perm = [dim] + [d for d in range(weight.ndim) if d != dim]
            for i, p in enumerate(perm):
                inv[p] = i
            out = paddle.transpose(out, inv)
        return out
