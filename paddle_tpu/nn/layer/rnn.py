"""Recurrent layers (reference: python/paddle/nn/layer/rnn.py).

Cells are eager Layers (each step is a couple of dispatched matmul ops) for
user-composed recurrences; the stock SimpleRNN/LSTM/GRU layers instead call the
fused ``rnn_layer_scan`` primitive (functional/rnn.py) — one lax.scan per
(layer, direction), the TPU equivalent of the reference's cuDNN fused rnn_op.
"""
from __future__ import annotations

import math

import numpy as np

from ...core.tensor import Tensor
from .. import functional as F
from ..functional import rnn_mod as F_rnn
from .. import initializer as I
from .layers import Layer
from .container import LayerList


def split_states(states, bidirectional=False, state_components=1):
    """[L*D, B, H]-packed states -> nested per-layer (per-direction) states
    (reference rnn.py:44)."""
    if state_components == 1:
        states = [states[i] for i in range(states.shape[0])]
        if not bidirectional:
            return states
        return [(states[i], states[i + 1]) for i in range(0, len(states), 2)]
    comps = [[s[i] for i in range(s.shape[0])] for s in states]
    packed = list(zip(*comps))  # [(h_i, c_i), ...]
    if not bidirectional:
        return packed
    return [(packed[i], packed[i + 1]) for i in range(0, len(packed), 2)]


def concat_states(states, bidirectional=False, state_components=1):
    """Inverse of split_states (reference rnn.py:97)."""
    from ...ops import manipulation as M

    if state_components == 1:
        flat = []
        for s in states:
            flat.extend(s if isinstance(s, (list, tuple)) else [s])
        return M.stack(flat, axis=0)
    flat = []
    for s in states:
        if bidirectional:
            flat.extend(list(s[0]) + list(s[1]))
        else:
            flat.extend(list(s))
    comps = [flat[i::state_components] for i in range(state_components)]
    return tuple(M.stack(c, axis=0) for c in comps)


class RNNCellBase(Layer):
    """Base cell (reference rnn.py:139)."""

    def get_initial_states(self, batch_ref, shape=None, dtype=None,
                           init_value=0.0, batch_dim_idx=0):
        from ...ops import creation

        if isinstance(batch_ref, (list, tuple)):
            batch_ref = batch_ref[0]
        batch = batch_ref.shape[batch_dim_idx]
        shape = shape if shape is not None else self.state_shape
        dtype = dtype or "float32"

        def make(s):
            if isinstance(s, (list, tuple)) and s and isinstance(s[0], (list, tuple)):
                return type(s)(make(x) for x in s)
            dims = [batch] + [int(d) for d in (s if isinstance(s, (list, tuple)) else [s])]
            return creation.full(dims, init_value, dtype=dtype)

        if isinstance(shape, (list, tuple)) and shape and isinstance(shape[0], (list, tuple)):
            return type(shape)(make(s) for s in shape)
        return make(shape)

    def _std_init(self, attr, shape, hidden_size):
        std = 1.0 / math.sqrt(hidden_size)
        return self.create_parameter(
            shape, attr=attr, default_initializer=I.Uniform(-std, std))


class SimpleRNNCell(RNNCellBase):
    r"""h' = act(W_ih x + b_ih + W_hh h + b_hh) (reference rnn.py:263)."""

    def __init__(self, input_size, hidden_size, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        if activation not in ("tanh", "relu"):
            raise ValueError("activation must be tanh or relu")
        self.activation = activation
        self.weight_ih = self._std_init(weight_ih_attr, [hidden_size, input_size], hidden_size)
        self.weight_hh = self._std_init(weight_hh_attr, [hidden_size, hidden_size], hidden_size)
        self.bias_ih = None if bias_ih_attr is False else \
            self._std_init(bias_ih_attr, [hidden_size], hidden_size)
        self.bias_hh = None if bias_hh_attr is False else \
            self._std_init(bias_hh_attr, [hidden_size], hidden_size)
        if bias_ih_attr is False:
            self._parameters["bias_ih"] = None
        if bias_hh_attr is False:
            self._parameters["bias_hh"] = None

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs, self.state_shape)
        from ...ops import linalg as M

        i2h = M.matmul(inputs, self.weight_ih, transpose_y=True)
        if self.bias_ih is not None:
            i2h = i2h + self.bias_ih
        h2h = M.matmul(states, self.weight_hh, transpose_y=True)
        if self.bias_hh is not None:
            h2h = h2h + self.bias_hh
        act = F.tanh if self.activation == "tanh" else F.relu
        h = act(i2h + h2h)
        return h, h

    @property
    def state_shape(self):
        return (self.hidden_size,)

    def extra_repr(self):
        return f"{self.input_size}, {self.hidden_size}"


class LSTMCell(RNNCellBase):
    r"""i,f,g,o-gated cell (reference rnn.py:399)."""

    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.weight_ih = self._std_init(weight_ih_attr, [4 * hidden_size, input_size], hidden_size)
        self.weight_hh = self._std_init(weight_hh_attr, [4 * hidden_size, hidden_size], hidden_size)
        self.bias_ih = None if bias_ih_attr is False else \
            self._std_init(bias_ih_attr, [4 * hidden_size], hidden_size)
        self.bias_hh = None if bias_hh_attr is False else \
            self._std_init(bias_hh_attr, [4 * hidden_size], hidden_size)
        if bias_ih_attr is False:
            self._parameters["bias_ih"] = None
        if bias_hh_attr is False:
            self._parameters["bias_hh"] = None

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs, self.state_shape)
        pre_h, pre_c = states
        from ...ops import linalg as M, manipulation as Man

        gates = M.matmul(inputs, self.weight_ih, transpose_y=True)
        if self.bias_ih is not None:
            gates = gates + self.bias_ih
        gates = gates + M.matmul(pre_h, self.weight_hh, transpose_y=True)
        if self.bias_hh is not None:
            gates = gates + self.bias_hh
        i, f, g, o = Man.split(gates, 4, axis=-1)
        i, f, o = F.sigmoid(i), F.sigmoid(f), F.sigmoid(o)
        c = f * pre_c + i * F.tanh(g)
        h = o * F.tanh(c)
        return h, (h, c)

    @property
    def state_shape(self):
        return ((self.hidden_size,), (self.hidden_size,))

    def extra_repr(self):
        return f"{self.input_size}, {self.hidden_size}"


class GRUCell(RNNCellBase):
    r"""r,z,c-gated cell, reset gate applied after the matmul (reference rnn.py:556)."""

    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.weight_ih = self._std_init(weight_ih_attr, [3 * hidden_size, input_size], hidden_size)
        self.weight_hh = self._std_init(weight_hh_attr, [3 * hidden_size, hidden_size], hidden_size)
        self.bias_ih = None if bias_ih_attr is False else \
            self._std_init(bias_ih_attr, [3 * hidden_size], hidden_size)
        self.bias_hh = None if bias_hh_attr is False else \
            self._std_init(bias_hh_attr, [3 * hidden_size], hidden_size)
        if bias_ih_attr is False:
            self._parameters["bias_ih"] = None
        if bias_hh_attr is False:
            self._parameters["bias_hh"] = None

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs, self.state_shape)
        pre_h = states
        from ...ops import linalg as M, manipulation as Man

        x_gates = M.matmul(inputs, self.weight_ih, transpose_y=True)
        if self.bias_ih is not None:
            x_gates = x_gates + self.bias_ih
        h_gates = M.matmul(pre_h, self.weight_hh, transpose_y=True)
        if self.bias_hh is not None:
            h_gates = h_gates + self.bias_hh
        x_r, x_z, x_c = Man.split(x_gates, 3, axis=-1)
        h_r, h_z, h_c = Man.split(h_gates, 3, axis=-1)
        r = F.sigmoid(x_r + h_r)
        z = F.sigmoid(x_z + h_z)
        c = F.tanh(x_c + r * h_c)
        h = (pre_h - c) * z + c
        return h, h

    @property
    def state_shape(self):
        return (self.hidden_size,)

    def extra_repr(self):
        return f"{self.input_size}, {self.hidden_size}"


class RNN(Layer):
    """Run a cell over a sequence (reference rnn.py:707)."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        if not hasattr(self.cell, "call"):
            self.cell.call = self.cell.forward
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None, **kwargs):
        return F_rnn.rnn(self.cell, inputs, initial_states=initial_states,
                         sequence_length=sequence_length,
                         time_major=self.time_major, is_reverse=self.is_reverse,
                         **kwargs)


class BiRNN(Layer):
    """Forward + backward cells over a sequence (reference rnn.py:782)."""

    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.cell_fw = cell_fw
        self.cell_bw = cell_bw
        if cell_fw.input_size != cell_bw.input_size:
            raise ValueError("input size of forward and backward cells must match")
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None, **kwargs):
        if isinstance(initial_states, (list, tuple)):
            assert len(initial_states) == 2, \
                "length of initial_states should be 2 when it is a list/tuple"
        return F_rnn.birnn(self.cell_fw, self.cell_bw, inputs, initial_states,
                           sequence_length, self.time_major, **kwargs)


class RNNBase(LayerList):
    """Stacked (bi)directional recurrence over the fused scan primitive
    (reference rnn.py:861; the could_use_cudnn fused path is the default here)."""

    def __init__(self, mode, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None):
        super().__init__()
        bidirectional_list = ("bidirectional", "bidirect")
        self.mode = mode
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.dropout = dropout
        self.num_directions = 2 if direction in bidirectional_list else 1
        self.time_major = time_major
        self.num_layers = num_layers
        self.state_components = 2 if mode == "LSTM" else 1
        self._has_bias = (bias_ih_attr is not False, bias_hh_attr is not False)
        kwargs = {
            "weight_ih_attr": weight_ih_attr,
            "weight_hh_attr": weight_hh_attr,
            "bias_ih_attr": bias_ih_attr,
            "bias_hh_attr": bias_hh_attr,
        }
        if mode == "LSTM":
            rnn_cls = LSTMCell
        elif mode == "GRU":
            rnn_cls = GRUCell
        else:
            rnn_cls = SimpleRNNCell
            kwargs["activation"] = self.activation

        if direction not in ("forward",) + bidirectional_list:
            raise ValueError(
                f"direction should be forward or bidirect (or bidirectional), "
                f"received direction = {direction}")
        if direction == "forward":
            self.append(RNN(rnn_cls(input_size, hidden_size, **kwargs),
                            False, time_major))
            for _ in range(1, num_layers):
                self.append(RNN(rnn_cls(hidden_size, hidden_size, **kwargs),
                                False, time_major))
        else:
            self.append(BiRNN(rnn_cls(input_size, hidden_size, **kwargs),
                              rnn_cls(input_size, hidden_size, **kwargs), time_major))
            for _ in range(1, num_layers):
                self.append(BiRNN(rnn_cls(2 * hidden_size, hidden_size, **kwargs),
                                  rnn_cls(2 * hidden_size, hidden_size, **kwargs),
                                  time_major))

        # flat-name aliases (weight_ih_l0, ... as in the reference's cudnn view)
        for layer in range(num_layers):
            for d in range(self.num_directions):
                cell = self._cell(layer, d)
                suffix = "_reverse" if d == 1 else ""
                object.__setattr__(self, f"weight_ih_l{layer}{suffix}", cell.weight_ih)
                object.__setattr__(self, f"weight_hh_l{layer}{suffix}", cell.weight_hh)
                if cell.bias_ih is not None:
                    object.__setattr__(self, f"bias_ih_l{layer}{suffix}", cell.bias_ih)
                if cell.bias_hh is not None:
                    object.__setattr__(self, f"bias_hh_l{layer}{suffix}", cell.bias_hh)

    def _cell(self, layer, direction):
        wrapper = self[layer]
        if self.num_directions == 1:
            return wrapper.cell
        return wrapper.cell_fw if direction == 0 else wrapper.cell_bw

    def _scan_mode(self):
        if self.mode in ("LSTM", "GRU"):
            return self.mode
        return "RNN_TANH" if self.activation == "tanh" else "RNN_RELU"

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ...ops import creation, manipulation as M

        batch_axis = 1 if self.time_major else 0
        batch = inputs.shape[batch_axis]
        dtype = str(inputs.dtype)
        LD = self.num_layers * self.num_directions
        if initial_states is None:
            zero = lambda: creation.zeros([LD, batch, self.hidden_size], dtype=dtype)
            initial_states = (zero(), zero()) if self.state_components == 2 else zero()
        states = initial_states if isinstance(initial_states, (list, tuple)) \
            else (initial_states,)

        if sequence_length is None:
            T = inputs.shape[0 if self.time_major else 1]
            seq_len = creation.full([batch], T, dtype="int32")
        else:
            seq_len = sequence_length

        mode = self._scan_mode()
        x = inputs
        finals_h, finals_c = [], []
        for layer in range(self.num_layers):
            outs = []
            for d in range(self.num_directions):
                idx = layer * self.num_directions + d
                cell = self._cell(layer, d)
                h0 = states[0][idx]
                c0 = states[1][idx] if self.state_components == 2 else \
                    creation.zeros([batch, self.hidden_size], dtype=dtype)
                b_ih = cell.bias_ih if cell.bias_ih is not None else \
                    creation.zeros([cell.weight_ih.shape[0]], dtype=dtype)
                b_hh = cell.bias_hh if cell.bias_hh is not None else \
                    creation.zeros([cell.weight_hh.shape[0]], dtype=dtype)
                ys, h_t, c_t = F_rnn.rnn_layer_scan(
                    x, h0, c0, cell.weight_ih, cell.weight_hh, b_ih, b_hh,
                    seq_len, mode=mode, reverse=bool(d == 1),
                    time_major=self.time_major)
                outs.append(ys)
                finals_h.append(h_t)
                finals_c.append(c_t)
            x = outs[0] if len(outs) == 1 else M.concat(outs, axis=-1)
            if self.dropout > 0.0 and layer < self.num_layers - 1:
                x = F.dropout(x, p=self.dropout, training=self.training)
        h_n = M.stack(finals_h, axis=0)
        if self.state_components == 2:
            final = (h_n, M.stack(finals_c, axis=0))
        else:
            final = h_n
        return x, final

    def extra_repr(self):
        s = f"{self.input_size}, {self.hidden_size}"
        if self.num_layers != 1:
            s += f", num_layers={self.num_layers}"
        if self.num_directions == 2:
            s += ", direction=bidirect"
        return s


class SimpleRNN(RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1, direction="forward",
                 time_major=False, dropout=0.0, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        if activation not in ("tanh", "relu"):
            raise ValueError("activation must be tanh or relu")
        self.activation = activation
        super().__init__("RNN", input_size, hidden_size, num_layers, direction,
                         time_major, dropout, weight_ih_attr, weight_hh_attr,
                         bias_ih_attr, bias_hh_attr)


class LSTM(RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1, direction="forward",
                 time_major=False, dropout=0.0, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__("LSTM", input_size, hidden_size, num_layers, direction,
                         time_major, dropout, weight_ih_attr, weight_hh_attr,
                         bias_ih_attr, bias_hh_attr)


class GRU(RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1, direction="forward",
                 time_major=False, dropout=0.0, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__("GRU", input_size, hidden_size, num_layers, direction,
                         time_major, dropout, weight_ih_attr, weight_hh_attr,
                         bias_ih_attr, bias_hh_attr)
