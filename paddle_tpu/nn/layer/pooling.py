"""Pooling layers (reference: python/paddle/nn/layer/pooling.py)."""
from __future__ import annotations

from .. import functional as F
from .layers import Layer


class MaxPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 return_mask=False, data_format="NCHW", name=None):
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.data_format = data_format

    def forward(self, x):
        return F.max_pool2d(x, self.kernel_size, self.stride, self.padding,
                            data_format=self.data_format)


class AvgPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 exclusive=True, divisor_override=None, data_format="NCHW", name=None):
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.exclusive = exclusive
        self.data_format = data_format

    def forward(self, x):
        return F.avg_pool2d(x, self.kernel_size, self.stride, self.padding,
                            exclusive=self.exclusive, data_format=self.data_format)


class AdaptiveAvgPool2D(Layer):
    def __init__(self, output_size, data_format="NCHW", name=None):
        super().__init__()
        self.output_size = output_size

    def forward(self, x):
        return F.adaptive_avg_pool2d(x, self.output_size)


class AdaptiveMaxPool2D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self.output_size = output_size

    def forward(self, x):
        return F.adaptive_max_pool2d(x, self.output_size)
