"""Common layers: Linear, Embedding, Dropout, Flatten, padding, upsample
(reference: python/paddle/nn/layer/common.py)."""
from __future__ import annotations

from ...framework import dtype as dtype_mod
from .. import functional as F
from .. import initializer as I
from .layers import Layer, ParamAttr


class Linear(Layer):
    def __init__(self, in_features, out_features, weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        self._in_features = in_features
        self._out_features = out_features
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=I.XavierUniform())
        if bias_attr is False:
            self.bias = None
            self._parameters["bias"] = None
        else:
            self.bias = self.create_parameter([out_features], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.linear(x, self.weight, self.bias)

    def extra_repr(self):
        return f"in_features={self._in_features}, out_features={self._out_features}"


class Embedding(Layer):
    """``sparse=True`` routes large tables to the host-sharded
    ``sparse.ShardedEmbeddingTable`` (dedup lookup, device hot-row cache,
    streamed misses, sparse (unique_ids, rows) gradients applied by the
    table's own row rule — no dense gradient, no dense Parameter in the
    optimizer). Tables below ``FLAGS_sparse_embedding_min_rows`` keep the
    dense device parameter — the documented fallback: a table that fits
    HBM gains nothing from host residency and dense grads keep it usable
    inside compiled train steps. ``sparse_table=`` attaches a pre-built
    table (cache size, shard count, row rule all caller-controlled)."""

    def __init__(self, num_embeddings, embedding_dim, padding_idx=None, sparse=False,
                 weight_attr=None, name=None, sparse_table=None):
        super().__init__()
        self._num_embeddings = num_embeddings
        self._embedding_dim = embedding_dim
        self._padding_idx = padding_idx
        self._sparse = bool(sparse)
        self._table = None
        if sparse_table is not None:
            self._table = sparse_table
        elif sparse:
            from ...framework import flags as _flags

            if num_embeddings >= _flags.flag("sparse_embedding_min_rows"):
                from ...sparse.embedding import ShardedEmbeddingTable

                self._table = ShardedEmbeddingTable(
                    num_embeddings, embedding_dim,
                    cache_rows=max(1024, num_embeddings // 16),
                    name=name)
        if self._table is not None:
            if (self._table.num_rows != num_embeddings
                    or self._table.dim != embedding_dim):
                raise ValueError(
                    f"sparse_table shape ({self._table.num_rows}, "
                    f"{self._table.dim}) != Embedding ({num_embeddings}, "
                    f"{embedding_dim})")
            self.weight = None  # canonical rows are the table's host shards
            self._parameters["weight"] = None
        else:
            self.weight = self.create_parameter(
                [num_embeddings, embedding_dim], attr=weight_attr,
                default_initializer=I.Normal(0.0, 1.0))

    def forward(self, x):
        if self._table is not None:
            return self._table.lookup(x, padding_idx=self._padding_idx)
        return F.embedding(x, self.weight, padding_idx=self._padding_idx,
                           sparse=self._sparse)

    def extra_repr(self):
        tail = ", sparse_table" if self._table is not None else ""
        return f"{self._num_embeddings}, {self._embedding_dim}{tail}"


class Dropout(Layer):
    def __init__(self, p=0.5, axis=None, mode="upscale_in_train", name=None):
        super().__init__()
        self.p = p
        self.axis = axis
        self.mode = mode

    def forward(self, x):
        return F.dropout(x, self.p, axis=self.axis, training=self.training, mode=self.mode)

    def extra_repr(self):
        return f"p={self.p}"


class Dropout2D(Layer):
    def __init__(self, p=0.5, data_format="NCHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x):
        return F.dropout2d(x, self.p, training=self.training, data_format=self.data_format)


class Flatten(Layer):
    def __init__(self, start_axis=1, stop_axis=-1):
        super().__init__()
        self.start_axis = start_axis
        self.stop_axis = stop_axis

    def forward(self, x):
        from ...ops import manipulation

        return manipulation.flatten(x, self.start_axis, self.stop_axis)


class Identity(Layer):
    def __init__(self, *args, **kwargs):
        super().__init__()

    def forward(self, x):
        return x


class Pad2D(Layer):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCHW", name=None):
        super().__init__()
        self.padding = padding
        self.mode = mode
        self.value = value
        self.data_format = data_format

    def forward(self, x):
        return F.pad(x, self.padding, self.mode, self.value, self.data_format)


class Upsample(Layer):
    def __init__(self, size=None, scale_factor=None, mode="nearest", align_corners=False,
                 align_mode=0, data_format="NCHW", name=None):
        super().__init__()
        self.size = size
        self.scale_factor = scale_factor
        self.mode = mode
        self.align_corners = align_corners
        self.align_mode = align_mode
        self.data_format = data_format

    def forward(self, x):
        return F.interpolate(x, self.size, self.scale_factor, self.mode,
                             self.align_corners, self.align_mode, self.data_format)


class PixelShuffle(Layer):
    def __init__(self, upscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.factor = upscale_factor

    def forward(self, x):
        return F.pixel_shuffle(x, self.factor)


class CosineSimilarity(Layer):
    def __init__(self, axis=1, eps=1e-8):
        super().__init__()
        self.axis = axis
        self.eps = eps

    def forward(self, x1, x2):
        return F.cosine_similarity(x1, x2, axis=self.axis, eps=self.eps)


class Bilinear(Layer):
    def __init__(self, in1_features, in2_features, out_features, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        self.weight = self.create_parameter([out_features, in1_features, in2_features],
                                            attr=weight_attr)
        self.bias = self.create_parameter([1, out_features], attr=bias_attr, is_bias=True)

    def forward(self, x1, x2):
        from ...ops import linalg

        out = linalg.einsum("bi,oij,bj->bo", x1, self.weight, x2)
        return out + self.bias
