"""nn.Layer: the module base class.

Reference: python/paddle/fluid/dygraph/layers.py:82 (Layer with hooks,
sublayers, state_dict). Parameters are Tensors with stop_gradient=False; all
structure bookkeeping is host-side Python — device math stays in the ops layer.
"""
from __future__ import annotations

import collections
from typing import Callable, Dict, Iterator, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from ...core.tensor import Tensor
from ...framework import dtype as dtype_mod


class Parameter(Tensor):
    """Trainable tensor (ParamBase analogue, fluid/framework.py:6274).

    ``dist_spec`` holds a jax PartitionSpec: the GSPMD placement of this
    parameter on the active mesh (the DistAttribute/dims_mapping analogue,
    reference auto_parallel/dist_attribute.py). None = replicated.
    """

    __slots__ = ("optimize_attr", "regularizer", "do_model_average", "need_clip",
                 "is_distributed", "dist_spec", "_stacked_into",
                 "_stream_meta")

    def __init__(self, data, name=None, trainable=True):
        super().__init__(data, stop_gradient=not trainable, name=name)
        self.trainable = trainable
        self.optimize_attr = {"learning_rate": 1.0}
        self.regularizer = None
        self.do_model_average = None
        self.need_clip = True
        self.is_distributed = False
        self.dist_spec = None


def check_not_stacked(params):
    """Reject parameters whose buffers were stacked into a compiled pipeline
    run after capture (wrong fleet order: optimizer before
    distributed_model) — training them would silently update dead arrays."""
    for p in params:
        if getattr(p, "_stacked_into", None) is not None:
            raise RuntimeError(
                "optimizer holds a parameter that was later stacked into a "
                "compiled pipeline run (StackedStageRun); its buffer is "
                "dead. Create the optimizer AFTER fleet.distributed_model / "
                "PipelineLayer engagement, from model.parameters() at that "
                "point.")


class Layer:
    def __init__(self, name_scope=None, dtype="float32"):
        self.training = True
        self._dtype = dtype
        self._parameters: Dict[str, Parameter] = collections.OrderedDict()
        self._sub_layers: Dict[str, "Layer"] = collections.OrderedDict()
        self._buffers: Dict[str, Tensor] = collections.OrderedDict()
        self._non_persistable_buffer_names = set()
        self._forward_pre_hooks = collections.OrderedDict()
        self._forward_post_hooks = collections.OrderedDict()
        self._casted_dtype = None

    # -- parameter/bookkeeping ----------------------------------------------
    def create_parameter(self, shape, attr=None, dtype=None, is_bias=False,
                         default_initializer=None):
        from .. import initializer as I

        dtype = dtype_mod.convert_dtype(dtype or self._dtype)
        if default_initializer is None:
            init = I.Constant(0.0) if is_bias else I.XavierUniform()
        else:
            init = default_initializer
        if attr is not None and getattr(attr, "initializer", None) is not None:
            init = attr.initializer
        data = init(shape, dtype)
        p = Parameter(data)
        if attr is not None and getattr(attr, "learning_rate", None) is not None:
            p.optimize_attr["learning_rate"] = attr.learning_rate
        if attr is not None and getattr(attr, "trainable", True) is False:
            p.trainable = False
            p.stop_gradient = True
        return p

    def add_parameter(self, name: str, parameter: Optional[Parameter]):
        self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name: str, sublayer: "Layer"):
        self._sub_layers[name] = sublayer
        return sublayer

    def register_buffer(self, name: str, tensor: Optional[Tensor], persistable=True):
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        return tensor

    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        subs = self.__dict__.get("_sub_layers")
        bufs = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call Layer.__init__ before assigning parameters")
            if subs is not None:
                subs.pop(name, None)
            params[name] = value
        elif isinstance(value, Layer):
            if subs is None:
                raise RuntimeError("call Layer.__init__ before assigning sublayers")
            if params is not None:
                params.pop(name, None)
            subs[name] = value
        else:
            # plain assignment evicts any same-named parameter/sublayer/buffer so
            # stale entries don't linger in state_dict/named_parameters
            for store in (params, subs, bufs):
                if store is not None and name in store:
                    store.pop(name)
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(f"{type(self).__name__!r} object has no attribute {name!r}")

    def __delattr__(self, name):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    # -- iteration ----------------------------------------------------------
    def named_parameters(self, prefix="", include_sublayers=True) -> Iterator[Tuple[str, Parameter]]:
        seen = set()
        for name, sub, p in self._walk(prefix):
            if p is not None and id(p) not in seen:
                seen.add(id(p))
                yield name, p

    def _walk(self, prefix=""):
        for name, p in self._parameters.items():
            if p is not None:
                yield (f"{prefix}.{name}" if prefix else name), self, p
        for sname, sub in self._sub_layers.items():
            if sub is None:
                continue
            sp = f"{prefix}.{sname}" if prefix else sname
            yield from sub._walk(sp)

    def parameters(self, include_sublayers=True) -> List[Parameter]:
        return [p for _, p in self.named_parameters()]

    def named_sublayers(self, prefix="", include_self=False):
        if include_self:
            yield prefix, self
        for name, sub in self._sub_layers.items():
            if sub is None:
                continue
            sp = f"{prefix}.{name}" if prefix else name
            yield sp, sub
            yield from sub.named_sublayers(sp)

    def sublayers(self, include_self=False) -> List["Layer"]:
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def children(self):
        return (l for l in self._sub_layers.values() if l is not None)

    def named_children(self):
        return ((n, l) for n, l in self._sub_layers.items() if l is not None)

    def named_buffers(self, prefix="", include_sublayers=True, persistable_only=False):
        for name, b in self._buffers.items():
            if b is None:
                continue
            if persistable_only and name in self._non_persistable_buffer_names:
                continue
            yield (f"{prefix}.{name}" if prefix else name), b
        if include_sublayers:
            for sname, sub in self._sub_layers.items():
                if sub is None:
                    continue
                sp = f"{prefix}.{sname}" if prefix else sname
                yield from sub.named_buffers(sp, persistable_only=persistable_only)

    def buffers(self, include_sublayers=True):
        return [b for _, b in self.named_buffers()]

    # -- mode / apply --------------------------------------------------------
    def train(self):
        self.training = True
        for l in self.sublayers():
            l.training = True
        return self

    def eval(self):
        self.training = False
        for l in self.sublayers():
            l.training = False
        return self

    def apply(self, fn: Callable):
        for l in self.sublayers(include_self=True):
            fn(l)
        return self

    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            d = dtype_mod.convert_dtype(dtype)
            for _, p in self.named_parameters():
                p.data = p.data.astype(d)
            for _, b in self.named_buffers():
                if dtype_mod.is_floating(b.dtype):
                    b.data = b.data.astype(d)
        return self

    def astype(self, dtype):
        return self.to(dtype=dtype)

    def float(self):
        return self.to(dtype="float32")

    def bfloat16(self):
        return self.to(dtype="bfloat16")

    # -- state dict ----------------------------------------------------------
    def state_dict(self, destination=None, include_sublayers=True, structured_name_prefix=""):
        dest = destination if destination is not None else collections.OrderedDict()
        for name, p in self.named_parameters(prefix=structured_name_prefix):
            dest[name] = p
        for name, b in self.named_buffers(prefix=structured_name_prefix, persistable_only=True):
            dest[name] = b
        return dest

    def set_state_dict(self, state_dict, use_structured_name=True):
        own = self.state_dict()
        missing, unexpected = [], []
        for k, v in state_dict.items():
            if k not in own:
                unexpected.append(k)
                continue
            arr = v.data if isinstance(v, Tensor) else jnp.asarray(np.asarray(v))
            tgt = own[k]
            if tuple(arr.shape) != tuple(tgt.data.shape):
                raise ValueError(f"shape mismatch for {k}: {arr.shape} vs {tgt.data.shape}")
            tgt.data = arr.astype(tgt.data.dtype)
        for k in own:
            if k not in state_dict:
                missing.append(k)
        return missing, unexpected

    load_dict = set_state_dict

    # -- hooks / call --------------------------------------------------------
    def register_forward_pre_hook(self, hook):
        handle = HookRemoveHelper(self._forward_pre_hooks)
        self._forward_pre_hooks[handle._id] = hook
        return handle

    def register_forward_post_hook(self, hook):
        handle = HookRemoveHelper(self._forward_post_hooks)
        self._forward_post_hooks[handle._id] = hook
        return handle

    def __call__(self, *inputs, **kwargs):
        for hook in self._forward_pre_hooks.values():
            out = hook(self, inputs)
            if out is not None:
                inputs = out if isinstance(out, tuple) else (out,)
        outputs = self.forward(*inputs, **kwargs)
        for hook in self._forward_post_hooks.values():
            out = hook(self, inputs, outputs)
            if out is not None:
                outputs = out
        return outputs

    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def extra_repr(self):
        return ""

    def __repr__(self):
        lines = [type(self).__name__ + "(" + self.extra_repr()]
        for name, sub in self._sub_layers.items():
            sub_repr = repr(sub).replace("\n", "\n  ")
            lines.append(f"  ({name}): {sub_repr}")
        return "\n".join(lines) + ")"

    def full_name(self):
        return type(self).__name__.lower()

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_grad()


class HookRemoveHelper:
    _next_id = [0]

    def __init__(self, hooks):
        self._hooks = hooks
        self._id = HookRemoveHelper._next_id[0]
        HookRemoveHelper._next_id[0] += 1

    def remove(self):
        self._hooks.pop(self._id, None)


class ParamAttr:
    """Parameter attribute config (reference: python/paddle/fluid/param_attr.py)."""

    def __init__(self, name=None, initializer=None, learning_rate=1.0,
                 regularizer=None, trainable=True, need_clip=True):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.need_clip = need_clip
