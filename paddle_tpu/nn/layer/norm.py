"""Norm layers (reference: python/paddle/nn/layer/norm.py).

SyncBatchNorm note: under SPMD the batch axis is already global — a plain
BatchNorm inside pjit with batch-sharded inputs IS sync BN (XLA inserts the
cross-replica reductions); the class exists for API parity.
"""
from __future__ import annotations

from ...core.tensor import Tensor
from ...framework import dtype as dtype_mod
from .. import functional as F
from .. import initializer as I
from .layers import Layer


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self._normalized_shape = list(normalized_shape)
        self._epsilon = epsilon
        if weight_attr is False:
            self.weight = None
            self._parameters["weight"] = None
        else:
            self.weight = self.create_parameter(
                self._normalized_shape, attr=weight_attr,
                default_initializer=I.Constant(1.0))
        if bias_attr is False:
            self.bias = None
            self._parameters["bias"] = None
        else:
            self.bias = self.create_parameter(
                self._normalized_shape, attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.layer_norm(x, self._normalized_shape, self.weight, self.bias, self._epsilon)

    def extra_repr(self):
        return f"normalized_shape={self._normalized_shape}, epsilon={self._epsilon}"


class RMSNorm(Layer):
    """Root-mean-square norm (Llama-family; not in reference snapshot — see SURVEY §5)."""

    def __init__(self, hidden_size, epsilon=1e-6, weight_attr=None, name=None):
        super().__init__()
        self._epsilon = epsilon
        self.weight = self.create_parameter(
            [hidden_size], attr=weight_attr, default_initializer=I.Constant(1.0))

    def forward(self, x):
        return F.rms_norm(x, self.weight, self._epsilon)


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, data_format="NCHW", use_global_stats=None, name=None):
        super().__init__()
        self._num_features = num_features
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_format = data_format
        self._use_global_stats = use_global_stats
        if weight_attr is False:
            self.weight = self.create_parameter([num_features], default_initializer=I.Constant(1.0))
            self.weight.stop_gradient = True
        else:
            self.weight = self.create_parameter(
                [num_features], attr=weight_attr, default_initializer=I.Constant(1.0))
        if bias_attr is False:
            self.bias = self.create_parameter([num_features], is_bias=True)
            self.bias.stop_gradient = True
        else:
            self.bias = self.create_parameter([num_features], attr=bias_attr, is_bias=True)
        from ...ops import creation

        self.register_buffer("_mean", creation.zeros([num_features]))
        self.register_buffer("_variance", creation.ones([num_features]))

    def forward(self, x):
        return F.batch_norm(
            x, self._mean, self._variance, self.weight, self.bias,
            training=self.training, momentum=self._momentum, epsilon=self._epsilon,
            data_format=self._data_format, use_global_stats=self._use_global_stats)

    def extra_repr(self):
        return f"num_features={self._num_features}, momentum={self._momentum}"


class BatchNorm1D(_BatchNormBase):
    pass


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    pass


class BatchNorm(_BatchNormBase):
    """Legacy fluid-style BatchNorm (acts like BatchNorm2D)."""

    def __init__(self, num_channels, act=None, momentum=0.9, epsilon=1e-5,
                 param_attr=None, bias_attr=None, data_layout="NCHW",
                 use_global_stats=None, **kwargs):
        super().__init__(num_channels, momentum, epsilon, param_attr, bias_attr,
                         data_layout, use_global_stats)
        self._act = act

    def forward(self, x):
        out = super().forward(x)
        if self._act == "relu":
            out = F.relu(out)
        elif self._act is not None:
            out = getattr(F, self._act)(out)
        return out


class SyncBatchNorm(_BatchNormBase):
    """Cross-replica BN. Under GSPMD with a batch-sharded mesh this is exactly
    BatchNorm (XLA all-reduces the moments); kept for API parity with
    python/paddle/nn/layer/norm.py SyncBatchNorm."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        if isinstance(layer, _BatchNormBase) and not isinstance(layer, SyncBatchNorm):
            new = SyncBatchNorm(layer._num_features, layer._momentum, layer._epsilon,
                                data_format=layer._data_format)
            new.weight.data = layer.weight.data
            new.bias.data = layer.bias.data
            new._mean.data = layer._mean.data
            new._variance.data = layer._variance.data
            return new
        for name, sub in list(layer._sub_layers.items()):
            layer._sub_layers[name] = cls.convert_sync_batchnorm(sub)
        return layer


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self._num_groups = num_groups
        self._epsilon = epsilon
        self.weight = self.create_parameter(
            [num_channels], attr=weight_attr, default_initializer=I.Constant(1.0))
        self.bias = self.create_parameter([num_channels], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.group_norm(x, self._num_groups, self.weight, self.bias, self._epsilon)


class InstanceNorm2D(Layer):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9, weight_attr=None,
                 bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self._epsilon = epsilon
        self.scale = self.create_parameter(
            [num_features], attr=weight_attr, default_initializer=I.Constant(1.0))
        self.bias = self.create_parameter([num_features], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.instance_norm(x, weight=self.scale, bias=self.bias, eps=self._epsilon)


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0, data_format="NCHW", name=None):
        super().__init__()
        self.size, self.alpha, self.beta, self.k = size, alpha, beta, k

    def forward(self, x):
        from ...ops import math as _m, manipulation as _mp
        import jax.numpy as jnp
        from ...core.dispatch import primitive, get_primitive

        return _lrn(x, size=self.size, alpha=self.alpha, beta=self.beta, k=self.k)


from ...core.dispatch import primitive as _primitive
import jax
import jax.numpy as _jnp


@_primitive("lrn_op")
def _lrn(x, *, size, alpha, beta, k):
    sq = _jnp.square(x)
    half = size // 2
    pads = [(0, 0), (half, size - 1 - half), (0, 0), (0, 0)]
    acc = jax.lax.reduce_window(sq, 0.0, jax.lax.add, (1, size, 1, 1), (1, 1, 1, 1), pads)
    return x / _jnp.power(k + alpha * acc, beta)


class SpectralNorm(Layer):
    def __init__(self, weight_shape, dim=0, power_iters=1, eps=1e-12, name=None):
        super().__init__()
        raise NotImplementedError("SpectralNorm layer: use nn.utils.spectral_norm")
