"""Recurrent ops, TPU-first (reference: python/paddle/nn/layer/rnn.py cells +
fluid.layers.rnn / the cuDNN rnn_op fused path, paddle/fluid/operators/rnn_op.h).

Design: one ``rnn_layer_scan`` primitive runs a whole (layer, direction) pass as
a single ``lax.scan`` — the input projection for every timestep is hoisted into
one big MXU matmul, only the [B,H]x[H,G] recurrent matmul lives inside the scan
body. Backward is jax's scan-vjp (the fused cuDNN-backward role). Multi-layer /
bidirectional stacks are short host loops over jitted per-layer calls so that
inter-layer dropout stays on the eager RNG path.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ...core.dispatch import primitive


def _cell_step(mode, h, c, xg_t, w_hh, b_hh):
    """One recurrence step from precomputed input gates xg_t [B, G]."""
    if mode == "LSTM":
        gates = xg_t + jnp.matmul(h, w_hh.T) + b_hh
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
        c_new = f * c + i * jnp.tanh(g)
        h_new = o * jnp.tanh(c_new)
        return h_new, c_new
    hg = jnp.matmul(h, w_hh.T) + b_hh
    if mode == "GRU":
        x_r, x_z, x_c = jnp.split(xg_t, 3, axis=-1)
        h_r, h_z, h_c = jnp.split(hg, 3, axis=-1)
        r = jax.nn.sigmoid(x_r + h_r)
        z = jax.nn.sigmoid(x_z + h_z)
        cand = jnp.tanh(x_c + r * h_c)  # reset gate applied after the matmul
        h_new = (h - cand) * z + cand
        return h_new, c
    act = jnp.tanh if mode == "RNN_TANH" else jax.nn.relu
    h_new = act(xg_t + hg)
    return h_new, c


@primitive("rnn_layer_scan")
def rnn_layer_scan(x, h0, c0, w_ih, w_hh, b_ih, b_hh, seq_len,
                   mode="LSTM", reverse=False, time_major=False):
    """Full-sequence single-(layer,direction) recurrence.

    x: [B,T,I] (or [T,B,I] when time_major). seq_len: [B] int32; steps at or
    beyond a row's length carry state through and emit zero outputs (matching
    the reference rnn op's sequence_length masking, fluid/layers/rnn.py mask
    semantics). Returns (outputs, h_T, c_T).
    """
    if not time_major:
        x = jnp.swapaxes(x, 0, 1)  # [T,B,I]
    T = x.shape[0]
    xg = jnp.matmul(x, w_ih.T) + b_ih  # [T,B,G]: all timesteps, one MXU matmul
    step_ids = jnp.arange(T)
    if reverse:
        xg = xg[::-1]
        step_ids = step_ids[::-1]
    valid = (step_ids[:, None] < seq_len[None, :]).astype(x.dtype)  # [T,B]

    def step(carry, inp):
        h, c = carry
        xg_t, m = inp
        h_new, c_new = _cell_step(mode, h, c, xg_t, w_hh, b_hh)
        m = m[:, None]
        h2 = m * h_new + (1.0 - m) * h
        c2 = m * c_new + (1.0 - m) * c
        return (h2, c2), m * h_new

    (h_t, c_t), ys = lax.scan(step, (h0, c0), (xg, valid))
    if reverse:
        ys = ys[::-1]
    if not time_major:
        ys = jnp.swapaxes(ys, 0, 1)
    return ys, h_t, c_t


def _map_structure(fn, s):
    if isinstance(s, (list, tuple)):
        return type(s)(_map_structure(fn, x) for x in s)
    return fn(s)


def rnn(cell, inputs, initial_states=None, sequence_length=None,
        time_major=False, is_reverse=False, **kwargs):
    """Generic cell-driven recurrence (reference: fluid/layers/rnn.py rnn()).

    Runs an arbitrary RNNCellBase over the time dim with host-side unrolling —
    the path for user-defined cells; the stock SimpleRNN/LSTM/GRU layers use
    the fused rnn_layer_scan primitive instead.
    """
    from ...ops import manipulation as M

    batch_axis = 1 if time_major else 0
    time_axis = 0 if time_major else 1
    T = inputs.shape[time_axis]
    if initial_states is None:
        initial_states = cell.get_initial_states(inputs, batch_dim_idx=batch_axis)
    states = initial_states
    outputs = []
    steps = range(T - 1, -1, -1) if is_reverse else range(T)
    mask = None
    if sequence_length is not None:
        import numpy as np

        seq = sequence_length.numpy() if hasattr(sequence_length, "numpy") \
            else np.asarray(sequence_length)
        mask = seq
    for t in steps:
        x_t = M.squeeze(M.slice(inputs, [time_axis], [t], [t + 1]), [time_axis])
        out, new_states = cell(x_t, states, **kwargs)
        if mask is not None:
            from ...ops import creation

            m = M.unsqueeze(creation.to_tensor((t < mask).astype("float32")), [-1])
            out = out * m
            olds = states if isinstance(states, (list, tuple)) else [states]
            if isinstance(new_states, (list, tuple)):
                new_states = type(new_states)(
                    ns * m + os * (1.0 - m) for ns, os in zip(new_states, olds))
            else:
                new_states = new_states * m + states * (1.0 - m)
        outputs.append(out)
        states = new_states
    if is_reverse:
        outputs = outputs[::-1]
    stacked = M.stack(outputs, axis=time_axis)
    return stacked, states


def birnn(cell_fw, cell_bw, inputs, initial_states=None, sequence_length=None,
          time_major=False, **kwargs):
    """Bidirectional generic recurrence (reference: fluid/layers/rnn.py birnn())."""
    from ...ops import manipulation as M

    if initial_states is None:
        states_fw, states_bw = None, None
    else:
        states_fw, states_bw = initial_states
    out_fw, st_fw = rnn(cell_fw, inputs, states_fw, sequence_length,
                        time_major=time_major, is_reverse=False, **kwargs)
    out_bw, st_bw = rnn(cell_bw, inputs, states_bw, sequence_length,
                        time_major=time_major, is_reverse=True, **kwargs)
    outputs = M.concat([out_fw, out_bw], axis=-1)
    return outputs, (st_fw, st_bw)
