"""nn.functional: the functional op surface (reference: python/paddle/nn/functional/)."""
from .activation import *  # noqa: F401,F403
from .common import *  # noqa: F401,F403
from .loss import *  # noqa: F401,F403
from .attention import scaled_dot_product_attention, flash_attention  # noqa: F401
from . import rnn as rnn_mod  # noqa: F401
from .rnn import rnn, birnn  # noqa: F401
from .vision import (  # noqa: F401
    grid_sample, affine_grid, fold, pixel_unshuffle, channel_shuffle,
    pairwise_distance,
)

from ...ops.manipulation import one_hot  # noqa: F401
from ...ops.manipulation import diag_embed  # noqa: F401,E402
from .common import (  # noqa: F401,E402
    max_pool1d, avg_pool1d, max_pool3d, avg_pool3d, max_unpool1d,
    max_unpool2d, max_unpool3d, adaptive_avg_pool1d, adaptive_max_pool1d,
    adaptive_avg_pool3d, adaptive_max_pool3d, conv3d, conv1d_transpose,
    conv3d_transpose, dropout3d, alpha_dropout, local_response_norm,
    bilinear, sequence_mask, zeropad2d, sparse_attention, relu_, softmax_,
    tanh_,
)
from .loss import (  # noqa: F401,E402
    ctc_loss, dice_loss, log_loss, label_smooth, hsigmoid_loss,
    margin_cross_entropy, class_center_sample, npair_loss,
    sigmoid_focal_loss,
)
from .vision import temporal_shift  # noqa: F401,E402
from .activation import elu_, gather_tree  # noqa: F401,E402
