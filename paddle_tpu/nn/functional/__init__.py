"""nn.functional: the functional op surface (reference: python/paddle/nn/functional/)."""
from .activation import *  # noqa: F401,F403
from .common import *  # noqa: F401,F403
from .loss import *  # noqa: F401,F403
from .attention import scaled_dot_product_attention, flash_attention  # noqa: F401
from . import rnn as rnn_mod  # noqa: F401
from .rnn import rnn, birnn  # noqa: F401
from .vision import (  # noqa: F401
    grid_sample, affine_grid, fold, pixel_unshuffle, channel_shuffle,
    pairwise_distance,
)

from ...ops.manipulation import one_hot  # noqa: F401
