"""Activation functionals (reference: python/paddle/nn/functional/activation.py).

Each is one fused jax primitive -> XLA fuses into surrounding matmuls (the role
the reference's hand-fused CUDA activation kernels play).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.dispatch import primitive, get_primitive
from ...core.tensor import Tensor

_THIS = globals()

_SIMPLE = {
    "relu": jax.nn.relu,
    "relu6": jax.nn.relu6,
    "sigmoid": jax.nn.sigmoid,
    "tanh_act": jnp.tanh,
    "silu": jax.nn.silu,
    "swish": jax.nn.silu,
    "softplus_d": jax.nn.softplus,
    "softsign": jax.nn.soft_sign,
    "mish": lambda x: x * jnp.tanh(jax.nn.softplus(x)),
    "hardswish": jax.nn.hard_swish,
    "hardsigmoid": lambda x: jnp.clip(x / 6.0 + 0.5, 0.0, 1.0),
    "tanhshrink": lambda x: x - jnp.tanh(x),
    "log_sigmoid": jax.nn.log_sigmoid,
}

for _name, _jfn in _SIMPLE.items():
    primitive("act_" + _name)(lambda x, _f=_jfn: _f(x))

    def _make(pname, public):
        def fn(x, name=None):
            return get_primitive(pname)(x)

        fn.__name__ = public
        return fn

    _public = {"tanh_act": "tanh", "softplus_d": "softplus"}.get(_name, _name)
    _THIS[_public] = _make("act_" + _name, _public)


@primitive("act_gelu")
def _gelu(x, *, approximate):
    return jax.nn.gelu(x, approximate=approximate)


def gelu(x, approximate=False, name=None):
    return _gelu(x, approximate=bool(approximate))


@primitive("act_leaky_relu")
def _leaky_relu(x, *, negative_slope):
    return jax.nn.leaky_relu(x, negative_slope)


def leaky_relu(x, negative_slope=0.01, name=None):
    return _leaky_relu(x, negative_slope=float(negative_slope))


@primitive("act_elu")
def _elu(x, *, alpha):
    return jax.nn.elu(x, alpha)


def elu(x, alpha=1.0, name=None):
    return _elu(x, alpha=float(alpha))


@primitive("act_celu")
def _celu(x, *, alpha):
    return jax.nn.celu(x, alpha)


def celu(x, alpha=1.0, name=None):
    return _celu(x, alpha=float(alpha))


@primitive("act_selu")
def _selu(x, *, scale, alpha):
    return scale * jnp.where(x > 0, x, alpha * jnp.expm1(x))


def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
    return _selu(x, scale=float(scale), alpha=float(alpha))


@primitive("act_hardtanh")
def _hardtanh(x, *, min, max):
    return jnp.clip(x, min, max)


def hardtanh(x, min=-1.0, max=1.0, name=None):
    return _hardtanh(x, min=float(min), max=float(max))


@primitive("act_hardshrink")
def _hardshrink(x, *, threshold):
    return jnp.where(jnp.abs(x) > threshold, x, 0.0)


def hardshrink(x, threshold=0.5, name=None):
    return _hardshrink(x, threshold=float(threshold))


@primitive("act_softshrink")
def _softshrink(x, *, threshold):
    return jnp.where(x > threshold, x - threshold, jnp.where(x < -threshold, x + threshold, 0.0))


def softshrink(x, threshold=0.5, name=None):
    return _softshrink(x, threshold=float(threshold))


@primitive("act_thresholded_relu")
def _thresholded_relu(x, *, threshold):
    return jnp.where(x > threshold, x, 0.0)


def thresholded_relu(x, threshold=1.0, name=None):
    return _thresholded_relu(x, threshold=float(threshold))


@primitive("act_softmax")
def _softmax(x, *, axis):
    return jax.nn.softmax(x, axis=axis)


def softmax(x, axis=-1, dtype=None, name=None):
    out = _softmax(x, axis=int(axis))
    if dtype is not None:
        from ...ops import manipulation

        out = manipulation.cast(out, dtype)
    return out


@primitive("act_log_softmax")
def _log_softmax(x, *, axis):
    return jax.nn.log_softmax(x, axis=axis)


def log_softmax(x, axis=-1, dtype=None, name=None):
    return _log_softmax(x, axis=int(axis))


@primitive("act_prelu")
def _prelu(x, weight):
    w = weight
    if w.ndim == 1 and w.shape[0] > 1 and x.ndim > 1:
        # per-channel (NCHW channel axis 1)
        shape = [1] * x.ndim
        shape[1] = w.shape[0]
        w = w.reshape(shape)
    return jnp.where(x > 0, x, w * x)


def prelu(x, weight, data_format="NCHW", name=None):
    return _prelu(x, weight)


@primitive("act_glu")
def _glu(x, *, axis):
    a, b = jnp.split(x, 2, axis=axis)
    return a * jax.nn.sigmoid(b)


def glu(x, axis=-1, name=None):
    return _glu(x, axis=int(axis))


@primitive("act_maxout")
def _maxout(x, *, groups, axis):
    shape = list(x.shape)
    c = shape[axis]
    shape[axis : axis + 1] = [c // groups, groups]
    return jnp.max(x.reshape(shape), axis=axis + 1)


def maxout(x, groups, axis=1, name=None):
    return _maxout(x, groups=int(groups), axis=int(axis) % x.ndim)


@primitive("act_gumbel_softmax", nondiff=False)
def _gumbel_softmax(x, key, *, temperature, hard, axis):
    g = -jnp.log(-jnp.log(jax.random.uniform(key, x.shape, x.dtype, 1e-20, 1.0)))
    y = jax.nn.softmax((x + g) / temperature, axis=axis)
    if hard:
        onehot = jax.nn.one_hot(
            jnp.argmax(y, axis=axis), x.shape[axis], axis=axis, dtype=y.dtype)
        y = onehot + y - jax.lax.stop_gradient(y)  # straight-through estimator
    return y


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    from ...framework import random as random_mod

    return _gumbel_softmax(
        x, random_mod.next_key(), temperature=float(temperature), hard=bool(hard), axis=int(axis)
    )


def elu_(x, alpha=1.0, name=None):
    """Inplace variant (reference elu_): rebinds x to the result."""
    out = elu(x, alpha)
    x._rebind(out)
    return x


@primitive("gather_tree_op", nondiff=True)
def _gather_tree(ids, parents):
    # ids/parents: [T, B, beam]; walk ancestry from the last step backwards
    T = ids.shape[0]

    def step(beams, t):
        # beams: [B, beam] current beam index per output slot
        tok = jnp.take_along_axis(ids[t], beams, axis=-1)
        par = jnp.take_along_axis(parents[t], beams, axis=-1)
        return par, tok

    init = jnp.broadcast_to(jnp.arange(ids.shape[2]),
                            ids.shape[1:]).astype(ids.dtype)
    _, toks = jax.lax.scan(step, init, jnp.arange(T - 1, -1, -1))
    return jnp.flip(toks, axis=0)


def gather_tree(ids, parents):
    """Beam-search ancestry walk (reference gather_tree op): rebuild full
    token paths from per-step ids + parent beam indices."""
    return _gather_tree(ids, parents)
