"""Loss functionals (reference: python/paddle/nn/functional/loss.py).

cross_entropy is the TP-shardable hot path: computed from log_softmax in one
fused primitive so XLA keeps it on-device in one kernel cluster.
"""
from __future__ import annotations

import jax
import numpy as np
import jax.numpy as jnp

from ...core.dispatch import primitive
from ...core.tensor import Tensor


def _reduce(out, reduction):
    if reduction == "mean":
        return jnp.mean(out)
    if reduction == "sum":
        return jnp.sum(out)
    return out


def _ce_core(logits, labels, axis, soft_label, ignore_index, use_softmax):
    """Shared CE math. use_softmax=False: input is already softmax
    probabilities and loss_j = -log(P[label_j]) (reference loss.py:1427-1433
    docs; softmax_with_cross_entropy_op.h:82 skips the softmax step).
    Returns (per-sample loss, mask, safe labels) — mask/safe are None for
    soft labels."""
    if use_softmax:
        logp = jax.nn.log_softmax(logits, axis=axis)
    else:
        logp = jnp.log(jnp.maximum(logits, 1e-30))
    if soft_label:
        return -jnp.sum(labels * logp, axis=axis), None, None
    lab = labels
    if lab.ndim == logits.ndim:
        lab = jnp.squeeze(lab, axis)
    mask = lab != ignore_index
    safe_lab = jnp.where(mask, lab, 0).astype(jnp.int32)
    picked = jnp.take_along_axis(logp, jnp.expand_dims(safe_lab, axis), axis=axis)
    loss = jnp.where(mask, -jnp.squeeze(picked, axis), 0.0)
    return loss, mask, safe_lab


@primitive("softmax_with_cross_entropy_op")
def _softmax_ce(logits, labels, *, axis, soft_label, reduction, ignore_index,
                use_softmax=True):
    loss, mask, _ = _ce_core(logits, labels, axis, soft_label, ignore_index,
                             use_softmax)
    if mask is not None and reduction == "mean":
        return jnp.sum(loss) / jnp.maximum(jnp.sum(mask), 1)
    return _reduce(loss, reduction)


@primitive("softmax_ce_weighted_op")
def _softmax_ce_weighted(logits, labels, weight, *, axis, soft_label, reduction,
                         ignore_index, use_softmax):
    # per-class weights: hard labels gather weight[label] (zeroed at
    # ignore_index); mean divides by the summed gathered weights — matching
    # reference loss.py weighted-mean semantics.
    loss, mask, safe_lab = _ce_core(logits, labels, axis, soft_label,
                                    ignore_index, use_softmax)
    if soft_label:
        wg = jnp.tensordot(labels.astype(weight.dtype), weight,
                           axes=[[axis], [0]])
    else:
        wg = jnp.take(weight, safe_lab) * mask.astype(weight.dtype)
    loss = loss * wg
    if reduction == "mean":
        denom = jnp.sum(wg)
        return jnp.sum(loss) / (denom + (denom == 0.0))
    return _reduce(loss, reduction)


def cross_entropy(input, label, weight=None, ignore_index=-100, reduction="mean",
                  soft_label=False, axis=-1, use_softmax=True, name=None):
    if weight is not None:
        return _softmax_ce_weighted(
            input, label, weight, axis=int(axis), soft_label=bool(soft_label),
            reduction=reduction, ignore_index=int(ignore_index),
            use_softmax=bool(use_softmax),
        )
    return _softmax_ce(
        input, label, axis=int(axis), soft_label=bool(soft_label),
        reduction=reduction, ignore_index=int(ignore_index),
        use_softmax=bool(use_softmax),
    )


def softmax_with_cross_entropy(logits, label, soft_label=False, ignore_index=-100,
                               numeric_stable_mode=True, return_softmax=False, axis=-1):
    loss = _softmax_ce(logits, label, axis=int(axis), soft_label=bool(soft_label),
                       reduction="none", ignore_index=int(ignore_index))
    from .activation import softmax as _softmax

    if return_softmax:
        return loss, _softmax(logits, axis=axis)
    return loss


def _nll_core(logp, labels, ignore_index):
    """Shared gather: class axis is 1 for K-dim input (N, C, d1, ...) per the
    reference nll_loss contract; returns (per-elem loss, mask, safe labels)."""
    if logp.ndim > 2:
        logp = jnp.moveaxis(logp, 1, -1)
    mask = labels != ignore_index
    safe = jnp.where(mask, labels, 0).astype(jnp.int32)
    picked = jnp.take_along_axis(logp, safe[..., None], axis=-1)
    loss = jnp.where(mask, -jnp.squeeze(picked, -1), 0.0)
    return loss, mask, safe


@primitive("nll_loss_op")
def _nll_loss(logp, labels, *, reduction, ignore_index):
    loss, mask, _ = _nll_core(logp, labels, ignore_index)
    if reduction == "mean":
        return jnp.sum(loss) / jnp.maximum(jnp.sum(mask), 1)
    return _reduce(loss, reduction)


@primitive("nll_loss_weighted_op")
def _nll_loss_weighted(logp, labels, weight, *, reduction, ignore_index):
    loss, mask, safe = _nll_core(logp, labels, ignore_index)
    wg = jnp.take(weight, safe) * mask.astype(weight.dtype)
    loss = loss * wg
    if reduction == "mean":
        denom = jnp.sum(wg)
        return jnp.sum(loss) / (denom + (denom == 0.0))
    return _reduce(loss, reduction)


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean", name=None):
    if weight is not None:
        return _nll_loss_weighted(input, label, weight, reduction=reduction,
                                  ignore_index=int(ignore_index))
    return _nll_loss(input, label, reduction=reduction, ignore_index=int(ignore_index))


@primitive("mse_loss_op")
def _mse(x, y, *, reduction):
    return _reduce(jnp.square(x - y), reduction)


def mse_loss(input, label, reduction="mean", name=None):
    return _mse(input, label, reduction=reduction)


@primitive("l1_loss_op")
def _l1(x, y, *, reduction):
    return _reduce(jnp.abs(x - y), reduction)


def l1_loss(input, label, reduction="mean", name=None):
    return _l1(input, label, reduction=reduction)


@primitive("smooth_l1_op")
def _smooth_l1(x, y, *, reduction, delta):
    d = jnp.abs(x - y)
    loss = jnp.where(d < delta, 0.5 * d * d / delta, d - 0.5 * delta)
    return _reduce(loss, reduction)


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    return _smooth_l1(input, label, reduction=reduction, delta=float(delta))


@primitive("bce_op")
def _bce(p, y, *, reduction, eps):
    p = jnp.clip(p, eps, 1.0 - eps)
    loss = -(y * jnp.log(p) + (1.0 - y) * jnp.log(1.0 - p))
    return _reduce(loss, reduction)


def binary_cross_entropy(input, label, weight=None, reduction="mean", name=None):
    if weight is not None:
        from ...ops import math as _m, reduction as _r

        out = _m.multiply(_bce(input, label, reduction="none", eps=1e-12), weight)
        if reduction == "mean":
            return _r.mean(out)
        if reduction == "sum":
            return _r.sum(out)
        return out
    return _bce(input, label, reduction=reduction, eps=1e-12)


@primitive("bce_logits_op")
def _bce_logits(x, y, *, reduction):
    # numerically-stable sigmoid CE: max(x,0) - x*y + log(1+exp(-|x|))
    loss = jnp.maximum(x, 0) - x * y + jnp.log1p(jnp.exp(-jnp.abs(x)))
    return _reduce(loss, reduction)


@primitive("bce_logits_weighted_op")
def _bce_logits_w(x, y, weight, pos_weight, *, reduction, has_w, has_pw):
    if has_pw:
        import jax

        # pos_weight scales the positive term: L = -[pw*y*logσ(x) +
        # (1-y)*logσ(-x)], stable via log-sigmoids
        loss = -(pos_weight * y * jax.nn.log_sigmoid(x)
                 + (1 - y) * jax.nn.log_sigmoid(-x))
    else:
        loss = jnp.maximum(x, 0) - x * y + jnp.log1p(jnp.exp(-jnp.abs(x)))
    if has_w:
        loss = loss * weight
    return _reduce(loss, reduction)


def binary_cross_entropy_with_logits(logit, label, weight=None, reduction="mean",
                                     pos_weight=None, name=None):
    if weight is None and pos_weight is None:
        return _bce_logits(logit, label, reduction=reduction)
    from ...ops import creation

    one = creation.ones_like(label)
    return _bce_logits_w(
        logit, label, weight if weight is not None else one,
        pos_weight if pos_weight is not None else one,
        reduction=reduction, has_w=weight is not None,
        has_pw=pos_weight is not None)


@primitive("kl_div_op")
def _kl_div(x, y, *, reduction):
    loss = y * (jnp.log(jnp.maximum(y, 1e-12)) - x)
    if reduction == "batchmean":
        return jnp.sum(loss) / x.shape[0]
    return _reduce(loss, reduction)


def kl_div(input, label, reduction="mean", name=None):
    return _kl_div(input, label, reduction=reduction)


@primitive("margin_ranking_op")
def _margin_ranking(x1, x2, y, *, margin, reduction):
    return _reduce(jnp.maximum(0.0, -y * (x1 - x2) + margin), reduction)


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean", name=None):
    return _margin_ranking(input, other, label, margin=float(margin), reduction=reduction)


@primitive("hinge_embedding_op")
def _hinge_embedding(x, y, *, margin, reduction):
    loss = jnp.where(y == 1, x, jnp.maximum(0.0, margin - x))
    return _reduce(loss, reduction)


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean", name=None):
    return _hinge_embedding(input, label, margin=float(margin), reduction=reduction)


@primitive("cosine_embedding_op")
def _cosine_embedding(x1, x2, y, *, margin, reduction):
    cos = jnp.sum(x1 * x2, -1) / jnp.maximum(
        jnp.linalg.norm(x1, axis=-1) * jnp.linalg.norm(x2, axis=-1), 1e-12)
    loss = jnp.where(y == 1, 1 - cos, jnp.maximum(0.0, cos - margin))
    return _reduce(loss, reduction)


def cosine_embedding_loss(input1, input2, label, margin=0.0, reduction="mean", name=None):
    return _cosine_embedding(input1, input2, label, margin=float(margin), reduction=reduction)


@primitive("ctc_like_square_op")
def _square_error_cost(x, y):
    return jnp.square(x - y)


def square_error_cost(input, label):
    return _square_error_cost(input, label)


# -- round-3 loss completion --------------------------------------------------

@primitive("ctc_loss_op")
def _ctc_loss(log_probs, labels, input_lengths, label_lengths, *, blank):
    import optax

    # paddle layout [T, B, K] -> optax [B, T, K]; optax uses blank=0 by
    # default and paddle allows arbitrary blank: roll the class axis so the
    # blank lands at position `blank` for optax's blank_id parameter
    lp = jnp.transpose(log_probs, (1, 0, 2))
    B, T = lp.shape[0], lp.shape[1]
    logit_pad = (jnp.arange(T)[None, :] >= input_lengths[:, None]) \
        .astype(lp.dtype)
    L = labels.shape[1]
    label_pad = (jnp.arange(L)[None, :] >= label_lengths[:, None]) \
        .astype(lp.dtype)
    return optax.ctc_loss(lp, logit_pad, labels, label_pad, blank_id=blank)


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False, name=None):
    """CTC (reference warpctc op, nn/functional/loss.py ctc_loss): forward
    algorithm over the [T, B, K] log-prob lattice."""
    per = _ctc_loss(log_probs, labels, input_lengths, label_lengths,
                    blank=int(blank))
    if reduction == "mean":
        from ...ops import manipulation as _m

        ll = _m.cast(label_lengths, str(per.dtype))
        return (per / ll).mean()
    if reduction == "sum":
        return per.sum()
    return per


@primitive("dice_loss_op")
def _dice_loss(input, label, *, epsilon):
    # input [N, ..., C] probabilities; label [N, ..., 1] class ids
    lab = jax.nn.one_hot(label[..., 0], input.shape[-1], dtype=input.dtype)
    reduce_dims = tuple(range(1, input.ndim))
    inse = jnp.sum(input * lab, axis=reduce_dims)
    dice_denom = jnp.sum(input, axis=reduce_dims) + jnp.sum(lab, axis=reduce_dims)
    dice = 1.0 - 2.0 * inse / (dice_denom + epsilon)
    return jnp.mean(dice)


def dice_loss(input, label, epsilon=1e-5, name=None):
    return _dice_loss(input, label, epsilon=float(epsilon))


@primitive("log_loss_op")
def _log_loss(input, label, *, epsilon):
    return -label * jnp.log(input + epsilon) \
        - (1.0 - label) * jnp.log(1.0 - input + epsilon)


def log_loss(input, label, epsilon=1e-4, name=None):
    return _log_loss(input, label, epsilon=float(epsilon))


@primitive("label_smooth_op")
def _label_smooth(label, *, epsilon):
    return (1.0 - epsilon) * label + epsilon / label.shape[-1]


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    if prior_dist is not None:
        eps = float(epsilon)
        return (1.0 - eps) * label + eps * prior_dist
    return _label_smooth(label, epsilon=float(epsilon))


@primitive("hsigmoid_loss_op")
def _hsigmoid_loss(x, labels, w, b, *, num_classes):
    """Default complete-binary-tree hierarchical softmax (reference
    hierarchical_sigmoid_op): class c's path follows the binary digits of
    c + num_classes down from the root; internal node i uses w[i-1]."""
    code_len = int(np.ceil(np.log2(num_classes)))
    codes = labels + num_classes  # node path encoded in binary
    loss = jnp.zeros(x.shape[0], x.dtype)
    for d in range(code_len, 0, -1):
        node = codes >> d  # ancestor at depth (from root)
        bit = (codes >> (d - 1)) & 1  # which child we descend into
        valid = node >= 1
        widx = jnp.clip(node - 1, 0, num_classes - 2)
        logits = jnp.sum(x * w[widx], axis=-1) + b[widx]
        # bit==1 -> right child: target 0/1 convention follows the sign trick
        t = bit.astype(x.dtype)
        bce = jnp.maximum(logits, 0) - logits * t + \
            jnp.log1p(jnp.exp(-jnp.abs(logits)))
        loss = loss + jnp.where(valid, bce, 0.0)
    return loss


def hsigmoid_loss(input, label, num_classes, weight, bias=None,
                  path_table=None, path_code=None, is_sparse=False,
                  name=None):
    if path_table is not None or path_code is not None:
        raise ValueError(
            "hsigmoid_loss custom path tables are not supported; the default "
            "complete-binary-tree coding is")
    if bias is None:
        from ...ops import creation

        bias = creation.zeros([num_classes - 1], str(input.dtype))
    per = _hsigmoid_loss(input, label, weight, bias,
                         num_classes=int(num_classes))
    return per.mean()


@primitive("margin_cross_entropy_op")
def _margin_ce(logits, label, *, m1, m2, m3, s):
    # logits are cosines; apply the combined ArcFace/CosFace margin to the
    # target class then scale and softmax-CE
    theta = jnp.arccos(jnp.clip(logits, -1.0 + 1e-7, 1.0 - 1e-7))
    target = jax.nn.one_hot(label, logits.shape[-1], dtype=logits.dtype)
    margin_cos = jnp.cos(m1 * theta + m2) - m3
    adjusted = jnp.where(target > 0, margin_cos, logits) * s
    logp = jax.nn.log_softmax(adjusted, axis=-1)
    loss = -jnp.sum(target * logp, axis=-1)
    return loss, jax.nn.softmax(adjusted, axis=-1)


def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5,
                         margin3=0.0, scale=64.0, group=None,
                         return_softmax=False, reduction="mean", name=None):
    """ArcFace/CosFace margin softmax (reference margin_cross_entropy op)."""
    loss, softmax = _margin_ce(logits, label, m1=float(margin1),
                               m2=float(margin2), m3=float(margin3),
                               s=float(scale))
    if reduction == "mean":
        loss = loss.mean()
    elif reduction == "sum":
        loss = loss.sum()
    if return_softmax:
        return loss, softmax
    return loss


def class_center_sample(label, num_classes, num_samples, group=None):
    """Sample class centers: the positives plus random negatives up to
    num_samples (reference class_center_sample op). Host-side sampling —
    eager only, like the reference's CPU path."""
    import numpy as np

    from ...core.tensor import Tensor as _T
    from ...ops import creation

    lab = np.asarray(label.numpy() if hasattr(label, "numpy") else label)
    pos = np.unique(lab)
    if len(pos) > num_samples:
        raise ValueError(
            f"class_center_sample: num_samples={num_samples} is smaller than "
            f"the {len(pos)} distinct positive classes in the batch; every "
            "positive must be kept (reference contract)")
    if len(pos) == num_samples:
        sampled = pos
    else:
        neg_pool = np.setdiff1d(np.arange(num_classes), pos)
        extra = np.random.choice(neg_pool, num_samples - len(pos),
                                 replace=False)
        sampled = np.concatenate([pos, extra])
    remap = -np.ones(num_classes, "int64")
    remap[sampled] = np.arange(len(sampled))
    return (creation.to_tensor(remap[lab]),
            creation.to_tensor(sampled.astype("int64")))


@primitive("npair_loss_op")
def _npair_loss(anchor, positive, labels, *, l2_reg):
    batch = anchor.shape[0]
    sim = jnp.matmul(anchor, positive.T)
    lab = labels.reshape(-1)
    target = (lab[:, None] == lab[None, :]).astype(anchor.dtype)
    target = target / jnp.sum(target, axis=1, keepdims=True)
    logp = jax.nn.log_softmax(sim, axis=1)
    ce = -jnp.mean(jnp.sum(target * logp, axis=1))
    reg = l2_reg * (jnp.mean(jnp.sum(anchor * anchor, 1))
                    + jnp.mean(jnp.sum(positive * positive, 1))) * 0.25
    return ce + reg


def npair_loss(anchor, positive, labels, l2_reg=0.002):
    """N-pair metric loss (reference npair_loss)."""
    return _npair_loss(anchor, positive, labels, l2_reg=float(l2_reg))


@primitive("sigmoid_focal_loss_op")
def _sigmoid_focal_loss(logit, label, norm, *, alpha, gamma, reduction):
    p = jax.nn.sigmoid(logit)
    ce = jnp.maximum(logit, 0) - logit * label + \
        jnp.log1p(jnp.exp(-jnp.abs(logit)))
    p_t = p * label + (1 - p) * (1 - label)
    a_t = alpha * label + (1 - alpha) * (1 - label)
    loss = a_t * ((1.0 - p_t) ** gamma) * ce
    loss = loss / norm
    return _reduce(loss, reduction)


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum", name=None):
    """RetinaNet focal loss (reference sigmoid_focal_loss)."""
    from ...ops import creation

    if normalizer is None:
        normalizer = creation.ones([1], str(logit.dtype))
    return _sigmoid_focal_loss(logit, label, normalizer, alpha=float(alpha),
                               gamma=float(gamma), reduction=reduction)
