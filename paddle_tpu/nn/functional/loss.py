"""Loss functionals (reference: python/paddle/nn/functional/loss.py).

cross_entropy is the TP-shardable hot path: computed from log_softmax in one
fused primitive so XLA keeps it on-device in one kernel cluster.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.dispatch import primitive
from ...core.tensor import Tensor


def _reduce(out, reduction):
    if reduction == "mean":
        return jnp.mean(out)
    if reduction == "sum":
        return jnp.sum(out)
    return out


def _ce_core(logits, labels, axis, soft_label, ignore_index, use_softmax):
    """Shared CE math. use_softmax=False: input is already softmax
    probabilities and loss_j = -log(P[label_j]) (reference loss.py:1427-1433
    docs; softmax_with_cross_entropy_op.h:82 skips the softmax step).
    Returns (per-sample loss, mask, safe labels) — mask/safe are None for
    soft labels."""
    if use_softmax:
        logp = jax.nn.log_softmax(logits, axis=axis)
    else:
        logp = jnp.log(jnp.maximum(logits, 1e-30))
    if soft_label:
        return -jnp.sum(labels * logp, axis=axis), None, None
    lab = labels
    if lab.ndim == logits.ndim:
        lab = jnp.squeeze(lab, axis)
    mask = lab != ignore_index
    safe_lab = jnp.where(mask, lab, 0).astype(jnp.int32)
    picked = jnp.take_along_axis(logp, jnp.expand_dims(safe_lab, axis), axis=axis)
    loss = jnp.where(mask, -jnp.squeeze(picked, axis), 0.0)
    return loss, mask, safe_lab


@primitive("softmax_with_cross_entropy_op")
def _softmax_ce(logits, labels, *, axis, soft_label, reduction, ignore_index,
                use_softmax=True):
    loss, mask, _ = _ce_core(logits, labels, axis, soft_label, ignore_index,
                             use_softmax)
    if mask is not None and reduction == "mean":
        return jnp.sum(loss) / jnp.maximum(jnp.sum(mask), 1)
    return _reduce(loss, reduction)


@primitive("softmax_ce_weighted_op")
def _softmax_ce_weighted(logits, labels, weight, *, axis, soft_label, reduction,
                         ignore_index, use_softmax):
    # per-class weights: hard labels gather weight[label] (zeroed at
    # ignore_index); mean divides by the summed gathered weights — matching
    # reference loss.py weighted-mean semantics.
    loss, mask, safe_lab = _ce_core(logits, labels, axis, soft_label,
                                    ignore_index, use_softmax)
    if soft_label:
        wg = jnp.tensordot(labels.astype(weight.dtype), weight,
                           axes=[[axis], [0]])
    else:
        wg = jnp.take(weight, safe_lab) * mask.astype(weight.dtype)
    loss = loss * wg
    if reduction == "mean":
        denom = jnp.sum(wg)
        return jnp.sum(loss) / (denom + (denom == 0.0))
    return _reduce(loss, reduction)


def cross_entropy(input, label, weight=None, ignore_index=-100, reduction="mean",
                  soft_label=False, axis=-1, use_softmax=True, name=None):
    if weight is not None:
        return _softmax_ce_weighted(
            input, label, weight, axis=int(axis), soft_label=bool(soft_label),
            reduction=reduction, ignore_index=int(ignore_index),
            use_softmax=bool(use_softmax),
        )
    return _softmax_ce(
        input, label, axis=int(axis), soft_label=bool(soft_label),
        reduction=reduction, ignore_index=int(ignore_index),
        use_softmax=bool(use_softmax),
    )


def softmax_with_cross_entropy(logits, label, soft_label=False, ignore_index=-100,
                               numeric_stable_mode=True, return_softmax=False, axis=-1):
    loss = _softmax_ce(logits, label, axis=int(axis), soft_label=bool(soft_label),
                       reduction="none", ignore_index=int(ignore_index))
    from .activation import softmax as _softmax

    if return_softmax:
        return loss, _softmax(logits, axis=axis)
    return loss


def _nll_core(logp, labels, ignore_index):
    """Shared gather: class axis is 1 for K-dim input (N, C, d1, ...) per the
    reference nll_loss contract; returns (per-elem loss, mask, safe labels)."""
    if logp.ndim > 2:
        logp = jnp.moveaxis(logp, 1, -1)
    mask = labels != ignore_index
    safe = jnp.where(mask, labels, 0).astype(jnp.int32)
    picked = jnp.take_along_axis(logp, safe[..., None], axis=-1)
    loss = jnp.where(mask, -jnp.squeeze(picked, -1), 0.0)
    return loss, mask, safe


@primitive("nll_loss_op")
def _nll_loss(logp, labels, *, reduction, ignore_index):
    loss, mask, _ = _nll_core(logp, labels, ignore_index)
    if reduction == "mean":
        return jnp.sum(loss) / jnp.maximum(jnp.sum(mask), 1)
    return _reduce(loss, reduction)


@primitive("nll_loss_weighted_op")
def _nll_loss_weighted(logp, labels, weight, *, reduction, ignore_index):
    loss, mask, safe = _nll_core(logp, labels, ignore_index)
    wg = jnp.take(weight, safe) * mask.astype(weight.dtype)
    loss = loss * wg
    if reduction == "mean":
        denom = jnp.sum(wg)
        return jnp.sum(loss) / (denom + (denom == 0.0))
    return _reduce(loss, reduction)


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean", name=None):
    if weight is not None:
        return _nll_loss_weighted(input, label, weight, reduction=reduction,
                                  ignore_index=int(ignore_index))
    return _nll_loss(input, label, reduction=reduction, ignore_index=int(ignore_index))


@primitive("mse_loss_op")
def _mse(x, y, *, reduction):
    return _reduce(jnp.square(x - y), reduction)


def mse_loss(input, label, reduction="mean", name=None):
    return _mse(input, label, reduction=reduction)


@primitive("l1_loss_op")
def _l1(x, y, *, reduction):
    return _reduce(jnp.abs(x - y), reduction)


def l1_loss(input, label, reduction="mean", name=None):
    return _l1(input, label, reduction=reduction)


@primitive("smooth_l1_op")
def _smooth_l1(x, y, *, reduction, delta):
    d = jnp.abs(x - y)
    loss = jnp.where(d < delta, 0.5 * d * d / delta, d - 0.5 * delta)
    return _reduce(loss, reduction)


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    return _smooth_l1(input, label, reduction=reduction, delta=float(delta))


@primitive("bce_op")
def _bce(p, y, *, reduction, eps):
    p = jnp.clip(p, eps, 1.0 - eps)
    loss = -(y * jnp.log(p) + (1.0 - y) * jnp.log(1.0 - p))
    return _reduce(loss, reduction)


def binary_cross_entropy(input, label, weight=None, reduction="mean", name=None):
    if weight is not None:
        from ...ops import math as _m, reduction as _r

        out = _m.multiply(_bce(input, label, reduction="none", eps=1e-12), weight)
        if reduction == "mean":
            return _r.mean(out)
        if reduction == "sum":
            return _r.sum(out)
        return out
    return _bce(input, label, reduction=reduction, eps=1e-12)


@primitive("bce_logits_op")
def _bce_logits(x, y, *, reduction):
    # numerically-stable sigmoid CE: max(x,0) - x*y + log(1+exp(-|x|))
    loss = jnp.maximum(x, 0) - x * y + jnp.log1p(jnp.exp(-jnp.abs(x)))
    return _reduce(loss, reduction)


@primitive("bce_logits_weighted_op")
def _bce_logits_w(x, y, weight, pos_weight, *, reduction, has_w, has_pw):
    if has_pw:
        import jax

        # pos_weight scales the positive term: L = -[pw*y*logσ(x) +
        # (1-y)*logσ(-x)], stable via log-sigmoids
        loss = -(pos_weight * y * jax.nn.log_sigmoid(x)
                 + (1 - y) * jax.nn.log_sigmoid(-x))
    else:
        loss = jnp.maximum(x, 0) - x * y + jnp.log1p(jnp.exp(-jnp.abs(x)))
    if has_w:
        loss = loss * weight
    return _reduce(loss, reduction)


def binary_cross_entropy_with_logits(logit, label, weight=None, reduction="mean",
                                     pos_weight=None, name=None):
    if weight is None and pos_weight is None:
        return _bce_logits(logit, label, reduction=reduction)
    from ...ops import creation

    one = creation.ones_like(label)
    return _bce_logits_w(
        logit, label, weight if weight is not None else one,
        pos_weight if pos_weight is not None else one,
        reduction=reduction, has_w=weight is not None,
        has_pw=pos_weight is not None)


@primitive("kl_div_op")
def _kl_div(x, y, *, reduction):
    loss = y * (jnp.log(jnp.maximum(y, 1e-12)) - x)
    if reduction == "batchmean":
        return jnp.sum(loss) / x.shape[0]
    return _reduce(loss, reduction)


def kl_div(input, label, reduction="mean", name=None):
    return _kl_div(input, label, reduction=reduction)


@primitive("margin_ranking_op")
def _margin_ranking(x1, x2, y, *, margin, reduction):
    return _reduce(jnp.maximum(0.0, -y * (x1 - x2) + margin), reduction)


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean", name=None):
    return _margin_ranking(input, other, label, margin=float(margin), reduction=reduction)


@primitive("hinge_embedding_op")
def _hinge_embedding(x, y, *, margin, reduction):
    loss = jnp.where(y == 1, x, jnp.maximum(0.0, margin - x))
    return _reduce(loss, reduction)


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean", name=None):
    return _hinge_embedding(input, label, margin=float(margin), reduction=reduction)


@primitive("cosine_embedding_op")
def _cosine_embedding(x1, x2, y, *, margin, reduction):
    cos = jnp.sum(x1 * x2, -1) / jnp.maximum(
        jnp.linalg.norm(x1, axis=-1) * jnp.linalg.norm(x2, axis=-1), 1e-12)
    loss = jnp.where(y == 1, 1 - cos, jnp.maximum(0.0, cos - margin))
    return _reduce(loss, reduction)


def cosine_embedding_loss(input1, input2, label, margin=0.0, reduction="mean", name=None):
    return _cosine_embedding(input1, input2, label, margin=float(margin), reduction=reduction)


@primitive("ctc_like_square_op")
def _square_error_cost(x, y):
    return jnp.square(x - y)


def square_error_cost(input, label):
    return _square_error_cost(input, label)
