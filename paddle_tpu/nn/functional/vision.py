"""Vision functionals: sampling/warping ops (reference:
python/paddle/nn/functional/vision.py — grid_sample/affine_grid over
grid_sampler_op.cu; fold/pixel ops in common.py).

TPU-native: grid_sample is one vmapped bilinear gather primitive with
per-corner zero-padding weights (grid_sample semantics — deliberately NOT the
roi_align-style clamped bilinear in vision/ops.py), affine_grid is pure index
math, fold is a scatter-add — all single fused executables.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.dispatch import primitive

__all__ = ["grid_sample", "affine_grid", "fold", "pixel_unshuffle",
           "channel_shuffle", "pairwise_distance"]


@primitive("grid_sample_op")
def _grid_sample(x, grid, *, mode, padding_mode, align_corners):
    """x [N,C,H,W]; grid [N,Ho,Wo,2] in [-1,1] (x then y, paddle layout)."""
    N, C, H, W = x.shape

    def unnormalize(coord, size):
        if align_corners:
            return (coord + 1.0) * 0.5 * (size - 1)
        return ((coord + 1.0) * size - 1.0) * 0.5

    gx = unnormalize(grid[..., 0], W)  # [N,Ho,Wo]
    gy = unnormalize(grid[..., 1], H)

    def reflect(coord, size):
        if size == 1:
            return jnp.zeros_like(coord)
        if align_corners:
            span = 2.0 * (size - 1)
            coord = jnp.abs(coord) % span
            return jnp.where(coord > size - 1, span - coord, coord)
        span = 2.0 * size
        coord = (coord + 0.5) % span
        coord = jnp.where(coord > size, span - coord, coord) - 0.5
        return jnp.clip(coord, 0, size - 1)

    if padding_mode == "border":
        gx = jnp.clip(gx, 0, W - 1)
        gy = jnp.clip(gy, 0, H - 1)
    elif padding_mode == "reflection":
        gx = reflect(gx, W)
        gy = reflect(gy, H)

    def sample_one(feat, yy, xx):
        if mode == "nearest":
            xi = jnp.clip(jnp.round(xx), 0, W - 1).astype(jnp.int32)
            yi = jnp.clip(jnp.round(yy), 0, H - 1).astype(jnp.int32)
            out = feat[:, yi, xi]
            if padding_mode == "zeros":
                valid = ((xx >= -0.5) & (xx <= W - 0.5)
                         & (yy >= -0.5) & (yy <= H - 0.5))
                out = out * valid.astype(feat.dtype)
            return out
        # bilinear with out-of-range zeroing for padding_mode == "zeros"
        x0 = jnp.floor(xx)
        y0 = jnp.floor(yy)
        wx = xx - x0
        wy = yy - y0
        out = 0.0
        for dy, wyv in ((0, 1 - wy), (1, wy)):
            for dx, wxv in ((0, 1 - wx), (1, wx)):
                xi = x0 + dx
                yi = y0 + dy
                inside = (xi >= 0) & (xi <= W - 1) & (yi >= 0) & (yi <= H - 1)
                xi_c = jnp.clip(xi, 0, W - 1).astype(jnp.int32)
                yi_c = jnp.clip(yi, 0, H - 1).astype(jnp.int32)
                v = feat[:, yi_c, xi_c]
                w = wyv * wxv
                if padding_mode == "zeros":
                    w = w * inside.astype(feat.dtype)
                out = out + v * w
        return out

    return jax.vmap(sample_one)(x, gy, gx)


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True, name=None):
    if mode not in ("bilinear", "nearest"):
        raise ValueError("mode must be bilinear or nearest")
    if padding_mode not in ("zeros", "border", "reflection"):
        raise ValueError("padding_mode must be zeros/border/reflection")
    return _grid_sample(x, grid, mode=mode, padding_mode=padding_mode,
                        align_corners=bool(align_corners))


@primitive("affine_grid_op")
def _affine_grid(theta, *, out_h, out_w, align_corners):
    """theta [N,2,3] -> sampling grid [N,H,W,2] (x,y in [-1,1])."""
    if align_corners:
        ys = jnp.linspace(-1.0, 1.0, out_h)
        xs = jnp.linspace(-1.0, 1.0, out_w)
    else:
        ys = (jnp.arange(out_h) * 2 + 1) / out_h - 1.0
        xs = (jnp.arange(out_w) * 2 + 1) / out_w - 1.0
    gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
    ones = jnp.ones_like(gx)
    base = jnp.stack([gx, gy, ones], axis=-1)  # [H,W,3]
    # sampling coordinates need full f32: no bf16 MXU shortcut here
    return jnp.einsum("nij,hwj->nhwi", theta, base, precision="highest")


def affine_grid(theta, out_shape, align_corners=True, name=None):
    n, c, h, w = [int(v) for v in out_shape]
    return _affine_grid(theta, out_h=h, out_w=w,
                        align_corners=bool(align_corners))


@primitive("fold_op")
def _fold(x, *, output_sizes, kernel_sizes, strides, paddings, dilations):
    """Inverse of unfold: [N, C*kh*kw, L] -> [N, C, H, W] via scatter-add."""
    N = x.shape[0]
    kh, kw = kernel_sizes
    sh, sw = strides
    ph, pw = paddings
    dh, dw = dilations
    H, W = output_sizes
    C = x.shape[1] // (kh * kw)
    oh = (H + 2 * ph - (dh * (kh - 1) + 1)) // sh + 1
    ow = (W + 2 * pw - (dw * (kw - 1) + 1)) // sw + 1
    cols = x.reshape(N, C, kh, kw, oh, ow)
    out = jnp.zeros((N, C, H + 2 * ph, W + 2 * pw), x.dtype)
    for i in range(kh):
        for j in range(kw):
            ys = i * dh
            xs = j * dw
            out = out.at[:, :, ys: ys + sh * oh: sh,
                         xs: xs + sw * ow: sw].add(cols[:, :, i, j])
    return out[:, :, ph: ph + H, pw: pw + W]


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1,
         name=None):
    def _pair(v):
        return (v, v) if isinstance(v, int) else tuple(int(a) for a in v)

    return _fold(x, output_sizes=_pair(output_sizes),
                 kernel_sizes=_pair(kernel_sizes), strides=_pair(strides),
                 paddings=_pair(paddings), dilations=_pair(dilations))


@primitive("pixel_unshuffle_op")
def _pixel_unshuffle(x, *, factor):
    n, c, h, w = x.shape
    r = factor
    x = x.reshape(n, c, h // r, r, w // r, r)
    return x.transpose(0, 1, 3, 5, 2, 4).reshape(n, c * r * r, h // r, w // r)


def pixel_unshuffle(x, downscale_factor, data_format="NCHW", name=None):
    if data_format == "NHWC":
        from ...ops import manipulation as _m

        out = _pixel_unshuffle(_m.transpose(x, [0, 3, 1, 2]),
                               factor=int(downscale_factor))
        return _m.transpose(out, [0, 2, 3, 1])
    if data_format != "NCHW":
        raise ValueError(f"pixel_unshuffle: bad data_format {data_format!r}")
    return _pixel_unshuffle(x, factor=int(downscale_factor))


@primitive("channel_shuffle_op")
def _channel_shuffle(x, *, groups):
    n, c, h, w = x.shape
    x = x.reshape(n, groups, c // groups, h, w)
    return x.transpose(0, 2, 1, 3, 4).reshape(n, c, h, w)


def channel_shuffle(x, groups, data_format="NCHW", name=None):
    if data_format == "NHWC":
        from ...ops import manipulation as _m

        out = _channel_shuffle(_m.transpose(x, [0, 3, 1, 2]),
                               groups=int(groups))
        return _m.transpose(out, [0, 2, 3, 1])
    if data_format != "NCHW":
        raise ValueError(f"channel_shuffle: bad data_format {data_format!r}")
    return _channel_shuffle(x, groups=int(groups))


@primitive("pairwise_distance_op")
def _pairwise_distance(x, y, *, p, epsilon, keepdim):
    d = x - y + epsilon
    return jnp.linalg.norm(d, ord=p, axis=-1, keepdims=keepdim)


def pairwise_distance(x, y, p=2.0, epsilon=1e-6, keepdim=False, name=None):
    return _pairwise_distance(x, y, p=float(p), epsilon=float(epsilon),
                              keepdim=bool(keepdim))


@primitive("temporal_shift_op")
def _temporal_shift(x, *, seg_num, shift_ratio):
    # x: [N*T, C, H, W] -> shift 1/r channels backward, 1/r forward in time
    nt, c, h, w = x.shape
    n = nt // seg_num
    xt = x.reshape(n, seg_num, c, h, w)
    fold = int(c * shift_ratio)
    back = jnp.concatenate([xt[:, 1:, :fold], jnp.zeros_like(xt[:, :1, :fold])], 1)
    fwd = jnp.concatenate([jnp.zeros_like(xt[:, :1, fold:2 * fold]),
                           xt[:, :-1, fold:2 * fold]], 1)
    rest = xt[:, :, 2 * fold:]
    out = jnp.concatenate([back, fwd, rest], axis=2)
    return out.reshape(nt, c, h, w)


def temporal_shift(x, seg_num, shift_ratio=0.25, name=None,
                   data_format="NCHW"):
    """TSM temporal shift (reference temporal_shift_op)."""
    return _temporal_shift(x, seg_num=int(seg_num),
                           shift_ratio=float(shift_ratio))
