"""Attention functionals.

The fused-attention hot op (reference: paddle/fluid/operators/fused/
fused_attention_op.cu + fmha_ref.h) re-designed TPU-first: a single fused
primitive that XLA maps onto MXU matmuls, with a Pallas flash-attention kernel
(paddle_tpu/kernels/flash_attention.py) engaged on TPU for long sequences.

Layout convention (paddle's): q/k/v are [batch, seq, num_heads, head_dim].
"""
from __future__ import annotations

import functools
import math
import os

import jax
import jax.numpy as jnp

from ...core.dispatch import primitive
from ...core.tensor import Tensor
from ...framework import random as random_mod


def _sdpa_xla(q, k, v, mask, *, causal, scale, dropout_p, key=None):
    # [b, s, h, d] -> attention over s with batched heads
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    logits = logits.astype(jnp.float32)
    if causal:
        qs, ks = q.shape[1], k.shape[1]
        causal_mask = jnp.tril(jnp.ones((qs, ks), bool), k=ks - qs)
        logits = jnp.where(causal_mask, logits, -1e30)
    if mask is not None:
        logits = logits + mask.astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    if dropout_p > 0.0 and key is not None:
        keep = jax.random.bernoulli(key, 1.0 - dropout_p, probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout_p), 0.0).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


@primitive("sdpa")
def _sdpa(q, k, v, *, causal, scale, impl="xla"):
    if impl == "flash":
        try:
            from ...kernels.flash_attention import flash_attention

            return flash_attention(q, k, v, causal=causal, scale=scale)
        except Exception:  # pragma: no cover - kernel unavailable
            pass
    return _sdpa_xla(q, k, v, None, causal=causal, scale=scale,
                     dropout_p=0.0)


@primitive("sdpa_mask")
def _sdpa_mask(q, k, v, mask, *, causal, scale):
    return _sdpa_xla(q, k, v, mask, causal=causal, scale=scale, dropout_p=0.0)


@primitive("sdpa_dropout")
def _sdpa_dropout(q, k, v, rngkey, *, causal, scale, dropout_p):
    return _sdpa_xla(q, k, v, None, causal=causal, scale=scale,
                     dropout_p=dropout_p, key=rngkey)


@primitive("sdpa_mask_dropout")
def _sdpa_mask_dropout(q, k, v, mask, rngkey, *, causal, scale, dropout_p):
    return _sdpa_xla(q, k, v, mask, causal=causal, scale=scale,
                     dropout_p=dropout_p, key=rngkey)


def attention_backend(sq: int, sk: int, head_dim: int,
                      platform: str = None) -> str:
    """Which kernel ``scaled_dot_product_attention`` lands on for a
    (platform, shape): ``'flash'`` (Pallas) or ``'xla'`` (fused-XLA
    softmax). The old hard-coded "TPU + long sequence" heuristic is now
    a documented threshold — ``FLAGS_flash_min_seq`` (live-read): both
    q and kv sequences must reach it, on top of the kernel's structural
    constraints (block-divisible sequences, MXU-friendly head_dim).

    The decision is passed to the ``sdpa`` primitive as an ATTR, so it
    participates in the jit cache key: a threshold-driven path flip
    shows up as a new cache key the ``analysis.retrace`` auditor names
    (``op:sdpa`` label) instead of silently recompiling.
    """
    if os.environ.get("PADDLE_TPU_DISABLE_FLASH", "0") == "1":
        return "xla"
    if platform is None:
        try:
            platform = jax.devices()[0].platform
        except Exception:
            return "xla"
    if platform == "cpu":
        return "xla"
    from ...framework import flags as flags_mod

    if not flags_mod.flag("use_pallas_flash_attention"):
        return "xla"
    min_seq = int(flags_mod.flag("flash_min_seq"))
    if sq < min_seq or sk < min_seq:
        return "xla"
    # structural: block-divisible sequences, MXU-friendly head_dim
    if sq % 128 or sk % 128 or head_dim not in (64, 128, 256):
        return "xla"
    return "flash"


def scaled_dot_product_attention(query, key, value, attn_mask=None, dropout_p=0.0,
                                 is_causal=False, training=True, scale=None, name=None):
    """q/k/v: [batch, seq, heads, head_dim]. attn_mask: additive float mask
    broadcastable to [b, h, sq, sk]."""
    d = query.shape[-1]
    s = float(scale) if scale is not None else 1.0 / math.sqrt(d)
    if dropout_p > 0.0 and training:
        rk = random_mod.next_key()
        if attn_mask is None:
            return _sdpa_dropout(query, key, value, rk, causal=bool(is_causal),
                                 scale=s, dropout_p=float(dropout_p))
        return _sdpa_mask_dropout(query, key, value, attn_mask, rk,
                                  causal=bool(is_causal), scale=s, dropout_p=float(dropout_p))
    if attn_mask is None:
        impl = attention_backend(query.shape[1], key.shape[1],
                                 query.shape[3])
        return _sdpa(query, key, value, causal=bool(is_causal), scale=s,
                     impl=impl)
    return _sdpa_mask(query, key, value, attn_mask, causal=bool(is_causal), scale=s)


flash_attention = scaled_dot_product_attention  # paddle.nn.functional.flash_attention alias
