"""Common nn functionals: linear, embedding, dropout, normalization, pooling,
interpolate (reference: python/paddle/nn/functional/{common,norm,pooling}.py).

Convs/pools use lax.conv_general_dilated / lax.reduce_window directly — the MXU
path for convs, fused window reductions for pools.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...core.dispatch import primitive
from ...core.tensor import Tensor
from ...framework import random as random_mod
from ...framework import dtype as dtype_mod


@primitive("linear_op")
def _linear(x, w, b):
    return jnp.matmul(x, w) + b


@primitive("linear_nobias_op")
def _linear_nb(x, w):
    return jnp.matmul(x, w)


def linear(x, weight, bias=None, name=None):
    if bias is None:
        return _linear_nb(x, weight)
    return _linear(x, weight, bias)


@primitive("embedding_op")
def _embedding(w, ids, *, padding_idx, oov=None):
    if oov == "clip":
        ids = jnp.clip(ids, 0, w.shape[0] - 1)
    out = jnp.take(w, ids, axis=0)
    if padding_idx is not None:
        mask = (ids == padding_idx)[..., None]
        out = jnp.where(mask, 0.0, out)
    return out


@_embedding.defvjp
def _embedding_vjp(ct, out, primals, *, padding_idx, oov=None):
    w, ids = primals
    if oov == "clip":
        ids = jnp.clip(ids, 0, w.shape[0] - 1)
    if padding_idx is not None:
        ct = jnp.where((ids == padding_idx)[..., None], 0.0, ct)
    gw = jnp.zeros_like(w).at[ids].add(ct.astype(w.dtype))
    return (gw, None)


def embedding(x, weight, padding_idx=None, sparse=False, name=None,
              oov_policy=None):
    """Row lookup with an EXPLICIT out-of-vocabulary policy.

    ``jnp.take`` clamps out-of-range ids silently — a recsys id stream
    with a hashing bug would train on row 0/row n-1 garbage without a
    peep. Policy (``FLAGS_embedding_oov_policy`` default, per-call
    override): ``'error'`` raises on concrete eager ids outside
    ``[0, num_rows)`` (inside a traced program ids are abstract — the
    check cannot run and the clamped gather remains, documented);
    ``'clip'`` opts into the clamp everywhere and makes it part of the
    op's cache key (the attr rides the jit key, so flipping policies
    retraces auditable)."""
    from ...framework import flags as _flags

    policy = oov_policy or _flags.flag("embedding_oov_policy")
    if policy not in ("error", "clip"):
        raise ValueError(
            f"embedding oov_policy must be 'error' or 'clip', got "
            f"{policy!r}")
    if policy == "error":
        ids = x.data if isinstance(x, Tensor) else x
        if not isinstance(ids, jax.core.Tracer):
            if not isinstance(ids, jax.Array):
                ids = np.asarray(ids)  # lists/scalars are checkable too
        if not isinstance(ids, jax.core.Tracer) and \
                getattr(ids, "size", 0):
            n = int((weight.data if isinstance(weight, Tensor)
                     else weight).shape[0])
            if isinstance(ids, np.ndarray):
                # host ids validate host-side (no H2D round-trip)
                lo, hi = int(ids.min()), int(ids.max())
            elif not jax.core.trace_state_clean():
                # CONCRETE device ids under an AMBIENT trace: possible
                # when an upstream op ran through an AOT-compiled
                # executable (persistent-cache per-op jits) — the
                # min/max readback below would be STAGED by the ambient
                # trace and np.asarray would crash on the new tracer.
                # Same contract as tracer ids: traced programs are
                # documented unchecked.
                lo, hi = 0, -1
            else:
                # ONE blocking readback for both bounds, not two
                lo, hi = (int(v) for v in np.asarray(
                    jnp.stack([jnp.min(ids), jnp.max(ids)])))
            if lo < 0 or hi >= n:
                raise ValueError(
                    f"embedding: id out of range [0, {n}) "
                    f"(min={lo}, max={hi}); pass oov_policy='clip' or set "
                    f"FLAGS_embedding_oov_policy='clip' for the clamped "
                    f"legacy behavior")
    return _embedding(weight, x, padding_idx=padding_idx,
                      oov=("clip" if policy == "clip" else None))


@primitive("dropout_op")
def _dropout(x, key, *, p, upscale):
    keep = jax.random.bernoulli(key, 1.0 - p, x.shape)
    if upscale:
        return jnp.where(keep, x / (1.0 - p), 0.0).astype(x.dtype)
    return jnp.where(keep, x, 0.0).astype(x.dtype)


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train", name=None):
    if not training or p == 0.0:
        if mode == "downscale_in_infer" and not training:
            from ...ops import math as _math

            return _math.scale(x, 1.0 - p)
        return x
    return _dropout(x, random_mod.next_key(), p=float(p), upscale=(mode == "upscale_in_train"))


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    if not training or p == 0.0:
        return x
    return _dropout2d(x, random_mod.next_key(), p=float(p), nchw=(data_format == "NCHW"))


@primitive("dropout2d_op")
def _dropout2d(x, key, *, p, nchw):
    shape = (x.shape[0], x.shape[1], 1, 1) if nchw else (x.shape[0], 1, 1, x.shape[3])
    keep = jax.random.bernoulli(key, 1.0 - p, shape)
    return jnp.where(keep, x / (1.0 - p), 0.0).astype(x.dtype)


# -- normalization -----------------------------------------------------------

@primitive("layer_norm_op")
def _layer_norm(x, w, b, *, eps, begin_axis):
    axes = tuple(range(begin_axis, x.ndim))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.var(x, axis=axes, keepdims=True)
    xn = (x - mean) * jax.lax.rsqrt(var + eps)
    return xn * w + b


@primitive("layer_norm_nowb_op")
def _layer_norm_nowb(x, *, eps, begin_axis):
    axes = tuple(range(begin_axis, x.ndim))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.var(x, axis=axes, keepdims=True)
    return (x - mean) * jax.lax.rsqrt(var + eps)


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-5, name=None):
    if isinstance(normalized_shape, int):
        normalized_shape = [normalized_shape]
    begin_axis = x.ndim - len(normalized_shape)
    if weight is None:
        return _layer_norm_nowb(x, eps=float(epsilon), begin_axis=begin_axis)
    return _layer_norm(x, weight, bias, eps=float(epsilon), begin_axis=begin_axis)


@primitive("rms_norm_op")
def _rms_norm(x, w, *, eps, fused=False):
    if fused:
        from ...kernels.pallas.rmsnorm import rms_norm as _fused

        return _fused(x, w, eps)
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    xn = x.astype(jnp.float32) * jax.lax.rsqrt(var + eps)
    return (xn * w.astype(jnp.float32)).astype(x.dtype)


@primitive("rms_norm_residual_op")
def _rms_norm_residual(x, res, w, *, eps, fused=False):
    """Pre-norm decoder pattern ``s = x + res; y = rmsnorm(s)`` ->
    (y, s): fused through kernels/pallas when the registry gate is open,
    else the composed two-op form (identical math)."""
    if fused:
        from ...kernels.pallas.rmsnorm import rms_norm_residual as _fused

        return _fused(x, res, w, eps)
    s = x + res
    var = jnp.mean(jnp.square(s.astype(jnp.float32)), axis=-1, keepdims=True)
    sn = s.astype(jnp.float32) * jax.lax.rsqrt(var + eps)
    return (sn * w.astype(jnp.float32)).astype(x.dtype), s


def _rms_fused_gate() -> bool:
    from ...kernels.registry import fused_enabled

    return fused_enabled("rms_norm")


def rms_norm(x, weight, epsilon=1e-6, name=None):
    """RMSNorm (not in the reference snapshot; required by the Llama
    family). The fused-kernel gate rides the jit cache key as an attr,
    so an ``FLAGS_fused_kernels`` flip retraces (retrace-auditable)."""
    return _rms_norm(x, weight, eps=float(epsilon), fused=_rms_fused_gate())


def rms_norm_residual(x, residual, weight, epsilon=1e-6, name=None):
    """Fused residual-add + RMSNorm -> ``(normed, new_residual)`` — the
    decoder-layer hot pattern (see docs/performance.md "Fused kernels")."""
    return _rms_norm_residual(x, residual, weight, eps=float(epsilon),
                              fused=_rms_fused_gate())


@primitive("batch_norm_infer_op")
def _bn_infer(x, mean, var, w, b, *, eps, axis):
    shape = [1] * x.ndim
    shape[axis] = x.shape[axis]
    mean = mean.reshape(shape)
    var = var.reshape(shape)
    w = w.reshape(shape)
    b = b.reshape(shape)
    return (x - mean) * jax.lax.rsqrt(var + eps) * w + b


@primitive("batch_norm_train_op")
def _bn_train(x, w, b, *, eps, axis):
    axes = tuple(i for i in range(x.ndim) if i != axis)
    mean = jnp.mean(x, axis=axes)
    var = jnp.var(x, axis=axes)
    shape = [1] * x.ndim
    shape[axis] = x.shape[axis]
    xn = (x - mean.reshape(shape)) * jax.lax.rsqrt(var.reshape(shape) + eps)
    return xn * w.reshape(shape) + b.reshape(shape), mean, var


def batch_norm(x, running_mean, running_var, weight, bias, training=False,
               momentum=0.9, epsilon=1e-5, data_format="NCHW", use_global_stats=None, name=None):
    axis = 1 if data_format.startswith("NC") else x.ndim - 1
    if use_global_stats is None:
        use_global_stats = not training
    if use_global_stats:
        return _bn_infer(x, running_mean, running_var, weight, bias, eps=float(epsilon), axis=axis)
    out, batch_mean, batch_var = _bn_train(x, weight, bias, eps=float(epsilon), axis=axis)
    # update running stats in place (matches reference's batch_norm mean/var outputs)
    if isinstance(running_mean, Tensor):
        m = momentum
        running_mean.set_value(m * running_mean.data + (1 - m) * batch_mean.data)
        # reference accumulates the *biased* saved variance
        # (paddle/phi/kernels/cpu/batch_norm_kernel.cc running_var update)
        running_var.set_value(m * running_var.data + (1 - m) * batch_var.data)
    return out


@primitive("group_norm_op")
def _group_norm(x, w, b, *, groups, eps):
    n, c = x.shape[0], x.shape[1]
    gshape = (n, groups, c // groups) + x.shape[2:]
    xg = x.reshape(gshape)
    axes = tuple(range(2, xg.ndim))
    mean = jnp.mean(xg, axis=axes, keepdims=True)
    var = jnp.var(xg, axis=axes, keepdims=True)
    xn = ((xg - mean) * jax.lax.rsqrt(var + eps)).reshape(x.shape)
    shape = [1, c] + [1] * (x.ndim - 2)
    return xn * w.reshape(shape) + b.reshape(shape)


def group_norm(x, num_groups, weight=None, bias=None, epsilon=1e-5, data_format="NCHW", name=None):
    from ...ops import creation

    if weight is None:
        weight = creation.ones([x.shape[1]], x.dtype)
    if bias is None:
        bias = creation.zeros([x.shape[1]], x.dtype)
    return _group_norm(x, weight, bias, groups=int(num_groups), eps=float(epsilon))


@primitive("instance_norm_op")
def _instance_norm(x, w, b, *, eps):
    axes = tuple(range(2, x.ndim))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.var(x, axis=axes, keepdims=True)
    xn = (x - mean) * jax.lax.rsqrt(var + eps)
    shape = [1, x.shape[1]] + [1] * (x.ndim - 2)
    return xn * w.reshape(shape) + b.reshape(shape)


def instance_norm(x, running_mean=None, running_var=None, weight=None, bias=None,
                  use_input_stats=True, momentum=0.9, eps=1e-5, data_format="NCHW", name=None):
    from ...ops import creation

    if weight is None:
        weight = creation.ones([x.shape[1]], x.dtype)
    if bias is None:
        bias = creation.zeros([x.shape[1]], x.dtype)
    return _instance_norm(x, weight, bias, eps=float(eps))


@primitive("l2_normalize_op")
def _normalize(x, *, p, axis, eps):
    norm = jnp.power(jnp.sum(jnp.power(jnp.abs(x), p), axis=axis, keepdims=True), 1.0 / p)
    return x / jnp.maximum(norm, eps)


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    return _normalize(x, p=float(p), axis=int(axis), eps=float(epsilon))


# -- convolution / pooling ---------------------------------------------------

def _pair(v, n=2):
    if isinstance(v, (list, tuple)):
        return tuple(int(x) for x in v)
    return (int(v),) * n


@primitive("conv2d_op")
def _conv2d(x, w, *, stride, padding, dilation, groups, nchw):
    dn = ("NCHW", "OIHW", "NCHW") if nchw else ("NHWC", "HWIO", "NHWC")
    if isinstance(padding, str):
        pad = padding
    else:
        pad = [(p, p) for p in padding] if len(padding) == 2 else [
            tuple(padding[0:2]), tuple(padding[2:4])]
    return jax.lax.conv_general_dilated(
        x, w, window_strides=stride, padding=pad, rhs_dilation=dilation,
        feature_group_count=groups, dimension_numbers=dn,
    )


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW", name=None):
    nchw = data_format == "NCHW"
    if isinstance(padding, str):
        pad = padding.upper()
    else:
        pad = _pair(padding) if not isinstance(padding, (list, tuple)) or len(padding) <= 4 else padding
    out = _conv2d(
        x, weight, stride=_pair(stride), padding=pad if isinstance(pad, str) else tuple(pad),
        dilation=_pair(dilation), groups=int(groups), nchw=nchw,
    )
    if bias is not None:
        from ...ops import manipulation

        shape = [1, -1, 1, 1] if nchw else [1, 1, 1, -1]
        out = out + manipulation.reshape(bias, shape)
    return out


@primitive("conv1d_op")
def _conv1d(x, w, *, stride, padding, dilation, groups):
    return jax.lax.conv_general_dilated(
        x, w, window_strides=(stride,), padding=[(padding, padding)],
        rhs_dilation=(dilation,), feature_group_count=groups,
        dimension_numbers=("NCH", "OIH", "NCH"),
    )


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL", name=None):
    out = _conv1d(x, weight, stride=int(stride), padding=int(padding),
                  dilation=int(dilation), groups=int(groups))
    if bias is not None:
        from ...ops import manipulation

        out = out + manipulation.reshape(bias, [1, -1, 1])
    return out


@primitive("conv2d_transpose_op")
def _conv2d_transpose(x, w, *, stride, padding, dilation, out_pad, groups):
    # paddle stores the transpose kernel as [in, out//groups, kh, kw]
    # (python/paddle/nn/layer/conv.py Conv2DTranspose). Express the op as the
    # gradient of a forward conv: flip spatial dims, swap I/O per group, then a
    # fractionally-strided (lhs_dilated) conv with gradient padding
    # lo = hi = dilation*(k-1) - p, plus output_padding on the high side —
    # matching paddle's out = (H-1)*s - 2p + d*(k-1) + 1 + op.
    g = groups
    cin, cog, kh, kw = w.shape
    w = jnp.flip(w, axis=(2, 3))
    w = w.reshape(g, cin // g, cog, kh, kw)
    w = jnp.transpose(w, (0, 2, 1, 3, 4)).reshape(g * cog, cin // g, kh, kw)
    pads = [
        (dilation[i] * (k - 1) - padding[i],
         dilation[i] * (k - 1) - padding[i] + out_pad[i])
        for i, k in enumerate((kh, kw))
    ]
    return jax.lax.conv_general_dilated(
        x, w, window_strides=(1, 1), padding=pads, lhs_dilation=stride,
        rhs_dilation=dilation, feature_group_count=g,
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
    )


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     groups=1, dilation=1, data_format="NCHW", output_size=None, name=None):
    if data_format == "NHWC":  # compute in NCHW, transpose at the edges
        from ...ops import manipulation as _m

        out = conv2d_transpose(_m.transpose(x, [0, 3, 1, 2]), weight, bias,
                               stride, padding, output_padding, groups,
                               dilation, "NCHW", output_size)
        return _m.transpose(out, [0, 2, 3, 1])
    if data_format != "NCHW":
        raise ValueError(f"conv2d_transpose: bad data_format {data_format!r}")
    st, pd, dl = _pair(stride), _pair(padding), _pair(dilation)
    op = _pair(output_padding)
    if output_size is not None:
        if op != (0, 0):
            raise ValueError(
                "output_padding and output_size can not be both set")
        if isinstance(output_size, Tensor):
            output_size = output_size.tolist()
        osz = _pair(output_size)
        kh, kw = weight.shape[2], weight.shape[3]
        op = tuple(
            osz[i] - ((x.shape[2 + i] - 1) * st[i] - 2 * pd[i] + dl[i] * (k - 1) + 1)
            for i, k in enumerate((kh, kw))
        )
        for i in range(2):
            if not 0 <= op[i] < st[i]:
                raise ValueError(
                    f"output_size[{i}]={osz[i]} is out of the legal range "
                    f"[min, min+stride) for the given input/kernel/stride")
    out = _conv2d_transpose(x, weight, stride=st, padding=pd, dilation=dl,
                            out_pad=op, groups=int(groups))
    if bias is not None:
        from ...ops import manipulation

        out = out + manipulation.reshape(bias, [1, -1, 1, 1])
    return out


@primitive("max_pool2d_op")
def _max_pool2d(x, *, ksize, stride, padding, nchw):
    window = (1, 1) + ksize if nchw else (1,) + ksize + (1,)
    strides = (1, 1) + stride if nchw else (1,) + stride + (1,)
    pads = ((0, 0), (0, 0)) + tuple((p, p) for p in padding) if nchw else \
        ((0, 0),) + tuple((p, p) for p in padding) + ((0, 0),)
    return jax.lax.reduce_window(x, -jnp.inf, jax.lax.max, window, strides, pads)


def max_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               return_mask=False, data_format="NCHW", name=None):
    ks = _pair(kernel_size)
    st = _pair(stride) if stride is not None else ks
    out = _max_pool2d(x, ksize=ks, stride=st, padding=_pair(padding),
                      nchw=data_format == "NCHW")
    if return_mask:
        if data_format != "NCHW":
            raise ValueError("max_pool2d return_mask requires NCHW")
        return out, _max_pool_nd_mask(x, ksize=ks, stride=st,
                                      padding=_pair(padding))
    return out


@primitive("avg_pool2d_op")
def _avg_pool2d(x, *, ksize, stride, padding, nchw, count_include_pad):
    window = (1, 1) + ksize if nchw else (1,) + ksize + (1,)
    strides = (1, 1) + stride if nchw else (1,) + stride + (1,)
    pads = ((0, 0), (0, 0)) + tuple((p, p) for p in padding) if nchw else \
        ((0, 0),) + tuple((p, p) for p in padding) + ((0, 0),)
    summed = jax.lax.reduce_window(x, 0.0, jax.lax.add, window, strides, pads)
    if count_include_pad or all(p == 0 for p in padding):
        denom = np.prod(ksize)
        return summed / denom
    ones = jnp.ones_like(x)
    counts = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window, strides, pads)
    return summed / counts


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW", name=None):
    ks = _pair(kernel_size)
    st = _pair(stride) if stride is not None else ks
    return _avg_pool2d(
        x, ksize=ks, stride=st, padding=_pair(padding), nchw=data_format == "NCHW",
        count_include_pad=not exclusive,
    )


def _adaptive_bins(size, out):
    """torch/paddle adaptive pooling bin edges: start=floor(i*s/o),
    end=ceil((i+1)*s/o). Static python ints — fine under jit."""
    return [(i * size // out, -(-(i + 1) * size // out)) for i in range(out)]


def _adaptive_pool2d_body(x, out_hw, reduce_fn):
    """Shared divisible-fast-path + general bin loop (NCHW)."""
    n, c, h, w = x.shape
    oh, ow = out_hw
    if h % oh == 0 and w % ow == 0:  # fast path: one reshape-reduce
        return reduce_fn(x.reshape(n, c, oh, h // oh, ow, w // ow), (3, 5))
    rows = []
    for hs, he in _adaptive_bins(h, oh):
        cols = [reduce_fn(x[:, :, hs:he, ws:we], (2, 3))
                for ws, we in _adaptive_bins(w, ow)]
        rows.append(jnp.stack(cols, axis=-1))
    return jnp.stack(rows, axis=-2)


@primitive("adaptive_avg_pool2d_op")
def _adaptive_avg_pool2d(x, *, out_hw):
    return _adaptive_pool2d_body(x, out_hw, lambda v, ax: jnp.mean(v, axis=ax))


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive_avg_pool2d(x, out_hw=_pair(output_size))


@primitive("adaptive_max_pool2d_op")
def _adaptive_max_pool2d_any(x, *, out_hw):
    return _adaptive_pool2d_body(x, out_hw, lambda v, ax: jnp.max(v, axis=ax))


@primitive("adaptive_max_pool2d_mask_op", nondiff=True)
def _adaptive_max_pool2d_mask(x, *, out_hw):
    """Flattened H*W argmax index per output cell (the reference's mask)."""
    n, c, h, w = x.shape
    oh, ow = out_hw
    rows = []
    for hs, he in _adaptive_bins(h, oh):
        cols = []
        for ws, we in _adaptive_bins(w, ow):
            win = x[:, :, hs:he, ws:we].reshape(n, c, -1)
            flat = jnp.argmax(win, axis=-1)
            wh = we - ws
            gh = hs + flat // wh
            gw = ws + flat % wh
            cols.append(gh * w + gw)
        rows.append(jnp.stack(cols, axis=-1))
    return jnp.stack(rows, axis=-2).astype(jnp.int32)


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    out = _adaptive_max_pool2d_any(x, out_hw=_pair(output_size))
    if return_mask:
        return out, _adaptive_max_pool2d_mask(x, out_hw=_pair(output_size))
    return out


@primitive("interpolate_nearest_op")
def _interp_nearest(x, *, size):
    return jax.image.resize(x, x.shape[:2] + size, method="nearest")


@primitive("interpolate_bilinear_op")
def _interp_bilinear(x, *, size)  :
    return jax.image.resize(x, x.shape[:2] + size, method="bilinear")


@primitive("interpolate_bicubic_op")
def _interp_bicubic(x, *, size):
    return jax.image.resize(x, x.shape[:2] + size, method="cubic")


@primitive("interpolate_trilinear_op")
def _interp_trilinear(x, *, size):
    return jax.image.resize(x, x.shape[:2] + size, method="linear")


def interpolate(x, size=None, scale_factor=None, mode="nearest",
                align_corners=False, align_mode=0, data_format="NCHW", name=None):
    if size is None:
        sf = scale_factor if isinstance(scale_factor, (list, tuple)) else [scale_factor] * 2
        size = (int(x.shape[2] * sf[0]), int(x.shape[3] * sf[1]))
    else:
        if isinstance(size, Tensor):
            size = size.tolist()
        size = tuple(int(s) for s in size)
    if mode == "nearest":
        return _interp_nearest(x, size=tuple(size))
    if mode in ("bilinear", "linear"):
        return _interp_bilinear(x, size=tuple(size))
    if mode in ("bicubic", "cubic"):
        return _interp_bicubic(x, size=tuple(size))
    if mode == "area":
        # paddle's area mode IS adaptive average pooling over the target grid
        return _adaptive_avg_pool2d(x, out_hw=tuple(size))
    if mode == "trilinear" and x.ndim == 5:
        return _interp_trilinear(x, size=tuple(size))
    raise ValueError(f"interpolate: unsupported mode {mode!r}")


def upsample(x, size=None, scale_factor=None, mode="nearest", align_corners=False,
             align_mode=0, data_format="NCHW", name=None):
    return interpolate(x, size, scale_factor, mode, align_corners, align_mode, data_format)


@primitive("pixel_shuffle_op")
def _pixel_shuffle(x, *, factor):
    n, c, h, w = x.shape
    r = factor
    x = x.reshape(n, c // (r * r), r, r, h, w)
    x = jnp.transpose(x, (0, 1, 4, 2, 5, 3))
    return x.reshape(n, c // (r * r), h * r, w * r)


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    return _pixel_shuffle(x, factor=int(upscale_factor))


@primitive("unfold_op")
def _unfold(x, *, ksize, stride, padding, dilation):
    n, c, h, w = x.shape
    patches = jax.lax.conv_general_dilated_patches(
        x, filter_shape=ksize, window_strides=stride,
        padding=[(padding[0], padding[0]), (padding[1], padding[1])],
        rhs_dilation=dilation, dimension_numbers=("NCHW", "OIHW", "NCHW"),
    )
    return patches.reshape(n, patches.shape[1], -1)


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    return _unfold(x, ksize=_pair(kernel_sizes), stride=_pair(strides),
                   padding=_pair(paddings), dilation=_pair(dilations))


@primitive("cosine_similarity_op")
def _cosine_similarity(x1, x2, *, axis, eps):
    dot = jnp.sum(x1 * x2, axis=axis)
    n1 = jnp.sqrt(jnp.sum(jnp.square(x1), axis=axis))
    n2 = jnp.sqrt(jnp.sum(jnp.square(x2), axis=axis))
    return dot / jnp.maximum(n1 * n2, eps)


def cosine_similarity(x1, x2, axis=1, eps=1e-8):
    return _cosine_similarity(x1, x2, axis=int(axis), eps=float(eps))


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    from ...ops import manipulation

    return manipulation.pad(x, pad, mode, value, data_format)


# -- 1-D / 3-D pooling + conv family (round-3 API completion) ----------------
# One generic N-spatial-dim reduce_window body serves every rank; the 2-D
# code above predates it and stays as-is (hot path, already tuned).

def _tuple_n(v, n):
    if isinstance(v, (list, tuple)):
        return tuple(int(x) for x in v)
    return (int(v),) * n


@primitive("pool_nd_op")
def _pool_nd(x, *, ksize, stride, padding, kind, count_include_pad):
    nd = len(ksize)
    window = (1, 1) + ksize
    strides = (1, 1) + stride
    pads = ((0, 0), (0, 0)) + tuple((p, p) for p in padding)
    if kind == "max":
        return jax.lax.reduce_window(x, -jnp.inf, jax.lax.max, window,
                                     strides, pads)
    summed = jax.lax.reduce_window(x, 0.0, jax.lax.add, window, strides, pads)
    if count_include_pad or all(p == 0 for p in padding):
        return summed / np.prod(ksize)
    counts = jax.lax.reduce_window(jnp.ones_like(x), 0.0, jax.lax.add,
                                   window, strides, pads)
    return summed / counts


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, name=None):
    ks = _tuple_n(kernel_size, 1)
    st = _tuple_n(stride, 1) if stride is not None else ks
    out = _pool_nd(x, ksize=ks, stride=st, padding=_tuple_n(padding, 1),
                   kind="max", count_include_pad=True)
    if return_mask:
        return out, _max_pool_nd_mask(x, ksize=ks, stride=st,
                                      padding=_tuple_n(padding, 1))
    return out


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, name=None):
    ks = _tuple_n(kernel_size, 1)
    st = _tuple_n(stride, 1) if stride is not None else ks
    return _pool_nd(x, ksize=ks, stride=st, padding=_tuple_n(padding, 1),
                    kind="avg", count_include_pad=not exclusive)


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCDHW", name=None):
    ks = _tuple_n(kernel_size, 3)
    st = _tuple_n(stride, 3) if stride is not None else ks
    out = _pool_nd(x, ksize=ks, stride=st, padding=_tuple_n(padding, 3),
                   kind="max", count_include_pad=True)
    if return_mask:
        return out, _max_pool_nd_mask(x, ksize=ks, stride=st,
                                      padding=_tuple_n(padding, 3))
    return out


def avg_pool3d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, divisor_override=None, data_format="NCDHW",
               name=None):
    ks = _tuple_n(kernel_size, 3)
    st = _tuple_n(stride, 3) if stride is not None else ks
    return _pool_nd(x, ksize=ks, stride=st, padding=_tuple_n(padding, 3),
                    kind="avg", count_include_pad=not exclusive)


@primitive("max_pool_nd_mask_op", nondiff=True)
def _max_pool_nd_mask(x, *, ksize, stride, padding):
    """Flattened spatial argmax index per window (paddle's unpool mask)."""
    nd = len(ksize)
    spatial = x.shape[2:]
    flat_sizes = np.array(spatial)
    # linear index of every input position
    lin = jnp.arange(int(np.prod(spatial))).reshape(spatial)
    lin = jnp.broadcast_to(lin, x.shape)
    if any(padding):
        padcfg = [(0, 0), (0, 0)] + [(p, p) for p in padding]
        xp = jnp.pad(x, padcfg, constant_values=-jnp.inf)
        linp = jnp.pad(lin, padcfg, constant_values=-1)
    else:
        xp, linp = x, lin
    window = (1, 1) + tuple(ksize)
    strides = (1, 1) + tuple(stride)
    # argmax via reduce_window over (value, index) pairs
    def sel(a, b):
        av, ai = a
        bv, bi = b
        take_b = bv > av
        return jnp.where(take_b, bv, av), jnp.where(take_b, bi, ai)
    vals, idxs = jax.lax.reduce_window(
        (xp, linp.astype(jnp.int32)), (-jnp.inf, jnp.int32(-1)), sel,
        window, strides, [(0, 0)] * (nd + 2))
    return idxs


@primitive("max_unpool_nd_op")
def _max_unpool_nd(x, indices, *, out_spatial):
    n, c = x.shape[:2]
    flat = int(np.prod(out_spatial))
    xf = x.reshape(n, c, -1)
    idx = indices.reshape(n, c, -1).astype(jnp.int32)
    out = jnp.zeros((n, c, flat), x.dtype)
    bi = jnp.arange(n)[:, None, None]
    ci = jnp.arange(c)[None, :, None]
    out = out.at[bi, ci, idx].set(xf)
    return out.reshape((n, c) + out_spatial)


def _unpool(x, indices, kernel_size, stride, padding, output_size, nd):
    ks = _tuple_n(kernel_size, nd)
    st = _tuple_n(stride, nd) if stride is not None else ks
    if output_size is None:
        out_spatial = tuple(
            (s - 1) * st[i] + ks[i] - 2 * _tuple_n(padding, nd)[i]
            for i, s in enumerate(x.shape[2:]))
    else:
        out_spatial = tuple(int(d) for d in output_size[-nd:])
    return _max_unpool_nd(x, indices, out_spatial=out_spatial)


def max_unpool1d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCL", output_size=None, name=None):
    return _unpool(x, indices, kernel_size, stride, padding, output_size, 1)


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None, name=None):
    return _unpool(x, indices, kernel_size, stride, padding, output_size, 2)


def max_unpool3d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None, name=None):
    return _unpool(x, indices, kernel_size, stride, padding, output_size, 3)


def _adaptive_pool_nd(x, out_sizes, reduce_fn):
    spatial = x.shape[2:]
    if all(s % o == 0 for s, o in zip(spatial, out_sizes)):
        shape = list(x.shape[:2])
        axes = []
        for i, (s, o) in enumerate(zip(spatial, out_sizes)):
            shape += [o, s // o]
            axes.append(2 + 2 * i + 1)
        return reduce_fn(x.reshape(shape), tuple(axes))
    # general bins: recursive per-dim construction (rare path, small outputs)
    def build(prefix_idx, t):
        dim = len(prefix_idx)
        if dim == len(out_sizes):
            return reduce_fn(t, tuple(range(2, 2 + len(out_sizes))))
        res = []
        for a, b in _adaptive_bins(t.shape[2 + dim], out_sizes[dim]):
            idx = [slice(None)] * t.ndim
            idx[2 + dim] = slice(a, b)
            res.append(build(prefix_idx + (0,), t[tuple(idx)]))
        return jnp.stack(res, axis=2 + dim)
    return build((), x)


@primitive("adaptive_pool_nd_op")
def _adaptive_pool_nd_prim(x, *, out_sizes, kind):
    fn = {"avg": lambda v, ax: jnp.mean(v, axis=ax),
          "max": lambda v, ax: jnp.max(v, axis=ax)}[kind]
    return _adaptive_pool_nd(x, out_sizes, fn)


def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive_pool_nd_prim(x, out_sizes=_tuple_n(output_size, 1),
                                  kind="avg")


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    out = _adaptive_pool_nd_prim(x, out_sizes=_tuple_n(output_size, 1),
                                 kind="max")
    if return_mask:
        raise ValueError("adaptive_max_pool1d return_mask: use "
                         "adaptive_max_pool2d on an unsqueezed input")
    return out


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive_pool_nd_prim(x, out_sizes=_tuple_n(output_size, 3),
                                  kind="avg")


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    out = _adaptive_pool_nd_prim(x, out_sizes=_tuple_n(output_size, 3),
                                 kind="max")
    if return_mask:
        raise ValueError("adaptive_max_pool3d return_mask is not provided; "
                         "derive indices via max_pool3d(return_mask=True)")
    return out


@primitive("conv3d_op")
def _conv3d(x, w, *, stride, padding, dilation, groups):
    return jax.lax.conv_general_dilated(
        x, w, window_strides=stride, padding=[(p, p) for p in padding],
        rhs_dilation=dilation, feature_group_count=groups,
        dimension_numbers=("NCDHW", "OIDHW", "NCDHW"))


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW", name=None):
    out = _conv3d(x, weight, stride=_tuple_n(stride, 3),
                  padding=_tuple_n(padding, 3),
                  dilation=_tuple_n(dilation, 3), groups=int(groups))
    if bias is not None:
        from ...ops import manipulation

        out = out + manipulation.reshape(bias, [1, -1, 1, 1, 1])
    return out


@primitive("conv_transpose_nd_op")
def _conv_transpose_nd(x, w, *, stride, padding, dilation, out_pad, groups):
    nd = len(stride)
    g = groups
    cin = w.shape[0]
    cog = w.shape[1]
    k = w.shape[2:]
    w = jnp.flip(w, axis=tuple(range(2, 2 + nd)))
    w = w.reshape((g, cin // g, cog) + k)
    w = jnp.moveaxis(w, 2, 1).reshape((g * cog, cin // g) + k)
    pads = [
        (dilation[i] * (k[i] - 1) - padding[i],
         dilation[i] * (k[i] - 1) - padding[i] + out_pad[i])
        for i in range(nd)
    ]
    spec = "NC" + "DHW"[-nd:]
    wspec = "OI" + "DHW"[-nd:]
    return jax.lax.conv_general_dilated(
        x, w, window_strides=(1,) * nd, padding=pads, lhs_dilation=stride,
        rhs_dilation=dilation, feature_group_count=g,
        dimension_numbers=(spec, wspec, spec))


def _out_pad_from_size(x, weight, output_size, st, pd, dl, nd):
    """Same conversion conv2d_transpose does: requested output size ->
    output_padding, validated against the [min, min+stride) legal range."""
    if isinstance(output_size, Tensor):
        output_size = output_size.tolist()
    osz = _tuple_n(output_size, nd)
    ks = weight.shape[2:]
    op = tuple(
        osz[i] - ((x.shape[2 + i] - 1) * st[i] - 2 * pd[i]
                  + dl[i] * (ks[i] - 1) + 1)
        for i in range(nd))
    for i in range(nd):
        if not 0 <= op[i] < st[i]:
            raise ValueError(
                f"output_size[{i}]={osz[i]} is out of the legal range "
                "[min, min+stride) for the given input/kernel/stride")
    return op


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format="NCL", name=None):
    st, pd, dl = _tuple_n(stride, 1), _tuple_n(padding, 1), _tuple_n(dilation, 1)
    op = _tuple_n(output_padding, 1)
    if output_size is not None:
        if op != (0,):
            raise ValueError("output_padding and output_size can not be both set")
        op = _out_pad_from_size(x, weight, output_size, st, pd, dl, 1)
    out = _conv_transpose_nd(
        x, weight, stride=st, padding=pd, dilation=dl, out_pad=op,
        groups=int(groups))
    if bias is not None:
        from ...ops import manipulation

        out = out + manipulation.reshape(bias, [1, -1, 1])
    return out


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format="NCDHW", name=None):
    st, pd, dl = _tuple_n(stride, 3), _tuple_n(padding, 3), _tuple_n(dilation, 3)
    op = _tuple_n(output_padding, 3)
    if output_size is not None:
        if op != (0, 0, 0):
            raise ValueError("output_padding and output_size can not be both set")
        op = _out_pad_from_size(x, weight, output_size, st, pd, dl, 3)
    out = _conv_transpose_nd(
        x, weight, stride=st, padding=pd, dilation=dl, out_pad=op,
        groups=int(groups))
    if bias is not None:
        from ...ops import manipulation

        out = out + manipulation.reshape(bias, [1, -1, 1, 1, 1])
    return out


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    """Whole-channel dropout for 5-D inputs (reference dropout3d)."""
    if not training or p == 0.0:
        return x
    from ...framework import random as random_mod
    from ...ops import creation

    keep = creation.rand([x.shape[0], x.shape[1], 1, 1, 1]) >= p
    from ...ops import manipulation as _m

    mask = _m.cast(keep, str(x.dtype)) / (1.0 - p)
    return x * mask


def alpha_dropout(x, p=0.5, training=True, name=None):
    """SELU-preserving dropout (reference alpha_dropout): keeps mean/var of
    self-normalizing activations."""
    if not training or p == 0.0:
        return x
    from ...ops import creation, manipulation as _m
    import math as _math

    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale
    keep = creation.rand(list(x.shape)) >= p
    mask = _m.cast(keep, str(x.dtype))
    a = (1.0 / _math.sqrt((1 - p) * (1 + p * alpha_p ** 2))) \
        if (1 - p) * (1 + p * alpha_p ** 2) > 0 else 1.0
    b = -a * alpha_p * p
    return a * (x * mask + alpha_p * (1.0 - mask)) + b


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0,
                        data_format="NCHW", name=None):
    """AlexNet LRN across channels (reference local_response_norm)."""
    sq = x * x
    from ...ops import manipulation as _m

    pad_lo = (size - 1) // 2
    pad_hi = size - 1 - pad_lo
    sq_sum = _lrn_sum(sq, pad_lo=pad_lo, pad_hi=pad_hi, size=size)
    return x / (k + alpha * sq_sum) ** beta


@primitive("lrn_sum_op")
def _lrn_sum(sq, *, pad_lo, pad_hi, size):
    padded = jnp.pad(sq, [(0, 0), (pad_lo, pad_hi)] +
                     [(0, 0)] * (sq.ndim - 2))
    return jax.lax.reduce_window(
        padded, 0.0, jax.lax.add, (1, size) + (1,) * (sq.ndim - 2),
        (1,) * sq.ndim, [(0, 0)] * sq.ndim)


@primitive("bilinear_op")
def _bilinear(x1, x2, w, b):
    # w: [out, in1, in2] -> out[n,o] = x1[n,i] w[o,i,j] x2[n,j] + b
    out = jnp.einsum("ni,oij,nj->no", x1, w, x2)
    return out + b if b is not None else out


def bilinear(x1, x2, weight, bias=None, name=None):
    if bias is None:
        from ...ops import creation

        bias = creation.zeros([1, weight.shape[0]], str(weight.dtype))
    return _bilinear(x1, x2, weight, bias)



@primitive("sequence_mask_op", nondiff=True)
def _sequence_mask(lengths, *, maxlen):
    return (jnp.arange(maxlen)[None, :] <
            lengths.reshape(-1, 1)).astype(jnp.int64).reshape(
        tuple(lengths.shape) + (maxlen,))


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    """[..., L] 0/1 mask from lengths (reference sequence_mask op)."""
    from ...ops import manipulation as _m

    if maxlen is None:
        import numpy as np

        maxlen = int(np.asarray(x.numpy()).max())
    out = _sequence_mask(x, maxlen=int(maxlen))
    return _m.cast(out, dtype)


def zeropad2d(x, padding, data_format="NCHW", name=None):
    return pad(x, padding, mode="constant", value=0.0,
               data_format=data_format)


def sparse_attention(query, key, value, sparse_csr_offset, sparse_csr_columns,
                     key_padding_mask=None, attn_mask=None, name=None):
    """Block-sparse attention (reference sparse_attention op, CUDA-only).

    TPU stance: XLA has no CSR attention lowering; the supported sparse
    pattern on TPU is blockwise flash attention (kernels/flash_attention) or
    ring attention for long context. Raises with that pointer."""
    raise ValueError(
        "sparse_attention's CSR kernel is CUDA-specific; on TPU use "
        "F.scaled_dot_product_attention (flash kernel) or "
        "distributed.context_parallel ring/ulysses attention")


def relu_(x, name=None):
    from .activation import relu

    out = relu(x)
    x._rebind(out)
    return x


def softmax_(x, axis=-1, dtype=None, name=None):
    from .activation import softmax

    out = softmax(x, axis)
    x._rebind(out)
    return x


def tanh_(x, name=None):
    from ...ops import math as _math

    out = _math.tanh(x)
    x._rebind(out)
    return x
