"""paddle_tpu.nn (reference surface: python/paddle/nn/)."""
from . import functional  # noqa: F401
from . import initializer  # noqa: F401

from .layer.layers import Layer, Parameter, ParamAttr  # noqa: F401
from .layer.common import (  # noqa: F401
    Linear, Embedding, Dropout, Dropout2D, Flatten, Identity, Pad2D, Upsample,
    PixelShuffle, CosineSimilarity, Bilinear,
)
from .layer.conv import Conv1D, Conv2D, Conv2DTranspose  # noqa: F401
from .layer.norm import (  # noqa: F401
    LayerNorm, RMSNorm, BatchNorm, BatchNorm1D, BatchNorm2D, BatchNorm3D,
    SyncBatchNorm, GroupNorm, InstanceNorm2D, LocalResponseNorm,
)
from .layer.pooling import (  # noqa: F401
    MaxPool2D, AvgPool2D, AdaptiveAvgPool2D, AdaptiveMaxPool2D,
)
from .layer.activation import (  # noqa: F401
    ReLU, ReLU6, GELU, Sigmoid, Tanh, Silu, Swish, Mish, Hardswish, Hardsigmoid,
    Softsign, Tanhshrink, LogSigmoid, LeakyReLU, ELU, SELU, CELU, Hardtanh,
    Hardshrink, Softshrink, Softplus, ThresholdedReLU, Softmax, LogSoftmax,
    PReLU, Maxout,
)
from .layer.loss import (  # noqa: F401
    CrossEntropyLoss, MSELoss, L1Loss, NLLLoss, BCELoss, BCEWithLogitsLoss,
    KLDivLoss, SmoothL1Loss, MarginRankingLoss, HingeEmbeddingLoss,
    CosineEmbeddingLoss,
)
from .layer.container import Sequential, LayerList, LayerDict, ParameterList  # noqa: F401
from .layer.moe import MoELayer, ExpertMLP  # noqa: F401
from .layer.rnn import (  # noqa: F401
    RNNCellBase, SimpleRNNCell, LSTMCell, GRUCell, RNN, BiRNN,
    SimpleRNN, LSTM, GRU,
)
from .layer.transformer import (  # noqa: F401
    MultiHeadAttention, TransformerEncoderLayer, TransformerEncoder,
    TransformerDecoderLayer, TransformerDecoder, Transformer,
)
from .clip import ClipGradByValue, ClipGradByNorm, ClipGradByGlobalNorm  # noqa: F401

from .layer.extension_r3 import (  # noqa: F401
    Conv3D, Conv1DTranspose, Conv3DTranspose,
    MaxPool1D, AvgPool1D, MaxPool3D, AvgPool3D,
    AdaptiveAvgPool1D, AdaptiveMaxPool1D, AdaptiveAvgPool3D, AdaptiveMaxPool3D,
    MaxUnPool1D, MaxUnPool2D, MaxUnPool3D,
    Pad1D, Pad3D, Dropout3D, AlphaDropout, PairwiseDistance, Fold,
    InstanceNorm1D, InstanceNorm3D, CTCLoss, HSigmoidLoss,
    BeamSearchDecoder, dynamic_decode,
    Unfold, ZeroPad2D, UpsamplingNearest2D, UpsamplingBilinear2D, SpectralNorm,
)
