"""Giant streamed embedding tables: host-sharded canonical storage with a
device hot-row cache (ROADMAP direction 3 — the sparse recsys workload).

Reference: paddle/fluid/distributed/ps/table/memory_sparse_table.cc +
ssd_sparse_table.h (two-tier sparse tables with an LRU hot tier) and
the_one_ps.py's DistributedLookupTable front end. TPU-native mapping:

- **canonical rows live on the HOST** (numpy shards, row ``r`` owned by
  shard ``r % n_shards`` — the PS key-hash convention), so table capacity
  is bound by host RAM, not HBM;
- a fixed-capacity **device hot-row cache** fronts the shards: admission
  is frequency-based (ghost counters — a row must prove itself before it
  earns a slot, the TinyLFU idea), eviction is LRU among cold rows;
- a training lookup dedups the batch (``np.unique`` + inverse), serves
  hits from the cache as ONE gather, and streams only the miss rows up
  through the PR-5 ``StreamLane`` — ``prefetch(next_ids)`` starts the
  next batch's miss fetch while the current step computes, so steady
  state approaches max(compute, miss-transfer);
- gradients come back as (unique_ids, rows) pairs: the host applies a
  **sparse row update** (optimizer.sparse rules — Adagrad by default) to
  the owning shard via scatter-add, never materializing a dense
  gradient, and cached rows are refreshed in place on device so the
  cache never diverges from the shards;
- a serving view (``serving_target()``) exposes the same table through
  ``ServingEngine`` as warmed fixed-shape lookup executables
  (miss-capacity buckets), zero-retrace in steady state.

Telemetry rides the ``embedding_stream`` hub family (hit/miss rows,
streamed bytes, stall ms, admissions/evictions) and the hot cache's bytes
register as a PR-8 memory component, so OOM forensics name it.
"""
from __future__ import annotations

import contextlib
import itertools
import threading
import time
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core import autograd
from ..core.tensor import Tensor
from ..optimizer.sparse import SparseRowRule, make_row_rule

__all__ = ["ShardedEmbeddingTable", "LocalShards", "HotRowCache",
           "EmbeddingLookupTarget", "LookupReplica", "zipf_ids",
           "flush_sparse_layers", "clear_sparse_pending", "sparse_tables"]

_TABLE_NO = itertools.count(1)

_FAM = None  # lazily-bound "embedding_stream" counter family


def _fam():
    global _FAM
    if _FAM is None:
        from ..observability import family

        _FAM = family("embedding_stream", ("metric",))
    return _FAM


_MISS_HIST = None  # lazily-bound "sparse_miss_rows" histogram

# Per-lookup cold-miss counts: the distribution the online tuner derives
# serving ``miss_caps`` from (quantile-cover over the merged fleet feed).
SPARSE_MISS_BUCKETS = (0, 1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024,
                       2048, 4096, 8192)


def _miss_hist():
    global _MISS_HIST
    if _MISS_HIST is None:
        from ..observability import histogram

        _MISS_HIST = histogram("sparse_miss_rows", SPARSE_MISS_BUCKETS)
    return _MISS_HIST


_ABSTRACT_ZERO_OK = [False]


@contextlib.contextmanager
def abstract_zero_lookups():
    """Sanction tracer-ids lookups to return shape-correct ZEROS for the
    duration — the planner's abstract fwd+bwd capture uses this (it
    prices table traffic analytically); everywhere else a traced lookup
    raises so an exported program can never silently carry zero
    embeddings."""
    _ABSTRACT_ZERO_OK.append(True)
    try:
        yield
    finally:
        _ABSTRACT_ZERO_OK.pop()


def _bucket(n: int, lo: int = 8) -> int:
    """Next power-of-two >= max(n, lo): the shape-bucket contract that
    keeps the eager combine executables to a closed family instead of one
    XLA compile per distinct unique-id count."""
    b = lo
    while b < n:
        b <<= 1
    return b


def zipf_ids(n: int, rows: int, a: float = 1.2, seed: int = 0,
             shuffle_rows: bool = True) -> np.ndarray:
    """A deterministic zipf-distributed id stream over ``[0, rows)`` — the
    canonical recsys access pattern (a small hot set carries most of the
    traffic). ``shuffle_rows`` permutes which rows are hot so the hot set
    is not just the low ids (exercises the hash-sharded layout)."""
    rng = np.random.RandomState(seed)
    raw = rng.zipf(float(a), size=int(n))
    ids = (raw - 1) % int(rows)
    if shuffle_rows:
        perm = np.random.RandomState(seed + 1).permutation(int(rows))
        ids = perm[ids]
    return ids.astype(np.int64)


# ---------------------------------------------------------------------------
# canonical host storage
# ---------------------------------------------------------------------------

class LocalShards:
    """In-process host shards: row ``r`` lives in shard ``r % n_shards``
    at local index ``r // n_shards`` (the PS routing convention). All
    shards draw from ONE full-table RNG stream in bounded blocks, so the
    sharded init equals the single-shard init row-for-row and peak init
    memory is O(block)."""

    def __init__(self, rows: int, dim: int, n_shards: int = 1,
                 seed: int = 0, init_std: float = 0.01):
        self.rows, self.dim = int(rows), int(dim)
        self.n_shards = max(int(n_shards), 1)
        self.shards: List[np.ndarray] = []
        rng = np.random.RandomState(seed)
        block = max(1, min(self.rows, (1 << 22) // max(self.dim, 1)))
        for s in range(self.n_shards):
            n_own = len(range(s, self.rows, self.n_shards))
            self.shards.append(np.empty((n_own, self.dim), np.float32))
        outs = [0] * self.n_shards
        for start in range(0, self.rows, block):
            stop = min(start + block, self.rows)
            chunk = (rng.randn(stop - start, self.dim) *
                     float(init_std)).astype(np.float32)
            for s in range(self.n_shards):
                first = (s - start) % self.n_shards
                mine = chunk[first::self.n_shards]
                self.shards[s][outs[s]:outs[s] + len(mine)] = mine
                outs[s] += len(mine)
        self._state: List[Optional[Dict[str, np.ndarray]]] = \
            [None] * self.n_shards

    def _route(self, ids: np.ndarray):
        owner = ids % self.n_shards
        local = ids // self.n_shards
        return owner, local

    def gather(self, ids: np.ndarray) -> np.ndarray:
        ids = np.asarray(ids, np.int64)
        if self.n_shards == 1:
            return self.shards[0][ids].copy()
        owner, local = self._route(ids)
        out = np.empty((len(ids), self.dim), np.float32)
        for s in range(self.n_shards):
            mask = owner == s
            if mask.any():
                out[mask] = self.shards[s][local[mask]]
        return out

    def apply(self, ids: np.ndarray, grads: np.ndarray,
              rule: SparseRowRule) -> np.ndarray:
        """Sparse row update on the owning shards (``ids`` pre-deduped,
        ``grads`` pre-accumulated per unique id). Returns the POST-update
        rows in ``ids`` order so the caller can refresh its cache."""
        ids = np.asarray(ids, np.int64)
        grads = np.asarray(grads, np.float32)
        out = np.empty((len(ids), self.dim), np.float32)
        owner, local = self._route(ids)
        for s in range(self.n_shards):
            mask = owner == s
            if not mask.any():
                continue
            li = local[mask]
            if self._state[s] is None and rule.state_slots:
                self._state[s] = rule.init_state(len(self.shards[s]),
                                                 self.dim)
            st_full = self._state[s] or {}
            st = {k: v[li] for k, v in st_full.items()}
            new_rows, new_st = rule.apply(self.shards[s][li], grads[mask],
                                          st)
            self.shards[s][li] = new_rows
            for k, v in new_st.items():
                st_full[k][li] = v
            out[mask] = new_rows
        return out

    def nbytes(self) -> int:
        return sum(int(sh.nbytes) for sh in self.shards) + sum(
            int(v.nbytes) for st in self._state if st for v in st.values())


# ---------------------------------------------------------------------------
# device hot-row cache
# ---------------------------------------------------------------------------

class HotRowCache:
    """Fixed-capacity device row cache with frequency-based admission.

    - ``ghost`` counters track access frequency for rows NOT in the cache
      (the ghost list of ARC/TinyLFU): a missed row is only admitted once
      it has been seen ``admit_threshold`` times, so one-off ids never
      evict a proven-hot row. The counter table is bounded; overflow ages
      every count by half and drops zeros — deterministic for a seeded
      stream.
    - eviction is LRU among rows NOT referenced by the current batch.

    All bookkeeping is host-side python/numpy; the device side is one
    ``[capacity, dim]`` array written with one batched scatter per
    admission set and one per in-place update set.
    """

    def __init__(self, capacity: int, dim: int, admit_threshold: int = 2,
                 ghost_cap: Optional[int] = None):
        self.capacity = int(capacity)
        self.dim = int(dim)
        self.admit_threshold = max(int(admit_threshold), 1)
        self.ghost_cap = int(ghost_cap or max(8 * self.capacity, 1024))
        self.dev = jnp.zeros((self.capacity, self.dim), jnp.float32)
        self._scatter_fns: Dict[int, Any] = {}
        self._slot: Dict[int, int] = {}           # id -> slot
        self._lru: "OrderedDict[int, int]" = OrderedDict()  # id -> slot
        self._free: List[int] = list(range(self.capacity - 1, -1, -1))
        self._ghost: Dict[int, int] = {}
        self.admissions = 0
        self.evictions = 0

    def __len__(self) -> int:
        return len(self._slot)

    def nbytes(self) -> int:
        return int(self.dev.nbytes)

    def slots_of(self, ids: np.ndarray
                 ) -> Tuple[np.ndarray, np.ndarray]:
        """(hit_mask, slots) for ``ids``; slots valid where hit_mask."""
        hit = np.zeros(len(ids), bool)
        slots = np.zeros(len(ids), np.int32)
        for i, r in enumerate(ids):
            s = self._slot.get(int(r))
            if s is not None:
                hit[i] = True
                slots[i] = s
        return hit, slots

    def touch(self, ids) -> None:
        for r in ids:
            r = int(r)
            if r in self._lru:
                self._lru.move_to_end(r)

    def note_access(self, ids) -> None:
        """Bump ghost counters (admission evidence) for every accessed id
        not currently cached; bounded with halving decay."""
        for r in ids:
            r = int(r)
            if r in self._slot:
                continue
            self._ghost[r] = self._ghost.get(r, 0) + 1
        if len(self._ghost) > self.ghost_cap:
            self._ghost = {k: v // 2 for k, v in self._ghost.items()
                           if v // 2 > 0}

    def admittable(self, ids) -> List[int]:
        """The subset of missed ``ids`` whose ghost count has reached the
        admission threshold (call after ``note_access``)."""
        return [int(r) for r in ids
                if self._ghost.get(int(r), 0) >= self.admit_threshold
                and int(r) not in self._slot]

    def admit(self, ids: Sequence[int], rows: np.ndarray,
              pinned: Optional[set] = None) -> int:
        """Install ``rows[i]`` for ``ids[i]`` (cold rows evicted
        LRU-first, never a ``pinned`` id — the current batch's working
        set). Returns how many were admitted; one batched device
        scatter."""
        pinned = pinned or set()
        take_rows: List[int] = []
        take_slots: List[int] = []
        for i, r in enumerate(ids):
            r = int(r)
            if r in self._slot:
                continue
            if self._free:
                slot = self._free.pop()
            else:
                victim = None
                for cand in self._lru:          # oldest first
                    if cand not in pinned:
                        victim = cand
                        break
                if victim is None:
                    break                        # everything pinned: skip
                slot = self._lru.pop(victim)
                del self._slot[victim]
                self.evictions += 1
            self._slot[r] = slot
            self._lru[r] = slot
            self._lru.move_to_end(r)
            self._ghost.pop(r, None)
            take_rows.append(i)
            take_slots.append(slot)
        if take_rows:
            self._scatter(take_slots, np.asarray(rows, np.float32)[take_rows])
            self.admissions += len(take_rows)
        return len(take_rows)

    def _scatter(self, slots, rows_np) -> None:
        """One bucket-padded device scatter (pad slots with ``capacity``
        -> dropped), so the executable family stays closed instead of one
        XLA compile per distinct row count."""
        n = len(slots)
        b = _bucket(n)
        sl = np.full(b, self.capacity, np.int32)
        sl[:n] = np.asarray(slots, np.int32)
        rows = np.zeros((b, self.dim), np.float32)
        rows[:n] = rows_np
        f = self._scatter_fns.get(b)
        if f is None:
            from ..jit.persistent_cache import cached_jit

            def scatter(dev, sl_, rows_):
                return dev.at[sl_].set(rows_, mode="drop")

            f = cached_jit(scatter, label=f"sparse:cache_scatter:{b}")
            self._scatter_fns[b] = f
        self.dev = f(self.dev, jnp.asarray(sl), jnp.asarray(rows))

    def update_rows(self, ids: np.ndarray, rows: np.ndarray) -> int:
        """In-place refresh for the subset of ``ids`` currently cached
        (post-update coherence). One batched scatter; returns count."""
        slots, keep = [], []
        for i, r in enumerate(ids):
            s = self._slot.get(int(r))
            if s is not None:
                slots.append(s)
                keep.append(i)
        if slots:
            self._scatter(slots, np.asarray(rows, np.float32)[keep])
        return len(slots)


# ---------------------------------------------------------------------------
# the table
# ---------------------------------------------------------------------------

class ShardedEmbeddingTable:
    """Row-sharded host-resident embedding table with a device hot-row
    cache and streamed miss fetches.

    ::

        table = ShardedEmbeddingTable(10_000_000, 64, cache_rows=100_000,
                                      rule="adagrad", lr=0.05)
        out = table.lookup(ids)            # Tensor on the autograd tape
        loss.backward()
        table.flush(update=True)           # sparse row update (host)
        table.prefetch(next_ids)           # overlap next batch's misses

    ``source`` defaults to in-process ``LocalShards``; pass
    ``distributed.ps.PsShardSource`` to back the table by a
    ParameterServer gang (the multi-process PS wiring — the server then
    owns the update rule). ``overlap=False`` builds the serialized
    StreamLane twin (the A/B baseline: identical bytes, nothing hidden).
    """

    def __init__(self, num_rows: int, dim: int, *, cache_rows: int = 4096,
                 n_shards: int = 1, rule: Any = "adagrad", lr: float = 0.05,
                 seed: int = 0, init_std: float = 0.01,
                 admit_threshold: int = 2, overlap: bool = True,
                 source: Any = None, name: Optional[str] = None,
                 rule_kwargs: Optional[Dict[str, Any]] = None):
        from ..jit.offload_stream import StreamLane

        self.num_rows, self.dim = int(num_rows), int(dim)
        self.name = name or f"table#{next(_TABLE_NO)}"
        self.rule = make_row_rule(rule, lr=lr, **(rule_kwargs or {}))
        self.source = source if source is not None else LocalShards(
            num_rows, dim, n_shards=n_shards, seed=seed, init_std=init_std)
        self.cache = HotRowCache(min(int(cache_rows), self.num_rows),
                                 self.dim, admit_threshold=admit_threshold)
        self.lane = StreamLane(overlap=overlap)
        from ..analysis.lockdep import rlock as _named_rlock  # lazy

        # one table mutex guards the HotRowCache too (its slots/ghost
        # state is only ever touched under _mu)
        self._mu = _named_rlock(f"sparse.Table[{self.name}]._mu")
        self._pending: List[Tuple[np.ndarray, int, Tensor]] = []
        self._accum: List[Tuple[np.ndarray, np.ndarray]] = []
        self._prefetch: Optional[Dict[str, Any]] = None
        self._dirty_since_prefetch: set = set()
        self._combine_fns: Dict[int, Callable] = {}
        self._serve_fns: Dict[Tuple[int, int], Callable] = {}
        self._stats = {"lookups": 0, "hit_rows": 0, "miss_rows": 0,
                       "streamed_bytes": 0, "stall_ms": 0.0,
                       "prefetch_hits": 0, "prefetch_stale_rows": 0,
                       "updates": 0, "updated_rows": 0,
                       "serve_lookups": 0, "serve_hit_rows": 0,
                       "serve_miss_rows": 0}
        # memory truth: the hot cache is a named component so pd_top and
        # OOM forensics attribute its bytes (PR-8 contract)
        try:
            from ..observability.memory import register_component

            register_component(f"sparse:{self.name}:hot_cache",
                               type(self).cache_bytes, owner=self)
        except Exception:
            pass

    # -- sizing ---------------------------------------------------------------
    def table_bytes(self) -> int:
        return self.num_rows * self.dim * 4

    def cache_bytes(self) -> int:
        return self.cache.nbytes()

    # -- the combine executables ----------------------------------------------
    def _combine_fn(self, u_pad: int) -> Callable:
        f = self._combine_fns.get(u_pad)
        if f is None:
            from ..jit.persistent_cache import cached_jit

            def combine(cache, hit_slots, hit_pos, miss_rows, miss_pos):
                out = jnp.zeros((u_pad, cache.shape[1]), cache.dtype)
                hits = jnp.take(cache, hit_slots, axis=0)
                out = out.at[hit_pos].set(hits, mode="drop")
                out = out.at[miss_pos].set(miss_rows, mode="drop")
                return out

            f = cached_jit(combine, label=f"sparse:{self.name}:combine")
            self._combine_fns[u_pad] = f
        return f

    # -- miss streaming --------------------------------------------------------
    def _staged_miss_block(self, miss_ids: np.ndarray) -> np.ndarray:
        """Host-gather the miss rows into a bucket-padded ``[m_pad, dim]``
        staging block — padded HOST-side so the device only ever sees the
        closed bucket family of shapes (no per-count XLA churn)."""
        block = np.zeros((_bucket(len(miss_ids)), self.dim), np.float32)
        if len(miss_ids):
            block[:len(miss_ids)] = self.source.gather(miss_ids)
        return block

    def _fetch_miss_rows(self, miss_ids: np.ndarray):
        """Host-gather + one lane h2d of the (padded) miss block; returns
        ``(rows_dev, rows_np, nbytes, stall_ms)`` — the HOST block rides
        along so admission can slice it without a device read-back."""
        rows_np = self._staged_miss_block(miss_ids)
        handle = self.lane.submit_rows(rows_np,
                                       tag=("sparse", self.name),
                                       names=(f"{self.name}:miss",))
        t0 = time.perf_counter()
        rows_dev = handle.rows()
        stall = (time.perf_counter() - t0) * 1e3
        return rows_dev, rows_np, int(rows_np.nbytes), stall

    def prefetch(self, ids) -> None:
        """Start streaming the NEXT batch's miss rows now, while the
        current step computes — the cross-step fill of the streamed
        lookup. Consumed by the next ``lookup`` whose unique-id set
        matches; rows updated in between are re-fetched (never stale)."""
        flat = self._flat_ids(ids)
        uniq = np.unique(flat)
        token = object()
        with self._mu:
            hit, _slots = self.cache.slots_of(uniq)
            miss_ids = uniq[~hit]
            # publish a placeholder FIRST so flush() keeps the dirty set
            # live while the gather+submit below runs unlocked (a lookup
            # landing in the gap sees handle=None and falls back to the
            # synchronous miss path — slower, never wrong)
            self._prefetch = {"uniq": uniq, "miss_ids": miss_ids,
                              "handle": None, "nbytes": 0, "token": token}
            self._dirty_since_prefetch = set()
            if not len(miss_ids):
                # fully cache-covered batch: nothing to stream (the hot
                # steady state) — skip the lane round-trip entirely
                return
        # host gather + bounded-lane submit block (a full 2-deep ring
        # parks the submitter): done with the table lock RELEASED (CC001)
        # so a concurrent lookup/flush never stalls behind the ring
        rows_np = self._staged_miss_block(miss_ids)
        handle = self.lane.submit_rows(
            rows_np, tag=("sparse", self.name, "prefetch"),
            names=(f"{self.name}:prefetch",))
        with self._mu:
            pf = self._prefetch
            if pf is None or pf.get("token") is not token:
                return  # consumed/replaced mid-flight: abandon the rows
            pf.update(handle=handle, rows_np=rows_np,
                      nbytes=int(rows_np.nbytes))

    @staticmethod
    def _flat_ids(ids) -> np.ndarray:
        arr = ids.numpy() if hasattr(ids, "numpy") else np.asarray(ids)
        return np.asarray(arr, np.int64).ravel()

    def _consume_prefetch(self, uniq, miss_ids):
        """If the outstanding prefetch covers this lookup, take its rows;
        re-fetch any row updated since it was issued (staleness guard).
        Returns (miss_rows_dev, miss_rows_np, streamed_bytes, stall_ms)
        or None."""
        pf = self._prefetch
        if pf is None or not np.array_equal(pf["uniq"], uniq):
            return None
        self._prefetch = None
        dirty = self._dirty_since_prefetch
        self._dirty_since_prefetch = set()
        if pf["handle"] is None:
            if len(miss_ids):          # membership drifted: fall back
                return None
            self._bump("prefetch_hits", 1)
            return (jnp.zeros((_bucket(0), self.dim), jnp.float32),
                    np.zeros((_bucket(0), self.dim), np.float32), 0, 0.0)
        t0 = time.perf_counter()
        # dispatched-futures consume (the PR-9 cross-step fill): take the
        # rows as soon as the transfer is ISSUED and let the runtime
        # sequence the landing behind the step's own compute; a
        # post-issue failure surfaces at the next lane interaction (the
        # PR-6 sticky contract)
        rows_dev = pf["handle"].rows_dispatched()
        stall = (time.perf_counter() - t0) * 1e3
        pids = pf["miss_ids"]
        rows_np = pf["rows_np"]
        if not np.array_equal(pids, miss_ids):
            # membership drifted (a lookup ran in between): fall back
            return None
        if dirty:
            stale = [i for i, r in enumerate(pids) if int(r) in dirty]
            if stale:
                # bucket-padded patch (same closed-shape-family contract
                # as every other cache write); the host twin is patched
                # too so admission slices stay fresh
                b = _bucket(len(stale))
                idx = np.full(b, rows_dev.shape[0], np.int32)
                idx[:len(stale)] = stale
                fresh = np.zeros((b, self.dim), np.float32)
                fresh[:len(stale)] = self.source.gather(
                    pids[np.asarray(stale)])
                rows_dev = rows_dev.at[jnp.asarray(idx)].set(
                    jnp.asarray(fresh), mode="drop")
                rows_np = rows_np.copy()
                rows_np[np.asarray(stale)] = fresh[:len(stale)]
                self._bump("prefetch_stale_rows", len(stale))
        self._bump("prefetch_hits", 1)
        return rows_dev, rows_np, pf["nbytes"], stall

    def _bump(self, key, n=1):
        self._stats[key] += n
        _fam().inc((key,), n)

    # -- training lookup -------------------------------------------------------
    def lookup(self, ids, padding_idx: Optional[int] = None) -> Tensor:
        """Dedup -> cache gather + streamed misses -> one tape-bridged
        embedding op. The returned Tensor participates in eager autograd;
        the row gradient is harvested by ``flush()`` after backward as a
        (unique_ids, rows) pair — no dense gradient ever exists."""
        from ..nn.functional.common import _embedding

        raw = ids.data if isinstance(ids, Tensor) else ids
        if isinstance(raw, jax.core.Tracer):
            if _ABSTRACT_ZERO_OK[-1]:
                # sanctioned abstract capture (planner profiling under
                # abstract_zero_lookups()): the host-side dedup cannot
                # run on a tracer — a shape-correct zero lookup keeps
                # the surrounding program traceable; the planner prices
                # the real table traffic via profile.embed_stream_bytes.
                return Tensor(jnp.zeros(tuple(raw.shape) + (self.dim,),
                                        jnp.float32))
            # anywhere else (jit.to_static, jit.save export, a compiled
            # TrainStep) a traced lookup would silently BAKE ZEROS into
            # the program — fail loudly instead
            raise NotImplementedError(
                f"ShardedEmbeddingTable[{self.name}]: lookups cannot be "
                "traced into a compiled/exported program — the canonical "
                "rows are host-resident and the dedup/cache routing is "
                "host work. Serve through table.serving_target() / keep "
                "the lookup in the eager step (hapi.Model.train_batch).")
        arr = ids.numpy() if hasattr(ids, "numpy") else np.asarray(ids)
        shape = tuple(np.shape(arr))
        flat = np.asarray(arr, np.int64).ravel()
        if len(flat) and (flat.min() < 0 or flat.max() >= self.num_rows):
            raise ValueError(
                f"ShardedEmbeddingTable[{self.name}]: id out of range "
                f"[0, {self.num_rows}) in lookup")
        uniq, inverse = np.unique(flat, return_inverse=True)
        with self._mu:
            self._bump("lookups", 1)
            self.cache.note_access(uniq)
            hit, slots = self.cache.slots_of(uniq)
            miss_ids = uniq[~hit]
            self.cache.touch(uniq[hit])
            self._bump("hit_rows", int(hit.sum()))
            self._bump("miss_rows", int(len(miss_ids)))
            got = self._consume_prefetch(uniq, miss_ids)
            if got is None:
                if len(miss_ids):
                    # the synchronous miss path is deliberately serialized
                    # under the table mutex: its stall is the product
                    # (measured into stall_ms) and prefetch() exists to
                    # hide it — hoisting it would let a racing lookup
                    # double-fetch the same rows
                    got = self._fetch_miss_rows(miss_ids)  # pd-lint: disable=CC001
                else:
                    got = (jnp.zeros((_bucket(0), self.dim), jnp.float32),
                           np.zeros((_bucket(0), self.dim), np.float32),
                           0, 0.0)
            miss_dev, miss_np, nbytes, stall = got
            self._bump("streamed_bytes", nbytes)
            self._stats["stall_ms"] += stall
            _fam().inc(("stall_ms",), stall)
            # frequency-gated admission: rows that have proven themselves
            # (ghost count >= threshold) earn a slot; the current batch's
            # ids are pinned so a victim is always a cold row
            admit = self.cache.admittable(miss_ids)
            if admit:
                # slice the HOST block (no device read-back — a
                # np.asarray(miss_dev) here would block on the in-flight
                # transfer and undo the dispatched-futures overlap)
                pos = {int(r): i for i, r in enumerate(miss_ids)}
                rows_np = miss_np[[pos[r] for r in admit]]
                self.cache.admit(admit, rows_np,
                                 pinned=set(int(r) for r in uniq))
            # combine into the [U_pad, dim] unique-rows block; the miss
            # block arrives already bucket-padded from the lane
            u, h, m = len(uniq), int(hit.sum()), len(miss_ids)
            u_pad, h_pad = _bucket(u), _bucket(h)
            m_pad = int(miss_dev.shape[0])
            hit_slots = np.zeros(h_pad, np.int32)
            hit_slots[:h] = slots[hit]
            hit_pos = np.full(h_pad, u_pad, np.int32)    # pad -> dropped
            hit_pos[:h] = np.nonzero(hit)[0]
            miss_pos = np.full(m_pad, u_pad, np.int32)
            miss_pos[:m] = np.nonzero(~hit)[0]
            rows = self._combine_fn(u_pad)(
                self.cache.dev, jnp.asarray(hit_slots),
                jnp.asarray(hit_pos), miss_dev, jnp.asarray(miss_pos))
        leaf = Tensor(rows, stop_gradient=not autograd.is_grad_enabled(),
                      name=f"{self.name}:rows")
        idx = Tensor(jnp.asarray(inverse.reshape(shape or (1,))
                                 .astype(np.int32)))
        pad_u = None
        if padding_idx is not None:
            # remap: padding zeroing happens on the UNIQUE axis position
            where = np.nonzero(uniq == int(padding_idx))[0]
            pad_u = int(where[0]) if len(where) else None
        out = _embedding(leaf, idx, padding_idx=pad_u, oov="clip")
        if not leaf.stop_gradient:
            with self._mu:
                self._pending.append((uniq, len(uniq), leaf))
        if not shape:  # scalar ids looked up through the (1,) reshape
            out = out[0]
        return out

    # -- gradient application ---------------------------------------------------
    def flush(self, update: bool = True) -> int:
        """Harvest pending row gradients (post-``backward``) into the
        accumulation buffer; ``update=True`` applies the sparse row rule
        to the owning shards (and refreshes cached rows in place).
        ``update=False`` is the accumulate(k) micro-step: grads merge
        host-side and apply once at the window boundary. Returns the
        number of unique rows updated (0 when accumulating)."""
        with self._mu:
            for uniq, n, leaf in self._pending:
                g = leaf.grad
                if g is None:
                    continue
                ga = np.asarray(g.data, np.float32)[:n]
                self._accum.append((uniq, ga))
                leaf.grad = None
            self._pending.clear()
            if not update or not self._accum:
                return 0
            ids = np.concatenate([a for a, _ in self._accum])
            gs = np.concatenate([g for _, g in self._accum])
            self._accum.clear()
            uniq, inv = np.unique(ids, return_inverse=True)
            merged = np.zeros((len(uniq), self.dim), np.float32)
            np.add.at(merged, inv, gs)
            new_rows = self.source.apply(uniq, merged, self.rule)
            self.cache.update_rows(uniq, new_rows)
            if self._prefetch is not None:
                self._dirty_since_prefetch.update(int(r) for r in uniq)
            self._bump("updates", 1)
            self._bump("updated_rows", len(uniq))
            return len(uniq)

    def clear_pending(self) -> None:
        """Drop harvested + pending gradients (the NaN-skip/poisoned-window
        path: the step never happened)."""
        with self._mu:
            self._pending.clear()
            self._accum.clear()

    # -- checkpointing ----------------------------------------------------------
    def save(self, path: str) -> str:
        """Checkpoint the canonical rows + row-rule state to one ``.npz``
        (atomic rename). The table is NOT part of ``state_dict()`` — a
        table-backed Embedding has no dense Parameter — so this is the
        checkpoint surface; ``hapi.Model.save`` warns when it would
        otherwise silently drop a table. LocalShards only: a
        ``PsShardSource`` table's authority is the server gang."""
        import os

        src = self.source
        if not isinstance(src, LocalShards):
            raise NotImplementedError(
                "ShardedEmbeddingTable.save: only LocalShards-backed "
                "tables checkpoint here; a PsShardSource table's "
                "authoritative rows live server-side")
        with self._mu:
            payload: Dict[str, Any] = {
                "meta": np.asarray([self.num_rows, self.dim,
                                    src.n_shards], np.int64)}
            for s, shard in enumerate(src.shards):
                payload[f"shard_{s}"] = shard
                for k, v in (src._state[s] or {}).items():
                    payload[f"state_{s}_{k}"] = v
        if not path.endswith(".npz"):
            path = path + ".npz"
        tmp = path + ".tmp.npz"
        np.savez(tmp.removesuffix(".npz"), **payload)
        os.replace(tmp, path)
        return path

    def load(self, path: str) -> "ShardedEmbeddingTable":
        """Restore rows + row-rule state saved by ``save``; the hot
        cache is rebuilt empty (re-warmed by traffic) so it can never
        serve pre-restore rows."""
        src = self.source
        if not isinstance(src, LocalShards):
            raise NotImplementedError(
                "ShardedEmbeddingTable.load: LocalShards-backed tables "
                "only")
        if not path.endswith(".npz"):
            path = path + ".npz"
        data = np.load(path)
        rows, dim, n_shards = (int(v) for v in data["meta"])
        if (rows, dim, n_shards) != (self.num_rows, self.dim,
                                     src.n_shards):
            raise ValueError(
                f"table checkpoint shape ({rows}, {dim}, x{n_shards}) != "
                f"this table ({self.num_rows}, {self.dim}, "
                f"x{src.n_shards})")
        with self._mu:
            for s in range(n_shards):
                src.shards[s][...] = data[f"shard_{s}"]
                st = {}
                for key in data.files:
                    if key.startswith(f"state_{s}_"):
                        st[key[len(f"state_{s}_"):]] = data[key].copy()
                src._state[s] = st or None
            self.cache = HotRowCache(self.cache.capacity, self.dim,
                                     admit_threshold=self.cache
                                     .admit_threshold)
            self._pending.clear()
            self._accum.clear()
            self._prefetch = None
            self._dirty_since_prefetch = set()
        return self

    # -- serving ---------------------------------------------------------------
    def serving_target(self, miss_caps: Optional[Sequence[int]] = None
                       ) -> "EmbeddingLookupTarget":
        """An engine-native ``ServingEngine`` target: warmed fixed-shape
        lookup executables over (cache, staged-miss-bucket) inputs."""
        return EmbeddingLookupTarget(self, miss_caps=miss_caps)

    def _serve_fn(self, n_ids: int, miss_cap: int) -> Callable:
        key = (n_ids, miss_cap)
        f = self._serve_fns.get(key)
        if f is None:
            from ..jit.persistent_cache import cached_jit

            def look(cache, staged, idx):
                return jnp.take(jnp.concatenate([cache, staged], axis=0),
                                idx, axis=0)

            f = cached_jit(
                look, label=f"serving:sparse:{self.name}:{n_ids}x{miss_cap}")
            self._serve_fns[key] = f
        return f

    def serve_lookup(self, ids_np: np.ndarray, miss_caps) -> np.ndarray:
        """One fixed-shape serving lookup: dedup, read-through (no
        admission, no gradient), misses staged into the smallest fitting
        padded bucket of ``miss_caps`` (int or sorted sequence), ONE warm
        gather executable. The cap is chosen UNDER the table lock from
        the same hit/miss split the lookup serves — a concurrent
        training eviction between a pre-pick and the lookup can never
        strand a request past its bucket. ``ids_np`` keeps its shape."""
        if isinstance(miss_caps, int):
            miss_caps = (miss_caps,)
        shape = np.shape(ids_np)
        # copy before the clamp: ravel of a contiguous input is a VIEW
        # and an in-place clip would write through to the caller's array
        flat = np.array(ids_np, np.int64).ravel()
        np.clip(flat, 0, self.num_rows - 1, out=flat)
        uniq, inverse = np.unique(flat, return_inverse=True)
        with self._mu:
            self._bump("serve_lookups", 1)
            hit, slots = self.cache.slots_of(uniq)
            miss_ids = uniq[~hit]
            self.cache.touch(uniq[hit])
            self._bump("serve_hit_rows", int(hit.sum()))
            self._bump("serve_miss_rows", int(len(miss_ids)))
            miss_cap = next((c for c in miss_caps if c >= len(miss_ids)),
                            None)
            if miss_cap is None:
                raise ValueError(
                    f"serve_lookup: {len(miss_ids)} misses exceed the "
                    f"largest declared miss bucket {miss_caps[-1]}")
            staged_np = np.zeros((miss_cap, self.dim), np.float32)
            if len(miss_ids):
                staged_np[:len(miss_ids)] = self.source.gather(miss_ids)
            # per-unique source index into concat(cache, staged)
            src = np.empty(len(uniq), np.int32)
            src[hit] = slots[hit]
            src[~hit] = self.cache.capacity + np.arange(
                len(miss_ids), dtype=np.int32)
            idx = src[inverse].astype(np.int32)
            cache_dev = self.cache.dev
        # observed OUTSIDE the table lock (hub mutexes under _mu would
        # order against every other provider); feeds miss-cap derivation
        try:
            _miss_hist().observe(float(len(miss_ids)))
        except Exception:
            pass
        rows = self._serve_fn(len(idx), miss_cap)(
            cache_dev, jnp.asarray(staged_np), jnp.asarray(idx))
        return np.asarray(rows).reshape(shape + (self.dim,))

    # -- observability ----------------------------------------------------------
    def stats(self) -> Dict[str, Any]:
        with self._mu:
            s = dict(self._stats)
            s["cache_rows"] = len(self.cache)
            s["cache_capacity"] = self.cache.capacity
            s["cache_bytes"] = self.cache.nbytes()
            s["admissions"] = self.cache.admissions
            s["evictions"] = self.cache.evictions
        total = s["hit_rows"] + s["miss_rows"]
        s["hit_rate"] = round(s["hit_rows"] / total, 4) if total else 0.0
        s["table_bytes"] = self.table_bytes()
        s["lane"] = self.lane.stats()
        return s


# ---------------------------------------------------------------------------
# ServingEngine-native target
# ---------------------------------------------------------------------------

class EmbeddingLookupTarget:
    """Duck-typed ``ServingEngine`` target (``build_serving_runner``):
    the engine coalesces/pads/buckets requests as usual, and THIS object
    builds the per-bucket runner — host dedup/routing around warmed
    fixed-shape gather executables, which a plain jitted-callable target
    could not express (the dedup is host work).

    Every (batch-bucket, seq) runner pre-warms its full miss-capacity
    executable family at build time, so a warmed engine serves lookups
    with zero fresh XLA compiles and zero retraces (CI-gated)."""

    def __init__(self, table: ShardedEmbeddingTable,
                 miss_caps: Optional[Sequence[int]] = None):
        self.table = table
        self._miss_caps = tuple(sorted(set(int(c) for c in miss_caps))) \
            if miss_caps else None

    def set_miss_caps(self, miss_caps: Optional[Sequence[int]]) -> None:
        """Replace the declared miss-capacity buckets (online retune).

        Validated through the same path as serving batch buckets
        (``BucketSpec._validated``): positive ints, no duplicates,
        canonical ascending order. Only affects runners built AFTER the
        call — already-warmed runners keep the cap family they compiled
        against, so the swap is applied through an engine respec /
        rolling restart, never mid-flight."""
        if miss_caps is None:
            self._miss_caps = None
            return
        from ..serving.buckets import BucketSpec
        self._miss_caps = BucketSpec._validated("miss_caps", miss_caps)

    def caps_for(self, n_ids: int) -> Tuple[int, ...]:
        """Miss-capacity buckets for an ``n_ids`` request block. The
        terminal cap is ALWAYS ``n_ids`` (the worst case — every unique
        id a cold miss), so a declared cap list can narrow the warm set
        but never leave a miss count unservable."""
        if self._miss_caps:
            return tuple(c for c in self._miss_caps if c < n_ids) \
                + (n_ids,)
        return tuple(sorted({min(64, n_ids), min(256, n_ids), n_ids}))

    def build_serving_runner(self, bucket_b: int, key: Tuple,
                             label: Optional[str] = None) -> Callable:
        (dt, shape), = key
        n_per = 1
        for d in shape:
            n_per *= int(d)
        n_ids = bucket_b * n_per
        caps = self.caps_for(n_ids)
        table = self.table
        # AOT-warm every miss-cap executable for this bucket so steady
        # state never compiles, whatever the miss count turns out to be
        dummy_idx = jnp.zeros((n_ids,), jnp.int32)
        for cap in caps:
            table._serve_fn(n_ids, cap)(
                table.cache.dev, jnp.zeros((cap, table.dim), jnp.float32),
                dummy_idx)

        def runner(np_inputs: List[np.ndarray]) -> List[np.ndarray]:
            # serve_lookup picks the smallest warmed miss bucket UNDER
            # the table lock (a pre-pick here could race a concurrent
            # training eviction past its cap); caps always terminate at
            # the every-id-cold worst case, so every request fits
            return [table.serve_lookup(np.asarray(np_inputs[0], np.int64),
                                       caps)]

        return runner


class LookupReplica:
    """Router-facing adapter: a table-lookup ``ServingEngine`` wearing
    the replica duck surface ``serving.ReplicaRouter`` scores on —
    ``queue_depth``/``metrics.latency_percentile`` come from the engine,
    ``kv_headroom`` is the hot cache's free-slot fraction, and
    ``prefix_match_tokens`` probes how many of a request's unique ids
    are already hot HERE, so the router's affinity term routes an id set
    to the replica whose cache covers it (the embedding analog of
    prefix-cache affinity). ``max_new_tokens`` is accepted and ignored
    (lookups generate nothing)."""

    def __init__(self, engine, table: ShardedEmbeddingTable):
        self.engine = engine
        self.table = table
        self.name = engine.name
        self.metrics = engine.metrics

    def start(self):
        self.engine.start()
        return self

    def close(self, drain: bool = True):
        self.engine.close(drain=drain)

    def queue_depth(self) -> int:
        return self.engine.queue_depth()

    def kv_headroom(self) -> float:
        c = self.table.cache
        return 1.0 - len(c) / max(c.capacity, 1)

    def prefix_match_tokens(self, prompt, blocks=None) -> int:
        uniq = np.unique(np.asarray(prompt, np.int64).ravel())
        with self.table._mu:
            hit, _ = self.table.cache.slots_of(uniq)
        return int(hit.sum())

    def submit(self, prompt, max_new_tokens: int = 0, deadline_ms=None):
        return self.engine.submit([np.asarray(prompt, np.int64)],
                                  deadline_ms=deadline_ms)

    def stats(self) -> Dict[str, Any]:
        return self.engine.stats()


# ---------------------------------------------------------------------------
# layer-walk helpers (hapi integration)
# ---------------------------------------------------------------------------

def sparse_tables(network) -> List[ShardedEmbeddingTable]:
    """Every ShardedEmbeddingTable reachable from ``network``'s layer
    tree (via the ``nn.Embedding(sparse=True)`` front end's ``_table``)."""
    out: List[ShardedEmbeddingTable] = []
    seen = set()

    def walk(layer):
        t = getattr(layer, "_table", None)
        if isinstance(t, ShardedEmbeddingTable) and id(t) not in seen:
            seen.add(id(t))
            out.append(t)
        for sub in getattr(layer, "_sub_layers", {}).values():
            if sub is not None:
                walk(sub)

    if network is not None:
        walk(network)
    return out


def flush_sparse_layers(network, update: bool = True) -> int:
    """Post-``backward`` helper for HAND-WRITTEN training loops: harvest
    every sparse table's row gradients; apply the sparse updates when
    ``update`` (the accumulate(k) boundary). ``hapi.Model`` does this
    automatically (with a cached table list) — use this only when you
    own the loop. Returns rows updated."""
    n = 0
    for t in sparse_tables(network):
        n += t.flush(update=update)
    return n


def clear_sparse_pending(network) -> None:
    """Hand-written-loop twin of the NaN-skip / dropped-window path:
    discard harvested grads (``hapi.Model`` does this automatically)."""
    for t in sparse_tables(network):
        t.clear_pending()
