"""paddle.sparse (reference: python/paddle/sparse/ + paddle/phi/kernels/sparse/).

TPU-native: SparseCooTensor wraps jax.experimental.sparse.BCOO — XLA lowers
BCOO matmul to gather/segment-sum HLO (TPUs have no sparse MXU path, matching
the reference's CPU/GPU sparse kernels in spirit: a distinct storage format
whose ops produce dense results where needed).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import sparse as jsparse

from ..core.tensor import Tensor
from .embedding import (  # noqa: F401
    EmbeddingLookupTarget, HotRowCache, LocalShards, LookupReplica,
    ShardedEmbeddingTable, clear_sparse_pending, flush_sparse_layers,
    sparse_tables, zipf_ids,
)

__all__ = ["sparse_coo_tensor", "sparse_csr_tensor", "SparseCooTensor",
           "matmul", "add", "relu", "is_sparse_coo",
           "ShardedEmbeddingTable", "LocalShards", "HotRowCache",
           "EmbeddingLookupTarget", "LookupReplica", "flush_sparse_layers",
           "clear_sparse_pending", "sparse_tables", "zipf_ids"]


class SparseCooTensor:
    """COO sparse tensor (reference phi/core/sparse_coo_tensor.h)."""

    def __init__(self, bcoo: jsparse.BCOO):
        self._bcoo = bcoo

    # -- paddle surface ------------------------------------------------------
    @property
    def shape(self):
        return list(self._bcoo.shape)

    @property
    def dtype(self):
        return self._bcoo.dtype

    def indices(self) -> Tensor:
        return Tensor(jnp.swapaxes(self._bcoo.indices, 0, 1))  # [ndim, nnz]

    def values(self) -> Tensor:
        return Tensor(self._bcoo.data)

    def nnz(self) -> int:
        return int(self._bcoo.nse)

    def to_dense(self) -> Tensor:
        return Tensor(self._bcoo.todense())

    def is_sparse_coo(self):
        return True

    def coalesce(self):
        return SparseCooTensor(self._bcoo.sum_duplicates())

    def __repr__(self):
        return (f"SparseCooTensor(shape={self.shape}, nnz={self.nnz()}, "
                f"dtype={self.dtype})")


def sparse_coo_tensor(indices, values, shape=None, dtype=None,
                      place=None, stop_gradient=True):
    """Build a COO tensor (reference sparse/creation.py sparse_coo_tensor).
    indices: [ndim, nnz]; values: [nnz]."""
    idx = indices.data if isinstance(indices, Tensor) else jnp.asarray(
        np.asarray(indices))
    val = values.data if isinstance(values, Tensor) else jnp.asarray(
        np.asarray(values, dtype or "float32"))
    idx = jnp.swapaxes(idx.astype(jnp.int32), 0, 1)  # BCOO wants [nnz, ndim]
    if shape is None:
        shape = tuple(int(m) + 1 for m in np.asarray(idx).max(axis=0))
    bcoo = jsparse.BCOO((val, idx), shape=tuple(int(s) for s in shape))
    return SparseCooTensor(bcoo)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None,
                      place=None, stop_gradient=True):
    """CSR input surface; stored as (row-sorted) COO internally — BCOO is the
    only XLA-lowered sparse format (reference sparse_csr_tensor.h role)."""
    crows = np.asarray(crows.numpy() if isinstance(crows, Tensor) else crows)
    cols = np.asarray(cols.numpy() if isinstance(cols, Tensor) else cols)
    rows = np.repeat(np.arange(len(crows) - 1), np.diff(crows))
    return sparse_coo_tensor(np.stack([rows, cols]), values, shape, dtype)


def is_sparse_coo(x):
    return isinstance(x, SparseCooTensor)


def _unwrap(x):
    if isinstance(x, SparseCooTensor):
        return x._bcoo
    if isinstance(x, Tensor):
        return x.data
    return jnp.asarray(x)


def matmul(x, y, name=None):
    """sparse @ dense -> dense (reference sparse/functional matmul)."""
    a, b = _unwrap(x), _unwrap(y)
    out = a @ b
    if isinstance(out, jsparse.BCOO):
        return SparseCooTensor(out)
    return Tensor(out)


def add(x, y, name=None):
    a, b = _unwrap(x), _unwrap(y)
    if isinstance(a, jsparse.BCOO) and isinstance(b, jsparse.BCOO):
        return SparseCooTensor((a + b).sum_duplicates())
    out = (a.todense() if isinstance(a, jsparse.BCOO) else a) + \
        (b.todense() if isinstance(b, jsparse.BCOO) else b)
    return Tensor(out)


def relu(x, name=None):
    """Elementwise on stored values only (reference sparse/nn relu)."""
    if isinstance(x, SparseCooTensor):
        b = x._bcoo
        return SparseCooTensor(jsparse.BCOO((jax.nn.relu(b.data), b.indices),
                                            shape=b.shape))
    return Tensor(jax.nn.relu(_unwrap(x)))
