"""paddle.signal (reference: python/paddle/signal.py — frame/overlap_add native
ops + stft/istft composed in python). TPU-native: framing is one strided
gather; the FFT rides paddle_tpu.fft.
"""
from __future__ import annotations

import jax.numpy as jnp

from . import fft as _fft
from .core.dispatch import primitive
from .core.tensor import Tensor

__all__ = ["frame", "overlap_add", "stft", "istft"]


@primitive("signal_frame")
def _frame(x, *, frame_length, hop_length, axis):
    if axis not in (-1, x.ndim - 1, 0):
        raise ValueError("frame: axis must be 0 or -1")
    time_last = axis in (-1, x.ndim - 1)
    if not time_last:
        x = jnp.moveaxis(x, 0, -1)
    n = x.shape[-1]
    num_frames = 1 + (n - frame_length) // hop_length
    idx = (jnp.arange(frame_length)[None, :]
           + hop_length * jnp.arange(num_frames)[:, None])  # [F, L]
    out = x[..., idx]  # [..., F, L]
    out = jnp.swapaxes(out, -1, -2)  # [..., L, F] (paddle layout)
    if not time_last:
        out = jnp.moveaxis(out, (-2, -1), (0, 1))
    return out


def frame(x, frame_length, hop_length, axis=-1, name=None):
    """Slice overlapping frames (reference signal.py frame)."""
    return _frame(x, frame_length=int(frame_length), hop_length=int(hop_length),
                  axis=int(axis))


@primitive("signal_overlap_add")
def _overlap_add(x, *, hop_length, axis):
    time_last = axis in (-1, x.ndim - 1)
    if not time_last:
        x = jnp.moveaxis(x, (0, 1), (-2, -1))
    # x: [..., frame_length, num_frames]
    frame_length, num_frames = x.shape[-2], x.shape[-1]
    out_len = (num_frames - 1) * hop_length + frame_length
    batch = x.shape[:-2]
    out = jnp.zeros(batch + (out_len,), x.dtype)
    for f in range(num_frames):  # static unroll; num_frames is trace-constant
        out = out.at[..., f * hop_length: f * hop_length + frame_length].add(
            x[..., f])
    if not time_last:
        out = jnp.moveaxis(out, -1, 0)
    return out


def overlap_add(x, hop_length, axis=-1, name=None):
    return _overlap_add(x, hop_length=int(hop_length), axis=int(axis))


def stft(x, n_fft, hop_length=None, win_length=None, window=None, center=True,
         pad_mode="reflect", normalized=False, onesided=True, name=None):
    """Short-time Fourier transform (reference signal.py stft). Returns
    [..., n_fft//2+1 (or n_fft), num_frames] complex."""
    from .ops import creation, manipulation as M

    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    if window is None:
        window = creation.ones([win_length])
    if win_length < n_fft:  # center-pad window to n_fft (reference behavior)
        pad = (n_fft - win_length) // 2
        window = M.concat([creation.zeros([pad]), window,
                           creation.zeros([n_fft - win_length - pad])])
    if center:
        p = n_fft // 2
        x = Tensor(jnp.pad(x.data, [(0, 0)] * (x.ndim - 1) + [(p, p)],
                           mode=pad_mode))
    frames = frame(x, n_fft, hop_length, axis=-1)  # [..., n_fft, F]
    frames = frames * M.unsqueeze(window, [-1])
    spec_fn = _fft.rfft if onesided else _fft.fft
    spec = spec_fn(frames, n=n_fft, axis=-2)
    if normalized:
        spec = spec * (1.0 / float(n_fft) ** 0.5)
    return spec


def istft(x, n_fft, hop_length=None, win_length=None, window=None, center=True,
          normalized=False, onesided=True, length=None, return_complex=False,
          name=None):
    """Inverse STFT with window-envelope normalization (reference signal.py)."""
    from .ops import creation, manipulation as M

    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    if window is None:
        window = creation.ones([win_length])
    if win_length < n_fft:
        pad = (n_fft - win_length) // 2
        window = M.concat([creation.zeros([pad]), window,
                           creation.zeros([n_fft - win_length - pad])])
    if return_complex and onesided:
        raise ValueError("istft: return_complex=True requires onesided=False")
    if normalized:
        x = x * float(n_fft) ** 0.5
    inv_fn = _fft.irfft if onesided else _fft.ifft
    frames = inv_fn(x, n=n_fft, axis=-2)  # [..., n_fft, F]
    if not onesided and not return_complex:
        frames = Tensor(frames.data.real)
    frames = frames * M.unsqueeze(window, [-1])
    out = overlap_add(frames, hop_length, axis=-1)
    # divide by the summed squared-window envelope
    wsq = M.unsqueeze(window * window, [-1])
    num_frames = x.shape[-1]
    env = _overlap_add(jnp.broadcast_to(
        wsq.data, (n_fft, num_frames)), hop_length=hop_length, axis=-1)
    env = Tensor(jnp.where(env.data > 1e-11, env.data, 1.0))  # floor the envelope
    out = out / env
    if center:
        p = n_fft // 2
        end = out.shape[-1] - p
        out = Tensor(out.data[..., p:end])
    if length is not None:
        out = Tensor(out.data[..., :length])
    return out
