"""Comparison / logic ops (paddle.tensor.logic equivalents). All nondiff."""
from __future__ import annotations

import jax.numpy as jnp

from ..core.dispatch import primitive, get_primitive
from ..core.tensor import Tensor
from .math import _scalar_operand

_THIS = globals()

_CMP = {
    "equal": jnp.equal,
    "not_equal": jnp.not_equal,
    "greater_than": jnp.greater,
    "greater_equal": jnp.greater_equal,
    "less_than": jnp.less,
    "less_equal": jnp.less_equal,
}

for _name, _jfn in _CMP.items():
    primitive(_name, nondiff=True)(lambda x, y, _f=_jfn: _f(x, y))

    def _make(pname):
        def fn(x, y, name=None):
            if not isinstance(x, Tensor) and isinstance(y, Tensor):
                x = _scalar_operand(y, x)
            if not isinstance(y, Tensor) and isinstance(x, Tensor):
                y = _scalar_operand(x, y)
            return get_primitive(pname)(x, y)

        fn.__name__ = pname
        return fn

    _THIS[_name] = _make(_name)


@primitive("allclose_op", nondiff=True)
def _allclose(x, y, *, rtol, atol, equal_nan):
    return jnp.allclose(x, y, rtol=rtol, atol=atol, equal_nan=equal_nan)


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return _allclose(x, y, rtol=float(rtol), atol=float(atol), equal_nan=bool(equal_nan))


@primitive("isclose_op", nondiff=True)
def _isclose(x, y, *, rtol, atol, equal_nan):
    return jnp.isclose(x, y, rtol=rtol, atol=atol, equal_nan=equal_nan)


def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return _isclose(x, y, rtol=float(rtol), atol=float(atol), equal_nan=bool(equal_nan))


@primitive("equal_all_op", nondiff=True)
def _equal_all(x, y):
    return jnp.array_equal(x, y)


def equal_all(x, y, name=None):
    return _equal_all(x, y)


def is_empty(x, name=None):
    return Tensor(jnp.asarray(x.size == 0))


def is_tensor(x):
    return isinstance(x, Tensor)
