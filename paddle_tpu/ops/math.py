"""Elementwise / scalar math ops (paddle.tensor.math equivalents).

Reference surface: python/paddle/tensor/math.py (dual-path _C_ops/append_op);
here every op is one pure jax primitive dispatched through the jit cache.
Binary ops follow the reference's scalar-promotion rule: a python scalar adopts
the tensor's dtype when compatible (float scalar + int tensor promotes to the
default float dtype).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import primitive
from ..core.tensor import Tensor
from ..framework import dtype as dtype_mod

_THIS = globals()


def _scalar_operand(x, other):
    """Convert a python scalar operand to an array with paddle-style promotion."""
    dt = x.dtype if isinstance(x, Tensor) else np.dtype(np.asarray(x).dtype)
    if isinstance(other, bool):
        return jnp.asarray(other)
    if isinstance(other, int):
        if dtype_mod.is_floating(dt) or dtype_mod.is_integer(dt):
            return jnp.asarray(other, dt)
        return jnp.asarray(other)
    if isinstance(other, float):
        if dtype_mod.is_floating(dt):
            return jnp.asarray(other, dt)
        return jnp.asarray(other, dtype_mod.get_default_dtype())
    if isinstance(other, complex):
        return jnp.asarray(other)
    return other


_BINARY = {
    "add": jnp.add,
    "subtract": jnp.subtract,
    "multiply": jnp.multiply,
    "divide": jnp.true_divide,
    "floor_divide": jnp.floor_divide,
    "remainder": jnp.remainder,
    "pow_t": jnp.power,
    "maximum": jnp.maximum,
    "minimum": jnp.minimum,
    "fmax": jnp.fmax,
    "fmin": jnp.fmin,
    "atan2": jnp.arctan2,
    "heaviside": jnp.heaviside,
    "logaddexp": jnp.logaddexp,
    "hypot": jnp.hypot,
    "copysign": jnp.copysign,
    "nextafter": jnp.nextafter,
    "gcd": jnp.gcd,
    "lcm": jnp.lcm,
}

for _name, _jfn in _BINARY.items():
    _p = primitive(_name)(lambda x, y, _f=_jfn: _f(x, y))

    def _make(pname):
        from ..core.dispatch import get_primitive

        def fn(x, y, name=None):
            if not isinstance(x, Tensor) and isinstance(y, Tensor):
                x = _scalar_operand(y, x)
            if not isinstance(y, Tensor) and isinstance(x, Tensor):
                y = _scalar_operand(x, y)
            return get_primitive(pname)(x, y)

        fn.__name__ = pname
        return fn

    _THIS[_name] = _make(_name)

mod = _THIS["remainder"]
floor_mod = _THIS["remainder"]


def pow(x, y, name=None):
    return _THIS["pow_t"](x, y)


_UNARY = {
    "exp": jnp.exp,
    "expm1": jnp.expm1,
    "log": jnp.log,
    "log2": jnp.log2,
    "log10": jnp.log10,
    "log1p": jnp.log1p,
    "sqrt": jnp.sqrt,
    "rsqrt": jax.lax.rsqrt,
    "abs": jnp.abs,
    "neg": jnp.negative,
    "sign": jnp.sign,
    "sin": jnp.sin,
    "cos": jnp.cos,
    "tan": jnp.tan,
    "asin": jnp.arcsin,
    "acos": jnp.arccos,
    "atan": jnp.arctan,
    "sinh": jnp.sinh,
    "cosh": jnp.cosh,
    "tanh": jnp.tanh,
    "asinh": jnp.arcsinh,
    "acosh": jnp.arccosh,
    "atanh": jnp.arctanh,
    "floor": jnp.floor,
    "ceil": jnp.ceil,
    "round": jnp.round,
    "trunc": jnp.trunc,
    "reciprocal": jnp.reciprocal,
    "square": jnp.square,
    "erf": jax.lax.erf,
    "erfinv": jax.lax.erf_inv,
    "sigmoid": jax.nn.sigmoid,
    "digamma": jax.scipy.special.digamma,
    "lgamma": jax.scipy.special.gammaln,
    "i0": jnp.i0,
    "frac": lambda x: x - jnp.trunc(x),
    "rad2deg": jnp.rad2deg,
    "deg2rad": jnp.deg2rad,
    "conj": jnp.conj,
    "angle": jnp.angle,
    "real": jnp.real,
    "imag": jnp.imag,
    "assign": lambda x: x + 0 if jnp.issubdtype(x.dtype, jnp.number) else jnp.copy(x),
    "logit": jax.scipy.special.logit,
}

for _name, _jfn in _UNARY.items():
    _p = primitive(_name)(lambda x, _f=_jfn: _f(x))

    def _make_u(pname):
        from ..core.dispatch import get_primitive

        def fn(x, name=None):
            return get_primitive(pname)(x)

        fn.__name__ = pname
        return fn

    _THIS[_name] = _make_u(_name)

negative = _THIS["neg"]

_UNARY_NONDIFF = {
    "isnan": jnp.isnan,
    "isinf": jnp.isinf,
    "isfinite": jnp.isfinite,
    "logical_not": jnp.logical_not,
    "bitwise_not": jnp.bitwise_not,
}
for _name, _jfn in _UNARY_NONDIFF.items():
    _p = primitive(_name, nondiff=True)(lambda x, _f=_jfn: _f(x))

    def _make_un(pname):
        from ..core.dispatch import get_primitive

        def fn(x, name=None):
            return get_primitive(pname)(x)

        fn.__name__ = pname
        return fn

    _THIS[_name] = _make_un(_name)

_BINARY_NONDIFF = {
    "logical_and": jnp.logical_and,
    "logical_or": jnp.logical_or,
    "logical_xor": jnp.logical_xor,
    "bitwise_and": jnp.bitwise_and,
    "bitwise_or": jnp.bitwise_or,
    "bitwise_xor": jnp.bitwise_xor,
}
for _name, _jfn in _BINARY_NONDIFF.items():
    _p = primitive(_name, nondiff=True)(lambda x, y, _f=_jfn: _f(x, y))

    def _make_bn(pname):
        from ..core.dispatch import get_primitive

        def fn(x, y, name=None):
            return get_primitive(pname)(x, y)

        fn.__name__ = pname
        return fn

    _THIS[_name] = _make_bn(_name)


@primitive("scale")
def _scale(x, *, scale, bias, bias_after_scale):
    if bias_after_scale:
        return scale * x + bias
    return scale * (x + bias)


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    s = scale.item() if isinstance(scale, Tensor) else float(scale)
    return _scale(x, scale=s, bias=float(bias), bias_after_scale=bool(bias_after_scale))


@primitive("clip")
def _clip(x, *, min, max):
    return jnp.clip(x, min, max)


def clip(x, min=None, max=None, name=None):
    mn = min.item() if isinstance(min, Tensor) else min
    mx = max.item() if isinstance(max, Tensor) else max
    return _clip(x, min=mn, max=mx)


@primitive("add_n")
def _add_n(*xs):
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    return out


def add_n(inputs, name=None):
    if isinstance(inputs, Tensor):
        return inputs
    return _add_n(*inputs)


@primitive("cumsum")
def _cumsum(x, *, axis):
    if axis is None:
        return jnp.cumsum(x.reshape(-1))
    return jnp.cumsum(x, axis)


def cumsum(x, axis=None, dtype=None, name=None):
    out = _cumsum(x, axis=axis if axis is None else int(axis))
    if dtype is not None:
        from . import manipulation as _manip

        out = _manip.cast(out, dtype)
    return out


@primitive("cumprod")
def _cumprod(x, *, dim):
    return jnp.cumprod(x, dim)


def cumprod(x, dim=None, dtype=None, name=None):
    out = _cumprod(x, dim=int(dim))
    if dtype is not None:
        from . import manipulation as _manip

        out = _manip.cast(out, dtype)
    return out


@primitive("cummax")
def _cummax(x, *, axis):
    return jax.lax.cummax(x, axis=axis)


@primitive("cummin")
def _cummin(x, *, axis):
    return jax.lax.cummin(x, axis=axis)


def cummax(x, axis=None, dtype="int64", name=None):
    ax = -1 if axis is None else int(axis)
    vals = _cummax(x if axis is not None else x.reshape([-1]), axis=0 if axis is None else ax)
    return vals


def cummin(x, axis=None, dtype="int64", name=None):
    ax = -1 if axis is None else int(axis)
    return _cummin(x if axis is not None else x.reshape([-1]), axis=0 if axis is None else ax)


@primitive("lerp")
def _lerp(x, y, w):
    return x + w * (y - x)


def lerp(x, y, weight, name=None):
    if not isinstance(weight, Tensor):
        weight = _scalar_operand(x, float(weight))
    return _lerp(x, y, weight)


@primitive("stanh")
def _stanh(x, *, scale_a, scale_b):
    return scale_b * jnp.tanh(scale_a * x)


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return _stanh(x, scale_a=float(scale_a), scale_b=float(scale_b))


@primitive("multiply_add")
def _multiply_add(x, y, z):
    return x * y + z


def multiply_add(x, y, z):
    return _multiply_add(x, y, z)


@primitive("kron")
def _kron(x, y):
    return jnp.kron(x, y)


def kron(x, y, name=None):
    return _kron(x, y)


@primitive("trace_op")
def _trace(x, *, offset, axis1, axis2):
    return jnp.trace(x, offset, axis1, axis2)


def trace(x, offset=0, axis1=0, axis2=1, name=None):
    return _trace(x, offset=int(offset), axis1=int(axis1), axis2=int(axis2))


@primitive("diff")
def _diff(x, *, n, axis):
    return jnp.diff(x, n=n, axis=axis)


def diff(x, n=1, axis=-1, prepend=None, append=None, name=None):
    if prepend is not None or append is not None:
        from .manipulation import concat

        parts = [p for p in (prepend, x, append) if p is not None]
        x = concat(parts, axis=int(axis))
    return _diff(x, n=int(n), axis=int(axis))


@primitive("nan_to_num")
def _nan_to_num(x, *, nan, posinf, neginf):
    return jnp.nan_to_num(x, nan=nan, posinf=posinf, neginf=neginf)


def nan_to_num(x, nan=0.0, posinf=None, neginf=None, name=None):
    return _nan_to_num(x, nan=float(nan), posinf=posinf, neginf=neginf)


def increment(x, value=1.0, name=None):
    """x + value as a new tensor (reference increment_op; the reference
    mutates in place — callers here rebind, matching the inplace-variant
    convention of the dispatch layer)."""
    return add(x, value)


@primitive("renorm_op")
def _renorm(x, *, p, axis, max_norm):
    moved = jnp.moveaxis(x, axis, 0)
    flat = moved.reshape(moved.shape[0], -1)
    norms = jnp.sum(jnp.abs(flat) ** p, axis=1) ** (1.0 / p)
    scale = jnp.where(norms > max_norm, max_norm / (norms + 1e-7), 1.0)
    out = flat * scale[:, None]
    return jnp.moveaxis(out.reshape(moved.shape), 0, axis)


def renorm(x, p, axis, max_norm, name=None):
    """Clamp each sub-tensor along `axis` to p-norm <= max_norm (reference
    renorm_op)."""
    return _renorm(x, p=float(p), axis=int(axis), max_norm=float(max_norm))
