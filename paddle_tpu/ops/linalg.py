"""Linear algebra ops (paddle.tensor.linalg / paddle.linalg equivalents).

Matmuls are the MXU path: they stay un-decomposed single jax primitives so XLA
tiles them onto the systolic array directly.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import primitive
from ..core.tensor import Tensor


@primitive("matmul_v2")
def _matmul(x, y, *, transpose_x, transpose_y):
    if transpose_x:
        x = jnp.swapaxes(x, -1, -2) if x.ndim > 1 else x
    if transpose_y:
        y = jnp.swapaxes(y, -1, -2) if y.ndim > 1 else y
    return jnp.matmul(x, y)


def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    return _matmul(x, y, transpose_x=bool(transpose_x), transpose_y=bool(transpose_y))


def bmm(x, y, name=None):
    return _matmul(x, y, transpose_x=False, transpose_y=False)


@primitive("dot_op")
def _dot(x, y):
    return jnp.sum(x * y, axis=-1)


def dot(x, y, name=None):
    return _dot(x, y)


def mm(input, mat2, name=None):
    return matmul(input, mat2)


def mv(x, vec, name=None):
    return matmul(x, vec)


@primitive("addmm_op")
def _addmm(input, x, y, *, beta, alpha):
    return beta * input + alpha * jnp.matmul(x, y)


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    return _addmm(input, x, y, beta=float(beta), alpha=float(alpha))


@primitive("outer_op")
def _outer(x, y):
    return jnp.outer(x, y)


def outer(x, y, name=None):
    return _outer(x, y)


@primitive("inner_op")
def _inner(x, y):
    return jnp.inner(x, y)


def inner(x, y, name=None):
    return _inner(x, y)


@primitive("einsum_op")
def _einsum(*ops, equation):
    return jnp.einsum(equation, *ops)


def einsum(equation, *operands):
    return _einsum(*operands, equation=equation)


@primitive("p_norm")
def _norm(x, *, p, axis, keepdim):
    if p == "fro" or (p == 2 and axis is None):
        return jnp.sqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=keepdim))
    if p == np.inf:
        return jnp.max(jnp.abs(x), axis=axis, keepdims=keepdim)
    if p == -np.inf:
        return jnp.min(jnp.abs(x), axis=axis, keepdims=keepdim)
    if p == 0:
        return jnp.sum((x != 0).astype(x.dtype), axis=axis, keepdims=keepdim)
    if p == 1:
        return jnp.sum(jnp.abs(x), axis=axis, keepdims=keepdim)
    return jnp.power(jnp.sum(jnp.power(jnp.abs(x), p), axis=axis, keepdims=keepdim), 1.0 / p)


def norm(x, p="fro", axis=None, keepdim=False, name=None):
    if isinstance(axis, (list, tuple)):
        axis = tuple(int(a) for a in axis)
    elif axis is not None:
        axis = int(axis)
    return _norm(x, p=p, axis=axis, keepdim=bool(keepdim))


@primitive("dist_op")
def _dist(x, y, *, p):
    d = jnp.abs(x - y)
    if p == 0:
        return jnp.sum((d != 0).astype(x.dtype))
    if p == np.inf:
        return jnp.max(d)
    if p == -np.inf:
        return jnp.min(d)
    return jnp.power(jnp.sum(jnp.power(d, p)), 1.0 / p)


def dist(x, y, p=2, name=None):
    return _dist(x, y, p=float(p))


# -- decompositions / solvers (jnp.linalg; differentiable through jax) -------

@primitive("cholesky_op")
def _cholesky(x, *, upper):
    L = jnp.linalg.cholesky(x)
    return jnp.swapaxes(L, -1, -2) if upper else L


def cholesky(x, upper=False, name=None):
    return _cholesky(x, upper=bool(upper))


@primitive("inverse_op")
def _inv(x):
    return jnp.linalg.inv(x)


def inv(x, name=None):
    return _inv(x)


inverse = inv


@primitive("qr_op")
def _qr(x, *, mode):
    q, r = jnp.linalg.qr(x, mode=mode)
    return q, r


def qr(x, mode="reduced", name=None):
    return _qr(x, mode=mode)


@primitive("svd_op")
def _svd(x, *, full_matrices):
    return tuple(jnp.linalg.svd(x, full_matrices=full_matrices))


def svd(x, full_matrices=False, name=None):
    return _svd(x, full_matrices=bool(full_matrices))


@primitive("eigh_op")
def _eigh(x, *, UPLO):
    w, v = jnp.linalg.eigh(x, UPLO=UPLO)
    return w, v


def eigh(x, UPLO="L", name=None):
    return _eigh(x, UPLO=UPLO)


@primitive("eigvalsh_op")
def _eigvalsh(x, *, UPLO):
    return jnp.linalg.eigvalsh(x, UPLO=UPLO)


def eigvalsh(x, UPLO="L", name=None):
    return _eigvalsh(x, UPLO=UPLO)


@primitive("solve_op")
def _solve(a, b):
    return jnp.linalg.solve(a, b)


def solve(x, y, name=None):
    return _solve(x, y)


@primitive("triangular_solve_op")
def _triangular_solve(a, b, *, upper, transpose, unitriangular):
    return jax.scipy.linalg.solve_triangular(
        a, b, lower=not upper, trans=1 if transpose else 0, unit_diagonal=unitriangular
    )


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False, name=None):
    return _triangular_solve(x, y, upper=bool(upper), transpose=bool(transpose), unitriangular=bool(unitriangular))


@primitive("cholesky_solve_op")
def _cholesky_solve(b, L, *, upper):
    return jax.scipy.linalg.cho_solve((L, not upper), b)


def cholesky_solve(x, y, upper=False, name=None):
    return _cholesky_solve(x, y, upper=bool(upper))


@primitive("matrix_power_op")
def _matrix_power(x, *, n):
    return jnp.linalg.matrix_power(x, n)


def matrix_power(x, n, name=None):
    return _matrix_power(x, n=int(n))


@primitive("matrix_rank_op", nondiff=True)
def _matrix_rank(x, *, tol):
    return jnp.linalg.matrix_rank(x, rtol=tol)


def matrix_rank(x, tol=None, hermitian=False, name=None):
    return _matrix_rank(x, tol=tol)


@primitive("pinv_op")
def _pinv(x, *, rcond):
    return jnp.linalg.pinv(x, rtol=rcond)


def pinv(x, rcond=1e-15, hermitian=False, name=None):
    return _pinv(x, rcond=float(rcond))


@primitive("det_op")
def _det(x):
    return jnp.linalg.det(x)


def det(x, name=None):
    return _det(x)


@primitive("slogdet_op")
def _slogdet(x):
    sign, logdet = jnp.linalg.slogdet(x)
    return jnp.stack([sign, logdet])


def slogdet(x, name=None):
    return _slogdet(x)


@primitive("lu_op")
def _lu(x):
    lu, piv = jax.scipy.linalg.lu_factor(x)
    return lu, piv.astype(jnp.int32)


def lu(x, pivot=True, get_infos=False, name=None):
    lu_, piv = _lu(x)
    if get_infos:
        from . import creation as _c

        return lu_, piv, _c.zeros([1], "int32")
    return lu_, piv


@primitive("cross_op")
def _cross(x, y, *, axis):
    return jnp.cross(x, y, axis=axis)


def cross(x, y, axis=9, name=None):
    if axis == 9:
        axis = next((i for i, s in enumerate(x.shape) if s == 3), -1)
    return _cross(x, y, axis=int(axis))


@primitive("histogram_op", nondiff=True)
def _histogram(x, *, bins, min, max):
    hist, _ = jnp.histogram(x, bins=bins, range=(min, max) if (min != 0 or max != 0) else None)
    return hist.astype(jnp.int32)


def histogram(input, bins=100, min=0, max=0, name=None):
    return _histogram(input, bins=int(bins), min=float(min), max=float(max))


@primitive("bincount_op", nondiff=True)
def _bincount(x, *, minlength):
    return jnp.bincount(x, minlength=minlength)


@primitive("bincount_weighted_op", nondiff=True)
def _bincount_w(x, weights, *, minlength):
    n = max(minlength, 1)
    out = jnp.zeros((n,), weights.dtype)
    out = out.at[x].add(weights)
    # grow to the true max bin if it exceeds minlength (static shape needed:
    # use the full possible range via length hint)
    return out


def bincount(x, weights=None, minlength=0, name=None):
    if weights is not None:
        import numpy as np
        import jax.core as jcore

        data = x.data if hasattr(x, "data") else x
        if isinstance(data, jcore.Tracer):
            # bin count must be static under XLA: inside a trace the caller
            # supplies it via minlength (the host-max derivation needs a
            # concrete value)
            if minlength <= 0:
                raise ValueError(
                    "bincount with weights under jit/to_static needs "
                    "minlength (> max(x)) — the output length cannot depend "
                    "on traced values")
            length = int(minlength)
        else:
            xv = np.asarray(data)
            length = int(max(int(xv.max()) + 1 if xv.size else 0, minlength))
        return _bincount_w(x, weights, minlength=length)
    return _bincount(x, minlength=int(minlength))


@primitive("corrcoef_op")
def _corrcoef(x, *, rowvar):
    return jnp.corrcoef(x, rowvar=rowvar)


def corrcoef(x, rowvar=True, name=None):
    return _corrcoef(x, rowvar=bool(rowvar))


@primitive("cov_op")
def _cov(x, *, rowvar, ddof):
    return jnp.cov(x, rowvar=rowvar, ddof=ddof)


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    return _cov(x, rowvar=bool(rowvar), ddof=1 if ddof else 0)


@primitive("multi_dot_op")
def _multi_dot(*xs):
    return jnp.linalg.multi_dot(xs)


def multi_dot(x, name=None):
    return _multi_dot(*x)


@primitive("linalg_lstsq")
def _lstsq(a, b, *, rcond):
    sol, res, rank, sv = jnp.linalg.lstsq(a, b, rcond=rcond)
    return sol, res, rank.astype(jnp.int32), sv


def lstsq(x, y, rcond=None, driver=None, name=None):
    """Least squares (reference linalg.lstsq over gels)."""
    return _lstsq(x, y, rcond=rcond)


@primitive("linalg_cond")
def _cond(x, *, p):
    return jnp.linalg.cond(x, p=p)


def cond(x, p=None, name=None):
    """Condition number (reference linalg.cond)."""
    return _cond(x, p=p if p in (None, 1, -1, 2, -2) or isinstance(p, str)
                 else float(p))


def eig(x, name=None):
    """General (complex) eigendecomposition.

    Host LAPACK op: general eig has no TPU/XLA lowering and this runtime's
    PJRT tunnel forbids host callbacks, so the matrix is pulled to host,
    decomposed with numpy, and the (complex, nondifferentiable) results
    re-uploaded. Eager-only — do not call inside jit-traced code; use eigh
    for the symmetric case, which lowers natively."""
    import numpy as np

    from ..core.tensor import Tensor as _T

    arr = np.asarray(x.data if isinstance(x, _T) else x)
    cdtype = np.complex64 if arr.dtype in (np.float32, np.complex64) \
        else np.complex128
    vals, vecs = np.linalg.eig(arr)
    # complex results live on the host CPU backend: TPU tunnels may not
    # accept complex uploads, and callers consume eigenvalues host-side
    cpu = jax.devices("cpu")[0]
    return (_T(jax.device_put(vals.astype(cdtype), cpu)),
            _T(jax.device_put(vecs.astype(cdtype), cpu)))


@primitive("tensordot_op")
def _tensordot(x, y, *, axes):
    return jnp.tensordot(x, y, axes=axes)


def tensordot(x, y, axes=2, name=None):
    if isinstance(axes, (list, tuple)):
        axes = tuple(tuple(int(v) for v in a) if isinstance(a, (list, tuple))
                     else int(a) for a in axes)
    else:
        axes = int(axes)
    return _tensordot(x, y, axes=axes)


def eigvals(x, name=None):
    """General eigenvalues (host-LAPACK eager op like eig — no XLA lowering
    for the general case, results complex on the host CPU backend)."""
    import numpy as np

    from ..core.tensor import Tensor as _T

    arr = np.asarray(x.data if isinstance(x, _T) else x)
    cdtype = np.complex64 if arr.dtype in (np.float32, np.complex64) \
        else np.complex128
    vals = np.linalg.eigvals(arr)
    cpu = jax.devices("cpu")[0]
    return _T(jax.device_put(vals.astype(cdtype), cpu))


@primitive("lu_unpack_op")
def _lu_unpack(lu_data, perm, *, unpack_ludata, unpack_pivots):
    n = lu_data.shape[-2]
    m = lu_data.shape[-1]
    k = min(n, m)
    L = jnp.tril(lu_data[..., :, :k], -1) + jnp.eye(n, k, dtype=lu_data.dtype)
    U = jnp.triu(lu_data[..., :k, :])
    # pivots -> permutation matrix (sequential row swaps, LAPACK ipiv style)
    P = jnp.eye(n, dtype=lu_data.dtype)
    def swap(P, i):
        j = perm[i]
        row_i, row_j = P[i], P[j]
        P = P.at[i].set(row_j).at[j].set(row_i)
        return P
    for i in range(perm.shape[-1]):
        P = swap(P, i)
    return P.T, L, U


def lu_unpack(x, y, unpack_ludata=True, unpack_pivots=True, name=None):
    """(P, L, U) from lu()'s packed output + pivots (reference lu_unpack).
    2-D inputs only — the pivot-walk below is unbatched."""
    if x.ndim != 2:
        raise ValueError(
            f"lu_unpack supports 2-D factors only (got ndim={x.ndim}); "
            "vmap over the batch for batched unpacking")
    return _lu_unpack(x, y, unpack_ludata=bool(unpack_ludata),
                      unpack_pivots=bool(unpack_pivots))
