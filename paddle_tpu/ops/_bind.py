"""Attach the op surface to Tensor as methods + dunders.

Plays the role of the generated pybind tensor methods in the reference
(paddle/fluid/pybind/eager_method.cc). The surface itself lives in
ops/api.yaml (the api.yaml-codegen SSoT, SURVEY §7(g)); tools/gen_op_api.py
turns it into ops/_api_registry.py, which this binder walks.
"""
from __future__ import annotations

from ..core.tensor import Tensor
from . import comparison, creation, linalg, manipulation, math, reduction
from ._api_registry import DUNDERS, INPLACE, METHODS

_MODULES = {"math": math, "reduction": reduction, "manipulation": manipulation,
            "linalg": linalg, "comparison": comparison}


def _bind():
    for module_name, names in METHODS.items():
        mod = _MODULES[module_name]
        for name in names:
            fn = getattr(mod, name)
            if not hasattr(Tensor, name):
                setattr(Tensor, name, fn)

    for dunder, (module_name, op, reflected) in DUNDERS.items():
        fn = getattr(_MODULES[module_name], op)
        if reflected:
            setattr(Tensor, dunder, lambda s, o, _f=fn: _f(o, s))
        else:
            setattr(Tensor, dunder, lambda s, o, _f=fn: _f(s, o))
    Tensor.__neg__ = lambda s: math.neg(s)
    Tensor.__abs__ = lambda s: math.abs(s)
    Tensor.__invert__ = lambda s: math.logical_not(s)
    Tensor.__getitem__ = manipulation.getitem
    Tensor.__setitem__ = manipulation.setitem

    # in-place variants (paddle `op_` convention): compute out-of-place, rebind
    def _make_inplace(fn, opname):
        def inplace(self, *a, **k):
            return self._rebind(fn(self, *a, **k))

        inplace.__name__ = opname + "_"
        return inplace

    for opname in INPLACE:
        fn = next((f for mod in _MODULES.values()
                   if (f := getattr(mod, opname, None)) is not None), None)
        if fn is None:  # fail at bind time, naming the offender
            raise AttributeError(
                f"api.yaml inplace op {opname!r} resolves in no ops module")
        setattr(Tensor, opname + "_", _make_inplace(fn, opname))

    def zero_(self):
        return self._rebind(creation.zeros_like(self))

    def fill_(self, value):
        return self._rebind(creation.full_like(self, value))

    Tensor.zero_ = zero_
    Tensor.fill_ = fill_


_bind()
