"""Attach the op surface to Tensor as methods + dunders.

Plays the role of the generated pybind tensor methods in the reference
(paddle/fluid/pybind/eager_method.cc): every functional op with a leading
tensor arg becomes a Tensor method.
"""
from __future__ import annotations

from ..core.tensor import Tensor
from . import comparison, creation, linalg, manipulation, math, reduction

_METHOD_SOURCES = [math, reduction, manipulation, linalg, comparison]

_METHODS = [
    # math
    "add", "subtract", "multiply", "divide", "floor_divide", "remainder", "mod",
    "pow", "maximum", "minimum", "fmax", "fmin", "atan2", "exp", "expm1", "log",
    "log2", "log10", "log1p", "sqrt", "rsqrt", "abs", "neg", "sign", "sin",
    "cos", "tan", "asin", "acos", "atan", "sinh", "cosh", "tanh", "asinh",
    "acosh", "atanh", "floor", "ceil", "round", "trunc", "reciprocal", "square",
    "erf", "erfinv", "sigmoid", "lgamma", "digamma", "frac", "conj", "angle",
    "real", "imag", "logit", "isnan", "isinf", "isfinite", "logical_and",
    "logical_or", "logical_xor", "logical_not", "bitwise_and", "bitwise_or",
    "bitwise_xor", "bitwise_not", "scale", "clip", "cumsum", "cumprod", "lerp",
    "kron", "trace", "diff", "nan_to_num",
    # reduction
    "sum", "mean", "prod", "max", "min", "amax", "amin", "all", "any",
    "logsumexp", "std", "var", "argmax", "argmin", "median", "quantile",
    "count_nonzero", "nansum", "nanmean",
    # manipulation
    "cast", "astype", "reshape", "transpose", "t", "flatten", "squeeze",
    "unsqueeze", "split", "chunk", "unbind", "tile", "expand", "broadcast_to",
    "expand_as", "flip", "roll", "rot90", "gather", "gather_nd",
    "take_along_axis", "put_along_axis", "scatter", "scatter_nd_add",
    "index_select", "index_sample", "topk", "argsort", "sort", "unique",
    "pad", "repeat_interleave", "masked_select", "masked_fill", "nonzero",
    "moveaxis", "slice", "numel",
    # linalg
    "matmul", "bmm", "dot", "mm", "mv", "norm", "dist", "cholesky", "inverse",
    "qr", "svd", "solve", "det", "matrix_power", "cross", "outer", "inner",
    "histogram", "bincount",
    # comparison
    "equal", "not_equal", "greater_than", "greater_equal", "less_than",
    "less_equal", "allclose", "isclose", "equal_all", "is_empty",
]


def _find(name):
    for mod in _METHOD_SOURCES:
        fn = getattr(mod, name, None)
        if fn is not None:
            return fn
    raise AttributeError(name)


def _bind():
    for name in _METHODS:
        fn = _find(name)
        if not hasattr(Tensor, name):
            setattr(Tensor, name, fn)

    # dunders
    Tensor.__add__ = lambda s, o: math.add(s, o)
    Tensor.__radd__ = lambda s, o: math.add(o, s)
    Tensor.__sub__ = lambda s, o: math.subtract(s, o)
    Tensor.__rsub__ = lambda s, o: math.subtract(o, s)
    Tensor.__mul__ = lambda s, o: math.multiply(s, o)
    Tensor.__rmul__ = lambda s, o: math.multiply(o, s)
    Tensor.__truediv__ = lambda s, o: math.divide(s, o)
    Tensor.__rtruediv__ = lambda s, o: math.divide(o, s)
    Tensor.__floordiv__ = lambda s, o: math.floor_divide(s, o)
    Tensor.__rfloordiv__ = lambda s, o: math.floor_divide(o, s)
    Tensor.__mod__ = lambda s, o: math.remainder(s, o)
    Tensor.__pow__ = lambda s, o: math.pow(s, o)
    Tensor.__rpow__ = lambda s, o: math.pow(o, s)
    Tensor.__neg__ = lambda s: math.neg(s)
    Tensor.__abs__ = lambda s: math.abs(s)
    Tensor.__matmul__ = lambda s, o: linalg.matmul(s, o)
    Tensor.__rmatmul__ = lambda s, o: linalg.matmul(o, s)
    Tensor.__eq__ = lambda s, o: comparison.equal(s, o)
    Tensor.__ne__ = lambda s, o: comparison.not_equal(s, o)
    Tensor.__gt__ = lambda s, o: comparison.greater_than(s, o)
    Tensor.__ge__ = lambda s, o: comparison.greater_equal(s, o)
    Tensor.__lt__ = lambda s, o: comparison.less_than(s, o)
    Tensor.__le__ = lambda s, o: comparison.less_equal(s, o)
    Tensor.__and__ = lambda s, o: math.logical_and(s, o)
    Tensor.__or__ = lambda s, o: math.logical_or(s, o)
    Tensor.__xor__ = lambda s, o: math.logical_xor(s, o)
    Tensor.__invert__ = lambda s: math.logical_not(s)
    Tensor.__getitem__ = manipulation.getitem
    Tensor.__setitem__ = manipulation.setitem

    # in-place variants (paddle `op_` convention): compute out-of-place, rebind
    def _make_inplace(opname):
        fn = _find(opname)

        def inplace(self, *a, **k):
            return self._rebind(fn(self, *a, **k))

        inplace.__name__ = opname + "_"
        return inplace

    for opname in ["add", "subtract", "multiply", "divide", "clip", "scale",
                   "exp", "sqrt", "reciprocal", "floor", "ceil", "round",
                   "squeeze", "unsqueeze", "reshape", "flatten", "cast"]:
        setattr(Tensor, opname + "_", _make_inplace(opname))

    def zero_(self):
        from . import creation

        return self._rebind(creation.zeros_like(self))

    def fill_(self, value):
        from . import creation

        return self._rebind(creation.full_like(self, value))

    Tensor.zero_ = zero_
    Tensor.fill_ = fill_


_bind()
