"""Shape / layout / indexing ops (paddle.tensor.manipulation equivalents)."""
from __future__ import annotations

import builtins

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import primitive
from ..core.tensor import Tensor
from ..framework import dtype as dtype_mod

_DYN = "__dyn__"


@primitive("cast")
def _cast(x, *, dtype):
    return x.astype(dtype)


def cast(x, dtype):
    return _cast(x, dtype=dtype_mod.convert_dtype(dtype))


astype = cast


@primitive("reshape")
def _reshape(x, *, shape):
    return jnp.reshape(x, shape)


def reshape(x, shape, name=None):
    if isinstance(shape, Tensor):
        shape = shape.tolist()
    return _reshape(x, shape=tuple(int(s) for s in shape))


@primitive("transpose2")
def _transpose(x, *, perm):
    return jnp.transpose(x, perm)


def transpose(x, perm, name=None):
    return _transpose(x, perm=tuple(int(p) for p in perm))


def t(x, name=None):
    if x.ndim < 2:
        from . import math as _math

        return _math.assign(x)
    return transpose(x, [1, 0])


@primitive("flatten_op")
def _flatten(x, *, start_axis, stop_axis):
    shape = x.shape
    nd = len(shape)
    s = start_axis % nd if nd else 0
    e = stop_axis % nd if nd else 0
    mid = 1
    for d in shape[s : e + 1]:
        mid *= d
    return jnp.reshape(x, shape[:s] + (mid,) + shape[e + 1 :])


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    return _flatten(x, start_axis=int(start_axis), stop_axis=int(stop_axis))


@primitive("squeeze_op")
def _squeeze(x, *, axis):
    if axis is None:
        return jnp.squeeze(x)
    axes = tuple(a % x.ndim for a in axis)
    axes = tuple(a for a in axes if x.shape[a] == 1)
    return jnp.squeeze(x, axes) if axes else x


def squeeze(x, axis=None, name=None):
    if axis is not None and not isinstance(axis, (list, tuple)):
        axis = [axis]
    return _squeeze(x, axis=None if axis is None else tuple(int(a) for a in axis))


@primitive("unsqueeze_op")
def _unsqueeze(x, *, axis):
    return jnp.expand_dims(x, axis)


def unsqueeze(x, axis, name=None):
    if isinstance(axis, Tensor):
        axis = axis.tolist()
    if not isinstance(axis, (list, tuple)):
        axis = [axis]
    return _unsqueeze(x, axis=tuple(int(a) for a in axis))


@primitive("concat_op")
def _concat(*xs, axis):
    return jnp.concatenate(xs, axis=axis)


def concat(x, axis=0, name=None):
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    return _concat(*x, axis=int(axis))


@primitive("stack_op")
def _stack(*xs, axis):
    return jnp.stack(xs, axis=axis)


def stack(x, axis=0, name=None):
    return _stack(*x, axis=int(axis))


@primitive("split_op")
def _split(x, *, sections, axis):
    if isinstance(sections, int):
        return tuple(jnp.split(x, sections, axis))
    # sections list: -1 entries are inferred
    sections = list(sections)
    total = x.shape[axis]
    if -1 in sections:
        known = sum(s for s in sections if s != -1)
        sections[sections.index(-1)] = total - known
    idx = np.cumsum(sections[:-1]).tolist()
    return tuple(jnp.split(x, idx, axis))


def split(x, num_or_sections, axis=0, name=None):
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    if isinstance(num_or_sections, (list, tuple)):
        sections = tuple(int(s) for s in num_or_sections)
    else:
        sections = int(num_or_sections)
    return list(_split(x, sections=sections, axis=int(axis)))


def chunk(x, chunks, axis=0, name=None):
    return split(x, int(chunks), axis)


@primitive("unbind_op")
def _unbind(x, *, axis):
    return tuple(jnp.moveaxis(x, axis, 0))


def unbind(x, axis=0):
    return list(_unbind(x, axis=int(axis)))


@primitive("tile_op")
def _tile(x, *, repeat_times):
    return jnp.tile(x, repeat_times)


def tile(x, repeat_times, name=None):
    if isinstance(repeat_times, Tensor):
        repeat_times = repeat_times.tolist()
    return _tile(x, repeat_times=tuple(int(r) for r in repeat_times))


@primitive("expand_op")
def _expand(x, *, shape):
    shape = tuple(
        x.shape[i - (len(shape) - x.ndim)] if s in (-1,) else s for i, s in enumerate(shape)
    )
    return jnp.broadcast_to(x, shape)


def expand(x, shape, name=None):
    if isinstance(shape, Tensor):
        shape = shape.tolist()
    return _expand(x, shape=tuple(int(s) for s in shape))


def broadcast_to(x, shape, name=None):
    return expand(x, shape)


def expand_as(x, y, name=None):
    return expand(x, y.shape)


def broadcast_tensors(inputs, name=None):
    shape = jnp.broadcast_shapes(*[tuple(t.shape) for t in inputs])
    return [expand(t, shape) for t in inputs]


@primitive("flip_op")
def _flip(x, *, axis):
    return jnp.flip(x, axis)


def flip(x, axis, name=None):
    if not isinstance(axis, (list, tuple)):
        axis = [axis]
    return _flip(x, axis=tuple(int(a) for a in axis))


@primitive("roll_op")
def _roll(x, *, shifts, axis):
    return jnp.roll(x, shifts, axis)


def roll(x, shifts, axis=None, name=None):
    if isinstance(shifts, (list, tuple)):
        shifts = tuple(int(s) for s in shifts)
    else:
        shifts = int(shifts)
    if isinstance(axis, (list, tuple)):
        axis = tuple(int(a) for a in axis)
    elif axis is not None:
        axis = int(axis)
    return _roll(x, shifts=shifts, axis=axis)


@primitive("rot90")
def _rot90(x, *, k, axes):
    return jnp.rot90(x, k, axes)


def rot90(x, k=1, axes=(0, 1), name=None):
    return _rot90(x, k=int(k), axes=tuple(int(a) for a in axes))


@primitive("gather_op")
def _gather(x, index, *, axis):
    idx = index
    if idx.ndim > 1:
        idx = idx.reshape(-1)
    return jnp.take(x, idx, axis=axis)


def gather(x, index, axis=0, name=None):
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    return _gather(x, index, axis=int(axis))


@primitive("gather_nd_op")
def _gather_nd(x, index):
    # index [..., k] indexes the first k dims of x
    k = index.shape[-1]
    idx = tuple(jnp.moveaxis(index, -1, 0))
    return x[idx]


def gather_nd(x, index, name=None):
    return _gather_nd(x, index)


@primitive("take_along_axis_op")
def _take_along_axis(x, index, *, axis):
    return jnp.take_along_axis(x, index, axis=axis)


def take_along_axis(arr, indices, axis, broadcast=True):
    return _take_along_axis(arr, indices, axis=int(axis))


@primitive("put_along_axis_op")
def _put_along_axis(x, index, value, *, axis, reduce):
    if reduce == "assign":
        return jnp.put_along_axis(x, index, value, axis=axis, inplace=False)
    dims = list(range(x.ndim))
    idx = [jnp.arange(s).reshape([-1 if i == d else 1 for i in dims])
           for d, s in enumerate(index.shape)]
    idx[axis] = index
    if reduce == "add":
        return x.at[tuple(idx)].add(value)
    if reduce in ("mul", "multiply"):
        return x.at[tuple(idx)].multiply(value)
    if reduce == "amin":
        return x.at[tuple(idx)].min(value)
    if reduce == "amax":
        return x.at[tuple(idx)].max(value)
    if reduce == "mean":
        # include_self semantics: scattered cells average the original value
        # together with every scattered contribution
        total = x.at[tuple(idx)].add(value)
        cnt = jnp.zeros(x.shape, jnp.float32).at[tuple(idx)].add(1.0)
        mean = (total.astype(jnp.float32) / (cnt + 1.0)).astype(x.dtype)
        return jnp.where(cnt > 0, mean, x)
    raise ValueError(f"put_along_axis: unsupported reduce {reduce!r}")


def put_along_axis(arr, indices, values, axis, reduce="assign"):
    if not isinstance(values, Tensor):
        values = Tensor(jnp.broadcast_to(jnp.asarray(values, arr.dtype), indices.data.shape))
    return _put_along_axis(arr, indices, values, axis=int(axis), reduce=reduce)


@primitive("scatter_op")
def _scatter(x, index, updates, *, overwrite):
    if overwrite:
        return x.at[index].set(updates)
    return x.at[index].add(updates)


def scatter(x, index, updates, overwrite=True, name=None):
    return _scatter(x, index, updates, overwrite=bool(overwrite))


@primitive("scatter_nd_add_op")
def _scatter_nd_add(x, index, updates):
    idx = tuple(jnp.moveaxis(index, -1, 0))
    return x.at[idx].add(updates)


def scatter_nd_add(x, index, updates, name=None):
    return _scatter_nd_add(x, index, updates)


@primitive("index_select_op")
def _index_select(x, index, *, axis):
    return jnp.take(x, index, axis=axis)


def index_select(x, index, axis=0, name=None):
    return _index_select(x, index, axis=int(axis))


@primitive("index_sample_op")
def _index_sample(x, index):
    return jnp.take_along_axis(x, index, axis=1)


def index_sample(x, index):
    return _index_sample(x, index)


@primitive("where_op")
def _where(cond, x, y):
    return jnp.where(cond, x, y)


def where(condition, x=None, y=None, name=None):
    if x is None and y is None:
        return nonzero(condition, as_tuple=True)
    return _where(condition, x, y)


def nonzero(x, as_tuple=False):
    # Dynamic-shape op: must resolve on host (not jittable) — same constraint the
    # reference hits with LoD/dynamic outputs; done via device_get.
    arr = np.asarray(x.data if isinstance(x, Tensor) else x)
    idx = np.nonzero(arr)
    if as_tuple:
        return tuple(Tensor(jnp.asarray(i)) for i in idx)
    return Tensor(jnp.asarray(np.stack(idx, axis=1)))


def masked_select(x, mask, name=None):
    arr = np.asarray(x.data if isinstance(x, Tensor) else x)
    m = np.asarray(mask.data if isinstance(mask, Tensor) else mask)
    return Tensor(jnp.asarray(arr[m]))


@primitive("masked_fill_op")
def _masked_fill(x, mask, *, value):
    return jnp.where(mask, jnp.asarray(value, x.dtype), x)


def masked_fill(x, mask, value, name=None):
    v = value.item() if isinstance(value, Tensor) else float(value)
    return _masked_fill(x, mask, value=v)


@primitive("top_k")
def _topk_vals(x, *, k, axis, largest):
    src = x if largest else -x
    if axis not in (-1, x.ndim - 1):
        src = jnp.moveaxis(src, axis, -1)
    vals, idxs = jax.lax.top_k(src, k)
    if not largest:
        vals = -vals
    if axis not in (-1, x.ndim - 1):
        vals = jnp.moveaxis(vals, -1, axis)
        idxs = jnp.moveaxis(idxs, -1, axis)
    return vals, idxs.astype(jnp.int32)


@_topk_vals.defvjp
def _topk_vjp(ct, out, primals, *, k, axis, largest):
    x = primals[0]
    vals, idxs = out
    ct_vals, _ = ct
    g = jnp.zeros(x.shape, x.dtype)
    if axis in (-1, x.ndim - 1):
        g = jnp.put_along_axis(g, idxs.astype(jnp.int32), ct_vals.astype(x.dtype), axis=-1, inplace=False)
    else:
        gm = jnp.moveaxis(g, axis, -1)
        gm = jnp.put_along_axis(
            gm, jnp.moveaxis(idxs, axis, -1).astype(jnp.int32),
            jnp.moveaxis(ct_vals, axis, -1).astype(x.dtype), axis=-1, inplace=False)
        g = jnp.moveaxis(gm, -1, axis)
    return (g,)


def topk(x, k, axis=-1, largest=True, sorted=True, name=None):
    if isinstance(k, Tensor):
        k = int(k.item())
    return _topk_vals(x, k=int(k), axis=int(axis), largest=bool(largest))


@primitive("argsort_op", nondiff=True)
def _argsort(x, *, axis, descending):
    idx = jnp.argsort(x, axis=axis)
    if descending:
        idx = jnp.flip(idx, axis=axis)
    return idx.astype(jnp.int32)


def argsort(x, axis=-1, descending=False, name=None):
    return _argsort(x, axis=int(axis), descending=bool(descending))


@primitive("sort_op")
def _sort(x, *, axis, descending):
    out = jnp.sort(x, axis=axis)
    if descending:
        out = jnp.flip(out, axis=axis)
    return out


def sort(x, axis=-1, descending=False, name=None):
    return _sort(x, axis=int(axis), descending=bool(descending))


def unique(x, return_index=False, return_inverse=False, return_counts=False, axis=None, dtype="int64", name=None):
    # dynamic-shape: host path
    arr = np.asarray(x.data if isinstance(x, Tensor) else x)
    res = np.unique(
        arr, return_index=return_index, return_inverse=return_inverse,
        return_counts=return_counts, axis=axis)
    if not isinstance(res, tuple):
        return Tensor(jnp.asarray(res))
    return tuple(Tensor(jnp.asarray(r)) for r in res)


@primitive("pad_op")
def _pad(x, *, pad, mode, value):
    if mode == "constant":
        return jnp.pad(x, pad, mode="constant", constant_values=value)
    return jnp.pad(x, pad, mode=mode)


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    if isinstance(pad, Tensor):
        pad = pad.tolist()
    pad = [int(p) for p in pad]
    nd = x.ndim
    if len(pad) == 2 * nd:
        # full-form paddle pad: [d0_l, d0_r, d1_l, d1_r, ...]
        widths = tuple((pad[2 * i], pad[2 * i + 1]) for i in range(nd))
    else:
        # NCHW-style: pad applies to the last len(pad)//2 spatial dims, reversed pairs
        k = len(pad) // 2
        widths = [(0, 0)] * (nd - k)
        for i in range(k):
            widths.append((pad[2 * (k - 1 - i)], pad[2 * (k - 1 - i) + 1]))
        widths = tuple(widths)
    mode_map = {"constant": "constant", "reflect": "reflect", "replicate": "edge", "circular": "wrap"}
    return _pad(x, pad=widths, mode=mode_map[mode], value=float(value))


@primitive("repeat_interleave_op")
def _repeat_interleave(x, *, repeats, axis):
    return jnp.repeat(x, repeats, axis=axis)


def repeat_interleave(x, repeats, axis=None, name=None):
    if isinstance(repeats, Tensor):
        repeats = tuple(repeats.tolist())
    return _repeat_interleave(x, repeats=repeats, axis=None if axis is None else int(axis))


@primitive("one_hot_op")
def _one_hot(x, *, num_classes, dtype):
    return jax.nn.one_hot(x, num_classes, dtype=dtype)


def one_hot(x, num_classes, name=None):
    return _one_hot(x, num_classes=int(num_classes), dtype=dtype_mod.get_default_dtype())


@primitive("getitem")
def _getitem_static(x, *, idx):
    return x[idx]


@primitive("getitem_dyn")
def _getitem_dyn(x, *dyn, tmpl):
    it = iter(dyn)
    full = tuple(next(it) if e == _DYN else e for e in tmpl)
    return x[full]


class _Slice:
    """Hashable stand-in for slice objects inside attr keys."""

    __slots__ = ("start", "stop", "step")

    def __init__(self, s):
        self.start, self.stop, self.step = s.start, s.stop, s.step


def _encode_idx(idx):
    """Split an index tuple into (static template, dynamic tensor args)."""
    if not isinstance(idx, tuple):
        idx = (idx,)
    tmpl, dyn = [], []
    for e in idx:
        if isinstance(e, Tensor):
            dyn.append(e)
            tmpl.append(_DYN)
        elif isinstance(e, (np.ndarray, jax.Array)):
            dyn.append(Tensor(jnp.asarray(e)))
            tmpl.append(_DYN)
        elif isinstance(e, (int, np.integer)):
            tmpl.append(int(e))
        elif isinstance(e, (builtins.slice, type(None), type(Ellipsis), bool)):
            tmpl.append(e)
        elif isinstance(e, (list,)):
            dyn.append(Tensor(jnp.asarray(e)))
            tmpl.append(_DYN)
        else:
            raise TypeError(f"Unsupported index element: {e!r}")
    return tuple(tmpl), dyn


def getitem(x, idx):
    tmpl, dyn = _encode_idx(idx)
    if dyn:
        return _getitem_dyn(x, *dyn, tmpl=tmpl)
    # slices aren't hashable keys pre-3.12; rebuild tuple inside via attr encoding
    enc = tuple(("slice", e.start, e.stop, e.step) if isinstance(e, builtins.slice) else e for e in tmpl)
    return _getitem_enc(x, idx=enc)


@primitive("getitem_enc")
def _getitem_enc(x, *, idx):
    dec = tuple(builtins.slice(e[1], e[2], e[3]) if isinstance(e, tuple) and e and e[0] == "slice" else e for e in idx)
    return x[dec]


@primitive("setitem_enc")
def _setitem_enc(x, v, *, idx):
    dec = tuple(builtins.slice(e[1], e[2], e[3]) if isinstance(e, tuple) and e and e[0] == "slice" else e for e in idx)
    return x.at[dec].set(v.astype(x.dtype))


@primitive("setitem_dyn")
def _setitem_dyn(x, v, *dyn, tmpl):
    it = iter(dyn)
    full = tuple(next(it) if e == _DYN else e for e in tmpl)
    return x.at[full].set(v.astype(x.dtype))


def setitem(x, idx, value):
    if not isinstance(value, Tensor):
        value = Tensor(jnp.asarray(value))
    tmpl, dyn = _encode_idx(idx)
    if dyn:
        new = _setitem_dyn(x, value, *dyn, tmpl=tmpl)
    else:
        enc = tuple(("slice", e.start, e.stop, e.step) if isinstance(e, builtins.slice) else e for e in tmpl)
        new = _setitem_enc(x, value, idx=enc)
    x._rebind(new)
    return x


@primitive("as_real")
def _as_real(x):
    return jnp.stack([jnp.real(x), jnp.imag(x)], axis=-1)


def as_real(x, name=None):
    return _as_real(x)


@primitive("as_complex")
def _as_complex(x):
    return jax.lax.complex(x[..., 0], x[..., 1])


def as_complex(x, name=None):
    return _as_complex(x)


@primitive("moveaxis_op")
def _moveaxis(x, *, source, destination):
    return jnp.moveaxis(x, source, destination)


def moveaxis(x, source, destination, name=None):
    s = tuple(source) if isinstance(source, (list, tuple)) else int(source)
    d = tuple(destination) if isinstance(destination, (list, tuple)) else int(destination)
    return _moveaxis(x, source=s, destination=d)


@primitive("slice_op")
def _slice_op(x, *, axes, starts, ends):
    out = x
    for ax, st, en in zip(axes, starts, ends):
        sl = [builtins.slice(None)] * x.ndim
        sl[ax] = builtins.slice(st, en)
        out = out[tuple(sl)]
    return out


def slice(x, axes, starts, ends):
    return _slice_op(x, axes=tuple(int(a) for a in axes), starts=tuple(int(s) for s in starts),
                     ends=tuple(int(e) for e in ends))


def numel(x, name=None):
    return Tensor(jnp.asarray(x.size, jnp.int32))


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    shard_size = (index_num + nshards - 1) // nshards
    return _shard_index(input, shard_size=shard_size, shard_id=int(shard_id), ignore_value=int(ignore_value))


@primitive("shard_index_op", nondiff=True)
def _shard_index(x, *, shard_size, shard_id, ignore_value):
    in_shard = (x // shard_size) == shard_id
    return jnp.where(in_shard, x % shard_size, ignore_value)


@primitive("searchsorted_op", nondiff=True)
def _searchsorted(sorted_seq, values, *, right):
    return jnp.searchsorted(sorted_seq, values,
                            side="right" if right else "left").astype(jnp.int64)


def searchsorted(sorted_sequence, values, out_int32=False, right=False, name=None):
    out = _searchsorted(sorted_sequence, values, right=bool(right))
    return cast(out, "int32") if out_int32 else out


def bucketize(x, sorted_sequence, out_int32=False, right=False, name=None):
    """reference ops: bucketize == searchsorted with 1-D boundaries."""
    return searchsorted(sorted_sequence, x, out_int32=out_int32, right=right)


@primitive("diag_embed_op")
def _diag_embed(x, *, offset, dim1, dim2):
    idx = jnp.arange(x.shape[-1])
    rows = idx + max(-offset, 0)
    cols = idx + max(offset, 0)
    out = jnp.zeros(x.shape[:-1] + (x.shape[-1] + abs(offset),) * 2, x.dtype)
    out = out.at[..., rows, cols].set(x)
    if (dim1, dim2) != (-2, -1):
        out = jnp.moveaxis(out, (-2, -1), (dim1, dim2))
    return out


def diag_embed(input, offset=0, dim1=-2, dim2=-1, name=None):
    return _diag_embed(input, offset=int(offset), dim1=int(dim1), dim2=int(dim2))


def unique_consecutive(x, return_inverse=False, return_counts=False, axis=None,
                       dtype="int64", name=None):
    """Eager host op (data-dependent output size, like unique). axis=None
    flattens; an integer axis deduplicates consecutive equal slices."""
    import numpy as np

    arr = np.asarray(x.data if isinstance(x, Tensor) else x)
    if axis is None:
        arr = arr.reshape(-1)
        changed = arr[1:] != arr[:-1]
    else:
        axis = int(axis) % arr.ndim
        arr = np.moveaxis(arr, axis, 0)
        changed = np.any(arr[1:] != arr[:-1],
                         axis=tuple(range(1, arr.ndim)))
    keep = np.concatenate([[True], changed]) if arr.shape[0] else \
        np.zeros((0,), bool)
    uniq = arr[keep]
    if axis is not None:
        uniq = np.moveaxis(uniq, 0, axis)
    results = [Tensor(jnp.asarray(uniq))]
    if return_inverse:
        results.append(Tensor(jnp.asarray(np.cumsum(keep) - 1)))
    if return_counts:
        idx = np.flatnonzero(keep)
        counts = np.diff(np.append(idx, arr.shape[0]))
        results.append(Tensor(jnp.asarray(counts)))
    return results[0] if len(results) == 1 else tuple(results)


@primitive("take_op")
def _take(x, index, *, mode):
    flat = x.reshape(-1)
    n = flat.shape[0]
    idx = index.astype(jnp.int32)
    if mode == "wrap":
        idx = ((idx % n) + n) % n
    elif mode == "clip":
        idx = jnp.clip(idx, 0, n - 1)
    else:  # raise-mode: negative python-style indices
        idx = jnp.where(idx < 0, idx + n, idx)
    return flat[idx]


def take(x, index, mode="raise", name=None):
    if mode not in ("raise", "wrap", "clip"):
        raise ValueError("mode must be raise/wrap/clip")
    if mode == "raise":
        # eager host bounds check: XLA gathers clamp silently
        import numpy as np

        idx = np.asarray(index.data if isinstance(index, Tensor) else index)
        n = int(np.prod(x.shape))
        if idx.size and (idx.min() < -n or idx.max() >= n):
            raise IndexError(
                f"take: index out of range for tensor with {n} elements "
                f"(got min {idx.min()}, max {idx.max()})")
    return _take(x, index, mode=mode)


@primitive("index_add_op")
def _index_add(x, index, value, *, axis):
    axis = axis % x.ndim
    return x.at[(builtins.slice(None),) * axis + (index,)].add(value)


def index_add(x, index, axis, value, name=None):
    return _index_add(x, index, value, axis=int(axis))


@primitive("index_put_op")
def _index_put(x, value, *indices, accumulate):
    idx = tuple(indices)
    if accumulate:
        return x.at[idx].add(value)
    return x.at[idx].set(value)


def index_put(x, indices, value, accumulate=False, name=None):
    return _index_put(x, value, *indices, accumulate=bool(accumulate))


@primitive("diagonal_op")
def _diagonal(x, *, offset, axis1, axis2):
    return jnp.diagonal(x, offset=offset, axis1=axis1, axis2=axis2)


def diagonal(x, offset=0, axis1=0, axis2=1, name=None):
    return _diagonal(x, offset=int(offset), axis1=int(axis1),
                     axis2=int(axis2))


@primitive("kthvalue_op")
def _kthvalue(x, *, k, axis, keepdim):
    vals = jnp.sort(x, axis=axis)
    idxs = jnp.argsort(x, axis=axis)
    v = jnp.take(vals, k - 1, axis=axis)
    i = jnp.take(idxs, k - 1, axis=axis)
    if keepdim:
        v = jnp.expand_dims(v, axis)
        i = jnp.expand_dims(i, axis)
    return v, i.astype(jnp.int64)


def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    """k-th smallest value (+index) along axis (reference kthvalue_op)."""
    return _kthvalue(x, k=int(k), axis=int(axis), keepdim=bool(keepdim))


@primitive("mode_op")
def _mode(x, *, axis, keepdim):
    # most frequent value: sort, count equal runs via comparisons (static
    # shapes, no data-dependent control flow)
    sx = jnp.sort(x, axis=axis)
    n = x.shape[axis]
    sx_m = jnp.moveaxis(sx, axis, -1)
    eq = sx_m[..., :, None] == sx_m[..., None, :]
    counts = eq.sum(-1)  # for each sorted position: multiplicity
    best = jnp.argmax(counts, axis=-1)
    val = jnp.take_along_axis(sx_m, best[..., None], axis=-1)[..., 0]
    # index: LAST occurrence in the original order (paddle contract)
    xm = jnp.moveaxis(x, axis, -1)
    match = xm == val[..., None]
    pos = jnp.arange(n)
    idx = jnp.max(jnp.where(match, pos, -1), axis=-1)
    if keepdim:
        val = val[..., None]
        idx = idx[..., None]
        val = jnp.moveaxis(val, -1, axis)
        idx = jnp.moveaxis(idx, -1, axis)
    return val, idx.astype(jnp.int64)


def mode(x, axis=-1, keepdim=False, name=None):
    return _mode(x, axis=int(axis), keepdim=bool(keepdim))


@primitive("multiplex_op")
def _multiplex(index, *inputs):
    stacked = jnp.stack(inputs)  # [n, batch, ...]
    rows = jnp.arange(inputs[0].shape[0])
    return stacked[index.reshape(-1).astype(jnp.int32), rows]


def multiplex(inputs, index, name=None):
    """Row r of the output comes from inputs[index[r]][r] (reference
    multiplex_op)."""
    return _multiplex(index, *inputs)


@primitive("scatter_nd_op")
def _scatter_nd(index, updates, *, shape):
    zeros = jnp.zeros(shape, updates.dtype)
    idx = tuple(jnp.moveaxis(index, -1, 0))
    return zeros.at[idx].add(updates)


def scatter_nd(index, updates, shape, name=None):
    return _scatter_nd(index, updates, shape=tuple(int(d) for d in shape))


@primitive("strided_slice_op")
def _strided_slice(x, *, axes, starts, ends, strides):
    sl = [builtins.slice(None)] * x.ndim
    for ax, st, en, sr in zip(axes, starts, ends, strides):
        sl[ax] = builtins.slice(st, en, sr)
    return x[tuple(sl)]


def strided_slice(x, axes, starts, ends, strides, name=None):
    def _vals(v):
        return tuple(int(e.item() if hasattr(e, "item") else e) for e in v)

    return _strided_slice(x, axes=_vals(axes), starts=_vals(starts),
                          ends=_vals(ends), strides=_vals(strides))


def unstack(x, axis=0, num=None, name=None):
    """Split along axis into that many rank-reduced tensors."""
    n = num or x.shape[axis]
    outs = []
    for i in range(n):
        outs.append(squeeze(slice(x, [axis], [i], [i + 1]), [axis]))
    return outs


@primitive("crop_op")
def _crop(x, *, offsets, lengths):
    sl = tuple(builtins.slice(o, o + l) for o, l in zip(offsets, lengths))
    return x[sl]


def crop(x, shape=None, offsets=None, name=None):
    """Crop a sub-box (reference crop_tensor_op): shape = output lengths
    (-1 = to the end), offsets default to 0."""
    ndim = x.ndim
    if offsets is None:
        offsets = [0] * ndim
    offsets = [int(o.item() if hasattr(o, "item") else o) for o in offsets]
    if shape is None:
        lengths = [int(d) - o for d, o in zip(x.shape, offsets)]
    else:
        lengths = [int(s.item() if hasattr(s, "item") else s) for s in shape]
        lengths = [int(x.shape[i]) - offsets[i] if l == -1 else l
                   for i, l in enumerate(lengths)]
    return _crop(x, offsets=tuple(offsets), lengths=tuple(lengths))


def reverse(x, axis, name=None):
    """Deprecated paddle alias of flip."""
    return flip(x, axis)
