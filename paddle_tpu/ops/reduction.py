"""Reduction ops (paddle.tensor.math reduce_* / stat equivalents)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import primitive
from ..core.tensor import Tensor
from ..framework import dtype as dtype_mod


def _norm_axis(axis):
    if axis is None:
        return None
    if isinstance(axis, Tensor):
        axis = axis.tolist()
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


def _make_reduce(name, jfn, nondiff=False):
    p = primitive(name, nondiff=nondiff)(
        lambda x, _f=jfn, *, axis, keepdim: _f(x, axis=axis, keepdims=keepdim)
    )

    def fn(x, axis=None, keepdim=False, name=None):
        return p(x, axis=_norm_axis(axis), keepdim=bool(keepdim))

    fn.__name__ = name
    return fn


sum = _make_reduce("reduce_sum", jnp.sum)
mean = _make_reduce("reduce_mean", jnp.mean)
prod = _make_reduce("reduce_prod", jnp.prod)
max = _make_reduce("reduce_max", jnp.max)
min = _make_reduce("reduce_min", jnp.min)
amax = _make_reduce("reduce_amax", jnp.max)
amin = _make_reduce("reduce_amin", jnp.min)
all = _make_reduce("reduce_all", jnp.all, nondiff=True)
any = _make_reduce("reduce_any", jnp.any, nondiff=True)
nansum = _make_reduce("reduce_nansum", jnp.nansum)
nanmean = _make_reduce("reduce_nanmean", jnp.nanmean)


@primitive("logsumexp")
def _logsumexp(x, *, axis, keepdim):
    return jax.scipy.special.logsumexp(x, axis=axis, keepdims=keepdim)


def logsumexp(x, axis=None, keepdim=False, name=None):
    return _logsumexp(x, axis=_norm_axis(axis), keepdim=bool(keepdim))


@primitive("reduce_std")
def _std(x, *, axis, unbiased, keepdim):
    return jnp.std(x, axis=axis, ddof=1 if unbiased else 0, keepdims=keepdim)


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    return _std(x, axis=_norm_axis(axis), unbiased=bool(unbiased), keepdim=bool(keepdim))


@primitive("reduce_var")
def _var(x, *, axis, unbiased, keepdim):
    return jnp.var(x, axis=axis, ddof=1 if unbiased else 0, keepdims=keepdim)


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    return _var(x, axis=_norm_axis(axis), unbiased=bool(unbiased), keepdim=bool(keepdim))


@primitive("arg_max", nondiff=True)
def _argmax(x, *, axis, keepdim, dtype):
    if axis is None:
        out = jnp.argmax(x.reshape(-1))
        return out.astype(dtype)
    out = jnp.argmax(x, axis=axis, keepdims=keepdim)
    return out.astype(dtype)


def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    return _argmax(
        x, axis=_norm_axis(axis), keepdim=bool(keepdim), dtype=dtype_mod.convert_dtype(dtype)
    )


@primitive("arg_min", nondiff=True)
def _argmin(x, *, axis, keepdim, dtype):
    if axis is None:
        return jnp.argmin(x.reshape(-1)).astype(dtype)
    return jnp.argmin(x, axis=axis, keepdims=keepdim).astype(dtype)


def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    return _argmin(
        x, axis=_norm_axis(axis), keepdim=bool(keepdim), dtype=dtype_mod.convert_dtype(dtype)
    )


@primitive("median")
def _median(x, *, axis, keepdim):
    return jnp.median(x, axis=axis, keepdims=keepdim)


def median(x, axis=None, keepdim=False, name=None):
    return _median(x, axis=_norm_axis(axis), keepdim=bool(keepdim))


@primitive("quantile")
def _quantile(x, *, q, axis, keepdim):
    return jnp.quantile(x, jnp.asarray(q), axis=axis, keepdims=keepdim)


def quantile(x, q, axis=None, keepdim=False, name=None):
    return _quantile(x, q=float(q) if np.isscalar(q) else tuple(q), axis=_norm_axis(axis), keepdim=bool(keepdim))


@primitive("count_nonzero", nondiff=True)
def _count_nonzero(x, *, axis, keepdim):
    return jnp.count_nonzero(x, axis=axis, keepdims=keepdim).astype(jnp.int32)


def count_nonzero(x, axis=None, keepdim=False, name=None):
    return _count_nonzero(x, axis=_norm_axis(axis), keepdim=bool(keepdim))
