"""Op corpus: the PHI-kernels equivalent (paddle/phi/kernels -> pure jax fns)."""
from . import creation, math, reduction, manipulation, linalg, comparison  # noqa: F401
from . import _bind  # noqa: F401  (attaches Tensor methods)

from .creation import *  # noqa: F401,F403
from .math import *  # noqa: F401,F403
from .reduction import *  # noqa: F401,F403
from .manipulation import *  # noqa: F401,F403
from .linalg import *  # noqa: F401,F403
from .comparison import *  # noqa: F401,F403


def grad_kind(name: str) -> str:
    """Gradient mechanism declared for a primitive in ops/backward.yaml
    (the reference's forward/backward api pairing): 'auto_vjp',
    'custom_vjp', or 'nondiff'. Raises KeyError for undeclared primitives —
    new ops must declare their grad story in the YAML."""
    from ._grad_registry import GRAD_KIND

    return GRAD_KIND[name]
