"""Op corpus: the PHI-kernels equivalent (paddle/phi/kernels -> pure jax fns)."""
from . import creation, math, reduction, manipulation, linalg, comparison  # noqa: F401
from . import _bind  # noqa: F401  (attaches Tensor methods)

from .creation import *  # noqa: F401,F403
from .math import *  # noqa: F401,F403
from .reduction import *  # noqa: F401,F403
from .manipulation import *  # noqa: F401,F403
from .linalg import *  # noqa: F401,F403
from .comparison import *  # noqa: F401,F403
