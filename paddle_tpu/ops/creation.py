"""Tensor creation ops (paddle.tensor.creation equivalents).

Reference surface: python/paddle/tensor/creation.py. Here each op is a pure jax
function; shapes/dtypes are static attrs so XLA sees fully static programs.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import primitive
from ..core.tensor import Tensor
from ..framework import dtype as dtype_mod
from ..framework import random as random_mod


def _dt(dtype, default=None):
    d = dtype_mod.convert_dtype(dtype)
    if d is None:
        d = default if default is not None else dtype_mod.get_default_dtype()
    return d


def to_tensor(data, dtype=None, place=None, stop_gradient=True):
    if isinstance(data, Tensor):
        arr = data.data
    else:
        arr = jnp.asarray(data)
    if dtype is not None:
        arr = arr.astype(dtype_mod.convert_dtype(dtype))
    elif not isinstance(data, (jax.Array, np.ndarray, Tensor)):
        # python scalars/lists: default-float like the reference's to_tensor
        if jnp.issubdtype(arr.dtype, jnp.floating):
            arr = arr.astype(dtype_mod.get_default_dtype())
    return Tensor(arr, stop_gradient=stop_gradient)


@primitive("full", nondiff=True)
def _full(*, shape, fill_value, dtype):
    return jnp.full(shape, fill_value, dtype)


def full(shape, fill_value, dtype=None, name=None):
    if isinstance(fill_value, Tensor):
        fill_value = fill_value.item()
    d = _dt(dtype, dtype_mod.float32 if isinstance(fill_value, float) else None)
    if dtype is None and isinstance(fill_value, (bool, int)):
        d = dtype_mod.bool_ if isinstance(fill_value, bool) else dtype_mod.convert_dtype("int64")
    return _full(shape=tuple(int(s) for s in shape), fill_value=fill_value, dtype=d)


def zeros(shape, dtype=None, name=None):
    return _full(shape=tuple(int(s) for s in shape), fill_value=0, dtype=_dt(dtype))


def ones(shape, dtype=None, name=None):
    return _full(shape=tuple(int(s) for s in shape), fill_value=1, dtype=_dt(dtype))


@primitive("full_like", nondiff=True)
def _full_like(x, *, fill_value, dtype):
    return jnp.full(x.shape, fill_value, dtype or x.dtype)


def full_like(x, fill_value, dtype=None, name=None):
    return _full_like(x, fill_value=fill_value, dtype=dtype_mod.convert_dtype(dtype))


def zeros_like(x, dtype=None, name=None):
    return full_like(x, 0, dtype)


def ones_like(x, dtype=None, name=None):
    return full_like(x, 1, dtype)


@primitive("arange", nondiff=True)
def _arange(*, start, end, step, dtype):
    return jnp.arange(start, end, step, dtype)


def arange(start=0, end=None, step=1, dtype=None, name=None):
    if end is None:
        start, end = 0, start
    for v in (start, end, step):
        if isinstance(v, Tensor):
            raise TypeError("arange bounds must be python numbers")
    if dtype is None:
        dtype = (
            dtype_mod.int64
            if all(isinstance(v, int) for v in (start, end, step))
            else dtype_mod.get_default_dtype()
        )
    return _arange(start=start, end=end, step=step, dtype=dtype_mod.convert_dtype(dtype))


@primitive("linspace", nondiff=True)
def _linspace(*, start, stop, num, dtype):
    return jnp.linspace(start, stop, num, dtype=dtype)


def linspace(start, stop, num, dtype=None, name=None):
    start = start.item() if isinstance(start, Tensor) else start
    stop = stop.item() if isinstance(stop, Tensor) else stop
    return _linspace(start=start, stop=stop, num=int(num), dtype=_dt(dtype))


@primitive("eye", nondiff=True)
def _eye(*, num_rows, num_columns, dtype):
    return jnp.eye(num_rows, num_columns, dtype=dtype)


def eye(num_rows, num_columns=None, dtype=None, name=None):
    return _eye(
        num_rows=int(num_rows),
        num_columns=int(num_columns) if num_columns is not None else int(num_rows),
        dtype=_dt(dtype),
    )


def empty(shape, dtype=None, name=None):
    return zeros(shape, dtype)


def empty_like(x, dtype=None, name=None):
    return zeros_like(x, dtype)


@primitive("tril")
def _tril(x, *, diagonal):
    return jnp.tril(x, diagonal)


def tril(x, diagonal=0, name=None):
    return _tril(x, diagonal=int(diagonal))


@primitive("triu")
def _triu(x, *, diagonal):
    return jnp.triu(x, diagonal)


def triu(x, diagonal=0, name=None):
    return _triu(x, diagonal=int(diagonal))


@primitive("diag")
def _diag(x, *, offset, padding_value):
    out = jnp.diag(x, offset)
    if x.ndim == 1 and padding_value != 0:
        # padding_value fills the OFF-diagonal cells of the built matrix
        # (reference diag_v2 contract; ignored for the 2-D extract case)
        n = out.shape[0]
        mask = jnp.eye(n, k=offset, dtype=bool)
        out = jnp.where(mask, out, jnp.asarray(padding_value, out.dtype))
    return out


def diag(x, offset=0, padding_value=0, name=None):
    return _diag(x, offset=int(offset), padding_value=float(padding_value))


@primitive("diagflat")
def _diagflat(x, *, offset):
    return jnp.diagflat(x, offset)


def diagflat(x, offset=0, name=None):
    return _diagflat(x, offset=int(offset))


def meshgrid(*args, **kwargs):
    from . import manipulation as _manip

    tensors = args[0] if len(args) == 1 and isinstance(args[0], (list, tuple)) else args
    return list(_meshgrid(*tensors))


@primitive("meshgrid")
def _meshgrid(*xs):
    return tuple(jnp.meshgrid(*xs, indexing="ij"))


def assign(x, output=None):
    from . import math as _math

    out = _math.assign(x)
    if output is not None:
        output._rebind(out)
        return output
    return out


def clone(x, name=None):
    from . import math as _math

    return _math.assign(x)


@primitive("tril_indices", nondiff=True)
def _tril_indices(*, row, col, offset):
    return jnp.stack(jnp.tril_indices(row, offset, col))


def tril_indices(row, col=None, offset=0, dtype="int64"):
    out = _tril_indices(row=int(row), col=int(col if col is not None else row), offset=int(offset))
    from . import manipulation as _manip

    return _manip.cast(out, dtype)


@primitive("triu_indices", nondiff=True)
def _triu_indices(*, row, col, offset):
    return jnp.stack(jnp.triu_indices(row, offset, col))


def triu_indices(row, col=None, offset=0, dtype="int64"):
    out = _triu_indices(row=int(row), col=int(col if col is not None else row), offset=int(offset))
    from . import manipulation as _manip

    return _manip.cast(out, dtype)


def complex(real, imag, name=None):
    from . import math as _math

    return _complex(real, imag)


@primitive("complex")
def _complex(re, im):
    return jax.lax.complex(re, im)


# -- random creation ---------------------------------------------------------

@primitive("uniform_random", nondiff=True)
def _uniform(key, *, shape, dtype, min, max):
    return jax.random.uniform(key, shape, dtype, minval=min, maxval=max)


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None):
    key = random_mod.next_key() if seed == 0 else jax.random.key(seed)
    return _uniform(key, shape=tuple(int(s) for s in shape), dtype=_dt(dtype), min=float(min), max=float(max))


def rand(shape, dtype=None, name=None):
    return uniform(shape, dtype, 0.0, 1.0)


@primitive("gaussian_random", nondiff=True)
def _normal(key, *, shape, dtype, mean, std):
    return mean + std * jax.random.normal(key, shape, dtype)


def normal(mean=0.0, std=1.0, shape=None, name=None):
    assert shape is not None, "normal() requires shape"
    return _normal(
        random_mod.next_key(),
        shape=tuple(int(s) for s in shape),
        dtype=dtype_mod.get_default_dtype(),
        mean=float(mean),
        std=float(std),
    )


def randn(shape, dtype=None, name=None):
    return _normal(
        random_mod.next_key(),
        shape=tuple(int(s) for s in shape),
        dtype=_dt(dtype),
        mean=0.0,
        std=1.0,
    )


def standard_normal(shape, dtype=None, name=None):
    return randn(shape, dtype)


@primitive("randint", nondiff=True)
def _randint(key, *, low, high, shape, dtype):
    return jax.random.randint(key, shape, low, high, dtype)


def randint(low=0, high=None, shape=(1,), dtype=None, name=None):
    if high is None:
        low, high = 0, low
    return _randint(
        random_mod.next_key(),
        low=int(low),
        high=int(high),
        shape=tuple(int(s) for s in shape),
        dtype=dtype_mod.convert_dtype(dtype) or dtype_mod.convert_dtype("int64"),
    )


@primitive("randperm", nondiff=True)
def _randperm(key, *, n, dtype):
    return jax.random.permutation(key, n).astype(dtype)


def randperm(n, dtype="int64", name=None):
    return _randperm(random_mod.next_key(), n=int(n), dtype=dtype_mod.convert_dtype(dtype))


@primitive("bernoulli", nondiff=True)
def _bernoulli_p(p, key):
    return jax.random.bernoulli(key, p).astype(p.dtype)


def bernoulli(x, name=None):
    return _bernoulli_p(x, random_mod.next_key())


@primitive("multinomial", nondiff=True)
def _multinomial(logp, key, *, num_samples, replacement):
    return jax.random.categorical(key, logp, axis=-1, shape=logp.shape[:-1] + (num_samples,))


def multinomial(x, num_samples=1, replacement=False, name=None):
    logp = jnp.log(jnp.asarray(x.data if isinstance(x, Tensor) else x))
    return _multinomial(
        Tensor(logp), random_mod.next_key(), num_samples=int(num_samples), replacement=bool(replacement)
    )


def randint_like(x, low=0, high=None, dtype=None, name=None):
    """Random ints with x's shape (reference randint_like)."""
    return randint(low, high, tuple(int(d) for d in x.shape),
                   dtype=dtype or str(x.dtype))


@primitive("poisson_op", nondiff=True)
def _poisson(key, x):
    return jax.random.poisson(key, x, dtype=jnp.int32).astype(x.dtype)


def poisson(x, name=None):
    """Element-wise Poisson draw with rate x (reference poisson op); the
    PRNG key rides as a traced operand so repeated calls reuse one compile
    (same pattern as _uniform above)."""
    return _poisson(random_mod.next_key(), x)


def create_parameter(shape, dtype="float32", name=None, attr=None,
                     is_bias=False, default_initializer=None):
    """Free-standing Parameter (reference layers/tensor.py create_parameter)."""
    from ..nn.layer.layers import Parameter
    from ..framework import random as random_mod

    if default_initializer is not None:
        t = zeros(shape, dtype)
        default_initializer(t)
        data = t.data
    elif is_bias:
        data = jnp.zeros(tuple(int(s) for s in shape),
                         dtype_mod.convert_dtype(dtype))
    else:
        import math as _m

        fan_in = int(shape[0]) if shape else 1
        bound = _m.sqrt(6.0 / max(fan_in, 1))
        t = rand(shape, dtype)
        data = (t.data * 2.0 - 1.0) * bound
    return Parameter(data, name=name)
