"""Device memory stats (reference roles: paddle/fluid/memory/stats.h
StatRegistry + python/paddle/device/cuda/__init__.py memory_allocated /
max_memory_allocated). TPU-native: PJRT owns the allocator, so stats come from
`Device.memory_stats()` (live HBM) plus a host-side registry of live
jax.Arrays for per-process accounting on backends without PJRT stats (CPU).

Every read degrades gracefully: `memory_stats()` ALWAYS returns a dict
carrying `bytes_in_use` and `peak_bytes_in_use` (an empty-stats backend
yields the live-array fallback, a partial-stats backend is normalized), so
consumers never KeyError on a backend change. The telemetry layer consumes
this module through `observability.memory.MemoryMonitor`.
"""
from __future__ import annotations

from typing import Dict, Optional

import jax

__all__ = ["memory_allocated", "max_memory_allocated", "memory_reserved",
           "memory_stats", "reset_max_memory_allocated", "empty_cache"]

_PEAK: Dict[int, int] = {}        # process-sampled high watermark per device
_PEAK_FLOOR: Dict[int, int] = {}  # allocator peak at the last reset (masked:
#                                   PJRT peaks are monotonic, resets are not)


def _device(device=None):
    if device is None:
        return jax.devices()[0]
    if isinstance(device, int):
        return jax.devices()[device]
    if isinstance(device, str):  # paddle-style ids: "gpu:0", "tpu:1", "cpu"
        platform, _, idx = device.partition(":")
        idx = int(idx) if idx else 0
        try:
            return jax.devices(platform)[idx]
        except RuntimeError:  # platform not present: fall back to default set
            return jax.devices()[idx]
    return device


def memory_stats(device=None) -> dict:
    """Raw PJRT stats dict, normalized to always carry ``bytes_in_use``
    and ``peak_bytes_in_use`` (ints); backends that expose none (or a
    partial dict) degrade to the live-array fallback / filled defaults
    instead of KeyError'ing their consumers."""
    dev = _device(device)
    try:
        stats = dev.memory_stats()
    except Exception:
        stats = None
    if stats:
        out = dict(stats)
        try:
            in_use = int(out.get("bytes_in_use", 0))
        except (TypeError, ValueError):
            in_use = 0
        out["bytes_in_use"] = in_use
        try:
            out["peak_bytes_in_use"] = int(
                out.get("peak_bytes_in_use", in_use))
        except (TypeError, ValueError):
            out["peak_bytes_in_use"] = in_use
        return out
    total = sum(
        arr.nbytes for arr in jax.live_arrays()
        if not getattr(arr, "is_deleted", lambda: False)()
        and dev in getattr(arr, "devices", lambda: set())())
    return {"bytes_in_use": int(total),
            "peak_bytes_in_use": max(int(total), _PEAK.get(dev.id, 0))}


def memory_allocated(device=None) -> int:
    """Bytes currently allocated on the device (reference
    device/cuda memory_allocated)."""
    stats = memory_stats(device)
    used = int(stats.get("bytes_in_use", 0))
    dev = _device(device)
    _PEAK[dev.id] = max(_PEAK.get(dev.id, 0), used)
    return used


def max_memory_allocated(device=None) -> int:
    """High watermark since process start — or since the last
    ``reset_max_memory_allocated(device)``."""
    stats = memory_stats(device)
    dev = _device(device)
    peak = int(stats.get("peak_bytes_in_use", 0))
    floor = _PEAK_FLOOR.get(dev.id, 0)
    if peak <= floor:
        # the allocator's (monotonic) peak predates the reset: masked; the
        # process-sampled watermark below carries the post-reset truth
        peak = 0
    return max(peak, _PEAK.get(dev.id, 0),
               int(stats.get("bytes_in_use", 0)))


def reset_max_memory_allocated(device=None) -> None:
    """Restart the high watermark at the CURRENT allocation (reference
    device/cuda reset_max_memory_allocated). PJRT's own peak counter is
    monotonic, so the pre-reset peak is masked rather than cleared — a
    later ``max_memory_allocated`` reports only highs reached after this
    call (seeded with the current ``bytes_in_use``)."""
    dev = _device(device)
    try:
        stats = dev.memory_stats()
    except Exception:
        stats = None
    stats = stats or {}
    in_use = int(stats.get("bytes_in_use", 0))
    if not stats:
        in_use = int(memory_stats(dev)["bytes_in_use"])
    _PEAK[dev.id] = in_use
    _PEAK_FLOOR[dev.id] = int(stats.get("peak_bytes_in_use", 0))


def memory_reserved(device=None) -> int:
    """Total reservable pool (bytes_limit) when PJRT reports one."""
    stats = memory_stats(device)
    return int(stats.get("bytes_limit", stats.get("bytes_in_use", 0)))


def empty_cache():
    """The reference releases cached allocator blocks; PJRT manages its own
    pool — provided for API compatibility (garbage-collects dropped arrays)."""
    import gc

    gc.collect()
