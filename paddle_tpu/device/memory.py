"""Device memory stats (reference roles: paddle/fluid/memory/stats.h
StatRegistry + python/paddle/device/cuda/__init__.py memory_allocated /
max_memory_allocated). TPU-native: PJRT owns the allocator, so stats come from
`Device.memory_stats()` (live HBM) plus a host-side registry of live
jax.Arrays for per-process accounting on backends without PJRT stats (CPU).
"""
from __future__ import annotations

from typing import Dict, Optional

import jax

__all__ = ["memory_allocated", "max_memory_allocated", "memory_reserved",
           "memory_stats", "empty_cache"]

_PEAK: Dict[int, int] = {}


def _device(device=None):
    if device is None:
        return jax.devices()[0]
    if isinstance(device, int):
        return jax.devices()[device]
    if isinstance(device, str):  # paddle-style ids: "gpu:0", "tpu:1", "cpu"
        platform, _, idx = device.partition(":")
        idx = int(idx) if idx else 0
        try:
            return jax.devices(platform)[idx]
        except RuntimeError:  # platform not present: fall back to default set
            return jax.devices()[idx]
    return device


def memory_stats(device=None) -> dict:
    """Raw PJRT stats dict (bytes_in_use, peak_bytes_in_use, ...) or a
    live-array fallback on backends that expose none."""
    dev = _device(device)
    try:
        stats = dev.memory_stats()
    except Exception:
        stats = None
    if stats:
        return dict(stats)
    total = sum(
        arr.nbytes for arr in jax.live_arrays()
        if dev in getattr(arr, "devices", lambda: set())())
    return {"bytes_in_use": total,
            "peak_bytes_in_use": max(total, _PEAK.get(dev.id, 0))}


def memory_allocated(device=None) -> int:
    """Bytes currently allocated on the device (reference
    device/cuda memory_allocated)."""
    stats = memory_stats(device)
    used = int(stats.get("bytes_in_use", 0))
    dev = _device(device)
    _PEAK[dev.id] = max(_PEAK.get(dev.id, 0), used)
    return used


def max_memory_allocated(device=None) -> int:
    stats = memory_stats(device)
    dev = _device(device)
    peak = int(stats.get("peak_bytes_in_use", 0))
    return max(peak, _PEAK.get(dev.id, 0))


def memory_reserved(device=None) -> int:
    """Total reservable pool (bytes_limit) when PJRT reports one."""
    stats = memory_stats(device)
    return int(stats.get("bytes_limit", stats.get("bytes_in_use", 0)))


def empty_cache():
    """The reference releases cached allocator blocks; PJRT manages its own
    pool — provided for API compatibility (garbage-collects dropped arrays)."""
    import gc

    gc.collect()
