/* Pluggable-device C ABI (the reference contract this mirrors:
 * /root/reference/paddle/phi/backends/device_ext.h:48 C_DeviceInterface —
 * a versioned struct of function pointers a hardware plugin fills in).
 *
 * TPU-native stance: the compute path talks to accelerators through PJRT, so
 * this interface covers the *runtime* surface a plugin must provide to appear
 * in paddle_tpu.device: lifecycle, device enumeration, raw memory, and a
 * synchronous copy engine. A plugin exports:
 *     int PT_InitPlugin(PT_DeviceInterface* iface);
 * filling every pointer and setting `size` for ABI versioning.
 */
#ifndef PADDLE_TPU_DEVICE_EXT_H_
#define PADDLE_TPU_DEVICE_EXT_H_

#include <stddef.h>
#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef enum { PT_SUCCESS = 0, PT_FAILED = 1 } PT_Status;

typedef struct {
  int id; /* logical device index */
} PT_Device;

typedef struct PT_DeviceInterface {
  size_t size;            /* sizeof(PT_DeviceInterface) the plugin built with */
  const char* type_name;  /* e.g. "fake_cpu" */

  /* lifecycle */
  PT_Status (*initialize)(void);
  PT_Status (*finalize)(void);

  /* enumeration */
  PT_Status (*get_device_count)(int* count);
  PT_Status (*init_device)(PT_Device device);
  PT_Status (*deinit_device)(PT_Device device);

  /* memory */
  PT_Status (*memory_allocate)(PT_Device device, void** ptr, size_t size);
  PT_Status (*memory_deallocate)(PT_Device device, void* ptr, size_t size);
  PT_Status (*memory_copy_h2d)(PT_Device device, void* dst, const void* src,
                               size_t size);
  PT_Status (*memory_copy_d2h)(PT_Device device, void* dst, const void* src,
                               size_t size);
  PT_Status (*device_memory_stats)(PT_Device device, size_t* total,
                                   size_t* free_bytes);

  /* execution */
  PT_Status (*synchronize_device)(PT_Device device);
} PT_DeviceInterface;

/* Every plugin exports exactly this symbol. */
typedef int (*PT_InitPluginFn)(PT_DeviceInterface* iface);

#ifdef __cplusplus
}
#endif

#endif /* PADDLE_TPU_DEVICE_EXT_H_ */
