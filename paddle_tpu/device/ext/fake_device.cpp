// Sample pluggable device: a host-memory "fake_cpu" backend.
//
// Reference role: /root/reference/paddle/fluid/platform/device/custom/
// fake_cpu_device.h (the test plugin validating the device_ext contract).
// Demonstrates the PT_DeviceInterface ABI end to end: enumeration, raw
// allocation with stats accounting, and the two copy directions.
#include <cstdlib>
#include <cstring>
#include <mutex>

#include "device_ext.h"

namespace {
constexpr int kDeviceCount = 2;
constexpr size_t kTotalBytes = 1ull << 30;
std::mutex g_mu;
size_t g_used = 0;

PT_Status init() { return PT_SUCCESS; }
PT_Status fini() { return PT_SUCCESS; }

PT_Status device_count(int* count) {
  *count = kDeviceCount;
  return PT_SUCCESS;
}

PT_Status init_device(PT_Device) { return PT_SUCCESS; }
PT_Status deinit_device(PT_Device) { return PT_SUCCESS; }

PT_Status mem_alloc(PT_Device, void** ptr, size_t size) {
  std::lock_guard<std::mutex> lk(g_mu);
  if (g_used + size > kTotalBytes) return PT_FAILED;
  *ptr = std::malloc(size);
  if (!*ptr) return PT_FAILED;
  g_used += size;
  return PT_SUCCESS;
}

PT_Status mem_free(PT_Device, void* ptr, size_t size) {
  std::lock_guard<std::mutex> lk(g_mu);
  std::free(ptr);
  g_used -= size > g_used ? g_used : size;
  return PT_SUCCESS;
}

PT_Status copy_h2d(PT_Device, void* dst, const void* src, size_t size) {
  std::memcpy(dst, src, size);
  return PT_SUCCESS;
}

PT_Status copy_d2h(PT_Device, void* dst, const void* src, size_t size) {
  std::memcpy(dst, src, size);
  return PT_SUCCESS;
}

PT_Status mem_stats(PT_Device, size_t* total, size_t* free_bytes) {
  std::lock_guard<std::mutex> lk(g_mu);
  *total = kTotalBytes;
  *free_bytes = kTotalBytes - g_used;
  return PT_SUCCESS;
}

PT_Status sync_device(PT_Device) { return PT_SUCCESS; }
}  // namespace

extern "C" int PT_InitPlugin(PT_DeviceInterface* iface) {
  if (!iface || iface->size < sizeof(PT_DeviceInterface)) return 1;
  iface->type_name = "fake_cpu";
  iface->initialize = init;
  iface->finalize = fini;
  iface->get_device_count = device_count;
  iface->init_device = init_device;
  iface->deinit_device = deinit_device;
  iface->memory_allocate = mem_alloc;
  iface->memory_deallocate = mem_free;
  iface->memory_copy_h2d = copy_h2d;
  iface->memory_copy_d2h = copy_d2h;
  iface->device_memory_stats = mem_stats;
  iface->synchronize_device = sync_device;
  return 0;
}
