"""paddle.device: device queries + the pluggable-device loader.

Reference: python/paddle/device/__init__.py (set/get_device, device counts)
and the PluggableDevice registration path (SURVEY Appendix A.1,
paddle/phi/backends/device_ext.h). The XLA device set comes from PJRT via jax;
custom hardware plugs in through the PT_DeviceInterface C ABI
(ext/device_ext.h) loaded by CustomDeviceRuntime.
"""
from __future__ import annotations

import ctypes
import os
from typing import Dict, List

from ..framework import get_device, set_device  # noqa: F401
from . import memory  # noqa: F401
from .memory import (  # noqa: F401
    memory_allocated, max_memory_allocated, memory_reserved, memory_stats,
    reset_max_memory_allocated, empty_cache,
)

_CUSTOM: Dict[str, "CustomDeviceRuntime"] = {}


def get_all_device_type() -> List[str]:
    import jax

    kinds = {d.platform for d in jax.devices()}
    return sorted(kinds) + get_all_custom_device_type()


def get_available_device() -> List[str]:
    import jax

    out = [f"{d.platform}:{d.id}" for d in jax.devices()]
    for name, rt in _CUSTOM.items():
        out.extend(f"{name}:{i}" for i in range(rt.device_count()))
    return out


def device_count() -> int:
    import jax

    return len(jax.devices())


def cuda_device_count() -> int:
    return 0  # TPU build


def is_compiled_with_custom_device(device_type: str) -> bool:
    return device_type in _CUSTOM


def get_all_custom_device_type() -> List[str]:
    return sorted(_CUSTOM)


class _Iface(ctypes.Structure):
    _fields_ = [
        ("size", ctypes.c_size_t),
        ("type_name", ctypes.c_char_p),
        ("initialize", ctypes.c_void_p),
        ("finalize", ctypes.c_void_p),
        ("get_device_count", ctypes.c_void_p),
        ("init_device", ctypes.c_void_p),
        ("deinit_device", ctypes.c_void_p),
        ("memory_allocate", ctypes.c_void_p),
        ("memory_deallocate", ctypes.c_void_p),
        ("memory_copy_h2d", ctypes.c_void_p),
        ("memory_copy_d2h", ctypes.c_void_p),
        ("device_memory_stats", ctypes.c_void_p),
        ("synchronize_device", ctypes.c_void_p),
    ]


class _Device(ctypes.Structure):
    _fields_ = [("id", ctypes.c_int)]


_STATUS = ctypes.c_int
_DEV_FN = ctypes.CFUNCTYPE(_STATUS, _Device)
_COUNT_FN = ctypes.CFUNCTYPE(_STATUS, ctypes.POINTER(ctypes.c_int))
_ALLOC_FN = ctypes.CFUNCTYPE(_STATUS, _Device, ctypes.POINTER(ctypes.c_void_p),
                             ctypes.c_size_t)
_FREE_FN = ctypes.CFUNCTYPE(_STATUS, _Device, ctypes.c_void_p, ctypes.c_size_t)
_COPY_FN = ctypes.CFUNCTYPE(_STATUS, _Device, ctypes.c_void_p, ctypes.c_void_p,
                            ctypes.c_size_t)
_STATS_FN = ctypes.CFUNCTYPE(_STATUS, _Device, ctypes.POINTER(ctypes.c_size_t),
                             ctypes.POINTER(ctypes.c_size_t))
_VOID_FN = ctypes.CFUNCTYPE(_STATUS)


class CustomDeviceRuntime:
    """ctypes view over a PT_DeviceInterface plugin (the core-side
    DeviceManager role, reference phi/backends/device_manager.cc)."""

    def __init__(self, lib_path: str):
        self._lib = ctypes.CDLL(lib_path)
        self._iface = _Iface()
        self._iface.size = ctypes.sizeof(_Iface)
        init_fn = self._lib.PT_InitPlugin
        init_fn.restype = ctypes.c_int
        init_fn.argtypes = [ctypes.POINTER(_Iface)]
        if init_fn(ctypes.byref(self._iface)) != 0:
            raise RuntimeError(f"plugin {lib_path} rejected the ABI handshake")
        self.type_name = self._iface.type_name.decode()
        if _VOID_FN(self._iface.initialize)() != 0:
            raise RuntimeError(f"plugin {self.type_name}: initialize failed")

    def device_count(self) -> int:
        n = ctypes.c_int(0)
        if _COUNT_FN(self._iface.get_device_count)(ctypes.byref(n)) != 0:
            raise RuntimeError("get_device_count failed")
        return n.value

    def memory_allocate(self, dev_id: int, size: int) -> int:
        ptr = ctypes.c_void_p(None)
        rc = _ALLOC_FN(self._iface.memory_allocate)(
            _Device(dev_id), ctypes.byref(ptr), size)
        if rc != 0 or not ptr.value:
            raise MemoryError(f"{self.type_name}: allocate({size}) failed")
        return ptr.value

    def memory_deallocate(self, dev_id: int, ptr: int, size: int):
        _FREE_FN(self._iface.memory_deallocate)(_Device(dev_id),
                                                ctypes.c_void_p(ptr), size)

    def copy_h2d(self, dev_id: int, dst: int, src: bytes):
        buf = ctypes.create_string_buffer(src, len(src))
        rc = _COPY_FN(self._iface.memory_copy_h2d)(
            _Device(dev_id), ctypes.c_void_p(dst),
            ctypes.cast(buf, ctypes.c_void_p), len(src))
        if rc != 0:
            raise RuntimeError("copy_h2d failed")

    def copy_d2h(self, dev_id: int, src: int, size: int) -> bytes:
        buf = ctypes.create_string_buffer(size)
        rc = _COPY_FN(self._iface.memory_copy_d2h)(
            _Device(dev_id), ctypes.cast(buf, ctypes.c_void_p),
            ctypes.c_void_p(src), size)
        if rc != 0:
            raise RuntimeError("copy_d2h failed")
        return buf.raw

    def memory_stats(self, dev_id: int):
        total = ctypes.c_size_t(0)
        free = ctypes.c_size_t(0)
        _STATS_FN(self._iface.device_memory_stats)(
            _Device(dev_id), ctypes.byref(total), ctypes.byref(free))
        return int(total.value), int(free.value)

    def synchronize(self, dev_id: int):
        _DEV_FN(self._iface.synchronize_device)(_Device(dev_id))


def load_custom_device(lib_path: str) -> CustomDeviceRuntime:
    """Register a PT_DeviceInterface plugin (reference: CUSTOM_DEVICE_ROOT
    scan in phi/backends/custom/custom_device.cc)."""
    rt = CustomDeviceRuntime(lib_path)
    _CUSTOM[rt.type_name] = rt
    return rt


def build_fake_device() -> str:
    """Compile the bundled sample plugin; returns the .so path (test helper)."""
    from ..utils import cpp_extension

    src_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)), "ext")
    lib = cpp_extension.load("fake_device",
                             [os.path.join(src_dir, "fake_device.cpp")],
                             extra_cxx_cflags=[f"-I{src_dir}"])
    return lib._name
