"""AST-lite dygraph-to-static conversion.

Reference: python/paddle/fluid/dygraph/dygraph_to_static/program_translator.py
:775 + ifelse_transformer.py / loop_transformer.py — the reference transpiles
EVERY Python `if`/`while` into runtime-dispatched control-flow ops so
tensor-dependent branches work under tracing.

TPU-native lite version: an ast pass rewrites the *simple* shapes —
  * `if t: return a` / `else: return b`          -> __pt_if(t, fa, fb)
  * `if t:` assigning plain names in each branch -> branch closures returning
    the assigned tuple, dispatched through __pt_if
  * `while t:` whose body assigns plain names    -> __pt_while carry loop
  * `for i in range(...)` / `for x in tensor:`   -> __pt_for carry loop
    (reference loop_transformer.py:486 for-to-while lowering)
  * top-level `break` / `continue` (incl. `if c: break`) -> guard-flag carry
    (reference break_continue_transformer.py's bool-flag rewrite)
  * `and` / `or` / `not` inside converted tests  -> __pt_bool_* dispatch
    (reference logical_transformer.py: logical_and/or ops under trace,
    short-circuit Python semantics when the operands are concrete)
into `paddle_tpu.static.nn.cond` / `while_loop`, which run plain Python when
the predicate is concrete and lower to `lax.cond`/`lax.while_loop` when it is
traced. Anything more complex is left untouched — tracing such code then hits
Tensor.__bool__'s pointer error instead of silently specializing a branch.
"""
from __future__ import annotations

import ast
import functools
import inspect
import textwrap
import types
from typing import List, Optional


def _runtime_if(pred, true_fn, false_fn):
    from ..static import nn as static_nn

    return static_nn.cond(pred, true_fn, false_fn)


def _runtime_while(cond_fn, body_fn, loop_vars):
    from ..static import nn as static_nn

    out = static_nn.while_loop(cond_fn, body_fn, list(loop_vars))
    return tuple(out)


def _pred_data(x):
    from ..core.tensor import Tensor

    return x.data if isinstance(x, Tensor) else x


def _is_traced(x):
    import jax

    return isinstance(_pred_data(x), jax.core.Tracer)


def _np_bool(x):
    import numpy as np

    return bool(np.asarray(_pred_data(x)))


def _runtime_bool_and(a, b_thunk):
    """`a and b` — short-circuits when `a` is concrete, logical_and under
    trace (both sides evaluated, like the reference's logical_and op)."""
    import jax.numpy as jnp

    from ..core.tensor import Tensor

    if not _is_traced(a):
        return b_thunk() if _np_bool(a) else a
    b = b_thunk()
    return Tensor(jnp.logical_and(jnp.asarray(_pred_data(a)).astype(bool),
                                  jnp.asarray(_pred_data(b)).astype(bool)))


def _runtime_bool_or(a, b_thunk):
    import jax.numpy as jnp

    from ..core.tensor import Tensor

    if not _is_traced(a):
        return a if _np_bool(a) else b_thunk()
    b = b_thunk()
    return Tensor(jnp.logical_or(jnp.asarray(_pred_data(a)).astype(bool),
                                 jnp.asarray(_pred_data(b)).astype(bool)))


def _runtime_bool_not(a):
    import jax.numpy as jnp

    from ..core.tensor import Tensor

    if not _is_traced(a):
        return not _np_bool(a)
    return Tensor(jnp.logical_not(jnp.asarray(_pred_data(a)).astype(bool)))


def _runtime_select(pred, new_thunk, old):
    """Guarded assignment `x = new if live else x` (break/continue lowering).
    The new value is a THUNK: on the concrete path a dead statement's RHS is
    never evaluated (it may be the very thing the break was protecting, e.g.
    `1.0/x` after `if x == 0: continue`). Structural over tuples so
    `a, b = ...` targets stay convertible."""
    import jax
    import jax.numpy as jnp

    from ..core.tensor import Tensor

    if not _is_traced(pred):
        return new_thunk() if _np_bool(pred) else old
    new = new_thunk()
    pd = jnp.asarray(_pred_data(pred)).astype(bool)

    def sel(n, o):
        nd = n.data if isinstance(n, Tensor) else n
        od = o.data if isinstance(o, Tensor) else o
        return Tensor(jnp.where(pd, nd, od))

    if isinstance(new, (tuple, list)):
        return type(new)(sel(n, o) for n, o in zip(new, old))
    return sel(new, old)


def _brk_hit(vs, brk_idx) -> bool:
    """True when the carried break flag is concretely set (the concrete
    paths below exit early instead of running masked dead iterations —
    plain Python `for` semantics, and guards after the break never run)."""
    if brk_idx is None:
        return False
    flag = vs[brk_idx]
    return not _is_traced(flag) and _np_bool(flag)


def _runtime_for_range(range_args, body_fn, loop_vars, brk_idx=None):
    """`for i in range(...)` -> carry loop. Concrete bounds run the Python
    loop; a traced stop lowers to a while carry over (i, *vars). The step
    must be concrete (its sign decides the loop predicate). `brk_idx`
    points at the lowered break flag in the carry, so the concrete path
    exits as soon as it trips."""
    import jax.numpy as jnp

    from ..core.tensor import Tensor

    vals = [_pred_data(a) for a in range_args]
    if len(vals) == 1:
        start, stop, step = 0, vals[0], 1
    elif len(vals) == 2:
        (start, stop), step = vals, 1
    else:
        start, stop, step = vals
    if _is_traced(step):
        raise ValueError(
            "dy2static for-range: the step must be concrete (its sign "
            "chooses the loop predicate); got a traced step")
    step = int(step)
    if step == 0:
        raise ValueError("range() arg 3 must not be zero")
    if not (_is_traced(start) or _is_traced(stop)):
        vs = list(loop_vars)
        for i in range(int(start), int(stop), step):
            vs = list(body_fn(i, *vs))
            if _brk_hit(vs, brk_idx):
                break
        return tuple(vs)

    from ..static import nn as static_nn

    def cond_fn(i, *vs):
        d = _pred_data(i)
        return Tensor(d < stop) if step > 0 else Tensor(d > stop)

    def body(i, *vs):
        out = body_fn(i, *vs)
        return (Tensor(_pred_data(i) + step),) + tuple(out)

    i0 = Tensor(jnp.asarray(start, jnp.int32))
    res = static_nn.while_loop(cond_fn, body, [i0] + list(loop_vars))
    return tuple(res[1:])


_FOR_UNROLL_LIMIT = 32


def _runtime_for_iter(xs, body_fn, loop_vars, brk_idx=None):
    """`for x in xs` — Tensors iterate dim 0 (unrolled when short, a
    dynamic-index while carry when long); other iterables run eagerly."""
    from ..core.tensor import Tensor

    if not isinstance(xs, Tensor):
        vs = list(loop_vars)
        for x in xs:
            vs = list(body_fn(x, *vs))
            if _brk_hit(vs, brk_idx):
                break
        return tuple(vs)
    n = int(xs.shape[0])
    if n <= _FOR_UNROLL_LIMIT:
        vs = list(loop_vars)
        for i in range(n):
            vs = list(body_fn(xs[i], *vs))
            if _brk_hit(vs, brk_idx):
                break
        return tuple(vs)
    import jax.numpy as jnp

    from ..static import nn as static_nn

    def cond_fn(i, *vs):
        return Tensor(_pred_data(i) < n)

    def body(i, *vs):
        out = body_fn(xs[i], *vs)
        return (Tensor(_pred_data(i) + 1),) + tuple(out)

    i0 = Tensor(jnp.asarray(0, jnp.int32))
    res = static_nn.while_loop(cond_fn, body, [i0] + list(loop_vars))
    return tuple(res[1:])


# -- call-graph conversion (reference call_transformer.py:25) -----------------
# Every call site in a converted function is wrapped in
# __pt_convert_call(f): user-defined plain-Python functions/methods get the
# same AST conversion (recursively, cached); builtins, stdlib, framework and
# third-party callables pass through untouched.

import weakref

# weak keys: per-call-created functions/lambdas routed through
# __pt_convert_call must not be pinned forever (module-level functions stay
# alive, so their conversions persist)
_CONVERT_CACHE: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()

_SKIP_MODULE_ROOTS = {
    "paddle_tpu", "jax", "jaxlib", "numpy", "torch", "flax", "optax",
    "einops", "chex", "builtins",
}


def _runtime_convert_call(f):
    import sys

    if not callable(f):
        return f
    target = f.__func__ if isinstance(f, types.MethodType) else f
    if not isinstance(target, types.FunctionType):
        return f  # builtins / C functions / classes / callable objects
    root = (getattr(target, "__module__", "") or "").split(".")[0]
    if root in _SKIP_MODULE_ROOTS or root in sys.stdlib_module_names:
        return f
    # "unchanged" is cached as None: a WeakKeyDictionary holds values
    # strongly, so storing the function as its own value would pin the key
    sentinel = object()
    converted = _CONVERT_CACHE.get(target, sentinel)
    if converted is sentinel:
        _CONVERT_CACHE[target] = None  # recursion guard: use the original
        converted = convert_to_static(target)
        _CONVERT_CACHE[target] = None if converted is target else converted
    if converted is None or converted is target:
        return f
    if isinstance(f, types.MethodType):
        return types.MethodType(converted, f.__self__)
    return converted


class _WrapCalls(ast.NodeTransformer):
    """fn(args) -> __pt_convert_call(fn)(args) for every call site whose
    callee isn't a conversion helper (the reference transpiles the call
    graph; we dispatch per call and decide at runtime)."""

    def __init__(self):
        self.changed = False

    def visit_Call(self, node):
        self.generic_visit(node)
        f = node.func
        if isinstance(f, ast.Name) and (f.id.startswith("__pt_")
                                        or f.id == "super"):
            return node
        self.changed = True
        node.func = ast.copy_location(
            ast.Call(func=_name("__pt_convert_call"), args=[f], keywords=[]),
            f)
        return node


def _assigned_names(stmts) -> Optional[List[str]]:
    """Plain Name targets assigned in stmts; None if anything else happens
    (calls with side effects on the RHS of an assignment are fine — only the
    statement SHAPE matters). Helper defs emitted by earlier conversions
    (`__pt_*`) and docstring exprs are allowed and contribute no names; a
    BARE call statement bails out (its side effect would run both-branch
    under lax.cond / once under lax.while_loop)."""
    names = []
    for st in stmts:
        if isinstance(st, ast.Assign):
            for t in st.targets:
                if isinstance(t, ast.Name):
                    names.append(t.id)
                elif isinstance(t, ast.Tuple) and all(
                        isinstance(e, ast.Name) for e in t.elts):
                    names.extend(e.id for e in t.elts)
                else:
                    return None
        elif isinstance(st, ast.AugAssign):
            if isinstance(st.target, ast.Name):
                names.append(st.target.id)
            else:
                return None
        elif isinstance(st, ast.FunctionDef) and st.name.startswith("__pt_"):
            continue
        elif isinstance(st, ast.Expr) and isinstance(st.value, ast.Constant):
            continue  # docstrings; a call Expr may carry side effects that
            # lax.cond/while (both-branch / once-only tracing) would distort
        else:
            return None
    # live/guard temps are re-derived at each iteration start, not carried
    return [n for n in names
            if not n.startswith(("__pt_live", "__pt_g_"))]


def _read_before_write(stmts, extra_reads=()) -> set:
    """Names loaded before their first assignment across the statement
    sequence — i.e. names the branch needs to pre-exist."""
    assigned: set = set()
    reads: set = set(extra_reads)
    for st in stmts:
        for node in ast.walk(st):
            if isinstance(node, ast.AugAssign) and isinstance(node.target,
                                                              ast.Name):
                if node.target.id not in assigned:
                    reads.add(node.target.id)
        for node in ast.walk(st):
            if (isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load)
                    and node.id not in assigned):
                reads.add(node.id)
        for node in ast.walk(st):
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for t in targets:
                    if isinstance(t, ast.Name):
                        assigned.add(t.id)
                    elif isinstance(t, ast.Tuple):
                        assigned.update(e.id for e in t.elts
                                        if isinstance(e, ast.Name))
    return reads


class _TestBoolOps(ast.NodeTransformer):
    """Rewrite `and`/`or`/`not` inside a (to-be-converted) TEST expression
    into __pt_bool_* dispatch. Right operands become thunks so Python's
    short-circuit order is preserved on the concrete path; under trace both
    sides evaluate and combine via logical ops (reference
    logical_transformer.py)."""

    def visit_Lambda(self, node):  # nested scopes keep their own semantics
        return node

    def visit_BoolOp(self, node):
        self.generic_visit(node)
        fn = "__pt_bool_and" if isinstance(node.op, ast.And) else \
            "__pt_bool_or"
        expr = node.values[0]
        for v in node.values[1:]:
            expr = ast.Call(func=ast.Name(id=fn, ctx=ast.Load()),
                            args=[expr,
                                  ast.Lambda(args=_no_args(), body=v)],
                            keywords=[])
        return expr

    def visit_UnaryOp(self, node):
        self.generic_visit(node)
        if isinstance(node.op, ast.Not):
            return ast.Call(func=ast.Name(id="__pt_bool_not", ctx=ast.Load()),
                            args=[node.operand], keywords=[])
        return node


def _conv_test(expr):
    return _TestBoolOps().visit(expr)


def _name(n, store=False):
    return ast.Name(id=n, ctx=ast.Store() if store else ast.Load())


def _call(fn, *args):
    return ast.Call(func=_name(fn), args=list(args), keywords=[])


def _thunk(expr):
    return ast.Lambda(args=_no_args(), body=expr)


def _lower_breaks(body, uid: int, for_loop: bool = False):
    """Rewrite top-level `break`/`continue` (bare, or the `if c: break` /
    `if c: continue` shapes) into live/brk guard flags — the reference
    break_continue_transformer.py bool-flag rewrite. Statements after the
    first guard become __pt_sel-guarded assignments (targets must provably
    pre-exist). Returns (new_body, has_break), or None when the body is out
    of scope. Bodies with no break/continue come back unchanged."""
    def _ctrl(st):
        if isinstance(st, (ast.Break, ast.Continue)):
            return ast.Constant(value=True), isinstance(st, ast.Break)
        if (isinstance(st, ast.If) and len(st.body) == 1 and not st.orelse
                and isinstance(st.body[0], (ast.Break, ast.Continue))):
            return st.test, isinstance(st.body[0], ast.Break)
        return None

    if not any(_ctrl(st) for st in body):
        return list(body), False

    live = f"__pt_live_{uid}"
    brk = f"__pt_brk_{uid}"
    pre = _read_before_write(body)
    has_break = any(_ctrl(st) and _ctrl(st)[1] for st in body)
    # live starts as "not already broken": for `for` loops the trip count is
    # fixed, so post-break iterations still run the (fully masked) body
    init = _call("__pt_bool_not", _name(brk)) if has_break else \
        ast.Constant(value=True)
    new = [ast.Assign(targets=[_name(live, store=True)], value=init)]
    # a for loop's trip count is fixed, so post-break iterations still enter
    # the body: EVERY statement needs the live mask, not just post-guard ones
    seen_guard = for_loop and has_break
    gi = 0
    for st in body:
        ctrl = _ctrl(st)
        if ctrl is not None:
            guard_expr, is_break = ctrl
            gi += 1
            gname = f"__pt_g_{uid}_{gi}"
            # the guard TEST is masked by live like every other statement:
            # concretely-dead iterations never evaluate it (it may only be
            # safe pre-break, e.g. an index bound the break protects), and
            # under trace a poisoned dead-lane test can't flip the flags
            new.append(ast.Assign(
                targets=[_name(gname, store=True)],
                value=_call("__pt_bool_and", _name(live),
                            _thunk(_conv_test(guard_expr)))))
            if is_break:
                hit = _call("__pt_bool_and", _name(live), _thunk(_name(gname)))
                new.append(ast.Assign(
                    targets=[_name(brk, store=True)],
                    value=_call("__pt_bool_or", _name(brk), _thunk(hit))))
            new.append(ast.Assign(
                targets=[_name(live, store=True)],
                value=_call("__pt_bool_and", _name(live),
                            _thunk(_call("__pt_bool_not", _name(gname))))))
            seen_guard = True
            continue
        if isinstance(st, (ast.Assign, ast.AugAssign)):
            if not seen_guard:
                new.append(st)
                continue
            if isinstance(st, ast.AugAssign):
                if not isinstance(st.target, ast.Name):
                    return None
                targets = [st.target.id]
                value = ast.BinOp(left=_name(st.target.id), op=st.op,
                                  right=st.value)
                store = _name(st.target.id, store=True)
                old = _name(st.target.id)
            else:
                if len(st.targets) != 1:
                    return None
                t = st.targets[0]
                if isinstance(t, ast.Name):
                    targets = [t.id]
                    old = _name(t.id)
                elif isinstance(t, ast.Tuple) and all(
                        isinstance(e, ast.Name) for e in t.elts):
                    targets = [e.id for e in t.elts]
                    old = ast.Tuple(elts=[_name(x) for x in targets],
                                    ctx=ast.Load())
                else:
                    return None
                store = t
                value = st.value
            if any(x not in pre for x in targets):
                return None  # guarded target may not pre-exist: bail
            new.append(ast.Assign(
                targets=[store],
                value=_call("__pt_sel", _name(live), _thunk(value), old)))
            continue
        if isinstance(st, ast.Expr) and isinstance(st.value, ast.Constant):
            new.append(st)  # docstrings only: a call Expr may have side
            continue        # effects that guards/trace can't mask
        return None  # anything else is out of scope
    return new, has_break


def _branch_fn(name: str, stmts, targets: List[str], params: List[str]):
    """def <name>(p=p, ...): <stmts>; return (targets...)"""
    args = ast.arguments(
        posonlyargs=[], args=[ast.arg(arg=p) for p in params], vararg=None,
        kwonlyargs=[], kw_defaults=[], kwarg=None,
        defaults=[ast.Name(id=p, ctx=ast.Load()) for p in params])
    ret = ast.Return(value=ast.Tuple(
        elts=[ast.Name(id=t, ctx=ast.Load()) for t in targets],
        ctx=ast.Load()))
    return ast.FunctionDef(name=name, args=args, body=list(stmts) + [ret],
                           decorator_list=[], returns=None)


class _ListAppend(ast.NodeTransformer):
    """`xs.append(e)` statement -> `xs = xs + [e]` inside a loop body about
    to be converted (reference list_transformer.py:28's list-to-tensor-array
    rewrite): the functional form makes the list a loop CARRY, so the
    concrete-trip path threads it like any other variable. Dynamic trip
    counts still fail loudly — a growing list cannot be a lax carry.

    Rebinding is only semantics-preserving for lists the function CREATED
    itself, so the rewrite fires only for `allowed` names (locally assigned
    a list literal, never a parameter): a caller-supplied list must keep
    its in-place mutation, and a deque/array receiver must keep its own
    append."""

    def __init__(self, allowed):
        self.changed = False
        self.allowed = set(allowed)

    def visit_FunctionDef(self, node):
        return node

    def visit_Lambda(self, node):
        return node

    def visit_Expr(self, node):
        v = node.value
        if (isinstance(v, ast.Call) and isinstance(v.func, ast.Attribute)
                and v.func.attr == "append" and len(v.args) == 1
                and not v.keywords and isinstance(v.func.value, ast.Name)
                and v.func.value.id in self.allowed):
            self.changed = True
            name = v.func.value.id
            return ast.copy_location(ast.Assign(
                targets=[_name(name, store=True)],
                value=ast.BinOp(
                    left=_name(name), op=ast.Add(),
                    right=ast.List(elts=[v.args[0]], ctx=ast.Load()))), node)
        return node


def _local_list_names(fdef) -> set:
    """Names safe for the append->rebind rewrite: every Assign to the name
    is a list literal, the name is not a parameter, and it does not ESCAPE
    before its append loops end — a Load that isn't an append receiver
    (alias = lst, f(lst), (lst, …)) occurring before/inside the loop would
    see the original object while the rewrite rebinds, silently dropping
    appends. Loads strictly after every append-carrying loop (the normal
    consumption: paddle.concat(lst)) are fine."""
    params = {a.arg for a in (fdef.args.posonlyargs + fdef.args.args +
                              fdef.args.kwonlyargs)}
    for a in (fdef.args.vararg, fdef.args.kwarg):
        if a is not None:
            params.add(a.arg)

    order: dict = {}
    # ctx/operator nodes are interned singletons shared across the tree —
    # numbering them would smear positions; only real syntax nodes count
    _skip = (ast.expr_context, ast.operator, ast.boolop, ast.unaryop,
             ast.cmpop)

    def number(node, counter=[0]):
        if not isinstance(node, _skip):
            order[id(node)] = counter[0]
            counter[0] += 1
        for child in ast.iter_child_nodes(node):
            number(child)

    number(fdef)

    def span_end(node):
        return max(order[id(n)] for n in ast.walk(node)
                   if not isinstance(n, _skip))

    append_receivers = set()  # id of the Name node in `name.append(e)`
    appends_in_loop: dict = {}  # name -> max end-position of its loops
    for node in ast.walk(fdef):
        if isinstance(node, (ast.For, ast.While)):
            end = span_end(node)
            for sub in ast.walk(node):
                if (isinstance(sub, ast.Call)
                        and isinstance(sub.func, ast.Attribute)
                        and sub.func.attr == "append"
                        and isinstance(sub.func.value, ast.Name)):
                    nm = sub.func.value.id
                    append_receivers.add(id(sub.func.value))
                    appends_in_loop[nm] = max(appends_in_loop.get(nm, 0),
                                              end)

    lit, non_lit, escapes = set(), set(), {}
    for node in ast.walk(fdef):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    (lit if isinstance(node.value, ast.List)
                     else non_lit).add(t.id)
        elif isinstance(node, ast.AugAssign) and isinstance(node.target,
                                                            ast.Name):
            non_lit.add(node.target.id)
        elif (isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load)
                and id(node) not in append_receivers):
            escapes[node.id] = min(escapes.get(node.id, order[id(node)]),
                                   order[id(node)])
    out = set()
    for nm in lit - non_lit - params:
        loop_end = appends_in_loop.get(nm)
        if loop_end is None:
            continue  # no append-in-loop: nothing to rewrite
        if nm in escapes and escapes[nm] <= loop_end:
            continue  # aliased/escaped before the loop finished
        out.add(nm)
    return out


class _CtrlFlow(ast.NodeTransformer):
    def __init__(self, list_names=()):
        self.changed = False
        self.n = 0
        self.list_names = set(list_names)

    def _lower_appends(self, body):
        """Apply the list-append rewrite to a COPY of the loop body (a later
        bail must leave the original statements untouched)."""
        import copy

        if not self.list_names:
            return list(body)
        la = _ListAppend(self.list_names)
        return [la.visit(copy.deepcopy(st)) for st in body]

    def _uid(self):
        self.n += 1
        return self.n

    # `if`/`while` nested in defs/lambdas keep their own scope — don't touch
    def visit_FunctionDef(self, node):
        return node

    def visit_AsyncFunctionDef(self, node):
        return node

    def visit_Lambda(self, node):
        return node

    def visit_If(self, node):
        self.generic_visit(node)
        # pattern A: both arms are a single `return <expr>`
        if (len(node.body) == 1 and isinstance(node.body[0], ast.Return)
                and len(node.orelse) == 1
                and isinstance(node.orelse[0], ast.Return)
                and node.body[0].value is not None
                and node.orelse[0].value is not None):
            self.changed = True
            call = ast.Call(
                func=ast.Name(id="__pt_if", ctx=ast.Load()),
                args=[_conv_test(node.test),
                      ast.Lambda(args=_no_args(), body=node.body[0].value),
                      ast.Lambda(args=_no_args(), body=node.orelse[0].value)],
                keywords=[])
            return ast.copy_location(ast.Return(value=call), node)
        # pattern B: both arms only assign plain names. A target assigned in
        # one arm only is convertible ONLY when that arm reads it before
        # writing (proof it pre-exists) — otherwise the other arm's return
        # would unbind a name that eager code never touched (e.g. a dead
        # store), so the whole `if` stays unconverted.
        body_names = _assigned_names(node.body)
        else_names = _assigned_names(node.orelse) if node.orelse else []
        if body_names is None or else_names is None or not (body_names or
                                                            else_names):
            return node
        bset, eset = set(body_names), set(else_names)
        rbw_body = _read_before_write(node.body)
        rbw_else = _read_before_write(node.orelse)
        for t in bset ^ eset:  # assigned in exactly one arm
            own_rbw = rbw_body if t in bset else rbw_else
            if t not in own_rbw:
                return node
        targets = sorted(bset | eset)
        uid = self._uid()
        reads = rbw_body | rbw_else
        params = [t for t in targets if t in reads]
        tfn = _branch_fn(f"__pt_true_{uid}", node.body, targets, params)
        ffn = _branch_fn(f"__pt_false_{uid}", node.orelse or [], targets,
                         params)
        assign = ast.Assign(
            targets=[ast.Tuple(
                elts=[ast.Name(id=t, ctx=ast.Store()) for t in targets],
                ctx=ast.Store())],
            value=ast.Call(func=ast.Name(id="__pt_if", ctx=ast.Load()),
                           args=[_conv_test(node.test),
                                 ast.Name(id=tfn.name, ctx=ast.Load()),
                                 ast.Name(id=ffn.name, ctx=ast.Load())],
                           keywords=[]))
        self.changed = True
        return [ast.copy_location(x, node) for x in (tfn, ffn, assign)]

    def visit_While(self, node):
        self.generic_visit(node)
        if node.orelse:
            return node
        uid = self._uid()
        lowered = _lower_breaks(self._lower_appends(node.body), uid)
        if lowered is None:
            return node
        body, has_break = lowered
        test = _conv_test(node.test)
        prelude = []
        if has_break:
            brk = f"__pt_brk_{uid}"
            # brk wins over the original predicate (evaluated first, so the
            # original test may even rely on loop-var bounds kept by brk)
            test = _call("__pt_bool_and",
                         _call("__pt_bool_not", _name(brk)), _thunk(test))
            prelude.append(ast.Assign(targets=[_name(brk, store=True)],
                                      value=ast.Constant(value=False)))
        carry = _assigned_names(body)
        if not carry:
            return node
        carry = sorted(set(carry))
        # every carried name must provably pre-exist (read before written in
        # test/body) — a loop-local temp would be unbound in the initial
        # carry list where the eager loop ran fine
        pre = _read_before_write([ast.Expr(value=test)] + body)
        if any(c not in pre for c in carry):
            return node
        cargs = ast.arguments(
            posonlyargs=[], args=[ast.arg(arg=c) for c in carry], vararg=None,
            kwonlyargs=[], kw_defaults=[], kwarg=None, defaults=[])
        cond_fn = ast.FunctionDef(
            name=f"__pt_cond_{uid}", args=cargs,
            body=[ast.Return(value=test)], decorator_list=[],
            returns=None)
        body_fn = ast.FunctionDef(
            name=f"__pt_body_{uid}", args=cargs,
            body=list(body) + [ast.Return(value=ast.Tuple(
                elts=[ast.Name(id=c, ctx=ast.Load()) for c in carry],
                ctx=ast.Load()))],
            decorator_list=[], returns=None)
        assign = ast.Assign(
            targets=[ast.Tuple(
                elts=[ast.Name(id=c, ctx=ast.Store()) for c in carry],
                ctx=ast.Store())],
            value=ast.Call(
                func=ast.Name(id="__pt_while", ctx=ast.Load()),
                args=[ast.Name(id=cond_fn.name, ctx=ast.Load()),
                      ast.Name(id=body_fn.name, ctx=ast.Load()),
                      ast.List(elts=[ast.Name(id=c, ctx=ast.Load())
                                     for c in carry], ctx=ast.Load())],
                keywords=[]))
        self.changed = True
        return [ast.copy_location(x, node)
                for x in prelude + [cond_fn, body_fn, assign]]

    def visit_For(self, node):
        """`for i in range(...)` / `for x in xs:` -> __pt_for_* carry loop
        (reference loop_transformer.py:486 for-to-while lowering)."""
        self.generic_visit(node)
        if node.orelse or not isinstance(node.target, ast.Name):
            return node
        uid = self._uid()
        lowered = _lower_breaks(self._lower_appends(node.body), uid,
                                for_loop=True)
        if lowered is None:
            return node
        body, has_break = lowered
        prelude = []
        if has_break:
            prelude.append(ast.Assign(
                targets=[_name(f"__pt_brk_{uid}", store=True)],
                value=ast.Constant(value=False)))
        carry = _assigned_names(body)
        if carry is None:
            return node
        carry = sorted(set(carry))
        loop_var = node.target.id
        if loop_var in carry or not carry:
            return node  # reassigned loop var / pure-side-effect body: bail
        pre = _read_before_write(body)
        if any(c not in pre for c in carry):
            return node
        it = node.iter
        if (isinstance(it, ast.Call) and isinstance(it.func, ast.Name)
                and it.func.id == "range" and not it.keywords
                and 1 <= len(it.args) <= 3
                and not any(isinstance(a, ast.Starred) for a in it.args)):
            helper = "__pt_for_range"
            iter_arg = ast.Tuple(elts=list(it.args), ctx=ast.Load())
        else:
            helper = "__pt_for_iter"
            iter_arg = it
        cargs = ast.arguments(
            posonlyargs=[], args=[ast.arg(arg=loop_var)] +
            [ast.arg(arg=c) for c in carry], vararg=None,
            kwonlyargs=[], kw_defaults=[], kwarg=None, defaults=[])
        body_fn = ast.FunctionDef(
            name=f"__pt_fbody_{uid}", args=cargs,
            body=list(body) + [ast.Return(value=ast.Tuple(
                elts=[ast.Name(id=c, ctx=ast.Load()) for c in carry],
                ctx=ast.Load()))],
            decorator_list=[], returns=None)
        call = _call(helper, iter_arg, _name(body_fn.name),
                     ast.List(elts=[_name(c) for c in carry],
                              ctx=ast.Load()))
        if has_break:
            # tell the runtime which carry slot is the break flag so the
            # concrete path exits early (plain Python `for` semantics)
            call.args.append(ast.Constant(
                value=carry.index(f"__pt_brk_{uid}")))
        assign = ast.Assign(
            targets=[ast.Tuple(
                elts=[ast.Name(id=c, ctx=ast.Store()) for c in carry],
                ctx=ast.Store())],
            value=call)
        self.changed = True
        return [ast.copy_location(x, node)
                for x in prelude + [body_fn, assign]]


def _no_args():
    return ast.arguments(posonlyargs=[], args=[], vararg=None, kwonlyargs=[],
                         kw_defaults=[], kwarg=None, defaults=[])


def _normalize_fallthrough(tree):
    """`if t: return A` followed by `return B` -> explicit else, so the
    two-arm return pattern fires (the most common early-return shape)."""
    for node in ast.walk(tree):
        for field in ("body", "orelse", "finalbody"):
            stmts = getattr(node, field, None)
            if not isinstance(stmts, list):
                continue
            for i in range(len(stmts) - 1):
                st, nxt = stmts[i], stmts[i + 1]
                if (isinstance(st, ast.If) and not st.orelse
                        and len(st.body) == 1
                        and isinstance(st.body[0], ast.Return)
                        and st.body[0].value is not None
                        and isinstance(nxt, ast.Return)
                        and nxt.value is not None):
                    st.orelse = [nxt]
                    del stmts[i + 1]
                    break


def convert_to_static(fn):
    """Rewrite fn's simple tensor-dependent if/while into runtime-dispatched
    control flow. Returns fn unchanged when there is nothing to convert or
    the source is unavailable/has closures (lite scope)."""
    raw = fn.__func__ if isinstance(fn, types.MethodType) else fn
    if getattr(raw, "__closure__", None):
        return fn  # free variables can't be rebound through exec
    try:
        src = textwrap.dedent(inspect.getsource(raw))
        tree = ast.parse(src)
    except (OSError, TypeError, SyntaxError, IndentationError):
        return fn
    fdef = tree.body[0]
    if not isinstance(fdef, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return fn

    def _is_to_static(dec) -> bool:
        node = dec.func if isinstance(dec, ast.Call) else dec
        name = node.attr if isinstance(node, ast.Attribute) else \
            getattr(node, "id", "")
        return name in ("to_static", "convert_to_static")

    # drop only the to_static-family decorators (they triggered this call);
    # behavioral decorators like no_grad re-apply on exec
    fdef.decorator_list = [d for d in fdef.decorator_list
                           if not _is_to_static(d)]
    _normalize_fallthrough(fdef)
    tr = _CtrlFlow(list_names=_local_list_names(fdef))
    # transform only the top-level function's body (nested defs keep scope)
    new_body = []
    for st in fdef.body:
        out = tr.visit(st)
        new_body.extend(out if isinstance(out, list) else [out])
    fdef.body = new_body
    # call-graph conversion (reference call_transformer.py:25), AFTER the
    # control-flow pass so its `range(...)`/helper patterns see the original
    # spellings: every remaining call site dispatches through
    # __pt_convert_call, so user helpers with tensor control flow convert
    # too instead of silently tracing one branch
    wc = _WrapCalls()
    fdef.body = [wc.visit(st) for st in fdef.body]
    if not (tr.changed or wc.changed):
        return fn
    ast.fix_missing_locations(tree)
    # exec against the LIVE module globals (plus the __pt_* helpers): a
    # converted function must see later rebinding of module-level names
    # (monkeypatching, lazy globals) exactly like the original — a snapshot
    # dict would pin every callee at conversion time. Only __pt_-prefixed
    # names are added to the module namespace.
    glb = raw.__globals__
    glb["__pt_if"] = _runtime_if
    glb["__pt_while"] = _runtime_while
    glb["__pt_for_range"] = _runtime_for_range
    glb["__pt_for_iter"] = _runtime_for_iter
    glb["__pt_bool_and"] = _runtime_bool_and
    glb["__pt_bool_or"] = _runtime_bool_or
    glb["__pt_bool_not"] = _runtime_bool_not
    glb["__pt_sel"] = _runtime_select
    glb["__pt_convert_call"] = _runtime_convert_call
    loc: dict = {}
    try:
        exec(compile(tree, f"<dy2static:{raw.__name__}>", "exec"), glb, loc)
    except Exception:  # e.g. a decorator that only resolves in a closure
        return fn
    new_fn = functools.wraps(raw)(loc[fdef.name])
    if isinstance(fn, types.MethodType):
        return types.MethodType(new_fn, fn.__self__)
    return new_fn
