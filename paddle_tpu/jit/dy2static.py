"""AST-lite dygraph-to-static conversion.

Reference: python/paddle/fluid/dygraph/dygraph_to_static/program_translator.py
:775 + ifelse_transformer.py / loop_transformer.py — the reference transpiles
EVERY Python `if`/`while` into runtime-dispatched control-flow ops so
tensor-dependent branches work under tracing.

TPU-native lite version: an ast pass rewrites the *simple* shapes —
  * `if t: return a` / `else: return b`          -> __pt_if(t, fa, fb)
  * `if t:` assigning plain names in each branch -> branch closures returning
    the assigned tuple, dispatched through __pt_if
  * `while t:` whose body assigns plain names    -> __pt_while carry loop
into `paddle_tpu.static.nn.cond` / `while_loop`, which run plain Python when
the predicate is concrete and lower to `lax.cond`/`lax.while_loop` when it is
traced. Anything more complex is left untouched — tracing such code then hits
Tensor.__bool__'s pointer error instead of silently specializing a branch.
"""
from __future__ import annotations

import ast
import functools
import inspect
import textwrap
import types
from typing import List, Optional


def _runtime_if(pred, true_fn, false_fn):
    from ..static import nn as static_nn

    return static_nn.cond(pred, true_fn, false_fn)


def _runtime_while(cond_fn, body_fn, loop_vars):
    from ..static import nn as static_nn

    out = static_nn.while_loop(cond_fn, body_fn, list(loop_vars))
    return tuple(out)


def _assigned_names(stmts) -> Optional[List[str]]:
    """Plain Name targets assigned in stmts; None if anything else happens
    (calls with side effects are fine — only the statement SHAPE matters)."""
    names = []
    for st in stmts:
        if isinstance(st, ast.Assign):
            for t in st.targets:
                if isinstance(t, ast.Name):
                    names.append(t.id)
                elif isinstance(t, ast.Tuple) and all(
                        isinstance(e, ast.Name) for e in t.elts):
                    names.extend(e.id for e in t.elts)
                else:
                    return None
        elif isinstance(st, ast.AugAssign):
            if isinstance(st.target, ast.Name):
                names.append(st.target.id)
            else:
                return None
        else:
            return None
    return names


def _read_before_write(stmts, extra_reads=()) -> set:
    """Names loaded before their first assignment across the statement
    sequence — i.e. names the branch needs to pre-exist."""
    assigned: set = set()
    reads: set = set(extra_reads)
    for st in stmts:
        for node in ast.walk(st):
            if isinstance(node, ast.AugAssign) and isinstance(node.target,
                                                              ast.Name):
                if node.target.id not in assigned:
                    reads.add(node.target.id)
        for node in ast.walk(st):
            if (isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load)
                    and node.id not in assigned):
                reads.add(node.id)
        for node in ast.walk(st):
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for t in targets:
                    if isinstance(t, ast.Name):
                        assigned.add(t.id)
                    elif isinstance(t, ast.Tuple):
                        assigned.update(e.id for e in t.elts
                                        if isinstance(e, ast.Name))
    return reads


def _branch_fn(name: str, stmts, targets: List[str], params: List[str]):
    """def <name>(p=p, ...): <stmts>; return (targets...)"""
    args = ast.arguments(
        posonlyargs=[], args=[ast.arg(arg=p) for p in params], vararg=None,
        kwonlyargs=[], kw_defaults=[], kwarg=None,
        defaults=[ast.Name(id=p, ctx=ast.Load()) for p in params])
    ret = ast.Return(value=ast.Tuple(
        elts=[ast.Name(id=t, ctx=ast.Load()) for t in targets],
        ctx=ast.Load()))
    return ast.FunctionDef(name=name, args=args, body=list(stmts) + [ret],
                           decorator_list=[], returns=None)


class _CtrlFlow(ast.NodeTransformer):
    def __init__(self):
        self.changed = False
        self.n = 0

    def _uid(self):
        self.n += 1
        return self.n

    # `if`/`while` nested in defs/lambdas keep their own scope — don't touch
    def visit_FunctionDef(self, node):
        return node

    def visit_AsyncFunctionDef(self, node):
        return node

    def visit_Lambda(self, node):
        return node

    def visit_If(self, node):
        self.generic_visit(node)
        # pattern A: both arms are a single `return <expr>`
        if (len(node.body) == 1 and isinstance(node.body[0], ast.Return)
                and len(node.orelse) == 1
                and isinstance(node.orelse[0], ast.Return)
                and node.body[0].value is not None
                and node.orelse[0].value is not None):
            self.changed = True
            call = ast.Call(
                func=ast.Name(id="__pt_if", ctx=ast.Load()),
                args=[node.test,
                      ast.Lambda(args=_no_args(), body=node.body[0].value),
                      ast.Lambda(args=_no_args(), body=node.orelse[0].value)],
                keywords=[])
            return ast.copy_location(ast.Return(value=call), node)
        # pattern B: both arms only assign plain names. A target assigned in
        # one arm only is convertible ONLY when that arm reads it before
        # writing (proof it pre-exists) — otherwise the other arm's return
        # would unbind a name that eager code never touched (e.g. a dead
        # store), so the whole `if` stays unconverted.
        body_names = _assigned_names(node.body)
        else_names = _assigned_names(node.orelse) if node.orelse else []
        if body_names is None or else_names is None or not (body_names or
                                                            else_names):
            return node
        bset, eset = set(body_names), set(else_names)
        rbw_body = _read_before_write(node.body)
        rbw_else = _read_before_write(node.orelse)
        for t in bset ^ eset:  # assigned in exactly one arm
            own_rbw = rbw_body if t in bset else rbw_else
            if t not in own_rbw:
                return node
        targets = sorted(bset | eset)
        uid = self._uid()
        reads = rbw_body | rbw_else
        params = [t for t in targets if t in reads]
        tfn = _branch_fn(f"__pt_true_{uid}", node.body, targets, params)
        ffn = _branch_fn(f"__pt_false_{uid}", node.orelse or [], targets,
                         params)
        assign = ast.Assign(
            targets=[ast.Tuple(
                elts=[ast.Name(id=t, ctx=ast.Store()) for t in targets],
                ctx=ast.Store())],
            value=ast.Call(func=ast.Name(id="__pt_if", ctx=ast.Load()),
                           args=[node.test,
                                 ast.Name(id=tfn.name, ctx=ast.Load()),
                                 ast.Name(id=ffn.name, ctx=ast.Load())],
                           keywords=[]))
        self.changed = True
        return [ast.copy_location(x, node) for x in (tfn, ffn, assign)]

    def visit_While(self, node):
        self.generic_visit(node)
        if node.orelse:
            return node
        carry = _assigned_names(node.body)
        if not carry:
            return node
        carry = sorted(set(carry))
        # every carried name must provably pre-exist (read before written in
        # test/body) — a loop-local temp would be unbound in the initial
        # carry list where the eager loop ran fine
        pre = _read_before_write([ast.Expr(value=node.test)] + node.body)
        if any(c not in pre for c in carry):
            return node
        uid = self._uid()
        cargs = ast.arguments(
            posonlyargs=[], args=[ast.arg(arg=c) for c in carry], vararg=None,
            kwonlyargs=[], kw_defaults=[], kwarg=None, defaults=[])
        cond_fn = ast.FunctionDef(
            name=f"__pt_cond_{uid}", args=cargs,
            body=[ast.Return(value=node.test)], decorator_list=[],
            returns=None)
        body_fn = ast.FunctionDef(
            name=f"__pt_body_{uid}", args=cargs,
            body=list(node.body) + [ast.Return(value=ast.Tuple(
                elts=[ast.Name(id=c, ctx=ast.Load()) for c in carry],
                ctx=ast.Load()))],
            decorator_list=[], returns=None)
        assign = ast.Assign(
            targets=[ast.Tuple(
                elts=[ast.Name(id=c, ctx=ast.Store()) for c in carry],
                ctx=ast.Store())],
            value=ast.Call(
                func=ast.Name(id="__pt_while", ctx=ast.Load()),
                args=[ast.Name(id=cond_fn.name, ctx=ast.Load()),
                      ast.Name(id=body_fn.name, ctx=ast.Load()),
                      ast.List(elts=[ast.Name(id=c, ctx=ast.Load())
                                     for c in carry], ctx=ast.Load())],
                keywords=[]))
        self.changed = True
        return [ast.copy_location(x, node)
                for x in (cond_fn, body_fn, assign)]


def _no_args():
    return ast.arguments(posonlyargs=[], args=[], vararg=None, kwonlyargs=[],
                         kw_defaults=[], kwarg=None, defaults=[])


def _normalize_fallthrough(tree):
    """`if t: return A` followed by `return B` -> explicit else, so the
    two-arm return pattern fires (the most common early-return shape)."""
    for node in ast.walk(tree):
        for field in ("body", "orelse", "finalbody"):
            stmts = getattr(node, field, None)
            if not isinstance(stmts, list):
                continue
            for i in range(len(stmts) - 1):
                st, nxt = stmts[i], stmts[i + 1]
                if (isinstance(st, ast.If) and not st.orelse
                        and len(st.body) == 1
                        and isinstance(st.body[0], ast.Return)
                        and st.body[0].value is not None
                        and isinstance(nxt, ast.Return)
                        and nxt.value is not None):
                    st.orelse = [nxt]
                    del stmts[i + 1]
                    break


def convert_to_static(fn):
    """Rewrite fn's simple tensor-dependent if/while into runtime-dispatched
    control flow. Returns fn unchanged when there is nothing to convert or
    the source is unavailable/has closures (lite scope)."""
    raw = fn.__func__ if isinstance(fn, types.MethodType) else fn
    if getattr(raw, "__closure__", None):
        return fn  # free variables can't be rebound through exec
    try:
        src = textwrap.dedent(inspect.getsource(raw))
        tree = ast.parse(src)
    except (OSError, TypeError, SyntaxError, IndentationError):
        return fn
    fdef = tree.body[0]
    if not isinstance(fdef, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return fn

    def _is_to_static(dec) -> bool:
        node = dec.func if isinstance(dec, ast.Call) else dec
        name = node.attr if isinstance(node, ast.Attribute) else \
            getattr(node, "id", "")
        return name in ("to_static", "convert_to_static")

    # drop only the to_static-family decorators (they triggered this call);
    # behavioral decorators like no_grad re-apply on exec
    fdef.decorator_list = [d for d in fdef.decorator_list
                           if not _is_to_static(d)]
    _normalize_fallthrough(fdef)
    tr = _CtrlFlow()
    # transform only the top-level function's body (nested defs keep scope)
    new_body = []
    for st in fdef.body:
        out = tr.visit(st)
        new_body.extend(out if isinstance(out, list) else [out])
    fdef.body = new_body
    if not tr.changed:
        return fn
    ast.fix_missing_locations(tree)
    glb = dict(raw.__globals__)
    glb["__pt_if"] = _runtime_if
    glb["__pt_while"] = _runtime_while
    loc: dict = {}
    try:
        exec(compile(tree, f"<dy2static:{raw.__name__}>", "exec"), glb, loc)
    except Exception:  # e.g. a decorator that only resolves in a closure
        return fn
    new_fn = functools.wraps(raw)(loc[fdef.name])
    if isinstance(fn, types.MethodType):
        return types.MethodType(new_fn, fn.__self__)
    return new_fn
