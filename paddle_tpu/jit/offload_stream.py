"""Streamed parameter offload: beyond-residence training on one chip
(3.08B measured on the 9.5GB chip; the resident ceiling is 1.83B).

Reference: python/paddle/distributed/fleet/meta_parallel/sharding/
sharding_stage3.py:50 (param offload) + :737 (TaskFlow prefetch) — the
reference streams each segment's params H2D ahead of use and keeps the
optimizer state host-side.

TPU-native mapping, ONE compiled step end-to-end:
- the transformer stack's [L, ...] stacked parameters (and their optimizer
  state) live in the TPU's PINNED HOST memory space;
- the forward copies one layer's slice into HBM right before its compute
  (XLA emits async copy-start/done — the prefetch), and autodiff's transpose
  of those copies lands the stacked gradient accumulator back in host memory;
- the optimizer update then walks the layers again: slice param/grad/state
  H2D, apply the functional rule on-device, and dynamic-update-slice the new
  values straight back into the host buffers.
Nothing ever crosses to another backend — every transfer is a TPU runtime
DMA (the CPU-backend hop costs ~15 s/GB through the remote-chip tunnel).
HBM holds only: edge params (embeddings/head/norms) + their state, one or
two layers' tensors in flight, and remat boundary activations.

Per-layer optimizer state is initialized per SLICE (factored optimizers see
the true [d1, d2] layer shape, not the stacked [L, d1, d2]) — the same
semantics as training the layers unstacked.
"""
from __future__ import annotations

import contextlib
import queue
import threading
import time
from typing import Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..core import autograd
from ..core.tensor import Tensor
from ..framework import random as random_mod
from ..nn.layer.layers import Layer


# -- latency-hiding streaming lane -------------------------------------------
# The reference hides host<->device traffic behind compute with
# ForwardPostHooks + TaskFlow prefetch (sharding_stage3.py:737); the
# TPU-native counterpart is a background thread issuing jax.device_put while
# the main thread keeps dispatching executables — the same one-thread double
# buffer io/prefetch.py uses for batches, here carrying parameter/optimizer
# stream groups for the offload train path (ZeRO-Offload's delayed, bucketed
# CPU update, Rajbhandari et al.).

_LANE_FAM = None  # lazily-bound "offload_stream" counter family


def _lane_fam():
    global _LANE_FAM
    if _LANE_FAM is None:
        from ..observability import family

        _LANE_FAM = family("offload_stream", ("metric",))
    return _LANE_FAM


_RESIL = None  # lazily-bound (faults injector, transient, retry_policy)


def _resil():
    global _RESIL
    if _RESIL is None:
        from ..distributed.resilience import metrics as rmetrics
        from ..distributed.resilience.faults import injector
        from ..distributed.resilience.retry import retry_policy, transient

        _RESIL = (injector, transient, retry_policy, rmetrics)
    return _RESIL


_PINNED_PROBE = [False, None]  # (probed, sharding-or-None), process-wide


def _probe_pinned_host():
    """Capability probe: a working ``pinned_host`` memory-kind placement
    on the default accelerator, verified by an actual 1-element
    round-trip (some jax builds LIST the memory kind but cannot place
    into it). CPU backends return None — everything is host RAM there
    and tier-1 must stay byte-identical on the direct path."""
    if _PINNED_PROBE[0]:
        return _PINNED_PROBE[1]
    sh = None
    try:
        from ..distributed.meta_parallel.stage_stack import _memory_sharding

        cand = _memory_sharding("pinned_host")
        if cand is not None:
            probe = jax.device_put(np.zeros((1,), np.float32), cand)
            probe.block_until_ready()
            jax.device_put(probe, jax.devices()[0]).block_until_ready()
            sh = cand
    except Exception:
        sh = None
    _PINNED_PROBE[0] = True
    _PINNED_PROBE[1] = sh
    return sh


def pinned_host_supported() -> bool:
    """Does this backend expose a usable pinned_host staging space?"""
    return _probe_pinned_host() is not None


class StreamTransferError(RuntimeError):
    """A lane transfer failed after its retry budget. Carries the failing
    direction, stream-group tag and parameter names so the raise at the
    consumer's ``wait()`` names WHAT was in flight, not just why. The
    original exception is ``__cause__``."""

    def __init__(self, kind: str, tag, names, cause: BaseException):
        self.kind = kind
        self.tag = tag
        self.names = tuple(names or ())
        named = f" params={list(self.names)}" if self.names else ""
        super().__init__(
            f"stream transfer failed: kind={kind} group={tag}{named}: "
            f"{type(cause).__name__}: {cause}")
        self.__cause__ = cause


def plan_stream_groups(nbytes_list: Sequence[int],
                       segment_size: int = 2 ** 20,
                       buffer_max_size: int = 2 ** 23) -> List[List[int]]:
    """Partition parameters (given per-param byte sizes, walk order
    preserved) into contiguous stream groups — the unit the offload lane
    transfers and the host update executes on.

    ``segment_size`` is the reference group_sharded_parallel knob: a group
    closes once it holds at least this many bytes (small params coalesce
    instead of each paying a transfer/dispatch). ``buffer_max_size`` caps
    the staging buffer: a group never grows past it by adding another
    param (one param larger than the cap still gets its own group — it
    cannot be split without changing the update math)."""
    segment_size = max(int(segment_size), 1)
    buffer_max_size = max(int(buffer_max_size), segment_size)
    groups: List[List[int]] = []
    cur: List[int] = []
    cur_bytes = 0
    for i, nb in enumerate(nbytes_list):
        nb = int(nb)
        if cur and (cur_bytes + nb > buffer_max_size
                    or cur_bytes >= segment_size):
            groups.append(cur)
            cur, cur_bytes = [], 0
        cur.append(i)
        cur_bytes += nb
    if cur:
        groups.append(cur)
    return groups


def _flight_event(kind: str, **data) -> None:
    """Land a lane event in the flight recorder's ring WHEN one exists
    (never creates one — training runs without a recorder pay only an
    attribute read). Telemetry must never mask the event it records."""
    try:
        from ..observability.trace import flight

        rec = flight._RECORDER
        if rec is not None:
            rec.record_event(kind, **data)
    except Exception:
        pass


class _TransferHandle:
    """One in-flight group transfer; ``wait()`` blocks the consumer and
    charges the blocked time to the lane's ``stall_ms``."""

    __slots__ = ("_event", "_box", "_lane", "_nbytes", "_unstaged",
                 "_dispatched", "_dispatch_taken")

    def __init__(self, lane):
        self._event = threading.Event()
        self._dispatched = threading.Event()  # transfers ISSUED (results
        # exist as jax futures) even though bytes may still be in flight
        self._dispatch_taken = False  # a consumer HOLDS the issued
        # futures (set under the lane lock by wait_dispatched)
        self._box: list = [None, None]  # result, exception
        self._lane = lane
        self._nbytes = 0      # staged bytes this handle accounts for
        self._unstaged = False  # staging decrement already applied

    def done(self) -> bool:
        return self._event.is_set()

    def wait(self):
        if not self._event.is_set():
            t0 = time.perf_counter()
            self._event.wait()
            self._lane._note_stall((time.perf_counter() - t0) * 1e3)
        if self._box[1] is not None:
            raise self._box[1]
        return self._box[0]

    def _set_dispatched(self, out) -> None:
        with self._lane._lock:
            self._box[0] = out
        self._dispatched.set()

    def _unpublish_for_retry(self) -> bool:
        """Worker-side half of the retry handshake: withdraw the issued
        futures so the retry can republish. Returns False when a
        consumer already took them — then retrying is unsafe (their
        arrays could not be replaced) and the caller must fail sticky."""
        with self._lane._lock:
            if self._dispatch_taken:
                return False
            self._box[0] = None
            self._dispatched.clear()
            return True

    def wait_dispatched(self):
        """Return the transfer's result arrays as soon as they are ISSUED
        (jax async futures) instead of landed — the cross-step pipeline
        fill: a consumer handing these straight to the next dispatched
        executable lets the runtime sequence the landing while the host
        races ahead to submit the next step's group-0 grad download. A
        transfer that fails after issue surfaces at the next lane
        interaction (the PR-6 sticky-failure contract), not here."""
        t0 = None
        while True:
            if self._event.is_set():
                break  # terminal: landed or failed-for-good
            if self._dispatched.is_set():
                taken = None
                with self._lane._lock:
                    if self._box[0] is not None:
                        # taking the futures forecloses any later retry
                        # (the worker's _unpublish_for_retry checks this
                        # under the same lock)
                        self._dispatch_taken = True
                        taken = self._box[0]
                if taken is not None:
                    if t0 is not None:  # _note_stall takes the lane lock
                        self._lane._note_stall(
                            (time.perf_counter() - t0) * 1e3)
                    return taken
                continue  # republish in flight (a retry withdrew them)
            if t0 is None:
                t0 = time.perf_counter()
            self._dispatched.wait(0.05)
        if t0 is not None:
            self._lane._note_stall((time.perf_counter() - t0) * 1e3)
        if self._box[1] is not None:
            raise self._box[1]
        return self._box[0]


class StreamLane:
    """Double-buffered host<->device transfer lane for stream groups.

    A single worker thread executes submitted transfers in order through a
    bounded two-deep queue (the device ring): while group *i*'s update
    computes, the lane is moving group *i+1* down and group *i-1* up, and a
    third submission blocks until a slot frees — the backpressure that caps
    staging memory at two groups. ``overlap=False`` runs every transfer
    inline at submit (the serialized A/B twin: identical dispatch order,
    nothing hidden).

    Telemetry (``observability`` family ``offload_stream`` + per-lane
    ``stats()``): bytes up/down, transfer/lane-busy ms, consumer stall ms,
    groups in flight. ``overlap_efficiency`` = transfer time hidden behind
    compute / total transfer time.
    """

    _LANE_NO = [0]

    def __init__(self, overlap: bool = True, depth: int = 2,
                 pinned_staging: Optional[bool] = None):
        import os as _os

        self.overlap = bool(overlap)
        self.depth = int(depth)
        if pinned_staging is None:
            pinned_staging = _os.environ.get(
                "PT_OFFLOAD_PINNED_STAGING", "1").strip().lower() not in (
                "0", "false", "off")
        self._pinned_sh = _probe_pinned_host() if pinned_staging else None
        self.pinned_staging = self._pinned_sh is not None
        from ..analysis.lockdep import lock as _named_lock  # lazy: no cycle

        self._lock = _named_lock("jit.StreamLane._lock")
        self._stats = {"h2d_bytes": 0, "d2h_bytes": 0, "transfer_ms": 0.0,
                       "stall_ms": 0.0, "transfers": 0, "in_flight_sum": 0,
                       "retries": 0, "pinned_staged": 0}
        self._staging_bytes = 0  # bytes of submissions not yet landed
        # memory truth: the lane's staging working set (the two-group cap
        # the offload estimator models) rides in the `memory` provider
        try:
            from ..observability.memory import register_component

            StreamLane._LANE_NO[0] += 1
            register_component(
                f"stream_lane#{StreamLane._LANE_NO[0]}:staging",
                type(self).staging_bytes, owner=self)
        except Exception:
            pass
        self.events: List[tuple] = []  # (kind, tag) in submission order
        self._q: "queue.Queue" = queue.Queue(maxsize=self.depth)
        self._thread: Optional[threading.Thread] = None
        self._closed = False
        self._seq = 0          # submission index (fault-site id)
        self._failure: Optional[BaseException] = None

    # -- submission -----------------------------------------------------------
    def submit(self, kind: str, arrays, placements, tag=None, names=None
               ) -> _TransferHandle:
        """Enqueue one group transfer. ``kind`` is ``"h2d"`` (params up) or
        ``"d2h"`` (grads/state down); ``placements`` is one sharding/device
        for every array or a per-array sequence; ``names`` (optional) are
        the in-flight parameter names, carried into any raised error.
        Blocks while the two-deep ring is full. A lane that already failed
        a transfer re-raises that failure here — the pipeline is poisoned
        and every subsequent interaction must say so."""
        if self._closed:
            raise RuntimeError("StreamLane is closed")
        if self._failure is not None:
            raise self._failure
        handle = _TransferHandle(self)
        if not isinstance(placements, (list, tuple)):
            placements = [placements] * len(arrays)
        handle._nbytes = sum(int(getattr(a, "nbytes", 0)) for a in arrays)
        with self._lock:
            self.events.append((kind, tag))
            self._stats["in_flight_sum"] += self._q.qsize()
            self._staging_bytes += handle._nbytes
            seq = self._seq
            self._seq += 1
        if not self.overlap:
            self._run_job(kind, arrays, placements, handle, tag, names, seq,
                          serialized=True)
            return handle
        if self._thread is None:
            self._thread = threading.Thread(target=self._worker, daemon=True,
                                            name="pt-offload-stream")
            self._thread.start()
        self._q.put((kind, arrays, placements, handle, tag, names, seq))
        if self._failure is not None and not handle._event.is_set():
            # the worker may have poisoned + drained (or exited) while we
            # were blocked in put() — our job could be sitting in a queue no
            # thread reads. Fail it here; idempotent vs the worker's drain.
            handle._box[1] = self._failure
            self._unstage(handle)
            handle._event.set()
        return handle

    def _unstage(self, handle) -> None:
        """Release ``handle``'s staging-byte accounting exactly once —
        called from whichever path completes the job (normal run, the
        poisoned-queue drain, or the submit-side orphan rescue), which can
        race each other."""
        with self._lock:
            if not handle._unstaged:
                handle._unstaged = True
                self._staging_bytes -= handle._nbytes

    def _worker(self):
        while True:
            job = self._q.get()
            if job is None:
                return
            self._run_job(*job)
            if self._failure is not None:
                # the walk is poisoned: fail everything already queued so
                # every consumer wait() raises instead of hanging, then die
                while True:
                    try:
                        job = self._q.get_nowait()
                    except queue.Empty:
                        break
                    if job is None:
                        break
                    job[3]._box[1] = self._failure
                    self._unstage(job[3])
                    job[3]._event.set()
                with self._lock:
                    self._thread = None
                return

    def _transfer_once(self, kind, arrays, placements, tag, seq, handle):
        injector, _transient, _policy, _rm = _resil()
        inj = injector()
        inj.check("slow_transfer", seq=seq, kind=kind, group=tag)
        inj.check("transfer", seq=seq, kind=kind, group=tag)
        if kind == "h2d":
            arrays = self._stage_pinned(arrays)
        out = [jax.device_put(a, p) if p is not None
               else jax.device_put(a)
               for a, p in zip(arrays, placements)]
        # results exist as async futures NOW: a wait_dispatched() consumer
        # may take them and keep pipelining across the step boundary
        handle._set_dispatched(out)
        # the transfer is only *done* when the bytes have landed —
        # blocking HERE (off the consumer thread when overlapped) is
        # what makes stall_ms mean "transfer not hidden"
        for o in out:
            o.block_until_ready()
        return out

    def _stage_pinned(self, arrays):
        """Bounce h2d source buffers living on the CPU *backend* through
        the accelerator's pinned_host memory space when this jax exposes
        one (the reference TaskFlow keeps its staging buffers pinned so
        the device DMA engine uploads without an intermediate pageable
        copy). Probed once; backends without the memory kind — CPU tier-1
        included — take the direct path untouched."""
        if not self.pinned_staging or self._pinned_sh is None:
            return arrays
        staged = []
        for a in arrays:
            try:
                on_cpu = all(d.platform == "cpu" for d in a.devices())
            except Exception:
                on_cpu = False
            staged.append(jax.device_put(a, self._pinned_sh)
                          if on_cpu else a)
        with self._lock:
            self._stats["pinned_staged"] += len(
                [1 for s, a in zip(staged, arrays) if s is not a])
        return staged

    def _run_job(self, kind, arrays, placements, handle, tag, names, seq,
                 serialized=False):
        t0 = time.perf_counter()
        try:
            injector, transient, retry_policy, rmetrics = _resil()
            retries, backoff_ms = retry_policy()
            attempt = 0
            nbytes = 0
            while True:
                try:
                    out = self._transfer_once(kind, arrays, placements, tag,
                                              seq, handle)
                    handle._box[0] = out
                    nbytes = sum(int(getattr(o, "nbytes", 0)) for o in out)
                    break
                except BaseException as e:
                    if attempt < retries and transient(e) \
                            and handle._unpublish_for_retry():
                        # bounded retry-with-backoff: transient transfer
                        # faults (flaky host link, injected) are eaten
                        # here — including landing-phase failures, AS LONG
                        # AS no wait_dispatched() consumer already holds
                        # the failed attempt's futures (those could not be
                        # replaced; _unpublish_for_retry refuses and we
                        # fail sticky — fail-stop beats a silently-
                        # poisoned pipeline)
                        attempt += 1
                        with self._lock:
                            self._stats["retries"] += 1
                        _lane_fam().inc(("retries",))
                        rmetrics.inc("retries")
                        _flight_event("stream_retry", direction=kind, group=tag,
                                      attempt=attempt)
                        time.sleep(backoff_ms * (2 ** (attempt - 1)) / 1e3)
                        continue
                    err = StreamTransferError(kind, tag, names, e)
                    handle._box[1] = err  # surfaces at the consumer's wait()
                    self._failure = err   # ...and at every later interaction
                    _flight_event("stream_error", direction=kind, group=tag,
                                  error=str(e)[:120])
                    break
            ms = (time.perf_counter() - t0) * 1e3
            with self._lock:
                self._stats[f"{kind}_bytes"] += nbytes
                self._stats["transfer_ms"] += ms
                self._stats["transfers"] += 1
                if serialized:
                    # inline transfer: the consumer waited for all of it
                    self._stats["stall_ms"] += ms
            fam = _lane_fam()
            fam.inc((f"{kind}_bytes",), nbytes)
            fam.inc(("transfer_ms",), ms)
            fam.inc(("transfers",))
            fam.inc(("groups_in_flight_sum",), self._q.qsize())
            if serialized:
                fam.inc(("stall_ms",), ms)
        finally:
            self._unstage(handle)
            # the consumer may already be blocked in wait(): it must wake
            # even if the telemetry above throws on this worker thread
            handle._event.set()

    def submit_rows(self, rows, placement=None, kind: str = "h2d",
                    tag=None, names=None) -> "RowStreamHandle":
        """Generic row-stream API: move ONE ``[n, dim]`` row block through
        the lane (default h2d — host-gathered embedding/feature rows up to
        the device). Same overlap/backpressure/retry/telemetry contract
        as the group transfers; the sparse embedding path
        (``sparse.embedding.ShardedEmbeddingTable``) is the flagship
        consumer, streaming per-batch miss rows and prefetching the next
        batch's while the current step computes."""
        handle = self.submit(kind, [rows], [placement], tag=tag,
                             names=names)
        return RowStreamHandle(handle)

    def _note_stall(self, ms: float):
        with self._lock:
            self._stats["stall_ms"] += ms
        _lane_fam().inc(("stall_ms",), ms)

    def staging_bytes(self) -> int:
        """Bytes of submitted-but-not-landed transfers — the lane's live
        staging working set (capped at ~two groups by the ring depth)."""
        with self._lock:
            return max(self._staging_bytes, 0)

    # -- reads ----------------------------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            s = dict(self._stats)
        s["staging_bytes"] = max(self._staging_bytes, 0)
        s["overlap"] = self.overlap
        s["pinned_staging"] = self.pinned_staging
        s["hidden_ms"] = max(s["transfer_ms"] - s["stall_ms"], 0.0)
        s["overlap_efficiency"] = round(
            s["hidden_ms"] / s["transfer_ms"], 4) if s["transfer_ms"] else 0.0
        return s

    def overlap_efficiency(self) -> float:
        return self.stats()["overlap_efficiency"]

    def reset_stats(self) -> None:
        with self._lock:
            for k in self._stats:
                self._stats[k] = 0 if isinstance(self._stats[k], int) else 0.0
            self.events = []

    def close(self) -> None:
        self._closed = True
        if self._thread is not None:
            self._q.put(None)
            self._thread = None

    def __del__(self):
        # lanes are owned by long-lived step objects; when the step goes,
        # the worker thread must not outlive it
        try:
            self.close()
        except Exception:
            pass


class RowStreamHandle:
    """One in-flight row-block transfer (``StreamLane.submit_rows``)."""

    __slots__ = ("_handle",)

    def __init__(self, handle: _TransferHandle):
        self._handle = handle

    def done(self) -> bool:
        return self._handle.done()

    def rows(self):
        """The landed device rows (blocks; consumer wait charged to the
        lane's ``stall_ms``)."""
        return self._handle.wait()[0]

    def rows_dispatched(self):
        """The rows as soon as the transfer is ISSUED (jax futures) — the
        cross-step fill variant; a post-issue failure surfaces at the
        next lane interaction (PR-6 sticky contract)."""
        return self._handle.wait_dispatched()[0]


@contextlib.contextmanager
def init_on_host():
    """Construct models larger than HBM without touching it: parameter init
    runs on the host CPU backend (the reference's offload models build their
    params host-side too, sharding_stage3 _segment_rank_params). Hand the
    model to StreamedTrainStep, which places every tensor — streamed stacks
    into pinned host memory, edge params into HBM.

    The global rng key moves to the CPU backend for the duration: implicit
    cross-backend reads of an accelerator-resident key inside CPU-placed
    init ops are unreliable through the remote-chip tunnel."""
    cpu = jax.devices("cpu")[0]
    gen = random_mod.default_generator()
    old_key = gen._key
    gen._key = jax.random.wrap_key_data(
        jax.device_put(np.asarray(jax.random.key_data(old_key)), cpu))
    try:
        with jax.default_device(cpu):
            yield
    finally:
        gen._key = old_key


# -- aligned host-slab packing ------------------------------------------------
# The TPU compiler's async host dynamic-update-slice emitter requires the
# written slab to be sublane/lane aligned (bf16: 16x128, f32: 8x128); 1-D or
# oddly-shaped per-layer slices (norm scales, factored optimizer vectors)
# crash it. Such buffers are stored host-side as [L, R, 128] zero-padded
# slabs; the true shape is restored on-device after each slice copy.


def _pack_dims(nelems: int, itemsize: int):
    lanes = 128
    sub = 16 if itemsize == 2 else 8
    r = -(-nelems // lanes)
    r = -(-r // sub) * sub
    return r, lanes


def _needs_pack(slice_shape, itemsize: int) -> bool:
    if (len(slice_shape) >= 2 and slice_shape[-1] % 128 == 0
            and slice_shape[-2] % (16 if itemsize == 2 else 8) == 0):
        return False
    return True


def _pack_np(arr):
    """[L, ...] numpy -> [L, R, 128] aligned slab."""
    L = arr.shape[0]
    flat = arr.reshape(L, -1)
    r, lanes = _pack_dims(flat.shape[1], arr.dtype.itemsize)
    out = np.zeros((L, r * lanes), arr.dtype)
    out[:, :flat.shape[1]] = flat
    return out.reshape(L, r, lanes)


def _unpack_dev(x, true_shape):
    n = 1
    for d in true_shape:
        n *= d
    return x.reshape(-1)[:n].reshape(true_shape)


def _pack_dev(x, packed_shape):
    r, lanes = packed_shape
    flat = x.reshape(-1)
    return jnp.pad(flat, (0, r * lanes - flat.size)).reshape(r, lanes)


def _host_available_bytes():
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemAvailable:"):
                    return int(line.split()[1]) * 1024
    except OSError:
        pass
    return None


def _find_runs(model: Layer):
    from ..distributed.meta_parallel.stage_stack import StackedStageRun

    runs = []

    def walk(layer):
        if isinstance(layer, StackedStageRun):
            runs.append(layer)
        for _, sub in getattr(layer, "_sub_layers", {}).items():
            walk(sub)

    walk(model)
    return runs


class StreamedTrainStep:
    """Single-chip capacity mode: jit.TrainStep's twin for models whose
    stacked decoder weights exceed HBM. Slower per step (every weight
    crosses the PCIe/host path twice) but lifts the resident ceiling from
    ~1.8B toward the host-RAM bound (3.08B measured at batch 2; larger
    sizes stop in the TPU compiler's memory-space assignment, which
    HBM-places the grad chains)."""

    def __init__(self, model: Layer, loss_fn: Callable, optimizer,
                 donate_host: bool | str = "auto"):
        from ..distributed.meta_parallel.stage_stack import _memory_sharding
        from ..nn.clip import ClipGradByGlobalNorm

        self.model = model
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        # donate_host halves the pinned-pool peak (params/state updated in
        # place) but DOUBLES step time through the remote tunnel (measured
        # 27.7 -> 54.2 s/step at 2.5B). 'auto' (default) donates only when
        # host RAM could not hold two copies of the parked buffers.
        self._donate_auto = donate_host == "auto"
        self.donate_host = bool(donate_host) and not self._donate_auto
        clip = optimizer._grad_clip
        if clip is not None and not isinstance(clip, ClipGradByGlobalNorm):
            raise NotImplementedError(
                "StreamedTrainStep: only ClipGradByGlobalNorm is supported "
                "for streamed params (other clips are per-tensor — apply "
                "them in the loss or drop grad_clip)")
        self._clip_norm = float(clip.clip_norm) if clip is not None else None
        runs = _find_runs(model)
        if not runs:
            raise ValueError(
                "StreamedTrainStep: the model has no StackedStageRun to "
                "stream (scan_layers=True models only); use jit.TrainStep")
        streamed_ids = {id(p) for r in runs for p in r._parameters.values()}
        opt = optimizer
        self.train_params = [p for p in opt._parameter_list
                             if not p.stop_gradient]
        self.streamed = [p for p in self.train_params
                         if id(p) in streamed_ids]
        self.edge = [p for p in self.train_params if id(p) not in streamed_ids]
        if not self.streamed:
            raise ValueError(
                "StreamedTrainStep: optimizer holds none of the stacked "
                "run's parameters (fleet order: build the stack first)")
        named = dict(model.named_parameters())
        train_ids = {id(p) for p in self.train_params}
        buffers = list(getattr(model, "named_buffers", lambda: [])())
        self.frozen = [p for p in named.values() if id(p) not in train_ids] \
            + [b for _, b in buffers]
        self._host_sh = _memory_sharding("pinned_host")
        self._dev_sh = _memory_sharding("device")
        dev = jax.devices()[0]
        cpu = jax.devices("cpu")[0]

        def to_np(arr):
            return np.asarray(arr)  # CPU-backend or device array: plain D2H

        # true per-layer shapes for streamed params (packing metadata)
        self._true_shape = {}
        self._state_shape = {}
        for r in runs:
            for (safe, _), ts in zip(r._names, r._slice_shapes):
                self._true_shape[id(r._parameters[safe])] = ts

        # per-layer optimizer state, stacked [L, ...] and parked next to the
        # params in pinned host memory; edge params/state live on device
        for p in self.streamed:
            meta = getattr(p, "_stream_meta", None)
            if meta is not None:
                # already parked by a previous StreamedTrainStep: buffers are
                # packed slabs — re-packing would corrupt them, and reading a
                # pinned_host array back through np round-trips HBM
                self._state_shape[id(p)] = meta["state_shapes"]
                continue
            L = p.data.shape[0]
            if id(p) not in opt._accumulators:
                with jax.default_device(cpu):
                    per_layer = [opt._init_state(jnp.asarray(s))
                                 for s in to_np(p.data)]
                    stacked = {
                        k: np.stack([np.asarray(st[k]) for st in per_layer])
                        for k in per_layer[0]
                    } if per_layer and per_layer[0] else {}
            else:
                # pre-existing accumulators (resident steps ran first): park
                # them too — leaving [L, ...] moments device-resident would
                # defeat the offload. Requires per-layer-stacked leaves
                # (elementwise optimizers); factored-over-stack state cannot
                # be reinterpreted per layer
                stacked = {}
                for k, v in opt._accumulators[id(p)].items():
                    if v.shape[:1] != (L,):
                        raise ValueError(
                            f"StreamedTrainStep: existing optimizer state "
                            f"'{k}' for a streamed param has shape "
                            f"{v.shape}, not per-layer [L={L}, ...]; reset "
                            f"the optimizer before switching to streaming")
                    stacked[k] = to_np(v)
            self._state_shape[id(p)] = {
                k: tuple(v.shape[1:]) for k, v in stacked.items()}
            opt._accumulators[id(p)] = {
                k: self._park(v) for k, v in stacked.items()}
            np_data = to_np(p.data)
            p.data = self._park(np_data)
            p._stream_meta = {"state_shapes": self._state_shape[id(p)]}
        for p in self.edge:
            if self._on_cpu(p.data):
                p.data = jax.device_put(to_np(p.data), dev)
            if id(p) not in opt._accumulators:
                opt._accumulators[id(p)] = opt._init_state(p.data)
        for t in self.frozen:
            if self._on_cpu(t.data):
                t.data = jax.device_put(to_np(t.data), dev)
        if self._donate_auto:
            parked = sum(int(p.data.nbytes) for p in self.streamed) + sum(
                int(v.nbytes)
                for p in self.streamed
                for v in opt._accumulators[id(p)].values())
            # no donation needs a second transient copy of the parked pool;
            # donate only when the host could not hold ~1.2x MORE than what
            # is already allocated (the pool itself was parked above, so
            # MemAvailable already excludes one copy) — donation is 2x step
            # time through the tunnel. CAVEAT: through a remote-chip tunnel
            # /proc/meminfo describes THIS client, not the TPU host — pass
            # an explicit bool when they differ.
            avail = _host_available_bytes()
            self.donate_host = bool(avail is not None
                                    and avail < 1.2 * parked)
        self._jitted = None

    def _park(self, np_arr):
        if self._host_sh is None:
            return jnp.asarray(np_arr)
        np_arr = np.asarray(np_arr)
        if _needs_pack(np_arr.shape[1:], np_arr.dtype.itemsize):
            np_arr = _pack_np(np_arr)
        return jax.device_put(np_arr, self._host_sh)

    @staticmethod
    def _on_cpu(arr) -> bool:
        try:
            return all(d.platform == "cpu" for d in arr.devices())
        except Exception:
            return False

    # -- the one compiled step ------------------------------------------------
    def _build(self, batch_arrays):
        from ..distributed.meta_parallel import stage_stack
        from . import _Binder

        model, loss_fn = self.model, self.loss_fn
        edge, streamed, frozen = self.edge, self.streamed, self.frozen
        opt = self.optimizer
        rule = type(opt)._rule
        hyper = opt._hyper()
        wd = opt._weight_decay
        decoupled = opt._decoupled
        host, devm = self._host_sh, self._dev_sh

        def flag_of(p):
            return 1.0 if (opt._decay_param_fn is None
                           or opt._decay_param_fn(p)) else 0.0

        def apply_rule(p_i, g_i, s_i, lr, step_no, flag):
            g_i = g_i.astype(p_i.dtype)
            if wd and not decoupled and flag:
                g_i = g_i + wd * p_i
            hyper_i = hyper if flag or "wd" not in hyper else \
                dict(hyper, wd=0.0)
            np_, ns = rule(p_i, g_i, s_i, lr, step_no, hyper_i)
            if wd and decoupled and flag:
                np_ = np_ - (lr * wd * p_i).astype(p_i.dtype)
            return np_, ns

        def d2h(x):
            return x if host is None else jax.device_put(x, host)

        def h2d(x):
            return x if devm is None else jax.device_put(x, devm)

        def step_fn(edge_arrays, streamed_arrays, edge_states, stream_states,
                    frozen_arrays, lr, step_no, rngkey, *batch):
            random_mod.default_generator().set_trace_key(rngkey)
            stage_stack._STREAM_MODE[0] = True
            try:
                def loss_of(edge_t, streamed_t):
                    ts = edge + streamed + frozen
                    with _Binder(ts) as b:
                        b.bind(list(edge_t) + list(streamed_t) +
                               list(frozen_arrays))
                        with autograd.no_grad():
                            loss = loss_fn(model, *[Tensor(a) for a in batch])
                    return loss.data.astype(jnp.float32)

                loss_val, (ge, gs) = jax.value_and_grad(
                    loss_of, argnums=(0, 1))(tuple(edge_arrays),
                                             tuple(streamed_arrays))

                # global-norm clip: one extra per-layer pass over the
                # host-resident grads (slice H2D, square, accumulate) BEFORE
                # any update consumes them — same semantics as
                # ClipGradByGlobalNorm over the unstacked grads. Slab
                # padding is zeros and contributes nothing to the norm.
                coef = None
                if self._clip_norm is not None:
                    sq = jnp.float32(0.0)
                    for g in ge:
                        sq = sq + jnp.sum(jnp.square(g.astype(jnp.float32)))
                    for gh in gs:
                        for i in range(gh.shape[0]):
                            g_i = h2d(jax.lax.index_in_dim(
                                gh, i, keepdims=False))
                            sq = sq + jnp.sum(
                                jnp.square(g_i.astype(jnp.float32)))
                    gnorm = jnp.sqrt(sq)
                    coef = jnp.minimum(
                        self._clip_norm / jnp.maximum(gnorm, 1e-12), 1.0)

                def clipped(g):
                    if coef is None:
                        return g
                    return (g.astype(jnp.float32) * coef).astype(g.dtype)

                # edge update: plain on-device fused rule
                new_edge, new_es = [], []
                for p, a, g, s in zip(edge, edge_arrays, ge, edge_states):
                    np_, ns = apply_rule(a, clipped(g), s, lr, step_no,
                                         flag_of(p))
                    new_edge.append(np_)
                    new_es.append(ns)

                # streamed update: walk the layers — slice H2D (unpacking
                # aligned slabs to the true shapes), rule on device, repack
                # and dynamic-update-slice back into the host buffers
                new_streamed, new_ss = [], []
                for p, ph, gh, st in zip(streamed, streamed_arrays, gs,
                                         stream_states):
                    out_p = ph
                    out_s = dict(st)
                    flag = flag_of(p)
                    p_ts = self._true_shape.get(id(p), tuple(ph.shape[1:]))
                    packed = tuple(ph.shape[1:]) != tuple(p_ts)
                    s_ts = self._state_shape.get(id(p), {})
                    for i in range(ph.shape[0]):
                        p_i = h2d(jax.lax.index_in_dim(ph, i, keepdims=False))
                        g_i = h2d(jax.lax.index_in_dim(gh, i, keepdims=False))
                        if packed:
                            p_i = _unpack_dev(p_i, p_ts)
                            g_i = _unpack_dev(g_i, p_ts)
                        g_i = clipped(g_i)
                        s_i = {}
                        for k, v in st.items():
                            sv = h2d(jax.lax.index_in_dim(v, i,
                                                          keepdims=False))
                            ts = s_ts.get(k, tuple(v.shape[1:]))
                            if tuple(v.shape[1:]) != tuple(ts):
                                sv = _unpack_dev(sv, ts)
                            s_i[k] = sv
                        np_, ns = apply_rule(p_i, g_i, s_i, lr, step_no, flag)
                        if packed:
                            np_ = _pack_dev(np_, tuple(ph.shape[1:]))
                        out_p = jax.lax.dynamic_update_index_in_dim(
                            out_p, d2h(np_[None]), i, 0)
                        for k, v in ns.items():
                            nv = v.astype(out_s[k].dtype)
                            if tuple(st[k].shape[1:]) != tuple(
                                    s_ts.get(k, tuple(st[k].shape[1:]))):
                                nv = _pack_dev(nv, tuple(st[k].shape[1:]))
                            out_s[k] = jax.lax.dynamic_update_index_in_dim(
                                out_s[k], d2h(nv[None]), i, 0)
                    new_streamed.append(out_p)
                    new_ss.append(out_s)
                return loss_val, new_edge, new_es, new_streamed, new_ss
            finally:
                stage_stack._STREAM_MODE[0] = False
                random_mod.default_generator().clear_trace_key()

        if host is None:
            return jax.jit(step_fn)
        # outputs that end in host memory must SAY so (XLA rejects programs
        # whose entry outputs were host-moved without a host output layout);
        # prefix pytrees broadcast over the state dicts
        out_sh = (devm, devm, devm, host, host)
        donate = (1, 3) if self.donate_host else ()
        return jax.jit(step_fn, out_shardings=out_sh, donate_argnums=donate)

    def __call__(self, *batch):
        opt = self.optimizer
        arrays = [b.data if isinstance(b, Tensor) else jnp.asarray(b)
                  for b in batch]
        if self._jitted is None:
            self._jitted = self._build(arrays)
        loss, new_edge, new_es, new_streamed, new_ss = self._jitted(
            [p.data for p in self.edge],
            [p.data for p in self.streamed],
            [opt._accumulators[id(p)] for p in self.edge],
            [opt._accumulators[id(p)] for p in self.streamed],
            [t.data for t in self.frozen],
            jnp.asarray(opt.get_lr(), jnp.float32),
            jnp.asarray(opt._global_step + 1, jnp.int32),
            random_mod.next_key(), *arrays)
        for p, a, s in zip(self.edge, new_edge, new_es):
            p.data = a
            opt._accumulators[id(p)] = s
        for p, a, s in zip(self.streamed, new_streamed, new_ss):
            p.data = a
            opt._accumulators[id(p)] = s
        opt._global_step += 1
        return Tensor(loss)


class _EarlyExit(Exception):
    """Carries the run input captured during an embed-only prefix trace."""

    def __init__(self, value):
        self.value = value


class SegmentedTrainStep:
    """Beyond-StreamedTrainStep capacity: a hand-segmented backward in ONE
    compiled step, with NO stacked [L, ...] gradient accumulator anywhere.

    Reference sharding_stage3.py:50 + :737 streams per-SEGMENT params and
    accumulates grads host-side; the TPU-native mapping here:

    - every layer's params + optimizer state live as SEPARATE per-layer
      pinned-host arrays (no [L, ...] stacks, so XLA's memory-space pass
      has no whole-stack gradient chain to HBM-place — the 3.08B wall of
      StreamedTrainStep);
    - forward: unrolled per-layer walk, each boundary activation copied to
      pinned host right after use;
    - head/embedding gradients: plain jax AD around an independent
      run-output variable (the run is snipped out of the autodiff graph);
    - backward: a manual reverse walk — slice params H2D, jax.vjp of ONE
      layer (recompute-from-boundary == remat), apply the optimizer rule
      immediately, write the updated params/state back to host. A layer's
      gradients die before the next layer's exist.

    Single StackedStageRun models only (the streamed flagship shape); MoE
    aux-loss stacks are not supported on this path.
    """

    def __init__(self, model: Layer, loss_fn: Callable, optimizer,
                 donate_host: bool = False):
        from ..distributed.meta_parallel.stage_stack import _memory_sharding

        self.model = model
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        # donation halves the pinned peak (no second copy at the step
        # boundary) at a measured ~2x step-time cost through the remote
        # tunnel; off by default — this box holds both copies
        self.donate_host = bool(donate_host)
        if optimizer._grad_clip is not None:
            raise NotImplementedError(
                "SegmentedTrainStep: grad clip needs the norm before any "
                "update; use StreamedTrainStep for clipped streaming")
        runs = _find_runs(model)
        if len(runs) != 1:
            raise ValueError(
                "SegmentedTrainStep supports exactly one StackedStageRun "
                f"(got {len(runs)}); use StreamedTrainStep/TrainStep")
        self.run = runs[0]
        if getattr(self.run, "_segmented_owned", False):
            raise ValueError(
                "SegmentedTrainStep: this model's stacked weights were "
                "already split into a previous SegmentedTrainStep (they "
                "live in that step's per-layer buffers — keep using it, or "
                "rebuild the model from model.state_dict())")
        # the step runs loss_fn in FOUR traced passes (fwd walk, head AD,
        # per-layer vjp recompute, embed vjp); stochasticity ANYWHERE in
        # the model (not just the stacked template — embeddings/pooler too)
        # would draw different rng per pass and silently break the chain
        # rule. Checked: Dropout-family layers (incl. 3D/Alpha) with p>0
        # and ANY float attr whose name mentions dropout — MHA.dropout,
        # RNN.dropout, DiT LabelEmbedding.dropout_prob, functional
        # *dropout_p all drive rng draws.
        from ..nn.layer.common import Dropout, Dropout2D
        from ..nn.layer.extension_r3 import AlphaDropout, Dropout3D
        from ..nn.layer.moe import MoELayer

        scan = list(model.sublayers(include_self=True)) + \
            list(self.run._template[0].sublayers(include_self=True))
        for sub in scan:
            if (isinstance(sub, (Dropout, Dropout2D, Dropout3D,
                                 AlphaDropout))
                    and getattr(sub, "p", 0.0) > 0.0):
                raise NotImplementedError(
                    "SegmentedTrainStep: dropout in the model would "
                    "resample per traced pass (inconsistent gradients); "
                    "use StreamedTrainStep or p=0")
            for attr, val in vars(sub).items():
                if ("dropout" in attr and isinstance(val, float)
                        and val > 0.0):
                    raise NotImplementedError(
                        f"SegmentedTrainStep: {type(sub).__name__}.{attr}="
                        f"{val} drives stochastic masking — inconsistent "
                        f"across traced passes; use StreamedTrainStep")
            if isinstance(sub, MoELayer):
                raise NotImplementedError(
                    "SegmentedTrainStep: MoE aux losses cannot cross the "
                    "segmented boundary; use StreamedTrainStep")
        opt = optimizer
        self.train_params = [p for p in opt._parameter_list
                             if not p.stop_gradient]
        run_param_ids = {id(p) for p in self.run._parameters.values()}
        self.edge = [p for p in self.train_params
                     if id(p) not in run_param_ids]
        named = dict(model.named_parameters())
        train_ids = {id(p) for p in self.train_params}
        buffers = list(getattr(model, "named_buffers", lambda: [])())
        self.frozen = [p for p in named.values()
                       if id(p) not in train_ids
                       and id(p) not in run_param_ids] + \
            [b for _, b in buffers]
        self._host_sh = _memory_sharding("pinned_host")
        self._dev_sh = _memory_sharding("device")
        dev = jax.devices()[0]
        cpu = jax.devices("cpu")[0]

        # split each stacked run param into per-layer HOST arrays + state
        self.depth = self.run.depth
        self._pnames = [safe for safe, _ in self.run._names]
        self._layer_params: List[List] = []   # [L][P] host arrays
        self._layer_states: List[List[dict]] = []
        self._decay_flags: List[float] = []
        stacked_params = [self.run._parameters[s] for s in self._pnames]
        for p in stacked_params:
            if p.stop_gradient:
                raise NotImplementedError(
                    "SegmentedTrainStep: frozen stacked params unsupported")
            self._decay_flags.append(
                1.0 if (opt._decay_param_fn is None
                        or opt._decay_param_fn(p)) else 0.0)
        for i in range(self.depth):
            row, srow = [], []
            for p in stacked_params:
                sl = np.asarray(p.data[i]) if not self._on_cpu(p.data) \
                    else np.asarray(p.data)[i]
                row.append(self._park_whole(sl))
                with jax.default_device(cpu):
                    st = opt._init_state(jnp.asarray(sl))
                srow.append({k: self._park_whole(np.asarray(v))
                             for k, v in st.items()})
            self._layer_params.append(row)
            self._layer_states.append(srow)
        # split complete — only NOW mark ownership (an earlier validation
        # failure must leave the run reusable)
        self.run._segmented_owned = True
        # drop the stacked copies: this step owns the canonical weights now.
        # model.state_dict() is wrapped so ordinary checkpointing still sees
        # the REAL weights (reassembled from the per-layer buffers) instead
        # of silently saving the freed placeholders.
        split_ids = {id(p) for p in stacked_params}
        for p in stacked_params:
            p.data = jnp.zeros((0,), p.data.dtype)
        name_of = {id(p): n for n, p in model.named_parameters()
                   if id(p) in split_ids}
        orig_state_dict = model.state_dict
        pname_index = {s: j for j, s in enumerate(self._pnames)}

        def state_dict_with_segments(*a, **k):
            sd = orig_state_dict(*a, **k)
            arrs = self.state_dict_arrays()
            for pid, name in name_of.items():
                safe = name.rsplit(".", 1)[-1]
                j = pname_index.get(safe)
                if j is not None and name in sd:
                    sd[name] = Tensor(jnp.asarray(arrs[self._pnames[j]]))
            return sd

        model.state_dict = state_dict_with_segments
        for p in self.edge:
            if self._on_cpu(p.data):
                p.data = jax.device_put(np.asarray(p.data), dev)
            if id(p) not in opt._accumulators:
                opt._accumulators[id(p)] = opt._init_state(p.data)
        for t in self.frozen:
            if self._on_cpu(t.data):
                t.data = jax.device_put(np.asarray(t.data), dev)
        self._jitted = None

    def _park_whole(self, np_arr):
        """Park ONE layer's slice on pinned host UNPACKED (true shape).

        StreamedTrainStep._park packs [L, ...] stacks into aligned [L, R,
        128] slabs because its compiled step dynamic-slices INTO the host
        arrays (the async-copy emitter needs sublane/lane alignment). The
        segmented step transfers each buffer WHOLE (h2d/d2h of the full
        array inside one jit), so the true shape is what the template and
        the optimizer rule must see — packing here bound slab-shaped
        weights into the model (r5 regression, caught by the seg bench
        row going red on TPU)."""
        np_arr = np.asarray(np_arr)
        if self._host_sh is None:
            return jnp.asarray(np_arr)
        return jax.device_put(np_arr, self._host_sh)
    _on_cpu = staticmethod(StreamedTrainStep._on_cpu)

    def state_dict_arrays(self):
        """Reassembled stacked host arrays (checkpointing hook)."""
        return {n: np.stack([np.asarray(self._layer_params[i][j])
                             for i in range(self.depth)])
                for j, n in enumerate(self._pnames)}

    def _build(self, batch_arrays):
        from ..distributed.meta_parallel import stage_stack
        from . import _Binder

        model, loss_fn = self.model, self.loss_fn
        run, opt = self.run, self.optimizer
        edge, frozen = self.edge, self.frozen
        rule = type(opt)._rule
        hyper = opt._hyper()
        wd = opt._weight_decay
        decoupled = opt._decoupled
        host, devm = self._host_sh, self._dev_sh
        depth, pnames = self.depth, self._pnames
        template = run._template[0]
        tparams = [dict(template.named_parameters())[orig]
                   for _, orig in run._names]
        flags = self._decay_flags

        def h2d(x):
            return x if devm is None else jax.device_put(x, devm)

        def d2h(x):
            return x if host is None else jax.device_put(x, host)

        def layer_fwd(params_dev, hidden):
            saved = [p.data for p in tparams]
            try:
                for p, a in zip(tparams, params_dev):
                    p.data = a
                with autograd.no_grad():
                    return template(Tensor(hidden)).data
            finally:
                for p, a in zip(tparams, saved):
                    p.data = a

        def apply_rule(p_i, g_i, s_i, lr, step_no, flag):
            g_i = g_i.astype(p_i.dtype)
            if wd and not decoupled and flag:
                g_i = g_i + wd * p_i
            hyper_i = hyper if flag or "wd" not in hyper else \
                dict(hyper, wd=0.0)
            np_, ns = rule(p_i, g_i, s_i, lr, step_no, hyper_i)
            if wd and decoupled and flag:
                np_ = np_ - (lr * wd * p_i).astype(p_i.dtype)
            return np_, ns

        def step_fn(edge_arrays, layer_params, layer_states, edge_states,
                    frozen_arrays, lr, step_no, rngkey, *batch):
            random_mod.default_generator().set_trace_key(rngkey)
            try:
                boundaries: List = []
                captured: dict = {}

                def bind_and_run(edge_t, handler):
                    ts = edge + frozen
                    stage_stack._SEG_HANDLER[0] = handler
                    try:
                        with _Binder(ts) as b:
                            b.bind(list(edge_t) + list(frozen_arrays))
                            with autograd.no_grad():
                                loss = loss_fn(model,
                                               *[Tensor(a) for a in batch])
                        return loss.data.astype(jnp.float32)
                    finally:
                        stage_stack._SEG_HANDLER[0] = None

                # 1) forward walk: real layer compute, boundaries to host
                def fwd_handler(_run, hidden):
                    h = hidden
                    for i in range(depth):
                        boundaries.append(d2h(h))
                        params_dev = [h2d(a) for a in layer_params[i]]
                        h = layer_fwd(params_dev, h)
                    captured["h_out"] = h
                    return h

                bind_and_run(tuple(edge_arrays), fwd_handler)
                h_out = captured["h_out"]

                # 2) head/embedding AD around an independent run output
                def loss_of(edge_t, hv):
                    def const_handler(_run, hidden):
                        captured["h_in"] = hidden
                        return hv
                    return bind_and_run(edge_t, const_handler)

                (loss_val, (g_edge, dh)) = jax.value_and_grad(
                    loss_of, argnums=(0, 1))(tuple(edge_arrays), h_out)

                # 3) reverse walk: per-layer vjp + immediate update
                new_layer_params, new_layer_states = [], []
                for i in range(depth - 1, -1, -1):
                    h_i = h2d(boundaries[i])
                    params_dev = [h2d(a) for a in layer_params[i]]
                    _, vjp = jax.vjp(layer_fwd, params_dev, h_i)
                    dparams, dh = vjp(dh)
                    new_row, new_srow = [], []
                    for a, g, st, flag in zip(params_dev, dparams,
                                              layer_states[i], flags):
                        st_dev = {k: h2d(v) for k, v in st.items()}
                        np_, ns = apply_rule(a, g, st_dev, lr, step_no,
                                             flag)
                        new_row.append(d2h(np_))
                        new_srow.append({k: d2h(v.astype(st[k].dtype))
                                         for k, v in ns.items()})
                    new_layer_params.append(new_row)
                    new_layer_states.append(new_srow)
                new_layer_params.reverse()
                new_layer_states.reverse()

                # 4) embedding-path edge grads: vjp through the captured
                # run INPUT (loss_of's head path never saw it)
                def h_in_of(edge_t):
                    def early_handler(_run, hidden):
                        raise _EarlyExit(hidden)
                    try:
                        bind_and_run(edge_t, early_handler)
                    except _EarlyExit as e:
                        return e.value
                    raise RuntimeError("run was never reached by loss_fn")

                _, vjp_embed = jax.vjp(h_in_of, tuple(edge_arrays))
                (g_embed,) = vjp_embed(dh)
                g_edge = [a + b for a, b in zip(g_edge, g_embed)]

                new_edge, new_es = [], []
                for p, a, g, s in zip(edge, edge_arrays, g_edge,
                                      edge_states):
                    flag = 1.0 if (opt._decay_param_fn is None
                                   or opt._decay_param_fn(p)) else 0.0
                    np_, ns = apply_rule(a, g, s, lr, step_no, flag)
                    new_edge.append(np_)
                    new_es.append(ns)
                return (loss_val, new_edge, new_es, new_layer_params,
                        new_layer_states)
            finally:
                random_mod.default_generator().clear_trace_key()

        donate = (1, 2) if self.donate_host else ()
        if host is None:
            return jax.jit(step_fn, donate_argnums=donate)
        out_sh = (devm, devm, devm, host, host)
        return jax.jit(step_fn, out_shardings=out_sh,
                       donate_argnums=donate)

    def __call__(self, *batch):
        opt = self.optimizer
        arrays = [b.data if isinstance(b, Tensor) else jnp.asarray(b)
                  for b in batch]
        if self._jitted is None:
            self._jitted = self._build(arrays)
        (loss, new_edge, new_es, new_lp, new_ls) = self._jitted(
            [p.data for p in self.edge],
            self._layer_params, self._layer_states,
            [opt._accumulators[id(p)] for p in self.edge],
            [t.data for t in self.frozen],
            jnp.asarray(opt.get_lr(), jnp.float32),
            jnp.asarray(opt._global_step + 1, jnp.int32),
            random_mod.next_key(), *arrays)
        for p, a, s in zip(self.edge, new_edge, new_es):
            p.data = a
            opt._accumulators[id(p)] = s
        self._layer_params = new_lp
        self._layer_states = new_ls
        opt._global_step += 1
        return Tensor(loss)
