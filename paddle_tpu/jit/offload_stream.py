"""Streamed parameter offload: 4B-class training on one chip.

Reference: python/paddle/distributed/fleet/meta_parallel/sharding/
sharding_stage3.py:50 (param offload) + :737 (TaskFlow prefetch) — the
reference streams each segment's params H2D ahead of use and keeps the fp32
master + optimizer state on the host.

TPU-native mapping: the transformer stack's [L, ...] stacked parameters live
in the TPU's PINNED HOST memory space; the compiled step copies one layer's
slice into HBM right before its compute (XLA emits async copy-start/done —
the prefetch), autodiff's transpose of those copies lands the stacked
gradient accumulator back in host memory, and the fp32 master update runs on
the host CPU backend. HBM holds only: edge params (embeddings/head/norms),
1-2 layers' weights in flight, and remat boundary activations.
"""
from __future__ import annotations

from typing import Callable, List

import jax
import jax.numpy as jnp
import numpy as np

from ..core import autograd
from ..core.tensor import Tensor
from ..framework import random as random_mod
from ..nn.layer.layers import Layer


import contextlib


@contextlib.contextmanager
def init_on_host():
    """Construct models larger than HBM without touching it: parameter init
    runs on the host CPU backend (the reference's offload models build their
    params host-side too, sharding_stage3 _segment_rank_params). Hand the
    model to StreamedTrainStep, which places every tensor — streamed stacks
    into pinned host memory, edge params into HBM.

    The global rng key moves to the CPU backend for the duration: implicit
    cross-backend reads of an accelerator-resident key inside CPU-placed
    init ops are unreliable through the remote-chip tunnel."""
    from ..framework import random as random_mod

    cpu = jax.devices("cpu")[0]
    gen = random_mod.default_generator()
    old_key = gen._key
    gen._key = jax.device_put(np.asarray(jax.random.key_data(old_key)), cpu)
    gen._key = jax.random.wrap_key_data(gen._key)
    try:
        with jax.default_device(cpu):
            yield
    finally:
        gen._key = old_key


def _find_runs(model: Layer):
    from ..distributed.meta_parallel.stage_stack import StackedStageRun

    runs = []

    def walk(layer):
        if isinstance(layer, StackedStageRun):
            runs.append(layer)
        for _, sub in getattr(layer, "_sub_layers", {}).items():
            walk(sub)

    walk(model)
    return runs


class StreamedTrainStep:
    """Single-chip capacity mode: jit.TrainStep's twin for models whose
    stacked decoder weights exceed HBM. Slower per step (every weight
    crosses PCIe/host twice per step) but lifts the resident ceiling from
    ~1.8B to 4B+ params on the 9.5GB chip."""

    def __init__(self, model: Layer, loss_fn: Callable, optimizer):
        from ..distributed.meta_parallel.stage_stack import _memory_sharding

        self.model = model
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        runs = _find_runs(model)
        if not runs:
            raise ValueError(
                "StreamedTrainStep: the model has no StackedStageRun to "
                "stream (scan_layers=True models only); use jit.TrainStep")
        streamed_ids = {id(p) for r in runs for p in r._parameters.values()}
        opt = optimizer
        self.train_params = [p for p in opt._parameter_list
                             if not p.stop_gradient]
        self.streamed = [p for p in self.train_params
                         if id(p) in streamed_ids]
        self.edge = [p for p in self.train_params if id(p) not in streamed_ids]
        if not self.streamed:
            raise ValueError(
                "StreamedTrainStep: optimizer holds none of the stacked "
                "run's parameters (fleet order: build the stack first)")
        named = dict(model.named_parameters())
        train_ids = {id(p) for p in self.train_params}
        buffers = list(getattr(model, "named_buffers", lambda: [])())
        self.frozen = [p for p in named.values() if id(p) not in train_ids] \
            + [b for _, b in buffers]
        self._host_sh = _memory_sharding("pinned_host")
        self._dev_sh = _memory_sharding("device")
        self._cpu = jax.devices("cpu")[0]
        # fp32 master + optimizer state on the host CPU backend (the
        # reference's offload destination). Read each param via plain D2H
        # BEFORE parking it: the tunnel cannot np.asarray a pinned_host
        # array (reads round-trip through HBM and can OOM)
        def to_cpu(arr):
            if self._on_cpu(arr):
                return arr
            return jax.device_put(np.asarray(arr), self._cpu)

        self._master = []
        for p in self.train_params:
            cpu_arr = to_cpu(p.data)
            self._master.append(
                jax.device_put(np.asarray(cpu_arr, np.float32), self._cpu))
            if id(p) not in opt._accumulators:
                opt._accumulators[id(p)] = opt._init_state(cpu_arr)
            else:
                opt._accumulators[id(p)] = {
                    k: jax.device_put(v, self._cpu)
                    for k, v in opt._accumulators[id(p)].items()}
            # place: streamed stacks -> pinned host; edge params -> HBM
            # (init_on_host models arrive entirely on the CPU backend)
            if id(p) in streamed_ids:
                if self._host_sh is not None:
                    parked = jax.device_put(
                        np.asarray(cpu_arr).astype(
                            str(p.data.dtype).replace("paddle.", ""))
                        if self._on_cpu(p.data) else p.data,
                        self._host_sh)
                    p.data = parked
            elif self._on_cpu(p.data):
                p.data = jax.device_put(p.data, jax.devices()[0])
        for t in self.frozen:
            if self._on_cpu(t.data):
                t.data = jax.device_put(t.data, jax.devices()[0])
        self._jitted = None

    @staticmethod
    def _on_cpu(arr) -> bool:
        try:
            return all(d.platform == "cpu" for d in arr.devices())
        except Exception:
            return False

    # -- compiled fwd+bwd -----------------------------------------------------
    def _build(self, batch_arrays):
        from ..distributed.meta_parallel import stage_stack
        from . import _Binder

        model, loss_fn = self.model, self.loss_fn
        edge, streamed, frozen = self.edge, self.streamed, self.frozen

        def fwd_bwd(edge_arrays, streamed_arrays, frozen_arrays, rngkey,
                    *batch):
            random_mod.default_generator().set_trace_key(rngkey)
            stage_stack._STREAM_MODE[0] = True
            try:
                def loss_of(edge_t, streamed_t):
                    ts = edge + streamed + frozen
                    with _Binder(ts) as b:
                        b.bind(list(edge_t) + list(streamed_t) +
                               list(frozen_arrays))
                        with autograd.no_grad():
                            loss = loss_fn(model, *[Tensor(a) for a in batch])
                    return loss.data.astype(jnp.float32)

                loss_val, (ge, gs) = jax.value_and_grad(
                    loss_of, argnums=(0, 1))(tuple(edge_arrays),
                                             tuple(streamed_arrays))
                return loss_val, list(ge), list(gs)
            finally:
                stage_stack._STREAM_MODE[0] = False
                random_mod.default_generator().clear_trace_key()

        if self._host_sh is None:  # CPU test backend without memory kinds
            return jax.jit(fwd_bwd)
        host, dev = self._host_sh, self._dev_sh
        in_sh = ([dev] * len(edge), [host] * len(streamed),
                 [dev] * len(frozen), dev)
        out_sh = (dev, [dev] * len(edge), [host] * len(streamed))
        return jax.jit(fwd_bwd, in_shardings=(*in_sh,) + (dev,) * len(batch_arrays),
                       out_shardings=out_sh)

    def _build_update(self):
        """Host-side fp32 master update (one CPU-jitted fn; the reference's
        offload optimizer step) — the loop itself is the shared
        optimizer.make_master_update."""
        from ..optimizer.optimizer import make_master_update

        dtypes = [p.data.dtype for p in self.train_params]
        update = make_master_update(self.optimizer, self.train_params, dtypes)
        return jax.jit(update, donate_argnums=(0, 2))

    def __call__(self, *batch):
        opt = self.optimizer
        arrays = [b.data if isinstance(b, Tensor) else jnp.asarray(b)
                  for b in batch]
        if self._jitted is None:
            self._jitted = (self._build(arrays), self._build_update())
        jit_fb, jit_upd = self._jitted
        loss, ge, gs = jit_fb([p.data for p in self.edge],
                              [p.data for p in self.streamed],
                              [t.data for t in self.frozen],
                              random_mod.next_key(), *arrays)
        # host-ward: edge grads cross D2H, streamed grads are already in
        # host memory (cross-backend host->host copy)
        grads_cpu = [jax.device_put(g, self._cpu) for g in ge + gs]
        del ge, gs
        ordered = self.edge + self.streamed
        states = [opt._accumulators[id(p)] for p in ordered]
        master = self._reorder_master(ordered)
        lr = jax.device_put(jnp.asarray(opt.get_lr(), jnp.float32), self._cpu)
        step_no = jax.device_put(jnp.asarray(opt._global_step + 1, jnp.int32),
                                 self._cpu)
        new_m, new_s, new_p = jit_upd(master, grads_cpu, states, lr, step_no)
        for p, m, s in zip(ordered, new_m, new_s):
            self._master_map[id(p)] = m
            opt._accumulators[id(p)] = s
        for p, a in zip(self.edge, new_p[:len(self.edge)]):
            p.data = jax.device_put(a, self._dev_sh) if self._dev_sh is not None \
                else jnp.asarray(np.asarray(a))
        for p, a in zip(self.streamed, new_p[len(self.edge):]):
            p.data = jax.device_put(a, self._host_sh) if self._host_sh is not None \
                else jnp.asarray(np.asarray(a))
        opt._global_step += 1
        return Tensor(loss)

    def _reorder_master(self, ordered):
        if not hasattr(self, "_master_map"):
            self._master_map = {id(p): m
                                for p, m in zip(self.train_params,
                                                self._master)}
        return [self._master_map[id(p)] for p in ordered]
