"""Capture-and-compile: the @to_static / CINN-role subsystem.

Reference: python/paddle/fluid/dygraph/dygraph_to_static/ (AST transpiler ->
ProgramDesc -> executor) and paddle2cinn (subgraph JIT). TPU-native redesign:
capture IS tracing — `jax.jit` over the eager op layer. The same eager ops run
under an outer trace, so there is no separate program IR to maintain; XLA is
the compiled executor (InterpreterCore role), and donation replaces the
memory-optimize pass.

Two entry points:
- ``to_static(layer_or_fn)``: compiled forward (inference / eval path).
- ``TrainStep(model, loss_fn, optimizer)``: whole-train-step compilation —
  forward + backward (jax.grad at array level) + fused optimizer update in ONE
  XLA executable with donated buffers. This is the TPU-performance path; the
  eager tape is bypassed entirely.
"""
from __future__ import annotations

import functools
from typing import Callable, List, Optional

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core import autograd
from ..framework import random as random_mod
from ..nn.layer.layers import Layer
from . import persistent_cache


def _collect_params(layer: Layer):
    named = list(layer.named_parameters())
    buffers = list(layer.named_buffers())
    return named, buffers


# Trace-cache audit hooks (paddle_tpu.analysis.retrace installs these; both
# default None so the path is untouched when auditing is off):
# _TRACE_AUDIT_HOOK(label, jitted) -> callable wraps freshly built compiled
# steps; _TRACE_NEWKEY_HOOK(label, key) records python-level cache-key drift
# (a new to_static specialization == a guaranteed recompile).
_TRACE_AUDIT_HOOK = None
_TRACE_NEWKEY_HOOK = None
_AUDIT_INSTANCE_NO = [0]


def _maybe_audit(label, jitted):
    return _TRACE_AUDIT_HOOK(label, jitted) if _TRACE_AUDIT_HOOK is not None \
        else jitted


_OBS = None  # lazily bound (StepTimeline, trace_cache CounterFamily)


def _obs():
    """(timeline, trace_cache family) — the observability hooks every
    compiled-step call site feeds. One-time late bind; per-call cost after
    that is a tuple load."""
    global _OBS
    if _OBS is None:
        from ..observability import family
        from ..observability.timeline import timeline

        _OBS = (timeline(), family("trace_cache", ("site", "event")))
    return _OBS


_MEMOBS = None  # lazily bound observability.memory (drift + OOM forensics)


def _memobs():
    """The memory-truth module every compiled step consults: an unarmed
    OOM-guard peek per call, drift recording only on cold builds."""
    global _MEMOBS
    if _MEMOBS is None:
        from ..observability import memory as _m

        _MEMOBS = _m
    return _MEMOBS


def _audit_instance_label(kind: str) -> str:
    """Per-instance audit label ("TrainStep#2"): two train steps with
    different batch shapes must not pool signatures in one bucket — that
    would report phantom recompiles."""
    _AUDIT_INSTANCE_NO[0] += 1
    return f"{kind}#{_AUDIT_INSTANCE_NO[0]}"


def make_param_updater(opt, train_params):
    """Per-param optimizer update math (grads -> new params/states): the
    ONE source of the weight-decay coupling / decoupled-decay / rule
    application every compiled step uses — TrainStep, the fused
    AccumulateStep, and ShardedTrainStep's mesh builds all call this, so
    their numerics cannot drift apart."""
    rule = type(opt)._rule
    hyper = opt._hyper()
    wd = opt._weight_decay
    decoupled = opt._decoupled
    wd_flags = tuple(
        1.0 if (opt._decay_param_fn is None or opt._decay_param_fn(p)) else 0.0
        for p in train_params)

    def apply(params, grads, states, lr, step_no):
        new_p, new_s = [], []
        for p, g, s, flag in zip(params, grads, states, wd_flags):
            g = g.astype(p.dtype)
            if wd and not decoupled and flag:
                g = g + wd * p
            hyper_i = hyper if flag or "wd" not in hyper \
                else dict(hyper, wd=0.0)
            np_, ns = rule(p, g, s, lr, step_no, hyper_i)
            if wd and decoupled and flag:
                np_ = np_ - (lr * wd * p).astype(p.dtype)
            new_p.append(np_)
            new_s.append(ns)
        return new_p, new_s

    return apply


class _Binder:
    """Temporarily swap Layer parameter/buffer .data with traced arrays."""

    def __init__(self, tensors: List[Tensor]):
        self.tensors = tensors
        self.saved = None

    def __enter__(self):
        self.saved = [t.data for t in self.tensors]
        return self

    def bind(self, arrays):
        for t, a in zip(self.tensors, arrays):
            t.data = a

    def __exit__(self, *exc):
        for t, a in zip(self.tensors, self.saved):
            t.data = a
        return False


class StaticLayer:
    """Compiled forward wrapper (TranslatedLayer/StaticFunction analogue)."""

    def __init__(self, layer_or_fn, input_spec=None, full_graph=True):
        self._is_layer = isinstance(layer_or_fn, Layer)
        self._target = layer_or_fn
        self._cache = {}
        self._audit_label = None  # assigned per instance on first compile
        # AST-lite dy2static (program_translator.py:775 role): rewrite simple
        # tensor-dependent if/while into runtime-dispatched cond/while_loop.
        # The conversion is scoped to THIS wrapper — the user's layer object
        # keeps its original eager forward (no instance mutation).
        from .dy2static import convert_to_static

        self._converted_forward = None
        if self._is_layer:
            fwd = type(layer_or_fn).forward
            conv = convert_to_static(fwd)
            if conv is not fwd:
                import types as _types

                self._converted_forward = _types.MethodType(conv, layer_or_fn)
        else:
            self._target = convert_to_static(layer_or_fn)

    def __call__(self, *args, **kwargs):
        # Tensor kwargs become traced inputs; everything else is static
        # (part of the compile-cache key), matching paddle's StaticFunction
        # kwargs contract.
        import numpy as _np

        def _is_data(v):
            return isinstance(v, (Tensor, jax.Array, _np.ndarray))

        kw_tensor = {k: v for k, v in sorted(kwargs.items()) if _is_data(v)}
        kw_static = {k: v for k, v in kwargs.items() if k not in kw_tensor}
        try:
            static_key = tuple(sorted(kw_static.items()))
            hash(static_key)
        except TypeError:
            raise TypeError(
                "to_static: non-Tensor keyword arguments must be hashable "
                f"(got {sorted(kw_static)})")
        # positional args: data is traced; plain Python values are STATIC
        # (python semantics preserved, cache key per value) like the
        # reference's StaticFunction
        data_idx = tuple(i for i, a in enumerate(args) if _is_data(a))
        static_args = tuple((i, a) for i, a in enumerate(args)
                            if not _is_data(a))
        try:
            hash(static_args)
        except TypeError:
            raise TypeError(
                "to_static: non-Tensor positional arguments must be hashable")
        arrays = [args[i].data if isinstance(args[i], Tensor) else args[i]
                  for i in data_idx]
        kw_arrays = [v.data if isinstance(v, Tensor) else v
                     for v in kw_tensor.values()]
        kw_names = tuple(kw_tensor)
        if self._is_layer:
            named, buffers = _collect_params(self._target)
            tensors = [p for _, p in named] + [b for _, b in buffers]
            key = ("layer", self._target.training, len(tensors), kw_names,
                   static_key, data_idx, static_args)
        else:
            tensors = []
            key = ("fn", kw_names, static_key, data_idx, static_args)
        _tc = _obs()[1]
        jitted = self._cache.get(key)
        _tc.inc(("to_static", "hit" if jitted is not None else "miss"))
        if jitted is None:
            target, is_layer = self._target, self._is_layer

            converted = self._converted_forward

            def run(param_arrays, input_arrays, kw_input_arrays, rngkey):
                random_mod.default_generator().set_trace_key(rngkey)
                kw = dict(zip(kw_names, (Tensor(a) for a in kw_input_arrays)))
                kw.update(kw_static)
                # interleave traced data and static python args back into
                # the original positional order
                full = dict(static_args)
                for i, a in zip(data_idx, input_arrays):
                    full[i] = Tensor(a)
                pos = [full[i] for i in sorted(full)]
                swapped = False
                try:
                    if is_layer:
                        if converted is not None:
                            # dy2static forward only inside this capture
                            target.forward = converted
                            swapped = True
                        named, buffers = _collect_params(target)
                        ts = [p for _, p in named] + [b for _, b in buffers]
                        with _Binder(ts) as b:
                            b.bind(param_arrays)
                            with autograd.no_grad():
                                out = target(*pos, **kw)
                    else:
                        with autograd.no_grad():
                            out = target(*pos, **kw)
                finally:
                    if swapped:
                        del target.forward  # restore the class method
                    random_mod.default_generator().clear_trace_key()
                return jax.tree_util.tree_map(
                    lambda t: t.data if isinstance(t, Tensor) else t, out,
                    is_leaf=lambda t: isinstance(t, Tensor))

            base = "to_static:" + getattr(self._target, "__name__",
                                          type(self._target).__name__)
            if self._audit_label is None:
                self._audit_label = _audit_instance_label(base)
            if _TRACE_NEWKEY_HOOK is not None:
                # a NEW python-level cache key == a guaranteed recompile
                # (static-arg / kwarg-structure drift): let the auditor
                # attribute it per WRAPPER instance
                _TRACE_NEWKEY_HOOK(self._audit_label, key)
            # each specialization is its own jit cache: give its call-
            # signature bucket a distinct label too, or two specializations
            # of one wrapper would read as phantom signature drift
            jitted = _maybe_audit(
                f"{self._audit_label}/k{len(self._cache)}",
                persistent_cache.cached_jit(
                    run, label=self._audit_label,
                    extra_meta=("to_static", repr(key))))
            self._cache[key] = jitted
        param_arrays = [t.data for t in tensors]
        out = jitted(param_arrays, arrays, kw_arrays, random_mod.next_key())
        return jax.tree_util.tree_map(Tensor, out)

    # paddle API-compat
    @property
    def forward(self):
        return self.__call__


def to_static(function=None, input_spec=None, build_strategy=None, backend=None,
              **kwargs):
    """@paddle.jit.to_static equivalent (reference: fluid/dygraph/jit.py:163)."""
    if function is None:
        return lambda f: to_static(f, input_spec)
    return StaticLayer(function, input_spec)


class TrainStep:
    """Whole-step compiler: the hybrid of InterpreterCore + generated grad ops.

    usage::
        step = paddle_tpu.jit.TrainStep(model, loss_fn, optimizer)
        loss = step(x, y)            # one XLA executable: fwd+bwd+update

    loss_fn(model, *batch) -> scalar loss Tensor.
    """

    def __init__(self, model: Layer, loss_fn: Callable, optimizer, donate=True):
        self.model = model
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self.donate = donate
        self._jitted = None
        self._init_opt_state()

    def _init_opt_state(self):
        opt = self.optimizer
        self.train_params = [p for p in opt._parameter_list if not p.stop_gradient]
        named, buffers = _collect_params(self.model)
        train_ids = {id(p) for p in self.train_params}
        self.frozen = [p for _, p in named if id(p) not in train_ids] + \
            [b for _, b in buffers]
        for p in self.train_params:
            if id(p) not in opt._accumulators:
                opt._accumulators[id(p)] = opt._init_state(p.data)

    def _make_updater(self):
        return make_param_updater(self.optimizer, self.train_params)

    def _build(self):
        opt = self.optimizer
        model, loss_fn = self.model, self.loss_fn
        clip = opt._grad_clip
        train_params = self.train_params
        frozen = self.frozen
        updater = self._make_updater()

        def step(params, states, frozen_arrays, lr, step_no, rngkey, *batch):
            random_mod.default_generator().set_trace_key(rngkey)
            try:
                def loss_of(param_arrays):
                    ts = train_params + frozen
                    with _Binder(ts) as b:
                        b.bind(list(param_arrays) + list(frozen_arrays))
                        with autograd.no_grad():
                            loss = loss_fn(model, *[Tensor(a) for a in batch])
                    return loss.data.astype(jnp.float32)

                loss_val, grads = jax.value_and_grad(loss_of)(tuple(params))
                grads = list(grads)
                if clip is not None:
                    grads = clip._apply_jax(grads)
                new_p, new_s = updater(params, grads, states, lr, step_no)
                return loss_val, new_p, new_s
            finally:
                random_mod.default_generator().clear_trace_key()

        donate = (0, 1) if self.donate else ()
        return persistent_cache.cached_jit(step, donate_argnums=donate,
                                           label="TrainStep")

    def accumulate(self, steps: int, remat: bool = False,
                   average: bool = True) -> "AccumulateStep":
        """Fused gradient accumulation: one executable that scans ``steps``
        microbatches (fwd+bwd each, optional remat), accumulates grads in
        fp32, and applies ONE optimizer update — numerically the k
        sequential micro-steps of the eager accumulation recipe (loss
        scaled 1/k when ``average``) without k dispatches or k optimizer
        launches. Call it with the FULL batch; dim 0 must divide by
        ``steps``."""
        return AccumulateStep(self, steps, remat=remat, average=average)

    def __call__(self, *batch):
        tl, tc = _obs()
        with tl.step():
            cold = self._jitted is None
            if cold:
                tc.inc(("train_step", "build"))
                self._jitted = _maybe_audit(
                    _audit_instance_label("TrainStep"), self._build())
            opt = self.optimizer
            params = [p.data for p in self.train_params]
            states = [opt._accumulators[id(p)] for p in self.train_params]
            frozen_arrays = [t.data for t in self.frozen]
            lr = jnp.asarray(opt.get_lr(), jnp.float32)
            step_no = jnp.asarray(opt._global_step + 1, jnp.int32)
            arrays = [b.data if isinstance(b, Tensor) else jnp.asarray(b) for b in batch]
            key = random_mod.next_key()
            mo = _memobs()
            drift_args = mo.struct_args(
                (params, states, frozen_arrays, lr, step_no, key)
                + tuple(arrays)) if cold and mo.drift_enabled() else None
            # cold call = trace + XLA compile + first run; warm = async
            # dispatch (a warm retrace from signature drift lands here too —
            # analysis.retrace names it)
            with tl.phase("compile" if cold else "host_dispatch"):
                with mo.oom_guard("train_step", label="TrainStep",
                                  step=opt._global_step):
                    loss, new_p, new_s = self._jitted(
                        params, states, frozen_arrays, lr, step_no,
                        key, *arrays)
            if tl.detailed:
                with tl.phase("device_block"):
                    jax.block_until_ready(loss)
            for p, a in zip(self.train_params, new_p):
                p.data = a
            for p, s in zip(self.train_params, new_s):
                opt._accumulators[id(p)] = s
            opt._global_step += 1
            if cold:
                mo.maybe_record_drift(self, arrays, "TrainStep",
                                      self._jitted, drift_args)
        return Tensor(loss)


class AccumulateStep:
    """Fused gradient-accumulation executable (``TrainStep.accumulate``).

    The microbatch loop is a ``lax.scan`` INSIDE one jitted-and-donated
    program: per iteration fwd+bwd on one microbatch (optionally under
    ``jax.checkpoint`` so activations rematerialize instead of living for
    the whole window), gradients accumulated into fp32 carries, then a
    single optimizer update from the window total. Equivalent to the eager
    recipe ``for mb: backward(loss(mb)/k); optimizer.step()`` — the
    lr-equivalent scaling of a full-batch mean loss — with one dispatch
    and no per-microbatch host round-trips.

    Duck-types the TrainStep capture surface (``_build``/``train_params``/
    ``frozen``/``optimizer``/``donate``) so ``analysis.capture`` and the
    HBM estimator model it, donation included.
    """

    def __init__(self, step: TrainStep, steps: int, remat: bool = False,
                 average: bool = True):
        if int(steps) < 1:
            raise ValueError(f"accumulate: steps must be >= 1, got {steps}")
        self._step = step
        self.steps = int(steps)
        self.remat = bool(remat)
        self.average = bool(average)
        self.model = step.model
        self.loss_fn = step.loss_fn
        self.optimizer = step.optimizer
        self.donate = step.donate
        self.train_params = step.train_params
        self.frozen = step.frozen
        self._jitted = None

    def _build(self):
        opt = self.optimizer
        model, loss_fn = self.model, self.loss_fn
        clip = opt._grad_clip
        train_params = self.train_params
        frozen = self.frozen
        k = self.steps
        scale = 1.0 / k if self.average else 1.0
        remat = self.remat
        updater = self._step._make_updater()

        def loss_of(param_arrays, frozen_arrays, mb):
            ts = train_params + frozen
            with _Binder(ts) as b:
                b.bind(list(param_arrays) + list(frozen_arrays))
                with autograd.no_grad():
                    loss = loss_fn(model, *[Tensor(a) for a in mb])
            return loss.data.astype(jnp.float32)

        # grads w.r.t. argnum 0 (params) only; remat recomputes the
        # microbatch forward during backward so window activations never
        # accumulate across scan iterations
        grad_fn = jax.value_and_grad(
            jax.checkpoint(loss_of) if remat else loss_of)

        def step(params, states, frozen_arrays, lr, step_no, rngkey, *batch):
            micro = tuple(
                a.reshape((k, a.shape[0] // k) + a.shape[1:]) for a in batch)
            keys = jax.random.split(rngkey, k)

            def body(acc, xs):
                key_i, mb = xs[0], xs[1:]
                random_mod.default_generator().set_trace_key(key_i)
                try:
                    loss_i, grads = grad_fn(tuple(params), frozen_arrays, mb)
                finally:
                    random_mod.default_generator().clear_trace_key()
                acc2 = [a + g.astype(jnp.float32) * scale
                        for a, g in zip(acc, grads)]
                return acc2, loss_i

            acc0 = [jnp.zeros(p.shape, jnp.float32) for p in train_params]
            accT, losses = jax.lax.scan(body, acc0, (keys,) + micro)
            grads = list(accT)
            if clip is not None:
                grads = clip._apply_jax(grads)
            new_p, new_s = updater(params, grads, states, lr, step_no)
            return jnp.mean(losses), new_p, new_s

        donate = (0, 1) if self.donate else ()
        return persistent_cache.cached_jit(
            step, donate_argnums=donate, label=f"TrainStep.accumulate({k})",
            extra_meta=("accum", k, self.average, self.remat))

    def __call__(self, *batch):
        opt = self.optimizer
        arrays = [b.data if isinstance(b, Tensor) else jnp.asarray(b)
                  for b in batch]
        for a in arrays:
            if a.ndim == 0 or a.shape[0] % self.steps != 0:
                raise ValueError(
                    f"accumulate({self.steps}): batch dim {a.shape} must "
                    f"divide by the microbatch count")
        tl, tc = _obs()
        with tl.step():
            cold = self._jitted is None
            if cold:
                tc.inc(("accumulate", "build"))
                self._jitted = _maybe_audit(
                    _audit_instance_label(
                        f"TrainStep.accumulate({self.steps})"),
                    self._build())
            params = [p.data for p in self.train_params]
            states = [opt._accumulators[id(p)] for p in self.train_params]
            frozen_arrays = [t.data for t in self.frozen]
            lr = jnp.asarray(opt.get_lr(), jnp.float32)
            step_no = jnp.asarray(opt._global_step + 1, jnp.int32)
            key = random_mod.next_key()
            mo = _memobs()
            drift_args = mo.struct_args(
                (params, states, frozen_arrays, lr, step_no, key)
                + tuple(arrays)) if cold and mo.drift_enabled() else None
            label = f"TrainStep.accumulate({self.steps})"
            with tl.phase("compile" if cold else "host_dispatch"):
                with mo.oom_guard("accumulate", label=label,
                                  step=opt._global_step):
                    loss, new_p, new_s = self._jitted(
                        params, states, frozen_arrays, lr, step_no,
                        key, *arrays)
            if tl.detailed:
                with tl.phase("device_block"):
                    jax.block_until_ready(loss)
            for p, a in zip(self.train_params, new_p):
                p.data = a
            for p, s in zip(self.train_params, new_s):
                opt._accumulators[id(p)] = s
            opt._global_step += 1
            if cold:
                mo.maybe_record_drift(self, arrays, label, self._jitted,
                                      drift_args)
        return Tensor(loss)


def _as_shape_struct(spec, poly_suffix=""):
    """InputSpec/Tensor/array -> jax.ShapeDtypeStruct; None dims become
    symbolic so the exported program accepts any batch size."""
    from jax import export as jexport

    if isinstance(spec, Tensor):
        return jax.ShapeDtypeStruct(tuple(spec.shape), spec.data.dtype)
    if hasattr(spec, "shape") and hasattr(spec, "dtype"):
        shape = tuple(spec.shape)
        dtype = jnp.dtype(str(spec.dtype).replace("paddle.", ""))
        if any(d is None or (isinstance(d, int) and d < 0) for d in shape):
            dims = [f"b{poly_suffix}_{i}"
                    if d is None or (isinstance(d, int) and d < 0) else str(d)
                    for i, d in enumerate(shape)]
            shape = jexport.symbolic_shape(",".join(dims))
        return jax.ShapeDtypeStruct(shape, dtype)
    raise TypeError(f"cannot build a trace signature from {spec!r}")


def save(layer, path, input_spec=None, **configs):
    """jit.save: AOT-export the traced forward (reference: fluid/dygraph/jit.py
    jit.save -> TranslatedLayer artifacts). Artifacts:

    - `<path>.pdmodel`   serialized StableHLO program (jax.export bytes),
      traced as fn(param_arrays, *inputs) for CPU+TPU platforms
    - `<path>.pdiparams` weights as npz (positional, matching the trace)
    - `<path>.pdmeta`    json: state-dict keys + input/output structure
    """
    import json

    from jax import export as jexport

    import numpy as np

    target = layer._target if isinstance(layer, StaticLayer) else layer
    if not isinstance(target, Layer):
        raise TypeError("jit.save expects an nn.Layer (or to_static of one)")
    if input_spec is None:
        raise ValueError(
            "jit.save needs input_spec=[InputSpec(...)|example Tensor, ...] "
            "to trace the forward")
    named, buffers = _collect_params(target)
    tensors = [p for _, p in named] + [b for _, b in buffers]
    keys = [k for k, _ in named] + [k for k, _ in buffers]
    arg_structs = [_as_shape_struct(s, poly_suffix=str(i))
                   for i, s in enumerate(input_spec)]
    param_structs = [jax.ShapeDtypeStruct(tuple(t.data.shape), t.data.dtype)
                     for t in tensors]

    def run(param_arrays, *input_arrays):
        ts = tensors
        with _Binder(ts) as b:
            b.bind(list(param_arrays))
            with autograd.no_grad():
                out = target(*[Tensor(a) for a in input_arrays])
        return jax.tree_util.tree_map(
            lambda t: t.data if isinstance(t, Tensor) else t, out,
            is_leaf=lambda t: isinstance(t, Tensor))

    was_training = target.training
    target.eval()  # export inference semantics (dropout off, BN running stats)
    try:
        exp = jexport.export(jax.jit(run), platforms=("cpu", "tpu"))(
            param_structs, *arg_structs)
    finally:
        if was_training:
            target.train()
    with open(path + ".pdmodel", "wb") as f:
        f.write(exp.serialize())
    with open(path + ".pdiparams", "wb") as f:
        np.savez(f, **{f"p{i}": np.asarray(t.data) for i, t in enumerate(tensors)})
    with open(path + ".pdmeta", "w") as f:
        json.dump({"param_keys": keys,
                   "num_inputs": len(arg_structs),
                   "input_specs": [
                       {"shape": [None if not isinstance(d, int) else d
                                  for d in s.shape],
                        "dtype": str(s.dtype)} for s in arg_structs]}, f)


class TranslatedLayer(Layer):
    """Loaded AOT program (reference: fluid/dygraph/io.py TranslatedLayer).

    Parameters are live: set_state_dict updates them and the next call feeds
    the new arrays into the exported executable."""

    def forward(self, *inputs):  # pragma: no cover - bound per-instance in load()
        raise RuntimeError("TranslatedLayer not initialized; use jit.load")


def load(path, **configs):
    """jit.load: rehydrate a jit.save artifact as a callable Layer."""
    import json
    import os

    import numpy as np
    from jax import export as jexport

    with open(path + ".pdmodel", "rb") as f:
        exp = jexport.deserialize(f.read())
    with open(path + ".pdmeta") as f:
        meta = json.load(f)
    data = np.load(path + ".pdiparams")
    arrays = [data[f"p{i}"] for i in range(len(meta["param_keys"]))]

    from ..nn.layer.layers import Parameter

    layer = TranslatedLayer()
    params = []
    for key, arr in zip(meta["param_keys"], arrays):
        p = Parameter(jnp.asarray(arr), name=key.replace(".", "_"))
        # register under the ORIGINAL dotted key: named_parameters/state_dict
        # then expose the same names the source model used, so
        # set_state_dict(trained_net.state_dict()) round-trips
        layer.add_parameter(key, p)
        params.append(p)

    # the exported program still pays an XLA compile per concrete input
    # shape; route it through the persistent cache so a warm process
    # (inference.Predictor load, serving warmup) skips those compiles
    call = persistent_cache.cached_jit(
        exp.call, label=f"jit.load:{os.path.basename(path)}")

    def forward(*inputs):
        arrs = [x.data if isinstance(x, Tensor) else jnp.asarray(x)
                for x in inputs]
        out = call([p.data for p in params], *arrs)
        return jax.tree_util.tree_map(Tensor, out)

    layer.forward = forward
    layer._param_keys = meta["param_keys"]
    layer.eval()
    return layer


def not_to_static(fn=None):
    return fn if fn is not None else (lambda f: f)


def ignore_module(modules):
    return None


class ProgramTranslator:
    """reference dygraph_to_static ProgramTranslator singleton: the
    enable/disable switch for to_static conversion."""

    _instance = None
    _enabled = True

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    @classmethod
    def get_instance(cls):
        return cls()

    def enable(self, enable_to_static: bool):
        ProgramTranslator._enabled = bool(enable_to_static)


class TracedLayer:
    """reference dygraph/jit.py TracedLayer: trace-and-run wrapper. The
    capture machinery is StaticLayer; this keeps the trace/save surface."""

    def __init__(self, layer, inputs):
        self._static = StaticLayer(layer)
        self._layer = layer
        self._inputs = inputs

    @staticmethod
    def trace(layer, inputs):
        tl = TracedLayer(layer, inputs)
        out = tl._static(*inputs)
        return out, tl

    def __call__(self, *args):
        return self._static(*args)

    def save_inference_model(self, path, feed=None, fetch=None):
        from . import save as _save

        return _save(self._layer, path, input_spec=list(self._inputs))


def set_code_level(level=100):
    """reference dy2static debug knob: we have no transpiled-code printer;
    stored for API compat."""
    import os

    os.environ["PT_DY2STATIC_CODE_LEVEL"] = str(level)


def set_verbosity(level=0, also_to_stdout=False):
    import os

    os.environ["PT_DY2STATIC_VERBOSITY"] = str(level)


from .offload_stream import (  # noqa: E402,F401
    SegmentedTrainStep, StreamedTrainStep, init_on_host,
)
