"""Persistent executable cache: cross-process warm starts for compiled XLA.

Reference role: the reference's inference engine ships *serialized
programs* — an ``AnalysisPredictor`` loads an optimized ProgramDesc from
disk and never re-runs the optimization passes; likewise fluid's
``ParallelExecutor`` reuses build results across runs. On TPU the
analogous cold-start tax is XLA compilation: every fresh process pays
seconds-to-minutes compiling the very same programs it compiled yesterday
(training steps, ``to_static`` forwards, every serving bucket warmup).

This module closes that gap with an on-disk cache of **compiled
executables**:

- key = SHA-256 over (lowered StableHLO text, backend platform,
  jax/jaxlib versions, donation metadata, sharding/static metadata) — a
  stale jax upgrade or a changed donation plan is a *different key*, never
  a wrong hit;
- value = ``jax.experimental.serialize_executable`` payload (the AOT
  `compiled.serialize()` path) plus a small header re-verified at load;
- backends that cannot serialize executables degrade to enabling JAX's own
  compilation-cache directory (same disk location, coarser granularity)
  so the warm start still happens one layer down.

Default **off** — nothing changes for code that doesn't opt in. Enable
with ``enable(dir)`` or the env vars ``PT_PERSISTENT_CACHE_DIR=<dir>`` /
``PT_PERSISTENT_CACHE=1`` (read once at import). Corrupt or stale entries
are ignored gracefully (treated as a miss and overwritten).

Counters: ``stats()`` reports hits / misses / backend compiles / load
errors, per label — surfaced through ``analysis.retrace`` summaries and
``serving`` ``engine.stats()``.
"""
from __future__ import annotations

import hashlib
import os
import pickle
import tempfile
import threading
from typing import Any, Callable, Dict, Optional, Tuple

__all__ = ["enable", "disable", "is_enabled", "cache_dir", "stats",
           "reset_stats", "cached_jit", "CachedJit", "clear"]

_MAGIC = b"PTXC1\n"  # format tag; bump on layout change


class _State:
    def __init__(self):
        self.enabled = False
        self.dir: Optional[str] = None
        self.serialize_broken = False   # backend can't serialize: fallback
        self.lock = threading.Lock()
        self.counters: Dict[str, int] = {
            "hits": 0, "misses": 0, "compiles": 0, "errors": 0}
        self.by_label: Dict[str, Dict[str, int]] = {}


_STATE = _State()


def _env_meta() -> Tuple[str, ...]:
    """Version/platform facet of every cache key."""
    import jax
    import jaxlib

    return (jax.__version__, jaxlib.__version__, jax.default_backend(),
            str(len(jax.devices())))


def enable(path: Optional[str] = None) -> str:
    """Turn the cache on (idempotent). Returns the active directory.

    Entries are unpickled at load, so the directory must not be writable
    by other users: the fallback default is per-uid under the tempdir,
    created 0700, and a directory owned by someone else is refused."""
    if path is None:
        uid = os.getuid() if hasattr(os, "getuid") else "u"
        path = _STATE.dir or os.environ.get("PT_PERSISTENT_CACHE_DIR") or \
            os.path.join(tempfile.gettempdir(),
                         f"paddle_tpu_exec_cache-{uid}")
    os.makedirs(path, mode=0o700, exist_ok=True)
    if hasattr(os, "getuid"):
        st = os.stat(path)
        if st.st_uid != os.getuid():
            raise RuntimeError(
                f"persistent_cache: refusing cache dir {path!r} owned by "
                f"uid {st.st_uid} (entries are unpickled at load; use a "
                f"directory this user owns)")
        if st.st_mode & 0o077:  # pre-existing dir may be wider than 0700
            os.chmod(path, 0o700)
            if os.stat(path).st_mode & 0o022:
                raise RuntimeError(
                    f"persistent_cache: cache dir {path!r} stays "
                    f"group/world-writable; entries are unpickled at load "
                    f"— use a private directory")
    _STATE.dir = path
    _STATE.enabled = True
    return path


def disable() -> None:
    _STATE.enabled = False


def is_enabled() -> bool:
    return _STATE.enabled


def cache_dir() -> Optional[str]:
    return _STATE.dir


def clear() -> int:
    """Delete every cache entry in the active directory; returns count."""
    if not _STATE.dir or not os.path.isdir(_STATE.dir):
        return 0
    n = 0
    for name in os.listdir(_STATE.dir):
        if name.endswith(".ptxc"):
            try:
                os.unlink(os.path.join(_STATE.dir, name))
                n += 1
            except OSError:
                pass
    return n


def stats() -> Dict[str, Any]:
    """Snapshot of the hit/miss/compile counters (plus per-label rows)."""
    with _STATE.lock:
        snap: Dict[str, Any] = dict(_STATE.counters)
        snap["by_label"] = {k: dict(v) for k, v in _STATE.by_label.items()}
    snap["enabled"] = _STATE.enabled
    snap["dir"] = _STATE.dir
    snap["backend_serialize_unsupported"] = _STATE.serialize_broken
    return snap


def reset_stats() -> None:
    with _STATE.lock:
        for k in _STATE.counters:
            _STATE.counters[k] = 0
        _STATE.by_label.clear()


def _count(kind: str, label: Optional[str]) -> None:
    with _STATE.lock:
        _STATE.counters[kind] = _STATE.counters.get(kind, 0) + 1
        if label:
            row = _STATE.by_label.setdefault(
                label, {"hits": 0, "misses": 0, "compiles": 0, "errors": 0})
            row[kind] = row.get(kind, 0) + 1


def _entry_path(key: str) -> str:
    return os.path.join(_STATE.dir or "", key + ".ptxc")


def _write_entry(key: str, header: Dict[str, Any], payload: Tuple) -> None:
    """Atomic write: tmp file + rename so a concurrent reader never sees a
    half-written entry (the corruption the loader must survive anyway).
    A write failure (dir pruned by a tmp cleaner, disk full) is dropped —
    the cache is an optimization, never the thing that sinks a step."""
    path = _entry_path(key)
    blob = _MAGIC + pickle.dumps((header, payload),
                                 protocol=pickle.HIGHEST_PROTOCOL)
    tmp = None
    try:
        fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path), suffix=".tmp")
        with os.fdopen(fd, "wb") as f:
            f.write(blob)
        os.replace(tmp, path)
    except OSError:
        if tmp is not None:
            try:
                os.unlink(tmp)
            except OSError:
                pass


def _read_entry(key: str, label: Optional[str]) -> Optional[Tuple]:
    """Load (header-verified) payload, or None on missing/corrupt/stale."""
    path = _entry_path(key)
    try:
        with open(path, "rb") as f:
            blob = f.read()
    except OSError:
        return None
    try:
        if not blob.startswith(_MAGIC):
            raise ValueError("bad magic")
        header, payload = pickle.loads(blob[len(_MAGIC):])
        # belt and braces: versions are part of the key already, but a
        # tampered/renamed file must still be rejected here
        if tuple(header.get("env", ())) != _env_meta():
            raise ValueError("stale entry: environment mismatch")
        return payload
    except Exception:
        _count("errors", label)
        try:
            os.unlink(path)  # evict so the rewrite below lands cleanly
        except OSError:
            pass
        return None


def _fallback_jax_cache() -> None:
    """Backend can't serialize executables: turn on JAX's own on-disk
    compilation cache in the same directory so a later process still skips
    the XLA backend work (coarser: caches at the XLA client layer)."""
    import jax

    try:
        jax.config.update("jax_compilation_cache_dir", _STATE.dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception:
        pass  # the cache is an optimization; never sink the caller


_SHARDING_REPRS: "weakref.WeakKeyDictionary" = None  # type: ignore[assignment]


def _sharding_repr(sharding) -> str:
    """repr(sharding), memoized per object: a train step's leaves mostly
    share a handful of sharding instances, and the enabled-path signature
    runs per call — don't rebuild the same strings every step."""
    global _SHARDING_REPRS
    if _SHARDING_REPRS is None:
        import weakref

        _SHARDING_REPRS = weakref.WeakKeyDictionary()
    try:
        return _SHARDING_REPRS[sharding]
    except (KeyError, TypeError):
        pass
    r = repr(sharding)
    try:
        _SHARDING_REPRS[sharding] = r
    except TypeError:
        pass
    return r


def _abstract_sig(args: Tuple) -> Tuple:
    """Shape/dtype/weak-type AND placement per leaf: an AOT-compiled
    executable is specialized to its input shardings, so same-shape args
    committed elsewhere must be a different entry, not a call-time
    mismatch error (plain jax.jit keys on sharding too)."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(args)
    sig = []
    for leaf in leaves:
        if isinstance(leaf, jax.Array):  # fast path: no abstractify
            committed = getattr(leaf, "committed", False)
            sig.append((tuple(leaf.shape), leaf.dtype.name,
                        bool(getattr(leaf, "weak_type", False)),
                        _sharding_repr(leaf.sharding) if committed
                        else None))
            continue
        aval = jax.api_util.shaped_abstractify(leaf)
        sharding = getattr(leaf, "sharding", None)
        committed = getattr(leaf, "committed", False)
        sig.append((tuple(aval.shape), str(aval.dtype),
                    bool(getattr(aval, "weak_type", False)),
                    _sharding_repr(sharding) if committed else None))
    return (tuple(sig), str(treedef))


class CachedJit:
    """``jax.jit`` with a persistent per-signature compile step.

    Calls behave exactly like the wrapped jitted function. When the cache
    is enabled, the first call of each abstract signature goes through
    lower → disk lookup → (deserialize | compile+serialize); later calls
    reuse the in-memory executable. When disabled, calls delegate straight
    to ``jax.jit``'s own cache — a single flag check of overhead.
    """

    def __init__(self, fun: Callable, label: Optional[str] = None,
                 donate_argnums: Tuple[int, ...] = (),
                 extra_meta: Tuple = (), **jit_kwargs):
        import jax

        self._label = label or getattr(fun, "__name__", "fn")
        self._donate = tuple(donate_argnums)
        self._extra_meta = tuple(str(m) for m in extra_meta)
        # sharding metadata is part of the key: a re-meshed program must
        # never collide with its single-chip twin
        for k in ("in_shardings", "out_shardings"):
            if k in jit_kwargs:
                self._extra_meta += (k + "=" + repr(jit_kwargs[k]),)
        self._jitted = jax.jit(fun, donate_argnums=self._donate or None,
                               **jit_kwargs)
        self._compiled: Dict[Tuple, Callable] = {}
        self._build_lock = threading.Lock()

    def __call__(self, *args):
        if not _STATE.enabled:
            return self._jitted(*args)
        import jax

        if any(isinstance(l, jax.core.Tracer)
               for l in jax.tree_util.tree_leaves(args)):
            # called under an outer trace (make_jaxpr / nested jit): the
            # AOT lower/compile path needs concrete avals — inline instead
            return self._jitted(*args)
        sig = _abstract_sig(args)
        runner = self._compiled.get(sig)
        if runner is None:
            with self._build_lock:
                runner = self._compiled.get(sig)
                if runner is None:
                    runner = self._build(args, sig)
                    self._compiled[sig] = runner
        return runner(*args)

    # -- compile path ---------------------------------------------------------
    def _key(self, lowered, sig) -> str:
        h = hashlib.sha256()
        h.update(lowered.as_text().encode())
        # sig carries input placements: the HLO text can be identical for
        # two placements whose compiled executables are not interchangeable
        h.update(repr(sig).encode())
        for part in _env_meta() + self._extra_meta:
            h.update(b"\x00" + part.encode())
        h.update(b"\x00donate=" + repr(self._donate).encode())
        return h.hexdigest()

    def _build(self, args, sig) -> Callable:
        lowered = self._jitted.lower(*args)
        key = self._key(lowered, sig)
        # serialize_broken gates WRITES only: one program that cannot
        # round-trip must not stop other programs' valid on-disk entries
        # from loading
        payload = _read_entry(key, self._label)
        if payload is not None:
            loaded = self._try_deserialize(payload)
            if loaded is not None:
                _count("hits", self._label)
                return loaded
        _count("misses", self._label)
        compiled = lowered.compile()
        _count("compiles", self._label)
        self._try_serialize(key, compiled)
        return compiled

    def _try_deserialize(self, payload) -> Optional[Callable]:
        try:
            from jax.experimental import serialize_executable

            return serialize_executable.deserialize_and_load(*payload)
        except Exception:
            _count("errors", self._label)
            return None

    def _try_serialize(self, key: str, compiled) -> None:
        if _STATE.serialize_broken or not _STATE.dir:
            return
        try:
            from jax.experimental import serialize_executable

            payload = serialize_executable.serialize(compiled)
            pickle.dumps(payload)  # probe: unpicklable trees = broken entry
        except Exception:
            # this backend (or this program) can't round-trip executables:
            # degrade to jax's own compilation-cache directory
            _STATE.serialize_broken = True
            _fallback_jax_cache()
            return
        _write_entry(key, {"env": _env_meta(), "label": self._label}, payload)

    # introspection used by jit._maybe_audit wrappers
    @property
    def __wrapped__(self):
        return self._jitted

    def lower(self, *args, **kwargs):
        return self._jitted.lower(*args, **kwargs)


def cached_jit(fun: Callable, label: Optional[str] = None,
               donate_argnums: Tuple[int, ...] = (),
               extra_meta: Tuple = (), **jit_kwargs) -> Callable:
    """Drop-in for ``jax.jit`` that persists compiles across processes.

    Always returns a ``CachedJit`` wrapper; when the cache is disabled the
    wrapper is a transparent passthrough to ``jax.jit``, so call sites can
    use this unconditionally.
    """
    return CachedJit(fun, label=label, donate_argnums=donate_argnums,
                     extra_meta=extra_meta, **jit_kwargs)


def _maybe_enable_from_env() -> None:
    d = os.environ.get("PT_PERSISTENT_CACHE_DIR", "").strip()
    flag = os.environ.get("PT_PERSISTENT_CACHE", "").strip().lower()
    if not d and flag not in ("1", "true", "on"):
        return
    try:
        enable(d or None)
    except Exception as e:
        # a bad env var must not make `import paddle_tpu` itself fail —
        # degrade to a disabled cache, loudly
        import warnings

        warnings.warn(f"persistent_cache: disabled ({e})", stacklevel=2)
        _STATE.enabled = False


_maybe_enable_from_env()
