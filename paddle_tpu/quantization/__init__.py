"""paddle.quantization (reference: python/paddle/fluid/contrib/slim/quantization
— QuantizationTransformPass / ImperativeQuantAware + fake_quantize ops under
paddle/fluid/operators/fake_quantize_op.cc).

TPU-native: fake-quant is one dispatched primitive with a straight-through
vjp (the fake_quantize_dequantize kernel role); QAT swaps Linear/Conv2D for
fake-quant wrappers; PTQ observes abs-max over calibration batches and
converts weights to int8 + scale (simulated dequant at matmul time — XLA
int8 matmul feeds the MXU on current TPUs via bf16 upcast).
"""
from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import primitive
from ..core.tensor import Tensor
from .. import nn

__all__ = ["fake_quant", "FakeQuantAbsMax", "QuantedLinear", "QuantedConv2D",
           "QAT", "PTQ", "quant_linear_int8"]


@primitive("fake_quant_dequant")
def _fake_qdq(x, scale, *, bits):
    qmax = float(2 ** (bits - 1) - 1)
    s = jnp.maximum(scale, 1e-9)
    q = jnp.clip(jnp.round(x / s * qmax), -qmax, qmax)
    return q * s / qmax


@_fake_qdq.defvjp
def _fake_qdq_vjp(ct, out, primals, *, bits):
    """Straight-through estimator: pass grads where |x| <= scale."""
    x, scale = primals
    mask = (jnp.abs(x) <= jnp.maximum(scale, 1e-9)).astype(ct.dtype)
    return ct * mask, None


def fake_quant(x, scale, bits=8):
    """Quantize-dequantize with STE backward (fake_quantize_dequantize role)."""
    return _fake_qdq(x, scale, bits=int(bits))


class FakeQuantAbsMax(nn.Layer):
    """Moving-average abs-max observer + fake quant (reference
    FakeQuantMovingAverageAbsMax)."""

    def __init__(self, bits=8, momentum=0.9):
        super().__init__()
        self.bits = bits
        self.momentum = momentum
        from ..ops import creation

        # scale == 0 means "never observed" — persisted through state_dict,
        # so a restored EMA continues instead of restarting from the batch max
        self.register_buffer("scale", creation.zeros([]))

    def forward(self, x):
        import numpy as np

        seen = float(np.asarray(self.scale.data)) > 0.0
        if self.training:
            from ..ops import reduction as R

            cur = R.max(x.abs()).astype("float32")
            if not seen:
                self.scale.data = cur.data
            else:
                self.scale.data = (self.momentum * self.scale.data
                                   + (1 - self.momentum) * cur.data)
        elif not seen:
            return x  # uncalibrated eval: pass through rather than zero out
        return fake_quant(x, self.scale, self.bits)


class QuantedLinear(nn.Layer):
    """Linear with weight + activation fake quant (QAT wrapper role)."""

    def __init__(self, layer: nn.Linear, bits=8):
        super().__init__()
        self.inner = layer
        self.bits = bits
        self.act_quant = FakeQuantAbsMax(bits)

    def forward(self, x):
        from ..ops import reduction as R

        x = self.act_quant(x)
        w = self.inner.weight
        w_scale = R.max(w.abs()).astype("float32")
        wq = fake_quant(w, w_scale, self.bits)
        from ..nn import functional as F

        return F.linear(x, wq, self.inner.bias)


class QuantedConv2D(nn.Layer):
    def __init__(self, layer: nn.Conv2D, bits=8):
        super().__init__()
        self.inner = layer
        self.bits = bits
        self.act_quant = FakeQuantAbsMax(bits)

    def forward(self, x):
        from ..ops import reduction as R
        from ..nn import functional as F

        x = self.act_quant(x)
        w = self.inner.weight
        wq = fake_quant(w, R.max(w.abs()).astype("float32"), self.bits)
        return F.conv2d(x, wq, self.inner.bias, self.inner._stride,
                        self.inner._padding, self.inner._dilation,
                        self.inner._groups)


class QAT:
    """Quant-aware training driver (reference ImperativeQuantAware.quantize)."""

    def __init__(self, bits=8):
        self.bits = bits

    def quantize(self, model: nn.Layer) -> nn.Layer:
        """Swap quantizable sublayers in place; returns the model."""
        for name, sub in list(model._sub_layers.items()):
            if isinstance(sub, nn.Linear):
                model._sub_layers[name] = QuantedLinear(sub, self.bits)
            elif isinstance(sub, nn.Conv2D):
                model._sub_layers[name] = QuantedConv2D(sub, self.bits)
            else:
                self.quantize(sub)
        return model


def quant_linear_int8(weight) -> tuple:
    """weight -> (int8 ndarray, float scale): the PTQ convert step."""
    w = np.asarray(weight.data if isinstance(weight, Tensor) else weight,
                   "float32")
    scale = float(np.abs(w).max()) or 1e-9
    q = np.clip(np.round(w / scale * 127.0), -127, 127).astype(np.int8)
    return q, scale


class _Int8Linear(nn.Layer):
    """Inference-only int8 linear: int8 weights + scale; activations are
    statically quantized with the calibrated abs-max when one was observed
    (the reference's activation-scale use in PostTrainingQuantization)."""

    def __init__(self, qweight: np.ndarray, scale: float, bias,
                 act_scale: Optional[float] = None, bits: int = 8):
        super().__init__()
        self.register_buffer("qweight", Tensor(jnp.asarray(qweight)))
        self.scale = scale
        self.act_scale = act_scale
        self.bits = bits
        self.bias = bias

    def forward(self, x):
        from ..nn import functional as F

        if self.act_scale:
            x = fake_quant(x, Tensor(jnp.asarray(self.act_scale, jnp.float32)),
                           self.bits)
        w = (self.qweight.astype(str(x.dtype)) * (self.scale / 127.0))
        return F.linear(x, w, self.bias)


class PTQ:
    """Post-training quantization (reference PostTrainingQuantization):
    calibrate activations, convert Linear weights to int8 + scale."""

    def __init__(self, bits=8):
        self.bits = bits
        self._observed: Dict[int, float] = {}
        self._hooks = []

    def quantize(self, model: nn.Layer) -> nn.Layer:
        """Install activation observers; run calibration batches, then
        convert()."""
        for _, sub in model.named_sublayers():
            if isinstance(sub, nn.Linear):
                def hook(l, ins, outs):
                    x = ins[0]
                    cur = float(np.abs(np.asarray(x.data)).max())
                    self._observed[id(l)] = max(self._observed.get(id(l), 0.0),
                                                cur)
                self._hooks.append(sub.register_forward_pre_hook(
                    lambda l, ins, _h=hook: _h(l, ins, None)))
        return model

    def convert(self, model: nn.Layer) -> nn.Layer:
        for h in self._hooks:
            h.remove()
        self._hooks = []
        self._convert(model)
        return model

    def _convert(self, model: nn.Layer):
        for name, sub in list(model._sub_layers.items()):
            if isinstance(sub, nn.Linear):
                q, scale = quant_linear_int8(sub.weight)
                model._sub_layers[name] = _Int8Linear(
                    q, scale, sub.bias,
                    act_scale=self._observed.get(id(sub)), bits=self.bits)
            else:
                self._convert(sub)
