"""Control-flow ops (reference: python/paddle/fluid/layers/control_flow.py
cond:2297, while_loop:1064, case, switch_case; exported via static/nn).

TPU-native dual path: with a *concrete* predicate (eager mode) the Python
branch runs directly — the autograd tape records through it like any other
ops. With a *traced* predicate (inside jit.to_static / TrainStep) the op
lowers to lax.cond / lax.while_loop / lax.switch so both branches compile into
the one XLA executable (the role of the reference's ConditionalBlockOp /
WhileOp sub-block execution).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.tensor import Tensor

__all__ = ["cond", "while_loop", "case", "switch_case"]


def _is_tracer(x):
    return isinstance(x, jax.core.Tracer)


def _to_data(tree):
    return jax.tree_util.tree_map(
        lambda t: t.data if isinstance(t, Tensor) else t, tree,
        is_leaf=lambda t: isinstance(t, Tensor))


def _to_tensor(tree):
    return jax.tree_util.tree_map(
        lambda a: Tensor(a) if isinstance(a, (jax.Array, jnp.ndarray)) else a,
        tree)


def _pred_value(pred):
    if isinstance(pred, Tensor):
        return pred.data
    return pred


def cond(pred, true_fn=None, false_fn=None, name=None, return_names=None):
    """Run true_fn() or false_fn() (both callables of no arguments).

    Both branches must return the same structure of Tensors (reference
    control_flow.py:2297 contract)."""
    pd = _pred_value(pred)
    if not _is_tracer(pd):
        chosen = true_fn if bool(np_bool(pd)) else false_fn
        return chosen() if chosen is not None else None
    if true_fn is None or false_fn is None:
        raise ValueError("traced cond requires both true_fn and false_fn")
    out = jax.lax.cond(jnp.asarray(pd).astype(bool).reshape(()),
                       lambda _: _to_data(true_fn()),
                       lambda _: _to_data(false_fn()),
                       operand=None)
    return _to_tensor(out)


def np_bool(x):
    import numpy as np

    return bool(np.asarray(x))


def while_loop(cond_fn, body_fn, loop_vars, is_test=False, name=None):
    """paddle.static.nn.while_loop (reference control_flow.py:1064).

    loop_vars is a list; body returns the same-length list. Shapes must be
    loop-invariant under trace (XLA requirement; the reference's WhileOp allows
    LoD growth, which has no TPU-legal equivalent — use padded buffers)."""
    if not isinstance(loop_vars, (list, tuple)) or not loop_vars:
        raise ValueError("loop_vars must be a non-empty list")
    probe = cond_fn(*loop_vars)
    pd = _pred_value(probe)
    if not _is_tracer(pd):
        vars_ = list(loop_vars)
        while np_bool(_pred_value(cond_fn(*vars_))):
            out = body_fn(*vars_)
            vars_ = list(out) if isinstance(out, (list, tuple)) else [out]
        return vars_
    flat = _to_data(list(loop_vars))

    def c(vs):
        return jnp.asarray(_pred_value(cond_fn(*_to_tensor(vs)))).reshape(())

    def b(vs):
        out = body_fn(*_to_tensor(vs))
        out = list(out) if isinstance(out, (list, tuple)) else [out]
        return _to_data(out)

    out = jax.lax.while_loop(c, b, flat)
    return _to_tensor(out)


def case(pred_fn_pairs, default=None, name=None):
    """First true predicate wins (reference control_flow.py case)."""
    if not pred_fn_pairs:
        raise ValueError("pred_fn_pairs must be non-empty")
    preds = [_pred_value(p) for p, _ in pred_fn_pairs]
    if not any(_is_tracer(p) for p in preds):
        for p, fn in pred_fn_pairs:
            if np_bool(_pred_value(p)):
                return fn()
        if default is None:
            return pred_fn_pairs[-1][1]()
        return default()
    # traced: right-fold into nested lax.cond
    tail = default if default is not None else pred_fn_pairs[-1][1]

    def build(i):
        if i == len(pred_fn_pairs):
            return tail
        p, fn = pred_fn_pairs[i]
        return lambda: cond(p, fn, build(i + 1))

    return build(0)()


def switch_case(branch_index, branch_fns, default=None, name=None):
    """Dispatch on an integer index (reference control_flow.py switch_case).

    branch_fns: dict {index: fn} or list of (index, fn) or list of fns."""
    if isinstance(branch_fns, dict):
        items = sorted(branch_fns.items())
    elif branch_fns and isinstance(branch_fns[0], (tuple, list)):
        items = sorted((int(i), f) for i, f in branch_fns)
    else:
        items = list(enumerate(branch_fns))
    idx = _pred_value(branch_index)
    if not _is_tracer(idx):
        import numpy as np

        i = int(np.asarray(idx))
        for k, fn in items:
            if k == i:
                return fn()
        if default is not None:
            return default()
        return items[-1][1]()
    fallback = default if default is not None else items[-1][1]
    keys = jnp.asarray([k for k, _ in items])
    # map arbitrary branch keys to dense positions; miss -> fallback slot
    dense = jnp.sum(jnp.where(keys == jnp.asarray(idx).reshape(()),
                              jnp.arange(len(items)), 0))
    hit = jnp.any(keys == jnp.asarray(idx).reshape(()))
    branches = [lambda _, f=fn: _to_data(f()) for _, fn in items]
    branches.append(lambda _: _to_data(fallback()))
    sel = jnp.where(hit, dense, len(items))
    out = jax.lax.switch(sel, branches, None)
    return _to_tensor(out)


def fc(x=None, size=None, num_flatten_dims=1, weight_attr=None,
       bias_attr=None, activation=None, name=None, input=None):
    """Fully-connected builder (reference static/nn/common.py fc): dims
    [num_flatten_dims:] flatten into the feature axis (weight
    [prod(trailing), size]) and the leading dims are restored on the output
    — fc([2,3,4], size=5, num_flatten_dims=2) -> [2,3,5] with a [4,5]
    weight; num_flatten_dims=1 -> [2,5] with a [12,5] weight. Build-time
    parameter creation is eager (the startup program's role); the matmul
    and activation record into the default program like any other op."""
    from ... import nn as nn_mod
    from ...nn import functional as F
    from ...ops import manipulation

    x = x if x is not None else input  # fluid-era keyword
    if x is None or size is None:
        raise ValueError("static.nn.fc requires x and size")
    nfd = int(num_flatten_dims)
    if not 0 < nfd < len(x.shape):
        raise ValueError(
            f"fc: num_flatten_dims={nfd} out of range for rank "
            f"{len(x.shape)} input")
    from ..compat import declared_shape

    declared = declared_shape(x)
    if declared is not None:
        # only the LEADING (batch) dim may be dynamic: trailing dims fold
        # into the weight shape and non-batch lead dims bake into the
        # recorded restore-reshape — a None there would silently build the
        # wrong Linear from the build-time dummy
        bad = [i for i, d in enumerate(declared)
               if i > 0 and (d is None or (isinstance(d, int) and d < 0))]
        if bad:
            raise ValueError(
                f"static.nn.fc: placeholder dims {bad} are dynamic but only "
                f"dim 0 (batch) may be None — trailing/middle dims size the "
                f"weight and the output reshape (declared {declared})")
    lead_shape = list(x.shape[:nfd])
    in_features = 1
    for d in x.shape[nfd:]:
        in_features *= int(d)
    if len(x.shape) > nfd + 1:
        x = manipulation.reshape(x, [-1] + [in_features])
    layer = nn_mod.Linear(in_features, int(size), weight_attr=weight_attr,
                          bias_attr=bias_attr)
    out = layer(x)
    if len(lead_shape) > 1:
        # -1 for the batch dim: build-time placeholder shapes are dummies
        # and the recorded reshape must respecialize per feed
        out = manipulation.reshape(
            out, [-1] + [int(d) for d in lead_shape[1:]] + [int(size)])
    if activation:
        out = getattr(F, activation)(out)
    return out


__all__ += ["fc"]
