"""paddle.static compat surface (reference: python/paddle/static/).

paddle_tpu is dygraph-first; graph capture is tracing, not a ProgramDesc
build. Two layers live here:

- the meaningful carry-overs: InputSpec (trace signatures), control-flow ops
  (lax.cond/while_loop backed), save/load_inference_model pointers;
- a full STATIC-MODE COMPAT SHIM (compat.py): enable_static() +
  static.data + program_guard + Executor.run(feed/fetch) implemented as
  record-and-replay over the dygraph dispatch, so reference-era static
  training scripts (the test_fit_a_line.py shape) run unmodified — without
  rebuilding a second IR.
"""
from __future__ import annotations

import numpy as np

from . import nn  # noqa: F401
from .input_spec import InputSpec  # noqa: F401
from .compat import (  # noqa: F401
    Executor, Program, data, default_main_program, default_startup_program,
    program_guard,
)


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None,
                         program=None, **kwargs):
    """Maps to jit.save of the traced layer (reference static/io.py)."""
    raise NotImplementedError(
        "use paddle_tpu.jit.save(layer, path, input_spec=[...]) — tracing "
        "replaces Program capture on this framework")


def load_inference_model(path_prefix, executor=None, **kwargs):
    raise NotImplementedError(
        "use paddle_tpu.jit.load(path) or paddle_tpu.inference.create_predictor")
