"""paddle.static compat surface (reference: python/paddle/static/).

paddle_tpu is dygraph-first: graph capture is `paddle_tpu.jit.to_static`
(tracing), not a ProgramDesc build. This module provides the pieces of the
static API that carry over meaningfully: InputSpec (trace signatures),
control-flow ops (lax.cond/while_loop backed), and save/load_inference_model
(jax.export AOT artifacts). Program/Executor raise with pointers to the
dygraph equivalents rather than emulating a second IR.
"""
from __future__ import annotations

import numpy as np

from . import nn  # noqa: F401
from .input_spec import InputSpec  # noqa: F401


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None,
                         program=None, **kwargs):
    """Maps to jit.save of the traced layer (reference static/io.py)."""
    raise NotImplementedError(
        "use paddle_tpu.jit.save(layer, path, input_spec=[...]) — tracing "
        "replaces Program capture on this framework")


def load_inference_model(path_prefix, executor=None, **kwargs):
    raise NotImplementedError(
        "use paddle_tpu.jit.load(path) or paddle_tpu.inference.create_predictor")


class Program:  # pragma: no cover - compat stub
    def __init__(self):
        raise NotImplementedError(
            "paddle_tpu has no ProgramDesc IR; capture graphs with "
            "paddle_tpu.jit.to_static (jaxpr/StableHLO is the program)")


class Executor:  # pragma: no cover - compat stub
    def __init__(self, place=None):
        raise NotImplementedError(
            "paddle_tpu has no static Executor; compiled execution is "
            "paddle_tpu.jit.to_static / jit.TrainStep (XLA executables)")


def default_main_program():  # pragma: no cover - compat stub
    raise NotImplementedError("no ProgramDesc IR; see paddle_tpu.jit")


def default_startup_program():  # pragma: no cover - compat stub
    raise NotImplementedError("no ProgramDesc IR; see paddle_tpu.jit")
