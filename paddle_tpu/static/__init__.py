"""paddle.static compat surface (reference: python/paddle/static/).

paddle_tpu is dygraph-first; graph capture is tracing, not a ProgramDesc
build. Two layers live here:

- the meaningful carry-overs: InputSpec (trace signatures), control-flow ops
  (lax.cond/while_loop backed), save/load_inference_model pointers;
- a full STATIC-MODE COMPAT SHIM (compat.py): enable_static() +
  static.data + program_guard + Executor.run(feed/fetch) implemented as
  record-and-replay over the dygraph dispatch, so reference-era static
  training scripts (the test_fit_a_line.py shape) run unmodified — without
  rebuilding a second IR.
"""
from __future__ import annotations

import numpy as np

from . import nn  # noqa: F401
from .input_spec import InputSpec  # noqa: F401
from .compat import (  # noqa: F401
    Executor, Program, data, default_main_program, default_startup_program,
    program_guard,
)


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None,
                         program=None, **kwargs):
    """Serialize the recorded static Program as a servable StableHLO
    artifact (reference static/io.py:433): jit.load- and
    inference.create_predictor-compatible .pdmodel/.pdiparams/.pdmeta."""
    from .compat import save_inference_model_impl

    return save_inference_model_impl(path_prefix, feed_vars, fetch_vars,
                                     program=program)


def load_inference_model(path_prefix, executor=None, **kwargs):
    """reference static/io.py load_inference_model: returns
    [inference_program, feed_target_names, fetch_targets]; the program is
    Executor.run-able with feed dicts + the returned fetch targets."""
    from .compat import load_inference_model_impl

    return load_inference_model_impl(path_prefix)
