"""InputSpec: trace signature descriptor (reference:
python/paddle/static/input.py InputSpec)."""
from __future__ import annotations

import numpy as np


class InputSpec:
    def __init__(self, shape, dtype="float32", name=None, stop_gradient=True):
        self.shape = tuple(shape)
        self.dtype = str(np.dtype(dtype)) if dtype not in (
            "bfloat16",) else "bfloat16"
        self.name = name
        self.stop_gradient = stop_gradient

    @classmethod
    def from_tensor(cls, tensor, name=None):
        return cls(tuple(tensor.shape), str(tensor.dtype), name)

    @classmethod
    def from_numpy(cls, ndarray, name=None):
        return cls(ndarray.shape, str(ndarray.dtype), name)

    def batch(self, batch_size):
        return InputSpec((batch_size,) + self.shape, self.dtype, self.name)

    def unbatch(self):
        if not self.shape:
            raise ValueError("unbatch: shape is empty")
        return InputSpec(self.shape[1:], self.dtype, self.name)

    def __repr__(self):
        return (f"InputSpec(shape={self.shape}, dtype={self.dtype}, "
                f"name={self.name})")

    def __eq__(self, other):
        return (isinstance(other, InputSpec) and self.shape == other.shape
                and self.dtype == other.dtype and self.name == other.name)

    def __hash__(self):
        return hash((self.shape, self.dtype, self.name))
