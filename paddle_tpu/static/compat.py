"""Static-graph compat shim: record-and-replay over the dygraph dispatch.

Reference: python/paddle/fluid/framework.py:4624 (Program), executor.py:1095
(Executor.run feed/fetch), python/paddle/static/input.py (data). No
ProgramDesc IR is rebuilt: under ``paddle.enable_static()`` every primitive
dispatch RECORDS an SSA node into the default Program while still computing
placeholder (dummy) values eagerly — Python build-phase control flow just
works — and ``Executor.run`` replays the recorded graph against the real
feed arrays. ``optimizer.minimize(loss)`` marks the program as a training
program: the replay then runs under ``jax.value_and_grad`` over the live
Parameters and applies the dygraph optimizer update, which is exactly the
role split of the reference's append_backward + optimizer ops.

Deliberate limits (documented, loud): the graph is shape-specialized per
feed (placeholder None dims re-trace, like to_static), and ops must flow
through the primitive dispatch (all of paddle_tpu's op corpus does).
"""
from __future__ import annotations

import weakref
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np


class _Node:
    __slots__ = ("prim", "attrs", "inputs", "out_ids", "multi")

    def __init__(self, prim, attrs, inputs, out_ids, multi):
        self.prim = prim
        self.attrs = attrs
        self.inputs = inputs  # list of ("value", aid) | ("param", Tensor)
        #                       | ("const", array)
        self.out_ids = out_ids
        self.multi = multi


class Program:
    """Recorded op list + feed table (reference framework.py:4624 Program)."""

    def __init__(self):
        self.nodes: List[_Node] = []
        self.feeds: Dict[str, tuple] = {}  # name -> (aid, dtype, shape)
        self._values: Dict[int, Any] = {}  # id -> dummy array (keeps ids live)
        self.train_spec = None  # (loss_aid, optimizer)

    # -- build-time recording ------------------------------------------------
    def _register_value(self, arr) -> int:
        aid = id(arr)
        self._values[aid] = arr
        return aid

    def add_feed(self, name, arr, dtype, shape):
        if name in self.feeds:
            raise ValueError(f"static.data: duplicate feed name '{name}'")
        self.feeds[name] = (self._register_value(arr), dtype, shape)

    def record(self, prim, attrs, arrays, tensors, outs_raw, multi):
        from ..nn.layer.layers import Parameter

        inputs = []
        for arr, t in zip(arrays, tensors):
            aid = id(arr)
            if aid in self._values:
                inputs.append(("value", aid))
            elif isinstance(t, Parameter):
                inputs.append(("param", t))  # live ref: replay reads t.data
            else:
                inputs.append(("const", arr))
        out_ids = [self._register_value(o) for o in outs_raw]
        self.nodes.append(_Node(prim, dict(attrs), inputs, out_ids, multi))

    # -- introspection -------------------------------------------------------
    def parameters(self):
        return [p for p in self.param_tensors() if not p.stop_gradient]

    def param_tensors(self):
        """Every Parameter the recorded graph reads (trainable or not)."""
        seen, out = set(), []
        for node in self.nodes:
            for kind, payload in node.inputs:
                if kind == "param" and id(payload) not in seen:
                    seen.add(id(payload))
                    out.append(payload)
        return out

    def set_train(self, loss, optimizer):
        aid = id(loss.data)
        if aid not in self._values:
            raise ValueError(
                "minimize(loss): the loss was not produced by this static "
                "program (build it between enable_static() and run())")
        self.train_spec = (aid, optimizer)
        if not optimizer._parameter_list:
            optimizer._parameter_list = self.parameters()

    # -- replay --------------------------------------------------------------
    def _replay(self, env: Dict[int, Any], param_override=None):
        for node in self.nodes:
            ins = []
            for kind, payload in node.inputs:
                if kind == "value":
                    v = env.get(payload)
                    if v is None:
                        # produced outside the feed cone (a build-time value
                        # that doesn't depend on feeds): use the dummy
                        v = self._values[payload]
                    ins.append(v)
                elif kind == "param":
                    if param_override is not None and id(payload) in param_override:
                        ins.append(param_override[id(payload)])
                    else:
                        ins.append(payload.data)
                else:
                    ins.append(payload)
            out = node.prim.fwd(node.attrs)(*ins)
            outs = tuple(out) if node.multi else (out,)
            for oid, o in zip(node.out_ids, outs):
                env[oid] = o
        return env

    def global_block(self):  # minimal compat surface
        return self

    def clone(self, for_test=False):
        import copy

        p = Program()
        p.nodes = list(self.nodes)
        p.feeds = dict(self.feeds)
        p._values = self._values  # shared dummy table (ids must match)
        p.train_spec = None if for_test else self.train_spec
        return p


_STATE = {"static": False}
_DEFAULT = {"main": Program(), "startup": Program()}
_GUARD_STACK: List[tuple] = []


def enable_static():
    if _STATE["static"]:
        return  # idempotent, like the reference mode switch: a second call
        #         must not wipe the program a script already built
    _STATE["static"] = True
    _DEFAULT["main"] = Program()
    _DEFAULT["startup"] = Program()


def disable_static():
    _STATE["static"] = False


def in_static_mode() -> bool:
    return _STATE["static"]


def default_main_program() -> Program:
    return _DEFAULT["main"]


def default_startup_program() -> Program:
    return _DEFAULT["startup"]


class program_guard:
    """Swap the default (main, startup) programs (reference
    framework.py program_guard)."""

    def __init__(self, main_program, startup_program=None):
        self.main = main_program
        self.startup = startup_program or Program()

    def __enter__(self):
        _GUARD_STACK.append((_DEFAULT["main"], _DEFAULT["startup"]))
        _DEFAULT["main"], _DEFAULT["startup"] = self.main, self.startup
        return self

    def __exit__(self, *exc):
        _DEFAULT["main"], _DEFAULT["startup"] = _GUARD_STACK.pop()
        return False


def record_dispatch(prim, attrs, arrays, tensors, outs_raw, multi):
    """Hook called from core.tensor.dispatch for every op in static mode."""
    _DEFAULT["main"].record(prim, attrs, arrays, tensors, outs_raw, multi)


def declared_shape(t) -> tuple:
    """The as-declared placeholder shape (None dims preserved) for a
    static.data Tensor, or None when `t` is not a feed placeholder. Builders
    use this to reject dims that must be concrete (static.nn.fc)."""
    aid = id(t.data) if hasattr(t, "data") else id(t)
    for _name, (fid, _dt, shape) in _DEFAULT["main"].feeds.items():
        if fid == aid:
            return tuple(shape)
    return None


def data(name: str, shape, dtype="float32", lod_level=0):
    """Feed placeholder (reference static/input.py data): a dummy-valued
    Tensor registered in the default program's feed table. None/-1 dims
    materialize as 1 at build time and re-specialize per feed at run."""
    from ..core.tensor import Tensor

    if not in_static_mode():
        raise RuntimeError("paddle.static.data requires enable_static()")
    dummy_shape = tuple(1 if (d is None or (isinstance(d, int) and d < 0))
                        else int(d) for d in shape)
    arr = jnp.zeros(dummy_shape, dtype)
    t = Tensor(arr, stop_gradient=True)
    t.name = name
    _DEFAULT["main"].add_feed(name, arr, dtype, tuple(shape))
    return t


class Executor:
    """reference executor.py:1095. run(startup) is a no-op (parameters
    initialize eagerly at build); run(main, feed, fetch_list) replays the
    recorded graph — with the training extension when minimize() was
    called: value_and_grad over the live Parameters + dygraph update."""

    def __init__(self, place=None):
        self.place = place

    def run(self, program=None, feed=None, fetch_list=None,
            return_numpy=True, **kwargs):
        program = program if program is not None else _DEFAULT["main"]
        if not isinstance(program, Program):
            raise TypeError(f"Executor.run expects a Program, got "
                            f"{type(program).__name__}")
        if not program.nodes and not program.feeds:
            return []  # startup program: nothing to execute
        feed = feed or {}
        missing = set(program.feeds) - set(feed)
        if missing:
            raise ValueError(f"Executor.run: missing feeds {sorted(missing)}")
        env: Dict[int, Any] = {}
        for name, (aid, dtype, _shape) in program.feeds.items():
            if name in feed:
                env[aid] = jnp.asarray(np.asarray(feed[name]), dtype)
        fetch_list = fetch_list or []
        fetch_ids = []
        for f in fetch_list:
            aid = id(f.data) if hasattr(f, "data") else id(f)
            fetch_ids.append(aid)

        if program.train_spec is not None:
            outs = self._run_train(program, env, fetch_ids)
        else:
            outs = self._run_infer(program, env, fetch_ids)
        if return_numpy:
            return [np.asarray(o) for o in outs]
        from ..core.tensor import Tensor

        return [Tensor(o) for o in outs]

    def _run_infer(self, program: Program, env, fetch_ids):
        """Inference replay, jit-compiled and cached per feed shape (same
        specialization contract as the training path)."""
        feed_keys = sorted(env.keys())
        all_params = program.param_tensors()  # args, NOT baked constants:
        #                                       training may update them
        cache_key = (
            tuple((k, tuple(env[k].shape), str(env[k].dtype))
                  for k in feed_keys),
            tuple(fetch_ids), None,
        )
        cache = program.__dict__.setdefault("_train_jit", {})
        jitted = cache.get(cache_key)
        if jitted is None:
            def infer_fn(param_arrays, feed_vals):
                override = {id(p): a for p, a in zip(all_params, param_arrays)}
                e = program._replay(dict(zip(feed_keys, feed_vals)),
                                    param_override=override)
                return tuple(e.get(aid, program._values.get(aid))
                             for aid in fetch_ids)

            jitted = jax.jit(infer_fn)
            cache[cache_key] = jitted
        return list(jitted([p.data for p in all_params],
                           [env[k] for k in feed_keys]))

    def _run_train(self, program: Program, env, fetch_ids):
        """One training iteration: grads via value_and_grad over the replay.
        The replay + autodiff is jax.jit-compiled and CACHED per feed shape
        (the to_static-style specialization the module docstring promises) —
        the hot loop of a static script must not re-trace per step."""
        from ..core.tensor import Tensor

        loss_aid, optimizer = program.train_spec
        params = optimizer._parameter_list or program.parameters()
        train_params = [p for p in params if not p.stop_gradient]
        train_ids = {id(p) for p in train_params}
        frozen_params = [p for p in program.param_tensors()
                         if id(p) not in train_ids]
        feed_keys = sorted(env.keys())
        cache_key = (
            tuple((k, tuple(env[k].shape), str(env[k].dtype))
                  for k in feed_keys),
            tuple(fetch_ids),
            tuple(id(p) for p in train_params),
        )
        cache = program.__dict__.setdefault("_train_jit", {})
        jitted = cache.get(cache_key)
        if jitted is None:
            def train_fn(param_arrays, frozen_arrays, feed_vals):
                base_env = dict(zip(feed_keys, feed_vals))
                frozen_map = {id(p): a
                              for p, a in zip(frozen_params, frozen_arrays)}

                def loss_of(pa):
                    override = dict(frozen_map)
                    override.update({id(p): a
                                     for p, a in zip(train_params, pa)})
                    e = program._replay(dict(base_env),
                                        param_override=override)
                    loss = e[loss_aid].astype(jnp.float32)
                    if loss.ndim:
                        loss = loss.mean()  # reference: mean vector losses
                    fetches = tuple(
                        e.get(aid, program._values.get(aid))
                        for aid in fetch_ids)
                    return loss, fetches

                (loss, fetches), grads = jax.value_and_grad(
                    loss_of, has_aux=True)(tuple(param_arrays))
                return loss, fetches, grads

            jitted = jax.jit(train_fn)
            cache[cache_key] = jitted
        _loss, fetches, grads = jitted(
            tuple(p.data for p in train_params),
            [p.data for p in frozen_params],
            [env[k] for k in feed_keys])
        for p, g in zip(train_params, grads):
            p.grad = Tensor(g.astype(p.dtype))
        optimizer.step()
        optimizer.clear_grad()
        return list(fetches)


def save_inference_model_impl(path_prefix, feed_vars, fetch_vars):
    raise NotImplementedError(
        "static save_inference_model: use paddle_tpu.jit.save on a dygraph "
        "layer — the static shim replays through the same jit machinery")
