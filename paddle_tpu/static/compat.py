"""Static-graph compat shim: record-and-replay over the dygraph dispatch.

Reference: python/paddle/fluid/framework.py:4624 (Program), executor.py:1095
(Executor.run feed/fetch), python/paddle/static/input.py (data). No
ProgramDesc IR is rebuilt: under ``paddle.enable_static()`` every primitive
dispatch RECORDS an SSA node into the default Program while still computing
placeholder (dummy) values eagerly — Python build-phase control flow just
works — and ``Executor.run`` replays the recorded graph against the real
feed arrays. ``optimizer.minimize(loss)`` marks the program as a training
program: the replay then runs under ``jax.value_and_grad`` over the live
Parameters and applies the dygraph optimizer update, which is exactly the
role split of the reference's append_backward + optimizer ops.

Deliberate limits (documented, loud): the graph is shape-specialized per
feed (placeholder None dims re-trace, like to_static), and ops must flow
through the primitive dispatch (all of paddle_tpu's op corpus does).
"""
from __future__ import annotations

import weakref
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np


class _Node:
    __slots__ = ("prim", "attrs", "inputs", "out_ids", "multi")

    def __init__(self, prim, attrs, inputs, out_ids, multi):
        self.prim = prim
        self.attrs = attrs
        self.inputs = inputs  # list of ("value", aid) | ("param", Tensor)
        #                       | ("const", array)
        self.out_ids = out_ids
        self.multi = multi


class Program:
    """Recorded op list + feed table (reference framework.py:4624 Program)."""

    def __init__(self):
        self.nodes: List[_Node] = []
        self.feeds: Dict[str, tuple] = {}  # name -> (aid, dtype, shape)
        self._values: Dict[int, Any] = {}  # id -> dummy array (keeps ids live)
        self.train_spec = None  # (loss_aid, optimizer)
        self._frozen = False  # set at first Executor.run: the build phase is
        # over, later eager ops (metrics on fetched results…) must not
        # append junk nodes that the next re-specialization would replay

    # -- build-time recording ------------------------------------------------
    def _register_value(self, arr) -> int:
        aid = id(arr)
        self._values[aid] = arr
        return aid

    def add_feed(self, name, arr, dtype, shape):
        if name in self.feeds:
            raise ValueError(f"static.data: duplicate feed name '{name}'")
        self.feeds[name] = (self._register_value(arr), dtype, shape)

    def record(self, prim, attrs, arrays, tensors, outs_raw, multi):
        from ..nn.layer.layers import Parameter

        if self._frozen:
            return  # run phase: eager ops between Executor.run calls are
            #         not part of the program (reference build/run split)
        inputs = []
        for arr, t in zip(arrays, tensors):
            aid = id(arr)
            if aid in self._values:
                inputs.append(("value", aid))
            elif isinstance(t, Parameter):
                inputs.append(("param", t))  # live ref: replay reads t.data
            else:
                inputs.append(("const", arr))
        out_ids = [self._register_value(o) for o in outs_raw]
        self.nodes.append(_Node(prim, dict(attrs), inputs, out_ids, multi))

    # -- introspection -------------------------------------------------------
    def parameters(self):
        return [p for p in self.param_tensors() if not p.stop_gradient]

    def param_tensors(self):
        """Every Parameter the recorded graph reads (trainable or not)."""
        seen, out = set(), []
        for node in self.nodes:
            for kind, payload in node.inputs:
                if kind == "param" and id(payload) not in seen:
                    seen.add(id(payload))
                    out.append(payload)
        return out

    def set_train(self, loss, optimizer):
        aid = id(loss.data)
        if aid not in self._values:
            raise ValueError(
                "minimize(loss): the loss was not produced by this static "
                "program (build it between enable_static() and run())")
        self.train_spec = (aid, optimizer)
        if not optimizer._parameter_list:
            optimizer._parameter_list = self.parameters()

    # -- replay --------------------------------------------------------------
    def _replay(self, env: Dict[int, Any], param_override=None):
        for node in self.nodes:
            ins = []
            for kind, payload in node.inputs:
                if kind == "value":
                    v = env.get(payload)
                    if v is None:
                        # produced outside the feed cone (a build-time value
                        # that doesn't depend on feeds): use the dummy
                        v = self._values[payload]
                    ins.append(v)
                elif kind == "param":
                    if param_override is not None and id(payload) in param_override:
                        ins.append(param_override[id(payload)])
                    else:
                        ins.append(payload.data)
                else:
                    ins.append(payload)
            out = node.prim.fwd(node.attrs)(*ins)
            outs = tuple(out) if node.multi else (out,)
            for oid, o in zip(node.out_ids, outs):
                env[oid] = o
        return env

    def global_block(self):  # minimal compat surface
        return self

    def clone(self, for_test=False):
        import copy

        p = Program()
        p.nodes = list(self.nodes)
        p.feeds = dict(self.feeds)
        p._values = self._values  # shared dummy table (ids must match)
        p.train_spec = None if for_test else self.train_spec
        return p


_STATE = {"static": False}
_DEFAULT = {"main": Program(), "startup": Program()}
_GUARD_STACK: List[tuple] = []


def enable_static():
    if _STATE["static"]:
        return  # idempotent, like the reference mode switch: a second call
        #         must not wipe the program a script already built
    _STATE["static"] = True
    _DEFAULT["main"] = Program()
    _DEFAULT["startup"] = Program()


def disable_static():
    _STATE["static"] = False


def in_static_mode() -> bool:
    return _STATE["static"]


def default_main_program() -> Program:
    return _DEFAULT["main"]


def default_startup_program() -> Program:
    return _DEFAULT["startup"]


class program_guard:
    """Swap the default (main, startup) programs (reference
    framework.py program_guard)."""

    def __init__(self, main_program, startup_program=None):
        self.main = main_program
        self.startup = startup_program or Program()

    def __enter__(self):
        _GUARD_STACK.append((_DEFAULT["main"], _DEFAULT["startup"]))
        _DEFAULT["main"], _DEFAULT["startup"] = self.main, self.startup
        return self

    def __exit__(self, *exc):
        _DEFAULT["main"], _DEFAULT["startup"] = _GUARD_STACK.pop()
        return False


def record_dispatch(prim, attrs, arrays, tensors, outs_raw, multi):
    """Hook called from core.tensor.dispatch for every op in static mode."""
    _DEFAULT["main"].record(prim, attrs, arrays, tensors, outs_raw, multi)


def declared_shape(t) -> tuple:
    """The as-declared placeholder shape (None dims preserved) for a
    static.data Tensor, or None when `t` is not a feed placeholder. Builders
    use this to reject dims that must be concrete (static.nn.fc)."""
    aid = id(t.data) if hasattr(t, "data") else id(t)
    for _name, (fid, _dt, shape) in _DEFAULT["main"].feeds.items():
        if fid == aid:
            return tuple(shape)
    return None


def data(name: str, shape, dtype="float32", lod_level=0):
    """Feed placeholder (reference static/input.py data): a dummy-valued
    Tensor registered in the default program's feed table. None/-1 dims
    materialize as 1 at build time and re-specialize per feed at run."""
    from ..core.tensor import Tensor

    if not in_static_mode():
        raise RuntimeError("paddle.static.data requires enable_static()")
    dummy_shape = tuple(1 if (d is None or (isinstance(d, int) and d < 0))
                        else int(d) for d in shape)
    arr = jnp.zeros(dummy_shape, dtype)
    t = Tensor(arr, stop_gradient=True)
    t.name = name
    _DEFAULT["main"].add_feed(name, arr, dtype, tuple(shape))
    return t


class Executor:
    """reference executor.py:1095. run(startup) is a no-op (parameters
    initialize eagerly at build); run(main, feed, fetch_list) replays the
    recorded graph — with the training extension when minimize() was
    called: value_and_grad over the live Parameters + dygraph update."""

    def __init__(self, place=None):
        self.place = place

    def run(self, program=None, feed=None, fetch_list=None,
            return_numpy=True, **kwargs):
        program = program if program is not None else _DEFAULT["main"]
        if isinstance(program, LoadedProgram):
            return program._run(feed or {}, fetch_list, return_numpy)
        if not isinstance(program, Program):
            raise TypeError(f"Executor.run expects a Program, got "
                            f"{type(program).__name__}")
        if not program.nodes and not program.feeds:
            return []  # startup program: nothing to execute
        feed = feed or {}
        missing = set(program.feeds) - set(feed)
        if missing:
            raise ValueError(f"Executor.run: missing feeds {sorted(missing)}")
        env: Dict[int, Any] = {}
        for name, (aid, dtype, _shape) in program.feeds.items():
            if name in feed:
                env[aid] = jnp.asarray(np.asarray(feed[name]), dtype)
        fetch_list = fetch_list or []
        fetch_ids = []
        for f in fetch_list:
            aid = id(f.data) if hasattr(f, "data") else id(f)
            if aid not in program._values:
                # silent alternative: a per-step cache miss + full re-trace
                # (advisor r4) — make the mistake loud instead
                raise ValueError(
                    "Executor.run: a fetch target was not produced by this "
                    "program's build phase (fetch the SAME Tensor objects "
                    "the build created — a freshly-computed tensor gets a "
                    "new id every step and would silently re-trace)")
            fetch_ids.append(aid)
        # feeds/fetches validated: the build phase is over (advisor r4 —
        # eager ops between runs must not grow the program). Freezing only
        # AFTER validation keeps a typo'd first run recoverable.
        program._frozen = True

        if program.train_spec is not None:
            outs = self._run_train(program, env, fetch_ids)
        else:
            outs = self._run_infer(program, env, fetch_ids)
        if return_numpy:
            return [np.asarray(o) for o in outs]
        from ..core.tensor import Tensor

        return [Tensor(o) for o in outs]

    def _run_infer(self, program: Program, env, fetch_ids):
        """Inference replay, jit-compiled and cached per feed shape (same
        specialization contract as the training path)."""
        feed_keys = sorted(env.keys())
        all_params = program.param_tensors()  # args, NOT baked constants:
        #                                       training may update them
        cache_key = (
            tuple((k, tuple(env[k].shape), str(env[k].dtype))
                  for k in feed_keys),
            tuple(fetch_ids), None,
        )
        cache = program.__dict__.setdefault("_train_jit", {})
        jitted = cache.get(cache_key)
        if jitted is None:
            def infer_fn(param_arrays, feed_vals):
                override = {id(p): a for p, a in zip(all_params, param_arrays)}
                e = program._replay(dict(zip(feed_keys, feed_vals)),
                                    param_override=override)
                return tuple(e.get(aid, program._values.get(aid))
                             for aid in fetch_ids)

            jitted = jax.jit(infer_fn)
            cache[cache_key] = jitted
        return list(jitted([p.data for p in all_params],
                           [env[k] for k in feed_keys]))

    def _run_train(self, program: Program, env, fetch_ids):
        """One training iteration: grads via value_and_grad over the replay.
        The replay + autodiff is jax.jit-compiled and CACHED per feed shape
        (the to_static-style specialization the module docstring promises) —
        the hot loop of a static script must not re-trace per step."""
        from ..core.tensor import Tensor

        loss_aid, optimizer = program.train_spec
        params = optimizer._parameter_list or program.parameters()
        train_params = [p for p in params if not p.stop_gradient]
        train_ids = {id(p) for p in train_params}
        frozen_params = [p for p in program.param_tensors()
                         if id(p) not in train_ids]
        feed_keys = sorted(env.keys())
        cache_key = (
            tuple((k, tuple(env[k].shape), str(env[k].dtype))
                  for k in feed_keys),
            tuple(fetch_ids),
            tuple(id(p) for p in train_params),
        )
        cache = program.__dict__.setdefault("_train_jit", {})
        jitted = cache.get(cache_key)
        if jitted is None:
            def train_fn(param_arrays, frozen_arrays, feed_vals):
                base_env = dict(zip(feed_keys, feed_vals))
                frozen_map = {id(p): a
                              for p, a in zip(frozen_params, frozen_arrays)}

                def loss_of(pa):
                    override = dict(frozen_map)
                    override.update({id(p): a
                                     for p, a in zip(train_params, pa)})
                    e = program._replay(dict(base_env),
                                        param_override=override)
                    loss = e[loss_aid].astype(jnp.float32)
                    if loss.ndim:
                        loss = loss.mean()  # reference: mean vector losses
                    fetches = tuple(
                        e.get(aid, program._values.get(aid))
                        for aid in fetch_ids)
                    return loss, fetches

                (loss, fetches), grads = jax.value_and_grad(
                    loss_of, has_aux=True)(tuple(param_arrays))
                return loss, fetches, grads

            jitted = jax.jit(train_fn)
            cache[cache_key] = jitted
        _loss, fetches, grads = jitted(
            tuple(p.data for p in train_params),
            [p.data for p in frozen_params],
            [env[k] for k in feed_keys])
        for p, g in zip(train_params, grads):
            p.grad = Tensor(g.astype(p.dtype))
        optimizer.step()
        optimizer.clear_grad()
        return list(fetches)


def save_inference_model_impl(path_prefix, feed_vars, fetch_vars,
                              program=None):
    """Serialize the recorded Program as a servable artifact (reference
    static/io.py:433 save_inference_model).

    The inference replay fn(param_arrays, *feed_arrays) -> fetches is
    AOT-exported through the SAME StableHLO pipeline as jit.save, so the
    artifact triple (.pdmodel/.pdiparams/.pdmeta) is jit.load- and
    inference.create_predictor-compatible; static-specific keys (feed
    names, fetch count) ride along in the meta for load_inference_model."""
    import json

    from jax import export as jexport

    program = (program if program is not None
               else _DEFAULT["main"]).clone(for_test=True)
    feed_vars = list(feed_vars if isinstance(feed_vars, (list, tuple))
                     else [feed_vars])
    fetch_vars = list(fetch_vars if isinstance(fetch_vars, (list, tuple))
                      else [fetch_vars])
    by_aid = {aid: (name, dtype, shape)
              for name, (aid, dtype, shape) in program.feeds.items()}
    feed_aids, feed_names, arg_structs = [], [], []
    from ..jit import _as_shape_struct
    from .input_spec import InputSpec

    for i, v in enumerate(feed_vars):
        aid = id(v.data) if hasattr(v, "data") else id(v)
        if aid not in by_aid:
            raise ValueError(
                "save_inference_model: every feed_var must be a "
                "static.data placeholder of this program")
        name, dtype, shape = by_aid[aid]
        feed_aids.append(aid)
        feed_names.append(name)
        arg_structs.append(_as_shape_struct(
            InputSpec(shape=list(shape), dtype=dtype), poly_suffix=str(i)))
    fetch_aids = []
    for v in fetch_vars:
        aid = id(v.data) if hasattr(v, "data") else id(v)
        if aid not in program._values:
            raise ValueError(
                "save_inference_model: every fetch_var must be produced by "
                "this program's build phase")
        fetch_aids.append(aid)
    # the fetch cone must be fully covered by feed_vars: a placeholder the
    # cone reads but the artifact doesn't feed would silently bake its
    # build-time dummy zeros into the servable
    needed = set(fetch_aids)
    for node in reversed(program.nodes):
        if any(oid in needed for oid in node.out_ids):
            needed.update(aid for kind, aid in node.inputs
                          if kind == "value")
    for name, (aid, _dt, _sh) in program.feeds.items():
        if aid in needed and aid not in feed_aids:
            raise ValueError(
                f"save_inference_model: fetch depends on placeholder "
                f"'{name}' which is not in feed_vars — the artifact would "
                f"serve its build-time dummy instead")

    params = program.param_tensors()
    param_structs = [jax.ShapeDtypeStruct(tuple(p.data.shape), p.data.dtype)
                     for p in params]

    def run(param_arrays, *feed_arrays):
        override = {id(p): a for p, a in zip(params, param_arrays)}
        env = dict(zip(feed_aids, feed_arrays))
        e = program._replay(env, param_override=override)
        return tuple(e.get(aid, program._values.get(aid))
                     for aid in fetch_aids)

    exp = jexport.export(jax.jit(run), platforms=("cpu", "tpu"))(
        param_structs, *arg_structs)
    keys, seen = [], set()
    for i, p in enumerate(params):
        k = getattr(p, "name", None) or f"static_param_{i}"
        if k in seen:
            k = f"{k}_{i}"
        seen.add(k)
        keys.append(k)
    with open(path_prefix + ".pdmodel", "wb") as f:
        f.write(exp.serialize())
    with open(path_prefix + ".pdiparams", "wb") as f:
        np.savez(f, **{f"p{i}": np.asarray(p.data)
                       for i, p in enumerate(params)})
    with open(path_prefix + ".pdmeta", "w") as f:
        json.dump({"param_keys": keys,
                   "num_inputs": len(arg_structs),
                   "input_specs": [
                       {"shape": [d if isinstance(d, int) else None
                                  for d in s.shape],
                        "dtype": str(s.dtype)} for s in arg_structs],
                   "static": {"feed_names": feed_names,
                              "num_fetch": len(fetch_aids)}}, f)


class _FetchTarget:
    """Opaque fetch handle returned by load_inference_model (plays the
    reference's fetch Variable role for the loaded program)."""

    __slots__ = ("index",)

    def __init__(self, index):
        self.index = index


class LoadedProgram:
    """The 'inference_program' returned by load_inference_model: wraps the
    jit.load'ed AOT executable so the reference idiom

        prog, feed_names, fetch_targets = static.load_inference_model(p, exe)
        exe.run(prog, feed={...}, fetch_list=fetch_targets)

    runs unchanged."""

    def __init__(self, layer, feed_names, num_fetch):
        self._layer = layer
        self.feed_names = list(feed_names)
        self._num_fetch = num_fetch

    def _run(self, feed, fetch_list, return_numpy):
        missing = set(self.feed_names) - set(feed)
        if missing:
            raise ValueError(f"Executor.run: missing feeds {sorted(missing)}")
        outs = self._layer(*[jnp.asarray(np.asarray(feed[n]))
                             for n in self.feed_names])
        outs = outs if isinstance(outs, (list, tuple)) else [outs]
        if fetch_list:
            idx = [f.index if isinstance(f, _FetchTarget) else int(f)
                   for f in fetch_list]
            outs = [outs[i] for i in idx]
        vals = [o.data if hasattr(o, "data") else o for o in outs]
        if return_numpy:
            return [np.asarray(v) for v in vals]
        from ..core.tensor import Tensor

        return [Tensor(v) for v in vals]


def load_inference_model_impl(path_prefix):
    """reference static/io.py load_inference_model: returns
    [inference_program, feed_target_names, fetch_targets]."""
    import json

    from .. import jit as jit_mod

    layer = jit_mod.load(path_prefix)
    with open(path_prefix + ".pdmeta") as f:
        meta = json.load(f)
    st = meta.get("static") or {}
    feed_names = st.get("feed_names",
                        [f"x{i}" for i in range(meta["num_inputs"])])
    num_fetch = st.get("num_fetch", 1)
    prog = LoadedProgram(layer, feed_names, num_fetch)
    return [prog, list(feed_names), [_FetchTarget(i)
                                     for i in range(num_fetch)]]
