"""paddle.reader — composable reader-creator decorators.

Reference: python/paddle/reader/decorator.py (cache :52, map_readers :92,
shuffle :134, chain :183, compose :248, buffered :308, firstn :367,
xmap_readers :412, multiprocess_reader :505). A "reader creator" is a
zero-arg callable returning an iterator of samples — the PS/dataset era's
input pipeline algebra. Thread/process plumbing maps onto the stdlib
(queue + threads) exactly like the reference; multiprocess_reader keeps
fork+pipe semantics via multiprocessing.
"""
from __future__ import annotations

import itertools
import queue as _queue
import random as _random
import threading

__all__ = ["cache", "map_readers", "shuffle", "chain", "compose",
           "buffered", "firstn", "xmap_readers", "multiprocess_reader",
           "ComposeNotAligned"]


class ComposeNotAligned(ValueError):
    pass


def cache(reader):
    """Materialize the first full pass; replay from memory after
    (decorator.py:52)."""
    all_data = []
    filled = [False]

    def creator():
        if not filled[0]:
            all_data.extend(reader())
            filled[0] = True
        return iter(all_data)
    return creator


def map_readers(func, *readers):
    """Zip readers, yield func(*one_of_each) (decorator.py:92)."""
    def creator():
        for items in zip(*[r() for r in readers]):
            yield func(*items)
    return creator


def shuffle(reader, buf_size):
    """Buffered shuffle: fill buf_size samples, emit shuffled, repeat
    (decorator.py:134)."""
    def creator():
        buf = []
        for s in reader():
            buf.append(s)
            if len(buf) >= buf_size:
                _random.shuffle(buf)
                yield from buf
                buf = []
        if buf:
            _random.shuffle(buf)
            yield from buf
    return creator


def chain(*readers):
    """Concatenate readers back-to-back (decorator.py:183)."""
    def creator():
        return itertools.chain(*[r() for r in readers])
    return creator


def compose(*readers, **kwargs):
    """Parallel-compose: one tuple per step, flattening tuple samples;
    check_alignment=True (default) raises ComposeNotAligned when readers
    run out at different lengths (decorator.py:248)."""
    check_alignment = kwargs.pop("check_alignment", True)
    if kwargs:
        raise TypeError(f"compose: unexpected kwargs {sorted(kwargs)}")

    def _flat(item):
        return item if isinstance(item, tuple) else (item,)

    def creator():
        iters = [r() for r in readers]
        if not check_alignment:
            for items in zip(*iters):
                yield sum((_flat(i) for i in items), ())
            return
        sentinel = object()
        for items in itertools.zip_longest(*iters, fillvalue=sentinel):
            if any(i is sentinel for i in items):
                raise ComposeNotAligned(
                    "compose: readers have different lengths")
            yield sum((_flat(i) for i in items), ())
    return creator


def buffered(reader, size):
    """Background thread keeps up to `size` samples ready
    (decorator.py:308). Producer exceptions re-raise in the consumer —
    a failed read must not look like a shorter dataset."""
    end = object()
    fail = object()

    def creator():
        q = _queue.Queue(maxsize=size)

        def fill():
            try:
                for s in reader():
                    q.put(s)
                q.put(end)
            except BaseException as e:  # forward, don't truncate
                q.put((fail, e))

        t = threading.Thread(target=fill, daemon=True,
                             name="pt-reader-fill")
        t.start()
        while True:
            s = q.get()
            if s is end:
                break
            if isinstance(s, tuple) and len(s) == 2 and s[0] is fail:
                raise s[1]
            yield s
    return creator


def firstn(reader, n):
    """First n samples (decorator.py:367)."""
    def creator():
        return itertools.islice(reader(), n)
    return creator


def xmap_readers(mapper, reader, process_num, buffer_size, order=False):
    """Apply `mapper` with `process_num` worker THREADS over the stream
    (decorator.py:412 — the reference's workers are threads too);
    order=True preserves input order."""
    end = object()
    fail = object()

    def creator():
        in_q = _queue.Queue(buffer_size)
        out_q = _queue.Queue(buffer_size)

        def feed():
            try:
                for i, s in enumerate(reader()):
                    in_q.put((i, s))
                for _ in range(process_num):
                    in_q.put(end)
            except BaseException as e:  # source died: wake every worker
                out_q.put((fail, e))
                for _ in range(process_num):
                    in_q.put(end)

        def work():
            while True:
                item = in_q.get()
                if item is end:
                    out_q.put(end)
                    return
                i, s = item
                try:
                    out_q.put((i, mapper(s)))
                except BaseException as e:  # mapper died: surface, exit
                    out_q.put((fail, e))
                    out_q.put(end)
                    return

        threading.Thread(target=feed, daemon=True,
                         name="pt-reader-feed").start()
        for i in range(process_num):
            threading.Thread(target=work, daemon=True,
                             name=f"pt-reader-work-{i}").start()

        def next_item():
            item = out_q.get()
            if isinstance(item, tuple) and len(item) == 2 and \
                    item[0] is fail:
                raise item[1]
            return item

        finished = 0
        if not order:
            while finished < process_num:
                item = next_item()
                if item is end:
                    finished += 1
                    continue
                yield item[1]
            return
        pending = {}
        want = 0
        while finished < process_num or pending:
            if want in pending:
                yield pending.pop(want)
                want += 1
                continue
            item = next_item()
            if item is end:
                finished += 1
                continue
            i, val = item
            pending[i] = val
        while want in pending:
            yield pending.pop(want)
            want += 1
    return creator


def _mp_failure_payload(e):
    """Cross-process failure envelope: the pickled exception INSTANCE (so
    the consumer re-raises the real type and can catch it specifically)
    plus the worker-side traceback text (lost by pickling)."""
    import pickle
    import traceback

    tb = traceback.format_exc()
    try:
        payload = pickle.dumps(e)
        pickle.loads(payload)  # must survive the round trip NOW, not later
    except Exception:
        payload = None  # unpicklable exception: fall back to the repr
    return ("F", payload, f"{type(e).__name__}: {e}", tb)


def _mp_raise(payload, desc, tb):
    """Re-raise a worker failure in the consumer. The original exception
    type propagates when it pickles; the worker traceback rides along as
    the __cause__ so nothing is flattened to a bare string."""
    import pickle

    cause = RuntimeError(
        f"multiprocess_reader worker failed: {desc}\n"
        f"worker traceback:\n{tb}")
    if payload is not None:
        try:
            exc = pickle.loads(payload)
        except Exception:
            raise cause
        raise exc from cause
    raise cause


def multiprocess_reader(readers, use_pipe=True, queue_size=1000):
    """Fan-in several readers from fork'd worker processes
    (decorator.py:505). Workers must only touch fork-safe state (numpy,
    files) — the same contract as the DataLoader workers. Samples ride
    tagged tuples so a None sample is data and a worker crash re-raises
    in the consumer (original exception type when picklable, worker
    traceback text chained as the __cause__) instead of truncating the
    stream.

    ``use_pipe`` selects the transport, like the reference's
    _read_into_pipe/_read_into_queue split: True (default) gives each
    worker its own one-way ``multiprocessing.Pipe`` and the consumer
    fans in via ``connection.wait``; False funnels every worker through
    one bounded ``multiprocessing.Queue(queue_size)``.
    """
    import multiprocessing as mp

    def creator_queue():
        q = mp.Queue(queue_size)

        def work(r):
            try:
                for s in r():
                    q.put(("S", s))
                q.put(("E",))
            except BaseException as e:
                q.put(_mp_failure_payload(e))

        procs = [mp.Process(target=work, args=(r,), daemon=True)
                 for r in readers]
        for p in procs:
            p.start()
        finished = 0
        while finished < len(readers):
            msg = q.get()
            if msg[0] == "E":
                finished += 1
            elif msg[0] == "F":
                _mp_raise(*msg[1:])
            else:
                yield msg[1]
        for p in procs:
            p.join(timeout=5)

    def creator_pipe():
        from multiprocessing.connection import wait

        def work(r, conn):
            try:
                for s in r():
                    conn.send(("S", s))
                conn.send(("E",))
            except BaseException as e:
                try:
                    conn.send(_mp_failure_payload(e))
                except Exception:  # payload itself unsendable
                    conn.send(("F", None, f"{type(e).__name__}: {e}", ""))
            finally:
                conn.close()

        conns, procs, owner = [], [], {}
        for r in readers:
            recv, send = mp.Pipe(duplex=False)
            p = mp.Process(target=work, args=(r, send), daemon=True)
            p.start()
            # close OUR copy of the write end immediately: recv() can then
            # raise EOFError when a worker dies without an envelope
            # (SIGKILL/OOM) instead of blocking forever — and the
            # start-then-next-pipe order keeps later workers from
            # inheriting this pipe's send fd
            send.close()
            procs.append(p)
            conns.append(recv)
            owner[recv] = p
        live = list(conns)
        while live:
            for conn in wait(live):
                try:
                    msg = conn.recv()
                except EOFError:
                    # EOF without the ("E",) envelope = the worker DIED
                    # (SIGKILL/OOM/os._exit): a truncated stream must not
                    # look like a shorter dataset
                    p = owner[conn]
                    p.join(timeout=5)
                    raise RuntimeError(
                        "multiprocess_reader worker died without finishing "
                        f"(exitcode {p.exitcode}); stream would be "
                        "truncated")
                if msg[0] == "E":
                    live.remove(conn)
                elif msg[0] == "F":
                    _mp_raise(*msg[1:])
                else:
                    yield msg[1]
        for p in procs:
            p.join(timeout=5)

    return creator_pipe if use_pipe else creator_queue
