"""paddle.reader — composable reader-creator decorators.

Reference: python/paddle/reader/decorator.py (cache :52, map_readers :92,
shuffle :134, chain :183, compose :248, buffered :308, firstn :367,
xmap_readers :412, multiprocess_reader :505). A "reader creator" is a
zero-arg callable returning an iterator of samples — the PS/dataset era's
input pipeline algebra. Thread/process plumbing maps onto the stdlib
(queue + threads) exactly like the reference; multiprocess_reader keeps
fork+pipe semantics via multiprocessing.
"""
from __future__ import annotations

import itertools
import queue as _queue
import random as _random
import threading

__all__ = ["cache", "map_readers", "shuffle", "chain", "compose",
           "buffered", "firstn", "xmap_readers", "multiprocess_reader",
           "ComposeNotAligned"]


class ComposeNotAligned(ValueError):
    pass


def cache(reader):
    """Materialize the first full pass; replay from memory after
    (decorator.py:52)."""
    all_data = []
    filled = [False]

    def creator():
        if not filled[0]:
            all_data.extend(reader())
            filled[0] = True
        return iter(all_data)
    return creator


def map_readers(func, *readers):
    """Zip readers, yield func(*one_of_each) (decorator.py:92)."""
    def creator():
        for items in zip(*[r() for r in readers]):
            yield func(*items)
    return creator


def shuffle(reader, buf_size):
    """Buffered shuffle: fill buf_size samples, emit shuffled, repeat
    (decorator.py:134)."""
    def creator():
        buf = []
        for s in reader():
            buf.append(s)
            if len(buf) >= buf_size:
                _random.shuffle(buf)
                yield from buf
                buf = []
        if buf:
            _random.shuffle(buf)
            yield from buf
    return creator


def chain(*readers):
    """Concatenate readers back-to-back (decorator.py:183)."""
    def creator():
        return itertools.chain(*[r() for r in readers])
    return creator


def compose(*readers, **kwargs):
    """Parallel-compose: one tuple per step, flattening tuple samples;
    check_alignment=True (default) raises ComposeNotAligned when readers
    run out at different lengths (decorator.py:248)."""
    check_alignment = kwargs.pop("check_alignment", True)
    if kwargs:
        raise TypeError(f"compose: unexpected kwargs {sorted(kwargs)}")

    def _flat(item):
        return item if isinstance(item, tuple) else (item,)

    def creator():
        iters = [r() for r in readers]
        if not check_alignment:
            for items in zip(*iters):
                yield sum((_flat(i) for i in items), ())
            return
        sentinel = object()
        for items in itertools.zip_longest(*iters, fillvalue=sentinel):
            if any(i is sentinel for i in items):
                raise ComposeNotAligned(
                    "compose: readers have different lengths")
            yield sum((_flat(i) for i in items), ())
    return creator


def buffered(reader, size):
    """Background thread keeps up to `size` samples ready
    (decorator.py:308). Producer exceptions re-raise in the consumer —
    a failed read must not look like a shorter dataset."""
    end = object()
    fail = object()

    def creator():
        q = _queue.Queue(maxsize=size)

        def fill():
            try:
                for s in reader():
                    q.put(s)
                q.put(end)
            except BaseException as e:  # forward, don't truncate
                q.put((fail, e))

        t = threading.Thread(target=fill, daemon=True)
        t.start()
        while True:
            s = q.get()
            if s is end:
                break
            if isinstance(s, tuple) and len(s) == 2 and s[0] is fail:
                raise s[1]
            yield s
    return creator


def firstn(reader, n):
    """First n samples (decorator.py:367)."""
    def creator():
        return itertools.islice(reader(), n)
    return creator


def xmap_readers(mapper, reader, process_num, buffer_size, order=False):
    """Apply `mapper` with `process_num` worker THREADS over the stream
    (decorator.py:412 — the reference's workers are threads too);
    order=True preserves input order."""
    end = object()
    fail = object()

    def creator():
        in_q = _queue.Queue(buffer_size)
        out_q = _queue.Queue(buffer_size)

        def feed():
            try:
                for i, s in enumerate(reader()):
                    in_q.put((i, s))
                for _ in range(process_num):
                    in_q.put(end)
            except BaseException as e:  # source died: wake every worker
                out_q.put((fail, e))
                for _ in range(process_num):
                    in_q.put(end)

        def work():
            while True:
                item = in_q.get()
                if item is end:
                    out_q.put(end)
                    return
                i, s = item
                try:
                    out_q.put((i, mapper(s)))
                except BaseException as e:  # mapper died: surface, exit
                    out_q.put((fail, e))
                    out_q.put(end)
                    return

        threading.Thread(target=feed, daemon=True).start()
        for _ in range(process_num):
            threading.Thread(target=work, daemon=True).start()

        def next_item():
            item = out_q.get()
            if isinstance(item, tuple) and len(item) == 2 and \
                    item[0] is fail:
                raise item[1]
            return item

        finished = 0
        if not order:
            while finished < process_num:
                item = next_item()
                if item is end:
                    finished += 1
                    continue
                yield item[1]
            return
        pending = {}
        want = 0
        while finished < process_num or pending:
            if want in pending:
                yield pending.pop(want)
                want += 1
                continue
            item = next_item()
            if item is end:
                finished += 1
                continue
            i, val = item
            pending[i] = val
        while want in pending:
            yield pending.pop(want)
            want += 1
    return creator


def multiprocess_reader(readers, use_pipe=True, queue_size=1000):
    """Fan-in several readers from fork'd worker processes
    (decorator.py:505). Workers must only touch fork-safe state (numpy,
    files) — the same contract as the DataLoader workers. Samples ride
    tagged tuples so a None sample is data and a worker crash raises
    in the consumer instead of truncating the stream."""
    import multiprocessing as mp

    def creator():
        q = mp.Queue(queue_size)

        def work(r):
            try:
                for s in r():
                    q.put(("S", s))
                q.put(("E", None))
            except BaseException as e:  # cross-process: send the repr
                q.put(("F", f"{type(e).__name__}: {e}"))

        procs = [mp.Process(target=work, args=(r,), daemon=True)
                 for r in readers]
        for p in procs:
            p.start()
        finished = 0
        while finished < len(readers):
            tag, val = q.get()
            if tag == "E":
                finished += 1
            elif tag == "F":
                raise RuntimeError(
                    f"multiprocess_reader worker failed: {val}")
            else:
                yield val
        for p in procs:
            p.join(timeout=5)
    return creator
