"""paddle.dataset — the legacy reader-creator facade.

Reference: python/paddle/dataset/ (uci_housing.py, imdb.py, mnist.py, …:
each module exposes train()/test() returning zero-arg reader creators).
TPU-native collapse: every loader adapts the corresponding
paddle_tpu.vision/text Dataset class (file-backed, loud on missing
downloads) into the reader-creator protocol that paddle.reader and the
PS data pipelines compose. Usage:

    train_reader = paddle.reader.shuffle(
        paddle.dataset.uci_housing.train(data_file=...), buf_size=500)
"""
from __future__ import annotations

import sys
import types

from . import common  # noqa: F401

__all__ = ["common"]


def _creator(cls, mode, kwargs):
    def reader():
        import inspect

        if "mode" in inspect.signature(cls.__init__).parameters:
            ds = cls(mode=mode, **kwargs)
        else:  # single-split datasets (Conll05st ships test only)
            ds = cls(**kwargs)
        for i in range(len(ds)):
            yield ds[i]
    return reader


def _module(name, cls_path, modes=("train", "test")):
    """Build a paddle.dataset.<name> module whose train()/test() wrap the
    Dataset class at cls_path ('pkg.mod:Class')."""
    mod = types.ModuleType(f"{__name__}.{name}")
    mod.__doc__ = (f"reader-creator facade over {cls_path} "
                   f"(reference python/paddle/dataset/{name}.py)")

    def _cls():
        path, cname = cls_path.split(":")
        import importlib

        return getattr(importlib.import_module(path), cname)

    def make(mode):
        def fn(**kwargs):
            return _creator(_cls(), mode, kwargs)
        fn.__name__ = mode
        fn.__doc__ = (f"{name}.{mode}(**dataset_kwargs) -> reader creator "
                      f"(pass the Dataset class's data_file=... here)")
        return fn

    for m in modes:
        setattr(mod, m, make(m))
    sys.modules[mod.__name__] = mod
    globals()[name] = mod
    __all__.append(name)
    return mod


_module("uci_housing", "paddle_tpu.text.datasets:UCIHousing")
_module("imdb", "paddle_tpu.text.datasets:Imdb")
_module("imikolov", "paddle_tpu.text.datasets:Imikolov")
_module("movielens", "paddle_tpu.text.datasets:Movielens")
_module("conll05", "paddle_tpu.text.datasets:Conll05st",
        modes=("test",))  # reference ships test split only
_module("wmt14", "paddle_tpu.text.datasets:WMT14")
_module("wmt16", "paddle_tpu.text.datasets:WMT16")
_module("mnist", "paddle_tpu.vision.datasets:MNIST")
_module("flowers", "paddle_tpu.vision.datasets:Flowers")

# cifar keeps the reference's split names: train10/test10 wrap Cifar10,
# train100/test100 wrap Cifar100 (python/paddle/dataset/cifar.py)
_cifar = _module("cifar", "paddle_tpu.vision.datasets:Cifar10", modes=())
for _m, _cls in (("train10", "Cifar10"), ("test10", "Cifar10"),
                 ("train100", "Cifar100"), ("test100", "Cifar100")):
    def _make_cifar(mode_name=_m, cls_name=_cls):
        mode = "train" if mode_name.startswith("train") else "test"

        def fn(**kwargs):
            import importlib

            cls = getattr(importlib.import_module(
                "paddle_tpu.vision.datasets"), cls_name)
            return _creator(cls, mode, kwargs)
        fn.__name__ = mode_name
        return fn
    setattr(_cifar, _m, _make_cifar())
