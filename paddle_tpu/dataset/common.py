"""paddle.dataset.common (reference python/paddle/dataset/common.py):
DATA_HOME resolution, md5 checking, and the split/cluster helpers the
PS-era pipelines used. Downloads are environment-blocked here — loaders
take explicit local files, and `download()` raises the same loud pointer
the vision/text Dataset classes do."""
from __future__ import annotations

import hashlib
import os
import pickle

__all__ = ["DATA_HOME", "md5file", "download", "split",
           "cluster_files_reader"]

DATA_HOME = os.path.expanduser(
    os.environ.get("PADDLE_DATA_HOME", "~/.cache/paddle/dataset"))


def md5file(fname: str) -> str:
    h = hashlib.md5()
    with open(fname, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def download(url: str, module_name: str, md5sum: str, save_name=None) -> str:
    """The reference fetches to DATA_HOME/<module>; this image has no
    egress. If the file is already cached (same layout), use it."""
    name = save_name or url.split("/")[-1]
    path = os.path.join(DATA_HOME, module_name, name)
    if os.path.exists(path) and (not md5sum or md5file(path) == md5sum):
        return path
    raise RuntimeError(
        f"paddle.dataset download is unavailable in this environment; "
        f"place the file at {path} (md5 {md5sum or 'any'}) or pass an "
        f"explicit data_file to the paddle_tpu.vision/text Dataset class")


def split(reader, line_count, suffix="%05d.pickle", dumper=None):
    """Split a reader's samples into pickled chunk files of `line_count`
    (reference common.py split role for cluster training)."""
    dumper = dumper or (lambda obj, f: pickle.dump(obj, f))
    buf, idx, out = [], 0, []
    if "%" not in suffix:
        raise ValueError("split: suffix must contain a %d-style placeholder")
    for sample in reader():
        buf.append(sample)
        if len(buf) == line_count:
            path = suffix % idx
            with open(path, "wb") as f:
                dumper(buf, f)
            out.append(path)
            buf, idx = [], idx + 1
    if buf:
        path = suffix % idx
        with open(path, "wb") as f:
            dumper(buf, f)
        out.append(path)
    return out


def cluster_files_reader(files_pattern, trainer_count, trainer_id,
                         loader=None):
    """Reader creator over this trainer's shard of chunk files
    (round-robin by index, reference common.py)."""
    import glob

    loader = loader or (lambda f: pickle.load(f))

    def creator():
        files = sorted(glob.glob(files_pattern))
        for i, path in enumerate(files):
            if i % trainer_count != trainer_id:
                continue
            with open(path, "rb") as f:
                for sample in loader(f):
                    yield sample
    return creator
