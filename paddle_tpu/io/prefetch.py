"""Async device prefetch: overlap host→device transfer with device compute.

Reference role: fluid's ``py_reader``/``DataLoader`` double buffering — the
async executor consumes batch N while the reader pushes batch N+1 into a
device-side queue. The TPU-native translation: a background thread calls
``jax.device_put`` on the NEXT batch while the current compiled step runs,
so the step never blocks on PCIe/host transfer. ``device_put`` is async
under jax (it returns immediately with the transfer in flight), which is
exactly what makes a one-thread double buffer sufficient.

Sharding-aware: pass ``sharding=`` a ``jax.sharding.Sharding`` (every leaf
lands there — the ``ShardedTrainStep`` batch layout), a callable
``leaf -> sharding | None`` for per-leaf placement, or nothing for a plain
committed transfer to the default device (the ``jit.TrainStep`` case).

::

    loader = io.DataLoader(ds, batch_size=32, prefetch_to_device=True)
    for x, y in loader: ...                       # already device-resident

    pf = io.DevicePrefetcher(loader, sharding=step.batch_sharding)
    for x, y in pf: ...                           # NamedSharding placement
"""
from __future__ import annotations

import queue
import threading
import time
import weakref
from typing import Any, Callable, Iterable, Optional, Union

__all__ = ["DevicePrefetcher", "prefetch_to_device"]

_FAM = None  # lazily-bound observability family (keeps import light)


def _fam():
    global _FAM
    if _FAM is None:
        from ..observability import family

        _FAM = family("prefetcher", ("metric",))
    return _FAM


def _resolve_sharding(sharding, leaf):
    if sharding is None:
        return None
    if callable(sharding) and not hasattr(sharding, "device_set"):
        return sharding(leaf)
    return sharding


def _put_tree(batch, sharding):
    """device_put every array leaf of a batch (Tensor-aware), committed.

    The transfers this enqueues are asynchronous; returning the tree does
    not wait for them — the consumer's compiled step does, by which time
    they have been overlapping its predecessor."""
    import jax
    import numpy as np

    from ..core.tensor import Tensor

    def put(leaf):
        if isinstance(leaf, Tensor):
            return Tensor(put(leaf.data))
        if isinstance(leaf, (jax.Array, np.ndarray)):
            sh = _resolve_sharding(sharding, leaf)
            return jax.device_put(leaf, sh) if sh is not None \
                else jax.device_put(leaf)
        return leaf

    return jax.tree_util.tree_map(
        put, batch, is_leaf=lambda t: isinstance(t, Tensor))


class DevicePrefetcher:
    """Double-buffered device feeder over any batch iterable.

    Re-iterable: each ``iter()`` starts a fresh background thread that
    pulls from the source, ``device_put``s the batch (sharding-aware) and
    parks up to ``depth`` device-resident batches in a bounded queue.
    Exceptions from the source surface at the consumer's ``next()``;
    abandoning the iterator mid-epoch (break / GC / ``close()``) stops the
    thread — the worker holds no reference to the run object, so dropping
    the iterator is enough. Sized only when the source is sized:
    ``len()`` exists exactly when ``len(source)`` does, keeping
    ``hasattr(__len__)`` probes (hapi's step counting) honest.
    """

    def __new__(cls, source: Iterable, *args, **kwargs):
        if cls is DevicePrefetcher and hasattr(type(source), "__len__"):
            return super().__new__(_SizedDevicePrefetcher)
        return super().__new__(cls)

    def __init__(self, source: Iterable,
                 sharding: Optional[Union[Any, Callable]] = None,
                 depth: int = 2):
        if depth < 1:
            raise ValueError("DevicePrefetcher: depth must be >= 1")
        self.source = source
        self.sharding = sharding
        self.depth = int(depth)

    def __iter__(self):
        return _PrefetchRun(iter(self.source), self.sharding, self.depth)


class _SizedDevicePrefetcher(DevicePrefetcher):
    def __len__(self):
        return len(self.source)  # type: ignore[arg-type]


class _PrefetchRun:
    _SENTINEL = object()

    def __init__(self, src, sharding, depth):
        # the worker closes over these LOCALS, never over self: when the
        # consumer drops the iterator, refcounting collects the run,
        # __del__ sets stop, and the thread exits on its next 0.2s tick
        q: "queue.Queue" = queue.Queue(maxsize=depth)
        stop = threading.Event()
        err_box = [None]
        sentinel = self._SENTINEL

        def worker():
            try:
                for batch in src:
                    put = _put_tree(batch, sharding)
                    while not stop.is_set():
                        try:
                            q.put(put, timeout=0.2)
                            break
                        except queue.Full:
                            continue
                    if stop.is_set():
                        return
            except BaseException as e:  # surface at the consumer
                err_box[0] = e
            finally:
                while not stop.is_set():
                    try:
                        q.put(sentinel, timeout=0.2)
                        break
                    except queue.Full:
                        continue

        self._q = q
        self._stop = stop
        self._err_box = err_box
        self._done = False
        self._thread = threading.Thread(target=worker, daemon=True,
                                        name="pt-device-prefetch")
        self._thread.start()
        try:
            # live queue-depth gauge for the most recent active run (weak:
            # an abandoned run reads 0, never pins the iterator alive)
            from ..observability import gauge

            ref = weakref.ref(self)
            gauge("prefetch_queue_depth",
                  lambda: (lambda r: r._q.qsize() if r is not None else 0)(
                      ref()))
        except Exception:
            pass

    def __iter__(self):
        return self

    def __next__(self):
        if self._done:  # exhausted iterators must KEEP raising, not block
            raise StopIteration
        t0 = time.perf_counter()
        item = self._q.get()
        if item is self._SENTINEL:
            self._done = True
            self._stop.set()
            if self._err_box[0] is not None:
                raise self._err_box[0]
            raise StopIteration
        # occupancy telemetry: how long the consumer stalled on this batch
        # and how deep the device-side queue ran (avg = depth_sum/batches)
        fam = _fam()
        fam.inc(("data_wait_ms",), (time.perf_counter() - t0) * 1e3)
        fam.inc(("batches",))
        fam.inc(("queue_depth_sum",), self._q.qsize())
        return item

    def close(self):
        """Abandon the run: stop the producer thread promptly."""
        self._done = True
        self._stop.set()
        try:  # unblock a producer parked on a full queue
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass

    def __del__(self):  # pragma: no cover - GC path
        try:
            self._stop.set()
        except Exception:
            pass


def prefetch_to_device(iterable: Iterable, sharding=None, depth: int = 2
                       ) -> DevicePrefetcher:
    """Functional spelling of ``DevicePrefetcher`` (flax's
    ``prefetch_to_device`` shape, Tensor-aware)."""
    return DevicePrefetcher(iterable, sharding=sharding, depth=depth)
