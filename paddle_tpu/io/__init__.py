"""paddle_tpu.io: datasets + DataLoader.

Reference: python/paddle/io/ + fluid/dataloader/ (multiprocess workers feeding
a LoDTensorBlockingQueue). TPU-native redesign: the loader is a host-side numpy
pipeline with a background-thread prefetcher that overlaps batch assembly with
device compute (device transfer is async under jax); multiprocess workers are
unnecessary because TPU input pipelines are host-CPU bound on decode, which
numpy/threads handle, and the heavy lifting (augment) vectorizes.
"""
from __future__ import annotations

import bisect
import itertools
import queue
import threading
from typing import Any, Iterable, Iterator, List, Optional, Sequence

import numpy as np

from ..core.tensor import Tensor
from ..framework import random as random_mod
from .prefetch import DevicePrefetcher, prefetch_to_device


class Dataset:
    """Map-style dataset (reference: python/paddle/io/Dataset)."""

    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset is not subscriptable")

    def __len__(self):
        raise RuntimeError("IterableDataset has no len()")


class TensorDataset(Dataset):
    def __init__(self, tensors: Sequence[Tensor]):
        lens = {t.shape[0] for t in tensors}
        assert len(lens) == 1, "all tensors must share dim 0"
        self.tensors = tensors

    def __getitem__(self, idx):
        return tuple(np.asarray(t.data[idx]) for t in self.tensors)

    def __len__(self):
        return self.tensors[0].shape[0]


class ComposeDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        assert all(len(d) == len(self.datasets[0]) for d in self.datasets)

    def __getitem__(self, idx):
        out = []
        for d in self.datasets:
            sample = d[idx]
            out.extend(sample if isinstance(sample, (tuple, list)) else [sample])
        return tuple(out)

    def __len__(self):
        return len(self.datasets[0])


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        for d in self.datasets:
            yield from d


class ConcatDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        self.cumulative_sizes = list(itertools.accumulate(len(d) for d in self.datasets))

    def __len__(self):
        return self.cumulative_sizes[-1]

    def __getitem__(self, idx):
        if idx < 0:
            idx += len(self)
        ds_idx = bisect.bisect_right(self.cumulative_sizes, idx)
        prev = 0 if ds_idx == 0 else self.cumulative_sizes[ds_idx - 1]
        return self.datasets[ds_idx][idx - prev]


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    total = len(dataset)
    if all(isinstance(l, float) for l in lengths):
        lengths = [int(total * l) for l in lengths]
        lengths[-1] = total - sum(lengths[:-1])
    assert sum(lengths) == total
    g = generator or random_mod.default_generator()
    perm = np.asarray(
        __import__("jax").random.permutation(g.next_key(), total)
    ).tolist()
    out, off = [], 0
    for l in lengths:
        out.append(Subset(dataset, perm[off : off + l]))
        off += l
    return out


# -- samplers ----------------------------------------------------------------

class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        return len(self.data_source)


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None, generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num_samples = num_samples
        self.generator = generator

    @property
    def num_samples(self):
        return self._num_samples or len(self.data_source)

    def __iter__(self):
        n = len(self.data_source)
        g = self.generator or random_mod.default_generator()
        import jax

        if self.replacement:
            idx = np.asarray(jax.random.randint(g.next_key(), (self.num_samples,), 0, n))
        else:
            idx = np.asarray(jax.random.permutation(g.next_key(), n))[: self.num_samples]
        return iter(idx.tolist())

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    """Reference: python/paddle/fluid/dataloader/batch_sampler.py."""

    def __init__(self, dataset=None, sampler=None, shuffle=False, batch_size=1,
                 drop_last=False):
        assert (dataset is None) != (sampler is None), "exactly one of dataset/sampler"
        if sampler is None:
            sampler = RandomSampler(dataset) if shuffle else SequenceSampler(dataset)
        self.sampler = sampler
        self.batch_size = int(batch_size)
        self.drop_last = drop_last

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """Rank-sharded batches (reference: dataloader/batch_sampler.py
    DistributedBatchSampler). Under SPMD data parallel the 'rank' is a
    data-mesh coordinate; see paddle_tpu.distributed."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        self.dataset = dataset
        self.batch_size = int(batch_size)
        self.shuffle = shuffle
        self.drop_last = drop_last
        if num_replicas is None or rank is None:
            from .. import distributed as dist

            num_replicas = num_replicas if num_replicas is not None else dist.get_world_size()
            rank = rank if rank is not None else dist.get_rank()
        self.nranks = num_replicas
        self.local_rank = rank
        self.epoch = 0
        self.num_samples = (len(dataset) + self.nranks - 1) // self.nranks
        self.total_size = self.num_samples * self.nranks

    def __iter__(self):
        n = len(self.dataset)
        if self.shuffle:
            import jax

            key = jax.random.key(self.epoch)
            indices = np.asarray(jax.random.permutation(key, n)).tolist()
        else:
            indices = list(range(n))
        indices += indices[: self.total_size - n]  # pad to even
        local = indices[self.local_rank : self.total_size : self.nranks]
        batch = []
        for idx in local:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size

    def set_epoch(self, epoch):
        self.epoch = epoch


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        self.weights = np.asarray(weights, dtype=np.float64)
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        idx = np.random.choice(len(p), self.num_samples, replace=self.replacement, p=p)
        return iter(idx.tolist())

    def __len__(self):
        return self.num_samples


# -- collate + loader --------------------------------------------------------

def default_collate_fn(batch: List[Any]):
    sample = batch[0]
    if isinstance(sample, (np.ndarray, np.generic)):
        return Tensor(np.stack(batch))
    if isinstance(sample, Tensor):
        import jax.numpy as jnp

        return Tensor(jnp.stack([t.data for t in batch]))
    if isinstance(sample, (int, float)):
        return Tensor(np.asarray(batch))
    if isinstance(sample, (list, tuple)):
        return tuple(default_collate_fn([b[i] for b in batch]) for i in range(len(sample)))
    if isinstance(sample, dict):
        return {k: default_collate_fn([b[k] for b in batch]) for k in sample}
    return Tensor(np.asarray(batch))


class DataLoader:
    """Reference: python/paddle/fluid/reader.py:146 DataLoader. Host pipeline +
    background-thread prefetch (the py_reader double-buffering role)."""

    def __init__(self, dataset, feed_list=None, places=None, return_list=True,
                 batch_sampler=None, batch_size=1, shuffle=False, drop_last=False,
                 collate_fn=None, num_workers=0, use_buffer_reader=True,
                 prefetch_factor=2, use_shared_memory=True, timeout=0,
                 worker_init_fn=None, persistent_workers=False,
                 prefetch_to_device=False, device_sharding=None):
        self.dataset = dataset
        # async device prefetch (io.prefetch): overlap the NEXT batch's
        # host->device transfer with the current step's compute.
        # device_sharding: a Sharding or leaf->sharding callable for
        # ShardedTrainStep batch layouts; None = plain committed transfer.
        self.prefetch_to_device = bool(prefetch_to_device)
        self.device_sharding = device_sharding
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.worker_init_fn = worker_init_fn
        self.use_shared_memory = use_shared_memory
        self.prefetch = max(2, prefetch_factor) if use_buffer_reader else 0
        self._iterable_mode = isinstance(dataset, IterableDataset)
        if self._iterable_mode:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            self.batch_sampler = BatchSampler(
                dataset=dataset, shuffle=shuffle, batch_size=batch_size,
                drop_last=drop_last)

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("IterableDataset DataLoader has no len()")
        return len(self.batch_sampler)

    def _batches(self) -> Iterator[Any]:
        if self._iterable_mode:
            batch = []
            for sample in self.dataset:
                batch.append(sample)
                if len(batch) == self.batch_size:
                    yield self.collate_fn(batch)
                    batch = []
            if batch and not self.drop_last:
                yield self.collate_fn(batch)
        else:
            for idx_batch in self.batch_sampler:
                yield self.collate_fn([self.dataset[i] for i in idx_batch])

    def __iter__(self):
        if self.prefetch_to_device:
            yield from DevicePrefetcher(self._host_iter(),
                                        sharding=self.device_sharding)
            return
        yield from self._host_iter()

    def _host_iter(self):
        if self.num_workers > 0 and not self._iterable_mode:
            yield from _MultiprocessIterator(self)
            return
        if self.prefetch == 0 and self.num_workers == 0:
            yield from self._batches()
            return
        yield from _PrefetchIterator(self._batches(), self.prefetch or 2)


class _PrefetchIterator:
    """Background-thread double buffering (py_reader analogue)."""

    _SENTINEL = object()

    def __init__(self, source, depth):
        self.q: "queue.Queue" = queue.Queue(maxsize=depth)
        self.err = None

        def worker():
            try:
                for item in source:
                    self.q.put(item)
            except BaseException as e:  # propagate to consumer
                self.err = e
            finally:
                self.q.put(self._SENTINEL)

        self.t = threading.Thread(target=worker, daemon=True,
                                  name="pt-io-prefetch")
        self.t.start()

    def __iter__(self):
        return self

    def __next__(self):
        item = self.q.get()
        if item is self._SENTINEL:
            if self.err is not None:
                raise self.err
            raise StopIteration
        return item


class WorkerInfo:
    def __init__(self, id, num_workers, seed, dataset):
        self.id = id
        self.num_workers = num_workers
        self.seed = seed
        self.dataset = dataset


_WORKER_INFO = None


def get_worker_info():
    """Inside a worker process: (id, num_workers, seed, dataset); else None.
    Reference: fluid/dataloader/worker.py get_worker_info."""
    return _WORKER_INFO


def _worker_loop(dataset, index_queue, result_queue, worker_id,
                 num_workers, base_seed, worker_init_fn, use_shared_memory):
    """Worker process body (reference: fluid/dataloader/dataloader_iter.py
    _worker_loop). Fetches samples by index and returns the raw sample lists —
    collation into Tensors happens in the parent so jax (and device transfer)
    stays off the forked workers entirely. With use_shared_memory, large
    ndarrays travel as POSIX shm descriptors instead of pickled pipe bytes
    (the reference's shared-memory LoDTensor handoff, dataloader/flat.py)."""
    global _WORKER_INFO
    _WORKER_INFO = WorkerInfo(worker_id, num_workers, base_seed + worker_id,
                              dataset)
    np.random.seed((base_seed + worker_id) % (2 ** 31))
    if worker_init_fn is not None:
        worker_init_fn(worker_id)
    if use_shared_memory:
        from ..incubate.multiprocessing import (release_sample_tree,
                                                share_sample_tree)
    while True:
        task = index_queue.get()
        if task is None:
            break
        batch_id, indices = task
        shared = []
        try:
            samples = [dataset[i] for i in indices]
            if use_shared_memory:
                for s in samples:  # collected so a mid-batch failure can free
                    shared.append(share_sample_tree(s))
                samples = shared
            result_queue.put((batch_id, samples, None))
        except Exception as e:  # propagate to parent
            if use_shared_memory:
                for s in shared:  # don't leak segments from earlier samples
                    try:
                        release_sample_tree(s)
                    except Exception:
                        pass
            result_queue.put((batch_id, None, e))


class _MultiprocessIterator:
    """Ordered multi-worker fetch (the reference's _DataLoaderIterMultiProcess,
    fluid/dataloader/dataloader_iter.py). Index batches are dealt round-robin
    to worker processes; results are reordered by batch id so output order
    matches the sampler regardless of worker timing."""

    def __init__(self, loader: "DataLoader"):
        import multiprocessing as mp

        self.loader = loader
        ctx = mp.get_context("fork")
        self.num_workers = loader.num_workers
        self.index_queues = [ctx.Queue() for _ in range(self.num_workers)]
        self.result_queue = ctx.Queue()
        base_seed = int(np.random.randint(0, 2 ** 31 - 1))
        self.workers = []
        for wid in range(self.num_workers):
            w = ctx.Process(
                target=_worker_loop,
                args=(loader.dataset, self.index_queues[wid], self.result_queue,
                      wid, self.num_workers, base_seed,
                      getattr(loader, "worker_init_fn", None),
                      getattr(loader, "use_shared_memory", False)),
                daemon=True)
            w.start()
            self.workers.append(w)
        self.batches = list(loader.batch_sampler)
        self.depth = max(2, loader.prefetch or 2) * self.num_workers
        self.next_dispatch = 0
        self.next_yield = 0
        self.cache = {}
        for _ in range(min(self.depth, len(self.batches))):
            self._dispatch()

    def _dispatch(self):
        bid = self.next_dispatch
        if bid >= len(self.batches):
            return
        self.index_queues[bid % self.num_workers].put((bid, self.batches[bid]))
        self.next_dispatch += 1

    def __iter__(self):
        return self

    def __next__(self):
        if self.next_yield >= len(self.batches):
            self._shutdown()
            raise StopIteration
        while self.next_yield not in self.cache:
            try:
                bid, samples, err = self.result_queue.get(timeout=5.0)
            except queue.Empty:
                dead = [w.pid for w in self.workers if not w.is_alive()]
                if dead:
                    self._shutdown()
                    raise RuntimeError(
                        f"DataLoader worker(s) {dead} exited unexpectedly "
                        f"(killed or crashed); check the dataset __getitem__ "
                        f"or reduce num_workers")
                continue
            if err is not None:
                self._shutdown()
                raise err
            self.cache[bid] = samples
        samples = self.cache.pop(self.next_yield)
        self.next_yield += 1
        self._dispatch()
        if getattr(self.loader, "use_shared_memory", False):
            from ..incubate.multiprocessing import restore_sample_tree

            samples = [restore_sample_tree(s) for s in samples]
        return self.loader.collate_fn(samples)

    def _shutdown(self):
        for q in self.index_queues:
            try:
                q.put(None)
            except Exception:
                pass
        for w in self.workers:
            w.join(timeout=5)
            if w.is_alive():
                w.terminate()
        self.workers = []
        if getattr(self.loader, "use_shared_memory", False):
            # free undelivered shm segments (early-exit / error paths): both
            # the reorder cache AND whatever is still in the result queue
            import queue as _q

            from ..incubate.multiprocessing import release_sample_tree

            for samples in self.cache.values():
                if samples:
                    release_sample_tree(samples)
            self.cache = {}
            while True:
                try:
                    _, samples, _err = self.result_queue.get_nowait()
                except (_q.Empty, OSError, ValueError):
                    break
                if samples:
                    release_sample_tree(samples)

    def __del__(self):  # pragma: no cover - GC path
        try:
            if self.workers:
                self._shutdown()
        except Exception:
            pass
