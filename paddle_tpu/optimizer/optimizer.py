"""Optimizers.

Reference surface: python/paddle/optimizer/optimizer.py:50 + the per-op CUDA
kernels in paddle/fluid/operators/optimizers/. TPU-native redesign: each
optimizer defines a *pure functional* update rule; Optimizer.step() applies it
to ALL parameters in one fused jitted call over the whole parameter pytree
(one XLA executable per step instead of one kernel launch per param — the
multi_tensor/fused-optimizer trick the reference implements by hand in
distributed_fused_lamb, for free from XLA).

The functional core (``_rule``) is also the export used by the compiled
train-step path (paddle_tpu.jit.compile_train_step) and ZeRO sharding.
"""
from __future__ import annotations

import functools
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..nn.layer.layers import Parameter
from .lr import LRScheduler


class Optimizer:
    _hyper_defaults: Dict[str, float] = {}

    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, name=None, **kwargs):
        if parameters is None:
            # static-graph scripts construct optimizers parameter-less and
            # let minimize(loss) collect the program's parameters (the
            # reference's static Optimizer contract); dygraph still requires
            # an explicit list at step() time
            from ..static import compat as _static

            if not _static.in_static_mode():
                raise ValueError(
                    "parameters must be provided (dygraph-style optimizer); "
                    "parameter-less construction is only valid under "
                    "paddle.enable_static() where minimize() collects them")
            parameters = []
        self._parameter_list = list(parameters)
        self._learning_rate = learning_rate
        self._grad_clip = grad_clip
        self._weight_decay = _wd_value(weight_decay)
        self._decoupled = False  # AdamW overrides
        self._decay_param_fn = None  # AdamW apply_decay_param_fun / Lamb exclude fn
        self._accumulators: Dict[int, Any] = {}
        self._global_step = 0
        self._jit_step_cache = {}

    # -- lr ------------------------------------------------------------------
    def get_lr(self) -> float:
        if isinstance(self._learning_rate, LRScheduler):
            return float(self._learning_rate.get_lr())
        return float(self._learning_rate)

    def set_lr(self, value: float):
        if isinstance(self._learning_rate, LRScheduler):
            raise RuntimeError("set_lr cannot override an LRScheduler")
        self._learning_rate = float(value)

    # -- functional rule (override) -----------------------------------------
    def _init_state(self, p: jax.Array) -> Dict[str, jax.Array]:
        return {}

    @staticmethod
    def _rule(p, g, state, lr, step, hyper):
        """Pure update: returns (new_p, new_state)."""
        raise NotImplementedError

    def _hyper(self) -> Dict[str, float]:
        return dict(self._hyper_defaults)

    # -- step ----------------------------------------------------------------
    def step(self):
        if not getattr(self, "_stack_checked", False):
            self._stack_checked = True
            from ..nn.layer.layers import check_not_stacked

            check_not_stacked(self._parameter_list)
        params = [p for p in self._parameter_list if not p.stop_gradient and p.grad is not None]
        if not params:
            self._finish_step()
            return
        for p in params:
            if id(p) not in self._accumulators:
                self._accumulators[id(p)] = self._init_state(p.data)
        p_arrs = [p.data for p in params]
        g_arrs = [p.grad.data for p in params]
        states = [self._accumulators[id(p)] for p in params]
        lr = jnp.asarray(self.get_lr(), jnp.float32)
        step_no = jnp.asarray(self._global_step + 1, jnp.int32)

        wd_flags = tuple(
            1.0 if (self._decay_param_fn is None or self._decay_param_fn(p)) else 0.0
            for p in params
        )
        fused = self._get_fused(len(params), tuple(self._clip_key()), wd_flags)
        new_ps, new_states = fused(p_arrs, g_arrs, states, lr, step_no)
        for p, np_, ns in zip(params, new_ps, new_states):
            p.data = np_
            self._accumulators[id(p)] = ns
        self._finish_step()

    def _finish_step(self):
        self._global_step += 1

    def _clip_key(self):
        c = self._grad_clip
        return (type(c).__name__, getattr(c, "clip_norm", None),
                getattr(c, "min", None), getattr(c, "max", None)) if c is not None else ("none",)

    def _get_fused(self, n, clip_key, wd_flags):
        key = (n, clip_key, wd_flags)
        f = self._jit_step_cache.get(key)
        if f is None:
            rule = type(self)._rule
            hyper = self._hyper()
            wd = self._weight_decay
            decoupled = self._decoupled
            clip = self._grad_clip

            def fused(p_arrs, g_arrs, states, lr, step_no):
                if clip is not None:
                    g_arrs = clip._apply_jax(g_arrs)
                out_p, out_s = [], []
                for p, g, s, flag in zip(p_arrs, g_arrs, states, wd_flags):
                    g = g.astype(p.dtype)
                    if wd and not decoupled and flag:
                        g = g + wd * p
                    hyper_i = hyper
                    if "wd" in hyper and not flag:
                        hyper_i = dict(hyper, wd=0.0)  # rule-internal decay (Lamb)
                    np_, ns = rule(p, g, s, lr, step_no, hyper_i)
                    if wd and decoupled and flag:
                        np_ = np_ - (lr * wd * p).astype(p.dtype)
                    out_p.append(np_)
                    out_s.append(ns)
                return out_p, out_s

            f = jax.jit(fused)
            self._jit_step_cache[key] = f
        return f

    # -- misc API ------------------------------------------------------------
    def clear_grad(self, set_to_zero=False):
        for p in self._parameter_list:
            p.clear_grad()

    clear_gradients = clear_grad

    def minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
        from ..static import compat as _static

        if _static.in_static_mode():
            # static shim: mark the default program as a training program
            # (the reference's append_backward + optimizer-ops role); the
            # Executor then runs value_and_grad + this optimizer's update
            _static.default_main_program().set_train(loss, self)
            return None, None
        self.step()
        return None, None

    def state_dict(self):
        sd = {"global_step": int(self._global_step)}
        if isinstance(self._learning_rate, LRScheduler):
            sd["LR_Scheduler"] = self._learning_rate.state_dict()
        for i, p in enumerate(self._parameter_list):
            acc = self._accumulators.get(id(p))
            if acc:
                for k, v in acc.items():
                    sd[f"{p.name}_{k}"] = Tensor(v)
        return sd

    def set_state_dict(self, state_dict):
        # signal compiled steps holding in-graph state (ShardedTrainStep AMP/
        # accumulation path) to re-seed from the restored host values
        self._state_version = getattr(self, "_state_version", 0) + 1
        self._global_step = int(state_dict.get("global_step", 0))
        if isinstance(self._learning_rate, LRScheduler) and "LR_Scheduler" in state_dict:
            self._learning_rate.set_state_dict(state_dict["LR_Scheduler"])
        for p in self._parameter_list:
            acc = {}
            proto = self._init_state(p.data)
            for k in proto:
                key = f"{p.name}_{k}"
                if key in state_dict:
                    v = state_dict[key]
                    acc[k] = v.data if isinstance(v, Tensor) else jnp.asarray(v)
                else:
                    acc[k] = proto[k]
            if acc:
                self._accumulators[id(p)] = acc

    @property
    def _param_groups(self):
        return self._parameter_list


def _wd_value(weight_decay):
    if weight_decay is None:
        return 0.0
    if hasattr(weight_decay, "_coeff"):  # regularizer.L2Decay
        return float(weight_decay._coeff)
    return float(weight_decay)


class SGD(Optimizer):
    @staticmethod
    def _rule(p, g, state, lr, step, hyper):
        return (p - lr.astype(p.dtype) * g), state


class Momentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None, name=None, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._hyper_defaults = {"momentum": float(momentum), "nesterov": float(use_nesterov)}

    def _init_state(self, p):
        return {"velocity": jnp.zeros_like(p)}

    @staticmethod
    def _rule(p, g, state, lr, step, hyper):
        mu = hyper["momentum"]
        v = mu * state["velocity"] + g
        if hyper["nesterov"]:
            update = g + mu * v
        else:
            update = v
        return p - lr.astype(p.dtype) * update, {"velocity": v}


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None, initial_accumulator_value=0.0, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._hyper_defaults = {"eps": float(epsilon), "init": float(initial_accumulator_value)}

    def _init_state(self, p):
        return {"moment": jnp.full_like(p, self._hyper_defaults["init"])}

    @staticmethod
    def _rule(p, g, state, lr, step, hyper):
        m = state["moment"] + g * g
        return p - lr.astype(p.dtype) * g / (jnp.sqrt(m) + hyper["eps"]), {"moment": m}


class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=None, grad_clip=None, lazy_mode=False,
                 multi_precision=False, name=None, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._hyper_defaults = {"beta1": float(beta1), "beta2": float(beta2),
                                "eps": float(epsilon)}

    def _init_state(self, p):
        return {"moment1": jnp.zeros_like(p), "moment2": jnp.zeros_like(p)}

    @staticmethod
    def _rule(p, g, state, lr, step, hyper):
        b1, b2, eps = hyper["beta1"], hyper["beta2"], hyper["eps"]
        gf = g.astype(jnp.float32)
        m = b1 * state["moment1"].astype(jnp.float32) + (1 - b1) * gf
        v = b2 * state["moment2"].astype(jnp.float32) + (1 - b2) * gf * gf
        t = step.astype(jnp.float32)
        mhat = m / (1 - jnp.power(b1, t))
        vhat = v / (1 - jnp.power(b2, t))
        upd = lr * mhat / (jnp.sqrt(vhat) + eps)
        return (p.astype(jnp.float32) - upd).astype(p.dtype), {
            "moment1": m.astype(state["moment1"].dtype),
            "moment2": v.astype(state["moment2"].dtype)}


class AdamW(Adam):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=0.01, lr_ratio=None, apply_decay_param_fun=None,
                 grad_clip=None, lazy_mode=False, multi_precision=False, name=None, **kw):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         weight_decay, grad_clip)
        self._decoupled = True
        if apply_decay_param_fun is not None:
            # paddle contract: fn(param.name) -> True means "apply decay"
            self._decay_param_fn = lambda p: apply_decay_param_fun(p.name)


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._hyper_defaults = {"beta1": float(beta1), "beta2": float(beta2), "eps": float(epsilon)}

    def _init_state(self, p):
        return {"moment": jnp.zeros_like(p), "inf_norm": jnp.zeros_like(p)}

    @staticmethod
    def _rule(p, g, state, lr, step, hyper):
        b1, b2, eps = hyper["beta1"], hyper["beta2"], hyper["eps"]
        m = b1 * state["moment"] + (1 - b1) * g
        u = jnp.maximum(b2 * state["inf_norm"], jnp.abs(g))
        t = step.astype(jnp.float32)
        lr_t = (lr / (1 - jnp.power(b1, t))).astype(p.dtype)
        return p - lr_t * m / (u + eps), {"moment": m, "inf_norm": u}


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._hyper_defaults = {"rho": float(rho), "eps": float(epsilon),
                                "momentum": float(momentum), "centered": float(centered)}

    def _init_state(self, p):
        return {"mean_square": jnp.zeros_like(p), "mean_grad": jnp.zeros_like(p),
                "velocity": jnp.zeros_like(p)}

    @staticmethod
    def _rule(p, g, state, lr, step, hyper):
        rho, eps, mu = hyper["rho"], hyper["eps"], hyper["momentum"]
        ms = rho * state["mean_square"] + (1 - rho) * g * g
        if hyper["centered"]:
            mg = rho * state["mean_grad"] + (1 - rho) * g
            denom = jnp.sqrt(ms - mg * mg + eps)
        else:
            mg = state["mean_grad"]
            denom = jnp.sqrt(ms + eps)
        v = mu * state["velocity"] + lr.astype(p.dtype) * g / denom
        return p - v, {"mean_square": ms, "mean_grad": mg, "velocity": v}


class Lamb(Optimizer):
    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9,
                 beta2=0.999, epsilon=1e-6, parameters=None, grad_clip=None,
                 exclude_from_weight_decay_fn=None, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip)
        # decay is folded into the trust-ratio rule (hyper["wd"]), not the base path
        self._hyper_defaults = {"beta1": float(beta1), "beta2": float(beta2),
                                "eps": float(epsilon), "wd": float(lamb_weight_decay)}
        if exclude_from_weight_decay_fn is not None:
            # paddle contract: fn(param) -> True means "exclude from decay"
            self._decay_param_fn = lambda p: not exclude_from_weight_decay_fn(p)

    def _init_state(self, p):
        return {"moment1": jnp.zeros_like(p), "moment2": jnp.zeros_like(p)}

    @staticmethod
    def _rule(p, g, state, lr, step, hyper):
        b1, b2, eps, wd = hyper["beta1"], hyper["beta2"], hyper["eps"], hyper["wd"]
        gf = g.astype(jnp.float32)
        pf = p.astype(jnp.float32)
        m = b1 * state["moment1"].astype(jnp.float32) + (1 - b1) * gf
        v = b2 * state["moment2"].astype(jnp.float32) + (1 - b2) * gf * gf
        t = step.astype(jnp.float32)
        mhat = m / (1 - jnp.power(b1, t))
        vhat = v / (1 - jnp.power(b2, t))
        r = mhat / (jnp.sqrt(vhat) + eps) + wd * pf
        p_norm = jnp.linalg.norm(pf)
        r_norm = jnp.linalg.norm(r)
        trust = jnp.where((p_norm > 0) & (r_norm > 0), p_norm / r_norm, 1.0)
        return (pf - lr * trust * r).astype(p.dtype), {
            "moment1": m.astype(state["moment1"].dtype),
            "moment2": v.astype(state["moment2"].dtype)}


class LarsMomentum(Optimizer):
    """LARS: layer-wise adaptive momentum (reference:
    python/paddle/fluid/optimizer.py LarsMomentumOptimizer +
    paddle/fluid/operators/optimizers/lars_momentum_op.cc).

    local_lr = lr * lars_coeff * ||p|| / (||g|| + lars_weight_decay * ||p||);
    velocity = mu * v + local_lr * (g + wd * p); p <- p - velocity.
    """

    def __init__(self, learning_rate=0.001, momentum=0.9, lars_coeff=0.001,
                 lars_weight_decay=0.0005, parameters=None, grad_clip=None,
                 exclude_from_weight_decay=None, epsilon=0.0, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip)
        self._hyper_defaults = {"momentum": float(momentum),
                                "lars_coeff": float(lars_coeff),
                                "wd": float(lars_weight_decay),
                                "eps": float(epsilon)}
        if exclude_from_weight_decay:
            # paddle contract: list of name fragments excluded from decay
            fragments = list(exclude_from_weight_decay)
            self._decay_param_fn = lambda p: not any(
                f in (p.name or "") for f in fragments)

    def _init_state(self, p):
        return {"velocity": jnp.zeros_like(p)}

    @staticmethod
    def _rule(p, g, state, lr, step, hyper):
        mu, coeff, wd, eps = (hyper["momentum"], hyper["lars_coeff"],
                              hyper["wd"], hyper["eps"])
        gf = g.astype(jnp.float32)
        pf = p.astype(jnp.float32)
        p_norm = jnp.linalg.norm(pf)
        g_norm = jnp.linalg.norm(gf)
        denom = g_norm + wd * p_norm + eps
        local_lr = jnp.where((p_norm > 0) & (denom > 0),
                             lr * coeff * p_norm / denom, lr)
        v = mu * state["velocity"].astype(jnp.float32) + local_lr * (gf + wd * pf)
        return (pf - v).astype(p.dtype), {"velocity": v.astype(state["velocity"].dtype)}


class Adafactor(Optimizer):
    """Adafactor (Shazeer & Stern 2018) — the TPU big-model optimizer
    (T5/PaLM recipe): second moments FACTORED into per-row/per-column
    accumulators, so optimizer state is O(n+m) per [n, m] matrix instead of
    O(n*m). On one 16GB chip this is what lets multi-billion-parameter
    models train resident (Adam's fp32 moment pair alone would be 8
    bytes/param). No reference counterpart (paddle ships Adam-family);
    included because the TPU-native bench path needs it.
    """

    def __init__(self, learning_rate=0.01, beta1=0.0, decay_rate=0.8,
                 epsilon1=1e-30, epsilon2=1e-3, clip_threshold=1.0,
                 multiply_by_parameter_scale=True, parameters=None,
                 weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._hyper_defaults = {
            "beta1": float(beta1), "decay": float(decay_rate),
            "eps1": float(epsilon1), "eps2": float(epsilon2),
            "clip": float(clip_threshold),
            "pscale": float(bool(multiply_by_parameter_scale)),
        }

    def _init_state(self, p):
        if p.ndim >= 2:
            st = {"vr": jnp.zeros(p.shape[:-1], jnp.float32),
                  "vc": jnp.zeros(p.shape[:-2] + p.shape[-1:], jnp.float32)}
        else:
            st = {"v": jnp.zeros(p.shape, jnp.float32)}
        if self._hyper_defaults["beta1"] > 0.0:
            st["m"] = jnp.zeros_like(p)
        return st

    @staticmethod
    def _rule(p, g, state, lr, step, hyper):
        eps1, eps2 = hyper["eps1"], hyper["eps2"]
        gf = g.astype(jnp.float32)
        pf = p.astype(jnp.float32)
        t = step.astype(jnp.float32)
        beta2t = 1.0 - jnp.power(t, -hyper["decay"])
        g2 = gf * gf + eps1
        new = {}
        if "v" in state:
            v = beta2t * state["v"] + (1 - beta2t) * g2
            new["v"] = v
            vhat = v
        else:
            vr = beta2t * state["vr"] + (1 - beta2t) * jnp.mean(g2, axis=-1)
            vc = beta2t * state["vc"] + (1 - beta2t) * jnp.mean(g2, axis=-2)
            new["vr"], new["vc"] = vr, vc
            # rank-1 reconstruction: vr vc^T / mean(vr)
            denom = jnp.mean(vr, axis=-1, keepdims=True)
            vhat = (vr / denom)[..., None] * vc[..., None, :]
        u = gf / jnp.sqrt(vhat)
        rms_u = jnp.sqrt(jnp.mean(u * u))
        u = u / jnp.maximum(1.0, rms_u / hyper["clip"])
        if "m" in state:
            m = hyper["beta1"] * state["m"].astype(jnp.float32) + \
                (1 - hyper["beta1"]) * u
            new["m"] = m.astype(state["m"].dtype)
            u = m
        scale = jnp.where(
            hyper["pscale"] > 0,
            jnp.maximum(eps2, jnp.sqrt(jnp.mean(pf * pf))), 1.0)
        return (pf - lr * scale * u).astype(p.dtype), new


class Adadelta(Optimizer):
    """reference optimizer/adadelta.py: accumulated squared grads + squared
    updates, rho-averaged."""

    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95,
                 parameters=None, weight_decay=None, grad_clip=None,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._hyper_defaults = {"rho": float(rho), "eps": float(epsilon)}

    def _init_state(self, p):
        return {"avg_squared_grad": jnp.zeros_like(p),
                "avg_squared_update": jnp.zeros_like(p)}

    @staticmethod
    def _rule(p, g, state, lr, step, hyper):
        rho, eps = hyper["rho"], hyper["eps"]
        g2 = rho * state["avg_squared_grad"] + (1 - rho) * g * g
        update = -jnp.sqrt(state["avg_squared_update"] + eps) / \
            jnp.sqrt(g2 + eps) * g
        u2 = rho * state["avg_squared_update"] + (1 - rho) * update * update
        return p + lr.astype(p.dtype) * update, {
            "avg_squared_grad": g2, "avg_squared_update": u2}


def make_master_update(opt, train_params, dtypes, with_clip=True):
    """fp32-master offload update used by ShardedTrainStep's optimizer-state
    offload: (master, grads, states, lr, step_no) -> (new_master,
    new_states, new_params_cast_to_model_dtype). jit.StreamedTrainStep
    deliberately does NOT use this: it applies the rule in the model dtype
    per layer slice (matching resident jit.TrainStep semantics — no fp32
    master) and rejects grad_clip, so its update lives with its streaming
    loop.

    ``with_clip=False`` strips the grad-clip application: the streaming
    offload executor runs this update per stream GROUP, and a global-norm
    clip applied to one group's grads would be wrong — the caller clips the
    full grad set on the device side before streaming."""
    rule = type(opt)._rule
    hyper = opt._hyper()
    wd = opt._weight_decay
    decoupled = opt._decoupled
    clip = opt._grad_clip if with_clip else None
    wd_flags = tuple(
        1.0 if (opt._decay_param_fn is None or opt._decay_param_fn(p)) else 0.0
        for p in train_params)

    def update(master, grads, states, lr, step_no):
        grads = [g.astype(jnp.float32) for g in grads]
        if clip is not None:
            grads = clip._apply_jax(grads)
        new_m, new_s, new_p = [], [], []
        for p, g, s, flag, dt in zip(master, grads, states, wd_flags, dtypes):
            if wd and not decoupled and flag:
                g = g + wd * p
            hyper_i = hyper if flag or "wd" not in hyper else dict(hyper, wd=0.0)
            np_, ns = rule(p, g, s, lr, step_no, hyper_i)
            if wd and decoupled and flag:
                np_ = np_ - lr * wd * p
            new_m.append(np_)
            new_s.append(ns)
            new_p.append(np_.astype(dt))
        return new_m, new_s, new_p

    return update
