"""paddle_tpu.optimizer (reference: python/paddle/optimizer/)."""
from . import lr  # noqa: F401
from .optimizer import (  # noqa: F401
    Optimizer, SGD, Momentum, Adagrad, Adam, AdamW, Adamax, RMSProp, Lamb,
    LarsMomentum, Adafactor, Adadelta,
)
from .sparse import (  # noqa: F401  (host-side sparse row rules)
    SparseRowAdagrad, SparseRowAdam, SparseRowRule, SparseRowSGD,
)
