"""Sparse per-row update rules for host-resident embedding shards.

Reference: paddle/fluid/operators/optimizers/{sparse_sgd, adagrad, adam}
lazy-mode kernels + distributed/ps/table sgd rules — the PS applies an
optimizer step to exactly the rows a batch touched, never materializing a
dense gradient or walking untouched state.

TPU-native stance: the canonical storage of a ``ShardedEmbeddingTable``
(sparse/embedding.py) is HOST memory (numpy), so the row update is a pure
numpy function over the gathered rows — ``(rows, grads, state_rows) ->
(new_rows, new_state_rows)``. The table gathers the touched rows + their
state slices from the owning shard, applies the rule ONCE per unique row
(duplicate ids are pre-accumulated by the caller), and scatters the
results back. The same rule instance updates the device hot-row cache in
place (the freshly-computed rows are uploaded), so host and cache never
diverge.

Rules mirror the dense ``optimizer.Optimizer._rule`` math restricted to
touched rows — for SGD/Momentum/Adagrad/Adam the dense update of an
untouched row is exactly zero (g=0 ⇒ no param change), so a sparse-rows
run is bit-equal to the dense run on the touched set and trivially equal
elsewhere. Adam is the deliberate exception: bias correction uses a
PER-ROW step count (the row's own update count), the standard lazy-Adam
semantics — a dense Adam would also decay untouched moments, which a
row-sparse table cannot (and should not: rows seen once a day would have
their moments flushed to zero by the decay).
"""
from __future__ import annotations

from typing import Dict, List, Tuple

import numpy as np

__all__ = ["SparseRowRule", "SparseRowSGD", "SparseRowAdagrad",
           "SparseRowAdam", "make_row_rule"]


class SparseRowRule:
    """One row-wise update policy: owns the per-row state layout
    (``state_slots``: name -> per-row width, dim-wide slots use the
    embedding dim) and the pure update ``apply``."""

    #: name -> columns per row ("dim" means the embedding width)
    state_slots: Dict[str, str] = {}

    def __init__(self, lr: float = 0.01):
        self.lr = float(lr)

    def init_state(self, n_rows: int, dim: int) -> Dict[str, np.ndarray]:
        out = {}
        for name, width in self.state_slots.items():
            w = dim if width == "dim" else int(width)
            out[name] = np.zeros((n_rows, w), np.float32)
        return out

    def apply(self, rows: np.ndarray, grads: np.ndarray,
              state: Dict[str, np.ndarray]
              ) -> Tuple[np.ndarray, Dict[str, np.ndarray]]:
        """Pure float32 numpy update over the touched rows only."""
        raise NotImplementedError


class SparseRowSGD(SparseRowRule):
    """Plain row SGD (reference sparse_sgd lazy kernel)."""

    state_slots: Dict[str, str] = {}

    def apply(self, rows, grads, state):
        return rows - self.lr * grads, state


class SparseRowAdagrad(SparseRowRule):
    """Row Adagrad (reference adagrad lazy kernel + the PS sparse-table
    default): per-row second-moment accumulator, touched rows only."""

    state_slots = {"moment": "dim"}

    def __init__(self, lr: float = 0.01, epsilon: float = 1e-6,
                 initial_accumulator_value: float = 0.0):
        super().__init__(lr)
        self.eps = float(epsilon)
        self.init_val = float(initial_accumulator_value)

    def init_state(self, n_rows, dim):
        st = super().init_state(n_rows, dim)
        if self.init_val:
            st["moment"] += self.init_val
        return st

    def apply(self, rows, grads, state):
        m = state["moment"] + grads * grads
        new = rows - self.lr * grads / (np.sqrt(m) + self.eps)
        return new, {"moment": m}


class SparseRowAdam(SparseRowRule):
    """Lazy Adam over rows: moments and the bias-correction step count
    advance only when a row is touched (its own update count rides a
    1-wide state slot)."""

    state_slots = {"moment1": "dim", "moment2": "dim", "count": "1"}

    def __init__(self, lr: float = 0.001, beta1: float = 0.9,
                 beta2: float = 0.999, epsilon: float = 1e-8):
        super().__init__(lr)
        self.b1, self.b2, self.eps = float(beta1), float(beta2), float(epsilon)

    def apply(self, rows, grads, state):
        t = state["count"] + 1.0
        m = self.b1 * state["moment1"] + (1 - self.b1) * grads
        v = self.b2 * state["moment2"] + (1 - self.b2) * grads * grads
        mhat = m / (1 - np.power(self.b1, t))
        vhat = v / (1 - np.power(self.b2, t))
        new = rows - self.lr * mhat / (np.sqrt(vhat) + self.eps)
        return new, {"moment1": m, "moment2": v, "count": t}


_RULES = {"sgd": SparseRowSGD, "adagrad": SparseRowAdagrad,
          "adam": SparseRowAdam}


def make_row_rule(spec, **kw) -> SparseRowRule:
    """'sgd' | 'adagrad' | 'adam' | a SparseRowRule instance."""
    if isinstance(spec, SparseRowRule):
        return spec
    try:
        cls = _RULES[str(spec).lower()]
    except KeyError:
        raise ValueError(
            f"unknown sparse row rule {spec!r}; known: {sorted(_RULES)} "
            "(or pass a SparseRowRule instance)")
    return cls(**kw)
