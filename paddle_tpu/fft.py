"""paddle.fft (reference: python/paddle/fft.py — fft_c2c/r2c/c2r ops over
cuFFT). TPU-native: jnp.fft lowers to XLA's FFT HLO; each public function is a
dispatched primitive so transforms join the tape (complex grads via jax vjp).
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .core.dispatch import primitive
from .core.tensor import Tensor

__all__ = ["fft", "ifft", "fft2", "ifft2", "fftn", "ifftn",
           "rfft", "irfft", "rfft2", "irfft2", "rfftn", "irfftn",
           "hfft", "ihfft", "fftfreq", "rfftfreq", "fftshift", "ifftshift"]


def _norm(norm):
    if norm in (None, "backward"):
        return "backward"
    if norm in ("forward", "ortho"):
        return norm
    raise ValueError(f"norm must be backward/forward/ortho, got {norm}")


def _make_1d(name, jfn):
    p = primitive(f"fft_{name}")(
        lambda x, *, n, axis, norm: jfn(x, n=n, axis=axis, norm=norm))

    def fn(x, n=None, axis=-1, norm="backward", name=None):
        return p(x, n=n if n is None else int(n), axis=int(axis),
                 norm=_norm(norm))

    fn.__name__ = name
    return fn


def _make_nd(name, jfn):
    p = primitive(f"fft_{name}")(
        lambda x, *, s, axes, norm: jfn(x, s=s, axes=axes, norm=norm))

    def fn(x, s=None, axes=None, norm="backward", name=None):
        return p(x, s=None if s is None else tuple(int(v) for v in s),
                 axes=None if axes is None else tuple(int(a) for a in axes),
                 norm=_norm(norm))

    fn.__name__ = name
    return fn


fft = _make_1d("fft", jnp.fft.fft)
ifft = _make_1d("ifft", jnp.fft.ifft)
rfft = _make_1d("rfft", jnp.fft.rfft)
irfft = _make_1d("irfft", jnp.fft.irfft)
hfft = _make_1d("hfft", jnp.fft.hfft)
ihfft = _make_1d("ihfft", jnp.fft.ihfft)

fftn = _make_nd("fftn", jnp.fft.fftn)
ifftn = _make_nd("ifftn", jnp.fft.ifftn)
rfftn = _make_nd("rfftn", jnp.fft.rfftn)
irfftn = _make_nd("irfftn", jnp.fft.irfftn)


def fft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return fftn(x, s=s, axes=axes, norm=norm)


def ifft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return ifftn(x, s=s, axes=axes, norm=norm)


def rfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return rfftn(x, s=s, axes=axes, norm=norm)


def irfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return irfftn(x, s=s, axes=axes, norm=norm)


def fftfreq(n, d=1.0, dtype=None, name=None):
    return Tensor(jnp.fft.fftfreq(int(n), float(d)).astype(
        np.dtype(dtype) if dtype else jnp.float32))


def rfftfreq(n, d=1.0, dtype=None, name=None):
    return Tensor(jnp.fft.rfftfreq(int(n), float(d)).astype(
        np.dtype(dtype) if dtype else jnp.float32))


@primitive("fft_fftshift")
def _fftshift(x, *, axes):
    return jnp.fft.fftshift(x, axes=axes)


def fftshift(x, axes=None, name=None):
    return _fftshift(x, axes=None if axes is None else tuple(
        int(a) for a in (axes if isinstance(axes, (list, tuple)) else [axes])))


@primitive("fft_ifftshift")
def _ifftshift(x, *, axes):
    return jnp.fft.ifftshift(x, axes=axes)


def ifftshift(x, axes=None, name=None):
    return _ifftshift(x, axes=None if axes is None else tuple(
        int(a) for a in (axes if isinstance(axes, (list, tuple)) else [axes])))
