"""paddle.hub (reference: python/paddle/hapi/hub.py — hubconf.py-driven model
loading). Zero-egress environment: only source='local' works; github/gitee
sources raise with a clear message instead of attempting a download.
"""
from __future__ import annotations

import importlib.util
import os
import sys

HUBCONF = "hubconf.py"


def _load_hubconf(repo_dir: str):
    path = os.path.join(repo_dir, HUBCONF)
    if not os.path.exists(path):
        raise FileNotFoundError(f"hub: no {HUBCONF} in {repo_dir}")
    spec = importlib.util.spec_from_file_location("paddle_tpu_hubconf", path)
    module = importlib.util.module_from_spec(spec)
    sys.path.insert(0, repo_dir)
    try:
        spec.loader.exec_module(module)
    finally:
        sys.path.remove(repo_dir)
    return module


def _require_local(source):
    if source != "local":
        raise RuntimeError(
            f"hub source {source!r} needs network access, unavailable in this "
            f"environment; clone the repo and use source='local'")


def list(repo_dir, source="local", force_reload=False):
    """Entry-point names exported by the repo's hubconf.py."""
    _require_local(source)
    m = _load_hubconf(repo_dir)
    return [name for name in dir(m)
            if callable(getattr(m, name)) and not name.startswith("_")]


def help(repo_dir, model, source="local", force_reload=False):
    _require_local(source)
    m = _load_hubconf(repo_dir)
    fn = getattr(m, model, None)
    if fn is None or not callable(fn):
        raise ValueError(f"hub: no entry point {model!r} in {repo_dir}")
    return fn.__doc__


def load(repo_dir, model, source="local", force_reload=False, **kwargs):
    """Instantiate a hubconf entry point."""
    _require_local(source)
    m = _load_hubconf(repo_dir)
    fn = getattr(m, model, None)
    if fn is None or not callable(fn):
        raise ValueError(f"hub: no entry point {model!r} in {repo_dir}")
    return fn(**kwargs)
