"""Terminal progress bar (reference: python/paddle/hapi/progressbar.py role)."""
from __future__ import annotations

import sys
import time


class ProgressBar:
    def __init__(self, num=None, width=30, verbose=1, file=sys.stdout):
        self._num = num
        self._width = width
        self._verbose = verbose
        self.file = file
        self._start = time.time()
        self._last_update = 0.0

    def update(self, current_num, values=None):
        values = values or []
        now = time.time()
        msg = []
        if self._num is not None:
            msg.append(f"step {current_num}/{self._num}")
        else:
            msg.append(f"step {current_num}")
        for k, v in values:
            if isinstance(v, (list, tuple)):
                v = " ".join(f"{x:.4f}" for x in v)
            elif isinstance(v, float):
                v = f"{v:.4f}"
            msg.append(f"{k}: {v}")
        elapsed = now - self._start
        if current_num:
            msg.append(f"{1e3 * elapsed / current_num:.0f}ms/step")
        line = " - ".join(msg)
        if self._verbose == 1:
            self.file.write("\r" + line)
            if self._num is not None and current_num >= self._num:
                self.file.write("\n")
            self.file.flush()
        elif self._verbose == 2:
            self.file.write(line + "\n")
            self.file.flush()

    def start(self):
        self._start = time.time()
