"""High-level API (reference: python/paddle/hapi/).

`Model` wraps an ``nn.Layer`` with fit/evaluate/predict loops driven by host
Python; every batch still executes through the eager per-op jit dispatch, so
the device math is identical to hand-written loops. Callbacks mirror the
reference's callback zoo (callbacks.py) with the same hook points.
"""
from .model import Model  # noqa: F401
from .model_summary import summary  # noqa: F401
from .dynamic_flops import flops  # noqa: F401
from . import callbacks  # noqa: F401
from . import hub  # noqa: F401
