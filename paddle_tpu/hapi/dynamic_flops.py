"""paddle.flops (reference: python/paddle/hapi/dynamic_flops.py).

Hook-based FLOP accounting over one traced forward — the same per-layer-type
count table as the reference (conv: 2*k*k*cin/g*cout*oh*ow, linear: 2*in*out,
norm/act/pool: elementwise)."""
from __future__ import annotations

import numpy as np

from .. import nn
from ..core.tensor import Tensor


def _numel(shape):
    return int(np.prod(shape)) if shape else 1


def _count(layer, inputs, output):
    x = inputs[0] if isinstance(inputs, (list, tuple)) else inputs
    out = output[0] if isinstance(output, (list, tuple)) else output
    name = type(layer).__name__
    if isinstance(layer, (nn.Conv2D, nn.Conv1D)):
        kernel = _numel(layer._kernel_size)
        cin = layer._in_channels // layer._groups
        out_elems = _numel(out.shape)
        flops = 2 * kernel * cin * out_elems
        if layer.bias is None:
            flops -= out_elems
        return flops
    if isinstance(layer, nn.Linear):
        out_elems = _numel(out.shape)
        flops = 2 * layer._in_features * out_elems
        if layer.bias is None:
            flops -= out_elems
        return flops
    if "Norm" in name:
        return 2 * _numel(x.shape)
    if "Pool" in name:
        return _numel(x.shape)
    if name in ("ReLU", "ReLU6", "GELU", "Sigmoid", "Tanh", "Silu", "Swish",
                "LeakyReLU", "Hardswish", "Hardsigmoid", "Mish", "ELU"):
        return _numel(x.shape)
    return 0


def flops(net, input_size, custom_ops=None, print_detail=False):
    """Count multiply-accumulate FLOPs of one forward (reference
    dynamic_flops.py flops). custom_ops: {LayerType: fn(layer, in, out)->int}.
    """
    custom_ops = custom_ops or {}
    records = []
    hooks = []

    def make_hook(layer):
        def hook(l, ins, outs):
            fn = None
            for cls, f in custom_ops.items():
                if isinstance(l, cls):
                    fn = f
                    break
            n = fn(l, ins, outs) if fn else _count(l, ins, outs)
            params = sum(_numel(p.shape) for p in l._parameters.values()
                         if p is not None)
            records.append((type(l).__name__, n, params))
        hooks.append(layer.register_forward_post_hook(hook))

    for _, sub in net.named_sublayers():
        if not sub._sub_layers:
            make_hook(sub)
    if not hooks:
        make_hook(net)

    was_training = net.training
    net.eval()
    try:
        from ..core import no_grad

        shape = [1 if (d is None or d < 0) else d for d in input_size]
        x = Tensor(np.zeros(shape, "float32"))
        with no_grad():
            net(x)
    finally:
        for h in hooks:
            h.remove()
        if was_training:
            net.train()

    total = sum(r[1] for r in records)
    if print_detail:
        print(f"{'Layer':<24}{'FLOPs':>16}{'Params':>12}")
        for name, n, p in records:
            print(f"{name:<24}{n:>16,}{p:>12,}")
        print(f"Total FLOPs: {total:,}")
    return total
