"""paddle.summary (reference: python/paddle/hapi/model_summary.py).

Runs one forward pass with forward-post hooks recording each leaf layer's
output shape and parameter count, then prints the familiar table.
"""
from __future__ import annotations

import numbers

import numpy as np

from ..core.tensor import Tensor
from ..nn.layer.layers import Layer


def _normalize_sizes(input_size):
    if isinstance(input_size, tuple) and all(isinstance(x, numbers.Number) or x is None
                                             for x in input_size):
        return [tuple(input_size)]
    if isinstance(input_size, (list, tuple)):
        return [tuple(s) for s in input_size]
    raise TypeError(f"unsupported input_size: {input_size!r}")


def summary(net: Layer, input_size, dtypes=None, input=None):
    """Print a per-layer summary; returns {'total_params', 'trainable_params'}."""
    sizes = _normalize_sizes(input_size)
    dtypes = dtypes or ["float32"] * len(sizes)
    if isinstance(dtypes, str):
        dtypes = [dtypes] * len(sizes)
    if input is not None:
        inputs = [input] if isinstance(input, Tensor) else list(input)
    else:
        inputs = []
        for s, dt in zip(sizes, dtypes):
            s = tuple(1 if (d is None or (isinstance(d, int) and d < 0)) else d
                      for d in s)
            if dt in ("int32", "int64"):
                inputs.append(Tensor(np.zeros(s, dt)))
            else:
                inputs.append(Tensor(np.random.default_rng(0).standard_normal(s).astype(dt)))

    rows = []
    hooks = []

    def register(layer, name):
        def hook(l, ins, outs):
            out = outs[0] if isinstance(outs, (list, tuple)) else outs
            shape = list(out.shape) if hasattr(out, "shape") else []
            n_params = sum(int(np.prod(p.shape)) for p in l._parameters.values()
                           if p is not None)
            rows.append((f"{type(l).__name__}-{len(rows) + 1}", name, shape, n_params))
        hooks.append(layer.register_forward_post_hook(hook))

    for name, sub in net.named_sublayers(include_self=False):
        if not sub._sub_layers:  # leaf layers only
            register(sub, name)
    if not rows and not hooks:
        register(net, "")

    was_training = net.training
    net.eval()
    try:
        from ..core import no_grad

        with no_grad():
            net(*inputs)
    finally:
        for h in hooks:
            h.remove()
        if was_training:
            net.train()

    total_params = 0
    trainable_params = 0
    seen = set()
    for _, p in net.named_parameters():
        if id(p) in seen:
            continue
        seen.add(id(p))
        n = int(np.prod(p.shape))
        total_params += n
        if p.trainable:
            trainable_params += n

    header = f"{'Layer (type)':<28}{'Output Shape':<26}{'Param #':>12}"
    line = "-" * len(header)
    print(line)
    print(header)
    print("=" * len(header))
    for lname, _, shape, n_params in rows:
        print(f"{lname:<28}{str(shape):<26}{n_params:>12,}")
    print("=" * len(header))
    print(f"Total params: {total_params:,}")
    print(f"Trainable params: {trainable_params:,}")
    print(f"Non-trainable params: {total_params - trainable_params:,}")
    print(line)
    return {"total_params": total_params, "trainable_params": trainable_params}
