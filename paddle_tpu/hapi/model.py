"""hapi.Model: fit/evaluate/predict loops (reference: python/paddle/hapi/model.py:1014).

TPU-native stance: there is exactly one execution adapter — the eager dygraph
path whose every op is a cached jitted XLA executable — so the reference's
StaticGraphAdapter/DynamicGraphAdapter split (model.py:252,667) collapses into
Model itself. Distributed fit() composes with paddle_tpu.distributed the same
way hand loops do (DistributedBatchSampler + GSPMD-annotated layers).
"""
from __future__ import annotations

import os
import time as _time
from contextlib import nullcontext
from typing import List, Optional

import numpy as np

from ..core.tensor import Tensor
from ..framework import io as fio
from ..metric import Metric
from .callbacks import config_callbacks


def to_list(value):
    if value is None:
        return []
    if isinstance(value, (list, tuple)):
        return list(value)
    return [value]


def _as_tensor(x):
    if isinstance(x, Tensor):
        return x
    return Tensor(np.asarray(x))


def _scalar(t):
    return float(np.asarray(t.data if isinstance(t, Tensor) else t))


def _timeline():
    from ..observability.timeline import timeline

    return timeline()


def _resilience():
    from ..distributed import resilience

    return resilience


def _nan_skip_exc():
    from ..core.tensor import NanStepSkipped

    return NanStepSkipped


def _oom_guard(site, **ids):
    """Memory-truth bracket (observability.memory): the deterministic
    ``oom`` fault site (``PT_FAULTS="oom@step=N"``) plus forensics — a
    RESOURCE_EXHAUSTED inside dumps the flight bundle with the memory
    report BEFORE the crash unwinds the loop."""
    from ..observability.memory import oom_guard

    return oom_guard(site, **ids)


def _auto_device_prefetch(loader, device_sharding):
    """fit(prefetch_to_device=None) default: a DistributedBatchSampler-
    driven DataLoader on an active multi-device mesh prefetches to the
    mesh's data placement automatically (the PR-3 follow-up) — the batch
    lands laid out for the sharded step, and the timeline's ``data_wait``
    shows the overlap win. Returns (enable, device_sharding)."""
    from ..io import DataLoader, DistributedBatchSampler

    if not isinstance(loader, DataLoader) or loader.prefetch_to_device:
        return False, device_sharding  # loader already prefetches (or n/a)
    if not isinstance(getattr(loader, "batch_sampler", None),
                      DistributedBatchSampler):
        return False, device_sharding
    try:
        from ..distributed.mesh import get_mesh_env
        from ..distributed.parallel import default_batch_sharding

        env = get_mesh_env()
        if env is None or env.nranks <= 1:
            return False, device_sharding
        if device_sharding is None:
            device_sharding = default_batch_sharding(env)
    except Exception:
        return False, device_sharding
    return True, device_sharding


class Model:
    """A Layer + optimizer + loss + metrics bundle with training loops.

    Reference: python/paddle/hapi/model.py:1014 (class Model). Same public
    surface: prepare / fit / evaluate / predict / train_batch / eval_batch /
    predict_batch / save / load / parameters / summary.
    """

    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._inputs = to_list(inputs)
        self._labels = to_list(labels)
        self._loss = None
        self._metrics = []
        self._optimizer = None
        self.mode = "train"
        self.stop_training = False

    # -- single-batch APIs ---------------------------------------------------
    def prepare(self, optimizer=None, loss=None, metrics=None, amp_configs=None):
        self._optimizer = optimizer
        if loss is not None and not callable(loss):
            raise TypeError("loss must be a callable (Layer or function)")
        self._loss = loss
        metrics = metrics or []
        for m in to_list(metrics):
            if not isinstance(m, Metric):
                raise TypeError(f"metric {m} is not a paddle_tpu.metric.Metric")
        self._metrics = to_list(metrics)
        self._amp_configs = amp_configs
        return self

    def _compute_loss(self, outputs, labels):
        losses = to_list(self._loss(*(to_list(outputs) + labels)))
        return losses

    def _sparse_tables(self):
        """ShardedEmbeddingTables behind the network's sparse Embedding
        layers (cached per network — the layer-tree walk is not a
        per-step cost)."""
        cached = getattr(self, "_sparse_tables_cache", None)
        if cached is None or cached[0] is not self.network:
            from ..sparse.embedding import sparse_tables

            cached = (self.network, sparse_tables(self.network))
            self._sparse_tables_cache = cached
        return cached[1]

    def train_batch(self, inputs, labels=None, update=True, _loss_scale=1.0):
        tl = _timeline()
        self.network.train()
        self.mode = "train"
        inputs = [_as_tensor(x) for x in to_list(inputs)]
        labels = [_as_tensor(x) for x in to_list(labels)]
        # StepTimeline phases: dispatch (fwd+bwd+update enqueue, async under
        # jax) vs the host blocking on device results (loss/metric readback)
        with tl.phase("host_dispatch"):
            outputs = self.network(*inputs)
            losses = self._compute_loss(outputs, labels)
            total = losses[0]
            for extra in losses[1:]:
                total = total + extra
            if _loss_scale != 1.0:  # gradient accumulation averages micro-batches
                (total * _loss_scale).backward()
            else:
                total.backward()
            # sparse embedding tables: harvest the (unique_ids, rows)
            # gradients every micro-step (the leaves are per-forward);
            # the host row update applies at the SAME boundary as the
            # dense optimizer step, so accumulate(k) composes
            for t in self._sparse_tables():
                t.flush(update=update)
            if update and self._optimizer is not None:
                self._optimizer.step()
                self._optimizer.clear_grad()
        # host BLOCKING on device results (loss/metric readback) — host
        # time, not device time; XPlane correlation owns device_compute_us
        with tl.phase("device_block"):
            metrics = []
            for m in self._metrics:
                metric_outs = m.compute(*(to_list(outputs) + labels))
                metrics.append(m.update(*[np.asarray(
                    t.data if isinstance(t, Tensor) else t) for t in to_list(metric_outs)]))
            loss_vals = [_scalar(l) for l in losses]
        if metrics:
            return loss_vals, metrics[0] if len(metrics) == 1 else metrics
        return loss_vals

    def _check_nan_step_fault(self, gstep: int) -> None:
        """``nan_step`` fault site: a scripted NaN-producing step at an
        exact global step index (``PT_FAULTS="nan_step@step=5"``). Fires
        as ``NanStepSkipped`` when FLAGS_check_nan_inf_action='skip' (the
        loop drops the step and continues); as a RuntimeError otherwise —
        the same two outcomes a REAL non-finite step has under the per-op
        guard."""
        from ..distributed.resilience.faults import injector

        if not injector().peek("nan_step", step=gstep):
            return
        from ..framework import flags as _flags

        msg = f"injected nan_step at step {gstep}"
        if _flags.flag("check_nan_inf_action") == "skip":
            raise _nan_skip_exc()(msg)
        raise RuntimeError(msg)

    def eval_batch(self, inputs, labels=None):
        self.network.eval()
        self.mode = "eval"
        from ..core import no_grad

        inputs = [_as_tensor(x) for x in to_list(inputs)]
        labels = [_as_tensor(x) for x in to_list(labels)]
        with no_grad():
            outputs = self.network(*inputs)
            loss_vals = []
            if self._loss is not None:
                loss_vals = [_scalar(l) for l in self._compute_loss(outputs, labels)]
        metrics = []
        for m in self._metrics:
            metric_outs = m.compute(*(to_list(outputs) + labels))
            metrics.append(m.update(*[np.asarray(
                t.data if isinstance(t, Tensor) else t) for t in to_list(metric_outs)]))
        if metrics:
            return loss_vals, metrics[0] if len(metrics) == 1 else metrics
        return loss_vals

    def predict_batch(self, inputs):
        self.network.eval()
        self.mode = "test"
        from ..core import no_grad

        inputs = [_as_tensor(x) for x in to_list(inputs)]
        with no_grad():
            outputs = self.network(*inputs)
        return [np.asarray(o.data) for o in to_list(outputs)]

    def parameters(self, *args, **kwargs):
        return self.network.parameters()

    # -- checkpoint ----------------------------------------------------------
    def save(self, path, training=True):
        """Save `<path>.pdparams` (+ `.pdopt` when training). For deployment
        (training=False) export the traced program via paddle_tpu.jit.save.

        Sparse embedding tables are NOT in ``state_dict()`` (their
        canonical rows are host-resident, not Parameters): they save
        alongside as ``<path>.sparse.<table>.npz`` so a plain ``save``
        never silently drops learned embeddings; ``load`` restores
        them."""
        if training:
            for t in self._sparse_tables():
                try:
                    t.save(f"{path}.sparse.{t.name}")
                except NotImplementedError:
                    import warnings

                    warnings.warn(
                        f"Model.save: sparse table {t.name!r} is not "
                        f"LocalShards-backed — its rows are NOT in this "
                        f"checkpoint (a PsShardSource table's authority "
                        f"is the server gang)", RuntimeWarning,
                        stacklevel=2)
        if not training:
            from .. import jit

            jit.save(self.network, path, input_spec=self._inputs or None)
            return
        dirname = os.path.dirname(path)
        if dirname:
            os.makedirs(dirname, exist_ok=True)
        fio.save(self.network.state_dict(), path + ".pdparams")
        if self._optimizer is not None:
            fio.save(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        param_state = fio.load(path + ".pdparams")
        missing, unexpected = self.network.set_state_dict(param_state)
        if not skip_mismatch and (missing or unexpected):
            raise ValueError(
                f"state dict mismatch: missing keys {missing}, "
                f"unexpected keys {unexpected} (pass skip_mismatch=True to ignore)")
        if not reset_optimizer and self._optimizer is not None \
                and os.path.exists(path + ".pdopt"):
            self._optimizer.set_state_dict(fio.load(path + ".pdopt"))
        for t in self._sparse_tables():
            sp = f"{path}.sparse.{t.name}.npz"
            if os.path.exists(sp):
                t.load(sp)
            else:
                # never silent: a renamed/auto-numbered table would
                # otherwise keep its fresh random rows after a "load"
                import warnings

                warnings.warn(
                    f"Model.load: no sparse-table checkpoint at {sp!r} — "
                    f"table {t.name!r} keeps its current rows (tables "
                    f"are matched by NAME; give tables stable name= "
                    f"values)", RuntimeWarning, stacklevel=2)
        return self

    # -- loops ---------------------------------------------------------------
    def _make_loader(self, data, batch_size, shuffle, num_workers, drop_last=False):
        from ..io import DataLoader, Dataset

        if data is None:
            return None
        if isinstance(data, DataLoader):
            return data
        if isinstance(data, Dataset):
            return DataLoader(data, batch_size=batch_size, shuffle=shuffle,
                              num_workers=num_workers, drop_last=drop_last)
        return data  # any iterable of batches

    def _split_batch(self, batch):
        batch = batch if isinstance(batch, (list, tuple)) else [batch]
        if (self._loss is not None or self._metrics) and len(batch) > 1:
            # convention: last element(s) are labels (reference model.py:1986)
            n_labels = max(1, len(self._labels)) if self._labels else 1
            return list(batch[:-n_labels]), list(batch[-n_labels:])
        return list(batch), []

    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None,
            accumulate_grad_batches=1, num_iters=None,
            prefetch_to_device=None, device_sharding=None,
            checkpoint_every=None, checkpoint_dir="checkpoints",
            checkpoint_keep=3, resume=False):
        """``checkpoint_every=N`` turns on the fault-tolerant runtime
        (``distributed.resilience``): every N train steps an
        ``AsyncCheckpointer`` snapshots params/optimizer/rng and commits in
        the background (save time hides behind the next steps' compute);
        SIGTERM is trapped and, at the next step boundary, drained into a
        final synchronous commit before the loop stops — a later
        ``fit(..., resume=True)`` continues from exactly that step, on
        whatever device count the relaunch has. ``resume=True`` restores
        the newest verified checkpoint under ``checkpoint_dir`` (epoch,
        step-in-epoch, rng and optimizer state included) and fast-forwards
        the loader to the first unseen batch. ``resume`` also accepts a
        PATH: restore from that directory while new saves keep landing in
        ``checkpoint_dir`` — the elastic fleet uses this to resume every
        rank from the fleet-wide newest commit after a membership change
        (each rank checkpoints into its own dir)."""
        assert train_data is not None, "train_data must be given"
        try:
            # flight recorder: every trained step lands in the bounded
            # ring; anomalies (regression/stall/fault burst), SIGQUIT and
            # preemption auto-dump a pd_dump diagnostic bundle. Ring-append
            # cost per step; must never block training.
            from ..observability.trace import flight_recorder

            flight_recorder()
            # memory truth: per-step watermark stamps into the monitor's
            # history (and, via the recorder's ring, into every bundle)
            from ..observability.memory import memory_monitor

            memory_monitor()
        except Exception:
            pass
        loader = self._make_loader(train_data, batch_size, shuffle, num_workers,
                                   drop_last=drop_last)
        eval_loader = self._make_loader(eval_data, batch_size, False, num_workers)
        auto_prefetch = False
        if prefetch_to_device is None:
            # default = auto: DistributedBatchSampler-driven loaders on an
            # active mesh prefetch to the mesh data placement
            prefetch_to_device, device_sharding = _auto_device_prefetch(
                loader, device_sharding)
            auto_prefetch = prefetch_to_device
        if prefetch_to_device:
            # io.prefetch: a background thread device_puts batch N+1 while
            # batch N trains, so the step never waits on the host transfer.
            # device_sharding: Sharding or leaf->sharding callable (e.g.
            # ShardedTrainStep.batch_sharding) for mesh-placed batches.
            from ..io import DevicePrefetcher

            loader = DevicePrefetcher(loader, sharding=device_sharding)
            # the auto decision was made on the TRAIN loader only — an eval
            # loader with its own sampler/batching keeps its old behavior
            # unless the caller opted in explicitly
            if eval_loader is not None and not auto_prefetch:
                eval_loader = DevicePrefetcher(eval_loader,
                                               sharding=device_sharding)
        steps = len(loader) if hasattr(loader, "__len__") else None
        metric_names = ["loss"] + [n for m in self._metrics for n in to_list(m.name())]
        cbks = config_callbacks(
            callbacks, model=self, epochs=epochs, steps=steps, log_freq=log_freq,
            save_freq=save_freq, save_dir=save_dir, verbose=verbose,
            metrics=metric_names)
        ckpt_ctx = None
        start_epoch = 0
        if checkpoint_every is not None:
            rz = _resilience()
            ck = rz.AsyncCheckpointer(checkpoint_dir, model=self.network,
                                      optimizer=self._optimizer,
                                      keep=checkpoint_keep, name="fit")
            rz.install_preemption_handler()
            ckpt_ctx = {"ck": ck, "every": max(int(checkpoint_every), 1),
                        "global_step": 0, "skip_steps": 0, "preempted": False}
            if resume:
                if isinstance(resume, str):
                    # resume FROM another root (the fleet's authoritative
                    # dir) while saving INTO checkpoint_dir
                    meta = rz.resume(resume, model=self.network,
                                     optimizer=self._optimizer)
                else:
                    meta = ck.resume()
                if meta is not None:
                    start_epoch = int(meta.get("epoch") or 0)
                    ckpt_ctx["global_step"] = int(meta["step"]) + 1
                    ckpt_ctx["last_save"] = int(meta["step"])
                    # fast-forward past the batches the saved step consumed
                    sie = meta.get("extra", {}).get("step_in_epoch")
                    if sie is not None:
                        ckpt_ctx["skip_steps"] = int(sie) + 1
                    # the rng state the interrupted EPOCH began with: a
                    # shuffling sampler redraws its permutation from the
                    # global generator at iter() time, so the resumed epoch
                    # must replay the draw from this state (the restored
                    # mid-step rng would yield a different batch order)
                    ckpt_ctx["resume_epoch_rng"] = \
                        meta.get("extra", {}).get("epoch_rng")
        self.stop_training = False
        cbks.on_begin("train")
        try:
            for epoch in range(start_epoch, epochs):
                if self.stop_training:
                    break
                cbks.on_epoch_begin(epoch)
                if ckpt_ctx is not None:
                    ckpt_ctx["epoch"] = epoch
                    if ckpt_ctx.get("resume_epoch_rng") is not None:
                        # resumed epoch: saves must carry the ORIGINAL
                        # epoch-begin rng, not the mid-step restored state
                        ckpt_ctx["epoch_rng"] = ckpt_ctx["resume_epoch_rng"]
                    else:
                        from ..framework import random as _random_mod

                        ckpt_ctx["epoch_rng"] = [
                            int(v) for v in _random_mod.get_rng_state()]
                logs = self._run_one_epoch(loader, cbks, "train",
                                           accumulate_grad_batches, num_iters,
                                           ckpt_ctx=ckpt_ctx)
                cbks.on_epoch_end(epoch, logs)
                if ckpt_ctx is not None and ckpt_ctx["preempted"]:
                    break
                if eval_loader is not None and (epoch % eval_freq == 0 or epoch == epochs - 1):
                    eval_logs = {"steps": len(eval_loader) if hasattr(eval_loader, "__len__") else None,
                                 "metrics": metric_names}
                    cbks.on_begin("eval", eval_logs)
                    eval_logs = self._run_one_epoch(eval_loader, cbks, "eval")
                    cbks.on_end("eval", eval_logs)
            cbks.on_end("train")
        finally:
            if ckpt_ctx is not None:
                ckpt_ctx["ck"].close()  # drain any in-flight save
        return self

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, num_iters=None):
        loader = self._make_loader(eval_data, batch_size, False, num_workers)
        metric_names = ["loss"] + [n for m in self._metrics for n in to_list(m.name())]
        cbks = config_callbacks(callbacks, model=self, log_freq=log_freq,
                                verbose=verbose, metrics=metric_names)
        logs = {"steps": len(loader) if hasattr(loader, "__len__") else None,
                "metrics": metric_names}
        cbks.on_begin("eval", logs)
        logs = self._run_one_epoch(loader, cbks, "eval", num_iters=num_iters)
        cbks.on_end("eval", logs)
        return {k: v for k, v in logs.items() if k in metric_names}

    def predict(self, test_data, batch_size=1, num_workers=0, stack_outputs=False,
                verbose=1, callbacks=None):
        loader = self._make_loader(test_data, batch_size, False, num_workers)
        cbks = config_callbacks(callbacks, model=self, verbose=verbose, metrics=[])
        logs = {"steps": len(loader) if hasattr(loader, "__len__") else None}
        cbks.on_begin("predict", logs)
        outputs: List[List[np.ndarray]] = []
        count = 0
        for step, batch in enumerate(loader):
            inputs, _labels = self._split_batch(batch)  # drop labels if present
            cbks.on_batch_begin("predict", step, {})
            outs = self.predict_batch(inputs)
            outputs.append(outs)
            count += outs[0].shape[0] if outs and hasattr(outs[0], "shape") else 1
            cbks.on_batch_end("predict", step, {})
        # regroup from per-batch to per-output (reference model.py:1960)
        n_out = len(outputs[0]) if outputs else 0
        grouped = [[b[i] for b in outputs] for i in range(n_out)]
        if stack_outputs:
            grouped = [np.concatenate(g, axis=0) for g in grouped]
        cbks.on_end("predict", {"samples": count})
        return grouped

    def _run_one_epoch(self, loader, cbks, mode, accumulate_grad_batches=1,
                       num_iters=None, ckpt_ctx=None):
        for m in self._metrics:
            m.reset()
        logs = {}
        count = 0
        pending = False
        nan_window = False  # current accumulation window had a NaN-skip
        tl = _timeline() if mode == "train" else None
        resumed_rng = None
        if mode == "train" and ckpt_ctx is not None and ckpt_ctx["skip_steps"] \
                and ckpt_ctx.pop("resume_epoch_rng", None) is not None:
            # rewind the global generator to the interrupted epoch's begin
            # state so a shuffling sampler redraws the SAME permutation the
            # original epoch trained on; the mid-step state (restored by
            # resume()) comes back right after the fast-forward
            from ..framework import random as _random_mod

            resumed_rng = _random_mod.get_rng_state()
            _random_mod.set_rng_state(
                tuple(int(v) for v in ckpt_ctx["epoch_rng"]))
        it = iter(loader)
        step = 0
        _END = object()
        if mode == "train" and ckpt_ctx is not None and ckpt_ctx["skip_steps"]:
            # resume fast-forward: consume the batches the checkpointed step
            # already trained on, so the loader replays the same sequence
            # the uninterrupted run would have seen
            for _ in range(ckpt_ctx["skip_steps"]):
                if next(it, _END) is _END:
                    break
                step += 1
            ckpt_ctx["skip_steps"] = 0
            if resumed_rng is not None:
                from ..framework import random as _random_mod

                _random_mod.set_rng_state(resumed_rng)
        while True:
            if num_iters is not None and step >= num_iters:
                break
            # one StepTimeline step = wait for the batch + run it; the
            # data_wait phase is where prefetch overlap shows up (near-zero
            # when the DevicePrefetcher keeps the queue fed)
            with (tl.step() if tl is not None else nullcontext()) as st:
                t_wait = _time.perf_counter()
                batch = next(it, _END)
                t_got = _time.perf_counter()
                if batch is _END:
                    if st is not None:
                        st.cancel()  # exhausted-loader probe is not a step
                    break
                inputs, labels = self._split_batch(batch)
                cbks.on_batch_begin(mode, step, logs)
                if mode == "train" and self.stop_training:
                    if st is not None:
                        st.cancel()  # cancelled steps record no phases
                    break
                if tl is not None:
                    tl.record("data_wait", (t_got - t_wait) * 1e3, t0=t_wait)
                if mode == "train":
                    update = (step + 1) % accumulate_grad_batches == 0
                    gstep = ckpt_ctx["global_step"] if ckpt_ctx is not None \
                        else step
                    try:
                        self._check_nan_step_fault(gstep)
                        with _oom_guard("fit", step=gstep):
                            outs = self.train_batch(
                                inputs, labels,
                                update=update and not nan_window,
                                _loss_scale=1.0 / accumulate_grad_batches)
                    except _nan_skip_exc() as e:
                        # skip-and-continue: the poisoned step is dropped
                        # whole (grads cleared, no optimizer update) and
                        # training goes on — counted for the monitors.
                        # Mid-accumulation-window the WINDOW is the step:
                        # the earlier micro-grads are already gone, so the
                        # boundary must not apply a partial, mis-scaled sum
                        import warnings

                        if self._optimizer is not None:
                            self._optimizer.clear_grad()
                        for t in self._sparse_tables():
                            t.clear_pending()
                        nan_window = accumulate_grad_batches > 1 and not update
                        pending = False
                        from ..distributed.resilience import metrics as _rm

                        _rm.inc("skipped_steps")
                        warnings.warn(
                            f"fit: skipping non-finite step {gstep}: {e}",
                            RuntimeWarning, stacklevel=2)
                        cbks.on_batch_end(mode, step, logs)
                        if st is not None:
                            st.cancel()
                        if ckpt_ctx is not None:
                            ckpt_ctx["global_step"] = gstep + 1
                        step += 1
                        continue
                    if update and nan_window:
                        # the window contained a dropped step: discard the
                        # partial remainder instead of stepping on it
                        if self._optimizer is not None:
                            self._optimizer.clear_grad()
                        for t in self._sparse_tables():
                            t.clear_pending()
                        nan_window = False
                        pending = False
                        stepped = False
                    else:
                        pending = not update
                        stepped = update
                else:
                    outs = self.eval_batch(inputs, labels)
                if self._metrics and self._loss is not None:
                    loss_vals, metric_vals = outs
                elif self._loss is not None:
                    loss_vals, metric_vals = outs, None
                else:
                    loss_vals, metric_vals = None, outs
                if loss_vals:
                    logs["loss"] = loss_vals[0] if len(loss_vals) == 1 else loss_vals
                if metric_vals is not None:
                    names = [n for m in self._metrics for n in to_list(m.name())]
                    vals = to_list(metric_vals)
                    for n, v in zip(names, vals if len(vals) == len(names) else vals * len(names)):
                        logs[n] = v
                bsz = inputs[0].shape[0] if inputs and hasattr(inputs[0], "shape") else 1
                count += bsz
                logs["batch_size"] = bsz
                cbks.on_batch_end(mode, step, logs)
                if mode == "train" and ckpt_ctx is not None:
                    gs = ckpt_ctx["global_step"]
                    ckpt_ctx["global_step"] = gs + 1
                    # checkpoints only at UPDATE boundaries: a snapshot
                    # taken mid-accumulation-window would lose the window's
                    # accumulated grads (never part of the snapshot) and a
                    # resume could not reproduce the uninterrupted run. A
                    # preemption therefore drains up to k-1 more micro-steps
                    # before its final commit.
                    if stepped:
                        rz = _resilience()
                        if rz.preempted():
                            # SIGTERM landed: drain the lane, commit a final
                            # synchronous checkpoint, stop cleanly —
                            # resume() continues from exactly this step
                            ckpt_ctx["ck"].preempt_commit(
                                step=gs, epoch=ckpt_ctx.get("epoch"),
                                extra={"step_in_epoch": step,
                                       "epoch_rng": ckpt_ctx.get("epoch_rng")})
                            ckpt_ctx["preempted"] = True
                            # the preemption is CONSUMED by this commit — a
                            # later fit() in the same process starts fresh
                            rz.clear_preemption()
                            self.stop_training = True
                            break
                        if gs - ckpt_ctx.get("last_save", -1) \
                                >= ckpt_ctx["every"]:
                            # since-last-save cadence, not (gs+1)%every:
                            # with accumulation only boundary steps are
                            # eligible and the modulo could starve
                            ckpt_ctx["ck"].save_async(
                                step=gs, epoch=ckpt_ctx.get("epoch"),
                                extra={"step_in_epoch": step,
                                       "epoch_rng": ckpt_ctx.get("epoch_rng")})
                            ckpt_ctx["last_save"] = gs
            step += 1
        if nan_window:
            # epoch ended inside a poisoned window: drop its remainder
            if self._optimizer is not None:
                self._optimizer.clear_grad()
            for t in self._sparse_tables():
                t.clear_pending()
        if pending:
            # flush the trailing partial accumulation group
            if self._optimizer is not None:
                self._optimizer.step()
                self._optimizer.clear_grad()
            for t in self._sparse_tables():
                t.flush(update=True)
        for m in self._metrics:
            res = m.accumulate()
            for n, v in zip(to_list(m.name()), to_list(res)):
                logs[n] = v
        logs["samples"] = count
        return logs

    def summary(self, input_size=None, dtype=None):
        from .model_summary import summary

        sizes = input_size
        if sizes is None and self._inputs:
            sizes = [tuple(s.shape) for s in self._inputs]
        assert sizes is not None, "input_size must be given (no InputSpec provided)"
        return summary(self.network, sizes, dtypes=dtype)
