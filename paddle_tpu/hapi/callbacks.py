"""Training callbacks (reference: python/paddle/hapi/callbacks.py).

Same hook contract as the reference CallbackList: on_{train,eval,predict}_
{begin,end}, on_epoch_{begin,end}, on_{mode}_batch_{begin,end}. All state the
hooks read lives in ``callback.params`` (epochs/steps/metrics/verbose), set by
``config_callbacks`` exactly like the reference's.
"""
from __future__ import annotations

import numbers
import os
import time
import warnings

from .progressbar import ProgressBar


def config_callbacks(callbacks=None, model=None, batch_size=None, epochs=None,
                     steps=None, log_freq=2, verbose=2, save_freq=1,
                     save_dir=None, metrics=None, mode="train"):
    cbks = callbacks if callbacks is not None else []
    cbks = cbks if isinstance(cbks, (list, tuple)) else [cbks]
    if not any(isinstance(k, ProgBarLogger) for k in cbks) and verbose:
        cbks = [ProgBarLogger(log_freq, verbose=verbose)] + list(cbks)
    if not any(isinstance(k, ModelCheckpoint) for k in cbks):
        cbks = list(cbks) + [ModelCheckpoint(save_freq, save_dir)]
    if not any(isinstance(k, LRScheduler) for k in cbks):
        cbks = list(cbks) + [LRScheduler()]
    for k in cbks:
        if isinstance(k, EarlyStopping) and k.save_dir is None:
            k.save_dir = save_dir
    cbk_list = CallbackList(cbks)
    cbk_list.set_model(model)
    metrics = metrics or []
    params = {
        "batch_size": batch_size,
        "epochs": epochs,
        "steps": steps,
        "verbose": verbose,
        "metrics": metrics,
    }
    cbk_list.set_params(params)
    return cbk_list


class CallbackList:
    def __init__(self, callbacks=None):
        self.callbacks = [c for c in (callbacks or [])]
        self.params = {}
        self.model = None

    def append(self, callback):
        self.callbacks.append(callback)

    def __iter__(self):
        return iter(self.callbacks)

    def set_params(self, params):
        for c in self.callbacks:
            c.set_params(params)
        self.params = params

    def set_model(self, model):
        for c in self.callbacks:
            c.set_model(model)
        self.model = model

    def _call(self, name, *args):
        for c in self.callbacks:
            fn = getattr(c, name, None)
            if fn is not None:
                fn(*args)

    def on_begin(self, mode, logs=None):
        self._call(f"on_{mode}_begin", logs or {})

    def on_end(self, mode, logs=None):
        self._call(f"on_{mode}_end", logs or {})

    def on_epoch_begin(self, epoch, logs=None):
        self._call("on_epoch_begin", epoch, logs or {})

    def on_epoch_end(self, epoch, logs=None):
        self._call("on_epoch_end", epoch, logs or {})

    def on_batch_begin(self, mode, step, logs=None):
        self._call(f"on_{mode}_batch_begin", step, logs or {})

    def on_batch_end(self, mode, step, logs=None):
        self._call(f"on_{mode}_batch_end", step, logs or {})


class Callback:
    """Base class (reference callbacks.py:127)."""

    def __init__(self):
        self.model = None
        self.params = {}

    def set_params(self, params):
        self.params = params

    def set_model(self, model):
        self.model = model

    def on_train_begin(self, logs=None): pass
    def on_train_end(self, logs=None): pass
    def on_eval_begin(self, logs=None): pass
    def on_eval_end(self, logs=None): pass
    def on_predict_begin(self, logs=None): pass
    def on_predict_end(self, logs=None): pass
    def on_epoch_begin(self, epoch, logs=None): pass
    def on_epoch_end(self, epoch, logs=None): pass
    def on_train_batch_begin(self, step, logs=None): pass
    def on_train_batch_end(self, step, logs=None): pass
    def on_eval_batch_begin(self, step, logs=None): pass
    def on_eval_batch_end(self, step, logs=None): pass
    def on_predict_batch_begin(self, step, logs=None): pass
    def on_predict_batch_end(self, step, logs=None): pass


class ProgBarLogger(Callback):
    """Loss/metric console logger (reference callbacks.py:297)."""

    def __init__(self, log_freq=1, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose
        self.epochs = None
        self.steps = None

    def on_train_begin(self, logs=None):
        self.epochs = self.params.get("epochs")
        self.train_metrics = self.params.get("metrics", [])

    def on_epoch_begin(self, epoch, logs=None):
        self.steps = self.params.get("steps")
        self.epoch = epoch
        self.train_step = 0
        if self.epochs and self.verbose:
            print(f"Epoch {epoch + 1}/{self.epochs}")
        self.train_progbar = ProgressBar(num=self.steps, verbose=self.verbose)

    def _updates(self, logs, bar, step):
        values = [(k, logs[k]) for k in self.params.get("metrics", []) if k in logs]
        bar.update(step, values)

    def on_train_batch_end(self, step, logs=None):
        self.train_step += 1
        if self.verbose and self.train_step % self.log_freq == 0:
            self._updates(logs or {}, self.train_progbar, self.train_step)

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            self._updates(logs or {}, self.train_progbar, self.train_step)

    def on_eval_begin(self, logs=None):
        logs = logs or {}
        self.eval_steps = logs.get("steps")
        self.eval_step = 0
        if self.verbose:
            print("Eval begin...")
        self.eval_progbar = ProgressBar(num=self.eval_steps, verbose=self.verbose)

    def on_eval_batch_end(self, step, logs=None):
        self.eval_step += 1
        if self.verbose and self.eval_step % self.log_freq == 0:
            self._updates(logs or {}, self.eval_progbar, self.eval_step)

    def on_eval_end(self, logs=None):
        if self.verbose:
            self._updates(logs or {}, self.eval_progbar, self.eval_step)
            print("Eval samples: %d" % (logs or {}).get("samples", 0))

    def on_predict_begin(self, logs=None):
        logs = logs or {}
        self.test_steps = logs.get("steps")
        self.test_step = 0
        if self.verbose:
            print("Predict begin...")
        self.test_progbar = ProgressBar(num=self.test_steps, verbose=self.verbose)

    def on_predict_batch_end(self, step, logs=None):
        self.test_step += 1
        if self.verbose and self.test_step % self.log_freq == 0:
            self.test_progbar.update(self.test_step, [])

    def on_predict_end(self, logs=None):
        if self.verbose:
            self.test_progbar.update(self.test_step, [])
            print("Predict samples: %d" % (logs or {}).get("samples", 0))


class ModelCheckpoint(Callback):
    """Periodic save (reference callbacks.py:533)."""

    def __init__(self, save_freq=1, save_dir=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch

    def _is_save(self):
        return self.model is not None and self.save_dir is not None

    def on_epoch_end(self, epoch, logs=None):
        if self._is_save() and (self.epoch + 1) % self.save_freq == 0:
            path = os.path.join(self.save_dir, str(epoch))
            self.model.save(path)

    def on_train_end(self, logs=None):
        if self._is_save():
            self.model.save(os.path.join(self.save_dir, "final"))


class LRScheduler(Callback):
    """Steps the optimizer's LRScheduler (reference callbacks.py:598)."""

    def __init__(self, by_step=True, by_epoch=False):
        super().__init__()
        if by_step and by_epoch:
            raise ValueError("by_step and by_epoch are mutually exclusive")
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _step(self):
        from ..optimizer.lr import LRScheduler as Sched

        opt = getattr(self.model, "_optimizer", None)
        if opt is not None and isinstance(opt._learning_rate, Sched):
            opt._learning_rate.step()

    def on_epoch_end(self, epoch, logs=None):
        if self.by_epoch:
            self._step()

    def on_train_batch_end(self, step, logs=None):
        if self.by_step:
            self._step()


class EarlyStopping(Callback):
    """Stop when a monitored metric stops improving (reference callbacks.py:689)."""

    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.verbose = verbose
        self.baseline = baseline
        self.min_delta = abs(min_delta)
        self.wait_epoch = 0
        self.best_weights = None
        self.stopped_epoch = 0
        self.save_best_model = save_best_model
        self.save_dir = None
        if mode not in ("auto", "min", "max"):
            warnings.warn(f"EarlyStopping mode {mode} unknown, falling back to auto")
            mode = "auto"
        if mode == "min" or (mode == "auto" and "acc" not in self.monitor):
            self.monitor_op = lambda cur, best: cur < best - self.min_delta
            self.best_value = float("inf")
        else:
            self.monitor_op = lambda cur, best: cur > best + self.min_delta
            self.best_value = -float("inf")

    def on_train_begin(self, logs=None):
        self.wait_epoch = 0
        if self.baseline is not None:
            self.best_value = self.baseline

    def on_eval_end(self, logs=None):
        logs = logs or {}
        self.stopped_epoch += 1  # evals happen once per epoch under fit()
        if self.monitor not in logs:
            warnings.warn(f"Monitor of EarlyStopping should be loss or metric name; "
                          f"{self.monitor} missing from eval logs")
            return
        current = logs[self.monitor]
        if isinstance(current, (list, tuple)):
            current = current[0]
        if isinstance(current, numbers.Number):
            if self.monitor_op(current, self.best_value):
                self.best_value = current
                self.wait_epoch = 0
                if self.save_best_model and self.save_dir is not None:
                    self.model.save(os.path.join(self.save_dir, "best_model"))
            else:
                self.wait_epoch += 1
            if self.wait_epoch > self.patience:
                self.model.stop_training = True
                if self.verbose:
                    print(f"Epoch {self.stopped_epoch}: Early stopping.")


class ReduceLROnPlateau(Callback):
    """Reduce LR when a metric plateaus (reference callbacks.py:958)."""

    def __init__(self, monitor="loss", factor=0.1, patience=10, verbose=1,
                 mode="auto", min_delta=1e-4, cooldown=0, min_lr=0):
        super().__init__()
        self.monitor = monitor
        if factor >= 1.0:
            raise ValueError("ReduceLROnPlateau does not support a factor >= 1.0")
        self.factor = factor
        self.patience = patience
        self.verbose = verbose
        self.min_delta = min_delta
        self.cooldown = cooldown
        self.cooldown_counter = 0
        self.min_lr = min_lr
        self.wait = 0
        if mode == "min" or (mode == "auto" and "acc" not in self.monitor):
            self.monitor_op = lambda a, b: a < b - self.min_delta
            self.best = float("inf")
        else:
            self.monitor_op = lambda a, b: a > b + self.min_delta
            self.best = -float("inf")

    def in_cooldown(self):
        return self.cooldown_counter > 0

    def on_eval_end(self, logs=None):
        logs = logs or {}
        current = logs.get(self.monitor)
        if current is None:
            warnings.warn(f"ReduceLROnPlateau monitor {self.monitor} missing from logs")
            return
        if isinstance(current, (list, tuple)):
            current = current[0]
        if self.in_cooldown():
            self.cooldown_counter -= 1
            self.wait = 0
        if self.monitor_op(current, self.best):
            self.best = current
            self.wait = 0
        elif not self.in_cooldown():
            self.wait += 1
            if self.wait >= self.patience:
                opt = getattr(self.model, "_optimizer", None)
                if opt is None:
                    return
                from ..optimizer.lr import LRScheduler as Sched

                if isinstance(opt._learning_rate, Sched):
                    warnings.warn("ReduceLROnPlateau needs a float lr, found scheduler")
                    return
                old_lr = opt.get_lr()
                new_lr = max(old_lr * self.factor, self.min_lr)
                if old_lr - new_lr > 1e-12:
                    opt.set_lr(new_lr)
                    if self.verbose:
                        print(f"ReduceLROnPlateau: reducing learning rate to {new_lr}.")
                self.cooldown_counter = self.cooldown
                self.wait = 0


class VisualDL(Callback):
    """Scalar logging (reference callbacks.py:843). Writes a plain JSONL log
    (the VisualDL wire format needs the visualdl package, not in this image)."""

    def __init__(self, log_dir):
        super().__init__()
        self.log_dir = log_dir
        self._fh = None

    def _write(self, mode, step, logs):
        import json

        if self._fh is None:
            os.makedirs(self.log_dir, exist_ok=True)
            self._fh = open(os.path.join(self.log_dir, "scalars.jsonl"), "a")
        record = {"mode": mode, "step": step}
        for k, v in (logs or {}).items():
            if isinstance(v, (list, tuple)) and v and isinstance(v[0], numbers.Number):
                record[k] = float(v[0])
            elif isinstance(v, numbers.Number):
                record[k] = float(v)
        self._fh.write(json.dumps(record) + "\n")
        self._fh.flush()

    def on_train_batch_end(self, step, logs=None):
        self._write("train", step, logs)

    def on_eval_end(self, logs=None):
        self._write("eval", 0, logs)

    def on_train_end(self, logs=None):
        if self._fh is not None:
            self._fh.close()
            self._fh = None
