"""paddle.profiler: host-span tracing + device (XPlane) capture + summaries.

Reference: python/paddle/profiler/profiler.py:224 (Profiler with scheduler
states CLOSED/READY/RECORD/RECORD_AND_RETURN), platform/profiler/host_tracer.cc
(RecordEvent spans into lock-free per-thread buffers), chrometracing_logger.cc
(chrome-trace export), profiler_statistic.py (op summary tables).

TPU-native split: device-side timing belongs to XLA — when ``timer_only`` is
False and a trace dir is set, the Profiler drives ``jax.profiler`` so traces
carry real TPU timelines (XPlane, viewable in TensorBoard/Perfetto). Host-side
``RecordEvent`` spans (op dispatch, dataloader, user scopes) are recorded in a
process-global buffer and exported as chrome-trace JSON; summaries aggregate
those spans per op name. Under FLAGS_benchmark each dispatched op blocks until
the device result is ready, so host spans become true op timings.
"""
from __future__ import annotations

import json
import os
import threading
import time
from enum import Enum
from typing import Callable, Iterable, Optional

__all__ = [
    "ProfilerState", "ProfilerTarget", "RecordEvent", "Profiler",
    "make_scheduler", "export_chrome_tracing", "load_profiler_result",
]


class ProfilerState(Enum):
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


class ProfilerTarget(Enum):
    CPU = 0
    GPU = 1
    TPU = 2
    CUSTOM_DEVICE = 3


class _HostEventRecorder:
    """Process-global span buffer (host_event_recorder.h equivalent)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.events = []  # (name, tid, start_us, dur_us, category)
        self.active = False

    def record(self, name, start_us, dur_us, category):
        if not self.active:
            return
        tid = threading.get_ident() & 0xFFFF
        with self._lock:
            self.events.append((name, tid, start_us, dur_us, category))

    def drain(self):
        with self._lock:
            ev, self.events = self.events, []
        return ev


_RECORDER = _HostEventRecorder()


def _now_us() -> float:
    return time.perf_counter() * 1e6


class RecordEvent:
    """User-instrumented span (platform/profiler/event_tracing.h RecordEvent).

    Usable as a context manager or begin()/end() pair::

        with profiler.RecordEvent("data_augment"):
            ...
    """

    def __init__(self, name: str, event_type: str = "UserDefined"):
        self.name = name
        self.event_type = event_type
        self._t0 = None

    def begin(self):
        self._t0 = _now_us()

    def end(self):
        if self._t0 is not None:
            _RECORDER.record(self.name, self._t0, _now_us() - self._t0, self.event_type)
            self._t0 = None

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()
        return False


def record_op_span(name: str, t0_us: float):
    """Called by core.dispatch per op while a profiler is recording."""
    _RECORDER.record(name, t0_us, _now_us() - t0_us, "Operator")


def is_recording() -> bool:
    return _RECORDER.active


def make_scheduler(*, closed: int, ready: int, record: int, repeat: int = 0,
                   skip_first: int = 0) -> Callable[[int], ProfilerState]:
    """Step-state scheduler (profiler.py make_scheduler, same state machine)."""
    period = closed + ready + record

    def schedule(step: int) -> ProfilerState:
        if step < skip_first:
            return ProfilerState.CLOSED
        s = step - skip_first
        if repeat and s >= repeat * period:
            return ProfilerState.CLOSED
        pos = s % period
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        return (ProfilerState.RECORD_AND_RETURN if pos == period - 1
                else ProfilerState.RECORD)

    return schedule


def export_chrome_tracing(dir_name: str, worker_name: Optional[str] = None):
    """on_trace_ready callback writing chrome-trace JSON (chrometracing_logger.cc)."""

    def handler(prof: "Profiler"):
        os.makedirs(dir_name, exist_ok=True)
        name = worker_name or f"host_{os.getpid()}"
        path = os.path.join(dir_name, f"{name}_time_{int(time.time())}.paddle_trace.json")
        prof._export_chrome(path)
        return path

    return handler


def load_profiler_result(path: str):
    with open(path) as f:
        return json.load(f)


class _OpSummary:
    __slots__ = ("calls", "total_us", "max_us", "min_us")

    def __init__(self):
        self.calls = 0
        self.total_us = 0.0
        self.max_us = 0.0
        self.min_us = float("inf")

    def add(self, dur):
        self.calls += 1
        self.total_us += dur
        self.max_us = max(self.max_us, dur)
        self.min_us = min(self.min_us, dur)


class Profiler:
    """paddle.profiler.Profiler (profiler.py:224) over host spans + jax.profiler.

    ``targets`` selects device capture: if ProfilerTarget.TPU (or GPU) is
    requested and ``trace_dir`` given (or an on_trace_ready from
    export_chrome_tracing), jax.profiler.start_trace captures XPlane device
    timelines alongside the host spans.
    """

    def __init__(self, *, targets: Optional[Iterable[ProfilerTarget]] = None,
                 scheduler=None, on_trace_ready=None, timer_only: bool = False,
                 trace_dir: Optional[str] = None):
        self.targets = set(targets) if targets else {ProfilerTarget.CPU, ProfilerTarget.TPU}
        if scheduler is None:
            self.scheduler = lambda step: ProfilerState.RECORD
        elif isinstance(scheduler, (tuple, list)):
            lo, hi = scheduler
            self.scheduler = make_scheduler(closed=lo, ready=0, record=hi - lo, repeat=1)
        else:
            self.scheduler = scheduler
        self.on_trace_ready = on_trace_ready
        self.timer_only = timer_only
        self.trace_dir = trace_dir
        self.step_num = 0
        self.state = ProfilerState.CLOSED
        self._events = []
        self._jax_tracing = False
        self._t_start = None

    # -- lifecycle ----------------------------------------------------------
    def start(self):
        self.state = self.scheduler(self.step_num)
        self._t_start = time.perf_counter()
        if self.state in (ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN):
            self._begin_record()
        return self

    def stop(self):
        if _RECORDER.active:
            self._events.extend(_RECORDER.drain())
            _RECORDER.active = False
        self._stop_jax()
        if self.state in (ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN):
            # RECORD_AND_RETURN already delivered this cycle's events in step()
            if self.on_trace_ready and self._events:
                self.on_trace_ready(self)
                self._events = []
        self.state = ProfilerState.CLOSED

    def step(self, num_samples: Optional[int] = None):
        """Advance the scheduler one training step."""
        if _RECORDER.active:
            self._events.extend(_RECORDER.drain())
        prev = self.state
        self.step_num += 1
        self.state = self.scheduler(self.step_num)
        recording = prev in (ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN)
        should = self.state in (ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN)
        if prev == ProfilerState.RECORD_AND_RETURN and self.on_trace_ready:
            self.on_trace_ready(self)
            self._events = []  # fresh buffer per cycle (repeat>1 schedulers)
        if should and not recording:
            self._begin_record()
        elif recording and not should:
            _RECORDER.active = False
            self._stop_jax()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    def _begin_record(self):
        from ..framework.flags import flag

        if flag("profiler_host_spans"):
            _RECORDER.active = True
        if not self.timer_only and self.trace_dir and not self._jax_tracing:
            try:
                import jax

                jax.profiler.start_trace(self.trace_dir)
                self._jax_tracing = True
            except Exception:
                self._jax_tracing = False

    def _stop_jax(self):
        if self._jax_tracing:
            try:
                import jax

                jax.profiler.stop_trace()
            except Exception:
                pass
            self._jax_tracing = False

    # -- reporting ----------------------------------------------------------
    def _export_chrome(self, path: str):
        trace = {"traceEvents": [
            {"name": n, "ph": "X", "ts": ts, "dur": dur, "pid": os.getpid(),
             "tid": tid, "cat": cat}
            for (n, tid, ts, dur, cat) in self._events
        ]}
        with open(path, "w") as f:
            json.dump(trace, f)
        return path

    def export(self, path: str, format: str = "json"):
        return self._export_chrome(path)

    def summary(self, sorted_by: str = "total", op_detail: bool = True,
                thread_sep: bool = False, time_unit: str = "ms") -> str:
        """Per-op aggregate table (profiler_statistic.py equivalent)."""
        agg = {}
        for (name, _tid, _ts, dur, cat) in self._events:
            agg.setdefault((cat, name), _OpSummary()).add(dur)
        div = {"s": 1e6, "ms": 1e3, "us": 1.0}[time_unit]
        rows = sorted(agg.items(), key=lambda kv: -kv[1].total_us)
        total = sum(s.total_us for _, s in rows) or 1.0
        lines = [
            f"{'Name':<40}{'Calls':>8}{'Total(' + time_unit + ')':>14}"
            f"{'Avg(' + time_unit + ')':>12}{'Max(' + time_unit + ')':>12}{'Ratio%':>8}",
            "-" * 94,
        ]
        for (cat, name), s in rows:
            lines.append(
                f"{name[:39]:<40}{s.calls:>8}{s.total_us / div:>14.3f}"
                f"{s.total_us / s.calls / div:>12.3f}{s.max_us / div:>12.3f}"
                f"{100.0 * s.total_us / total:>8.2f}")
        return "\n".join(lines)

    @property
    def events(self):
        return list(self._events)
