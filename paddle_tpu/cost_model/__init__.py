"""paddle.cost_model — program cost measurement.

Reference: python/paddle/cost_model/cost_model.py:23 (CostModel:
profile_measure runs a static program under the profiler and returns
per-op cost data; static_cost_data serves a pre-benchmarked op table).
TPU-native mapping: a static Program replays through the jit cache, so
profile_measure times a real Executor.run under the profiler and reports
wall time + the op-span table; static op costs come from the analytic
step-time model the auto-parallel planner uses (flops/bytes over
device peaks) instead of a shipped GPU benchmark JSON.
"""
from __future__ import annotations

import time
from typing import Dict, List, Optional

from . import comm  # noqa: F401
from . import embedding  # noqa: F401  (streamed-table traffic term)
from .comm import LinkModel, link_model_for, calibrate_from_counters  # noqa: F401

__all__ = ["CostModel", "comm", "embedding", "LinkModel", "link_model_for",
           "calibrate_from_counters"]


class CostModel:
    """reference cost_model.py:23."""

    def __init__(self):
        self._static_cost_data: Optional[dict] = None

    def build_program(self):
        """The reference's demo program: fc + mean under static mode."""
        import paddle_tpu as paddle
        import paddle_tpu.static as static

        paddle.enable_static()
        main_program = static.Program()
        startup_program = static.Program()
        with static.program_guard(main_program, startup_program):
            data = static.data(name="X", shape=[None, 1], dtype="float32")
            hidden = paddle.nn.Linear(1, 10)(data)
            loss = hidden.mean()
            paddle.optimizer.SGD(learning_rate=0.01).minimize(loss)
        return startup_program, main_program

    def profile_measure(self, startup_program, main_program,
                        device: str = "tpu",
                        fetch_cost_list: List[str] = ("time",),
                        feed: Optional[Dict] = None,
                        warmup: int = 1, iters: int = 3) -> dict:
        """Run the program under the profiler; returns {'time': ms,
        'op_table': [...]} (the ProfileMeasure role). `feed` defaults to
        the build_program demo feed."""
        import numpy as np

        import paddle_tpu.static as static
        from paddle_tpu import profiler as prof_mod

        exe = static.Executor()
        exe.run(startup_program)
        if feed is None:
            feed = {"X": np.random.random((10, 1)).astype("float32")}
        for _ in range(max(warmup, 0)):
            exe.run(main_program, feed=feed, fetch_list=[])
        prof = prof_mod.Profiler()
        prof.start()
        t0 = time.perf_counter()
        for _ in range(max(iters, 1)):
            exe.run(main_program, feed=feed, fetch_list=[])
        dt_ms = (time.perf_counter() - t0) / max(iters, 1) * 1e3
        prof.stop()
        out = {"time": dt_ms}
        try:
            summary = prof.summary()
            out["op_table"] = summary if isinstance(summary, list) else \
                getattr(summary, "rows", summary)
        except Exception as e:  # profiling detail must not sink the measure
            out["op_table_error"] = str(e)[:200]
        return out

    # -- static (analytic) op costs ------------------------------------------
    def static_cost_data(self) -> dict:
        """Analytic per-op cost table (the static_op_benchmark.json role):
        flops/bytes formulas evaluated at a reference shape on this
        device's peaks, for the ops the planner's step-time model knows."""
        if self._static_cost_data is None:
            from ..distributed.auto_parallel.engine import (
                _ICI_BYTES_PER_S, _PEAK_FLOPS)

            n, h = 4096, 4096  # reference shape: [n,h]x[h,h]
            matmul_ms = 2 * n * h * h / _PEAK_FLOPS * 1e3
            ew_ms = n * h * 2 * 2 / 8.1e11 * 1e3  # r+w bf16 at HBM bw
            self._static_cost_data = {
                "device": "tpu-v5e",
                "peak_flops": _PEAK_FLOPS,
                "ici_bytes_per_s": _ICI_BYTES_PER_S,
                "ops": {
                    "matmul": {"forward_ms": matmul_ms,
                               "backward_ms": 2 * matmul_ms},
                    "elementwise_add": {"forward_ms": ew_ms,
                                        "backward_ms": ew_ms},
                    "relu": {"forward_ms": ew_ms, "backward_ms": ew_ms},
                    "softmax": {"forward_ms": 3 * ew_ms,
                                "backward_ms": 3 * ew_ms},
                },
            }
        return self._static_cost_data

    # bf16 peak FLOPS + HBM stream bandwidth per chip generation
    DEVICE_PEAKS = {
        "tpu-v4": (275e12, 1.2e12),
        "tpu-v5e": (197e12, 8.1e11),
        "tpu-v5p": (459e12, 2.765e12),
        "tpu-v6e": (918e12, 1.6e12),
    }

    # -- static whole-program costs (paddle_tpu.analysis backed) -------------
    def static_program_cost(self, target, *args,
                            device: str = "tpu-v5e") -> dict:
        """Whole-program analytic cost WITHOUT running it: capture `target`
        (callable / jit.TrainStep / static Program, with example inputs)
        through paddle_tpu.analysis and price its op-graph on `device`'s
        peaks (see DEVICE_PEAKS). Returns flops/bytes/est_ms plus the
        peak-HBM estimate — the reference CostModel's static half, finally
        with real content."""
        from .. import analysis as A
        from ..distributed.auto_parallel.engine import _ICI_BYTES_PER_S

        if device not in self.DEVICE_PEAKS:
            raise KeyError(f"unknown device {device!r}; known: "
                           f"{sorted(self.DEVICE_PEAKS)}")
        peak_flops, hbm_bw = self.DEVICE_PEAKS[device]
        prog = A.capture(target, *args)
        est = A.estimate_peak(prog)
        flops = prog.total_flops()
        bytes_moved = prog.total_bytes()
        compute_ms = flops / peak_flops * 1e3
        memory_ms = bytes_moved / hbm_bw * 1e3
        return {
            "device": device,
            "num_eqns": len(prog.nodes),
            "total_flops": flops,
            "total_bytes": bytes_moved,
            "compute_ms": compute_ms,
            "memory_ms": memory_ms,
            "est_step_ms": max(compute_ms, memory_ms),  # roofline
            "arithmetic_intensity": flops / max(bytes_moved, 1),
            "peak_hbm_bytes": est.peak_bytes,
            "peak_hbm_gb": round(est.peak_gb, 3),
            "ici_bytes_per_s": _ICI_BYTES_PER_S,
            "top_ops": prog.summary()["top_ops"],
        }

    def static_memory_estimate(self, target, *args) -> dict:
        """Peak-HBM live-range estimate for `target` (analysis.memory)."""
        from .. import analysis as A

        return A.estimate_peak(A.capture(target, *args)).to_dict()

    def plan_parallel(self, model, n_devices=None, hbm_bytes=None,
                      batch: int = 8, seq: int = 128, **kw):
        """The auto-parallel planner through the CostModel surface
        (reference cost_model.py serves the planner; ours delegates to
        ``distributed.auto_parallel.plan`` — same cost tables, see
        ``cost_model.comm``)."""
        from ..distributed.auto_parallel.planner import plan

        return plan(model, n_devices=n_devices, hbm_bytes=hbm_bytes,
                    batch=batch, seq=seq, **kw)

    def get_static_op_time(self, op_name: str, forward: bool = True,
                           dtype: str = "float32") -> dict:
        if not op_name:
            raise ValueError("op_name should not be empty")
        data = self.static_cost_data()["ops"]
        if op_name not in data:
            raise KeyError(
                f"no static cost entry for {op_name!r}; known: "
                f"{sorted(data)} (extend static_cost_data or use "
                f"profile_measure for real timings)")
        key = "forward_ms" if forward else "backward_ms"
        return {"op_time_ms": data[op_name][key], "dtype": dtype}
