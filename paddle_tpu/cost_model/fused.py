"""Per-op fused-kernel cost entries for the auto-parallel planner.

The ``kernels/pallas`` layer changes the compute term of a candidate:
fused RMSNorm/RoPE remove whole HBM round-trips of the activation
stream, and the fused MoE dispatch cuts the measured ``dispatch_share``
of the MoE MLP. ``plan()`` must see those deltas or it will keep ranking
configs by the composed-path cost and mis-order candidates whose
bottleneck a fusion removes — these entries are what make the kernel
layer a *system* input rather than a local speedup.

Each entry models one op's saving as bytes-not-moved (normalized to HBM
stream time) or as a fraction of the MoE compute term, with constants
seeded from this repo's measurements (BENCH r04 ``dispatch_share``
0.148; the fused target 0.06) and overridable by a persisted calibration
profile (``cost_model.comm.save_calibration`` stores measured
fused-vs-composed per-op times from the bench A/B next to the link
tables, keyed by (topology, jax version)).

``fused_gain_s(profile, cfg, link, ops)`` returns the predicted seconds
saved per step for the enabled op set — ``score_config`` subtracts it
and records the per-op breakdown, so enabling fused entries visibly
re-ranks (or at minimum re-prices) candidates: the ci.sh kernels gate
asserts exactly that.
"""
from __future__ import annotations

import math
from dataclasses import dataclass, replace
from typing import Any, Dict, Iterable, Optional, Tuple

__all__ = ["FusedOpEntry", "FUSED_OP_ENTRIES", "fused_entries",
           "fused_gain_s", "enabled_fused_ops"]


@dataclass(frozen=True)
class FusedOpEntry:
    """One fused op's cost-delta model.

    ``hbm_passes_saved``: activation-stream round-trips removed per
    application, fwd+bwd combined (an elementwise op reads + writes the
    tensor once per pass; the composed forms add reduction re-reads,
    table reads and concat writes the fusion eliminates).
    ``applications_per_layer``: how many times the op runs per decoder
    layer. ``act_scale``: the op's operand size relative to the [b, s, h]
    hidden block. MoE dispatch is modeled separately via the measured
    ``dispatch_share`` pair; serving-only ops carry zero train-step gain.
    """

    name: str
    hbm_passes_saved: float = 0.0
    applications_per_layer: float = 0.0
    act_scale: float = 1.0
    # MoE dispatch model: fraction of the MoE MLP spent on routing/
    # dispatch, composed vs fused (BENCH r04 measured vs fused target;
    # calibrated from the bench A/B when a profile is persisted)
    dispatch_share_composed: float = 0.0
    dispatch_share_fused: float = 0.0
    train_step: bool = True  # False: serving-side only, no train gain
    note: str = ""

    def override(self, **kw) -> "FusedOpEntry":
        return replace(self, **kw)


FUSED_OP_ENTRIES: Dict[str, FusedOpEntry] = {
    # 2 norms/layer; composed RMSNorm reads the row for the mean-square
    # reduction and again for the normalize (fwd), and the backward
    # re-reads twice more; the residual variant also folds the separate
    # add's round-trip in. ~3 round-trips saved per application fwd+bwd.
    "rms_norm": FusedOpEntry(
        "rms_norm", hbm_passes_saved=3.0, applications_per_layer=2.0,
        note="reduction re-read + normalize pass + residual-add fold"),
    # q and k per layer (~1 + kv/heads of a hidden block); the composed
    # form materializes cos/sin tables and a concat intermediate.
    "rope": FusedOpEntry(
        "rope", hbm_passes_saved=2.0, applications_per_layer=1.5,
        note="cos/sin table + rotate-half concat intermediates"),
    "moe_dispatch": FusedOpEntry(
        "moe_dispatch", dispatch_share_composed=0.148,
        dispatch_share_fused=0.06,
        note="BENCH r04 dispatch_share 0.148 -> fused routing kernel + "
             "scalar-prefetch gathers"),
    "paged_attention": FusedOpEntry(
        "paged_attention", train_step=False,
        note="serving decode only — priced by the serving A/B, not the "
             "train-step planner"),
}

# fraction of a MoE model's compute term spent in the expert-MLP stack
# (the r04 probe shapes: expert FFN ≈ attention+embed+head at top-2 with
# per-expert FFNs smaller than dense) — the dispatch share applies to it
_MOE_MLP_COMPUTE_FRAC = 0.55


def fused_entries(topology: Optional[str] = None) -> Dict[str, FusedOpEntry]:
    """The entry table, with any persisted calibration overrides for this
    (topology, jax version) merged in — under the same
    ``PT_LINK_CALIBRATION=1`` opt-in as the link tables (the bench writes
    profiles unconditionally; consuming them must stay armed explicitly
    so CI ranking assertions remain deterministic)."""
    table = dict(FUSED_OP_ENTRIES)
    import os

    if os.environ.get("PT_LINK_CALIBRATION", "0") != "1":
        return table
    try:
        from .comm import load_calibration

        prof = load_calibration(topology)
        for name, kw in ((prof or {}).get("fused") or {}).items():
            if name in table and isinstance(kw, dict):
                safe = {k: float(v) for k, v in kw.items()
                        if k in ("hbm_passes_saved",
                                 "applications_per_layer", "act_scale",
                                 "dispatch_share_composed",
                                 "dispatch_share_fused")}
                table[name] = table[name].override(**safe)
    except Exception:
        pass
    return table


def enabled_fused_ops() -> Tuple[str, ...]:
    """The ops the live kernel registry would actually engage (the
    planner's default when the caller does not pin a set)."""
    try:
        from ..kernels.registry import enabled_ops, registry

        registry()  # make sure the builtin library is registered
        return enabled_ops()
    except Exception:
        return ()


def fused_gain_s(profile, cfg: Dict[str, Any], link,
                 ops: Optional[Iterable[str]] = None,
                 entries: Optional[Dict[str, FusedOpEntry]] = None,
                 compute_s: float = 0.0
                 ) -> Tuple[float, Dict[str, float]]:
    """Predicted seconds-per-step saved by the enabled fused ops for ONE
    candidate config. ``profile`` is the planner ``ModelProfile``;
    ``compute_s`` is the candidate's priced compute term (the MoE
    dispatch share applies to it)."""
    if ops is None:
        ops = enabled_fused_ops()
    ops = set(ops)
    if not ops:
        return 0.0, {}
    entries = entries or fused_entries(getattr(link, "name", None))
    mesh = cfg.get("mesh", {})
    data = mesh.get("dp", 1) * mesh.get("sharding", 1)
    shard = max(data * mesh.get("cp", 1) * mesh.get("pp", 1), 1)
    layers = max(profile.num_layers, 1)
    # one [b, s, h] hidden block's bytes on this candidate's shard —
    # sqrt(mp) matches the planner's own activation model (the residual
    # stream is replicated over mp, the fat intermediates sharded)
    act_block = (profile.batch * profile.seq * max(profile.hidden, 1) *
                 profile.dtype_size) / shard / \
        math.sqrt(max(mesh.get("mp", 1), 1))
    bwd_factor = 4.0 / 3.0 if cfg.get("remat") else 1.0  # recompute re-runs
    per_op: Dict[str, float] = {}
    for name in sorted(ops):
        ent = entries.get(name)
        if ent is None or not ent.train_step:
            continue
        if name == "moe_dispatch":
            if profile.num_experts <= 1:
                continue
            s_c, s_f = ent.dispatch_share_composed, ent.dispatch_share_fused
            moe_s = compute_s * _MOE_MLP_COMPUTE_FRAC
            # composed pays dispatch on top of the FFN: t = ffn/(1-share)
            gain = moe_s * (1.0 / max(1.0 - s_c, 1e-3) -
                            1.0 / max(1.0 - s_f, 1e-3))
        else:
            bytes_saved = (ent.hbm_passes_saved *
                           ent.applications_per_layer * ent.act_scale *
                           act_block * layers * bwd_factor)
            gain = bytes_saved / link.hbm_bytes_per_s
        if gain > 0:
            per_op[name] = gain
    return sum(per_op.values()), per_op
