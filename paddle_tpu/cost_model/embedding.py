"""Streamed-embedding traffic as a planner cost term.

A ``sparse.ShardedEmbeddingTable`` moves its per-batch MISS rows over the
host link every step (PR-5 ``StreamLane``), partially hidden behind
compute by the cross-step prefetch. A candidate config that shrinks
compute below the exposed miss-transfer time gains nothing from more
chips — the planner must price the table traffic or it will keep ranking
recsys configs by compute alone (the same argument that put the offload
stream and the fused kernels into ``plan()``).

The model: expected streamed bytes per step =
``unique_ids_per_step * (1 - hit_rate) * dim * 4``, with ``hit_rate``
taken from the table's LIVE counters once traffic has flowed (every
bench round is a calibration round) and a conservative default before
that. Exposed seconds = bytes / host link bandwidth x (1 - the link's
measured hidden fraction) — the same shape as the offload term.
"""
from __future__ import annotations

from typing import Any, List, Optional

__all__ = ["DEFAULT_MISS_RATE", "expected_stream_bytes", "embed_stream_s",
           "table_rows"]

#: before a table has served traffic, assume the zipf-ish default: the
#: hot cache absorbs ~80% of unique rows (the bench acceptance floor)
DEFAULT_MISS_RATE = 0.2


def table_rows(model) -> List[Any]:
    """The ShardedEmbeddingTables reachable from ``model`` (empty for
    dense models — the term then prices to zero)."""
    try:
        from ..sparse.embedding import sparse_tables

        return sparse_tables(model)
    except Exception:
        return []


def expected_stream_bytes(model, batch: int, seq: int,
                          miss_rate: Optional[float] = None) -> int:
    """Expected per-step miss-row bytes across every sparse table in
    ``model`` at (batch, seq) ids per step."""
    total = 0
    ids_per_step = max(int(batch), 1) * max(int(seq), 1)
    for t in table_rows(model):
        if miss_rate is None:
            st = t.stats()
            seen = st["hit_rows"] + st["miss_rows"]
            mr = (1.0 - st["hit_rate"]) if seen else DEFAULT_MISS_RATE
        else:
            mr = float(miss_rate)
        uniq = min(ids_per_step, int(t.num_rows))
        total += int(uniq * mr * t.dim * 4)
    return total


def embed_stream_s(nbytes: int, link) -> float:
    """Exposed seconds of miss-row streaming per step on ``link`` (the
    prefetch hides ``host_hidden_frac`` of it, same as the offload
    stream's model)."""
    if nbytes <= 0:
        return 0.0
    return float(nbytes) / link.host_bytes_per_s * \
        (1.0 - link.host_hidden_frac)
