"""Per-link communication cost tables for the auto-parallel planner.

Reference role: python/paddle/distributed/auto_parallel/cost_model.py —
the reference prices candidate distributed programs with a measured
per-op/per-link cost table before picking a plan. TPU-native mapping:
collectives are XLA ops over ICI (or host memcpy on the CPU test mesh),
so a topology is priced by four numbers — peak matmul FLOPS, link
bandwidth, per-collective launch latency, and per-executable dispatch
overhead — plus the host-link bandwidth the offload executor streams
through. The seeds below come from this repo's own measurements:

- ``tpu-v5e``: the BENCH hbm_envelope rounds (197 TFLOP/s bf16 peak,
  ~90 GB/s per-direction ICI ring) and the PR-5 ``stream_capacity``
  legs (effective host link ~2 GB/s through the axon tunnel);
- ``cpu-host``: the 8-device ``--xla_force_host_platform_device_count``
  dryrun mesh (MULTICHIP_r05) — "links" are memcpys between thread
  shards, cheap on bytes but expensive per collective (every extra
  partitioned op pays SPMD overhead on an oversubscribed host).

Tables are overridable per call (``LinkModel(**overrides)``) and
re-calibratable from the live PR-4 collective byte/call counters plus
XPlane device timings (``calibrate_from_counters``).
"""
from __future__ import annotations

from dataclasses import dataclass, replace, asdict
from typing import Dict, Optional

__all__ = ["LinkModel", "LINK_TABLES", "link_model_for", "ring_factor",
           "reduce_scatter_factor", "all_to_all_factor",
           "all_gather_factor", "calibrate_from_counters"]


@dataclass(frozen=True)
class LinkModel:
    """One topology's cost constants (everything the step-time model
    multiplies bytes/flops by)."""

    name: str = "tpu-v5e"
    peak_flops: float = 197e12        # per-device matmul peak (model dtype)
    hbm_bytes_per_s: float = 8.1e11   # device memory stream bandwidth
    ici_bytes_per_s: float = 9e10     # per-direction inter-device link
    coll_latency_s: float = 1e-5      # per-collective launch/sync charge
    dispatch_s: float = 1e-4          # per-executable host dispatch charge
    host_bytes_per_s: float = 2e9     # host<->device offload stream link
    host_hidden_frac: float = 0.6     # offload transfer fraction the
    # double-buffered lane hides behind the group updates (PR-5 measured
    # overlap_efficiency ~0.23-0.54 CPU, higher on real DMA links)

    def to_dict(self) -> Dict[str, float]:
        return asdict(self)

    def override(self, **kw) -> "LinkModel":
        return replace(self, **kw)


LINK_TABLES: Dict[str, LinkModel] = {
    "tpu-v4": LinkModel("tpu-v4", peak_flops=275e12,
                        hbm_bytes_per_s=1.2e12),
    "tpu-v5e": LinkModel("tpu-v5e"),
    "tpu-v5p": LinkModel("tpu-v5p", peak_flops=459e12,
                         hbm_bytes_per_s=2.765e12,
                         ici_bytes_per_s=2e11),
    "tpu-v6e": LinkModel("tpu-v6e", peak_flops=918e12,
                         hbm_bytes_per_s=1.6e12),
    # the 8-device CPU host mesh every dryrun/CI leg runs on: bytes are
    # cheap (shared memory), partitioned-op overhead is what ranks configs
    "cpu-host": LinkModel("cpu-host", peak_flops=2e10,
                          hbm_bytes_per_s=2e10, ici_bytes_per_s=8e9,
                          coll_latency_s=5e-5, dispatch_s=3e-4,
                          host_bytes_per_s=2e9, host_hidden_frac=0.35),
}


def link_model_for(topology: Optional[str] = None, **overrides) -> LinkModel:
    """Resolve a LinkModel: explicit topology name, else the live jax
    backend (CPU test meshes -> "cpu-host", TPU kinds by generation)."""
    if topology is None:
        try:
            import jax

            dev = jax.devices()[0]
            if dev.platform == "cpu":
                topology = "cpu-host"
            else:
                kind = getattr(dev, "device_kind", "").lower()
                topology = next((k for k in LINK_TABLES
                                 if k.startswith("tpu")
                                 and k.split("-")[1] in kind), "tpu-v5e")
        except Exception:
            topology = "tpu-v5e"
    base = LINK_TABLES.get(topology)
    if base is None:
        raise KeyError(f"unknown topology {topology!r}; known: "
                       f"{sorted(LINK_TABLES)} (or pass overrides on one)")
    return base.override(**overrides) if overrides else base


# -- bytes-on-wire multipliers ------------------------------------------------

def ring_factor(n: int) -> float:
    """Ring all-reduce: each rank moves 2(n-1)/n of the payload."""
    return 2.0 * (n - 1) / n if n > 1 else 0.0


def reduce_scatter_factor(n: int) -> float:
    """Reduce-scatter (the os_g grad path): half an all-reduce."""
    return (n - 1) / n if n > 1 else 0.0


def all_to_all_factor(n: int) -> float:
    """All-to-all (MoE dispatch/combine): (n-1)/n of the payload leaves
    this rank."""
    return (n - 1) / n if n > 1 else 0.0


def all_gather_factor(n: int) -> float:
    """Ring all-gather (ZeRO param materialization): each rank receives
    (n-1)/n of the payload."""
    return (n - 1) / n if n > 1 else 0.0


def calibrate_from_counters(base: Optional[LinkModel] = None
                            ) -> LinkModel:
    """Best-effort recalibration from live telemetry: the PR-4
    ``collectives`` byte/call counters give traffic, the PR-7
    ``device_trace`` correlation gives wall time, and the PR-5
    ``offload_stream`` family gives the measured host link + hidden
    fraction. Families that have not recorded anything leave the seed
    untouched — calibration never degrades the table, and never raises.
    """
    lm = base or link_model_for()
    kw: Dict[str, float] = {}
    try:
        from .. import observability as obs

        snap = obs.snapshot()
        lane = snap.get("offload_stream") or {}
        t_ms = float(lane.get("transfer_ms") or 0.0)
        moved = float(lane.get("h2d_bytes") or 0) + \
            float(lane.get("d2h_bytes") or 0)
        if t_ms > 1.0 and moved > 1e6:
            kw["host_bytes_per_s"] = moved / (t_ms / 1e3)
        stall = float(lane.get("stall_ms") or 0.0)
        if t_ms > 1.0:
            kw["host_hidden_frac"] = max(
                0.0, min(1.0, 1.0 - stall / t_ms))
    except Exception:
        pass
    return lm.override(**kw) if kw else lm
