"""Per-link communication cost tables for the auto-parallel planner.

Reference role: python/paddle/distributed/auto_parallel/cost_model.py —
the reference prices candidate distributed programs with a measured
per-op/per-link cost table before picking a plan. TPU-native mapping:
collectives are XLA ops over ICI (or host memcpy on the CPU test mesh),
so a topology is priced by four numbers — peak matmul FLOPS, link
bandwidth, per-collective launch latency, and per-executable dispatch
overhead — plus the host-link bandwidth the offload executor streams
through. The seeds below come from this repo's own measurements:

- ``tpu-v5e``: the BENCH hbm_envelope rounds (197 TFLOP/s bf16 peak,
  ~90 GB/s per-direction ICI ring) and the PR-5 ``stream_capacity``
  legs (effective host link ~2 GB/s through the axon tunnel);
- ``cpu-host``: the 8-device ``--xla_force_host_platform_device_count``
  dryrun mesh (MULTICHIP_r05) — "links" are memcpys between thread
  shards, cheap on bytes but expensive per collective (every extra
  partitioned op pays SPMD overhead on an oversubscribed host).

Tables are overridable per call (``LinkModel(**overrides)``) and
re-calibratable from the live PR-4 collective byte/call counters plus
XPlane device timings (``calibrate_from_counters``).
"""
from __future__ import annotations

import json
import os
from dataclasses import dataclass, replace, asdict
from typing import Any, Dict, Optional

__all__ = ["LinkModel", "LINK_TABLES", "link_model_for",
           "calibrated_link_model", "ring_factor",
           "reduce_scatter_factor", "all_to_all_factor",
           "all_gather_factor", "calibrate_from_counters",
           "save_calibration", "load_calibration", "calibration_path",
           "kv_ship_seconds", "kv_reprefill_seconds",
           "kv_migration_crossover"]


@dataclass(frozen=True)
class LinkModel:
    """One topology's cost constants (everything the step-time model
    multiplies bytes/flops by)."""

    name: str = "tpu-v5e"
    peak_flops: float = 197e12        # per-device matmul peak (model dtype)
    hbm_bytes_per_s: float = 8.1e11   # device memory stream bandwidth
    ici_bytes_per_s: float = 9e10     # per-direction inter-device link
    coll_latency_s: float = 1e-5      # per-collective launch/sync charge
    dispatch_s: float = 1e-4          # per-executable host dispatch charge
    host_bytes_per_s: float = 2e9     # host<->device offload stream link
    host_hidden_frac: float = 0.6     # offload transfer fraction the
    # double-buffered lane hides behind the group updates (PR-5 measured
    # overlap_efficiency ~0.23-0.54 CPU, higher on real DMA links)

    def to_dict(self) -> Dict[str, float]:
        return asdict(self)

    def override(self, **kw) -> "LinkModel":
        return replace(self, **kw)


LINK_TABLES: Dict[str, LinkModel] = {
    "tpu-v4": LinkModel("tpu-v4", peak_flops=275e12,
                        hbm_bytes_per_s=1.2e12),
    "tpu-v5e": LinkModel("tpu-v5e"),
    "tpu-v5p": LinkModel("tpu-v5p", peak_flops=459e12,
                         hbm_bytes_per_s=2.765e12,
                         ici_bytes_per_s=2e11),
    "tpu-v6e": LinkModel("tpu-v6e", peak_flops=918e12,
                         hbm_bytes_per_s=1.6e12),
    # the 8-device CPU host mesh every dryrun/CI leg runs on: bytes are
    # cheap (shared memory), partitioned-op overhead is what ranks configs
    "cpu-host": LinkModel("cpu-host", peak_flops=2e10,
                          hbm_bytes_per_s=2e10, ici_bytes_per_s=8e9,
                          coll_latency_s=5e-5, dispatch_s=3e-4,
                          host_bytes_per_s=2e9, host_hidden_frac=0.35),
}


def link_model_for(topology: Optional[str] = None, **overrides) -> LinkModel:
    """Resolve a LinkModel: explicit topology name, else the live jax
    backend (CPU test meshes -> "cpu-host", TPU kinds by generation)."""
    if topology is None:
        try:
            import jax

            dev = jax.devices()[0]
            if dev.platform == "cpu":
                topology = "cpu-host"
            else:
                kind = getattr(dev, "device_kind", "").lower()
                topology = next((k for k in LINK_TABLES
                                 if k.startswith("tpu")
                                 and k.split("-")[1] in kind), "tpu-v5e")
        except Exception:
            topology = "tpu-v5e"
    base = LINK_TABLES.get(topology)
    if base is None:
        raise KeyError(f"unknown topology {topology!r}; known: "
                       f"{sorted(LINK_TABLES)} (or pass overrides on one)")
    # persisted calibration (opt-in: PT_LINK_CALIBRATION=1 so CI ranking
    # assertions stay deterministic unless a round armed it): measured
    # per-(topology, jax version) refits land on top of the seed table,
    # explicit caller overrides still win
    if os.environ.get("PT_LINK_CALIBRATION", "0") == "1":
        prof = load_calibration(topology)
        if prof:
            cal = {k: float(v) for k, v in (prof.get("link") or {}).items()
                   if k in base.to_dict() and k != "name"}
            if cal:
                base = base.override(**cal)
    return base.override(**overrides) if overrides else base


def calibrated_link_model(topology: Optional[str] = None,
                          **overrides) -> LinkModel:
    """``link_model_for`` with the persisted calibration ALWAYS merged
    (no ``PT_LINK_CALIBRATION`` gate): the explicit opt-in the online
    tuner's live re-scoring uses — a runtime deciding whether to swap
    the active plan must rank under measured link rates, while CI's
    deterministic ranking assertions keep the env-gated path."""
    lm = link_model_for(topology)
    prof = load_calibration(topology or lm.name)
    if prof:
        cal = {k: float(v) for k, v in (prof.get("link") or {}).items()
               if k in lm.to_dict() and k != "name"}
        if cal:
            lm = lm.override(**cal)
    return lm.override(**overrides) if overrides else lm


# -- bytes-on-wire multipliers ------------------------------------------------

def ring_factor(n: int) -> float:
    """Ring all-reduce: each rank moves 2(n-1)/n of the payload."""
    return 2.0 * (n - 1) / n if n > 1 else 0.0


def reduce_scatter_factor(n: int) -> float:
    """Reduce-scatter (the os_g grad path): half an all-reduce."""
    return (n - 1) / n if n > 1 else 0.0


def all_to_all_factor(n: int) -> float:
    """All-to-all (MoE dispatch/combine): (n-1)/n of the payload leaves
    this rank."""
    return (n - 1) / n if n > 1 else 0.0


def all_gather_factor(n: int) -> float:
    """Ring all-gather (ZeRO param materialization): each rank receives
    (n-1)/n of the payload."""
    return (n - 1) / n if n > 1 else 0.0


# -- KV page migration (disaggregated prefill/decode serving) -----------------
# Prices the ship-pages-vs-re-prefill decision: moving a prompt's paged
# KV across replicas costs bytes on the replica-to-replica link (the
# host link on a CPU fleet, DCN/ICI on a real one) plus a fixed RPC
# round-trip charge; recomputing it costs the prompt's prefill FLOPs.
# The crossover prompt length is where shipping starts winning — the
# bench's measured ratio validates the same quantities end-to-end.

def kv_ship_seconds(lm: LinkModel, wire_bytes: float,
                    rpc_overhead_s: float = 2e-3) -> float:
    """Wall-clock to ship ``wire_bytes`` of packed KV pages between two
    replicas: bytes over the inter-replica link plus a per-transfer
    RPC/staging charge (export head + chunk round trips + install
    commit)."""
    return float(wire_bytes) / lm.host_bytes_per_s + \
        float(rpc_overhead_s)


def kv_reprefill_seconds(lm: LinkModel, prompt_tokens: int,
                         flops_per_token: float) -> float:
    """Wall-clock to RECOMPUTE a prompt's KV on the target replica: the
    prefill FLOPs at the link model's effective peak, plus one
    executable dispatch."""
    return (float(prompt_tokens) * float(flops_per_token)
            ) / lm.peak_flops + lm.dispatch_s


def kv_migration_crossover(lm: LinkModel, page_len: int,
                           bytes_per_page: float,
                           flops_per_token: float,
                           quantized: bool = False,
                           max_pages: int = 4096) -> Dict[str, Any]:
    """The planner's migration policy input: for each prompt size find
    whether shipping the pages beats re-prefilling, and the crossover
    page count (smallest page count where ship wins; None when
    re-prefill always wins inside ``max_pages``). ``quantized`` halves
    the transit bytes (int8 per-page scales are noise next to the
    payload)."""
    scale = 0.5 if quantized else 1.0
    crossover = None
    for n in range(1, int(max_pages) + 1):
        ship = kv_ship_seconds(lm, n * bytes_per_page * scale)
        pre = kv_reprefill_seconds(lm, n * page_len, flops_per_token)
        if ship < pre:
            crossover = n
            break
    sample = crossover or int(max_pages)
    return {
        "crossover_pages": crossover,
        "ship_s": kv_ship_seconds(
            lm, sample * bytes_per_page * scale),
        "reprefill_s": kv_reprefill_seconds(
            lm, sample * page_len, flops_per_token),
        "quantized": bool(quantized),
        "bytes_per_page": float(bytes_per_page) * scale,
    }


_COLLECTIVE_OP_MARKERS = ("all-reduce", "all-gather", "all-to-all",
                          "reduce-scatter", "collective-permute",
                          "allreduce", "allgather", "alltoall")


def _is_collective_op(name: str) -> bool:
    n = name.lower()
    return any(m in n for m in _COLLECTIVE_OP_MARKERS)


def calibrate_from_counters(base: Optional[LinkModel] = None, *,
                            flops_per_step: Optional[float] = None,
                            persist: bool = False) -> LinkModel:
    """Best-effort recalibration from live telemetry — every bench round
    becomes a calibration round (ROADMAP direction 5's planner leg):

    - the PR-5 ``offload_stream`` family refits the host link bandwidth
      and hidden fraction (the original host-link-only calibration);
    - the PR-7 ``device_trace`` op table refits the ICI link: XPlane-
      measured device time of collective-shaped ops against the PR-4
      ``collectives`` byte counters gives measured bytes-on-wire/s;
    - with a ``flops_per_step`` hint (the planner profile knows it), the
      per-step XPlane ``device_compute_us`` refits the effective
      ``peak_flops`` — compute calibration, not just links.

    Families that have not recorded anything leave the seed untouched —
    calibration never degrades the table, and never raises.

    ``persist=True`` writes the refit next to the persistent executable
    cache, keyed by (topology, jax version); ``link_model_for`` merges
    it back when ``PT_LINK_CALIBRATION=1``, which is how the planner's
    per-topology tables learn from measured rounds.
    """
    lm = base or link_model_for()
    kw: Dict[str, float] = {}
    try:
        from .. import observability as obs

        snap = obs.snapshot()
        lane = snap.get("offload_stream") or {}
        t_ms = float(lane.get("transfer_ms") or 0.0)
        moved = float(lane.get("h2d_bytes") or 0) + \
            float(lane.get("d2h_bytes") or 0)
        if t_ms > 1.0 and moved > 1e6:
            kw["host_bytes_per_s"] = moved / (t_ms / 1e3)
        stall = float(lane.get("stall_ms") or 0.0)
        if t_ms > 1.0:
            kw["host_hidden_frac"] = max(
                0.0, min(1.0, 1.0 - stall / t_ms))
        # XPlane-measured per-op device times (PR-7 op table). The byte
        # counters are PROCESS-CUMULATIVE while the op table covers one
        # capture window, so both sides normalize to per-step rates:
        # bytes over every timeline step vs device time over the steps
        # the capture correlated — dividing raw totals would inflate the
        # bandwidth by (total steps / captured steps).
        dt = snap.get("device_trace") or {}
        op_table = dt.get("op_table") or []
        coll_us = sum(float(r.get("total_us") or 0.0) for r in op_table
                      if _is_collective_op(str(r.get("op", ""))))
        cap_steps = float(dt.get("steps_correlated") or 0)
        tl_steps = float((snap.get("step_timeline") or {}).get("steps")
                         or 0)
        colls = (snap.get("collectives") or {}).get("values") or {}
        coll_bytes = sum(float(v or 0.0) for k, v in colls.items()
                         if str(k).endswith("|bytes"))
        if coll_us > 100.0 and coll_bytes > 1e6 and cap_steps > 0 \
                and tl_steps > 0:
            bytes_per_step = coll_bytes / tl_steps
            us_per_step = coll_us / cap_steps
            kw["ici_bytes_per_s"] = bytes_per_step / (us_per_step / 1e6)
        if flops_per_step:
            per_step = float(((dt.get("device_compute_us") or {})
                              .get("per_step_avg")) or 0.0)
            if per_step > 100.0:
                kw["peak_flops"] = float(flops_per_step) / (per_step / 1e6)
    except Exception:
        pass
    lm = lm.override(**kw) if kw else lm
    if persist and kw:
        try:
            save_calibration(lm)
        except Exception:
            pass  # persistence is best-effort, never sinks the caller
    return lm


# -- persisted calibration profiles -------------------------------------------
# One JSON per (topology, jax version), living next to the persistent
# executable cache (same lifecycle: measured artifacts that make a fresh
# process as smart as the last one). Shape:
#   {"link": {<LinkModel field>: value, ...},
#    "fused": {<op>: {<FusedOpEntry field>: value, ...}, ...},
#    "meta": {...}}

def calibration_path(topology: Optional[str] = None) -> str:
    import jax

    topo = topology or link_model_for().name
    ver = getattr(jax, "__version__", "unknown")
    root = os.environ.get("PT_CALIBRATION_DIR")
    if not root:
        try:
            from ..jit import persistent_cache

            root = persistent_cache.cache_dir()
        except Exception:
            root = None
    if not root:
        root = os.path.join(os.path.expanduser("~"), ".cache", "paddle_tpu")
    return os.path.join(root, "calibration", f"{topo}-jax{ver}.json")


def save_calibration(lm: LinkModel, fused: Optional[Dict[str, Dict]] = None,
                     topology: Optional[str] = None) -> str:
    """Persist a measured profile (merging over any prior file so a
    round that only refit the link keeps earlier fused-op rows)."""
    path = calibration_path(topology or lm.name)
    prior = load_calibration(topology or lm.name) or {}
    seed = LINK_TABLES.get(lm.name)
    link_delta = {k: v for k, v in lm.to_dict().items()
                  if k != "name" and
                  (seed is None or getattr(seed, k) != v)}
    payload = {
        "link": dict(prior.get("link") or {}, **link_delta),
        "fused": dict(prior.get("fused") or {}, **(fused or {})),
        "meta": {"topology": lm.name},
    }
    os.makedirs(os.path.dirname(path), exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(payload, f, indent=1, sort_keys=True)
    os.replace(tmp, path)
    return path


def load_calibration(topology: Optional[str] = None
                     ) -> Optional[Dict[str, Any]]:
    try:
        path = calibration_path(topology)
        if not os.path.exists(path):
            return None
        with open(path) as f:
            return json.load(f)
    except Exception:
        return None
