"""Device mesh environment: the NCCL-comm-registry replacement.

Reference: fleet/base/topology.py (CommunicateTopology:36 cartesian rank mesh,
HybridCommunicateGroup:117 building NCCL groups per axis) + platform
collective_helper.h NCCLCommContext. TPU-native: ONE `jax.sharding.Mesh` whose
named axes are the parallelism dimensions; "creating a comm group" becomes
naming an axis; collectives are XLA ops lowered over ICI/DCN.

Axes (superset of the reference's ['data','pipe','sharding','model'] — we add
the context/expert axes the reference lacked, SURVEY §5 long-context note):
    dp   data parallel
    pp   pipeline stages
    sdp  ZeRO sharding (parameter/optimizer-state sharding)
    mp   tensor (model) parallel
    cp   context/sequence parallel
    ep   expert parallel
"""
from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

AXES = ("dp", "pp", "sdp", "mp", "cp", "ep")

_GLOBAL: Dict[str, Optional[object]] = {"env": None}


def _auto_axes(mesh, axis_names) -> frozenset:
    """Mesh axes that must stay AUTO (GSPMD) for a shard_map manual over
    `axis_names`. Size-1 axes are harmless to treat as manual, so they are
    excluded — which routes pure-manual meshes down the (much better
    supported) full-manual path of the older shard_map."""
    sizes = dict(mesh.shape)
    return frozenset(ax for ax in mesh.axis_names
                     if ax not in axis_names and sizes.get(ax, 1) > 1)


def shard_map_compat(f, mesh, in_specs, out_specs, axis_names=None,
                     check_vma=False):
    """`jax.shard_map` (the jax>=0.8 surface: axis_names = the manual set,
    check_vma) over whatever this jax provides. Older jax spells the same
    thing `jax.experimental.shard_map.shard_map(check_rep=..., auto=...)`
    with auto = the complement of the manual set."""
    if hasattr(jax, "shard_map"):
        kw = {"check_vma": check_vma}
        if axis_names:
            kw["axis_names"] = set(axis_names)
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, **kw)
    from jax.experimental.shard_map import shard_map as _shard_map

    kw = {"check_rep": bool(check_vma)}
    if axis_names:
        auto = _auto_axes(mesh, axis_names)
        if auto:
            kw["auto"] = auto
    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      **kw)


def shard_map_requires_native(axis_names, env) -> None:
    """Raise a clear error when a partial-auto shard_map over THIS mesh
    cannot work on an older jax (no jax.shard_map): kernels inside the
    manual region crash the 0.4-era partial-auto lowering outright."""
    if hasattr(jax, "shard_map"):
        return
    auto = _auto_axes(env.mesh, axis_names)
    if auto:
        raise NotImplementedError(
            f"this operation needs a partial-auto shard_map (manual over "
            f"{sorted(axis_names)}, auto over {sorted(auto)}) which this "
            f"jax ({jax.__version__}) cannot lower reliably; upgrade jax "
            f"or collapse the auto axes to size 1")


class MeshEnv:
    """The live mesh + axis degrees (HybridCommunicateGroup role)."""

    def __init__(self, degrees: Dict[str, int], devices: Optional[Sequence] = None):
        devices = list(devices if devices is not None else jax.devices())
        full = {ax: int(degrees.get(ax, 1)) for ax in AXES}
        n = math.prod(full.values())
        if n != len(devices):
            raise ValueError(
                f"product of axis degrees {full} = {n} != device count {len(devices)}")
        self.degrees = full
        # Axis order chooses ICI locality: mp (heaviest traffic) innermost.
        self.axis_names = tuple(ax for ax in ("pp", "dp", "sdp", "ep", "cp", "mp"))
        shape = tuple(full[ax] for ax in self.axis_names)
        dev_array = np.asarray(devices).reshape(shape)
        self.mesh = Mesh(dev_array, self.axis_names)

    # -- queries (CommunicateTopology API shape) ----------------------------
    def get_dim(self, axis: str) -> int:
        return self.degrees[axis]

    @property
    def nranks(self) -> int:
        return math.prod(self.degrees.values())

    def sharding_for(self, spec: PartitionSpec) -> NamedSharding:
        return NamedSharding(self.mesh, spec)

    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, PartitionSpec())

    def __repr__(self):
        used = {k: v for k, v in self.degrees.items() if v > 1}
        return f"MeshEnv({used or 'single-device'}, devices={self.nranks})"


def init_mesh(dp=1, mp=1, pp=1, sharding=1, cp=1, ep=1, devices=None) -> MeshEnv:
    """Create + install the global mesh (fleet._init_hybrid_parallel_env role)."""
    env = MeshEnv({"dp": dp, "mp": mp, "pp": pp, "sdp": sharding, "cp": cp, "ep": ep},
                  devices)
    _GLOBAL["env"] = env
    return env


def auto_mesh(devices=None) -> MeshEnv:
    """All devices on dp (pure data parallel) — the default world."""
    devices = list(devices if devices is not None else jax.devices())
    return init_mesh(dp=len(devices), devices=devices)


def get_mesh_env() -> Optional[MeshEnv]:
    return _GLOBAL["env"]


def require_mesh_env() -> MeshEnv:
    env = _GLOBAL["env"]
    if env is None:
        env = auto_mesh()
    return env


def reset_mesh():
    _GLOBAL["env"] = None
