"""Disk-spill sparse table + pluggable accessor seam.

Reference: paddle/fluid/distributed/ps/table/ssd_sparse_table.h:21 (two-tier
memory+SSD sparse table with eviction) and ctr_accessor.cc (per-row slot
metadata + update policy). This is the same architecture at laptop scale:

- hot tier: LRU dict of dirty/recent rows, bounded by a byte budget;
- cold tier: a np.memmap file holding EVERY row (written block-wise at
  create with the same RNG stream as the in-RAM table, so sharded init is
  byte-identical to `ParameterServer.create_table`);
- accessor: a per-row policy hook owning the extra metadata slots and the
  update rule — `SGDAccessor` is the plain table, `CtrAccessor` keeps
  show/click counters per row (the reference's CTR feature-value layout).

The table serves the same gather/scatter surface the ParameterServer's
pull/push handlers need; rows beyond the hot budget spill to disk instead
of growing the process.
"""
from __future__ import annotations

import os
from collections import OrderedDict
from typing import Optional

import numpy as np


class SGDAccessor:
    """Plain rows, SGD update (the default/dense accessor role)."""

    slots = 0  # extra metadata columns per row

    def init_slots(self, n_rows):
        return None

    def on_push(self, rows, meta, grads, lr, counts=None, clicks=None):
        rows -= lr * grads
        return rows, meta


class CtrAccessor(SGDAccessor):
    """CTR-style accessor (reference ctr_accessor.cc): per-row [show,
    click] counters updated on every push; the embedding update is scaled
    by a frequency-aware factor (rows that were never shown learn at full
    rate, heavily-shown rows stabilize)."""

    slots = 2  # show, click

    def __init__(self, click_weight: float = 1.0):
        self.click_weight = float(click_weight)

    def init_slots(self, n_rows):
        return np.zeros((n_rows, self.slots), "float32")

    def on_push(self, rows, meta, grads, lr, counts=None, clicks=None):
        meta[:, 0] += 1.0 if counts is None else np.asarray(counts, "f4")
        if clicks is not None:
            meta[:, 1] += np.asarray(clicks, "float32")
        damp = 1.0 / np.sqrt(1.0 + meta[:, 0:1])
        rows -= lr * damp * grads
        return rows, meta


class SpillSparseTable:
    """Two-tier [rows_own, dim] row store: LRU hot dict over a memmap."""

    def __init__(self, rows: int, dim: int, hot_bytes: int,
                 path: str, init_std: float = 0.01, seed: int = 0,
                 server_id: int = 0, n_servers: int = 1, accessor=None):
        self.dim = int(dim)
        self.accessor = accessor or SGDAccessor()
        self.n_own = len(range(server_id, rows, n_servers))
        row_bytes = self.dim * 4 + self.accessor.slots * 4
        self.hot_budget_rows = max(int(hot_bytes) // max(row_bytes, 1), 1)
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._mm = np.memmap(path, dtype="float32", mode="w+",
                             shape=(self.n_own, self.dim))
        # identical init stream to ParameterServer.create_table: block-wise
        # full-table draw, this server keeps rows r % n == id
        rng = np.random.RandomState(seed)
        block = max(1, min(rows, (1 << 22) // max(dim, 1)))
        out = 0
        for start in range(0, rows, block):
            stop = min(start + block, rows)
            chunk = (rng.randn(stop - start, dim) * init_std).astype(
                "float32")
            mine = chunk[(server_id - start) % n_servers::n_servers]
            self._mm[out:out + len(mine)] = mine
            out += len(mine)
        self._mm.flush()
        self._meta_mm: Optional[np.memmap] = None
        if self.accessor.slots:
            self._meta_mm = np.memmap(path + ".slots", dtype="float32",
                                      mode="w+",
                                      shape=(self.n_own,
                                             self.accessor.slots))
        self._hot: "OrderedDict[int, tuple]" = OrderedDict()  # rid -> (row, meta)
        self.spills = 0  # eviction write-backs (observability/testing)

    # -- tiering -------------------------------------------------------------
    def _load(self, rid: int):
        ent = self._hot.get(rid)
        if ent is not None:
            self._hot.move_to_end(rid)
            return ent
        row = np.array(self._mm[rid])
        meta = (np.array(self._meta_mm[rid])
                if self._meta_mm is not None else None)
        self._hot[rid] = (row, meta)
        self._evict()
        return self._hot[rid]

    def _evict(self):
        while len(self._hot) > self.hot_budget_rows:
            rid, (row, meta) = self._hot.popitem(last=False)  # LRU
            self._mm[rid] = row
            if meta is not None:
                self._meta_mm[rid] = meta
            self.spills += 1

    def flush(self):
        for rid, (row, meta) in self._hot.items():
            self._mm[rid] = row
            if meta is not None:
                self._meta_mm[rid] = meta
        self._mm.flush()
        if self._meta_mm is not None:
            self._meta_mm.flush()

    # -- the pull/push surface ----------------------------------------------
    def gather(self, local_ids) -> np.ndarray:
        return np.stack([self._load(int(r))[0] for r in local_ids])

    def scatter_sub(self, local_ids, grads, lr: float, clicks=None):
        """Duplicate ids accumulate (the np.subtract.at contract of the
        in-RAM table): grads/clicks are summed per unique row before the
        accessor applies them once."""
        local_ids = np.asarray(local_ids)
        grads = np.asarray(grads, "float32")
        uniq, inv, counts = np.unique(local_ids, return_inverse=True,
                                      return_counts=True)
        gsum = np.zeros((len(uniq), grads.shape[1]), "float32")
        np.add.at(gsum, inv, grads)
        csum = None
        if clicks is not None:
            csum = np.zeros((len(uniq),), "float32")
            np.add.at(csum, inv, np.asarray(clicks, "float32"))
        rows = self.gather(uniq)
        metas = None
        if self.accessor.slots:
            metas = np.stack([self._load(int(r))[1] for r in uniq])
        rows, metas = self.accessor.on_push(
            rows, metas, gsum, float(lr),
            counts=counts.astype("float32"), clicks=csum)
        for i, r in enumerate(uniq):
            self._hot[int(r)] = (rows[i],
                                 metas[i] if metas is not None else None)
            self._hot.move_to_end(int(r))
        self._evict()
