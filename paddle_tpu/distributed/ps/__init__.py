"""Parameter-server mode (reference: paddle/fluid/distributed/ps/ +
python/paddle/distributed/ps/the_one_ps.py:796 — pserver processes hold
dense/sparse tables; trainers pull params and push grads through brpc).

TPU-native scope: dense SPMD training belongs to GSPMD; the PS covers what
SPMD cannot — giant sparse embedding tables that never fit a chip and update
sparsely. Architecture mirrored from the reference at reduced scale:

  * multi-server row sharding: sparse row r lives on server ``r % n_servers``
    (the reference's key-hash table shards, brpc_ps_client.h routing); dense
    tables split into contiguous chunks, one per server.
  * batched wire ops: one request carries the whole batch's unique rows (ids
    + rows/grads as single ndarray payloads over the native TCPStore).
  * AsyncCommunicator: background push thread with a bounded queue (the
    reference's communicator.cc send queue / async PS mode).

Servers and trainers are gang-spawned processes (launch/process.py); the
rendezvous/wire is the native TCPStore daemon (store/store.cpp).
"""
from __future__ import annotations

import io
import os
import queue
import threading
from typing import Dict, List, Optional

import numpy as np

from ..store import TCPStore
from .graph_table import GraphTable  # noqa: F401

__all__ = ["ParameterServer", "PsTrainer", "SparseEmbedding",
           "AsyncCommunicator", "GraphTable", "PsShardSource"]


def _dumps(arr: np.ndarray) -> bytes:
    buf = io.BytesIO()
    np.save(buf, arr)
    return buf.getvalue()


def _loads(raw: bytes) -> np.ndarray:
    return np.load(io.BytesIO(raw))


def _own_client(store: TCPStore) -> TCPStore:
    """Blocking gets hold a per-connection lock, so the serving loop and each
    trainer need their own client socket to the same daemon."""
    return TCPStore(host=store.host, port=store.port, is_master=False,
                    world_size=store.world_size, timeout=store.timeout)


def _dense_chunks(total: int, n: int) -> List[int]:
    """Chunk offsets [0, ..., total]: server s owns [off[s], off[s+1])."""
    base, extra = divmod(total, n)
    offs = [0]
    for s in range(n):
        offs.append(offs[-1] + base + (1 if s < extra else 0))
    return offs


class ParameterServer:
    """Holds this server's shard of every table; applies pushed gradients
    (reference ps/table/memory_sparse_table.cc + dense table)."""

    def __init__(self, store: TCPStore, server_id: int = 0, n_servers: int = 1,
                 request_timeout: int = 10):
        self.store = _own_client(store)
        # bounded gets: a trainer dying mid-request must not wedge serving
        # for the full default 900s (see _loop's retry handling)
        self.store._lib.tcpstore_set_timeout(self.store._fd,
                                             int(request_timeout))
        self.store.timeout = int(request_timeout)
        self.server_id = int(server_id)
        self.n_servers = int(n_servers)
        self.tables: Dict[str, np.ndarray] = {}   # sparse shards [rows/n, d]
        self.dense: Dict[str, np.ndarray] = {}    # dense chunks (flat)
        self.lr: Dict[str, float] = {}
        self._mu = threading.Lock()  # create_table vs serving loop
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @property
    def _pfx(self) -> str:
        return f"ps/s{self.server_id}"

    def create_table(self, name: str, shape, lr: float = 0.1, init_std=0.01,
                     seed: int = 0, hot_bytes: Optional[int] = None,
                     spill_dir: Optional[str] = None, accessor=None):
        """Sparse table: this server materializes rows r % n_servers == id.
        All servers draw from the same seed so the sharded init equals the
        single-server init row-for-row; rows are drawn in bounded blocks so
        peak memory is O(block), not O(full table) — the point of sharding
        giant tables.

        `hot_bytes` switches the shard to a disk-spill two-tier store
        (reference ssd_sparse_table.h role, spill_table.SpillSparseTable):
        only ~hot_bytes of rows stay in RAM, the rest live in a memmap under
        `spill_dir`; `accessor` plugs a CTR-style per-row update policy."""
        rows, dim = int(shape[0]), int(shape[1])
        if hot_bytes is not None:
            from .spill_table import SpillSparseTable

            path = os.path.join(spill_dir or ".", f"ps_{name}_"
                                f"s{self.server_id}.bin")
            table = SpillSparseTable(rows, dim, hot_bytes, path,
                                     init_std=init_std, seed=seed,
                                     server_id=self.server_id,
                                     n_servers=self.n_servers,
                                     accessor=accessor)
            with self._mu:
                self.tables[name] = table
                self.lr[name] = float(lr)
            self.store.set(f"ps/{name}/meta",
                           _dumps(np.asarray([rows, dim, self.n_servers],
                                             "int64")))
            return self
        rng = np.random.RandomState(seed)
        n_own = len(range(self.server_id, rows, self.n_servers))
        shard = np.empty((n_own, dim), "float32")
        block = max(1, min(rows, (1 << 22) // max(dim, 1)))  # ~16MB f32
        out = 0
        for start in range(0, rows, block):
            stop = min(start + block, rows)
            # the row-major randn stream is identical to one full-table draw
            chunk = (rng.randn(stop - start, dim) * init_std).astype("float32")
            first = (self.server_id - start) % self.n_servers
            mine = chunk[first::self.n_servers]
            shard[out:out + len(mine)] = mine
            out += len(mine)
        with self._mu:
            self.tables[name] = shard
            self.lr[name] = float(lr)
        self.store.set(f"ps/{name}/meta",
                       _dumps(np.asarray([rows, dim, self.n_servers], "int64")))
        return self

    def create_dense_table(self, name: str, init: np.ndarray, lr: float = 0.1):
        """Dense table: contiguous chunk of the flattened parameter."""
        flat = np.asarray(init, "float32").ravel()
        offs = _dense_chunks(flat.size, self.n_servers)
        with self._mu:
            self.dense[name] = flat[offs[self.server_id]:
                                    offs[self.server_id + 1]].copy()
            self.lr[name] = float(lr)
        self.store.set(f"ps/{name}/dmeta",
                       _dumps(np.asarray(list(np.shape(init)) +
                                         [self.n_servers], "int64")))
        return self

    # -- serving loop --------------------------------------------------------
    def run(self, poll_interval=0.01):
        """Serve pull/push requests until stop() (reference brpc service loop;
        here requests rendezvous through store counters)."""
        self._thread = threading.Thread(target=self._loop,
                                        args=(poll_interval,), daemon=True,
                                        name="pt-ps-server")
        self._thread.start()
        return self

    MAX_REQUEST_RETRIES = 3  # ticks before a payload-less request is skipped

    def _loop(self, poll_interval):
        import sys

        served: Dict[tuple, int] = {}
        retries: Dict[tuple, int] = {}

        def give_up(kind, name):
            """A trainer died between bumping the counter and writing its
            payload: after MAX_REQUEST_RETRIES timeouts, skip that id so the
            table keeps serving everyone else."""
            k = served.get((kind, name), 0) + 1
            key = (kind, name, k)
            retries[key] = retries.get(key, 0) + 1
            if retries[key] >= self.MAX_REQUEST_RETRIES:
                print(f"ParameterServer[{name}]: abandoning {kind} request "
                      f"{k} (no payload after {retries[key]} attempts)",
                      file=sys.stderr)
                served[(kind, name)] = k
                retries.pop(key, None)

        def drain(kind, name, handler):
            try:
                n_req = self.store.add(f"{self._pfx}/{name}/{kind}_req", 0)
                while served.get((kind, name), 0) < n_req:
                    k = served.get((kind, name), 0) + 1
                    handler(name, k)
                    served[(kind, name)] = k
            except TimeoutError:
                give_up(kind, name)
            except Exception as e:  # pragma: no cover - defensive
                print(f"ParameterServer[{name}]: {e!r}", file=sys.stderr)

        def h_pull(name, k):
            table = self.tables[name]
            ids = _loads(self.store.get(f"{self._pfx}/{name}/pull/{k}/ids"))
            local = ids // self.n_servers  # ids are GLOBAL row numbers
            rows = (table.gather(local) if hasattr(table, "gather")
                    else table[local])
            self.store.set(f"{self._pfx}/{name}/pull/{k}/rows", _dumps(rows))
            self.store.delete_key(f"{self._pfx}/{name}/pull/{k}/ids")

        def h_push(name, k):
            table = self.tables[name]
            ids = _loads(self.store.get(f"{self._pfx}/{name}/push/{k}/ids"))
            grads = _loads(self.store.get(f"{self._pfx}/{name}/push/{k}/grads"))
            local = ids // self.n_servers
            if hasattr(table, "scatter_sub"):  # disk-spill tier + accessor
                table.scatter_sub(local, grads, self.lr[name])
            else:
                np.subtract.at(table, local, self.lr[name] * grads)
            self.store.set(f"{self._pfx}/{name}/push/{k}/done", b"1")
            self.store.delete_key(f"{self._pfx}/{name}/push/{k}/ids")
            self.store.delete_key(f"{self._pfx}/{name}/push/{k}/grads")

        def h_dpull(name, k):
            chunk = self.dense[name]
            self.store.set(f"{self._pfx}/{name}/dpull/{k}/rows", _dumps(chunk))

        def h_dpush(name, k):
            grads = _loads(self.store.get(f"{self._pfx}/{name}/dpush/{k}/g"))
            self.dense[name] -= self.lr[name] * grads
            self.store.set(f"{self._pfx}/{name}/dpush/{k}/done", b"1")
            self.store.delete_key(f"{self._pfx}/{name}/dpush/{k}/g")

        while not self._stop.is_set():
            with self._mu:
                sparse = list(self.tables)
                dense = list(self.dense)
            for name in sparse:
                drain("pull", name, h_pull)
                drain("push", name, h_push)
            for name in dense:
                drain("dpull", name, h_dpull)
                drain("dpush", name, h_dpush)
            self._stop.wait(poll_interval)

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            # outlast a get blocked for the full request timeout
            self._thread.join(timeout=self.store.timeout + 2)
            if self._thread.is_alive():  # pragma: no cover - defensive
                return  # leak the fd rather than close it under the thread
        self.store.close()


class PsTrainer:
    """Trainer-side client routing batched pulls/pushes across the server
    shards (reference brpc_ps_client.h fan-out + region merge)."""

    def __init__(self, store: TCPStore, n_servers: int = 1):
        self.store = _own_client(store)
        self.n_servers = int(n_servers)

    def _route(self, ids: np.ndarray):
        """Per-server (server_id, local_positions, server_ids) split."""
        owner = ids % self.n_servers
        out = []
        for s in range(self.n_servers):
            pos = np.nonzero(owner == s)[0]
            if len(pos):
                out.append((s, pos, ids[pos]))
        return out

    def pull(self, table: str, ids: np.ndarray) -> np.ndarray:
        ids = np.asarray(ids, "int64")
        meta = _loads(self.store.get(f"ps/{table}/meta"))
        dim = int(meta[1])
        out = np.empty((len(ids), dim), "float32")
        routed = self._route(ids)
        # pipeline: write every server's request first, then read replies
        reqs = []
        for s, pos, sids in routed:
            req = self.store.add(f"ps/s{s}/{table}/pull_req", 1)
            self.store.set(f"ps/s{s}/{table}/pull/{req}/ids", _dumps(sids))
            reqs.append((s, pos, req))
        for s, pos, req in reqs:
            rows = _loads(self.store.get(f"ps/s{s}/{table}/pull/{req}/rows"))
            self.store.delete_key(f"ps/s{s}/{table}/pull/{req}/rows")
            out[pos] = rows
        return out

    def push(self, table: str, ids: np.ndarray, grads: np.ndarray,
             wait: bool = False):
        ids = np.asarray(ids, "int64")
        grads = np.asarray(grads, "float32")
        reqs = []
        for s, pos, sids in self._route(ids):
            req = self.store.add(f"ps/s{s}/{table}/push_req", 1)
            self.store.set(f"ps/s{s}/{table}/push/{req}/grads",
                           _dumps(grads[pos]))
            self.store.set(f"ps/s{s}/{table}/push/{req}/ids", _dumps(sids))
            reqs.append((s, req))
        if wait:  # per-request ack: immune to other trainers' pushes
            for s, req in reqs:
                self.store.wait([f"ps/s{s}/{table}/push/{req}/done"])
                self.store.delete_key(f"ps/s{s}/{table}/push/{req}/done")

    # -- dense tables --------------------------------------------------------
    def pull_dense(self, table: str) -> np.ndarray:
        meta = _loads(self.store.get(f"ps/{table}/dmeta"))
        shape, n = tuple(int(d) for d in meta[:-1]), int(meta[-1])
        reqs = []
        for s in range(n):
            req = self.store.add(f"ps/s{s}/{table}/dpull_req", 1)
            reqs.append((s, req))
        chunks = []
        for s, req in reqs:
            chunks.append(_loads(
                self.store.get(f"ps/s{s}/{table}/dpull/{req}/rows")))
            self.store.delete_key(f"ps/s{s}/{table}/dpull/{req}/rows")
        return np.concatenate(chunks).reshape(shape)

    def push_dense(self, table: str, grad: np.ndarray, wait: bool = False):
        meta = _loads(self.store.get(f"ps/{table}/dmeta"))
        n = int(meta[-1])
        flat = np.asarray(grad, "float32").ravel()
        offs = _dense_chunks(flat.size, n)
        reqs = []
        for s in range(n):
            req = self.store.add(f"ps/s{s}/{table}/dpush_req", 1)
            self.store.set(f"ps/s{s}/{table}/dpush/{req}/g",
                           _dumps(flat[offs[s]:offs[s + 1]]))
            reqs.append((s, req))
        if wait:
            for s, req in reqs:
                self.store.wait([f"ps/s{s}/{table}/dpush/{req}/done"])
                self.store.delete_key(f"ps/s{s}/{table}/dpush/{req}/done")


class AsyncCommunicator:
    """Background push thread with a bounded send queue (reference
    communicator.cc AsyncCommunicator: grads queue up, a worker drains them;
    a full queue back-pressures the trainer instead of growing unbounded)."""

    def __init__(self, trainer: PsTrainer, max_queue: int = 64):
        self.trainer = trainer
        self.q: "queue.Queue" = queue.Queue(maxsize=max_queue)
        self.errors: List[Exception] = []
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="pt-ps-push")
        self._thread.start()

    def _loop(self):
        import sys

        while True:
            try:
                item = self.q.get(timeout=0.1)
            except queue.Empty:
                if self._stop.is_set():
                    return
                continue
            try:
                kind, table, a, b = item
                if kind == "sparse":
                    self.trainer.push(table, a, b, wait=True)
                else:
                    self.trainer.push_dense(table, a, wait=True)
            except Exception as e:
                # a failed push must not kill the drain thread: later items
                # would never be applied and flush()/stop() would hang on
                # q.join(). Record and keep draining.
                self.errors.append(e)
                print(f"AsyncCommunicator: push to {table!r} failed: {e!r}",
                      file=sys.stderr)
            finally:
                self.q.task_done()

    def push(self, table: str, ids, grads):
        self.q.put(("sparse", table, ids, grads))  # blocks when full

    def push_dense(self, table: str, grad):
        self.q.put(("dense", table, grad, None))

    def flush(self):
        """Block until every queued push has been applied server-side."""
        self.q.join()

    def stop(self):
        self.flush()
        self._stop.set()
        self._thread.join(timeout=5)


class PsShardSource:
    """The PS wiring of ``sparse.ShardedEmbeddingTable``: canonical rows
    live in a ParameterServer gang instead of in-process numpy — the
    table's hot-row cache, streaming and dedup front the SAME pull/push
    wire protocol ``SparseEmbedding`` uses, so a multi-process PS cluster
    (launch/process.py gangs) serves tables beyond one host's RAM.

    The SERVER owns the update policy (its ``lr`` / accessor — the
    reference contract: trainers push raw row gradients); the table's
    local row rule is ignored on this source. ``apply`` pushes the
    accumulated (unique_ids, grads) pairs and pulls the post-update rows
    back so the device cache stays coherent with the authoritative
    shards."""

    def __init__(self, trainer: "PsTrainer", table: str, rows: int,
                 dim: int):
        self.trainer = trainer
        self.table = table
        self.rows, self.dim = int(rows), int(dim)

    def gather(self, ids: np.ndarray) -> np.ndarray:
        ids = np.asarray(ids, "int64")
        if not len(ids):
            return np.zeros((0, self.dim), "float32")
        return self.trainer.pull(self.table, ids)

    def apply(self, ids: np.ndarray, grads: np.ndarray, rule) -> np.ndarray:
        ids = np.asarray(ids, "int64")
        if not len(ids):
            return np.zeros((0, self.dim), "float32")
        # wait=True: the pull below must observe the applied update
        self.trainer.push(self.table, ids, np.asarray(grads, "float32"),
                          wait=True)
        return self.trainer.pull(self.table, ids)

    def nbytes(self) -> int:
        return 0  # rows live server-side, not in this process


class SparseEmbedding:
    """Distributed lookup table (reference DistributedLookupTable /
    distributed/ps sparse table): pulls rows per batch, pushes row grads."""

    def __init__(self, trainer: PsTrainer, table: str, embedding_dim: int,
                 communicator: Optional[AsyncCommunicator] = None):
        self.trainer = trainer
        self.table = table
        self.dim = embedding_dim
        self.communicator = communicator
        self._last = None  # (unique_ids, inverse) of the live batch

    def forward(self, ids):
        from ...core.tensor import Tensor
        import jax.numpy as jnp

        flat = np.asarray(ids.numpy() if hasattr(ids, "numpy") else ids,
                          "int64").ravel()
        uniq, inverse = np.unique(flat, return_inverse=True)
        rows = self.trainer.pull(self.table, uniq)
        self._last = (uniq, inverse, tuple(np.shape(
            ids.numpy() if hasattr(ids, "numpy") else ids)))
        out = rows[inverse].reshape(*self._last[2], self.dim)
        t = Tensor(jnp.asarray(out))
        t.stop_gradient = False
        return t

    __call__ = forward

    def push_grad(self, grad, wait=True):
        """Push d(loss)/d(embedding_out) back as row gradients; rides the
        AsyncCommunicator when one is attached (async PS mode)."""
        assert self._last is not None, "forward must run before push_grad"
        uniq, inverse, shape = self._last
        g = np.asarray(grad.numpy() if hasattr(grad, "numpy") else grad,
                       "float32").reshape(-1, self.dim)
        acc = np.zeros((len(uniq), self.dim), "float32")
        np.add.at(acc, inverse, g)
        if self.communicator is not None:
            self.communicator.push(self.table, uniq, acc)
        else:
            self.trainer.push(self.table, uniq, acc, wait=wait)
