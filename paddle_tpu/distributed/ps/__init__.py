"""Parameter-server mode (reference: paddle/fluid/distributed/ps/ +
python/paddle/distributed/fleet PS strategies — pserver processes hold dense/
sparse tables; trainers pull params and push grads).

TPU-native scope: dense training belongs to SPMD/GSPMD, so the PS here covers
the role SPMD cannot: giant sparse embedding tables that never fit a chip and
update sparsely. Tables live server-side; the wire is the native TCPStore
(store/store.cpp), values as raw ndarray bytes — trainers pull rows for the
batch, compute on-device, and push row gradients back for a server-side SGD
update (async, like the reference's async PS mode).
"""
from __future__ import annotations

import io
import threading
from typing import Dict, Optional

import numpy as np

from ..store import TCPStore

__all__ = ["ParameterServer", "PsTrainer", "SparseEmbedding"]


def _dumps(arr: np.ndarray) -> bytes:
    buf = io.BytesIO()
    np.save(buf, arr)
    return buf.getvalue()


def _loads(raw: bytes) -> np.ndarray:
    return np.load(io.BytesIO(raw))


def _own_client(store: TCPStore) -> TCPStore:
    """Blocking gets hold a per-connection lock, so the serving loop and each
    trainer need their own client socket to the same daemon."""
    return TCPStore(host=store.host, port=store.port, is_master=False,
                    world_size=store.world_size, timeout=store.timeout)


class ParameterServer:
    """Holds sparse tables; applies pushed row-gradients (table_manager role,
    reference ps/table/memory_sparse_table.cc)."""

    def __init__(self, store: TCPStore, server_id: int = 0,
                 request_timeout: int = 10):
        self.store = _own_client(store)
        # bounded gets: a trainer dying mid-request must not wedge serving
        # for the full default 900s (see _loop's retry handling)
        self.store._lib.tcpstore_set_timeout(self.store._fd,
                                             int(request_timeout))
        self.store.timeout = int(request_timeout)
        self.server_id = server_id
        self.tables: Dict[str, np.ndarray] = {}
        self.lr: Dict[str, float] = {}
        self._mu = threading.Lock()  # create_table vs serving loop
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def create_table(self, name: str, shape, lr: float = 0.1, init_std=0.01,
                     seed: int = 0):
        rng = np.random.RandomState(seed)
        with self._mu:
            self.tables[name] = (rng.randn(*shape) * init_std).astype("float32")
            self.lr[name] = float(lr)
        self.store.set(f"ps/{name}/meta", _dumps(np.asarray(shape, "int64")))
        return self

    # -- serving loop --------------------------------------------------------
    def run(self, poll_interval=0.01):
        """Serve pull/push requests until stop() (reference brpc service loop;
        here requests rendezvous through store counters)."""
        self._thread = threading.Thread(target=self._loop,
                                        args=(poll_interval,), daemon=True)
        self._thread.start()
        return self

    MAX_REQUEST_RETRIES = 3  # ticks before a payload-less request is skipped

    def _loop(self, poll_interval):
        import sys

        served_pull: Dict[str, int] = {}
        served_push: Dict[str, int] = {}
        retries: Dict[tuple, int] = {}

        def give_up(kind, name, served):
            """A trainer died between bumping the counter and writing its
            payload: after MAX_REQUEST_RETRIES timeouts, skip that id so the
            table keeps serving everyone else."""
            k = served.get(name, 0) + 1
            key = (kind, name, k)
            retries[key] = retries.get(key, 0) + 1
            if retries[key] >= self.MAX_REQUEST_RETRIES:
                print(f"ParameterServer[{name}]: abandoning {kind} request "
                      f"{k} (no payload after {retries[key]} attempts)",
                      file=sys.stderr)
                served[name] = k
                retries.pop(key, None)

        while not self._stop.is_set():
            with self._mu:
                snapshot = list(self.tables.items())
            for name, table in snapshot:
                # pulls: trainer writes ids, bumps request counter
                try:
                    n_req = self.store.add(f"ps/{name}/pull_req", 0)
                    while served_pull.get(name, 0) < n_req:
                        k = served_pull.get(name, 0) + 1
                        ids = _loads(self.store.get(f"ps/{name}/pull/{k}/ids"))
                        rows = table[ids]
                        self.store.set(f"ps/{name}/pull/{k}/rows", _dumps(rows))
                        self.store.delete_key(f"ps/{name}/pull/{k}/ids")
                        served_pull[name] = k  # progress survives a later retry
                except TimeoutError:
                    give_up("pull", name, served_pull)
                except Exception as e:  # pragma: no cover - defensive
                    print(f"ParameterServer[{name}]: {e!r}", file=sys.stderr)
                # pushes: trainer writes (ids, grads), bumps counter
                try:
                    n_push = self.store.add(f"ps/{name}/push_req", 0)
                    while served_push.get(name, 0) < n_push:
                        k = served_push.get(name, 0) + 1
                        ids = _loads(self.store.get(f"ps/{name}/push/{k}/ids"))
                        grads = _loads(
                            self.store.get(f"ps/{name}/push/{k}/grads"))
                        np.subtract.at(table, ids, self.lr[name] * grads)
                        # per-request ack, then free the payload keys
                        self.store.set(f"ps/{name}/push/{k}/done", b"1")
                        self.store.delete_key(f"ps/{name}/push/{k}/ids")
                        self.store.delete_key(f"ps/{name}/push/{k}/grads")
                        served_push[name] = k
                except TimeoutError:
                    give_up("push", name, served_push)
                except Exception as e:  # pragma: no cover - defensive
                    print(f"ParameterServer[{name}]: {e!r}", file=sys.stderr)
            self._stop.wait(poll_interval)

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            # outlast a get blocked for the full request timeout
            self._thread.join(timeout=self.store.timeout + 2)
            if self._thread.is_alive():  # pragma: no cover - defensive
                return  # leak the fd rather than close it under the thread
        self.store.close()


class PsTrainer:
    """Trainer-side pull/push client (reference fleet communicator role)."""

    def __init__(self, store: TCPStore):
        self.store = _own_client(store)

    def pull(self, table: str, ids: np.ndarray) -> np.ndarray:
        req = self.store.add(f"ps/{table}/pull_req", 1)
        self.store.set(f"ps/{table}/pull/{req}/ids",
                       _dumps(np.asarray(ids, "int64")))
        # get() blocks until the server answers this request id
        rows = _loads(self.store.get(f"ps/{table}/pull/{req}/rows"))
        self.store.delete_key(f"ps/{table}/pull/{req}/rows")
        return rows

    def push(self, table: str, ids: np.ndarray, grads: np.ndarray,
             wait: bool = False):
        req = self.store.add(f"ps/{table}/push_req", 1)
        self.store.set(f"ps/{table}/push/{req}/grads",
                       _dumps(np.asarray(grads, "float32")))
        self.store.set(f"ps/{table}/push/{req}/ids",
                       _dumps(np.asarray(ids, "int64")))
        if wait:  # per-request ack: immune to other trainers' pushes
            self.store.wait([f"ps/{table}/push/{req}/done"])
            self.store.delete_key(f"ps/{table}/push/{req}/done")


class SparseEmbedding:
    """Distributed lookup table (reference DistributedLookupTable /
    distributed/ps sparse table): pulls rows per batch, pushes row grads."""

    def __init__(self, trainer: PsTrainer, table: str, embedding_dim: int):
        self.trainer = trainer
        self.table = table
        self.dim = embedding_dim
        self._last = None  # (unique_ids, inverse) of the live batch

    def forward(self, ids):
        from ...core.tensor import Tensor
        import jax.numpy as jnp

        flat = np.asarray(ids.numpy() if hasattr(ids, "numpy") else ids,
                          "int64").ravel()
        uniq, inverse = np.unique(flat, return_inverse=True)
        rows = self.trainer.pull(self.table, uniq)
        self._last = (uniq, inverse, tuple(np.shape(
            ids.numpy() if hasattr(ids, "numpy") else ids)))
        out = rows[inverse].reshape(*self._last[2], self.dim)
        t = Tensor(jnp.asarray(out))
        t.stop_gradient = False
        return t

    __call__ = forward

    def push_grad(self, grad, wait=True):
        """Push d(loss)/d(embedding_out) back as row gradients."""
        assert self._last is not None, "forward must run before push_grad"
        uniq, inverse, shape = self._last
        g = np.asarray(grad.numpy() if hasattr(grad, "numpy") else grad,
                       "float32").reshape(-1, self.dim)
        acc = np.zeros((len(uniq), self.dim), "float32")
        np.add.at(acc, inverse, g)
        self.trainer.push(self.table, uniq, acc, wait=wait)
