"""In-memory GNN graph table — the PS graph-storage tier at library scale.

Reference: paddle/fluid/distributed/ps/table/common_graph_table.h:355
(GraphTable: add_graph_node, random_sample_neighbors, random_sample_nodes,
pull_graph_list, get/set_node_feat over sharded adjacency lists with
optional weighted sampling). This keeps the same surface on a CSR-backed
numpy store: edges accumulate in python lists, `build()` freezes them into
CSR arrays for O(1) slicing, and samplers run vectorized numpy — the
sampling results feed the jit'ed GNN compute path as ordinary arrays
(data-dependent shapes stay OUTSIDE jit by design, like every io path)."""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np


class GraphTable:
    """One homogeneous edge type (the reference instantiates one table per
    edge type); directed edges src -> dst."""

    def __init__(self, seed: int = 0):
        self._src: List[np.ndarray] = []
        self._dst: List[np.ndarray] = []
        self._wgt: List[np.ndarray] = []
        self._feat: Dict[int, np.ndarray] = {}
        self._rng = np.random.default_rng(seed)
        self._csr: Optional[Tuple[np.ndarray, np.ndarray, np.ndarray,
                                  Optional[np.ndarray]]] = None

    # -- construction (add_graph_node / add edges role) ----------------------
    def add_edges(self, src, dst, weights=None):
        src = np.asarray(src, np.int64).reshape(-1)
        dst = np.asarray(dst, np.int64).reshape(-1)
        if src.shape != dst.shape:
            raise ValueError(f"add_edges: src/dst length mismatch "
                             f"({src.size} vs {dst.size})")
        wgt = np.ones(src.size, np.float32) if weights is None \
            else np.asarray(weights, np.float32).reshape(-1)
        if wgt.size != src.size:
            raise ValueError(f"add_edges: weights length {wgt.size} != "
                             f"edge count {src.size}")
        self._src.append(src)
        self._dst.append(dst)
        self._wgt.append(wgt)
        self._csr = None

    def set_node_feat(self, ids, feats):
        feats = np.asarray(feats)
        for i, nid in enumerate(np.asarray(ids, np.int64).reshape(-1)):
            self._feat[int(nid)] = feats[i]

    def get_node_feat(self, ids, dim: Optional[int] = None) -> np.ndarray:
        rows = []
        for nid in np.asarray(ids, np.int64).reshape(-1):
            f = self._feat.get(int(nid))
            if f is None:
                if dim is None:
                    raise KeyError(
                        f"get_node_feat: node {int(nid)} has no features "
                        f"(pass dim= for a zero default)")
                f = np.zeros((dim,), np.float32)
            rows.append(f)
        return np.stack(rows) if rows else np.zeros((0, dim or 0), np.float32)

    # -- freeze --------------------------------------------------------------
    def build(self):
        """Freeze accumulated edges into CSR over the dense id range
        [0, max_id] (the reference shards by id; one shard here)."""
        if not self._src:
            raise ValueError("GraphTable.build: no edges added")
        src = np.concatenate(self._src)
        dst = np.concatenate(self._dst)
        wgt = np.concatenate(self._wgt)
        n = int(max(src.max(), dst.max())) + 1
        order = np.argsort(src, kind="stable")
        src, dst, wgt = src[order], dst[order], wgt[order]
        indptr = np.zeros(n + 1, np.int64)
        np.add.at(indptr, src + 1, 1)
        indptr = np.cumsum(indptr)
        uniform = bool(np.all(wgt == wgt[0]))
        self._csr = (indptr, dst, wgt, None if uniform else wgt)
        return self

    def _require_csr(self):
        if self._csr is None:
            self.build()
        return self._csr

    @property
    def num_nodes(self) -> int:
        return self._require_csr()[0].size - 1

    @property
    def num_edges(self) -> int:
        return self._require_csr()[1].size

    def neighbors(self, nid: int) -> np.ndarray:
        indptr, dst, _, _ = self._require_csr()
        return dst[indptr[nid]:indptr[nid + 1]]

    # -- serving surface (reference :359-372) --------------------------------
    def pull_graph_list(self, start: int, size: int) -> np.ndarray:
        """Node ids [start, start+size) that have at least one out-edge."""
        indptr, _, _, _ = self._require_csr()
        deg = np.diff(indptr)
        ids = np.nonzero(deg > 0)[0]
        return ids[start:start + size]

    def random_sample_nodes(self, sample_size: int) -> np.ndarray:
        ids = self.pull_graph_list(0, self.num_nodes)
        if ids.size == 0:
            return ids
        return self._rng.choice(ids, size=min(sample_size, ids.size),
                                replace=False)

    def random_sample_neighbors(self, ids, sample_size: int,
                                ) -> Tuple[np.ndarray, np.ndarray]:
        """[n, sample_size] neighbor ids + bool mask (False = padded slot:
        fewer neighbors than requested). Weighted when edge weights were
        non-uniform, matching the reference's WeightedSampler."""
        indptr, dst, wgt, weighted = self._require_csr()
        ids = np.asarray(ids, np.int64).reshape(-1)
        out = np.zeros((ids.size, sample_size), np.int64)
        mask = np.zeros((ids.size, sample_size), bool)
        for r, nid in enumerate(ids):
            lo, hi = int(indptr[nid]), int(indptr[nid + 1])
            deg = hi - lo
            if deg == 0:
                continue
            k = min(sample_size, deg)
            if weighted is None:
                idx = self._rng.choice(deg, size=k, replace=False)
            else:
                p = weighted[lo:hi] / weighted[lo:hi].sum()
                idx = self._rng.choice(deg, size=k, replace=False, p=p)
            out[r, :k] = dst[lo + idx]
            mask[r, :k] = True
        return out, mask

    def clear_nodes(self):
        self._src, self._dst, self._wgt = [], [], []
        self._feat.clear()
        self._csr = None

    # -- persistence (reference :406 save) -----------------------------------
    def save(self, path: str):
        indptr, dst, wgt, _ = self._require_csr()
        feat_ids = np.asarray(sorted(self._feat), np.int64)
        feats = (np.stack([self._feat[int(i)] for i in feat_ids])
                 if feat_ids.size else np.zeros((0, 0), np.float32))
        np.savez(path, indptr=indptr, dst=dst, wgt=wgt,
                 feat_ids=feat_ids, feats=feats)

    @classmethod
    def load(cls, path: str, seed: int = 0) -> "GraphTable":
        z = np.load(path if path.endswith(".npz") else path + ".npz")
        t = cls(seed=seed)
        wgt = z["wgt"]
        indptr, dst = z["indptr"], z["dst"]
        uniform = bool(wgt.size == 0 or np.all(wgt == wgt[0]))
        t._csr = (indptr, dst, wgt, None if uniform else wgt)
        # also repopulate the edge lists so a later add_edges() composes
        # with the loaded graph instead of silently replacing it at the
        # next build()
        src = np.repeat(np.arange(indptr.size - 1, dtype=np.int64),
                        np.diff(indptr))
        t._src, t._dst, t._wgt = [src], [dst.copy()], [wgt.copy()]
        for i, nid in enumerate(z["feat_ids"]):
            t._feat[int(nid)] = z["feats"][i]
        return t

    def to_csc(self) -> Tuple[np.ndarray, np.ndarray]:
        """(row, colptr) of the CSC form — the layout
        incubate.graph_khop_sampler consumes (reference
        graph_khop_sampler.py:23 takes CSC row/colptr)."""
        indptr, dst, _, _ = self._require_csr()
        n = indptr.size - 1
        src = np.repeat(np.arange(n, dtype=np.int64), np.diff(indptr))
        order = np.argsort(dst, kind="stable")
        row = src[order]
        colptr = np.zeros(n + 1, np.int64)
        np.add.at(colptr, dst + 1, 1)
        return row, np.cumsum(colptr)
