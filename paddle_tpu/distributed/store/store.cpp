// TCP key-value store: the control-plane rendezvous component.
//
// Reference: paddle/fluid/distributed/store/tcp_store.h:91 (TCPStore with a
// MasterDaemon serving set/get/add/wait over a socket protocol). This is the
// native (C++) piece of the runtime the survey (§7 stage 4) keeps off the XLA
// path: worker bootstrap, barriers, and address exchange before any mesh
// exists. Exposed through a C ABI consumed via ctypes (no pybind11 in image).
//
// Wire protocol (little-endian):
//   request:  u8 cmd | u32 klen | key bytes | u32 vlen | value bytes
//   response: u32 vlen | value bytes            (GET/WAIT/ADD)
//             ADD's value is the new counter as 8-byte i64.
// Commands: 1=SET 2=GET(blocking) 3=ADD 4=WAIT(blocking) 5=DELETE 6=PING
#include <arpa/inet.h>
#include <cerrno>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <map>
#include <mutex>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <string>
#include <sys/socket.h>
#include <thread>
#include <unistd.h>
#include <vector>

namespace {

enum Cmd : uint8_t { SET = 1, GET = 2, ADD = 3, WAIT = 4, DEL = 5, PING = 6 };

struct Daemon {
  int listen_fd = -1;
  int port = 0;
  std::thread accept_thread;
  std::mutex mu;
  std::condition_variable cv;
  std::map<std::string, std::vector<uint8_t>> kv;
  bool stopping = false;
  std::vector<std::thread> workers;
  std::vector<int> conn_fds;  // open client sockets, for shutdown wakeup
};

bool read_exact(int fd, void* buf, size_t n) {
  auto* p = static_cast<uint8_t*>(buf);
  while (n > 0) {
    ssize_t r = ::recv(fd, p, n, 0);
    if (r <= 0) {
      if (r < 0 && errno == EINTR) continue;
      return false;
    }
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool write_exact(int fd, const void* buf, size_t n) {
  auto* p = static_cast<const uint8_t*>(buf);
  while (n > 0) {
    ssize_t r = ::send(fd, p, n, MSG_NOSIGNAL);
    if (r <= 0) {
      if (r < 0 && errno == EINTR) continue;
      return false;
    }
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool send_value(int fd, const std::vector<uint8_t>& v) {
  uint32_t len = static_cast<uint32_t>(v.size());
  if (!write_exact(fd, &len, 4)) return false;
  return v.empty() || write_exact(fd, v.data(), v.size());
}

void serve_conn(Daemon* d, int fd) {
  for (;;) {
    uint8_t cmd;
    uint32_t klen = 0, vlen = 0;
    if (!read_exact(fd, &cmd, 1) || !read_exact(fd, &klen, 4)) break;
    std::string key(klen, '\0');
    if (klen && !read_exact(fd, key.data(), klen)) break;
    if (!read_exact(fd, &vlen, 4)) break;
    std::vector<uint8_t> val(vlen);
    if (vlen && !read_exact(fd, val.data(), vlen)) break;

    if (cmd == SET) {
      std::lock_guard<std::mutex> lk(d->mu);
      d->kv[key] = std::move(val);
      d->cv.notify_all();
    } else if (cmd == GET || cmd == WAIT) {
      std::unique_lock<std::mutex> lk(d->mu);
      d->cv.wait(lk, [&] { return d->stopping || d->kv.count(key) > 0; });
      if (d->stopping) break;
      std::vector<uint8_t> out = (cmd == GET) ? d->kv[key]
                                              : std::vector<uint8_t>{1};
      lk.unlock();
      if (!send_value(fd, out)) break;
    } else if (cmd == ADD) {
      int64_t delta = 0;
      if (val.size() == 8) std::memcpy(&delta, val.data(), 8);
      int64_t now;
      {
        std::lock_guard<std::mutex> lk(d->mu);
        auto& cell = d->kv[key];
        int64_t cur = 0;
        if (cell.size() == 8) std::memcpy(&cur, cell.data(), 8);
        now = cur + delta;
        cell.resize(8);
        std::memcpy(cell.data(), &now, 8);
        d->cv.notify_all();
      }
      std::vector<uint8_t> out(8);
      std::memcpy(out.data(), &now, 8);
      if (!send_value(fd, out)) break;
    } else if (cmd == DEL) {
      std::lock_guard<std::mutex> lk(d->mu);
      d->kv.erase(key);
    } else if (cmd == PING) {
      if (!send_value(fd, {1})) break;
    } else {
      break;
    }
  }
  {
    std::lock_guard<std::mutex> lk(d->mu);
    for (auto it = d->conn_fds.begin(); it != d->conn_fds.end(); ++it) {
      if (*it == fd) {
        d->conn_fds.erase(it);
        break;
      }
    }
  }
  ::close(fd);
}

void accept_loop(Daemon* d) {
  for (;;) {
    int fd = ::accept(d->listen_fd, nullptr, nullptr);
    if (fd < 0) {
      if (errno == EINTR) continue;
      break;  // listen socket closed -> shut down
    }
    int one = 1;
    ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    std::lock_guard<std::mutex> lk(d->mu);
    if (d->stopping) {
      ::close(fd);
      break;
    }
    d->conn_fds.push_back(fd);
    d->workers.emplace_back(serve_conn, d, fd);
  }
}

}  // namespace

extern "C" {

// Start the master daemon. port=0 picks a free port. Returns an opaque handle
// (nullptr on failure); *out_port receives the bound port.
void* tcpstore_server_start(int port, int* out_port) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return nullptr;
  int one = 1;
  ::setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0 ||
      ::listen(fd, 128) != 0) {
    ::close(fd);
    return nullptr;
  }
  socklen_t len = sizeof(addr);
  ::getsockname(fd, reinterpret_cast<sockaddr*>(&addr), &len);
  auto* d = new Daemon();
  d->listen_fd = fd;
  d->port = ntohs(addr.sin_port);
  if (out_port) *out_port = d->port;
  d->accept_thread = std::thread(accept_loop, d);
  return d;
}

void tcpstore_server_stop(void* handle) {
  auto* d = static_cast<Daemon*>(handle);
  if (!d) return;
  {
    std::lock_guard<std::mutex> lk(d->mu);
    d->stopping = true;
    d->cv.notify_all();
    // wake workers blocked in recv() so they observe `stopping` and exit
    for (int fd : d->conn_fds) ::shutdown(fd, SHUT_RDWR);
  }
  ::shutdown(d->listen_fd, SHUT_RDWR);
  ::close(d->listen_fd);
  if (d->accept_thread.joinable()) d->accept_thread.join();
  for (auto& t : d->workers)
    if (t.joinable()) t.join();  // safe: every blocking site is unblocked above
  delete d;
}

// ---- client ---------------------------------------------------------------

int tcpstore_connect(const char* host, int port) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return -1;
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::inet_pton(AF_INET, host, &addr.sin_addr) != 1) {
    ::close(fd);
    return -1;
  }
  if (::connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0) {
    ::close(fd);
    return -1;
  }
  int one = 1;
  ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  return fd;
}

void tcpstore_close(int fd) { ::close(fd); }

// Bound how long blocking ops (GET/WAIT/ADD replies) may stall.
int tcpstore_set_timeout(int fd, int seconds) {
  timeval tv{};
  tv.tv_sec = seconds;
  return ::setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
}

static bool send_req(int fd, uint8_t cmd, const char* key, int klen,
                     const uint8_t* val, int vlen) {
  uint32_t k = static_cast<uint32_t>(klen), v = static_cast<uint32_t>(vlen);
  return write_exact(fd, &cmd, 1) && write_exact(fd, &k, 4) &&
         (klen == 0 || write_exact(fd, key, klen)) && write_exact(fd, &v, 4) &&
         (vlen == 0 || write_exact(fd, val, vlen));
}

int tcpstore_set(int fd, const char* key, int klen, const uint8_t* val,
                 int vlen) {
  return send_req(fd, SET, key, klen, val, vlen) ? 0 : -1;
}

// Blocking get. Returns value length (truncated to cap), -1 on error.
int tcpstore_get(int fd, const char* key, int klen, uint8_t* out, int cap) {
  if (!send_req(fd, GET, key, klen, nullptr, 0)) return -1;
  uint32_t vlen = 0;
  if (!read_exact(fd, &vlen, 4)) return -1;
  std::vector<uint8_t> buf(vlen);
  if (vlen && !read_exact(fd, buf.data(), vlen)) return -1;
  int n = static_cast<int>(vlen) < cap ? static_cast<int>(vlen) : cap;
  if (n > 0) std::memcpy(out, buf.data(), n);
  return static_cast<int>(vlen);
}

int64_t tcpstore_add(int fd, const char* key, int klen, int64_t delta) {
  uint8_t payload[8];
  std::memcpy(payload, &delta, 8);
  if (!send_req(fd, ADD, key, klen, payload, 8)) return INT64_MIN;
  uint32_t vlen = 0;
  if (!read_exact(fd, &vlen, 4) || vlen != 8) return INT64_MIN;
  int64_t out;
  if (!read_exact(fd, &out, 8)) return INT64_MIN;
  return out;
}

int tcpstore_wait(int fd, const char* key, int klen) {
  if (!send_req(fd, WAIT, key, klen, nullptr, 0)) return -1;
  uint32_t vlen = 0;
  if (!read_exact(fd, &vlen, 4)) return -1;
  std::vector<uint8_t> buf(vlen);
  if (vlen && !read_exact(fd, buf.data(), vlen)) return -1;
  return 0;
}

int tcpstore_delete(int fd, const char* key, int klen) {
  return send_req(fd, DEL, key, klen, nullptr, 0) ? 0 : -1;
}

}  // extern "C"
