"""Control-plane KV store (reference: paddle/fluid/distributed/store/
tcp_store.h:91 TCPStore / store.h Store).

The daemon + client are native C++ (store.cpp), compiled on first use with the
system toolchain and bound via ctypes (SURVEY §7 stage 4 keeps this component
off the XLA path: bootstrap/rendezvous before any mesh exists).
"""
from __future__ import annotations

import ctypes
import os
import threading

__all__ = ["TCPStore", "Store"]

_LIB = None
_LIB_LOCK = threading.Lock()


def _build_lib() -> ctypes.CDLL:
    global _LIB
    with _LIB_LOCK:
        if _LIB is not None:
            return _LIB
        from ...utils import cpp_extension

        src_dir = os.path.dirname(os.path.abspath(__file__))
        lib = cpp_extension.load("tcpstore",
                                 [os.path.join(src_dir, "store.cpp")],
                                 build_directory=src_dir)
        lib.tcpstore_server_start.restype = ctypes.c_void_p
        lib.tcpstore_server_start.argtypes = [ctypes.c_int,
                                              ctypes.POINTER(ctypes.c_int)]
        lib.tcpstore_server_stop.argtypes = [ctypes.c_void_p]
        lib.tcpstore_connect.restype = ctypes.c_int
        lib.tcpstore_connect.argtypes = [ctypes.c_char_p, ctypes.c_int]
        lib.tcpstore_close.argtypes = [ctypes.c_int]
        lib.tcpstore_set_timeout.restype = ctypes.c_int
        lib.tcpstore_set_timeout.argtypes = [ctypes.c_int, ctypes.c_int]
        lib.tcpstore_set.restype = ctypes.c_int
        lib.tcpstore_set.argtypes = [ctypes.c_int, ctypes.c_char_p, ctypes.c_int,
                                     ctypes.c_char_p, ctypes.c_int]
        lib.tcpstore_get.restype = ctypes.c_int
        lib.tcpstore_get.argtypes = [ctypes.c_int, ctypes.c_char_p, ctypes.c_int,
                                     ctypes.c_char_p, ctypes.c_int]
        lib.tcpstore_add.restype = ctypes.c_int64
        lib.tcpstore_add.argtypes = [ctypes.c_int, ctypes.c_char_p, ctypes.c_int,
                                     ctypes.c_int64]
        lib.tcpstore_wait.restype = ctypes.c_int
        lib.tcpstore_wait.argtypes = [ctypes.c_int, ctypes.c_char_p, ctypes.c_int]
        lib.tcpstore_delete.restype = ctypes.c_int
        lib.tcpstore_delete.argtypes = [ctypes.c_int, ctypes.c_char_p, ctypes.c_int]
        _LIB = lib
        return lib


class Store:
    """Abstract store API (reference store.h)."""

    def set(self, key: str, value):  # pragma: no cover - interface
        raise NotImplementedError

    def get(self, key: str) -> bytes:
        raise NotImplementedError

    def add(self, key: str, amount: int) -> int:
        raise NotImplementedError

    def wait(self, keys):
        raise NotImplementedError


class TCPStore(Store):
    """TCP-backed KV store (reference tcp_store.h:91).

    The designated master (is_master=True) hosts the native daemon; every
    process (master included) talks to it through the native client. barrier()
    composes add+wait the way the reference's paddle.distributed.barrier
    control plane does.
    """

    def __init__(self, host="127.0.0.1", port=0, is_master=False,
                 world_size=1, timeout=900):
        self._lib = _build_lib()
        self._server = None
        self.host = host
        self.world_size = int(world_size)
        if is_master:
            out_port = ctypes.c_int(0)
            self._server = self._lib.tcpstore_server_start(int(port),
                                                           ctypes.byref(out_port))
            if not self._server:
                raise RuntimeError(f"TCPStore: cannot bind port {port}")
            port = out_port.value
        elif not port:
            raise ValueError("non-master TCPStore needs the master's port")
        self.port = int(port)
        self._fd = self._lib.tcpstore_connect(host.encode(), self.port)
        if self._fd < 0:
            raise RuntimeError(f"TCPStore: cannot connect to {host}:{self.port}")
        self.timeout = int(timeout)
        if self.timeout > 0:
            self._lib.tcpstore_set_timeout(self._fd, self.timeout)
        self._lock = threading.Lock()

    def _drop_connection(self):
        """Invalidate + reopen the socket after a timed-out request.

        The server worker may still be blocked on the old request and will
        eventually write its reply to the old fd; reusing that fd would let the
        next request parse the stale reply as its own (silently wrong values).
        Caller holds self._lock.
        """
        if self._fd >= 0:
            self._lib.tcpstore_close(self._fd)
        self._fd = self._lib.tcpstore_connect(self.host.encode(), self.port)
        if self._fd < 0:
            raise RuntimeError(
                f"TCPStore: lost connection to {self.host}:{self.port} and "
                "could not reconnect")
        if self.timeout > 0:
            self._lib.tcpstore_set_timeout(self._fd, self.timeout)

    # -- Store API -----------------------------------------------------------
    def set(self, key: str, value):
        data = value if isinstance(value, (bytes, bytearray)) else str(value).encode()
        with self._lock:
            rc = self._lib.tcpstore_set(self._fd, key.encode(), len(key.encode()),
                                        bytes(data), len(data))
            if rc != 0:
                self._drop_connection()
        if rc != 0:
            raise RuntimeError("TCPStore.set failed")

    def get(self, key: str) -> bytes:
        k = key.encode()
        cap = 1 << 16
        while True:
            buf = ctypes.create_string_buffer(cap)
            with self._lock:
                n = self._lib.tcpstore_get(self._fd, k, len(k), buf, cap)
                if n < 0:
                    self._drop_connection()
            if n < 0:
                raise TimeoutError(
                    f"TCPStore.get({key}) failed or timed out after "
                    f"{self.timeout}s")
            if n <= cap:
                return buf.raw[:n]
            cap = n  # value larger than buffer: retry with exact size

    def add(self, key: str, amount: int = 1) -> int:
        k = key.encode()
        with self._lock:
            out = self._lib.tcpstore_add(self._fd, k, len(k), int(amount))
            if out == -(2 ** 63):
                self._drop_connection()
        if out == -(2 ** 63):
            raise RuntimeError("TCPStore.add failed")
        return int(out)

    def wait(self, keys, timeout=None):
        """Block until every key exists.

        `timeout` (seconds) overrides the store-level timeout for this call
        only — the socket deadline is re-armed around the blocking wait, so a
        long-lived client can make short liveness-checked waits (poll a key,
        check a subprocess, poll again) without a second connection. On
        expiry raises TimeoutError and the connection comes back with the
        store-level timeout.
        """
        keys = keys if isinstance(keys, (list, tuple)) else [keys]
        t = self.timeout if timeout is None else max(1, int(timeout))
        for key in keys:
            k = key.encode()
            with self._lock:
                if t != self.timeout:
                    self._lib.tcpstore_set_timeout(self._fd, t)
                rc = self._lib.tcpstore_wait(self._fd, k, len(k))
                if rc != 0:
                    self._drop_connection()  # reconnect re-arms self.timeout
                elif t != self.timeout:
                    self._lib.tcpstore_set_timeout(self._fd, self.timeout)
            if rc != 0:
                raise TimeoutError(
                    f"TCPStore.wait({key}) failed or timed out after {t}s")

    def delete_key(self, key: str):
        k = key.encode()
        with self._lock:
            self._lib.tcpstore_delete(self._fd, k, len(k))

    def barrier(self, tag="barrier"):
        """All world_size processes rendezvous on the counter `tag`.
        Generation-keyed so the same tag can barrier repeatedly."""
        n = self.add(f"_{tag}/count", 1)
        gen = (n - 1) // self.world_size
        if n % self.world_size == 0:
            self.set(f"_{tag}/done{gen}", b"1")
        self.wait([f"_{tag}/done{gen}"])

    def close(self):
        if self._fd >= 0:
            self._lib.tcpstore_close(self._fd)
            self._fd = -1
        if self._server:
            self._lib.tcpstore_server_stop(self._server)
            self._server = None

    def __del__(self):  # pragma: no cover - GC path
        try:
            self.close()
        except Exception:
            pass
