"""ZeRO group-sharded API.

Reference: python/paddle/distributed/sharding/group_sharded.py
(group_sharded_parallel wrapping ShardingStage2/3 + ShardingOptimizerStage2,
fleet/meta_parallel/sharding/). TPU-native: the three stages are placement
policies, not runtime objects —
  stage 1 (os):    optimizer states sharded over 'sdp'
  stage 2 (os_g):  + gradients sharded (reduce-scatter emerges from GSPMD)
  stage 3 (p_g_os): + parameters sharded, all-gathered on use
All three annotate `dist_spec`s consumed by ShardedTrainStep; XLA emits the
same reduce-scatter/all-gather pattern the reference hand-codes with hooks
(sharding_stage3.py:50 ForwardPostHooks / TaskFlow prefetch).
"""
from __future__ import annotations

from jax.sharding import PartitionSpec as P

from ..nn.layer.layers import Layer
from .mesh import require_mesh_env
from .meta_parallel.wrappers import apply_sharding_specs, ShardingParallel


def group_sharded_parallel(model: Layer, optimizer, level: str = "p_g_os",
                           scaler=None, group=None, offload=False,
                           sync_buffers=False, buffer_max_size=2**23,
                           segment_size=2**20, sync_comm=False):
    """reference group_sharded.py:group_sharded_parallel(level in
    {'os','os_g','p_g_os'})."""
    env = require_mesh_env()
    if level not in ("os", "os_g", "p_g_os"):
        raise ValueError(f"bad sharding level {level!r}")
    if offload:
        # reference sharding_utils.py offload / sharding_stage3.py:50
        # offload=True: fp32 master params + optimizer state live on host
        # memory; ShardedTrainStep splits the step into a mesh fwd+bwd
        # executable and per-GROUP host update executables driven by a
        # double-buffered streaming lane (grads stream down, fresh params
        # stream up, overlapped with the updates) — HBM holds only
        # params+grads+activations plus a two-group staging working set.
        optimizer._offload = True
    # group sizing for the streaming executor (reference segment_size /
    # buffer_max_size of group_sharded_parallel, previously accepted and
    # ignored): segment_size = minimum bytes before a stream group closes
    # (small params coalesce), buffer_max_size = staging-buffer cap a group
    # never grows past. Consumed by ShardedTrainStep._ensure_stream_update
    # via jit.offload_stream.plan_stream_groups.
    optimizer._stream_segment_size = int(segment_size)
    optimizer._stream_buffer_max_size = int(buffer_max_size)
    if level == "p_g_os":
        # full parameter sharding
        apply_sharding_specs(model, env, axis="sdp")
    # os / os_g: parameters stay replicated; optimizer-state sharding is
    # applied by ShardedTrainStep which places state like its param — for os
    # levels we mark state-only sharding via the optimizer flag:
    optimizer._zero_stage = {"os": 1, "os_g": 2, "p_g_os": 3}[level]
    if scaler is not None:
        return model, optimizer, scaler
    return model, optimizer


def save_group_sharded_model(model, output, optimizer=None):
    from ..framework import io as fio

    fio.save(model.state_dict(), output + ".pdparams")
    if optimizer is not None:
        fio.save(optimizer.state_dict(), output + ".pdopt")
