"""Distributed checkpointing with mesh resharding.

Reference roles: python/paddle/distributed/auto_parallel/converter.py (merge +
re-slice tensors when the parallel strategy changes between save and load) and
paddle/fluid/incubate/checkpoint/auto_checkpoint.py:71 (train-state epoch
metadata). TPU-native design: each host writes only the shards it owns
(`Array.addressable_shards`, replica 0) plus a JSON manifest recording global
shape/dtype/PartitionSpec; load reassembles the global array from any saved
partitioning and `jax.device_put`s it onto the *target* sharding — save on
sdp8, restore on mp2·dp4 works without a converter matrix.
"""
from __future__ import annotations

import hashlib
import json
import os
import re
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding

from ..core.tensor import Tensor


class CheckpointCorrupt(RuntimeError):
    """A saved file does not match its manifest checksum (torn save,
    bit rot, or a partially-overwritten directory)."""


def _np_dtype(name: str) -> np.dtype:
    """Resolve a dtype name to numpy, including ml_dtypes (bfloat16, float8_*).

    np.dtype('bfloat16') raises TypeError — the extension dtypes register as
    types on ml_dtypes, not as numpy string aliases.
    """
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


def _spec_to_json(spec) -> list:
    if spec is None:
        return []
    out = []
    for e in tuple(spec):
        if e is None:
            out.append(None)
        elif isinstance(e, (tuple, list)):
            out.append(list(e))
        else:
            out.append(str(e))
    return out


def _sanitize(key: str) -> str:
    safe = re.sub(r"[^A-Za-z0-9_.-]", "_", key)
    if safe != key:  # disambiguate keys that collide after substitution
        import hashlib

        safe += "-" + hashlib.sha1(key.encode()).hexdigest()[:8]
    return safe


def shard_plan(arr) -> List[Tuple[List[int], List[int], "jax.Array"]]:
    """The (starts, stops, device_shard) walk behind every writer: one row
    per distinct owned slice (replica 0, deduped). A 0-d / unsharded array
    degrades to one whole-array row. The async checkpointer dispatches its
    d2h copies from this plan on the submitting thread (ordering-safe
    against later donation) before the background writer serializes."""
    if not isinstance(arr, jax.Array):
        arr = jnp.asarray(np.asarray(arr))
    rows: List[Tuple[List[int], List[int], jax.Array]] = []
    seen_slices = set()
    for shard in arr.addressable_shards:
        if shard.replica_id != 0:
            continue  # one copy per distinct slice
        idx = shard.index  # tuple of slices into the global array
        starts = [0 if s.start is None else int(s.start) for s in idx]
        stops = [int(dim) if s.stop is None else int(s.stop)
                 for s, dim in zip(idx, arr.shape)]
        slice_key = (tuple(starts), tuple(stops))
        if slice_key in seen_slices:
            continue
        seen_slices.add(slice_key)
        rows.append((starts, stops, shard.data))
    if not rows:  # 0-d or fully-remote (shouldn't happen 1-host)
        rows.append(([0] * arr.ndim, [int(d) for d in arr.shape], arr))
    return rows


def _atomic_npy(path: str, data: np.ndarray) -> str:
    """Write ``<path>`` via tmp + fsync + ``os.replace`` (no reader ever
    sees a partial file); returns the sha256 of the written bytes."""
    # call-time import: resilience.commit imports from this module
    from .resilience.commit import HashingWriter

    tmp = f"{path}.tmp-{os.getpid()}"
    with open(tmp, "wb") as f:
        hw = HashingWriter(f)  # sha256 computed as the bytes land
        np.save(hw, data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return hw.hexdigest()


def save_state_dict(state_dict: Dict, path: str, process_rank: Optional[int] = None):
    """Write a sharded checkpoint directory.

    state_dict values may be Tensors (possibly GSPMD-sharded), jax arrays, or
    numpy arrays. Layout: `<path>/manifest.json` + one `.npy` per owned shard.

    Commit protocol (shared with ``distributed.resilience``): every shard
    file lands via tmp + fsync + ``os.replace`` and carries a sha256 in the
    manifest; the manifest fragment itself is replaced LAST. A crash
    mid-save therefore leaves either the intact previous manifest (whose
    checksums flag any half-overwritten shards at load) or no manifest at
    all — never a silently-torn shard/manifest mix.
    """
    os.makedirs(path, exist_ok=True)
    rank = process_rank if process_rank is not None else jax.process_index()
    manifest = {"format": 2, "entries": {}}
    for key, val in state_dict.items():
        arr = val.data if isinstance(val, Tensor) else val
        safe = _sanitize(key)
        if not isinstance(arr, jax.Array):
            arr = jnp.asarray(np.asarray(arr))
        sharding = arr.sharding
        spec = getattr(sharding, "spec", None)
        entry = {
            "global_shape": [int(d) for d in arr.shape],
            "dtype": str(arr.dtype),
            "spec": _spec_to_json(spec),
            "shards": [],
        }
        for starts, stops, shard_data in shard_plan(arr):
            fname = f"{safe}.r{rank}.s{len(entry['shards'])}.npy"
            sha = _atomic_npy(os.path.join(path, fname),
                              np.asarray(shard_data))
            entry["shards"].append({"file": fname, "starts": starts,
                                    "stops": stops, "sha256": sha})
        manifest["entries"][key] = entry
    # each rank writes its own fragment; load merges them (multi-host safe).
    # fragment replaced atomically LAST: the commit point of this rank's save
    frag = os.path.join(path, f"manifest.r{rank}.json")
    tmp = f"{frag}.tmp-{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(manifest, f, indent=1)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, frag)


def _read_shard(path: str, sh: dict, verify: bool) -> np.ndarray:
    """Read a shard ONCE: hash the bytes and np.load from the same buffer
    (the save path hashes while writing for the same single-pass reason)."""
    want = sh.get("sha256")
    if not verify or not want:  # format-1 checkpoints carry no checksums
        return np.load(path)
    import io

    with open(path, "rb") as f:
        raw = f.read()
    if hashlib.sha256(raw).hexdigest() != want:
        raise CheckpointCorrupt(
            f"shard {sh['file']} fails its manifest checksum (torn or "
            f"partially-overwritten save); restore from an older checkpoint")
    return np.load(io.BytesIO(raw))


def _assemble(path: str, entry: dict, verify: bool = True) -> np.ndarray:
    """Rebuild the global ndarray from saved shards (converter.merge role).
    ``verify`` re-hashes each shard against its manifest sha256 (when
    present) so a torn shard/manifest mix raises ``CheckpointCorrupt``
    instead of silently loading mixed-step weights."""
    shape = tuple(entry["global_shape"])
    out = np.empty(shape, dtype=_np_dtype(entry["dtype"]))
    filled = np.zeros(shape, dtype=bool) if shape else None
    for sh in entry["shards"]:
        data = _read_shard(os.path.join(path, sh["file"]), sh, verify)
        if data.dtype != out.dtype:
            if (data.dtype.kind == "V"
                    and data.dtype.itemsize == out.dtype.itemsize):
                # np.save writes ml_dtypes arrays with a void descr ('V2');
                # the bytes are right, only the type tag is lost.
                data = data.view(out.dtype)
            else:
                raise ValueError(
                    f"shard {sh['file']} dtype {data.dtype} does not match "
                    f"manifest dtype {out.dtype}")
        idx = tuple(slice(a, b) for a, b in zip(sh["starts"], sh["stops"]))
        out[idx] = data
        if filled is not None:
            filled[idx] = True
    if filled is not None and not filled.all():
        raise RuntimeError(
            "checkpoint is missing shards for part of the tensor (multi-host "
            "save dirs must be merged into one directory before load)")
    return out


def _read_manifest(path: str) -> dict:
    """Merge all ranks' manifest fragments into one entry table."""
    import glob

    frags = sorted(glob.glob(os.path.join(path, "manifest.r*.json")))
    if not frags:
        raise FileNotFoundError(f"no manifest.r*.json under {path}")
    entries: dict = {}
    for fp in frags:
        with open(fp) as f:
            m = json.load(f)
        for key, entry in m["entries"].items():
            if key in entries:
                entries[key]["shards"].extend(entry["shards"])
            else:
                entries[key] = entry
    return entries


def load_state_dict(state_dict: Dict, path: str, strict: bool = True,
                    verify: bool = True):
    """Fill `state_dict`'s tensors in place from `<path>`, resharding onto each
    target's current sharding (different mesh/layout than at save time is fine).
    ``verify`` checks manifest sha256 checksums where present (raises
    ``CheckpointCorrupt`` on a torn save).
    """
    entries = _read_manifest(path)
    missing = [k for k in state_dict if k not in entries]
    if strict and missing:
        raise ValueError(f"checkpoint missing keys: {missing}")
    for key, val in state_dict.items():
        if key not in entries:
            continue
        entry = entries[key]
        arr = _assemble(path, entry, verify=verify)
        if isinstance(val, Tensor):
            tgt = val.data
            if tuple(arr.shape) != tuple(tgt.shape):
                raise ValueError(
                    f"{key}: checkpoint shape {arr.shape} != target {tgt.shape}")
            new = jnp.asarray(arr.astype(_np_dtype(str(tgt.dtype))))
            sharding = tgt.sharding
            if isinstance(sharding, NamedSharding):
                new = jax.device_put(new, sharding)  # reshard onto target mesh
            val.data = new
        else:
            state_dict[key] = arr
    return state_dict


def load_manifest(path: str) -> dict:
    return {"entries": _read_manifest(path)}


def save_sharded_model(layer, optimizer, path: str):
    """Convenience: model params + optimizer accumulators in one directory."""
    sd = dict(layer.state_dict())
    if optimizer is not None:
        for k, v in optimizer.state_dict().items():
            if isinstance(v, Tensor):
                sd[f"opt.{k}"] = v
    save_state_dict(sd, path)


def load_sharded_model(layer, optimizer, path: str):
    sd = dict(layer.state_dict())
    load_state_dict(sd, path, strict=True)
    if optimizer is not None:
        opt_sd = optimizer.state_dict()
        opt_keys = {f"opt.{k}": k for k, v in opt_sd.items()
                    if isinstance(v, Tensor)}
        manifest = load_manifest(path)
        present = {mk: ok for mk, ok in opt_keys.items()
                   if mk in manifest["entries"]}
        if present:
            sub = {mk: opt_sd[ok] for mk, ok in present.items()}
            load_state_dict(sub, path, strict=False)
